"""Host-side p2p networking: gossip, req/resp RPC, peers, sync.

The distributed half of the node (SURVEY §2.10). The reference's stack is
libp2p (gossipsub + eth2 RPC + discv5) with noise/yamux transports; this
implementation keeps the same protocol SURFACE — fork-digest gossip topics
with spec message-ids, SSZ-snappy RPC methods, Status handshakes, peer
scoring/banning, range sync — on the host network (ICI/DCN carry only
device collectives; p2p always stays on the host CPU). Transport security
is the real libp2p Noise XX handshake (network/noise.py) when a
NoiseTransport is supplied: streams are then encrypted and the peer's
ed25519 identity is verified and used for identity-level bans. Gossip now
runs a real gossipsub v1.1 control mesh (network/gossipsub/): per-topic
D-regular meshes with heartbeat GRAFT/PRUNE maintenance, IHAVE/IWANT lazy
gossip over an mcache, and peer scoring gating every mesh decision.
Remaining wire-compat gaps vs mainnet libp2p: multistream-select/yamux
muxing, protobuf gossipsub RPC (frames here are SSZ behind a tag byte),
and discv5 packet crypto (discovery uses its own UDP record protocol).

Components: `NetworkService` (service/mod.rs analog) owning the server +
peer set, `GossipRouter` (socket/handler bridge around
gossipsub.GossipsubBehaviour), `PeerManager` (scoring/banning,
peer_manager/peerdb/score.rs), and the sync engine (network/sync/:
multi-peer range sync, resumable backfill, unknown-root block lookups —
sync/manager.rs + range_sync/ + backfill_sync/ + block_lookups/)."""

from __future__ import annotations

import queue
import socket
import threading
import time
from dataclasses import dataclass, field

from ..beacon_processor import (
    BATCHED_WORK_TYPES,
    BeaconProcessor,
    ReprocessQueue,
    WorkEvent,
    WorkType,
)
from ..metrics import REGISTRY, inc_counter, set_gauge
from ..utils.logging import get_logger
from . import messages as M
from .gossipsub import (
    DEFERRED,
    FrameError,
    GossipsubBehaviour,
    beacon_score_params,
    beacon_score_thresholds,
    decode_frame,
    short_topic as _short_topic,
)
from .rpc import (
    RpcClient,
    RpcError,
    RpcServer,
    _read_exact,
    _recv_block,
    _send_block,
    _send_protocol,
)

log = get_logger("lighthouse_tpu.network")

# peer scoring (peerdb/score.rs shape)
# (the sync engine imports these lazily at call time — network.sync is
# imported below, after the constants it needs exist)
SCORE_INVALID_MESSAGE = -10.0
SCORE_TIMELY_MESSAGE = 0.5
# failed/timed-out RPC (PeerAction::MidToleranceError class): mild — an
# unresponsive peer drifts down instead of staying pristine while honest
# peers absorb implication penalties
SCORE_RPC_FAILURE = -1.0
BAN_THRESHOLD = -40.0
MAX_SCORE = 100.0
BAN_DURATION = 3600.0  # bans expire (peerdb's ban period); entry then drops
_GOSSIP_IO_TIMEOUT = 30.0  # bounds send stalls AND idle reader probes

# gossip outcome accounting (reference Accept/Ignore/Reject semantics):
# rejects downscore the forwarder; ignores and internal errors never do
REGISTRY.counter(
    "gossip_internal_error_total",
    "gossip handlers that failed on OUR side (store fault, bug) — "
    "logged and not relayed, but the forwarding peer is NOT penalized",
).inc(0)
REGISTRY.counter(
    "gossip_ignored_total",
    "gossip messages neither relayed nor penalized (unknown root/parent, "
    "ordering races, reprocess parking)",
).inc(0)
REGISTRY.counter(
    "gossip_relay_dropped_total",
    "accepted messages whose mesh relay was shed (relay queue full) — "
    "processed locally, not re-forwarded",
).inc(0)


class GossipIgnore(Exception):
    """A gossip message we can't act on through no fault of the forwarder
    (reference Ignore): unknown root/parent being recovered, work parked
    in the reprocess queue. Not relayed, not penalized."""


@dataclass
class _GossipWork:
    """One decoded gossip message riding a beacon_processor lane: enough
    context for the queued handler to complete the deferred relay /
    downscore decision when validation finishes."""

    topic: str
    item: object
    data: bytes
    origin: str


@dataclass
class _QueuedTopic:
    """Registration record for a queue-routed gossip topic."""

    work_type: WorkType
    decode: object  # data -> item (reader thread; cheap, reject-on-raise)
    process: object  # item -> None (worker thread; raises to classify)
    #: optional whole-drained-batch processor for batched WorkTypes:
    #: items -> list[Exception | None] (one outcome per item, in order)
    process_batch: object = None


@dataclass
class Peer:
    host: str
    port: int
    client: RpcClient
    status: M.StatusMessage | None = None
    score: float = 0.0
    banned: bool = False
    banned_at: float = 0.0
    gossip_sock: socket.socket | None = None
    lock: threading.Lock = field(default_factory=threading.Lock)
    # Noise-authenticated libp2p-style identity (None on plain TCP).
    # Bans recorded against this id survive address changes — a banned
    # node redialing from a new port keeps its cryptographic identity.
    noise_peer_id: str | None = None

    @property
    def peer_id(self) -> str:
        return f"{self.host}:{self.port}"


class PeerManager:
    def __init__(self):
        self._peers: dict[str, Peer] = {}
        # noise identity -> ban timestamp: identity-level bans (used when
        # the transport authenticates peers; address bans alone can be
        # dodged by redialing from a fresh port)
        self._banned_ids: dict[str, float] = {}
        self._lock = threading.Lock()

    def add(self, peer: Peer) -> bool:
        """Register a peer. Refused (False) when the peer id is banned —
        redialing must not mint a fresh unbanned identity (peerdb keeps
        banned peers listed for exactly this reason). A reconnect of a
        known peer inherits its score (reconnecting must not launder a
        bad score back to 0) and releases the stale socket."""
        stale_sock = None
        with self._lock:
            if peer.noise_peer_id is not None:
                banned_at = self._banned_ids.get(peer.noise_peer_id)
                if banned_at is not None:
                    if time.monotonic() - banned_at < BAN_DURATION:
                        return False
                    self._banned_ids.pop(peer.noise_peer_id, None)
            existing = self._peers.get(peer.peer_id)
            if existing is not None:
                if existing.banned:
                    if time.monotonic() - existing.banned_at < BAN_DURATION:
                        return False
                    # expired ban: the identity starts fresh
                    self._peers.pop(peer.peer_id)
                    existing = None
            if existing is not None:
                peer.score = existing.score
                with existing.lock:
                    stale_sock = existing.gossip_sock
                    existing.gossip_sock = None
            self._peers[peer.peer_id] = peer
            n = self._gauge_count()
        if stale_sock is not None:
            try:
                stale_sock.close()
            except OSError:
                pass
        set_gauge("network_peers", n)
        return True

    def is_banned(self, peer_id: str) -> bool:
        with self._lock:
            p = self._peers.get(peer_id)
            if p is None or not p.banned:
                return False
            if time.monotonic() - p.banned_at >= BAN_DURATION:
                # ban served: drop the dead entry entirely (bounds the
                # table — banned identities don't accumulate forever)
                self._peers.pop(peer_id, None)
                return False
            return True

    def _gauge_count(self) -> int:
        """Connected (non-banned) peers — call under self._lock."""
        return sum(1 for p in self._peers.values() if not p.banned)

    def remove(self, peer_id: str):
        """Drop a disconnected peer. Banned entries are kept — a ban must
        survive the connection teardown that usually follows it (peerdb's
        ban list outlives the session)."""
        with self._lock:
            p = self._peers.get(peer_id)
            if p is not None and not p.banned:
                self._peers.pop(peer_id)
            n = self._gauge_count()
        set_gauge("network_peers", n)

    def peers(self) -> list[Peer]:
        with self._lock:
            return [p for p in self._peers.values() if not p.banned]

    def get(self, peer_id: str) -> Peer | None:
        """A connected, non-banned peer by id (None otherwise)."""
        with self._lock:
            p = self._peers.get(peer_id)
            return None if p is None or p.banned else p

    def report(self, peer_id: str, delta: float) -> Peer | None:
        """Score adjustment; banning at threshold (score.rs behavior). A
        fresh ban also severs the live connection — the reference
        disconnects banned peers, not just future redials."""
        newly_banned = None
        with self._lock:
            p = self._peers.get(peer_id)
            if p is None:
                return None
            p.score = min(MAX_SCORE, p.score + delta)
            if p.score <= BAN_THRESHOLD and not p.banned:
                p.banned = True
                p.banned_at = time.monotonic()
                if p.noise_peer_id is not None:
                    self._banned_ids[p.noise_peer_id] = p.banned_at
                newly_banned = p
                inc_counter("network_peers_banned_total")
            n = self._gauge_count()
        if newly_banned is not None:
            set_gauge("network_peers", n)
            # close outside the manager lock (peer.lock orders with publish)
            with newly_banned.lock:
                if newly_banned.gossip_sock is not None:
                    try:
                        newly_banned.gossip_sock.close()
                    except OSError:
                        pass
                    newly_banned.gossip_sock = None
        return p


class GossipRouter:
    """Socket/handler bridge around a gossipsub v1.1 control mesh.

    The flood-publish stand-in graduated (network/gossipsub/): the
    behaviour owns mesh membership, the mcache, scoring and dedup; this
    router supplies its transport (peer sockets via the PeerManager), its
    validation, and its peer-exchange records.

    Validation is QUEUE-ROUTED (the event-driven-node refactor): a topic
    registered via `subscribe_queued` runs only a thin decode step on the
    socket reader thread — the chain-touching process step rides its own
    beacon_processor WorkType lane, so reader threads never block on
    state transitions, priority ordering (blocks before attestations)
    holds under storm, and full queues shed load through the processor's
    drop-counted backpressure instead of stalling sockets. The
    validate-then-forward contract survives: `_deliver` returns the
    gossipsub DEFERRED sentinel and the queued handler reports the
    outcome via `behaviour.complete_validation`, which performs exactly
    the relay/score steps the inline path would have. Outcomes follow the
    reference Accept/Ignore/Reject split — only Rejects (the chain's
    ValueError validation family) cost the forwarder score; internal faults are
    logged and counted (`gossip_internal_error_total`) without penalizing
    an innocent peer. Plain `subscribe` keeps the inline contract for
    relay-only/auxiliary topics."""

    def __init__(
        self,
        service: "NetworkService",
        params=None,
        thresholds=None,
        config=None,
    ):
        self.service = service
        self._handlers: dict[str, object] = {}
        self._queued: dict[str, _QueuedTopic] = {}
        #: one runner object per WorkType (NOT per topic): the processor
        #: coalesces batched kinds by handler identity, so all 64
        #: attestation subnets must share one runner to share one batch
        self._runners: dict[WorkType, object] = {}
        # deferred-Accept relays ride their own thread: the mesh forward
        # is a blocking socket send (peer.lock, 30 s I/O timeout) and
        # must not wedge the beacon_processor's scarce workers behind one
        # stalled peer — a full relay queue sheds the FORWARD only
        # (counted; the message was already processed locally)
        self._relay_q: queue.Queue = queue.Queue(maxsize=1024)
        self._relay_stop = threading.Event()
        self._relay_thread = threading.Thread(
            target=self._relay_loop,
            daemon=True,
            name=f"gossip-relay-{service.port}",
        )
        self._relay_thread.start()
        domain = service.spec.message_domain_valid_snappy
        self.behaviour = GossipsubBehaviour(
            send=self._send_frame,
            deliver=self._deliver,
            mid_fn=lambda data: M.message_id(domain, data),
            px_provider=self._px_records,
            params=params,
            thresholds=thresholds,
            config=config,
        )

    def subscribe(self, topic: str, handler):
        """Inline-validated subscription (legacy contract): handler runs
        on the reader thread; raising rejects. Chain-touching handlers
        belong on `subscribe_queued` (the queue-discipline lint rule
        enforces this — handlers here must not call chain.process_*)."""
        self._handlers[topic] = handler
        self.behaviour.subscribe(topic)

    def subscribe_queued(
        self,
        topic: str,
        work_type: WorkType,
        decode,
        process=None,
        process_batch=None,
    ):
        """Queue-routed subscription: `decode` runs inline on the reader
        thread (raise = reject + downscore); the decoded item is submitted
        on `work_type`'s lane and `process` (or `process_batch` for the
        coalescing kinds) runs on a worker, classifying its outcome by
        exception: clean return = Accept (relay + credit), GossipIgnore =
        Ignore, ValueError (the chain's validation family) = Reject
        (downscore), anything else = internal error (counted, never the
        peer's fault)."""
        self._queued[topic] = _QueuedTopic(
            work_type=work_type,
            decode=decode,
            process=process,
            process_batch=process_batch,
        )
        self.behaviour.subscribe(topic)

    def publish(self, topic: str, data: bytes):
        """Local publish: into the mcache and out via the mesh."""
        self.behaviour.publish(topic, data)

    def ensure_mesh(self, topic: str):
        """Eagerly fill a topic's mesh (duty-subnet joins shouldn't wait
        for the next heartbeat)."""
        self.behaviour.graft_now(topic)

    def heartbeat(self):
        """One behaviour heartbeat + dial any peer-exchange candidates."""
        self.behaviour.heartbeat()
        self._dial_px()

    def mesh_peers(self, topic: str) -> set[str]:
        return self.behaviour.mesh_peers(topic)

    # -- behaviour plumbing ----------------------------------------------

    def on_frame(self, peer_id: str, raw: bytes):
        """One length-delimited gossip block from a peer's reader."""
        try:
            frame = decode_frame(raw)
        except FrameError:
            self.service.peers.report(peer_id, SCORE_INVALID_MESSAGE)
            inc_counter("gossip_invalid_total")
            return
        self.behaviour.handle_frame(peer_id, frame)

    def _deliver(self, topic: str, data: bytes, origin: str):
        """Validate-then-forward (gossipsub accept/reject semantics): a
        message our handler rejects is NOT relayed, so invalid data never
        costs downstream peers score — and the rejection feeds both the
        gossipsub score (graylisting) and the PeerManager (banning).

        Queue-routed topics decode here (thin, reader-thread) and defer
        the chain-touching validation to the beacon_processor: the relay
        decision returns DEFERRED and lands later via the queued
        handler's outcome. A full lane sheds the message (drop-counted by
        `submit`) — neither relayed nor penalized, never a stalled
        socket."""
        q = self._queued.get(topic)
        if q is not None:
            try:
                item = q.decode(data)
            except Exception:  # noqa: BLE001 — undecodable gossip: reject
                self.service.peers.report(origin, SCORE_INVALID_MESSAGE)
                inc_counter("gossip_invalid_total")
                return False
            self.service.processor.submit(
                q.work_type,
                _GossipWork(topic=topic, item=item, data=data, origin=origin),
                self._runner_for(q.work_type),
            )
            return DEFERRED
        handler = self._handlers.get(topic)
        if handler is None:
            return True  # relay-only topic: forwardable, nothing local
        try:
            handler(data)
        except Exception:  # noqa: BLE001 — invalid gossip: reject
            self.service.peers.report(origin, SCORE_INVALID_MESSAGE)
            inc_counter("gossip_invalid_total")
            return False
        self.service.peers.report(origin, SCORE_TIMELY_MESSAGE)
        return True

    # -- queued validation (worker side) ---------------------------------

    def _runner_for(self, work_type: WorkType):
        runner = self._runners.get(work_type)
        if runner is None:
            runner = (
                self._run_queued_batch
                if work_type in BATCHED_WORK_TYPES
                else self._run_queued_single
            )
            self._runners[work_type] = runner
        return runner

    def _run_queued_single(self, work: _GossipWork):
        entry = self._queued[work.topic]
        self._complete(work, self._classify(entry.process, work.item))

    def _run_queued_batch(self, works: list):
        """One drained batch of a coalescing kind: group by registration
        (all attestation subnets share one) and hand `process_batch` the
        whole item list — that is what turns a storm of per-message
        verifications into one RLC signature batch."""
        groups: dict[int, tuple[_QueuedTopic, list]] = {}
        for w in works:
            entry = self._queued[w.topic]
            fn = entry.process_batch or entry.process
            # group by the UNDERLYING function: distinct bound-method
            # objects wrapping the same method must coalesce
            key = id(getattr(fn, "__func__", fn))
            groups.setdefault(key, (entry, []))[1].append(w)
        for entry, ws in groups.values():
            if entry.process_batch is None:
                for w in ws:
                    self._complete(w, self._classify(entry.process, w.item))
                continue
            try:
                outcomes = entry.process_batch([w.item for w in ws])
                if len(outcomes) != len(ws):
                    # a short/long outcome list would leave tail messages
                    # with NO relay/score decision — the silent-drop class
                    # this pipeline is built to eliminate
                    raise RuntimeError(
                        f"process_batch returned {len(outcomes)} outcomes "
                        f"for {len(ws)} items"
                    )
            except Exception as e:  # noqa: BLE001 — whole-batch fault
                outcomes = [e] * len(ws)
            for w, err in zip(ws, outcomes):
                self._complete(w, err)

    @staticmethod
    def _classify(process, item):
        """Run one process step, returning its outcome exception (None =
        Accept). Workers never see these raise — classification is the
        router's, not the processor's error counter's."""
        try:
            process(item)
            return None
        except Exception as e:  # noqa: BLE001 — classified by _complete
            return e

    def _complete(self, work: _GossipWork, err):
        """Deferred relay/score decision (reference Accept/Ignore/Reject):
        clean = relay + credit; Ignore = drop quietly; Reject (a chain
        ValueError) = penalize origin, never relay; anything else
        is an INTERNAL error — our store/bug, not the peer's message — so
        it is logged and counted but costs the origin nothing."""
        if err is None:
            self.service.peers.report(work.origin, SCORE_TIMELY_MESSAGE)
            try:
                self._relay_q.put_nowait((work.topic, work.data, work.origin))
            except queue.Full:
                inc_counter("gossip_relay_dropped_total")
        elif isinstance(err, GossipIgnore):
            inc_counter("gossip_ignored_total")
        elif isinstance(err, ValueError):
            self.behaviour.complete_validation(
                work.topic, work.data, work.origin, False
            )
            self.service.peers.report(work.origin, SCORE_INVALID_MESSAGE)
            inc_counter("gossip_invalid_total")
        else:
            inc_counter("gossip_internal_error_total")
            log.warning(
                "gossip handler internal error",
                topic=_short_topic(work.topic),
                error=f"{type(err).__name__}: {str(err)[:200]}",
            )

    def _relay_loop(self):
        """Deferred-Accept completions: mcache entry, P2 credit, and the
        eager mesh forward (`behaviour.complete_validation`) — serialized
        off the worker pool so socket stalls cost relay latency, not
        validation throughput."""
        while not self._relay_stop.is_set():
            try:
                item = self._relay_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if item is None:
                return
            topic, data, origin = item
            try:
                self.behaviour.complete_validation(topic, data, origin, True)
            except Exception as e:  # noqa: BLE001 — relay must outlive faults
                log.warning("gossip relay failed", error=str(e)[:200])

    def stop(self):
        """Stop the relay thread (joined — NetworkService.stop's
        zero-thread-leak audit covers it). Event-first: a full queue or a
        relay mid-send must not block the caller."""
        self._relay_stop.set()
        try:
            self._relay_q.put_nowait(None)
        except queue.Full:
            pass
        self._relay_thread.join(timeout=2)

    def _send_frame(self, peer_id: str, payload: bytes):
        """Every outbound gossip frame (data AND control: GRAFT/PRUNE/
        IHAVE) crosses the service's egress seam first — the fault plane
        the testnet harness scripts partitions/eclipses/late-delivery
        through. None = the edge is dark (frame dropped); >0 = delivered
        that many seconds late on a timer thread (the caller — relay
        thread, heartbeat — must never sleep for an injected delay)."""
        delay = self.service.egress_delay(peer_id)
        if delay is None:
            return
        if delay > 0:
            t = threading.Timer(delay, self._send_frame_now, args=(peer_id, payload))
            t.daemon = True
            t.start()
            return
        self._send_frame_now(peer_id, payload)

    def _send_frame_now(self, peer_id: str, payload: bytes):
        peer = self.service.peers.get(peer_id)
        if peer is None:
            return
        try:
            with peer.lock:
                if peer.gossip_sock is None:
                    return
                _send_block(peer.gossip_sock, payload)
        except OSError:
            self.service._drop_peer(peer)

    def _px_records(self, topic: str, exclude: str) -> list[tuple[str, str, int]]:
        """Peer-exchange records for a PRUNE: the topic's other mesh
        members, addressed as we dialed them."""
        out = []
        for pid in self.behaviour.mesh.get(topic, ()):
            if pid == exclude:
                continue
            peer = self.service.peers.get(pid)
            if peer is not None:
                out.append((peer.noise_peer_id or pid, peer.host, peer.port))
        return out

    _PX_DIAL_MAX_PEERS = 8

    def _dial_px(self):
        """Dial peer-exchange candidates on their own short-lived thread:
        connect() is a synchronous TCP+handshake and one dead PX record
        must not stall the heartbeat cadence (mesh repair, IHAVE
        emission) for a connect timeout."""
        candidates = self.behaviour.take_px_candidates()
        if not candidates:
            return
        threading.Thread(
            target=self._dial_px_worker,
            args=(candidates,),
            daemon=True,
            name=f"gossip-px-dial-{self.service.port}",
        ).start()

    def _dial_px_worker(self, candidates):
        svc = self.service
        for _pid, host, port in candidates:
            if svc._stopping:
                return
            have = {(p.host, p.port) for p in svc.peers.peers()}
            if len(have) >= self._PX_DIAL_MAX_PEERS:
                return
            if (host, port) in have or port == svc.port:
                continue
            try:
                svc.connect(host, port)
            except Exception:  # noqa: BLE001 — dead PX record; move on
                continue


# the sync engine lives in its own package (network/sync/); imported here
# AFTER the score constants it references at call time
from .sync import SyncConfig, SyncManager, SyncService  # noqa: E402


class NetworkService:
    """service/mod.rs analog: owns the listener, peers, gossip router and
    sync manager, and bridges gossip to the beacon chain (the network
    crate's Router + NetworkBeaconProcessor roles in one place)."""

    #: gossipsub heartbeat cadence (config.rs heartbeat_interval is 0.7s;
    #: shorter here — simulator slots are fast). 0/None disables the
    #: timer thread (tests drive `gossip.heartbeat()` by hand).
    HEARTBEAT_INTERVAL = 0.3

    def __init__(
        self,
        chain,
        host: str = "127.0.0.1",
        port: int = 0,
        bootnodes=None,
        transport=None,
        heartbeat_interval: float | None = HEARTBEAT_INTERVAL,
        gossip_params=None,
        gossip_thresholds=None,
        gossip_config=None,
        sync_config=None,
        processor_workers: int = 2,
        sync_service_interval: float | None = None,
        node_id: bytes | None = None,
    ):
        self.chain = chain
        self.spec = chain.spec
        # transport security seam: None = plain TCP; a NoiseTransport
        # (network/noise.py) secures every stream with the libp2p Noise XX
        # handshake, as the reference's transport builder does
        self.transport = transport
        self.peers = PeerManager()
        # the node's prioritized work-queue scheduler: sync segments and
        # backfill windows queue here (CHAIN_SEGMENT / BACKFILL_SYNC), and
        # unknown-block work parks in the reprocess queue until its block
        # lands (the NetworkBeaconProcessor wiring)
        self.processor = BeaconProcessor(
            num_workers=processor_workers, name="network_beacon_processor"
        )
        self.reprocess = ReprocessQueue()
        self.sync = SyncManager(self, config=sync_config)
        # autonomous catch-up (sync/manager.rs main-loop role): started in
        # start() when an interval is configured — the node path enables
        # it so range sync no longer waits for a caller. 0/None disables,
        # same convention as heartbeat_interval (a 0-second poll would be
        # a busy loop, not "continuous")
        self.sync_service = (
            SyncService(self.sync, interval=sync_service_interval)
            if sync_service_interval
            else None
        )
        self.metadata_seq = 1
        self.server = RpcServer(self, host, port)
        self.port = self.server.port
        self.heartbeat_interval = heartbeat_interval
        self._hb_thread = None
        self._stopping = False
        self._stop_event = threading.Event()
        #: last slot the heartbeat tick saw: reprocess slot drains/expiry
        #: fire once per slot edge
        self._last_tick_slot = -1
        # discv5 analog: advertise our record, bootstrap from bootnodes
        # (None → discovery disabled, as with the reference's --disable-discovery)
        self.discovery = None
        if bootnodes is not None:
            from .discovery import DiscoveryService

            self.discovery = DiscoveryService(
                tcp_port=self.port,
                fork_digest=self.fork_digest(),
                host=host,
                bootnodes=list(bootnodes),
            )

        # PeerDAS custody + sampling duty (das/): custody columns derive
        # from a stable node id — supplied by the scenario/fleet layer, or
        # defaulted from the listen port (deterministic per node). The DA
        # checker learns the custody set so its column route can complete.
        import hashlib as _hashlib

        from ..das import SamplingEngine
        from ..das.custody import column_subnet as _column_subnet

        self._column_subnet = _column_subnet
        if node_id is None:
            node_id = _hashlib.sha256(
                b"lighthouse-tpu-node" + self.port.to_bytes(8, "little")
            ).digest()
        self.node_id = bytes(node_id)
        self.sampling = SamplingEngine(self.node_id, chain.E)
        chain.data_availability_checker.set_custody(self.sampling.custody)
        #: roots whose sampling verdict has already been recorded (the
        #: slot-tick retry must not re-query peers for a settled root)
        self._sampled_roots: set = set()

        digest = self.fork_digest()
        self.topic_block = M.gossip_topic(digest, M.TOPIC_BEACON_BLOCK)
        # one topic per attestation subnet; a full node stays subscribed
        # to all of them (it relays every subnet, as the reference's
        # default subscribe-all-subnets simulator config does), while the
        # SubnetService tracks duty subnets for ENR advertisement and
        # eager mesh joins
        self.attestation_topics = {
            i: M.gossip_topic(digest, M.attestation_subnet_topic_name(i))
            for i in range(M.ATTESTATION_SUBNET_COUNT)
        }
        self.topic_att = self.attestation_topics[0]
        self.topic_aggregate = M.gossip_topic(digest, M.TOPIC_AGGREGATE)
        self.topic_exit = M.gossip_topic(digest, M.TOPIC_VOLUNTARY_EXIT)
        self.topic_proposer_slashing = M.gossip_topic(
            digest, M.TOPIC_PROPOSER_SLASHING
        )
        self.topic_attester_slashing = M.gossip_topic(
            digest, M.TOPIC_ATTESTER_SLASHING
        )
        self.topic_sync_committee = M.gossip_topic(
            digest, M.TOPIC_SYNC_COMMITTEE
        )
        self.topic_blob_sidecar = M.gossip_topic(digest, M.TOPIC_BLOB_SIDECAR)
        # one topic per data-column subnet (peerdas p2p): a full node
        # subscribes to all of them — it relays every column and its
        # custody subset is always fed — while custody tracking stays the
        # SamplingEngine's concern
        self.data_column_topics = {
            i: M.gossip_topic(digest, M.data_column_subnet_topic_name(i))
            for i in range(chain.E.DATA_COLUMN_SIDECAR_SUBNET_COUNT)
        }
        # scoring parameters are keyed by the node's actual topic strings,
        # so the router is built only once the topics exist
        # (gossipsub_scoring_parameters.rs shape)
        if gossip_params is None:
            gossip_params = beacon_score_params(
                self.topic_block,
                self.topic_aggregate,
                self.attestation_topics,
                extra_topics=[
                    self.topic_exit,
                    self.topic_proposer_slashing,
                    self.topic_attester_slashing,
                    self.topic_sync_committee,
                    self.topic_blob_sidecar,
                    *self.data_column_topics.values(),
                ],
            )
        if gossip_thresholds is None:
            gossip_thresholds = beacon_score_thresholds()
        self.gossip = GossipRouter(
            self,
            params=gossip_params,
            thresholds=gossip_thresholds,
            config=gossip_config,
        )
        # every gossip kind is queue-routed: thin decode on the reader
        # thread, chain work on its own prioritized WorkType lane
        # (network_beacon_processor/gossip_methods.rs shape)
        self.gossip.subscribe_queued(
            self.topic_block,
            WorkType.GOSSIP_BLOCK,
            self._decode_gossip_block,
            self._process_gossip_block,
        )
        # NOTE: each subnet registration mints a fresh bound-method
        # object; the batch runner groups by the UNDERLYING function
        # (`__func__`), so all 64 subnets still coalesce into one
        # process_attestation_batch call per drained batch
        for topic in self.attestation_topics.values():
            self.gossip.subscribe_queued(
                topic,
                WorkType.GOSSIP_ATTESTATION,
                self._decode_gossip_attestation,
                process_batch=self._process_gossip_attestation_batch,
            )
        self.gossip.subscribe_queued(
            self.topic_aggregate,
            WorkType.GOSSIP_AGGREGATE,
            self._decode_gossip_aggregate,
            self._process_gossip_aggregate,
        )
        self.gossip.subscribe_queued(
            self.topic_exit,
            WorkType.GOSSIP_VOLUNTARY_EXIT,
            self._decode_gossip_exit,
            self._process_gossip_exit,
        )
        self.gossip.subscribe_queued(
            self.topic_proposer_slashing,
            WorkType.GOSSIP_PROPOSER_SLASHING,
            self._decode_gossip_proposer_slashing,
            self._process_gossip_proposer_slashing,
        )
        self.gossip.subscribe_queued(
            self.topic_attester_slashing,
            WorkType.GOSSIP_ATTESTER_SLASHING,
            self._decode_gossip_attester_slashing,
            self._process_gossip_attester_slashing,
        )
        self.gossip.subscribe_queued(
            self.topic_sync_committee,
            WorkType.GOSSIP_SYNC_COMMITTEE,
            self._decode_gossip_sync_committee,
            self._process_gossip_sync_committee,
        )
        self.gossip.subscribe_queued(
            self.topic_blob_sidecar,
            WorkType.GOSSIP_BLOB_SIDECAR,
            self._decode_gossip_blob_sidecar,
            self._process_gossip_blob_sidecar,
        )
        # all column subnets share one lane and one underlying handler
        # function, same as the attestation subnets above
        for topic in self.data_column_topics.values():
            self.gossip.subscribe_queued(
                topic,
                WorkType.GOSSIP_DATA_COLUMN_SIDECAR,
                self._decode_gossip_data_column_sidecar,
                self._process_gossip_data_column_sidecar,
            )

    # -- lifecycle -------------------------------------------------------------

    def start(self):
        self.server.start()
        if self.discovery is not None:
            self.discovery.start()
        if self.heartbeat_interval:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                daemon=True,
                name=f"gossip-heartbeat-{self.port}",
            )
            self._hb_thread.start()
        if self.sync_service is not None:
            self.sync_service.start()
        return self

    def _heartbeat_loop(self):
        while not self._stopping:
            self._stop_event.wait(self.heartbeat_interval)
            if self._stopping:
                break
            try:
                self.gossip.heartbeat()
            except Exception as e:  # noqa: BLE001 — heartbeat must outlive faults
                log.warning("gossip heartbeat failed", error=str(e)[:200])
            try:
                self.slot_tick()
            except Exception as e:  # noqa: BLE001 — ditto
                log.warning("slot tick failed", error=str(e)[:200])

    def slot_tick(self):
        """Once per slot edge (heartbeat-driven; tests call directly):
        re-fire reprocess work held for the new slot and expire held
        unknown-block work whose block never came — the bound that stops
        the ReprocessQueue leaking under storm. Idempotent within a slot."""
        slot = int(self.chain.slot_clock.now())
        if slot == self._last_tick_slot:
            return
        self._last_tick_slot = slot
        self.reprocess.slot_started(slot, self.processor)
        self.reprocess.expire(slot)
        # per-slot PeerDAS sampling duty: retry staged blocks still
        # lacking a positive verdict (das/sampling.py)
        self._sample_pending()
        # slasher epoch detection rides its own lowest-priority processor
        # lane (WorkType.SLASHER_PROCESS) — queued here, never run on this
        # heartbeat thread; the service's epoch claim keeps this and the
        # client slot timer from double-processing
        if self.chain.slasher_service is not None:
            self.chain.slasher_service.on_slot(slot, processor=self.processor)
        # next-slot state pre-advance rides its own low-priority lane
        # (WorkType.STATE_ADVANCE) — queued here, never run on this
        # heartbeat thread; the timer's slot claim keeps this and the
        # client slot timer from double-advancing
        if self.chain.state_advance_timer is not None:
            self.chain.state_advance_timer.on_slot_tick(
                slot, processor=self.processor
            )

    def discover_and_connect(self, max_peers: int = 8) -> int:
        """One discovery round → dial every new connectable record
        (discovery.rs find_peers → peer_manager dial flow)."""
        if self.discovery is None:
            return 0
        self.discovery.maintain()  # evict stale records before querying
        connected = 0
        have = {(p.host, p.port) for p in self.peers.peers()}
        local_id = self.discovery.local_enr.node_id
        for enr in self.discovery.discover():
            if connected >= max_peers:
                break
            addr = (enr.ip, enr.tcp_port)
            if addr in have or enr.node_id == local_id:
                continue
            try:
                self.connect(*addr)  # refuses banned peers before dialing
            except Exception:  # noqa: BLE001 — dead record; discovery moves on
                continue
            have.add(addr)
            connected += 1
        return connected

    def stop(self):
        """Graceful teardown, audited for thread leaks: the sync-service
        loop, the heartbeat/slot-tick thread, the RPC server, and the
        processor's manager+workers are all JOINED; queued processor work
        is abandoned with a counter, and held reprocess work is cleared
        the same way — nothing dropped silently, nothing left running."""
        self._stopping = True
        self._stop_event.set()
        if self.sync_service is not None:
            self.sync_service.stop()
        self.sync.stop()
        # the heartbeat/slot-tick thread joins BEFORE the processor shuts
        # down: an in-flight slot_tick re-submits drained reprocess work,
        # which must not land in a dead processor's queues (it would sit
        # there uncounted — the silent drop this audit exists to prevent)
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
            self._hb_thread = None
        self.gossip.stop()
        if self.discovery is not None:
            self.discovery.stop()
        for p in self.peers.peers():
            try:
                p.client.goodbye(M.GOODBYE_CLIENT_SHUTDOWN)
            except Exception:  # noqa: BLE001
                pass
            self._drop_peer(p)
        self.server.stop()
        self.processor.shutdown()
        self.reprocess.clear()

    # -- fault-plane seam --------------------------------------------------------

    def egress_delay(self, peer_id: str) -> float | None:
        """Gossip egress policy for one outbound frame to `peer_id`:
        0.0 = send now (production behavior), a positive value = deliver
        that late, None = drop (the edge is dark). The testnet fault
        plane (testing/testnet.py) overrides this to script partitions,
        eclipses, and late-delivery regimes over otherwise-real nodes."""
        return 0.0

    # -- identity / status ------------------------------------------------------

    def fork_digest(self) -> bytes:
        st = self.chain.head_state
        return M.compute_fork_digest(
            self.spec, st.fork.current_version, st.genesis_validators_root
        )

    def local_status(self) -> M.StatusMessage:
        chain = self.chain
        fin = chain.finalized_checkpoint
        return M.StatusMessage(
            fork_digest=self.fork_digest(),
            finalized_root=fin.root,
            finalized_epoch=fin.epoch,
            head_root=chain.head_root,
            head_slot=chain.head_state.slot,
        )

    # -- peer connection --------------------------------------------------------

    def connect(self, host: str, port: int) -> Peer:
        """Dial a peer: Status handshake (irrelevant-network check), then a
        persistent gossip stream."""
        if self.peers.is_banned(f"{host}:{port}"):
            raise RpcError("peer is banned")
        client = RpcClient(host, port, transport=self.transport, mux=True)
        try:
            status = client.status(self.local_status())
            if bytes(status.fork_digest) != self.fork_digest():
                client.goodbye(M.GOODBYE_IRRELEVANT_NETWORK)
                raise RpcError("peer on a different fork digest")
        except BaseException:
            # the muxed connection (+ reader thread) must not outlive a
            # failed dial
            client.close()
            raise
        peer = Peer(host=host, port=port, client=client, status=status)
        try:
            # the gossip stream rides the SAME muxed connection as the RPC
            # substreams — one TCP (+ one noise handshake) per direction
            gossip_sock = client._open(M.PROTO_GOSSIP)
            peer.noise_peer_id = getattr(gossip_sock, "remote_peer_id", None)
            peer.gossip_sock = gossip_sock
            # bounded I/O: a stalled remote must not wedge publish (sendall
            # holds peer.lock); the reader probes idle timeouts harmlessly
            peer.gossip_sock.settimeout(_GOSSIP_IO_TIMEOUT)
            # announce our listening port so the peer can identify us
            # (_open already negotiated the gossip protocol on the stream)
            _send_block(peer.gossip_sock, self.port.to_bytes(4, "little"))
        except BaseException:
            client.close()
            raise
        if not self.peers.add(peer):
            # refusal cleanup must not mask the refusal: close/goodbye are
            # best-effort against a peer that may already be gone
            try:
                peer.gossip_sock.close()
                client.goodbye(M.GOODBYE_BANNED)
            except (OSError, RpcError):
                pass
            client.close()
            raise RpcError("peer is banned")
        # register with the behaviour (and announce our subscriptions)
        # BEFORE the reader starts: the remote's SUBSCRIBE frames arrive
        # immediately and would be dropped for an unknown peer
        self.gossip.behaviour.add_peer(peer.peer_id)
        # a fresh peer may be the way out of a capped sync backoff or a
        # negatively-cached lookup root (partition heal): wake the loop
        # instead of sleeping it out, and void the "nobody had it" verdicts
        self.sync.lookups.peer_connected()
        if self.sync_service is not None:
            self.sync_service.on_peer_connected()
        t = threading.Thread(
            target=self._gossip_reader,
            args=(peer.gossip_sock, peer.peer_id),
            daemon=True,
            name=f"gossip-{peer.peer_id}",
        )
        t.start()
        return peer

    def _drop_peer(self, peer: Peer):
        with peer.lock:  # publish checks/uses the socket under this lock
            if peer.gossip_sock is not None:
                try:
                    peer.gossip_sock.close()
                except OSError:
                    pass
                peer.gossip_sock = None
        try:
            peer.client.close()  # tear down the muxed RPC connection
        except OSError:
            pass
        self.gossip.behaviour.remove_peer(peer.peer_id)
        self.peers.remove(peer.peer_id)

    # -- gossip plumbing --------------------------------------------------------

    def _handle_gossip_stream(self, sock):
        """Server side of an inbound gossip stream: register the dialer as
        a peer (by its announced listen port) and read messages forever."""
        listen_port = int.from_bytes(_recv_block(sock), "little")
        host = sock.getpeername()[0]
        peer = Peer(
            host=host,
            port=listen_port,
            client=RpcClient(
                host, listen_port, transport=self.transport, mux=True
            ),
            gossip_sock=sock,
            noise_peer_id=getattr(sock, "remote_peer_id", None),
        )
        if not self.peers.add(peer):
            try:
                sock.close()
            except OSError:
                pass
            return
        self.gossip.behaviour.add_peer(peer.peer_id)
        self.sync.lookups.peer_connected()
        if self.sync_service is not None:
            self.sync_service.on_peer_connected()
        self._gossip_reader(sock, peer.peer_id)

    def _gossip_reader(self, sock, peer_id: str):
        sock.settimeout(_GOSSIP_IO_TIMEOUT)
        while not self._stopping:
            # idle-safe probe: a timeout BEFORE a frame starts just retries;
            # a timeout mid-frame (stalled sender) is a real failure
            try:
                first = sock.recv(1)
            except TimeoutError:
                continue
            except OSError:
                break
            if not first:
                break
            try:
                framed = _recv_block(sock, first_byte=first)
            except (RpcError, OSError):
                break
            if self.peers.is_banned(peer_id):
                break  # ban landed while this frame was in flight
            self.gossip.on_frame(peer_id, framed)

    # -- chain bridging (network_beacon_processor/gossip_methods.rs) ------------

    def decode_block(self, data: bytes):
        try:
            return self.chain.types.decode_by_fork("SignedBeaconBlock", data)
        except ValueError as e:
            raise RpcError(str(e)) from e

    # decode steps run INLINE on the socket reader (cheap SSZ work only;
    # raising rejects + downscores); process steps run on beacon_processor
    # workers and classify via GossipIgnore / ValueError / internal error.

    def _decode_gossip_block(self, data: bytes):
        import time as _time

        signed = self.decode_block(data)
        # observation milestone at the earliest point we can name the
        # block: even if the import detours through the queue or a parent
        # lookup, the eventual BlockTimes keeps the true gossip arrival.
        # Clock-clamped: a hostile far-future slot must not enter the
        # cache (it would never be min-slot-evicted nor finality-pruned)
        slot = int(signed.message.slot)
        if slot <= self.chain.slot_clock.now() + 1:
            self.chain.block_times_cache.set_observed(
                signed.message.hash_tree_root(), slot, _time.monotonic()
            )
        return signed

    def _process_gossip_block(self, signed):
        from ..beacon_chain.chain import BlobsUnavailableError, BlockError

        try:
            root = self.chain.process_block(signed)
        except BlobsUnavailableError:
            # expected ordering race, not peer fault: the block is staged
            # in the DA checker and plausibly valid — relay it (the
            # completing sidecar's handler imports it here later)
            log.info("block waiting on sidecars", slot=signed.message.slot)
            return
        except BlockError as e:
            if "parent unknown" in str(e):
                # not the forwarder's fault either: WE are missing the
                # ancestry — recover it via a parent lookup instead of
                # downscoring (sync/block_lookups parent-chain path)
                log.info(
                    "gossip block has unknown parent; starting lookup",
                    slot=signed.message.slot,
                )
                self.sync.on_unknown_parent_block(signed)
                raise GossipIgnore("unknown parent") from e
            raise  # BlockError(ValueError): genuine invalidity → reject
        # release work parked under this root (attestations that arrived
        # before the block, the usual out-of-order gossip case) — without
        # this, only lookup-recovered blocks would ever drain the queue
        self.reprocess.block_imported(root, self.processor)
        log.info(
            "gossip block imported",
            slot=signed.message.slot,
            root=root.hex()[:12],
        )

    def _decode_gossip_attestation(self, data: bytes):
        return self.chain.types.Attestation.deserialize(data)

    def _process_gossip_attestation_batch(self, atts: list) -> list:
        """A whole drained GOSSIP_ATTESTATION batch in ONE RLC signature
        verification — the coalescing that makes the attestation lane
        survive a flood. Returns one outcome per item (None = accept)."""
        results = self.chain.process_attestation_batch(atts)
        out = []
        for att, res in zip(atts, results):
            if not isinstance(res, Exception):
                out.append(None)
            elif "unknown beacon block root" in str(res):
                out.append(self._park_unknown_root_attestation(att))
            elif "outside propagation window" in str(res):
                out.append(self._park_early_attestation(att, res))
            else:
                out.append(res)
        return out

    #: clock-disparity tolerance for EARLY gossip (the reference's
    #: MAXIMUM_GOSSIP_CLOCK_DISPARITY role, in slots): work this far
    #: ahead parks until its slot starts; further is a hostile timestamp
    EARLY_ATTESTATION_SLOT_TOLERANCE = 2

    def _park_early_attestation(self, att, err):
        """Propagation-window violations are IGNORE, never Reject (the
        gossip spec's ATTESTATION_PROPAGATION_SLOT_RANGE semantics —
        lateness is congestion, not malice, and penalizing it graylists
        honest mesh peers exactly when the network is struggling). The
        near-future case (peer clock slightly ahead) additionally parks
        until its slot starts — the slot tick re-fires it through
        `_reprocess_attestation`. Hostile far-future timestamps are
        ignored WITHOUT parking (they must not occupy the queue)."""
        slot = int(att.data.slot)
        now = int(self.chain.slot_clock.now())
        if now < slot <= now + self.EARLY_ATTESTATION_SLOT_TOLERANCE:
            self.reprocess.hold_for_slot(
                slot,
                WorkEvent(
                    WorkType.UNKNOWN_BLOCK_ATTESTATION,
                    att,
                    self._reprocess_attestation,
                ),
            )
            return GossipIgnore("early attestation held for its slot")
        return GossipIgnore(str(err))

    def _park_unknown_root_attestation(self, att):
        """Hold the attestation until its block lands (the
        work_reprocessing_queue path, now capped + slot-stamped) and go
        find the block; a cap refusal is load shed, still an Ignore."""
        root = bytes(att.data.beacon_block_root)
        held = self.reprocess.hold_for_block(
            root,
            WorkEvent(
                WorkType.UNKNOWN_BLOCK_ATTESTATION,
                att,
                self._reprocess_attestation,
            ),
            slot=int(att.data.slot),
        )
        if held:
            self.sync.on_unknown_block_root(root)
        return GossipIgnore("unknown beacon block root")

    def _reprocess_attestation(self, att):
        """Reprocess-queue re-fire: the unknown block imported, so the held
        attestation gets its real verification pass now."""
        results = self.chain.process_attestation_batch([att])
        if results and isinstance(results[0], Exception):
            raise results[0]  # worker counts it in beacon_processor_errors

    def _decode_gossip_aggregate(self, data: bytes):
        return self.chain.types.SignedAggregateAndProof.deserialize(data)

    def _process_gossip_aggregate(self, agg):
        """Aggregates get the same unknown-root parking attestations have
        had since PR 5 — an aggregate that beats its block by one hop used
        to be an error charged to an innocent forwarder."""
        from ..beacon_chain.attestation_verification import AttestationError

        try:
            self.chain.process_aggregate(agg)
        except AttestationError as e:
            if "outside propagation window" in str(e):
                # window violations are IGNORE, same as attestations
                raise GossipIgnore(str(e)) from e
            if "unknown beacon block root" not in str(e):
                raise
            data = agg.message.aggregate.data
            root = bytes(data.beacon_block_root)
            held = self.reprocess.hold_for_block(
                root,
                WorkEvent(
                    WorkType.UNKNOWN_BLOCK_AGGREGATE,
                    agg,
                    self._reprocess_aggregate,
                ),
                slot=int(data.slot),
            )
            if held:
                self.sync.on_unknown_block_root(root)
            raise GossipIgnore("unknown beacon block root") from e

    def _reprocess_aggregate(self, agg):
        self.chain.process_aggregate(agg)

    # exits/slashings are spec-verified (signatures included) against the
    # head state before pooling — an unverifiable op would otherwise be
    # packed into our own proposal (gossip_methods.rs); the process steps
    # are thin late-binding wrappers over the chain methods (a ValueError
    # from the spec check classifies as a reject).

    def _decode_gossip_exit(self, data: bytes):
        return self.chain.types.SignedVoluntaryExit.deserialize(data)

    def _process_gossip_exit(self, exit_):
        self.chain.process_voluntary_exit(exit_)

    def _decode_gossip_proposer_slashing(self, data: bytes):
        return self.chain.types.ProposerSlashing.deserialize(data)

    def _process_gossip_proposer_slashing(self, slashing):
        self.chain.process_proposer_slashing(slashing)

    def _decode_gossip_attester_slashing(self, data: bytes):
        return self.chain.types.AttesterSlashing.deserialize(data)

    def _process_gossip_attester_slashing(self, slashing):
        self.chain.process_attester_slashing(slashing)

    def _decode_gossip_sync_committee(self, data: bytes):
        return self.chain.types.SyncCommitteeMessage.deserialize(data)

    def _process_gossip_sync_committee(self, msg):
        self.chain.process_sync_committee_message(msg)

    def _decode_gossip_blob_sidecar(self, data: bytes):
        return self.chain.types.BlobSidecar.deserialize(data)

    def _process_gossip_blob_sidecar(self, sc):
        """KZG-verify and stage a gossiped sidecar; when this sidecar
        completes a staged block's set, import that block NOW — its own
        gossip arrived earlier, failed the DA gate, and is dedup'd by the
        seen-cache, so nothing else will retry it. An unknown PARENT for
        the completed block starts a lookup instead of downscoring the
        sidecar's forwarder (it did nothing wrong)."""
        block_root = sc.signed_block_header.message.hash_tree_root()
        avail = self.chain.process_blob_sidecars(block_root, [sc])
        self._import_completed_block(block_root, avail)

    def _import_completed_block(self, block_root: bytes, avail):
        """Import a block whose DA components just became complete (blob
        and column sidecar handlers + the sampling verdict path). An
        unknown PARENT starts a lookup; any other import failure is
        Ignore, never a penalty — the component's forwarder could not
        have known (the component itself verified), and the block's own
        gossip path penalizes whoever forwarded an invalid block."""
        from ..beacon_chain.chain import BlockError

        if not avail.available or self.chain.fork_choice.contains_block(
            block_root
        ):
            return
        try:
            self.chain.process_block(avail.block)
        except BlockError as e:
            if "parent unknown" in str(e):
                log.info(
                    "completed block has unknown parent; starting lookup",
                    root=block_root.hex()[:12],
                )
                self.sync.on_unknown_parent_block(avail.block)
                raise GossipIgnore("unknown parent") from e
            log.info(
                "completed block failed import",
                root=block_root.hex()[:12],
                error=str(e)[:120],
            )
            raise GossipIgnore(str(e)) from e
        self.reprocess.block_imported(block_root, self.processor)

    def _decode_gossip_data_column_sidecar(self, data: bytes):
        return self.chain.types.DataColumnSidecar.deserialize(data)

    def _process_gossip_data_column_sidecar(self, sc):
        """Verify (header binding + batched cell KZG) and stage a gossiped
        data column; then run the sampling duty for its block if still
        unsettled — a column arriving means its block is circulating, so
        peers plausibly hold the sample columns by now. Availability may
        complete here via any column route (custody+sampling or >=50%
        reconstruction) and imports the staged block exactly as a
        completing blob does."""
        from ..beacon_chain.chain import BlobsUnavailableError

        block_root = sc.signed_block_header.message.hash_tree_root()
        try:
            avail = self.chain.process_data_column_sidecars(block_root, [sc])
        except BlobsUnavailableError as e:
            # IGNORE class: locally missing prerequisites (e.g. no KZG
            # engine) — never the forwarder's fault
            raise GossipIgnore(str(e)) from e
        self._maybe_sample(block_root)
        if not avail.available:
            avail = self.chain.data_availability_checker.check_availability(
                block_root
            )
        self._import_completed_block(block_root, avail)

    # -- PeerDAS sampling duty (das/sampling.py) --------------------------------

    def _maybe_sample(self, block_root: bytes):
        """One sampling attempt per root: query the engine's selected
        non-custody columns from peers (DataColumnSidecarsByRoot), stage
        whatever verified, and record the verdict with the DA checker."""
        checker = self.chain.data_availability_checker
        if (
            block_root in self._sampled_roots
            or not checker.sampling_pending(block_root)
        ):
            return
        self._sampled_roots.add(block_root)
        have = set(checker.staged_columns(block_root))
        ok, fetched = self.sampling.sample(
            block_root, have, lambda col: self._fetch_column(block_root, col)
        )
        if fetched:
            try:
                self.chain.process_data_column_sidecars(
                    block_root, fetched, verify_header_signature=False
                )
            except ValueError:
                # a peer served a non-verifying sample: counts as a miss
                ok = False
        checker.set_sampling_result(
            block_root, ok, slot=self.chain.slot_clock.now()
        )

    def _fetch_column(self, block_root: bytes, column: int):
        """First peer that serves (and roots) the requested column wins."""
        ident = M.BlobIdentifier(block_root=block_root, index=int(column))
        decode = self.chain.types.DataColumnSidecar.deserialize
        for peer in self.peers.peers():
            try:
                scs = peer.client.data_column_sidecars_by_root([ident], decode)
            except (RpcError, OSError, ValueError):
                continue
            for sc in scs:
                if (
                    int(sc.index) == int(column)
                    and sc.signed_block_header.message.hash_tree_root()
                    == block_root
                ):
                    return sc
        return None

    def _sample_pending(self):
        """Slot-tick retry: staged blocks without a positive sampling
        verdict (their columns raced ahead of the block, no peer held the
        samples yet, or an earlier attempt missed) get one fresh attempt
        per slot edge."""
        checker = self.chain.data_availability_checker
        for root in checker.pending_roots():
            if not checker.staged_columns(root):
                continue  # no column traffic for this block: blob route
            self._sampled_roots.discard(root)  # one fresh attempt per edge
            try:
                self._maybe_sample(root)
                self._import_completed_block(
                    root, checker.check_availability(root)
                )
            except (ValueError, GossipIgnore):
                # AvailabilityCheckError / ignorable import outcome:
                # nothing to relay or penalize on a timer tick
                continue
        # settled roots that left the pending dict no longer need their
        # dedup marker (bound the set across a long run)
        self._sampled_roots &= set(checker.pending_roots(with_block=False))

    # -- publishing -------------------------------------------------------------

    def publish_block(self, signed_block):
        self.gossip.publish(self.topic_block, signed_block.serialize())

    def publish_attestation(self, attestation):
        """Publish on the attestation's own subnet topic
        (compute_subnet_for_attestation over the committee layout)."""
        t = self.chain.types
        data = attestation.data
        try:
            from ..state_processing.accessors import committee_cache_at

            cc = committee_cache_at(
                self.chain.head_state, data.target.epoch, self.chain.E
            )
            subnet = M.compute_subnet_for_attestation(
                cc.committees_per_slot, data.slot, data.index, self.chain.E
            )
        except Exception:  # noqa: BLE001 — unknown epoch: default subnet
            subnet = 0
        self.gossip.publish(
            self.attestation_topics[subnet],
            t.Attestation.serialize_value(attestation),
        )

    def publish_aggregate(self, signed_aggregate):
        self.gossip.publish(self.topic_aggregate, signed_aggregate.serialize())

    def publish_voluntary_exit(self, signed_exit):
        self.gossip.publish(self.topic_exit, signed_exit.serialize())

    def publish_proposer_slashing(self, slashing):
        self.gossip.publish(self.topic_proposer_slashing, slashing.serialize())

    def publish_attester_slashing(self, slashing):
        self.gossip.publish(self.topic_attester_slashing, slashing.serialize())

    def publish_sync_committee_message(self, message):
        self.gossip.publish(self.topic_sync_committee, message.serialize())

    def publish_blob_sidecar(self, sidecar):
        self.gossip.publish(self.topic_blob_sidecar, sidecar.serialize())

    def publish_data_column_sidecar(self, sidecar):
        """Publish a column on its own subnet topic (column j rides
        subnet j % DATA_COLUMN_SIDECAR_SUBNET_COUNT)."""
        subnet = self._column_subnet(sidecar.index, self.chain.E)
        self.gossip.publish(self.data_column_topics[subnet], sidecar.serialize())

    # -- RPC server data providers ----------------------------------------------

    def blocks_by_range(self, start_slot: int, count: int):
        return [signed for _root, signed in self._blocks_by_range_with_roots(
            start_slot, count
        )]

    def _blocks_by_range_with_roots(self, start_slot: int, count: int):
        """Canonical chain walk from head backwards (store-backed); each
        block's root comes free from the walk — never re-hashed."""
        chain = self.chain
        root = chain.head_root
        wanted = range(int(start_slot), int(start_slot) + int(count))
        found = {}
        while root and root != b"\x00" * 32:
            signed = chain._blocks_by_root.get(root) or chain.store.get_block(root)
            if signed is None:
                break
            slot = signed.message.slot
            if slot < int(start_slot):
                break
            if slot in wanted:
                found[slot] = (bytes(root), signed)
            root = signed.message.parent_root
        return [found[slot] for slot in sorted(found)]

    def blocks_by_root(self, roots: list):
        out = []
        for root in roots:
            signed = self.chain._blocks_by_root.get(bytes(root)) or (
                self.chain.store.get_block(bytes(root))
            )
            if signed is not None:
                out.append(signed)
        return out

    def blob_sidecars_by_range(self, start_slot: int, count: int):
        """Sidecars for canonical blocks in [start, start+count) in
        (slot, index) order (deneb/p2p BlobSidecarsByRange)."""
        out = []
        for root, _signed in self._blocks_by_range_with_roots(start_slot, count):
            out.extend(self.chain.store.get_blob_sidecars(root))
        return out

    def blob_sidecars_by_root(self, blob_ids: list):
        out = []
        by_root: dict[bytes, list] = {}
        for bid in blob_ids:
            root = bytes(bid.block_root)
            if root not in by_root:
                by_root[root] = self.chain.store.get_blob_sidecars(root)
            for sc in by_root[root]:
                if int(sc.index) == int(bid.index):
                    out.append(sc)
        return out

    def data_column_sidecars_by_range(
        self, start_slot: int, count: int, columns: list
    ):
        """Column sidecars for canonical blocks in [start, start+count),
        filtered to the requested column indices (peerdas p2p
        DataColumnSidecarsByRange)."""
        wanted = {int(c) for c in columns}
        out = []
        for root, _signed in self._blocks_by_range_with_roots(start_slot, count):
            for sc in self._columns_for_root(root):
                if not wanted or int(sc.index) in wanted:
                    out.append(sc)
        return out

    def data_column_sidecars_by_root(self, column_ids: list):
        out = []
        by_root: dict[bytes, list] = {}
        for cid in column_ids:
            root = bytes(cid.block_root)
            if root not in by_root:
                by_root[root] = self._columns_for_root(root)
            for sc in by_root[root]:
                if int(sc.index) == int(cid.index):
                    out.append(sc)
        return out

    def _columns_for_root(self, root: bytes) -> list:
        """Persisted columns for imported blocks; staged (verified but
        not-yet-imported) columns otherwise — sampling peers must be able
        to serve within the block's own slot, before import lands."""
        stored = self.chain.store.get_data_column_sidecars(root)
        if stored:
            return stored
        staged = self.chain.data_availability_checker.staged_columns(root)
        return [staged[j] for j in sorted(staged)]
