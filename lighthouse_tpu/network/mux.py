"""Stream multiplexing over one (optionally Noise-secured) connection.

The reference muxes every RPC substream over a single transport
connection per peer (libp2p yamux below the eth2 RPC,
lighthouse_network's transport builder). This is the same shape on a
deliberately small frame protocol:

    frame := [u32 stream_id BE][u8 flags][u32 length BE][payload]
    flags:  SYN=1 (open), FIN=2 (half-close), RST=4 (abort)

The initiator allocates odd stream ids, the responder even ones (yamux's
convention). Flow control leans on TCP/Noise backpressure rather than
yamux's explicit windows — at beacon-RPC message sizes (≤4 MiB, framed
in ≤64 KiB chunks) the kernel buffer does the job; this is the one
documented divergence from yamux proper.

`MuxStream` exposes the same socket subset the RPC framing uses
(recv/sendall/settimeout/shutdown/close, plus getpeername and the noise
`remote_peer_id` passthrough), so the protocol layer runs unchanged
whether it sits on a raw socket, a NoiseSocket, or a muxed stream of
either."""

from __future__ import annotations

import struct
import threading
from collections import deque

FLAG_SYN = 1
FLAG_FIN = 2
FLAG_RST = 4

_HDR = struct.Struct(">IBI")
MAX_FRAME_PAYLOAD = 1 << 16


class MuxError(OSError):
    pass


# Underlying-socket timeout: bounds SEND stalls (a peer that stops
# reading cannot wedge publish/RPC forever — the blocked sendall raises
# and the connection is dropped). The reader treats the same timeout as
# an idle no-op and keeps waiting.
_IO_TIMEOUT = 30.0
# Concurrent-substream cap per connection: SYN floods cost the attacker a
# connection, not our thread table.
MAX_STREAMS_PER_CONN = 256
# Per-stream receive-buffer cap: the reader drains the socket eagerly, so
# TCP backpressure alone cannot bound a slow consumer's buffer — a stream
# whose unread bytes exceed this is reset instead of growing without
# limit (2× the biggest legal payload).
MAX_STREAM_BUFFER = 8 << 20


class MuxStream:
    def __init__(self, conn: "MuxedConnection", stream_id: int):
        self._conn = conn
        self.stream_id = stream_id
        self._buf = deque()
        self._buffered = 0  # unread bytes queued in _buf
        self._cond = threading.Condition()
        self._eof = False
        self._reset = False
        self._sent_fin = False
        self._timeout: float | None = None

    # -- receive ---------------------------------------------------------
    def _feed(self, data: bytes) -> bool:
        """Queue received plaintext. False = buffer cap exceeded (the
        connection resets the stream instead of buffering unboundedly)."""
        with self._cond:
            if self._buffered + len(data) > MAX_STREAM_BUFFER:
                return False
            self._buf.append(data)
            self._buffered += len(data)
            self._cond.notify_all()
        return True

    def _feed_eof(self, reset: bool = False):
        with self._cond:
            self._eof = True
            self._reset = self._reset or reset
            self._cond.notify_all()

    def recv(self, n: int) -> bytes:
        with self._cond:
            while not self._buf:
                if self._reset:
                    raise MuxError(f"stream {self.stream_id} reset by peer")
                if self._eof:
                    return b""
                if not self._cond.wait(self._timeout):
                    raise TimeoutError("mux stream read timed out")
            chunk = self._buf[0]
            if len(chunk) <= n:
                self._buf.popleft()
                self._buffered -= len(chunk)
                return chunk
            self._buf[0] = chunk[n:]
            self._buffered -= n
            return chunk[:n]

    # -- send ------------------------------------------------------------
    def sendall(self, data: bytes):
        data = bytes(data)
        for i in range(0, len(data), MAX_FRAME_PAYLOAD):
            self._conn.send_frame(
                self.stream_id, 0, data[i:i + MAX_FRAME_PAYLOAD]
            )

    # -- lifecycle -------------------------------------------------------
    def shutdown(self, how):
        # SHUT_WR semantics: signal end-of-stream to the reader side —
        # the RPC server uses this to delimit streamed responses
        self._send_fin()

    def close(self):
        self._send_fin()
        self._conn._forget(self.stream_id)

    def _send_fin(self):
        if not self._sent_fin:
            self._sent_fin = True
            try:
                self._conn.send_frame(self.stream_id, FLAG_FIN, b"")
            except OSError:
                pass  # connection already gone

    # -- plumbing --------------------------------------------------------
    def settimeout(self, t):
        self._timeout = t

    def getpeername(self):
        return self._conn.getpeername()

    @property
    def remote_peer_id(self):
        # noise identity of the UNDERLYING connection (None on plain TCP)
        return getattr(self._conn._sock, "remote_peer_id", None)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MuxedConnection:
    """One shared connection carrying many logical streams."""

    def __init__(self, sock, initiator: bool, on_stream=None,
                 accept_inbound: bool | None = None):
        # bound send stalls; the reader retries on the same timeout
        try:
            sock.settimeout(_IO_TIMEOUT)
        except OSError:
            pass
        self._sock = sock
        self._initiator = initiator
        self._next_id = 1 if initiator else 2
        self._streams: dict[int, MuxStream] = {}
        self._accept_q: deque[MuxStream] = deque()
        self._accept_cond = threading.Condition()
        self._send_lock = threading.Lock()
        self._id_lock = threading.Lock()
        self._dead = False
        self._on_stream = on_stream  # server callback: fn(stream)
        # whether unsolicited inbound SYNs are accepted at all: a purely
        # outbound (RPC-client) connection RSTs them instead of queueing
        # streams nobody will ever consume
        self._accept_inbound = (
            accept_inbound
            if accept_inbound is not None
            else (on_stream is not None or not initiator)
        )
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name="mux-reader"
        )
        self._reader.start()

    # -- outbound --------------------------------------------------------
    def open_stream(self) -> MuxStream:
        if self._dead:
            raise MuxError("mux connection is closed")
        with self._id_lock:
            sid = self._next_id
            self._next_id += 2
        stream = MuxStream(self, sid)
        self._streams[sid] = stream
        self.send_frame(sid, FLAG_SYN, b"")
        return stream

    def send_frame(self, sid: int, flags: int, payload: bytes):
        if self._dead:
            raise MuxError("mux connection is closed")
        with self._send_lock:
            try:
                self._sock.sendall(_HDR.pack(sid, flags, len(payload)) + payload)
            except OSError:
                self._kill()
                raise

    # -- inbound ---------------------------------------------------------
    def accept(self, timeout: float | None = None) -> MuxStream | None:
        with self._accept_cond:
            while not self._accept_q:
                if self._dead:
                    return None
                if not self._accept_cond.wait(timeout):
                    return None
            return self._accept_q.popleft()

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = self._sock.recv(n - len(buf))
            except TimeoutError:
                continue  # idle is fine; partial progress is preserved
            if not chunk:
                raise MuxError("mux connection closed")
            buf += chunk
        return bytes(buf)

    def _read_loop(self):
        try:
            while True:
                sid, flags, length = _HDR.unpack(self._read_exact(_HDR.size))
                if length > MAX_FRAME_PAYLOAD:
                    # protocol violation: an attacker-claimed length must
                    # not drive the allocation
                    raise MuxError(f"oversized mux frame ({length} bytes)")
                payload = self._read_exact(length) if length else b""
                if flags & FLAG_SYN and sid not in self._streams:
                    if (
                        not self._accept_inbound
                        or len(self._streams) >= MAX_STREAMS_PER_CONN
                    ):
                        # unsolicited (client conn) or flooding: refuse
                        try:
                            self.send_frame(sid, FLAG_RST, b"")
                        except OSError:
                            pass
                        continue
                    stream = MuxStream(self, sid)
                    self._streams[sid] = stream
                    if self._on_stream is not None:
                        threading.Thread(
                            target=self._on_stream,
                            args=(stream,),
                            daemon=True,
                            name=f"mux-stream-{sid}",
                        ).start()
                    else:
                        with self._accept_cond:
                            self._accept_q.append(stream)
                            self._accept_cond.notify()
                stream = self._streams.get(sid)
                if stream is None:
                    continue  # frame for a stream we already forgot
                if payload and not stream._feed(payload):
                    # slow consumer past the buffer cap: reset the stream
                    stream._feed_eof(reset=True)
                    self._forget(sid)
                    try:
                        self.send_frame(sid, FLAG_RST, b"")
                    except OSError:
                        pass
                    continue
                if flags & FLAG_RST:
                    stream._feed_eof(reset=True)
                elif flags & FLAG_FIN:
                    stream._feed_eof()
        except (OSError, struct.error):
            pass
        finally:
            self._kill()

    # -- teardown --------------------------------------------------------
    def _forget(self, sid: int):
        self._streams.pop(sid, None)

    def _kill(self):
        self._dead = True
        for stream in list(self._streams.values()):
            stream._feed_eof(reset=False)
        with self._accept_cond:
            self._accept_cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self):
        self._kill()

    @property
    def alive(self) -> bool:
        return not self._dead

    def getpeername(self):
        return self._sock.getpeername()
