"""Gossipsub v1.1 control-mesh subsystem (libp2p wire-compat milestone 1).

The reference vendors libp2p gossipsub (17k LoC) and tunes it via
gossipsub_scoring_parameters.rs; this package is that layer's shape on
the host transport: SSZ-framed control messages (frames), a rolling
message cache (mcache), the v1.1 peer-score engine (score, params), and
the mesh/gossip behaviour itself (behaviour). NetworkService's
GossipRouter owns one GossipsubBehaviour and bridges it to sockets,
handlers, and the PeerManager.
"""

from .behaviour import (
    DEFERRED,
    GossipsubBehaviour,
    GossipsubConfig,
    _short_topic as short_topic,
)
from .frames import (
    FrameError,
    GraftFrame,
    IHaveFrame,
    IWantFrame,
    PeerRecord,
    PruneFrame,
    PublishFrame,
    SubscriptionFrame,
    decode_frame,
    encode_frame,
)
from .mcache import MessageCache
from .params import beacon_score_params, beacon_score_thresholds
from .score import (
    PeerScore,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)

__all__ = [
    "DEFERRED",
    "FrameError",
    "GossipsubBehaviour",
    "GossipsubConfig",
    "GraftFrame",
    "IHaveFrame",
    "IWantFrame",
    "MessageCache",
    "PeerRecord",
    "PeerScore",
    "PeerScoreParams",
    "PeerScoreThresholds",
    "PruneFrame",
    "PublishFrame",
    "SubscriptionFrame",
    "TopicScoreParams",
    "beacon_score_params",
    "beacon_score_thresholds",
    "decode_frame",
    "encode_frame",
    "short_topic",
]
