"""GossipsubBehaviour: mesh maintenance, lazy gossip, score-gated control.

The behaviour.rs analog sized to this stack: a per-topic mesh (D/D_lo/D_hi
bounds enforced by a heartbeat that GRAFTs under-filled and PRUNEs
over-filled meshes, v1.1 PRUNE backoff + peer exchange), lazy gossip
(IHAVE over the mcache gossip window to D_lazy non-mesh peers, IWANT
pull with promise tracking), and the PeerScore engine gating every
decision: graylisted peers are ignored wholesale, negative-score peers
are never grafted and get pruned, gossip flows only to/from peers above
the gossip threshold, and PX records are accepted only from peers above
the PX threshold. Opportunistic grafting (behaviour.rs heartbeat tail)
re-seeds a mesh whose median score has sagged.

Transport-agnostic: the owner supplies `send(peer_id, frame_bytes)`,
`deliver(topic, data, origin) -> bool | DEFERRED` (app validation; False =
invalid; the `DEFERRED` sentinel means the owner queued validation and
will report the outcome later via `complete_validation` — nothing is
forwarded, scored, or cached until then), and a message-id function. All outgoing frames are computed under the
state lock but SENT after it is released (socket sends serialize on
per-peer locks upstream; holding the mesh lock across them would wedge
every reader thread on one stalled peer). The heartbeat is caller-driven:
pass ticks from a timer thread (NetworkService) or call `heartbeat()`
directly in tests — no wall clock in mesh logic.

Known, accepted ordering race: frames from two threads (e.g. graft_now on
a duty thread vs a concurrent heartbeat prune) may reach a peer in the
opposite order of the local state changes. The resulting asymmetry is
self-correcting within one exchange — the stale GRAFT lands inside the
backoff our PRUNE just set, so the peer refuses it and both sides settle
unmeshed — and serializing sends under the state lock would let one
stalled socket wedge every reader thread, which is the worse trade.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass

from ...metrics import REGISTRY, inc_counter, set_distribution, set_gauge
from ...utils.logging import get_logger
from . import frames as F
from .mcache import MessageCache
from .score import PeerScore, PeerScoreParams, PeerScoreThresholds

log = get_logger("gossipsub")

# Mesh observability (the reference's gossipsub_mesh_peers family):
# per-topic mesh-size gauges are updated every heartbeat; the fixed
# topic kinds are registered eagerly at zero so dashboards (and
# conftest) see the series before the first mesh forms — subnet topics
# (beacon_attestation_<n>, …) appear on their first heartbeat.
for _kind in (
    "beacon_block",
    "beacon_aggregate_and_proof",
    "voluntary_exit",
    "proposer_slashing",
    "attester_slashing",
):
    set_gauge("gossipsub_mesh_peers", 0, topic=_kind)

#: peer-score distribution as a HISTOGRAM: every peer's score observed
#: once per heartbeat. The min/p50/max gauges (set_distribution below)
#: churn with mesh membership; the cumulative buckets don't forget, so
#: rate() over them gives the score distribution over a time window —
#: the signal the event-driven-node work needs to see a slow graylist
#: slide. Buckets track the v1.1 thresholds (-80 graylist / -60 publish
#: / -40 gossip) plus a positive-score ladder.
_PEER_SCORE_HIST = REGISTRY.histogram(
    "gossipsub_peer_score_distribution",
    "per-peer gossipsub score, observed once per heartbeat per peer",
    buckets=(-80.0, -60.0, -40.0, -20.0, -10.0, -5.0, -1.0, 0.0,
             1.0, 5.0, 10.0, 20.0, 50.0, 100.0),
)


@dataclass
class GossipsubConfig:
    """Mesh geometry + heartbeat policy (config.rs defaults)."""

    d: int = 6  # mesh target degree
    d_lo: int = 4  # graft below
    d_hi: int = 12  # prune above
    d_lazy: int = 6  # IHAVE fan-out per topic per heartbeat
    d_score: int = 3  # peers retained by score when pruning an oversized mesh
    history_length: int = 5  # mcache windows kept
    gossip_window: int = 3  # mcache windows advertised in IHAVE
    prune_backoff: int = 16  # heartbeats before a pruned peer may re-GRAFT
    iwant_promise_ticks: int = 3  # heartbeats before an IWANT counts broken
    gossip_retransmission: int = 3  # times one message answers IWANTs
    max_iwant_per_ihave: int = 500
    max_ihave_messages: int = 10  # IHAVE frames honored per peer per heartbeat
    max_ihave_ids: int = 5000  # advertised ids honored per peer per heartbeat
    max_backoff_factor: int = 4  # clamp on remote PRUNE backoff (x our own)
    opportunistic_graft_ticks: int = 8
    opportunistic_graft_peers: int = 2
    flood_publish: bool = True  # self-publish to all peers above publish thr.
    seen_cap: int = 1 << 16


def _short_topic(topic: str) -> str:
    parts = topic.split("/")
    return parts[-2] if len(parts) >= 2 else topic


#: returned by a `deliver` callback that queued validation instead of
#: running it inline (the event-driven gossip path): the behaviour parks
#: the message — no forward, no score, no mcache — until the owner calls
#: `complete_validation` with the real outcome
DEFERRED = object()


class GossipsubBehaviour:
    def __init__(
        self,
        send,
        deliver,
        mid_fn,
        px_provider=None,
        params: PeerScoreParams | None = None,
        thresholds: PeerScoreThresholds | None = None,
        config: GossipsubConfig | None = None,
        seed: int | None = None,
    ):
        self._send = send
        self._deliver = deliver
        self._mid = mid_fn
        self._px_provider = px_provider
        self.config = config or GossipsubConfig()
        self.thresholds = thresholds or PeerScoreThresholds()
        self.score = PeerScore(params)
        self.mcache = MessageCache(
            self.config.history_length, self.config.gossip_window
        )
        self._lock = threading.RLock()
        self._rng = random.Random(seed)
        self.ticks = 0
        self.peers: set[str] = set()
        self.peer_topics: dict[str, set[str]] = {}
        self.subscriptions: set[str] = set()
        self.mesh: dict[str, set[str]] = {}
        #: (topic, peer) -> tick until which GRAFT is refused
        self.backoff: dict[tuple[str, str], int] = {}
        self._seen: dict[bytes, int] = {}
        #: mid -> (peer, deadline tick) for outstanding IWANTs
        self._promises: dict[bytes, tuple[str, int]] = {}
        #: peer -> [ihave frames, advertised ids] this heartbeat (reset
        #: each tick: the libp2p max_ihave_messages/-length budgets)
        self._ihave_budget: dict[str, list[int]] = {}
        #: drained by the owner for dialing (v1.1 PX)
        self._px_candidates: list[tuple[str, str, int]] = []

    # -- helpers ---------------------------------------------------------

    def _flush(self, out: list[tuple[str, bytes]]):
        """Send computed frames AFTER the state lock is released."""
        for peer_id, payload in out:
            self._send(peer_id, payload)

    def _first_sight(self, mid: bytes) -> bool:
        if mid in self._seen:
            return False
        self._seen[mid] = self.ticks
        while len(self._seen) > self.config.seen_cap:
            self._seen.pop(next(iter(self._seen)))
        return True

    def _subscribed_peers(self, topic: str) -> list[str]:
        return [
            p for p in self.peers if topic in self.peer_topics.get(p, ())
        ]

    def _make_prune(self, topic: str, peer_id: str, px: bool) -> bytes:
        records = []
        if px and self._px_provider is not None:
            if self.score.score(peer_id) >= 0:
                for pid, host, port in self._px_provider(topic, peer_id)[
                    : F.MAX_PX_PEERS
                ]:
                    records.append(
                        F.PeerRecord(
                            peer_id=pid.encode()[:96],
                            host=host.encode()[:64],
                            port=port,
                        )
                    )
        inc_counter("gossipsub_prunes_sent_total")
        return F.encode_frame(
            F.PruneFrame(
                topic=topic.encode(),
                backoff=self.config.prune_backoff,
                px=records,
            )
        )

    def _do_prune(
        self, topic: str, peer_id: str, out: list, px: bool = True
    ):
        self.mesh.get(topic, set()).discard(peer_id)
        self.score.prune(peer_id, topic)
        self.backoff[(topic, peer_id)] = self.ticks + self.config.prune_backoff
        out.append((peer_id, self._make_prune(topic, peer_id, px)))

    def _do_graft(self, topic: str, peer_id: str, out: list):
        self.mesh.setdefault(topic, set()).add(peer_id)
        self.score.graft(peer_id, topic)
        inc_counter("gossipsub_grafts_sent_total")
        out.append(
            (peer_id, F.encode_frame(F.GraftFrame(topic=topic.encode())))
        )

    # -- membership ------------------------------------------------------

    def add_peer(self, peer_id: str):
        """A gossip link came up: track the peer and announce our topics."""
        with self._lock:
            self.peers.add(peer_id)
            self.peer_topics.setdefault(peer_id, set())
            self.score.add_peer(peer_id)
            out = [
                (
                    peer_id,
                    F.encode_frame(
                        F.SubscriptionFrame(subscribe=True, topic=t.encode())
                    ),
                )
                for t in sorted(self.subscriptions)
            ]
        self._flush(out)

    def remove_peer(self, peer_id: str):
        with self._lock:
            self.peers.discard(peer_id)
            self.peer_topics.pop(peer_id, None)
            for members in self.mesh.values():
                members.discard(peer_id)
            self.score.remove_peer(peer_id)
            self._ihave_budget.pop(peer_id, None)
            # a departed peer's backoff entries must not leak: cheap peer
            # ids would otherwise grow the table without bound
            for key in [k for k in self.backoff if k[1] == peer_id]:
                del self.backoff[key]

    def subscribe(self, topic: str):
        with self._lock:
            if topic in self.subscriptions:
                return
            self.subscriptions.add(topic)
            self.mesh.setdefault(topic, set())
            out = [
                (
                    p,
                    F.encode_frame(
                        F.SubscriptionFrame(subscribe=True, topic=topic.encode())
                    ),
                )
                for p in self.peers
            ]
        self._flush(out)

    def unsubscribe(self, topic: str):
        with self._lock:
            if topic not in self.subscriptions:
                return
            self.subscriptions.discard(topic)
            out = []
            for p in list(self.mesh.get(topic, ())):
                self._do_prune(topic, p, out, px=True)
            self.mesh.pop(topic, None)
            out.extend(
                (
                    p,
                    F.encode_frame(
                        F.SubscriptionFrame(
                            subscribe=False, topic=topic.encode()
                        )
                    ),
                )
                for p in self.peers
            )
        self._flush(out)

    # -- publishing ------------------------------------------------------

    def publish(self, topic: str, data: bytes):
        """Local publish: eager-push to the mesh (flood_publish widens to
        every subscribed peer above the publish threshold — the reference
        default for our own messages: robustness over bandwidth)."""
        mid = self._mid(data)
        with self._lock:
            if not self._first_sight(mid):
                return
            self.mcache.put(mid, topic, data)
            if self.config.flood_publish:
                targets = [
                    p
                    for p in self._subscribed_peers(topic)
                    if self.score.score(p) >= self.thresholds.publish_threshold
                ]
            else:
                targets = list(self.mesh.get(topic, ()))
                if not targets:
                    subscribed = self._subscribed_peers(topic)
                    targets = self._rng.sample(
                        subscribed, min(self.config.d, len(subscribed))
                    )
            payload = F.encode_frame(
                F.PublishFrame(topic=topic.encode(), data=data)
            )
            out = [(p, payload) for p in targets]
        inc_counter("gossip_messages_total", topic=_short_topic(topic))
        self._flush(out)

    # -- inbound frames --------------------------------------------------

    def handle_frame(self, peer_id: str, frame):
        """Dispatch one decoded control/publish frame from a peer."""
        if isinstance(frame, F.PublishFrame):
            self._handle_publish(
                peer_id, bytes(frame.topic).decode(), bytes(frame.data)
            )
        elif isinstance(frame, F.SubscriptionFrame):
            self._handle_subscription(
                peer_id, bool(frame.subscribe), bytes(frame.topic).decode()
            )
        elif isinstance(frame, F.GraftFrame):
            self._handle_graft(peer_id, bytes(frame.topic).decode())
        elif isinstance(frame, F.PruneFrame):
            self._handle_prune(peer_id, frame)
        elif isinstance(frame, F.IHaveFrame):
            self._handle_ihave(
                peer_id,
                bytes(frame.topic).decode(),
                [bytes(m) for m in frame.message_ids],
            )
        elif isinstance(frame, F.IWantFrame):
            self._handle_iwant(peer_id, [bytes(m) for m in frame.message_ids])

    def _graylisted(self, peer_id: str) -> bool:
        return self.score.score(peer_id) < self.thresholds.graylist_threshold

    def _handle_publish(self, peer_id: str, topic: str, data: bytes):
        mid = self._mid(data)
        with self._lock:
            if self._graylisted(peer_id):
                inc_counter("gossipsub_graylist_dropped_total")
                return
            if topic not in self.subscriptions:
                # real gossipsub drops publishes for unsubscribed topics:
                # caching or P2-crediting them would let junk topics farm
                # score and fill the mcache with 4 MiB frames
                inc_counter("gossipsub_unsubscribed_dropped_total")
                return
            if not self._first_sight(mid):
                self.score.duplicate_delivery(peer_id, topic)
                return
            self._promises.pop(mid, None)
        # validation runs OUTSIDE the lock: chain import is slow and must
        # not serialize the whole mesh behind one message
        valid = self._deliver(topic, data, peer_id)
        if valid is DEFERRED:
            # validation queued (beacon_processor lane): the relay and
            # score decisions wait for complete_validation — the reader
            # thread returns to its socket immediately
            return
        self._finish_validation(topic, data, peer_id, mid, bool(valid))

    def complete_validation(
        self, topic: str, data: bytes, origin: str, valid: bool
    ):
        """Deferred-validation outcome for a message whose `deliver`
        returned DEFERRED: applies exactly the post-validation steps the
        inline path would have — invalid → P4 penalty; valid → mcache,
        P2 credit, eager forward to the mesh (minus the origin). Safe if
        the origin disconnected meanwhile (score ops no-op)."""
        self._finish_validation(topic, data, origin, None, valid)

    def _finish_validation(
        self, topic: str, data: bytes, peer_id: str, mid: bytes | None,
        valid: bool,
    ):
        if valid and mid is None:
            # deferred path: the receive-time mid wasn't carried through
            # the queue hop; recompute only on Accept (the reject path
            # never needs it) and outside the mesh lock
            mid = self._mid(data)
        with self._lock:
            if not valid:
                self.score.invalid_message(peer_id, topic)
                return
            # only validated messages enter the mcache: IWANT must never
            # serve (and IHAVE never advertise) data we rejected
            self.mcache.put(mid, topic, data)
            self.score.first_delivery(peer_id, topic)
            # eager forward: mesh peers only (the gossipsub split); before
            # the first heartbeat forms a mesh, fall back to every
            # subscribed peer so bootstrap relaying is never silent
            members = self.mesh.get(topic) or set(self._subscribed_peers(topic))
            payload = F.encode_frame(
                F.PublishFrame(topic=topic.encode(), data=data)
            )
            out = [(p, payload) for p in members if p != peer_id]
        inc_counter("gossip_messages_total", topic=_short_topic(topic))
        self._flush(out)

    #: cap on tracked subscriptions per peer: a junk-topic flood must not
    #: grow per-peer state (and score() iteration cost) without bound
    MAX_PEER_TOPICS = 1024

    def _handle_subscription(self, peer_id: str, subscribe: bool, topic: str):
        with self._lock:
            if peer_id not in self.peers:
                return  # in-flight frame racing a disconnect: no ghosts
            topics = self.peer_topics.setdefault(peer_id, set())
            if subscribe:
                if len(topics) < self.MAX_PEER_TOPICS:
                    topics.add(topic)
            else:
                topics.discard(topic)
                self.mesh.get(topic, set()).discard(peer_id)
                if topic in self.subscriptions:
                    self.score.prune(peer_id, topic)

    def _handle_graft(self, peer_id: str, topic: str):
        with self._lock:
            if peer_id not in self.peers or self._graylisted(peer_id):
                return
            out: list[tuple[str, bytes]] = []
            if topic not in self.subscriptions:
                # refuse without tracking the topic: junk-topic GRAFTs
                # must not create per-peer state
                out.append((peer_id, self._make_prune(topic, peer_id, px=False)))
            else:
                # a GRAFT on one of our topics implies the peer subscribes
                self.peer_topics.setdefault(peer_id, set()).add(topic)
                if self.backoff.get((topic, peer_id), 0) > self.ticks:
                    # v1.1: grafting through backoff is a protocol violation
                    self.score.behaviour_penalty(peer_id)
                    self._do_prune(topic, peer_id, out, px=False)
                elif self.score.score(peer_id) < 0:
                    self._do_prune(topic, peer_id, out, px=False)
                elif peer_id in self.mesh.setdefault(topic, set()):
                    # duplicate GRAFT: membership unchanged, and crucially
                    # the P1/P3 mesh_time clock is NOT reset — re-GRAFTing
                    # must not dodge the delivery-deficit activation
                    pass
                elif len(self.mesh[topic]) >= self.config.d_hi:
                    self._do_prune(topic, peer_id, out, px=True)
                else:
                    self.mesh[topic].add(peer_id)
                    self.score.graft(peer_id, topic)
                    inc_counter("gossipsub_grafts_received_total")
        self._flush(out)

    def _handle_prune(self, peer_id: str, frame: F.PruneFrame):
        topic = bytes(frame.topic).decode()
        with self._lock:
            if peer_id not in self.peers or self._graylisted(peer_id):
                return
            if topic not in self.subscriptions:
                return  # junk-topic PRUNEs must not create backoff/score state
            self.mesh.get(topic, set()).discard(peer_id)
            self.score.prune(peer_id, topic)
            # clamp the remote-supplied backoff: an unclamped uint64 would
            # be a permanent entry the heartbeat cleanup can never expire
            backoff = min(
                int(frame.backoff) or self.config.prune_backoff,
                self.config.prune_backoff * self.config.max_backoff_factor,
            )
            self.backoff[(topic, peer_id)] = self.ticks + backoff
            inc_counter("gossipsub_prunes_received_total")
            if (
                len(frame.px)
                and self.score.score(peer_id)
                >= self.thresholds.accept_px_threshold
            ):
                for rec in frame.px:
                    self._px_candidates.append(
                        (
                            bytes(rec.peer_id).decode(errors="replace"),
                            bytes(rec.host).decode(errors="replace"),
                            int(rec.port),
                        )
                    )

    def _handle_ihave(self, peer_id: str, topic: str, mids: list[bytes]):
        with self._lock:
            inc_counter("gossipsub_ihave_received_total")
            if self.score.score(peer_id) < self.thresholds.gossip_threshold:
                return
            if topic not in self.subscriptions:
                return
            # per-peer per-heartbeat budget (libp2p max_ihave_messages /
            # max_ihave_length): without it one peer could grow _promises
            # and elicit IWANT replies proportionally to its send rate
            budget = self._ihave_budget.setdefault(peer_id, [0, 0])
            budget[0] += 1
            if budget[0] > self.config.max_ihave_messages:
                return
            id_room = self.config.max_ihave_ids - budget[1]
            if id_room <= 0:
                return
            mids = mids[: min(self.config.max_iwant_per_ihave, id_room)]
            budget[1] += len(mids)
            wanted = [
                m
                for m in mids
                if m not in self._seen and m not in self._promises
            ]
            if not wanted:
                return
            deadline = self.ticks + self.config.iwant_promise_ticks
            for m in wanted:
                self._promises[m] = (peer_id, deadline)
            inc_counter("gossipsub_iwant_sent_total", amount=len(wanted))
            out = [
                (peer_id, F.encode_frame(F.IWantFrame(message_ids=wanted)))
            ]
        self._flush(out)

    def _handle_iwant(self, peer_id: str, mids: list[bytes]):
        with self._lock:
            inc_counter("gossipsub_iwant_received_total")
            if self.score.score(peer_id) < self.thresholds.gossip_threshold:
                return
            out = []
            served = 0
            for m in mids:
                entry = self.mcache.get_for_iwant(
                    m, peer_id, self.config.gossip_retransmission
                )
                if entry is None:
                    continue
                topic, data = entry
                out.append(
                    (
                        peer_id,
                        F.encode_frame(
                            F.PublishFrame(topic=topic.encode(), data=data)
                        ),
                    )
                )
                served += 1
            if served:
                inc_counter("gossipsub_iwant_served_total", amount=served)
        self._flush(out)

    # -- heartbeat -------------------------------------------------------

    def heartbeat(self):
        """One mesh-maintenance round; call at a fixed cadence."""
        cfg = self.config
        with self._lock:
            self.ticks += 1
            self.score.refresh()
            self._ihave_budget.clear()
            for key in [k for k, t in self.backoff.items() if t <= self.ticks]:
                del self.backoff[key]
            out: list[tuple[str, bytes]] = []
            scores = {p: self.score.score(p) for p in self.peers}
            for topic in self.subscriptions:
                members = self.mesh.setdefault(topic, set())
                # evict: gone, unsubscribed, or negative-score members
                for p in list(members):
                    if p not in self.peers or topic not in self.peer_topics.get(
                        p, ()
                    ):
                        members.discard(p)
                        self.score.prune(p, topic)
                    elif scores[p] < 0:
                        self._do_prune(topic, p, out, px=False)
                candidates = [
                    p
                    for p in self._subscribed_peers(topic)
                    if p not in members
                    and scores[p] >= 0
                    and self.backoff.get((topic, p), 0) <= self.ticks
                ]
                if len(members) < cfg.d_lo and candidates:
                    self._rng.shuffle(candidates)
                    for p in candidates[: cfg.d - len(members)]:
                        self._do_graft(topic, p, out)
                elif len(members) > cfg.d_hi:
                    # score-aware pruning: keep the best d_score outright,
                    # fill the rest of D at random (v1.1 §3.3)
                    ranked = sorted(
                        members, key=lambda p: scores[p], reverse=True
                    )
                    keep = ranked[: cfg.d_score]
                    rest = ranked[cfg.d_score :]
                    self._rng.shuffle(rest)
                    keep += rest[: cfg.d - len(keep)]
                    for p in set(members) - set(keep):
                        self._do_prune(topic, p, out, px=True)
                elif (
                    self.ticks % cfg.opportunistic_graft_ticks == 0
                    and len(members) >= 2
                ):
                    ranked = sorted(scores[p] for p in members)
                    median = ranked[len(ranked) // 2]
                    if median < self.thresholds.opportunistic_graft_threshold:
                        uppers = [
                            p
                            for p in candidates
                            if scores[p] > max(median, 0.0)
                        ]
                        self._rng.shuffle(uppers)
                        for p in uppers[: cfg.opportunistic_graft_peers]:
                            self._do_graft(topic, p, out)
            # lazy gossip: IHAVE the gossip window to non-mesh peers
            for topic in self.mcache.topics_in_gossip_window():
                if topic not in self.subscriptions:
                    continue
                mids = self.mcache.gossip_ids(topic)
                if not mids:
                    continue
                members = self.mesh.get(topic, set())
                lazy = [
                    p
                    for p in self._subscribed_peers(topic)
                    if p not in members
                    and scores[p] >= self.thresholds.gossip_threshold
                ]
                self._rng.shuffle(lazy)
                payload = F.encode_frame(
                    F.IHaveFrame(
                        topic=topic.encode(),
                        message_ids=mids[: F.MAX_MESSAGE_IDS],
                    )
                )
                out.extend((p, payload) for p in lazy[: cfg.d_lazy])
            # broken IWANT promises -> behaviour penalty
            for mid in [
                m for m, (_, dl) in self._promises.items() if dl <= self.ticks
            ]:
                peer_id, _ = self._promises.pop(mid)
                if mid not in self._seen:
                    self.score.behaviour_penalty(peer_id)
                    inc_counter("gossipsub_broken_promises_total")
            self.mcache.shift()
            for topic, members in self.mesh.items():
                if members or topic in self.subscriptions:
                    set_gauge(
                        "gossipsub_mesh_peers",
                        len(members),
                        topic=_short_topic(topic),
                    )
            if scores:
                set_distribution("gossipsub_peer_score", scores.values())
                for v in scores.values():
                    _PEER_SCORE_HIST.observe(v)
        self._flush(out)

    # -- owner accessors -------------------------------------------------

    def mesh_peers(self, topic: str) -> set[str]:
        with self._lock:
            return set(self.mesh.get(topic, ()))

    def peer_score(self, peer_id: str) -> float:
        with self._lock:
            return self.score.score(peer_id)

    def graft_now(self, topic: str):
        """Eagerly fill one topic's mesh (duty subnets shouldn't wait for
        the next heartbeat). Requires a prior subscribe(): silently
        adding the subscription here would skip the SUBSCRIBE broadcast
        and leave us invisible to the topic's flood/gossip emitters."""
        cfg = self.config
        with self._lock:
            if topic not in self.subscriptions:
                return
            members = self.mesh.setdefault(topic, set())
            out: list[tuple[str, bytes]] = []
            candidates = [
                p
                for p in self._subscribed_peers(topic)
                if p not in members
                and self.score.score(p) >= 0
                and self.backoff.get((topic, p), 0) <= self.ticks
            ]
            self._rng.shuffle(candidates)
            for p in candidates[: cfg.d - len(members)]:
                self._do_graft(topic, p, out)
        self._flush(out)

    def take_px_candidates(self) -> list[tuple[str, str, int]]:
        with self._lock:
            out, self._px_candidates = self._px_candidates, []
            return out

    def seen(self, mid: bytes) -> bool:
        with self._lock:
            return mid in self._seen
