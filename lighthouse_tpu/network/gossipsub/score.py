"""Gossipsub v1.1 peer scoring engine.

The parameter families of the reference's vendored peer_score.rs and the
GossipSub paper (Vyzovitis et al. §4): per-topic P1 time-in-mesh,
P2 first-message-deliveries, P3 mesh-message-delivery deficit,
P4 invalid-message penalty — combined under per-topic weights and a
positive-contribution cap — plus the global P7 behaviour penalty
(backoff violations, broken IWANT promises). P5 (app-specific) is an
optional callback; P6 (IP colocation) has no analog on a host-local
transport. Counters decay once per heartbeat via `refresh()`, which is
also the time base for P1 and the P3 activation window, so scoring unit
tests are fully deterministic — no wall clock anywhere.

Score thresholds (the v1.1 gating points) live in `PeerScoreThresholds`:
gossip emission, self-publish flood, graylisting, peer-exchange
acceptance, and opportunistic grafting all check against them.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class TopicScoreParams:
    """One topic's parameter family (TopicScoreParams in peer_score)."""

    topic_weight: float = 1.0
    # P1: time in mesh (units: heartbeats, capped)
    time_in_mesh_weight: float = 0.02
    time_in_mesh_cap: float = 300.0
    # P2: first message deliveries (decaying counter, capped)
    first_message_deliveries_weight: float = 1.0
    first_message_deliveries_decay: float = 0.9
    first_message_deliveries_cap: float = 100.0
    # P3: mesh message delivery deficit — squared shortfall below the
    # threshold, active only after `activation` heartbeats in the mesh
    # (weight <= 0; 0 disables)
    mesh_message_deliveries_weight: float = 0.0
    mesh_message_deliveries_decay: float = 0.9
    mesh_message_deliveries_cap: float = 100.0
    mesh_message_deliveries_threshold: float = 4.0
    mesh_message_deliveries_activation: int = 8
    # P4: invalid messages — squared decaying counter (weight <= 0)
    invalid_message_deliveries_weight: float = -2.0
    invalid_message_deliveries_decay: float = 0.99


@dataclass
class PeerScoreParams:
    topics: dict[str, TopicScoreParams] = field(default_factory=dict)
    #: fallback family for topics without an explicit entry
    default_topic: TopicScoreParams = field(default_factory=TopicScoreParams)
    #: cap on the summed POSITIVE topic contributions (negatives always count)
    topic_score_cap: float = 100.0
    # P7: behaviour penalty (squared decaying counter beyond a grace threshold)
    behaviour_penalty_weight: float = -5.0
    behaviour_penalty_decay: float = 0.9
    behaviour_penalty_threshold: float = 0.0
    #: decayed counters below this snap to 0 (decay_to_zero)
    decay_to_zero: float = 0.01
    #: optional P5 hook: peer_id -> float, added with weight 1
    app_specific: object | None = None

    def for_topic(self, topic: str) -> TopicScoreParams:
        return self.topics.get(topic, self.default_topic)


@dataclass
class PeerScoreThresholds:
    """v1.1 gating thresholds (PeerScoreThresholds in the reference)."""

    gossip_threshold: float = -40.0  # below: no IHAVE to/from the peer
    publish_threshold: float = -60.0  # below: excluded from flood publish
    graylist_threshold: float = -80.0  # below: all frames ignored
    accept_px_threshold: float = 10.0  # PX only from peers above this
    opportunistic_graft_threshold: float = 1.0  # graft when mesh median below


class _TopicStats:
    __slots__ = (
        "in_mesh",
        "mesh_time",
        "first_message_deliveries",
        "mesh_message_deliveries",
        "invalid_message_deliveries",
    )

    def __init__(self):
        self.in_mesh = False
        self.mesh_time = 0  # heartbeats since graft
        self.first_message_deliveries = 0.0
        self.mesh_message_deliveries = 0.0
        self.invalid_message_deliveries = 0.0


class _PeerStats:
    __slots__ = ("topics", "behaviour_penalty")

    def __init__(self):
        self.topics: dict[str, _TopicStats] = {}
        self.behaviour_penalty = 0.0

    def topic(self, t: str) -> _TopicStats:
        s = self.topics.get(t)
        if s is None:
            s = self.topics[t] = _TopicStats()
        return s


class PeerScore:
    """Per-peer score state + the weighted-sum evaluation."""

    def __init__(self, params: PeerScoreParams | None = None):
        self.params = params or PeerScoreParams()
        self._peers: dict[str, _PeerStats] = {}

    # -- membership ------------------------------------------------------

    def add_peer(self, peer_id: str):
        self._peers.setdefault(peer_id, _PeerStats())

    def remove_peer(self, peer_id: str):
        self._peers.pop(peer_id, None)

    def known(self, peer_id: str) -> bool:
        return peer_id in self._peers

    # -- event observations ---------------------------------------------

    def graft(self, peer_id: str, topic: str):
        s = self._peers.setdefault(peer_id, _PeerStats()).topic(topic)
        s.in_mesh = True
        s.mesh_time = 0

    def prune(self, peer_id: str, topic: str):
        p = self._peers.get(peer_id)
        if p is not None:
            s = p.topic(topic)
            s.in_mesh = False
            s.mesh_time = 0

    def first_delivery(self, peer_id: str, topic: str):
        """Peer was the first to deliver a valid message (P2; counts for
        P3 too when the peer is a mesh member)."""
        p = self._peers.get(peer_id)
        if p is None:
            return
        tp = self.params.for_topic(topic)
        s = p.topic(topic)
        s.first_message_deliveries = min(
            tp.first_message_deliveries_cap, s.first_message_deliveries + 1
        )
        if s.in_mesh:
            s.mesh_message_deliveries = min(
                tp.mesh_message_deliveries_cap, s.mesh_message_deliveries + 1
            )

    def duplicate_delivery(self, peer_id: str, topic: str):
        """A (timely) duplicate from a mesh member still counts toward its
        mesh delivery quota (P3) — eager push doing its job."""
        p = self._peers.get(peer_id)
        if p is None:
            return
        s = p.topic(topic)
        if s.in_mesh:
            tp = self.params.for_topic(topic)
            s.mesh_message_deliveries = min(
                tp.mesh_message_deliveries_cap, s.mesh_message_deliveries + 1
            )

    def invalid_message(self, peer_id: str, topic: str):
        p = self._peers.get(peer_id)
        if p is not None:
            p.topic(topic).invalid_message_deliveries += 1

    def behaviour_penalty(self, peer_id: str, count: float = 1.0):
        """P7: backoff-violating GRAFTs, broken IWANT promises."""
        p = self._peers.get(peer_id)
        if p is not None:
            p.behaviour_penalty += count

    # -- evaluation ------------------------------------------------------

    def score(self, peer_id: str) -> float:
        p = self._peers.get(peer_id)
        if p is None:
            return 0.0
        params = self.params
        positive_topics = 0.0
        negative_topics = 0.0
        for topic, s in p.topics.items():
            tp = params.for_topic(topic)
            t_score = 0.0
            if s.in_mesh:
                t_score += tp.time_in_mesh_weight * min(
                    float(s.mesh_time), tp.time_in_mesh_cap
                )
            t_score += (
                tp.first_message_deliveries_weight * s.first_message_deliveries
            )
            if (
                tp.mesh_message_deliveries_weight < 0
                and s.in_mesh
                and s.mesh_time >= tp.mesh_message_deliveries_activation
                and s.mesh_message_deliveries
                < tp.mesh_message_deliveries_threshold
            ):
                deficit = (
                    tp.mesh_message_deliveries_threshold
                    - s.mesh_message_deliveries
                )
                t_score += tp.mesh_message_deliveries_weight * deficit * deficit
            t_score += tp.invalid_message_deliveries_weight * (
                s.invalid_message_deliveries * s.invalid_message_deliveries
            )
            weighted = tp.topic_weight * t_score
            if weighted > 0:
                positive_topics += weighted
            else:
                negative_topics += weighted
        total = min(positive_topics, params.topic_score_cap) + negative_topics
        excess = p.behaviour_penalty - params.behaviour_penalty_threshold
        if excess > 0:
            total += params.behaviour_penalty_weight * excess * excess
        if params.app_specific is not None:
            total += params.app_specific(peer_id)
        return total

    def scores(self) -> dict[str, float]:
        return {pid: self.score(pid) for pid in self._peers}

    # -- decay / time base ----------------------------------------------

    def refresh(self):
        """Once per heartbeat: decay counters, advance time-in-mesh."""
        params = self.params
        zero = params.decay_to_zero
        for p in self._peers.values():
            for topic, s in p.topics.items():
                tp = params.for_topic(topic)
                s.first_message_deliveries *= tp.first_message_deliveries_decay
                if s.first_message_deliveries < zero:
                    s.first_message_deliveries = 0.0
                s.mesh_message_deliveries *= tp.mesh_message_deliveries_decay
                if s.mesh_message_deliveries < zero:
                    s.mesh_message_deliveries = 0.0
                s.invalid_message_deliveries *= (
                    tp.invalid_message_deliveries_decay
                )
                if s.invalid_message_deliveries < zero:
                    s.invalid_message_deliveries = 0.0
                if s.in_mesh:
                    s.mesh_time += 1
            p.behaviour_penalty *= params.behaviour_penalty_decay
            if p.behaviour_penalty < zero:
                p.behaviour_penalty = 0.0
