"""Beacon-chain scoring parameters (gossipsub_scoring_parameters.rs analog).

The reference derives per-topic weights from spec constants (expected
message rates per slot/epoch); this sizes the same *structure* to the
simulator's scale: beacon_block carries the most weight, aggregates half,
and the 64 attestation subnets split one block-equivalent between them —
so no single subnet can mint (or cost) as much score as block gossip.
Invalid messages are weighted so that a handful of garbage frames on any
topic outweighs all achievable positive score (the paper's "penalties
dominate" design rule), while the PeerManager's ban threshold (4 invalid
reports) still fires before the default graylist for plain flooding —
banning is the outer defense, graylisting the mesh-local one.
"""

from __future__ import annotations

from .score import PeerScoreParams, PeerScoreThresholds, TopicScoreParams

#: mesh delivery deficit stays disabled (weight 0) by default: at
#: simulator node counts a quiet-but-honest peer would otherwise bleed
#: score during empty slots. The engine supports it; opt in per-topic.


def _topic_family(weight: float, first_cap: float) -> TopicScoreParams:
    return TopicScoreParams(
        topic_weight=weight,
        time_in_mesh_weight=0.02,
        time_in_mesh_cap=300.0,
        first_message_deliveries_weight=1.0,
        first_message_deliveries_decay=0.9,
        first_message_deliveries_cap=first_cap,
        mesh_message_deliveries_weight=0.0,
        invalid_message_deliveries_weight=-2.0,
        invalid_message_deliveries_decay=0.99,
    )


def beacon_score_params(
    block_topic: str,
    aggregate_topic: str,
    attestation_topics: dict[int, str] | None = None,
    extra_topics: list[str] | None = None,
) -> PeerScoreParams:
    """Parameter set for the beacon topic families, keyed by the node's
    actual fork-digest topic strings."""
    topics: dict[str, TopicScoreParams] = {
        block_topic: _topic_family(weight=1.0, first_cap=100.0),
        aggregate_topic: _topic_family(weight=0.5, first_cap=200.0),
    }
    for topic in (attestation_topics or {}).values():
        # 64 subnets share one block-equivalent of weight
        topics[topic] = _topic_family(weight=1.0 / 64.0, first_cap=300.0)
    for topic in extra_topics or []:
        topics[topic] = _topic_family(weight=0.25, first_cap=50.0)
    return PeerScoreParams(
        topics=topics,
        default_topic=_topic_family(weight=0.25, first_cap=50.0),
        topic_score_cap=100.0,
        behaviour_penalty_weight=-5.0,
        behaviour_penalty_decay=0.9,
    )


def beacon_score_thresholds() -> PeerScoreThresholds:
    return PeerScoreThresholds(
        gossip_threshold=-40.0,
        publish_threshold=-60.0,
        graylist_threshold=-80.0,
        accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
