"""Rolling message cache (gossipsub mcache.rs analog).

Holds full messages for `history_length` heartbeat windows; the most
recent `gossip_window` windows feed IHAVE emission, while IWANT can be
answered from anywhere in the history. `shift()` runs once per heartbeat
and drops the oldest window's entries.
"""

from __future__ import annotations


class MessageCache:
    def __init__(self, history_length: int = 5, gossip_window: int = 3):
        if not 0 < gossip_window <= history_length:
            raise ValueError("gossip_window must be in (0, history_length]")
        self.history_length = history_length
        self.gossip_window = gossip_window
        #: newest window first; each window is a list of (mid, topic)
        self._windows: list[list[tuple[bytes, str]]] = [[]]
        self._msgs: dict[bytes, tuple[str, bytes]] = {}
        #: (mid -> peer -> serves): IWANT anti-spam counted PER REQUESTER
        #: (libp2p gossip_retransmission) — a global count would refuse
        #: honest requesters once d_lazy > the cap, and their broken
        #: promises would then penalize US
        self._transmits: dict[bytes, dict[str, int]] = {}

    def put(self, mid: bytes, topic: str, data: bytes):
        if mid in self._msgs:
            return
        self._msgs[mid] = (topic, data)
        self._transmits[mid] = {}
        self._windows[0].append((mid, topic))

    def get(self, mid: bytes) -> tuple[str, bytes] | None:
        return self._msgs.get(mid)

    def get_for_iwant(
        self, mid: bytes, peer_id: str, limit: int
    ) -> tuple[str, bytes] | None:
        """Fetch for an IWANT response, counting the retransmission; None
        once THIS requester has been served `limit` times."""
        entry = self._msgs.get(mid)
        if entry is None:
            return None
        counts = self._transmits[mid]
        if counts.get(peer_id, 0) >= limit:
            return None
        counts[peer_id] = counts.get(peer_id, 0) + 1
        return entry

    def gossip_ids(self, topic: str) -> list[bytes]:
        """Message ids in the gossip window for one topic (IHAVE payload)."""
        out = []
        for window in self._windows[: self.gossip_window]:
            out.extend(mid for mid, t in window if t == topic)
        return out

    def topics_in_gossip_window(self) -> set[str]:
        return {
            t for window in self._windows[: self.gossip_window] for _, t in window
        }

    def shift(self):
        """Heartbeat rotation: age every window, drop the oldest."""
        self._windows.insert(0, [])
        while len(self._windows) > self.history_length:
            for mid, _topic in self._windows.pop():
                self._msgs.pop(mid, None)
                self._transmits.pop(mid, None)

    def __len__(self) -> int:
        return len(self._msgs)
