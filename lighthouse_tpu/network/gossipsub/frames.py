"""Gossipsub v1.1 control-frame wire encoding.

The reference vendors libp2p gossipsub whose RPC is protobuf
(gossipsub/src/protocol.rs, rpc_proto); this stack is SSZ end-to-end, so
control frames are SSZ containers behind a 1-byte frame tag — the same
IHAVE/IWANT/GRAFT/PRUNE/SUBSCRIBE vocabulary (v1.1 adds PRUNE backoff +
peer-exchange records) carried over the persistent gossip stream that
previously carried only naive topic-framed publishes.

Frame layout: `<tag u8><ssz body>`. Message ids are the 20-byte spec
gossip message-id (SHA256(domain + data)[:20], network/messages.py), so
IHAVE/IWANT lists pack as fixed Bytes20 vectors. Golden encodings are
pinned in tests/test_gossipsub_frames.py.
"""

from __future__ import annotations

from ...ssz.core import (
    ByteList,
    ByteVector,
    Container,
    DeserializationError,
    List,
    boolean,
    uint16,
    uint64,
)

Bytes20 = ByteVector[20]

#: frame tags (the u8 envelope discriminant)
TAG_PUBLISH = 0
TAG_SUBSCRIBE = 1
TAG_GRAFT = 2
TAG_PRUNE = 3
TAG_IHAVE = 4
TAG_IWANT = 5

MAX_TOPIC_LEN = 256
MAX_MESSAGE_IDS = 5000  # libp2p default max_ihave_length
MAX_PX_PEERS = 16
MAX_GOSSIP_DATA = 1 << 22  # matches rpc.MAX_PAYLOAD


class PublishFrame(Container):
    """A full message: eager push to mesh peers, or an IWANT response."""

    topic: ByteList[MAX_TOPIC_LEN]
    data: ByteList[MAX_GOSSIP_DATA]


class SubscriptionFrame(Container):
    """SUBSCRIBE/UNSUBSCRIBE announcement (subscribe=False leaves)."""

    subscribe: boolean
    topic: ByteList[MAX_TOPIC_LEN]


class GraftFrame(Container):
    """GRAFT: add me to your mesh for this topic."""

    topic: ByteList[MAX_TOPIC_LEN]


class PeerRecord(Container):
    """v1.1 peer-exchange record carried on PRUNE: enough for the pruned
    peer to dial a replacement (signed ENRs in the reference; here the
    noise peer id plus the host/port the record-holder dialed)."""

    peer_id: ByteList[96]
    host: ByteList[64]
    port: uint16


class PruneFrame(Container):
    """PRUNE: removal from the mesh, with v1.1 backoff (heartbeats the
    pruned peer must wait before re-GRAFTing) and peer-exchange records."""

    topic: ByteList[MAX_TOPIC_LEN]
    backoff: uint64
    px: List[PeerRecord, MAX_PX_PEERS]


class IHaveFrame(Container):
    """Lazy gossip: message ids seen recently on a topic."""

    topic: ByteList[MAX_TOPIC_LEN]
    message_ids: List[Bytes20, MAX_MESSAGE_IDS]


class IWantFrame(Container):
    """Pull request for full messages advertised via IHAVE."""

    message_ids: List[Bytes20, MAX_MESSAGE_IDS]


_FRAME_TYPES = {
    TAG_PUBLISH: PublishFrame,
    TAG_SUBSCRIBE: SubscriptionFrame,
    TAG_GRAFT: GraftFrame,
    TAG_PRUNE: PruneFrame,
    TAG_IHAVE: IHaveFrame,
    TAG_IWANT: IWantFrame,
}
_TAG_OF = {cls: tag for tag, cls in _FRAME_TYPES.items()}


class FrameError(ValueError):
    pass


def encode_frame(frame) -> bytes:
    tag = _TAG_OF.get(type(frame))
    if tag is None:
        raise FrameError(f"not a gossipsub frame: {type(frame).__name__}")
    return bytes([tag]) + frame.serialize()


def decode_frame(data: bytes):
    if not data:
        raise FrameError("empty frame")
    cls = _FRAME_TYPES.get(data[0])
    if cls is None:
        raise FrameError(f"unknown frame tag {data[0]}")
    try:
        return cls.deserialize(data[1:])
    except (DeserializationError, ValueError, IndexError) as e:
        raise FrameError(f"bad {cls.__name__}: {e}") from e
