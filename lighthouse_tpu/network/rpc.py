"""Req/resp RPC over TCP with SSZ-snappy payloads.

Mirrors lighthouse_network's rpc stack (src/rpc/{methods,protocol,codec}):
each stream opens with a length-prefixed protocol id (the multistream
negotiation, collapsed to its essential byte exchange), the request is one
varint-length-prefixed ssz_snappy payload, and responses are chunks of
`<result byte><varint len><ssz_snappy payload>` — result 0 = success,
1 = invalid request, 2 = server error (p2p-interface.md resp encoding).
Transport security (noise) and muxing (yamux) sit below this layer in the
reference; here each stream is one TCP connection on the host network."""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time

from ..metrics import REGISTRY, inc_counter
from ..utils.snappy import compress, decompress
from . import messages as M

RESP_SUCCESS = 0
RESP_INVALID_REQUEST = 1
RESP_SERVER_ERROR = 2
RESP_RATE_LIMITED = 3  # p2p-interface ResourceUnavailable-class refusal

MAX_PAYLOAD = 1 << 22  # 4 MiB cap (gossip_max_size class bound)
MAX_REQUEST_BLOCKS = 1024
MAX_REQUEST_BLOB_SIDECARS = 768  # deneb p2p: 128 blocks × 6 blobs
MAX_REQUEST_DATA_COLUMN_SIDECARS = 16384  # peerdas p2p: 128 blocks × 128 cols

#: protocol id → short method name for per-method latency metrics (the
#: `proto.split("/")[-3]` component the request counters already use)
_RPC_METHODS = {
    proto: proto.split("/")[-3]
    for proto in (
        M.PROTO_STATUS,
        M.PROTO_PING,
        M.PROTO_METADATA,
        M.PROTO_GOODBYE,
        M.PROTO_BLOCKS_BY_RANGE,
        M.PROTO_BLOCKS_BY_ROOT,
        M.PROTO_BLOBS_BY_RANGE,
        M.PROTO_BLOBS_BY_ROOT,
        M.PROTO_DATA_COLUMNS_BY_RANGE,
        M.PROTO_DATA_COLUMNS_BY_ROOT,
    )
}
#: request-latency buckets: local-loopback pings are sub-ms, a clamped
#: 1024-block ByRange stream can take seconds
_RPC_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0,
)
# Per-method request-latency histograms, eagerly registered (conftest
# asserts the series): server side measures decode→response-complete,
# client side measures dial/substream-open→last-chunk — the number the
# sync engine's peer selection would want to rank on.
_SERVER_SECONDS = {
    proto: REGISTRY.histogram(
        # lint: allow(metric-hygiene) -- bounded by the protocol table
        f"rpc_server_request_seconds_{method}",
        f"server-side request handling wall time: {method}",
        buckets=_RPC_LATENCY_BUCKETS,
    )
    for proto, method in _RPC_METHODS.items()
}
_CLIENT_SECONDS = {
    proto: REGISTRY.histogram(
        # lint: allow(metric-hygiene) -- bounded by the protocol table
        f"rpc_client_request_seconds_{method}",
        f"client-side request round-trip wall time: {method}",
        buckets=_RPC_LATENCY_BUCKETS,
    )
    for proto, method in _RPC_METHODS.items()
}


class _TimedClientRequest:
    """Observe dial→last-chunk wall time into the per-method client
    histogram on exit (failures and refusals included — they are the
    latency the caller experienced)."""

    __slots__ = ("_proto", "_t0")

    def __init__(self, proto: str):
        self._proto = proto

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        hist = _CLIENT_SECONDS.get(self._proto)
        if hist is not None:
            hist.observe(time.perf_counter() - self._t0)
        return False


class RpcError(RuntimeError):
    pass


# -- framing ------------------------------------------------------------------


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(sock, first_byte: bytes | None = None) -> int:
    out = 0
    shift = 0
    while True:
        if first_byte is not None:
            b = first_byte[0]
            first_byte = None
        else:
            b = _read_exact(sock, 1)[0]
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out
        shift += 7
        if shift > 35:
            raise RpcError("varint too long")


def _read_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise RpcError("connection closed")
        buf += chunk
    return bytes(buf)


def _send_block(sock, data: bytes):
    """ssz_snappy payload: <varint uncompressed-len><compressed-len u32>
    <snappy frames>. The spec relies on stream framing for the compressed
    boundary; over raw TCP an explicit length prefix carries it."""
    if len(data) > MAX_PAYLOAD:
        raise RpcError("payload too large")
    comp = compress(data)
    sock.sendall(_write_varint(len(data)) + struct.pack("<I", len(comp)) + comp)


def _recv_block(sock, first_byte: bytes | None = None) -> bytes:
    expected = _read_varint(sock, first_byte)
    if expected > MAX_PAYLOAD:
        raise RpcError("payload too large")
    comp_len = struct.unpack("<I", _read_exact(sock, 4))[0]
    if comp_len > MAX_PAYLOAD * 2:
        raise RpcError("compressed payload too large")
    data = decompress(_read_exact(sock, comp_len))
    if len(data) != expected:
        raise RpcError("length prefix mismatch")
    return data


def _send_protocol(sock, proto: str):
    raw = proto.encode()
    sock.sendall(bytes([len(raw)]) + raw)


def _recv_protocol(sock) -> str:
    n = _read_exact(sock, 1)[0]
    return _read_exact(sock, n).decode()


# -- server --------------------------------------------------------------------


class RpcServer:
    """Serves the req/resp protocols for one beacon node; gossip streams
    are handed off to the network service's subscriber loop."""

    def __init__(self, node, host: str = "127.0.0.1", port: int = 0,
                 rate_limiter=None):
        from .rate_limiter import RateLimiter

        self.node = node  # NetworkService
        # per-peer, per-protocol token buckets (rpc/rate_limiter.rs)
        self.rate_limiter = rate_limiter or RateLimiter()

        rpc = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    sock = self.request
                    transport = getattr(rpc.node, "transport", None)
                    if transport is not None:
                        # bound the handshake; streams set their own
                        # timeouts afterwards
                        sock.settimeout(10.0)
                        sock = transport.wrap_inbound(sock)
                        sock.settimeout(None)
                    proto = _recv_protocol(sock)
                    if proto == M.PROTO_MUX:
                        rpc._serve_mux(sock)
                        return
                    rpc._dispatch_stream(proto, sock)
                except (RpcError, OSError):
                    # NoiseError subclasses OSError: security failures
                    # drop the stream like any dead connection
                    pass

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="rpc_server"
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    # -- request dispatch -------------------------------------------------------

    def _dispatch_stream(self, proto: str, sock):
        """One protocol stream → its handler (shared between dedicated
        sockets and mux substreams, so new protocols work over both)."""
        if proto == M.PROTO_GOSSIP:
            self.node._handle_gossip_stream(sock)
            return
        self._handle_rpc(proto, sock)

    def _serve_mux(self, sock):
        """Serve many RPC substreams over one connection (the yamux
        layer, network/mux.py). Each inbound stream opens with its own
        protocol id and is handled exactly like a dedicated socket."""
        from .mux import MuxedConnection

        rpc = self

        def on_stream(stream):
            try:
                rpc._dispatch_stream(_recv_protocol(stream), stream)
            except (RpcError, OSError):
                pass
            finally:
                stream.close()

        conn = MuxedConnection(sock, initiator=False, on_stream=on_stream)
        conn._reader.join()  # handler thread lives as long as the conn

    def _peer_key(self, sock) -> str:
        """Bucket key: the noise-authenticated identity when the stream is
        secured, else the remote host (ports rotate per request stream)."""
        noise_id = getattr(sock, "remote_peer_id", None)
        if noise_id is not None:
            return noise_id
        try:
            return sock.getpeername()[0]
        except OSError:
            return "?"

    def _limited(self, sock, proto: str, cost: float) -> bool:
        """True (and the refusal already sent) when over quota."""
        if self.rate_limiter.allow(self._peer_key(sock), proto, cost):
            return False
        inc_counter("rpc_rate_limited_total", protocol=proto.split("/")[-3])
        self._respond(sock, RESP_RATE_LIMITED, b"rate limited")
        return True

    def _handle_rpc(self, proto: str, sock):
        hist = _SERVER_SECONDS.get(proto)
        if hist is None:
            self._handle_rpc_inner(proto, sock)
            return
        t0 = time.perf_counter()
        try:
            self._handle_rpc_inner(proto, sock)
        finally:
            # rate-limited and failed requests are observed too: the
            # latency a peer EXPERIENCES includes our refusals
            hist.observe(time.perf_counter() - t0)

    def _handle_rpc_inner(self, proto: str, sock):
        inc_counter("rpc_requests_total", protocol=proto.split("/")[-3])
        node = self.node
        if proto == M.PROTO_STATUS:
            _req = M.StatusMessage.deserialize(_recv_block(sock))
            if self._limited(sock, proto, 1):
                return
            self._respond(sock, RESP_SUCCESS, node.local_status().serialize())
        elif proto == M.PROTO_PING:
            _req = M.Ping.deserialize(_recv_block(sock))
            if self._limited(sock, proto, 1):
                return
            self._respond(
                sock, RESP_SUCCESS, M.Ping(data=node.metadata_seq).serialize()
            )
        elif proto == M.PROTO_METADATA:
            if self._limited(sock, proto, 1):
                return
            self._respond(
                sock,
                RESP_SUCCESS,
                M.MetadataMessage(
                    seq_number=node.metadata_seq, attnets=0
                ).serialize(),
            )
        elif proto == M.PROTO_GOODBYE:
            _req = M.GoodbyeReason.deserialize(_recv_block(sock))
            if self._limited(sock, proto, 1):
                return
            self._respond(sock, RESP_SUCCESS, M.GoodbyeReason(reason=0).serialize())
        elif proto == M.PROTO_BLOCKS_BY_RANGE:
            req = M.BlocksByRangeRequest.deserialize(_recv_block(sock))
            if req.step != 1:
                self._respond(sock, RESP_INVALID_REQUEST, b"")
                return
            # server-side cap: a hostile count is CLAMPED (the spec lets
            # servers respond with fewer blocks), so one request can never
            # stream the whole store — and the rate-limiter cost is priced
            # on the clamped work actually asked for
            count = min(int(req.count), MAX_REQUEST_BLOCKS)
            if self._limited(sock, proto, count):
                return
            self._stream(sock, node.blocks_by_range, req.start_slot, count)
        elif proto == M.PROTO_BLOCKS_BY_ROOT:
            req = M.BlocksByRootRequest.deserialize(_recv_block(sock))
            roots = list(req.roots)[:MAX_REQUEST_BLOCKS]
            if self._limited(sock, proto, max(1, len(roots))):
                return
            self._stream(sock, node.blocks_by_root, roots)
        elif proto == M.PROTO_BLOBS_BY_RANGE:
            req = M.BlobsByRangeRequest.deserialize(_recv_block(sock))
            # blob responses are ~128KiB each — the spec bounds this
            # protocol by sidecar count (MAX_REQUEST_BLOB_SIDECARS), not
            # block count; clamp the block count to what fits the cap
            max_blobs = node.chain.E.MAX_BLOBS_PER_BLOCK
            count = min(int(req.count), MAX_REQUEST_BLOB_SIDECARS // max_blobs)
            if self._limited(sock, proto, count * max_blobs):
                return
            self._stream(sock, node.blob_sidecars_by_range, req.start_slot, count)
        elif proto == M.PROTO_BLOBS_BY_ROOT:
            req = M.BlobsByRootRequest.deserialize(_recv_block(sock))
            blob_ids = list(req.blob_ids)[:MAX_REQUEST_BLOB_SIDECARS]
            if self._limited(sock, proto, max(1, len(blob_ids))):
                return
            self._stream(sock, node.blob_sidecars_by_root, blob_ids)
        elif proto == M.PROTO_DATA_COLUMNS_BY_RANGE:
            req = M.DataColumnsByRangeRequest.deserialize(_recv_block(sock))
            # column responses are bounded by sidecar count, not block
            # count: clamp the slot span so count × wanted-columns fits
            # the cap (the spec lets servers respond with fewer)
            columns = sorted({int(c) for c in req.columns})
            n_cols = max(1, len(columns))
            count = min(
                int(req.count), MAX_REQUEST_DATA_COLUMN_SIDECARS // n_cols
            )
            if self._limited(sock, proto, count * n_cols):
                return
            self._stream(
                sock,
                node.data_column_sidecars_by_range,
                req.start_slot,
                count,
                columns,
            )
        elif proto == M.PROTO_DATA_COLUMNS_BY_ROOT:
            req = M.DataColumnsByRootRequest.deserialize(_recv_block(sock))
            column_ids = list(req.column_ids)[:MAX_REQUEST_DATA_COLUMN_SIDECARS]
            if self._limited(sock, proto, max(1, len(column_ids))):
                return
            self._stream(sock, node.data_column_sidecars_by_root, column_ids)
        else:
            self._respond(sock, RESP_INVALID_REQUEST, b"")

    def _stream(self, sock, provider, *args):
        """Stream a provider's chunks. A provider fault becomes ONE
        explicit SERVER_ERROR chunk instead of a silently-dying stream —
        syncing clients must see the difference between "peer has nothing
        here" (clean end-of-stream) and "peer failed mid-request" (retry
        on another peer)."""
        try:
            items = provider(*args)
        except Exception:  # noqa: BLE001 — provider fault, not stream fault
            inc_counter("rpc_server_errors_total")
            self._respond(sock, RESP_SERVER_ERROR, b"")
            return
        for item in items:
            self._respond(sock, RESP_SUCCESS, item.serialize())
        sock.shutdown(socket.SHUT_WR)

    @staticmethod
    def _respond(sock, result: int, payload: bytes):
        sock.sendall(bytes([result]))
        _send_block(sock, payload)


# -- client --------------------------------------------------------------------


class RpcClient:
    """One-shot request streams to a peer (rpc/outbound.rs analog)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 transport=None, mux: bool = False):
        self.addr = (host, port)
        self.timeout = timeout
        self.transport = transport  # None = plain TCP
        # mux=True: one persistent (noise-handshaked once) connection
        # carries every request as a substream — the yamux shape. False:
        # one TCP connection per request stream.
        self.mux = mux
        self._mux_conn = None
        self._mux_lock = threading.Lock()

    def _dial(self):
        sock = socket.create_connection(self.addr, timeout=self.timeout)
        if self.transport is not None:
            try:
                sock = self.transport.wrap_outbound(sock)
            except Exception:
                sock.close()
                raise
        return sock

    def _open(self, proto: str):
        if self.mux:
            from .mux import MuxedConnection

            with self._mux_lock:
                if self._mux_conn is None or not self._mux_conn.alive:
                    sock = self._dial()
                    _send_protocol(sock, M.PROTO_MUX)
                    # the conn replaces the dial timeout with its own IO
                    # timeout: sends stay bounded, idle reads just retry
                    self._mux_conn = MuxedConnection(sock, initiator=True)
                stream = self._mux_conn.open_stream()
            stream.settimeout(self.timeout)
            _send_protocol(stream, proto)
            return stream
        sock = self._dial()
        _send_protocol(sock, proto)
        return sock

    def close(self):
        with self._mux_lock:
            if self._mux_conn is not None:
                self._mux_conn.close()
                self._mux_conn = None

    def _request_one(self, proto: str, payload: bytes) -> bytes:
        with _TimedClientRequest(proto), self._open(proto) as sock:
            _send_block(sock, payload)
            result = _read_exact(sock, 1)[0]
            data = _recv_block(sock)
            if result != RESP_SUCCESS:
                raise RpcError(f"{proto}: error response {result}: {data!r}")
            return data

    def status(self, local: M.StatusMessage) -> M.StatusMessage:
        return M.StatusMessage.deserialize(
            self._request_one(M.PROTO_STATUS, local.serialize())
        )

    def ping(self, seq: int) -> int:
        resp = M.Ping.deserialize(
            self._request_one(M.PROTO_PING, M.Ping(data=seq).serialize())
        )
        return int(resp.data)

    def metadata(self) -> M.MetadataMessage:
        with _TimedClientRequest(M.PROTO_METADATA), self._open(
            M.PROTO_METADATA
        ) as sock:
            # metadata has no request body
            result = _read_exact(sock, 1)[0]
            data = _recv_block(sock)
            if result != RESP_SUCCESS:
                raise RpcError("metadata error")
            return M.MetadataMessage.deserialize(data)

    def goodbye(self, reason: int):
        try:
            self._request_one(
                M.PROTO_GOODBYE, M.GoodbyeReason(reason=reason).serialize()
            )
        except (RpcError, OSError):
            pass

    def _stream_blocks(self, proto: str, payload: bytes, decode_block):
        out = []
        with _TimedClientRequest(proto), self._open(proto) as sock:
            _send_block(sock, payload)
            while True:
                try:
                    result_b = sock.recv(1)
                except OSError:
                    break
                if not result_b:
                    break
                result = result_b[0]
                data = _recv_block(sock)
                if result != RESP_SUCCESS:
                    raise RpcError(f"{proto}: chunk error {result}")
                out.append(decode_block(data))
        return out

    def blocks_by_range(self, start_slot: int, count: int, decode_block):
        req = M.BlocksByRangeRequest(start_slot=start_slot, count=count, step=1)
        return self._stream_blocks(
            M.PROTO_BLOCKS_BY_RANGE, req.serialize(), decode_block
        )

    def blocks_by_root(self, roots: list, decode_block):
        req = M.BlocksByRootRequest(roots=roots)
        return self._stream_blocks(
            M.PROTO_BLOCKS_BY_ROOT, req.serialize(), decode_block
        )

    def blob_sidecars_by_range(self, start_slot: int, count: int, decode_sidecar):
        req = M.BlobsByRangeRequest(start_slot=start_slot, count=count)
        return self._stream_blocks(
            M.PROTO_BLOBS_BY_RANGE, req.serialize(), decode_sidecar
        )

    def blob_sidecars_by_root(self, blob_ids: list, decode_sidecar):
        req = M.BlobsByRootRequest(blob_ids=blob_ids)
        return self._stream_blocks(
            M.PROTO_BLOBS_BY_ROOT, req.serialize(), decode_sidecar
        )

    def data_column_sidecars_by_range(
        self, start_slot: int, count: int, columns: list, decode_sidecar
    ):
        req = M.DataColumnsByRangeRequest(
            start_slot=start_slot, count=count, columns=list(columns)
        )
        return self._stream_blocks(
            M.PROTO_DATA_COLUMNS_BY_RANGE, req.serialize(), decode_sidecar
        )

    def data_column_sidecars_by_root(self, column_ids: list, decode_sidecar):
        req = M.DataColumnsByRootRequest(column_ids=column_ids)
        return self._stream_blocks(
            M.PROTO_DATA_COLUMNS_BY_ROOT, req.serialize(), decode_sidecar
        )
