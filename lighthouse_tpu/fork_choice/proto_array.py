"""Array-program proto-array fork choice DAG.

Mirrors consensus/proto_array (proto_array.rs, proto_array_fork_choice.rs)
— a flat array of nodes in insertion order (parents before children),
vote tracking with lazy deltas, one backwards pass to apply score changes,
O(1) head lookup, pruning at finalization — but stores BOTH axes columnar:

  * the node axis as parallel numpy arrays (parent index, weight,
    justified/finalized epochs, unrealized epochs, best-child,
    best-descendant, execution status) with capacity-doubling growth —
    the layout `proto_array.rs` keeps deliberately flat so score
    application is a single linear pass;
  * the validator axis as resident vote columns
    (`current_root_index`/`next_root_index` uint32, `next_epoch` uint64)
    over an append-only root-interning table whose `rid -> node index`
    map survives pruning (pruned roots resolve to the -1 sentinel, never
    a stale index) — replacing the per-validator
    `dict[int, VoteTracker]` the scalar oracle still walks.

A round's score deltas are ONE gather + `np.add.at` scatter-add over the
old/new balance arrays (equivocating validators masked), accumulated as
separate add/subtract columns so the weight update stays in the
`safe_arith` u64 register: underflow (a negative node weight) is an
ALWAYS-ON explicit check raising ProtoArrayError before any write, and
the `add_u64`/`sub_u64` lanes additionally prove no u64 wrap under
LIGHTHOUSE_TPU_SANITIZE=1 (overflow is unreachable at realistic total
stake — ~2^55 Gwei — but the sanitizer pins the invariant). The backwards
weight roll and the best-child/best-descendant refresh stay sequential
over the (small) node axis — children after parents by construction —
while every per-validator step is an array program.

Batch vote ingestion (`process_attestation_batch`) consumes the PR 7
columnar attesting-index arrays: a drained GOSSIP_ATTESTATION batch
updates votes in one vectorized write instead of ~16k dict operations.

The pre-columnar scalar walk is retained verbatim in
`proto_array_reference.py` (differential oracle + bench control, per the
established reference-module pattern); `fork_choice_get_head_ms` in
bench.py measures this module against it at 1M applied votes.
"""

from __future__ import annotations

from enum import Enum

import numpy as np

from ..metrics import REGISTRY
from ..utils.safe_arith import add_u64, sub_u64
from ..utils.tracing import span

_ZERO_ROOT = b"\x00" * 32


class ExecutionStatus(Enum):
    """Execution-layer view of a block's payload (proto_array.rs
    ExecutionStatus): irrelevant pre-merge, optimistic until the EL verdict."""

    IRRELEVANT = "irrelevant"
    OPTIMISTIC = "optimistic"
    VALID = "valid"
    INVALID = "invalid"


#: numpy uint8 codes for the execution-status column
_ES_CODE = {
    ExecutionStatus.IRRELEVANT: 0,
    ExecutionStatus.OPTIMISTIC: 1,
    ExecutionStatus.VALID: 2,
    ExecutionStatus.INVALID: 3,
}
_ES_FROM_CODE = {v: k for k, v in _ES_CODE.items()}
_ES_INVALID = _ES_CODE[ExecutionStatus.INVALID]
_ES_OPTIMISTIC = _ES_CODE[ExecutionStatus.OPTIMISTIC]
_ES_VALID = _ES_CODE[ExecutionStatus.VALID]


class ProtoArrayError(ValueError):
    pass


_VOTES_APPLIED = REGISTRY.counter(
    "fork_choice_votes_applied_total",
    "latest-message vote updates accepted into the proto-array columns, "
    "by ingestion path",
)
for _path in ("batch", "single"):
    _VOTES_APPLIED.inc(0, path=_path)

# the get_head trace-root + child-stage histograms must exist at zero:
# the fork_choice bench reads the stage breakdown eagerly and the
# conftest guard asserts the series (same pattern as the epoch stages)
for _span_name in (
    "trace_span_seconds_fork_choice_get_head",
    "trace_span_seconds_delta_compute",
    "trace_span_seconds_weight_roll",
    "trace_span_seconds_best_child",
):
    REGISTRY.histogram(
        # lint: allow(metric-hygiene) -- bounded by the literal tuple above
        _span_name,
        "span duration: fork-choice get_head stage",
    )


def _update_best(parent_i, child_i, viable, weights, bc, bd, roots):
    """`_maybe_update_best_child_and_descendant` (proto_array.rs) over
    indexable column storage (-1 sentinel for None). `viable`, `weights`,
    `bc`, `bd` may be numpy arrays or plain lists — the batched refresh
    pass hands in lists for speed, the incremental on_block path hands in
    the arrays themselves."""

    def leads_to_viable(i):
        d = bd[i]
        return bool(viable[d]) if d >= 0 else bool(viable[i])

    def set_best(c):
        bc[parent_i] = c
        d = bd[c]
        bd[parent_i] = d if d >= 0 else c

    child_leads_to_viable = leads_to_viable(child_i)
    best = bc[parent_i]
    if best == child_i:
        if not child_leads_to_viable:
            bc[parent_i] = -1
            bd[parent_i] = -1
        else:
            set_best(child_i)
    elif best < 0:
        if child_leads_to_viable:
            set_best(child_i)
    else:
        best_viable = leads_to_viable(best)
        if child_leads_to_viable and not best_viable:
            set_best(child_i)
        elif child_leads_to_viable and (
            weights[child_i] > weights[best]
            or (
                weights[child_i] == weights[best]
                and roots[child_i] > roots[best]
            )
        ):
            # tie-break on higher root lexicographically (matches the
            # reference's deterministic tie-break)
            set_best(child_i)


class _LazyViable:
    """Per-index viability without materializing the whole mask — the
    incremental (single parent/child) update path."""

    __slots__ = ("pa",)

    def __init__(self, pa: "ProtoArray"):
        self.pa = pa

    def __getitem__(self, i):
        return self.pa._viable_index(int(i))


class ProtoArray:
    def __init__(self, justified_epoch: int, finalized_epoch: int):
        self.indices: dict[bytes, int] = {}
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self.prune_threshold = 256
        # Previous proposer boost, subtracted on the next score pass
        # (the reference stores this as previous_proposer_boost).
        self._prev_boost_root: bytes = _ZERO_ROOT
        self._prev_boost_amount: int = 0
        # -- node-axis columns (parallel arrays, [cap], first _n live) --
        self._n = 0
        cap = 64
        self._roots: list[bytes] = []
        self._state_roots: list[bytes] = []
        self._slots = np.zeros(cap, dtype=np.int64)
        self._parents = np.full(cap, -1, dtype=np.int64)
        self._je = np.zeros(cap, dtype=np.int64)
        self._fe = np.zeros(cap, dtype=np.int64)
        # unrealized checkpoints: -1 encodes "not set" (falls back to the
        # realized epoch in the viability filter)
        self._uje = np.full(cap, -1, dtype=np.int64)
        self._ufe = np.full(cap, -1, dtype=np.int64)
        self._weights = np.zeros(cap, dtype=np.uint64)
        self._best_child = np.full(cap, -1, dtype=np.int64)
        self._best_desc = np.full(cap, -1, dtype=np.int64)
        self._exec = np.zeros(cap, dtype=np.uint8)
        # -- vote-root interning (validator columns point at rids, not
        # node indexes: rids are stable across pruning; the rid->node map
        # is re-shifted on prune with -1 for dropped roots, and rids no
        # longer referenced by any vote column or live node are compacted
        # away through the registered owner — without that, a long-lived
        # node would leak one entry per root ever voted for) --
        self._root_ids: dict[bytes, int] = {_ZERO_ROOT: 0}
        self._n_rids = 1
        self._rid_to_node = np.full(64, -1, dtype=np.int64)
        #: the ProtoArrayForkChoice owning the validator vote columns;
        #: prune asks it which rids are live and hands it the rid remap
        self._vote_columns = None

    def __len__(self) -> int:
        return self._n

    # ------------------------------------------------------------------ insert

    def _grow_nodes(self):
        cap = max(64, 2 * len(self._slots))
        for name in (
            "_slots",
            "_parents",
            "_je",
            "_fe",
            "_uje",
            "_ufe",
            "_weights",
            "_best_child",
            "_best_desc",
            "_exec",
        ):
            old = getattr(self, name)
            fill = -1 if old.dtype == np.int64 and name != "_slots" else 0
            new = np.full(cap, fill, dtype=old.dtype)
            new[: self._n] = old[: self._n]
            setattr(self, name, new)

    def on_block(
        self,
        slot: int,
        root: bytes,
        parent_root: bytes | None,
        state_root: bytes,
        justified_epoch: int,
        finalized_epoch: int,
        unrealized_justified_epoch: int | None = None,
        unrealized_finalized_epoch: int | None = None,
        execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT,
    ):
        if root in self.indices:
            return
        parent = self.indices.get(parent_root) if parent_root is not None else None
        index = self._n
        if index >= len(self._slots):
            self._grow_nodes()
        self._roots.append(root)
        self._state_roots.append(state_root)
        self._slots[index] = slot
        self._parents[index] = -1 if parent is None else parent
        self._je[index] = justified_epoch
        self._fe[index] = finalized_epoch
        self._uje[index] = (
            -1 if unrealized_justified_epoch is None else unrealized_justified_epoch
        )
        self._ufe[index] = (
            -1 if unrealized_finalized_epoch is None else unrealized_finalized_epoch
        )
        self._weights[index] = 0
        self._best_child[index] = -1
        self._best_desc[index] = -1
        self._exec[index] = _ES_CODE[execution_status]
        self._n = index + 1
        self.indices[root] = index
        # a root voted for before its block arrived (or re-added after a
        # prune) must resolve to the live node again
        rid = self._root_ids.get(root)
        if rid is not None:
            self._rid_to_node[rid] = index
        if parent is not None:
            _update_best(
                parent,
                index,
                _LazyViable(self),
                self._weights,
                self._best_child,
                self._best_desc,
                self._roots,
            )

    # ------------------------------------------------------- vote interning

    def vote_root_id(self, root: bytes) -> int:
        """Intern a vote target root: a stable uint32 id for the validator
        columns. Ids never move; the id->node map is refreshed on prune
        and on (re-)insertion of the root's block."""
        rid = self._root_ids.get(root)
        if rid is None:
            rid = self._n_rids
            if rid >= len(self._rid_to_node):
                new = np.full(2 * len(self._rid_to_node), -1, dtype=np.int64)
                new[: self._n_rids] = self._rid_to_node[: self._n_rids]
                self._rid_to_node = new
            self._root_ids[root] = rid
            self._rid_to_node[rid] = self.indices.get(root, -1)
            self._n_rids = rid + 1
        return rid

    # ------------------------------------------------------------------ scores

    def apply_score_changes(
        self,
        deltas: list[int],
        justified_epoch: int,
        finalized_epoch: int,
        proposer_boost_root: bytes = _ZERO_ROOT,
        proposer_boost_amount: int = 0,
    ):
        """Scalar-compat entry (signed per-node deltas): split into the
        add/subtract columns and run the array pass."""
        if len(deltas) != self._n:
            raise ProtoArrayError("delta length mismatch")
        d = np.asarray(deltas, dtype=np.int64)
        pos = np.where(d > 0, d, 0).astype(np.uint64)
        neg = np.where(d < 0, -d, 0).astype(np.uint64)
        self.apply_score_changes_arrays(
            pos,
            neg,
            justified_epoch,
            finalized_epoch,
            proposer_boost_root,
            proposer_boost_amount,
        )

    def apply_score_changes_arrays(
        self,
        pos: np.ndarray,
        neg: np.ndarray,
        justified_epoch: int,
        finalized_epoch: int,
        proposer_boost_root: bytes = _ZERO_ROOT,
        proposer_boost_amount: int = 0,
    ):
        """One backwards pass over the node columns: roll child deltas
        into parents (children after parents in insertion order, so the
        roll is a single linear sweep), apply them to the weight column
        through the checked u64 helpers, refresh best_child /
        best_descendant (proto_array.rs apply_score_changes). `pos`/`neg`
        are uint64 add/subtract accumulators, [n] each; both are consumed
        (mutated) by this call."""
        n = self._n
        if len(pos) != n or len(neg) != n:
            raise ProtoArrayError("delta length mismatch")
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        # Proposer boost is transient: undo last pass's boost, apply this
        # pass's (the reference's previous_proposer_boost bookkeeping).
        if self._prev_boost_amount:
            pi = self.indices.get(self._prev_boost_root)
            if pi is not None:
                neg[pi] = add_u64(neg[pi], self._prev_boost_amount)
        if proposer_boost_amount:
            bi = self.indices.get(proposer_boost_root)
            if bi is not None:
                pos[bi] = add_u64(pos[bi], proposer_boost_amount)
        self._prev_boost_root = proposer_boost_root
        self._prev_boost_amount = proposer_boost_amount

        with span("weight_roll"):
            # subtree accumulation over python ints (no intermediate wrap
            # regardless of magnitude), then ONE checked u64 column update:
            # weight' = (weight + pos) - neg, with underflow = the scalar
            # oracle's "negative node weight" error, checked explicitly
            pos_l = pos.tolist()
            neg_l = neg.tolist()
            parents = self._parents[:n].tolist()
            for i in range(n - 1, 0, -1):
                p = parents[i]
                if p >= 0:
                    pos_l[p] += pos_l[i]
                    neg_l[p] += neg_l[i]
            pos_t = np.asarray(pos_l, dtype=np.uint64)
            neg_t = np.asarray(neg_l, dtype=np.uint64)
            total = add_u64(self._weights[:n], pos_t)
            if bool((total < neg_t).any()):
                raise ProtoArrayError("negative node weight")
            self._weights[:n] = sub_u64(total, neg_t)

        with span("best_child"):
            self._refresh_best_children()

    # ------------------------------------------------------------------ head

    def _viability_mask(self) -> np.ndarray:
        """Vectorized node_is_viable_for_head over all live nodes: the
        (unrealized-or-realized) checkpoints must agree with the store's,
        and the payload must not be invalid."""
        n = self._n
        uje = self._uje[:n]
        ufe = self._ufe[:n]
        j = np.where(uje >= 0, uje, self._je[:n])
        f = np.where(ufe >= 0, ufe, self._fe[:n])
        ok_j = (j >= self.justified_epoch) | (self.justified_epoch == 0)
        ok_f = (f >= self.finalized_epoch) | (self.finalized_epoch == 0)
        return (self._exec[:n] != _ES_INVALID) & ok_j & ok_f

    def _viable_index(self, i: int) -> bool:
        if self._exec[i] == _ES_INVALID:
            return False
        uje = int(self._uje[i])
        ufe = int(self._ufe[i])
        j = uje if uje >= 0 else int(self._je[i])
        f = ufe if ufe >= 0 else int(self._fe[i])
        correct_justified = j >= self.justified_epoch or self.justified_epoch == 0
        correct_finalized = f >= self.finalized_epoch or self.finalized_epoch == 0
        return correct_justified and correct_finalized

    def _refresh_best_children(self):
        """Backwards best-child/best-descendant pass. Viability is ONE
        vectorized mask; the walk itself is sequential over the (small)
        node axis — a child's best_descendant must already reflect this
        pass when its parent is visited, which backwards insertion order
        guarantees."""
        n = self._n
        if n <= 1:
            return
        viable = self._viability_mask().tolist()
        parents = self._parents[:n].tolist()
        weights = self._weights[:n].tolist()
        bc = self._best_child[:n].tolist()
        bd = self._best_desc[:n].tolist()
        roots = self._roots
        for i in range(n - 1, 0, -1):
            p = parents[i]
            if p >= 0:
                _update_best(p, i, viable, weights, bc, bd, roots)
        self._best_child[:n] = bc
        self._best_desc[:n] = bd

    def node_is_viable_for_head_at(self, index: int) -> bool:
        """Index-addressed viability (the scalar oracle's
        node_is_viable_for_head took a ProtoNode)."""
        return self._viable_index(index)

    def find_head(self, justified_root: bytes) -> bytes:
        ji = self.indices.get(justified_root)
        if ji is None:
            raise ProtoArrayError(f"justified root {justified_root.hex()} unknown")
        bd = int(self._best_desc[ji])
        best = bd if bd >= 0 else ji
        if not self._viable_index(best):
            raise ProtoArrayError("best node is not viable for head")
        return self._roots[best]

    def get_proposer_head(
        self,
        slot: int,
        head_root: bytes,
        committee_weight: int,
        head_threshold_pct: int,
        parent_threshold_pct: int,
        slots_per_epoch: int,
    ) -> bytes | None:
        """The structural/weight half of spec `get_proposer_head`
        (proto_array_fork_choice.rs `proposer_head_info`): the parent
        root to build on instead of `head_root`, or None to keep the
        head. The caller (ForkChoice/chain layer) owns the remaining
        conditions — head lateness, finalization distance, and
        proposing-on-time — because they live outside the array.

        Weights must be fresh from the last `get_head` pass; this method
        deliberately does NOT rerun it (the boost bookkeeping in
        apply_score_changes is stateful). If the last pass applied a
        proposer boost to the head, it is backed out here so the head is
        judged on attestation weight alone."""
        hi = self.indices.get(head_root)
        if hi is None:
            return None
        pi = int(self._parents[hi])
        if pi < 0:
            return None
        head_slot = int(self._slots[hi])
        parent_slot = int(self._slots[pi])
        # single-slot re-org only: head is its parent's immediate
        # successor and we propose the very next slot — deeper re-orgs
        # risk splitting the vote
        if parent_slot + 1 != head_slot or head_slot + 1 != int(slot):
            return None
        # shuffling stability: a re-org across an epoch boundary changes
        # the proposer shuffling the rest of the network computed
        if int(slot) % int(slots_per_epoch) == 0:
            return None
        # FFG competitiveness: the parent's chain must justify the same
        # epoch the head's does, or the re-org block could lose the FFG
        # race it would otherwise have won through the head
        uje = self._uje
        je = self._je
        head_j = int(uje[hi]) if int(uje[hi]) >= 0 else int(je[hi])
        parent_j = int(uje[pi]) if int(uje[pi]) >= 0 else int(je[pi])
        if head_j != parent_j:
            return None
        head_weight = int(self._weights[hi])
        if self._prev_boost_root == head_root:
            # saturating: Python ints don't wrap, but the boost may
            # exceed the attestation weight of a genuinely weak head
            head_weight = max(0, head_weight - int(self._prev_boost_amount))
        parent_weight = int(self._weights[pi])
        cw = int(committee_weight)
        head_weak = head_weight < cw * int(head_threshold_pct) // 100
        parent_strong = parent_weight > cw * int(parent_threshold_pct) // 100
        if not (head_weak and parent_strong):
            return None
        return self._roots[pi]

    # ------------------------------------------------------------------ misc

    def block_slot_at(self, index: int) -> int:
        return int(self._slots[index])

    def execution_status_of(self, root: bytes) -> ExecutionStatus | None:
        i = self.indices.get(root)
        return _ES_FROM_CODE[int(self._exec[i])] if i is not None else None

    def ancestor_at_slot(self, root: bytes, slot: int) -> bytes | None:
        """Spec get_ancestor: the block in `root`'s chain at or before `slot`
        (walks parents; returns None if root is unknown or the walk leaves
        the array)."""
        i = self.indices.get(root)
        if i is None:
            return None
        slots = self._slots
        parents = self._parents
        while i >= 0:
            if slots[i] <= slot:
                return self._roots[i]
            i = int(parents[i])
        return None

    def is_descendant(self, ancestor_root: bytes, descendant_root: bytes) -> bool:
        ai = self.indices.get(ancestor_root)
        di = self.indices.get(descendant_root)
        if ai is None or di is None:
            return False
        slots = self._slots
        parents = self._parents
        a_slot = slots[ai]
        i = di
        while i >= 0 and slots[i] >= a_slot:
            if i == ai:
                return True
            i = int(parents[i])
        return False

    def propagate_execution_payload_validity(self, root: bytes):
        """Mark a block and all its ancestors VALID (an EL VALID verdict
        implies all ancestors valid)."""
        i = self.indices.get(root)
        while i is not None and i >= 0:
            if self._exec[i] in (_ES_OPTIMISTIC, _ES_VALID):
                self._exec[i] = _ES_VALID
            i = int(self._parents[i])

    def invalidate_block(self, root: bytes):
        """Mark a block and all its descendants INVALID
        (on_invalid_execution_payload): one forward descendant-mask pass
        (children after parents), then a full best-child refresh."""
        start = self.indices.get(root)
        if start is None:
            return
        n = self._n
        parents = self._parents[:n].tolist()
        bad = np.zeros(n, dtype=bool)
        bad[start] = True
        for i in range(start + 1, n):
            p = parents[i]
            if p >= 0 and bad[p]:
                bad[i] = True
        self._exec[:n][bad] = _ES_INVALID
        self._refresh_best_children()

    def maybe_prune(self, finalized_root: bytes):
        """Drop nodes before the finalized root (maybe_prune in
        proto_array.rs); keeps indices dense. The remap is one vectorized
        index shift per pointer column (gather through a remap table, -1
        sentinel for dropped targets) — including the vote-root map, so
        votes referencing pruned roots resolve to the sentinel, never a
        stale index."""
        fi = self.indices.get(finalized_root)
        if fi is None or fi < self.prune_threshold:
            return
        n = self._n
        # descendant mask: one forward pass (children after parents)
        parents_l = self._parents[:n].tolist()
        desc = np.zeros(n, dtype=bool)
        desc[fi] = True
        for i in range(fi + 1, n):
            p = parents_l[i]
            if p >= 0 and desc[p]:
                desc[i] = True
        keep = np.nonzero(desc)[0]
        k = keep.size
        remap = np.full(n, -1, dtype=np.int64)
        remap[keep] = np.arange(k, dtype=np.int64)

        def _shift(col: np.ndarray) -> np.ndarray:
            old = col[keep]
            # fancy-index through the remap table; -1 rows read remap[-1]
            # (garbage) and are overwritten by the sentinel mask
            shifted = remap[old]
            return np.where(old >= 0, shifted, -1)

        self._parents[:k] = _shift(self._parents[:n])
        self._best_child[:k] = _shift(self._best_child[:n])
        self._best_desc[:k] = _shift(self._best_desc[:n])
        for name in ("_slots", "_je", "_fe", "_uje", "_ufe", "_weights", "_exec"):
            col = getattr(self, name)
            col[:k] = col[keep]
        keep_l = keep.tolist()
        self._roots = [self._roots[i] for i in keep_l]
        self._state_roots = [self._state_roots[i] for i in keep_l]
        self.indices = {r: i for i, r in enumerate(self._roots)}
        self._n = k
        # vote-root map: pruned roots resolve to -1 from here on
        m = self._n_rids
        old_map = self._rid_to_node[:m]
        shifted = remap[np.where(old_map >= 0, old_map, 0)]
        new_map = np.where(old_map >= 0, shifted, -1)
        owner = self._vote_columns
        if owner is None:
            self._rid_to_node[:m] = new_map
            return
        # compact the intern table: keep rid 0 (zero root), every rid a
        # vote column still references, and every rid whose root survived
        # the prune; everything else is unreachable — drop it and re-shift
        # the columns through the rid remap (vectorized, like the node
        # pointer columns above)
        live = owner._live_rid_mask(m)
        live[0] = True
        live |= new_map >= 0
        if bool(live.all()):
            self._rid_to_node[:m] = new_map
            return
        kept = int(np.count_nonzero(live))
        rid_remap = np.zeros(m, dtype=np.int64)  # dead rids -> 0, unreferenced
        rid_remap[live] = np.arange(kept, dtype=np.int64)
        self._rid_to_node[:kept] = new_map[live]
        self._n_rids = kept
        self._root_ids = {
            root: int(rid_remap[rid])
            for root, rid in self._root_ids.items()
            if live[rid]
        }
        owner._remap_rids(rid_remap)


def _sized_u64(arr: np.ndarray, m: int) -> np.ndarray:
    """`arr` truncated or zero-padded to m rows (the scalar oracle's
    `x[vi] if vi < len(x) else 0` bound, vectorized)."""
    if len(arr) == m:
        return arr
    if len(arr) > m:
        return arr[:m]
    out = np.zeros(m, dtype=np.uint64)
    out[: len(arr)] = arr
    return out


class ProtoArrayForkChoice:
    """Proto-array + resident vote columns + balance-weighted deltas
    (proto_array_fork_choice.rs), fully columnar: see the module
    docstring. The scalar oracle lives in `proto_array_reference`."""

    def __init__(
        self,
        finalized_root: bytes,
        finalized_slot: int,
        finalized_state_root: bytes,
        justified_epoch: int,
        finalized_epoch: int,
    ):
        self.proto_array = ProtoArray(justified_epoch, finalized_epoch)
        # validator-axis vote columns; length = allocated capacity, a row
        # of (0, 0, 0) is "never voted" (rid 0 = the zero root)
        self._cur_rid = np.zeros(0, dtype=np.uint32)
        self._next_rid = np.zeros(0, dtype=np.uint32)
        self._next_epoch = np.zeros(0, dtype=np.uint64)
        # balances applied on the LAST score pass, held as a uint64 array
        # (copied only when the caller hands over a genuinely new vector —
        # the scalar oracle re-copied the full list on every get_head)
        self._balances = np.zeros(0, dtype=np.uint64)
        # prune-time rid compaction asks these columns what is live
        self.proto_array._vote_columns = self
        self.proto_array.on_block(
            slot=finalized_slot,
            root=finalized_root,
            parent_root=None,
            state_root=finalized_state_root,
            justified_epoch=justified_epoch,
            finalized_epoch=finalized_epoch,
        )

    # ------------------------------------------------------------------ votes

    @property
    def balances(self) -> np.ndarray:
        return self._balances

    def _grow_validators(self, m: int):
        cur = len(self._cur_rid)
        if m <= cur:
            return
        cap = max(64, cur)
        while cap < m:
            cap *= 2
        for name in ("_cur_rid", "_next_rid", "_next_epoch"):
            old = getattr(self, name)
            new = np.zeros(cap, dtype=old.dtype)
            new[:cur] = old
            setattr(self, name, new)

    def _live_rid_mask(self, m: int) -> np.ndarray:
        """[m] bool: rids some validator's current/next vote references
        (never-voted rows reference rid 0, which stays live anyway)."""
        mask = np.zeros(m, dtype=bool)
        mask[self._cur_rid] = True
        mask[self._next_rid] = True
        return mask

    def _remap_rids(self, rid_remap: np.ndarray):
        """Prune-time rid compaction: shift both vote columns through the
        remap table (every referenced rid is live by construction, so the
        gather is exact; dead slots map to 0 and are never read)."""
        self._cur_rid = rid_remap[self._cur_rid].astype(np.uint32)
        self._next_rid = rid_remap[self._next_rid].astype(np.uint32)

    def process_attestation(
        self, validator_index: int, block_root: bytes, target_epoch: int
    ):
        """Single-vote ingestion (the aggregate / block path)."""
        self._grow_validators(validator_index + 1)
        rid = self.proto_array.vote_root_id(block_root)
        vi = validator_index
        # Accept strictly-newer votes, or the first vote ever (epoch-0
        # attestations must land on a fresh default tracker).
        is_default = (
            self._cur_rid[vi] == 0
            and self._next_rid[vi] == 0
            and self._next_epoch[vi] == 0
        )
        if target_epoch > self._next_epoch[vi] or is_default:
            self._next_rid[vi] = rid
            self._next_epoch[vi] = target_epoch
            _VOTES_APPLIED.inc(path="single")

    def process_attestation_batch(
        self, validator_indices, block_root: bytes, target_epoch: int
    ):
        """Batch vote ingestion: one vectorized accept-mask + write for a
        whole attesting-index array (the drained-gossip-batch entry; the
        PR 7 `attesting_indices_array` is the natural input)."""
        v = np.asarray(validator_indices, dtype=np.int64)
        if v.size == 0:
            return
        self._grow_validators(int(v.max()) + 1)
        rid = self.proto_array.vote_root_id(block_root)
        is_default = (
            (self._cur_rid[v] == 0)
            & (self._next_rid[v] == 0)
            & (self._next_epoch[v] == 0)
        )
        accept = (np.uint64(target_epoch) > self._next_epoch[v]) | is_default
        tv = v[accept]
        if tv.size:
            self._next_rid[tv] = rid
            self._next_epoch[tv] = target_epoch
            _VOTES_APPLIED.inc(int(tv.size), path="batch")

    # ------------------------------------------------------------------ blocks

    def on_block(self, **kwargs):
        self.proto_array.on_block(**kwargs)

    def contains_block(self, root: bytes) -> bool:
        return root in self.proto_array.indices

    def block_slot(self, root: bytes) -> int | None:
        i = self.proto_array.indices.get(root)
        return self.proto_array.block_slot_at(i) if i is not None else None

    # ------------------------------------------------------------------ deltas

    def _compute_deltas(self, new_balances, equivocating: set[int]):
        """A round's score deltas as two uint64 scatter-add columns
        (add / subtract, so the weight update stays checked u64): gather
        each changed vote's old/new node index through the rid map, ONE
        `np.add.at` per side. Equivocating validators only ever subtract
        (their old vote is removed forever and the columns reset to the
        zero root), exactly the scalar oracle's semantics — including
        skipping unchanged votes even when balances moved."""
        pa = self.proto_array
        n = pa._n
        pos = np.zeros(n, dtype=np.uint64)
        neg = np.zeros(n, dtype=np.uint64)
        m = len(self._cur_rid)
        nb = np.asarray(new_balances, dtype=np.uint64)
        if m:
            cur = self._cur_rid
            nxt = self._next_rid
            changed = cur != nxt
            eq = None
            if equivocating:
                eq = np.fromiter(
                    equivocating, dtype=np.int64, count=len(equivocating)
                )
                eq = eq[eq < m]
            old_b = _sized_u64(self._balances, m)
            new_b = _sized_u64(nb, m)
            rid_map = pa._rid_to_node
            if eq is not None and eq.size:
                eq_mask = np.zeros(m, dtype=bool)
                eq_mask[eq] = True
                sub_i = np.nonzero(changed | eq_mask)[0]
                add_i = np.nonzero(changed & ~eq_mask)[0]
            else:
                sub_i = np.nonzero(changed)[0]
                add_i = sub_i
            if sub_i.size:
                cn = rid_map[cur[sub_i]]
                valid = cn >= 0
                np.add.at(neg, cn[valid], old_b[sub_i[valid]])
            if add_i.size:
                nn = rid_map[nxt[add_i]]
                valid = nn >= 0
                np.add.at(pos, nn[valid], new_b[add_i[valid]])
                # mark applied — a pruned next_root must not leave the old
                # subtraction repeating on every later pass
                self._cur_rid[add_i] = nxt[add_i]
            if eq is not None and eq.size:
                self._cur_rid[eq] = 0
                self._next_rid[eq] = 0
        self._balances = nb
        return pos, neg

    # ------------------------------------------------------------------ head

    def get_head(
        self,
        justified_checkpoint_root: bytes,
        justified_epoch: int,
        finalized_epoch: int,
        justified_state_balances,
        proposer_boost_root: bytes = _ZERO_ROOT,
        proposer_boost_amount: int = 0,
        equivocating_indices: set[int] | None = None,
    ) -> bytes:
        with span("fork_choice_get_head", nodes=self.proto_array._n):
            with span("delta_compute"):
                pos, neg = self._compute_deltas(
                    justified_state_balances, equivocating_indices or set()
                )
            self.proto_array.apply_score_changes_arrays(
                pos,
                neg,
                justified_epoch,
                finalized_epoch,
                proposer_boost_root,
                proposer_boost_amount,
            )
            return self.proto_array.find_head(justified_checkpoint_root)
