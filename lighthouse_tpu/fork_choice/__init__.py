"""Fork choice (consensus/{fork_choice,proto_array} equivalent).

`proto_array` is the columnar (array-program) implementation;
`proto_array_reference` retains the scalar walk as the differential
oracle and bench control.
"""

from .fork_choice import (
    Checkpoint,
    ForkChoice,
    ForkChoiceError,
    ForkChoiceStore,
    InvalidAttestation,
    InvalidBlock,
)
from .proto_array import (
    ExecutionStatus,
    ProtoArray,
    ProtoArrayError,
    ProtoArrayForkChoice,
)
from .proto_array_reference import (
    ProtoArrayForkChoiceReference,
    ProtoArrayReference,
    ProtoNode,
    VoteTracker,
)

__all__ = [
    "Checkpoint",
    "ForkChoice",
    "ForkChoiceError",
    "ForkChoiceStore",
    "InvalidAttestation",
    "InvalidBlock",
    "ExecutionStatus",
    "ProtoArray",
    "ProtoArrayError",
    "ProtoArrayForkChoice",
    "ProtoArrayForkChoiceReference",
    "ProtoArrayReference",
    "ProtoNode",
    "VoteTracker",
]
