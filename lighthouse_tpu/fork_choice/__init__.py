"""Fork choice (consensus/{fork_choice,proto_array} equivalent)."""

from .fork_choice import (
    Checkpoint,
    ForkChoice,
    ForkChoiceError,
    ForkChoiceStore,
    InvalidAttestation,
    InvalidBlock,
)
from .proto_array import (
    ExecutionStatus,
    ProtoArray,
    ProtoArrayError,
    ProtoArrayForkChoice,
    ProtoNode,
    VoteTracker,
)

__all__ = [
    "Checkpoint",
    "ForkChoice",
    "ForkChoiceError",
    "ForkChoiceStore",
    "InvalidAttestation",
    "InvalidBlock",
    "ExecutionStatus",
    "ProtoArray",
    "ProtoArrayError",
    "ProtoArrayForkChoice",
    "ProtoNode",
    "VoteTracker",
]
