"""Spec fork choice over the proto-array
(consensus/fork_choice/src/fork_choice.rs: on_block :642, on_attestation
:1037, get_head :468, proposer boost, equivocation handling).

The store tracks justified/finalized checkpoints and the proposer boost;
weights come from the justified state's effective balances, supplied by a
`balances_provider` (the beacon chain's justified-balances cache in the
reference)."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..state_processing.accessors import (
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    get_active_validator_indices,
    get_current_epoch,
)
from ..types.chain_spec import GENESIS_EPOCH, ChainSpec

_EMPTY_BALANCES = np.zeros(0, dtype=np.uint64)

# Same-slot gossip votes held back for one slot (spec ATTESTATION_DELAY).
# Bounded: at the target scale one slot carries ~31k aggregates, so a cap
# well above that only trips on a flood, where shedding the tail is the
# right call anyway.
_MAX_DEFERRED_ATTESTATIONS = 65_536

from ..metrics import REGISTRY  # noqa: E402

_DEFERRED_ATTESTATIONS = REGISTRY.counter(
    "fork_choice_deferred_attestations_total",
    "same-slot gossip attestations held for the next tick, by outcome",
)
for _outcome in ("deferred", "applied", "dropped"):
    # lint: allow(metric-hygiene) -- bounded by the literal tuple above
    _DEFERRED_ATTESTATIONS.inc(0, outcome=_outcome)


class ForkChoiceError(ValueError):
    pass


class InvalidAttestation(ForkChoiceError):
    pass


class UnknownAncestor(InvalidAttestation):
    """The head block's chain cannot be walked to the target epoch (the
    ancestor is pre-finalization / pruned out of the proto-array). Distinct
    from genuine FFG/LMD target inconsistency so callers can treat it as
    queueable rather than invalid (spec: unknown blocks are ignored, not
    rejected)."""


class InvalidBlock(ForkChoiceError):
    pass


@dataclass
class Checkpoint:
    epoch: int
    root: bytes


@dataclass
class ForkChoiceStore:
    """Spec Store subset (fork_choice_store.rs trait surface)."""

    current_slot: int
    justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    unrealized_justified_checkpoint: Checkpoint
    unrealized_finalized_checkpoint: Checkpoint
    proposer_boost_root: bytes = b"\x00" * 32
    equivocating_indices: set[int] = field(default_factory=set)


class ForkChoice:
    def __init__(self, store: ForkChoiceStore, proto, spec: ChainSpec, E):
        from .proto_array import ProtoArrayForkChoice

        self.store = store
        self.proto: ProtoArrayForkChoice = proto
        self.spec = spec
        self.E = E
        # Gossip attestations for the CURRENT slot: the spec forbids
        # counting them before slot+1 (validate_on_attestation's
        # "from the future" rule), but dropping them starves the weights
        # a proposer-boost re-org decision reads one slot later. Queue
        # them here and drain at the next on_tick — fork_choice.rs
        # queued_attestations / ATTESTATION_DELAY_SLOTS.
        self._deferred_attestations: list = []
        # Effective balances of active validators at the justified state,
        # held as a uint64 array: the proto-array keeps a reference (its
        # "old balances" for the next delta round) instead of re-copying a
        # 1M-element Python list per get_head. Replaced wholesale on
        # justified-checkpoint changes, never mutated in place.
        self._justified_balances = _EMPTY_BALANCES
        # Set when a checkpoint promotion couldn't materialize the justified
        # state (tick-path with a cold cache); get_head retries the provider
        # so head selection never keeps stale weights longer than necessary.
        self._justified_balances_stale = False
        # Optional: block_root -> state, so justified balances come from the
        # actual justified checkpoint state (the reference's justified
        # balances cache); falls back to the importing block's state.
        self.state_provider = None

    # ------------------------------------------------------------------ init

    @classmethod
    def from_anchor(cls, anchor_root: bytes, anchor_state, spec: ChainSpec, E):
        """Initialize from a genesis or checkpoint (weak subjectivity) state
        (fork_choice.rs from_anchor)."""
        from .proto_array import ProtoArrayForkChoice

        epoch = get_current_epoch(anchor_state, E)
        cp = Checkpoint(epoch=max(epoch, GENESIS_EPOCH), root=anchor_root)
        store = ForkChoiceStore(
            current_slot=anchor_state.slot,
            justified_checkpoint=cp,
            finalized_checkpoint=cp,
            unrealized_justified_checkpoint=cp,
            unrealized_finalized_checkpoint=cp,
        )
        proto = ProtoArrayForkChoice(
            finalized_root=anchor_root,
            finalized_slot=anchor_state.slot,
            finalized_state_root=anchor_state.hash_tree_root(),
            justified_epoch=cp.epoch,
            finalized_epoch=cp.epoch,
        )
        fc = cls(store, proto, spec, E)
        fc._justified_balances = _active_balances(anchor_state, E)
        return fc

    # ------------------------------------------------------------------ ticks

    def on_tick(self, slot: int):
        """Advance wall-clock slot; reset proposer boost at slot start and,
        on epoch boundaries, promote the unrealized checkpoints to the store
        (spec on_tick_per_slot) — without this, justification can lag
        indefinitely when no new blocks arrive."""
        while self.store.current_slot < slot:
            self.store.current_slot += 1
            self.store.proposer_boost_root = b"\x00" * 32
            if self.store.current_slot % self.E.SLOTS_PER_EPOCH == 0:
                self._update_checkpoints(
                    self.store.unrealized_justified_checkpoint,
                    self.store.unrealized_finalized_checkpoint,
                    state=None,
                )
        self._drain_deferred_attestations()

    def _drain_deferred_attestations(self):
        """Apply queued same-slot votes that the clock has now cleared.
        Entries whose slot is still current stay queued (an on_tick that
        doesn't advance the slot must not re-defer or double-count)."""
        q = self._deferred_attestations
        if not q:
            return
        cur = self.store.current_slot
        ready = [ia for ia in q if int(ia.data.slot) < cur]
        if not ready:
            return
        self._deferred_attestations = [
            ia for ia in q if int(ia.data.slot) >= cur
        ]
        # per-item isolation inside the batch: a vote that went stale in
        # the queue (e.g. pruned head) costs only itself
        self.on_attestation_batch(ready)
        _DEFERRED_ATTESTATIONS.inc(len(ready), outcome="applied")

    # ------------------------------------------------------------------ block

    def on_block(
        self,
        current_slot: int,
        block,
        block_root: bytes,
        state,
        is_timely: bool = False,
    ):
        """Register an imported block (fork_choice.rs:642). `state` is the
        post-state; unrealized checkpoints are drawn from it by running
        justification processing on a throwaway copy."""
        self.on_tick(max(current_slot, self.store.current_slot))
        if block.slot > current_slot:
            raise InvalidBlock(f"future block: {block.slot} > {current_slot}")
        if not self.proto.contains_block(block.parent_root):
            raise InvalidBlock("unknown parent")
        finalized_slot = compute_start_slot_at_epoch(
            self.store.finalized_checkpoint.epoch, self.E
        )
        if block.slot <= finalized_slot:
            raise InvalidBlock("block conflicts with finality (too old)")
        if not self.proto.proto_array.is_descendant(
            self.store.finalized_checkpoint.root, block.parent_root
        ):
            raise InvalidBlock("block does not descend from finalized root")

        # Proposer boost: first timely block for the current slot.
        if (
            is_timely
            and block.slot == current_slot
            and self.store.proposer_boost_root == b"\x00" * 32
        ):
            self.store.proposer_boost_root = block_root

        unrealized_j, unrealized_f = self._compute_unrealized_checkpoints(state)

        # Checkpoint update rules (pull-up tips)
        self._update_checkpoints(
            Checkpoint(
                state.current_justified_checkpoint.epoch,
                state.current_justified_checkpoint.root,
            ),
            Checkpoint(
                state.finalized_checkpoint.epoch, state.finalized_checkpoint.root
            ),
            state,
        )
        if unrealized_j.epoch > self.store.unrealized_justified_checkpoint.epoch:
            self.store.unrealized_justified_checkpoint = unrealized_j
        if unrealized_f.epoch > self.store.unrealized_finalized_checkpoint.epoch:
            self.store.unrealized_finalized_checkpoint = unrealized_f
        # Blocks from prior epochs are pulled up immediately.
        if compute_epoch_at_slot(block.slot, self.E) < compute_epoch_at_slot(
            current_slot, self.E
        ):
            self._update_checkpoints(unrealized_j, unrealized_f, state)

        self.proto.on_block(
            slot=block.slot,
            root=block_root,
            parent_root=block.parent_root,
            state_root=block.state_root,
            justified_epoch=state.current_justified_checkpoint.epoch,
            finalized_epoch=state.finalized_checkpoint.epoch,
            unrealized_justified_epoch=unrealized_j.epoch,
            unrealized_finalized_epoch=unrealized_f.epoch,
        )

    def _update_checkpoints(
        self, justified: Checkpoint, finalized: Checkpoint, state=None
    ):
        if justified.epoch > self.store.justified_checkpoint.epoch:
            self.store.justified_checkpoint = justified
            # Vote weights must come from the justified state's effective
            # balances (spec). The provider serves the actual justified
            # state; the importing block's post-state is a fallback whose
            # active set matches at the justified epoch in all but deep-reorg
            # edge cases; with neither, keep the previous balances but mark
            # them stale so get_head retries the provider before selecting.
            balance_state = None
            if self.state_provider is not None:
                balance_state = self.state_provider(justified.root)
            if balance_state is None:
                balance_state = state
            if balance_state is not None:
                self._justified_balances = _active_balances(
                    balance_state, self.E, at_epoch=justified.epoch
                )
                self._justified_balances_stale = False
            else:
                self._justified_balances_stale = True
        if finalized.epoch > self.store.finalized_checkpoint.epoch:
            self.store.finalized_checkpoint = finalized
            self.proto.proto_array.maybe_prune(finalized.root)

    def _compute_unrealized_checkpoints(self, state):
        """Run justification on a throwaway copy to see what this chain tip
        would justify at the next boundary (compute_pulled_up_tip)."""
        from ..state_processing.per_epoch import (
            process_justification_and_finalization,
        )

        epoch = get_current_epoch(state, self.E)
        if epoch <= GENESIS_EPOCH + 1:
            return (
                Checkpoint(
                    state.current_justified_checkpoint.epoch,
                    state.current_justified_checkpoint.root,
                ),
                Checkpoint(
                    state.finalized_checkpoint.epoch,
                    state.finalized_checkpoint.root,
                ),
            )
        tmp = state.copy()
        process_justification_and_finalization(tmp, self.E)
        return (
            Checkpoint(
                tmp.current_justified_checkpoint.epoch,
                tmp.current_justified_checkpoint.root,
            ),
            Checkpoint(
                tmp.finalized_checkpoint.epoch, tmp.finalized_checkpoint.root
            ),
        )

    # ------------------------------------------------------------------ votes

    def on_attestation(self, indexed_attestation, is_from_block: bool = False):
        """Track latest messages (fork_choice.rs:1037)."""
        data = indexed_attestation.data
        if self._maybe_defer(indexed_attestation, is_from_block):
            return
        self._validate_on_attestation(data, is_from_block)
        for vi in indexed_attestation.attesting_indices:
            if vi not in self.store.equivocating_indices:
                self.proto.process_attestation(
                    vi, data.beacon_block_root, data.target.epoch
                )

    def on_attestation_batch(
        self, indexed_attestations, is_from_block: bool = False
    ) -> list:
        """Batch latest-message tracking for a drained gossip batch: each
        attestation is validated exactly like `on_attestation`, then the
        accepted ones are grouped by (head root, target epoch) and their
        attesting-index arrays (the PR 7 columnar assembly —
        `attesting_indices` is a PersistentList whose `load_array` is one
        C-speed conversion) concatenate into ONE vectorized vote write per
        group instead of ~16k per-validator dict operations. Returns one
        entry per input: None on acceptance, the InvalidAttestation
        otherwise (callers treat fork-choice rejection as non-fatal,
        exactly like the scalar path's per-item try/except)."""
        groups: dict[tuple[bytes, int], list] = {}
        results: list = []
        for ia in indexed_attestations:
            # per-item guard, matching the scalar path's per-attestation
            # try/except: one malformed attestation must cost only its own
            # vote, never the rest of the batch
            try:
                data = ia.data
                if self._maybe_defer(ia, is_from_block):
                    results.append(None)
                    continue
                self._validate_on_attestation(data, is_from_block)
                indices = ia.attesting_indices
                arr = (
                    indices.load_array()
                    if hasattr(indices, "load_array")
                    else np.asarray(list(indices), dtype=np.uint64)
                )
            except Exception as e:  # noqa: BLE001 — per-item isolation
                results.append(
                    e
                    if isinstance(e, InvalidAttestation)
                    else InvalidAttestation(str(e))
                )
                continue
            results.append(None)
            groups.setdefault(
                (bytes(data.beacon_block_root), int(data.target.epoch)), []
            ).append(arr)
        equivocating = self.store.equivocating_indices
        eq_arr = None
        if equivocating:
            eq_arr = np.fromiter(
                equivocating, dtype=np.uint64, count=len(equivocating)
            )
        for (root, epoch), chunks in groups.items():
            try:
                v = np.concatenate(chunks) if len(chunks) > 1 else chunks[0]
                if eq_arr is not None:
                    v = v[~np.isin(v, eq_arr)]
                self.proto.process_attestation_batch(v, root, epoch)
            except Exception:  # noqa: BLE001 — a hard error in one
                continue  # (root, epoch) group must not drop the others
        return results

    def _maybe_defer(self, indexed_attestation, is_from_block: bool) -> bool:
        """Queue a gossip attestation from the store's current slot — or
        ahead of it, when the store lags the wall clock between ticks —
        for the tick that clears it, instead of rejecting it as "from
        the future" (fork_choice.rs queued_attestations): its committee
        saw the head this slot, and the next slot's proposer-boost
        re-org decision needs that weight. Upstream gossip validation
        already bounds data.slot by the wall clock, so the queue depth
        is one slot's traffic (plus the cap). Structural validation runs
        NOW (with `is_from_block=True`, which skips exactly the two
        gossip recency rules — one satisfied for any queueable slot, the
        other the reason we defer), so the queue only ever holds votes
        that will count. Returns True if the attestation was consumed
        (queued or cap-shed)."""
        if is_from_block:
            return False
        data = indexed_attestation.data
        if int(data.slot) < self.store.current_slot:
            return False
        self._validate_on_attestation(data, is_from_block=True)
        if len(self._deferred_attestations) >= _MAX_DEFERRED_ATTESTATIONS:
            _DEFERRED_ATTESTATIONS.inc(outcome="dropped")
            return True
        self._deferred_attestations.append(indexed_attestation)
        _DEFERRED_ATTESTATIONS.inc(outcome="deferred")
        return True

    def _validate_on_attestation(self, data, is_from_block: bool):
        # Recency applies to gossip only; attestations carried in blocks may
        # be arbitrarily old when syncing (spec validate_on_attestation).
        if not is_from_block:
            current_epoch = compute_epoch_at_slot(
                self.store.current_slot, self.E
            )
            if data.target.epoch not in (
                current_epoch,
                max(0, current_epoch - 1),
            ):
                raise InvalidAttestation(
                    f"target epoch {data.target.epoch} not current/previous"
                )
        if data.target.epoch != compute_epoch_at_slot(data.slot, self.E):
            raise InvalidAttestation("target epoch does not match slot")
        if not self.proto.contains_block(data.target.root):
            raise InvalidAttestation("unknown target root")
        if not self.proto.contains_block(data.beacon_block_root):
            raise InvalidAttestation("unknown head block")
        head_slot = self.proto.block_slot(data.beacon_block_root)
        if head_slot is not None and head_slot > data.slot:
            raise InvalidAttestation("attestation to a future block")
        # FFG/LMD consistency: the target must be the checkpoint block of the
        # head block's chain at target.epoch (spec validate_on_attestation;
        # fork_choice.rs target-root ancestor check).
        target_slot = compute_start_slot_at_epoch(data.target.epoch, self.E)
        checkpoint_block = self.proto.proto_array.ancestor_at_slot(
            data.beacon_block_root, target_slot
        )
        if checkpoint_block is None:
            raise UnknownAncestor(
                "head block's chain does not reach the target epoch in the "
                "proto-array (pre-finalization or pruned ancestor)"
            )
        if checkpoint_block != data.target.root:
            raise InvalidAttestation(
                "attestation target is inconsistent with the head block's "
                "chain at the target epoch"
            )
        if not is_from_block and self.store.current_slot < data.slot + 1:
            raise InvalidAttestation("attestation from the future")

    def on_equivocation(self, validator_indices):
        self.store.equivocating_indices.update(validator_indices)

    # ------------------------------------------------------------------ head

    def get_head(self, current_slot: int | None = None) -> bytes:
        """Recompute and return the canonical head root (fork_choice.rs:468)."""
        if current_slot is not None:
            self.on_tick(current_slot)
        if self._justified_balances_stale and self.state_provider is not None:
            jcp = self.store.justified_checkpoint
            balance_state = self.state_provider(jcp.root)
            if balance_state is not None:
                self._justified_balances = _active_balances(
                    balance_state, self.E, at_epoch=jcp.epoch
                )
                self._justified_balances_stale = False
        boost_amount = 0
        if self.store.proposer_boost_root != b"\x00" * 32:
            total = _total_balance(self._justified_balances)
            committee_weight = total // self.E.SLOTS_PER_EPOCH
            boost_amount = (
                committee_weight * self.spec.proposer_score_boost // 100
            )
        return self.proto.get_head(
            justified_checkpoint_root=self.store.justified_checkpoint.root,
            justified_epoch=self.store.justified_checkpoint.epoch,
            finalized_epoch=self.store.finalized_checkpoint.epoch,
            justified_state_balances=self._justified_balances,
            proposer_boost_root=self.store.proposer_boost_root,
            proposer_boost_amount=boost_amount,
            equivocating_indices=self.store.equivocating_indices,
        )

    def contains_block(self, root: bytes) -> bool:
        return self.proto.contains_block(root)

    def get_proposer_head(
        self, slot: int, head_root: bytes, head_late: bool
    ) -> bytes:
        """Spec `get_proposer_head` (proposer boost re-org): the root the
        proposer of `slot` should build on — the head's PARENT when the
        head is a weak, late, non-finality-risking, single-slot block the
        boosted re-org block would beat; otherwise the head itself.

        `head_late` is supplied by the caller (BlockTimesCache observed
        milestone vs the attestation deadline) — lateness is an
        observation-time property the fork-choice store never sees.
        Weights are read as left by the last `get_head` pass; callers run
        this right after a head recompute (every import triggers one), so
        they are at most one pending-attestation batch stale."""
        if not head_late:
            return head_root
        epoch = compute_epoch_at_slot(slot, self.E)
        max_epochs = self.spec.reorg_max_epochs_since_finalization
        if epoch - self.store.finalized_checkpoint.epoch > max_epochs:
            return head_root
        total = _total_balance(self._justified_balances)
        committee_weight = total // self.E.SLOTS_PER_EPOCH
        parent = self.proto.proto_array.get_proposer_head(
            slot,
            head_root,
            committee_weight,
            self.spec.reorg_head_weight_threshold,
            self.spec.reorg_parent_weight_threshold,
            self.E.SLOTS_PER_EPOCH,
        )
        return parent if parent is not None else head_root


def _total_balance(balances) -> int:
    return int(np.asarray(balances, dtype=np.uint64).sum(dtype=np.uint64))


def _active_balances(state, E, at_epoch: int | None = None):
    """Effective balances of active validators as a [n] uint64 array —
    one vectorized mask over the resident registry columns when the state
    carries them (the per-validator list comprehension was a 1M-element
    Python sweep on every justified-checkpoint change)."""
    from ..state_processing.accessors import _fresh_columns

    epoch = get_current_epoch(state, E) if at_epoch is None else at_epoch
    cols = _fresh_columns(state)
    if cols is not None:
        return np.where(
            cols.active_mask(epoch), cols.effective_balance, np.uint64(0)
        )
    return np.fromiter(
        (
            v.effective_balance
            if v.activation_epoch <= epoch < v.exit_epoch
            else 0
            for v in state.validators
        ),
        dtype=np.uint64,
        count=len(state.validators),
    )
