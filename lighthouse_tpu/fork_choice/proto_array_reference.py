# lint: allow-file(safe-arith) -- retained scalar oracle, kept verbatim as
# the differential baseline and bench control for the columnar rewrite
"""Scalar proto-array fork choice — the retained differential oracle.

This is the pre-columnar implementation of `proto_array.py`, kept
verbatim (per the established reference-module pattern:
`pairing_reference`, `epoch_reference`, `process_attestations_reference`)
as:

  * the differential oracle the columnar rewrite is fuzzed against
    (tests/test_fork_choice_columnar.py — bit-identical head roots,
    weights, and prune survivors across randomized vote churn), and
  * the bench control `fork_choice_get_head_ms` reports `vs_baseline`
    against (scalar oracle on a validator subsample, same run).

It walks Python `ProtoNode` objects and a per-validator
`dict[int, VoteTracker]` on every `get_head` — exactly the scalar cost
shape the columnar module replaces. Do not optimize this file.
"""

from __future__ import annotations

from dataclasses import dataclass

from .proto_array import ExecutionStatus, ProtoArrayError


@dataclass
class ProtoNode:
    slot: int
    root: bytes
    parent: int | None  # index into ProtoArray.nodes
    state_root: bytes
    justified_epoch: int
    finalized_epoch: int
    # Unrealized checkpoints ("pull-up tips", modern fork choice)
    unrealized_justified_epoch: int | None = None
    unrealized_finalized_epoch: int | None = None
    weight: int = 0
    best_child: int | None = None
    best_descendant: int | None = None
    execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT


@dataclass
class VoteTracker:
    """Latest attestation message per validator (vote_tracker in
    proto_array_fork_choice.rs)."""

    current_root: bytes = b"\x00" * 32
    next_root: bytes = b"\x00" * 32
    next_epoch: int = 0


class ProtoArrayReference:
    def __init__(self, justified_epoch: int, finalized_epoch: int):
        self.nodes: list[ProtoNode] = []
        self.indices: dict[bytes, int] = {}
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self.prune_threshold = 256
        # Previous proposer boost, subtracted on the next score pass
        # (the reference stores this as previous_proposer_boost).
        self._prev_boost_root: bytes = b"\x00" * 32
        self._prev_boost_amount: int = 0

    # ------------------------------------------------------------------ insert

    def on_block(
        self,
        slot: int,
        root: bytes,
        parent_root: bytes | None,
        state_root: bytes,
        justified_epoch: int,
        finalized_epoch: int,
        unrealized_justified_epoch: int | None = None,
        unrealized_finalized_epoch: int | None = None,
        execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT,
    ):
        if root in self.indices:
            return
        parent = self.indices.get(parent_root) if parent_root is not None else None
        node = ProtoNode(
            slot=slot,
            root=root,
            parent=parent,
            state_root=state_root,
            justified_epoch=justified_epoch,
            finalized_epoch=finalized_epoch,
            unrealized_justified_epoch=unrealized_justified_epoch,
            unrealized_finalized_epoch=unrealized_finalized_epoch,
        )
        index = len(self.nodes)
        self.nodes.append(node)
        self.indices[root] = index
        if parent is not None:
            self._maybe_update_best_child_and_descendant(parent, index)

    # ------------------------------------------------------------------ scores

    def apply_score_changes(
        self,
        deltas: list[int],
        justified_epoch: int,
        finalized_epoch: int,
        proposer_boost_root: bytes = b"\x00" * 32,
        proposer_boost_amount: int = 0,
    ):
        """One backwards pass: add deltas, roll child weight into parent,
        refresh best_child/best_descendant (proto_array.rs
        apply_score_changes)."""
        if len(deltas) != len(self.nodes):
            raise ProtoArrayError("delta length mismatch")
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        # Proposer boost is transient: undo last pass's boost, apply this
        # pass's (the reference's previous_proposer_boost bookkeeping).
        if self._prev_boost_amount:
            pi = self.indices.get(self._prev_boost_root)
            if pi is not None:
                deltas[pi] -= self._prev_boost_amount
        if proposer_boost_amount:
            bi = self.indices.get(proposer_boost_root)
            if bi is not None:
                deltas[bi] += proposer_boost_amount
        self._prev_boost_root = proposer_boost_root
        self._prev_boost_amount = proposer_boost_amount

        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            delta = deltas[i]
            node.weight += delta
            if node.weight < 0:
                raise ProtoArrayError("negative node weight")
            if node.parent is not None:
                deltas[node.parent] += delta
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.parent is not None:
                self._maybe_update_best_child_and_descendant(node.parent, i)

    # ------------------------------------------------------------------ head

    def node_is_viable_for_head(self, node: ProtoNode) -> bool:
        """Viability filter (node_is_viable_for_head in proto_array.rs):
        the node's (unrealized-or-realized) checkpoints must agree with the
        store's, and its payload must not be invalid."""
        if node.execution_status == ExecutionStatus.INVALID:
            return False
        j = (
            node.unrealized_justified_epoch
            if node.unrealized_justified_epoch is not None
            else node.justified_epoch
        )
        f = (
            node.unrealized_finalized_epoch
            if node.unrealized_finalized_epoch is not None
            else node.finalized_epoch
        )
        correct_justified = j >= self.justified_epoch or self.justified_epoch == 0
        correct_finalized = f >= self.finalized_epoch or self.finalized_epoch == 0
        return correct_justified and correct_finalized

    def _leads_to_viable_head(self, node: ProtoNode) -> bool:
        if node.best_descendant is not None:
            return self.node_is_viable_for_head(self.nodes[node.best_descendant])
        return self.node_is_viable_for_head(node)

    def _maybe_update_best_child_and_descendant(self, parent_i: int, child_i: int):
        parent = self.nodes[parent_i]
        child = self.nodes[child_i]
        child_leads_to_viable = self._leads_to_viable_head(child)

        if parent.best_child == child_i:
            if not child_leads_to_viable:
                parent.best_child = None
                parent.best_descendant = None
            else:
                self._set_best(parent, child_i)
        elif parent.best_child is None:
            if child_leads_to_viable:
                self._set_best(parent, child_i)
        else:
            best = self.nodes[parent.best_child]
            best_viable = self._leads_to_viable_head(best)
            if child_leads_to_viable and not best_viable:
                self._set_best(parent, child_i)
            elif child_leads_to_viable and (
                child.weight > best.weight
                or (child.weight == best.weight and child.root > best.root)
            ):
                # tie-break on higher root lexicographically (matches the
                # reference's deterministic tie-break)
                self._set_best(parent, child_i)

    def _set_best(self, parent: ProtoNode, child_i: int):
        child = self.nodes[child_i]
        parent.best_child = child_i
        parent.best_descendant = (
            child.best_descendant if child.best_descendant is not None else child_i
        )

    def find_head(self, justified_root: bytes) -> bytes:
        ji = self.indices.get(justified_root)
        if ji is None:
            raise ProtoArrayError(f"justified root {justified_root.hex()} unknown")
        node = self.nodes[ji]
        best = (
            self.nodes[node.best_descendant]
            if node.best_descendant is not None
            else node
        )
        if not self.node_is_viable_for_head(best):
            raise ProtoArrayError("best node is not viable for head")
        return best.root

    def get_proposer_head(
        self,
        slot: int,
        head_root: bytes,
        committee_weight: int,
        head_threshold_pct: int,
        parent_threshold_pct: int,
        slots_per_epoch: int,
    ) -> bytes | None:
        """Scalar oracle for ProtoArray.get_proposer_head: one node at a
        time over ProtoNode objects, no column reads. Same contract —
        the parent root to build on, or None to keep the head; the
        caller owns lateness/finalization/on-time conditions."""
        hi = self.indices.get(head_root)
        if hi is None:
            return None
        head = self.nodes[hi]
        if head.parent is None:
            return None
        parent = self.nodes[head.parent]
        if parent.slot + 1 != head.slot or head.slot + 1 != slot:
            return None
        if slot % slots_per_epoch == 0:
            return None
        head_j = (
            head.unrealized_justified_epoch
            if head.unrealized_justified_epoch is not None
            else head.justified_epoch
        )
        parent_j = (
            parent.unrealized_justified_epoch
            if parent.unrealized_justified_epoch is not None
            else parent.justified_epoch
        )
        if head_j != parent_j:
            return None
        head_weight = head.weight
        if self._prev_boost_root == head_root:
            head_weight = max(0, head_weight - self._prev_boost_amount)
        head_weak = head_weight < committee_weight * head_threshold_pct // 100
        parent_strong = (
            parent.weight > committee_weight * parent_threshold_pct // 100
        )
        if not (head_weak and parent_strong):
            return None
        return parent.root

    # ------------------------------------------------------------------ misc

    def ancestor_at_slot(self, root: bytes, slot: int) -> bytes | None:
        """Spec get_ancestor: the block in `root`'s chain at or before `slot`
        (walks parents; returns None if root is unknown or the walk leaves
        the array)."""
        i = self.indices.get(root)
        while i is not None:
            node = self.nodes[i]
            if node.slot <= slot:
                return node.root
            i = node.parent
        return None

    def is_descendant(self, ancestor_root: bytes, descendant_root: bytes) -> bool:
        ai = self.indices.get(ancestor_root)
        di = self.indices.get(descendant_root)
        if ai is None or di is None:
            return False
        a_slot = self.nodes[ai].slot
        i = di
        while i is not None and self.nodes[i].slot >= a_slot:
            if i == ai:
                return True
            i = self.nodes[i].parent
        return False

    def propagate_execution_payload_validity(self, root: bytes):
        """Mark a block and all its ancestors VALID (an EL VALID verdict
        implies all ancestors valid)."""
        i = self.indices.get(root)
        while i is not None:
            node = self.nodes[i]
            if node.execution_status in (
                ExecutionStatus.OPTIMISTIC,
                ExecutionStatus.VALID,
            ):
                node.execution_status = ExecutionStatus.VALID
            i = node.parent

    def invalidate_block(self, root: bytes):
        """Mark a block and all its descendants INVALID
        (on_invalid_execution_payload)."""
        start = self.indices.get(root)
        if start is None:
            return
        bad = {start}
        self.nodes[start].execution_status = ExecutionStatus.INVALID
        for i in range(start + 1, len(self.nodes)):
            if self.nodes[i].parent in bad:
                bad.add(i)
                self.nodes[i].execution_status = ExecutionStatus.INVALID
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.parent is not None:
                self._maybe_update_best_child_and_descendant(node.parent, i)

    def maybe_prune(self, finalized_root: bytes):
        """Drop nodes before the finalized root (maybe_prune in
        proto_array.rs); keeps indices dense."""
        fi = self.indices.get(finalized_root)
        if fi is None or fi < self.prune_threshold:
            return
        keep = [
            i
            for i in range(len(self.nodes))
            if i >= fi
            and (
                self.nodes[i].root == finalized_root
                or self.is_descendant(finalized_root, self.nodes[i].root)
            )
        ]
        remap = {old: new for new, old in enumerate(keep)}
        new_nodes = []
        for old in keep:
            n = self.nodes[old]
            n.parent = remap.get(n.parent) if n.parent is not None else None
            n.best_child = remap.get(n.best_child) if n.best_child is not None else None
            n.best_descendant = (
                remap.get(n.best_descendant) if n.best_descendant is not None else None
            )
            new_nodes.append(n)
        self.nodes = new_nodes
        self.indices = {n.root: i for i, n in enumerate(self.nodes)}


class ProtoArrayForkChoiceReference:
    """Scalar proto-array + vote tracking + balance-weighted deltas
    (proto_array_fork_choice.rs) — the per-validator dict walk the
    columnar `ProtoArrayForkChoice` replaced."""

    def __init__(
        self,
        finalized_root: bytes,
        finalized_slot: int,
        finalized_state_root: bytes,
        justified_epoch: int,
        finalized_epoch: int,
    ):
        self.proto_array = ProtoArrayReference(justified_epoch, finalized_epoch)
        self.votes: dict[int, VoteTracker] = {}
        self.balances: list[int] = []
        self.proto_array.on_block(
            slot=finalized_slot,
            root=finalized_root,
            parent_root=None,
            state_root=finalized_state_root,
            justified_epoch=justified_epoch,
            finalized_epoch=finalized_epoch,
        )

    def process_attestation(
        self, validator_index: int, block_root: bytes, target_epoch: int
    ):
        vote = self.votes.setdefault(validator_index, VoteTracker())
        # Accept strictly-newer votes, or the first vote ever (epoch-0
        # attestations must land on a fresh default tracker).
        is_default = (
            vote.current_root == b"\x00" * 32
            and vote.next_root == b"\x00" * 32
            and vote.next_epoch == 0
        )
        if target_epoch > vote.next_epoch or is_default:
            vote.next_root = block_root
            vote.next_epoch = target_epoch

    def on_block(self, **kwargs):
        self.proto_array.on_block(**kwargs)

    def contains_block(self, root: bytes) -> bool:
        return root in self.proto_array.indices

    def block_slot(self, root: bytes) -> int | None:
        i = self.proto_array.indices.get(root)
        return self.proto_array.nodes[i].slot if i is not None else None

    def _compute_deltas(self, new_balances: list[int], equivocating: set[int]):
        deltas = [0] * len(self.proto_array.nodes)
        idx = self.proto_array.indices
        for vi, vote in self.votes.items():
            if vote.current_root == vote.next_root and vi not in equivocating:
                continue
            old_balance = self.balances[vi] if vi < len(self.balances) else 0
            new_balance = new_balances[vi] if vi < len(new_balances) else 0
            if vi in equivocating:
                # equivocating validators: remove their old vote forever
                ci = idx.get(vote.current_root)
                if ci is not None:
                    deltas[ci] -= old_balance
                vote.current_root = b"\x00" * 32
                vote.next_root = b"\x00" * 32
                continue
            ci = idx.get(vote.current_root)
            if ci is not None:
                deltas[ci] -= old_balance
            ni = idx.get(vote.next_root)
            if ni is not None:
                deltas[ni] += new_balance
            # Always mark applied — a pruned next_root must not leave the
            # old subtraction repeating on every later pass.
            vote.current_root = vote.next_root
        self.balances = list(new_balances)
        return deltas

    def get_head(
        self,
        justified_checkpoint_root: bytes,
        justified_epoch: int,
        finalized_epoch: int,
        justified_state_balances: list[int],
        proposer_boost_root: bytes = b"\x00" * 32,
        proposer_boost_amount: int = 0,
        equivocating_indices: set[int] | None = None,
    ) -> bytes:
        deltas = self._compute_deltas(
            justified_state_balances, equivocating_indices or set()
        )
        self.proto_array.apply_score_changes(
            deltas,
            justified_epoch,
            finalized_epoch,
            proposer_boost_root,
            proposer_boost_amount,
        )
        return self.proto_array.find_head(justified_checkpoint_root)
