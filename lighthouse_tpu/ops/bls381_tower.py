"""Device Fq2/Fq6/Fq12 tower arithmetic for BLS12-381 pairings.

Extends the batched limb kernels of `ops.bls381` (Fq/Fq2 over [..., 48]
int32 Montgomery limbs) up the tower used by the optimal-ate pairing:

    Fq2  = Fq[u]/(u²+1)          shape [..., 2, 48]
    Fq6  = Fq2[v]/(v³−ξ), ξ=u+1  shape [..., 3, 2, 48]
    Fq12 = Fq6[w]/(w²−v)         shape [..., 2, 3, 2, 48]

All values are in Montgomery form. The formulas mirror the host tower in
`crypto/bls12_381/fields.py` (the correctness oracle in tests) — Karatsuba
Fq2/Fq6/Fq12 multiplication, tower inversion reduced to one Fq inversion
(done by Fermat with a fixed 381-bit square-and-multiply scan; device code
cannot use extended Euclid's data-dependent loop), and Frobenius via
host-precomputed γ coefficients pushed as Montgomery limb constants.

Role in the reference: these are the Fq12 field ops inside blst's pairing
(vendored C/assembly, crypto/bls/src/impls/blst.rs:112) — here batched over
the signature-set dimension and jit/shard-friendly.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..crypto.bls12_381 import fields as HF
from ..crypto.bls12_381.fields import P
from .bls381 import (
    NLIMB,
    R_MONT,
    _ONE_MONT,
    int_to_limbs,
    mod_add,
    mod_sub,
    mont_mul,
)

# ---------------------------------------------------------------------------
# Constants (host ints → Montgomery limb arrays)
# ---------------------------------------------------------------------------


def fq_const(v: int) -> np.ndarray:
    """Fq constant in Montgomery limb form, shape [48]."""
    return int_to_limbs(v * R_MONT % P)


def fq2_const(c) -> np.ndarray:
    """Fq2 constant (c0, c1) → [2, 48] Montgomery limbs."""
    return np.stack([fq_const(c[0]), fq_const(c[1])])


_FQ_ZERO = np.zeros(NLIMB, dtype=np.int32)
F2_ONE_DEV = np.stack([_ONE_MONT, _FQ_ZERO])
F2_ZERO_DEV = np.zeros((2, NLIMB), dtype=np.int32)

# Frobenius coefficients (derived on host in fields.py, not memorized):
#   v^p  = γ6_1·v,  v^{2p} = γ6_2·v²,  w^p = γ12·w
_G6_1_DEV = fq2_const(HF._G6_1)
_G6_2_DEV = fq2_const(HF._G6_2)
_G12_DEV = fq2_const(HF._G12)

# Fixed exponent bits for Fermat inversion a^(p-2), LSB first.
_PM2_BITS = np.array([(P - 2) >> i & 1 for i in range((P - 2).bit_length())],
                     dtype=np.int32)


# ---------------------------------------------------------------------------
# Fq2 ops ([..., 2, 48]); complements ops.bls381.DevFq2
# ---------------------------------------------------------------------------


def f2_add(a, b):
    return jnp.stack(
        [mod_add(a[..., 0, :], b[..., 0, :]), mod_add(a[..., 1, :], b[..., 1, :])],
        axis=-2,
    )


def f2_sub(a, b):
    return jnp.stack(
        [mod_sub(a[..., 0, :], b[..., 0, :]), mod_sub(a[..., 1, :], b[..., 1, :])],
        axis=-2,
    )


def f2_neg(a):
    return f2_sub(jnp.zeros_like(a), a)


def f2_conj(a):
    c1 = mod_sub(jnp.zeros_like(a[..., 1, :]), a[..., 1, :])
    return jnp.stack([a[..., 0, :], c1], axis=-2)


def f2_mul(a, b):
    """Karatsuba: 3 base mults."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    b0, b1 = b[..., 0, :], b[..., 1, :]
    t0 = mont_mul(a0, b0)
    t1 = mont_mul(a1, b1)
    cross = mont_mul(mod_add(a0, a1), mod_add(b0, b1))
    return jnp.stack(
        [mod_sub(t0, t1), mod_sub(mod_sub(cross, t0), t1)], axis=-2
    )


def f2_sqr(a):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    c0 = mont_mul(mod_add(a0, a1), mod_sub(a0, a1))
    t = mont_mul(a0, a1)
    return jnp.stack([c0, mod_add(t, t)], axis=-2)


def f2_mul_xi(a):
    """ξ·a = (c0−c1) + (c0+c1)u."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack([mod_sub(a0, a1), mod_add(a0, a1)], axis=-2)


def f2_mul_fq(a, s):
    """Fq2 × Fq scalar: s shape [..., 48]."""
    return jnp.stack(
        [mont_mul(a[..., 0, :], s), mont_mul(a[..., 1, :], s)], axis=-2
    )


def f2_double(a):
    return f2_add(a, a)


def f2_triple(a):
    return f2_add(f2_add(a, a), a)


def fq_inv(a):
    """Fermat a^(p−2) over [..., 48] limbs — fixed 380-iteration scan with
    static bits (no data-dependent control flow under jit)."""
    bits = jnp.asarray(_PM2_BITS)
    one = jnp.broadcast_to(jnp.asarray(_ONE_MONT), a.shape).astype(jnp.int32)

    def body(carry, bit):
        acc, base = carry
        acc = jnp.where(bit > 0, mont_mul(acc, base), acc)
        return (acc, mont_mul(base, base)), None

    (acc, _), _ = lax.scan(body, (one, a), bits)
    return acc


def f2_inv(a):
    """1/(a0+a1u) = (a0 − a1u)/(a0²+a1²): one Fq inversion."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    norm = mod_add(mont_mul(a0, a0), mont_mul(a1, a1))
    ninv = fq_inv(norm)
    return jnp.stack(
        [mont_mul(a0, ninv), mod_sub(jnp.zeros_like(a0), mont_mul(a1, ninv))],
        axis=-2,
    )


def f2_select(c, a, b):
    """c: [...] bool."""
    return jnp.where(c[..., None, None], a, b)


def f2_is_zero(a):
    return jnp.all(a == 0, axis=(-1, -2))


# ---------------------------------------------------------------------------
# Fq6 ops ([..., 3, 2, 48])
# ---------------------------------------------------------------------------


def _f6(c0, c1, c2):
    return jnp.stack([c0, c1, c2], axis=-3)


def f6_slots(a):
    return a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]


def f6_add(a, b):
    a0, a1, a2 = f6_slots(a)
    b0, b1, b2 = f6_slots(b)
    return _f6(f2_add(a0, b0), f2_add(a1, b1), f2_add(a2, b2))


def f6_sub(a, b):
    a0, a1, a2 = f6_slots(a)
    b0, b1, b2 = f6_slots(b)
    return _f6(f2_sub(a0, b0), f2_sub(a1, b1), f2_sub(a2, b2))


def f6_neg(a):
    return f6_sub(jnp.zeros_like(a), a)


def f6_mul(a, b):
    """Toom-style 6-mult Fq6 product (mirrors host f6_mul)."""
    a0, a1, a2 = f6_slots(a)
    b0, b1, b2 = f6_slots(b)
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    c0 = f2_add(
        t0,
        f2_mul_xi(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)), f2_add(t1, t2))),
    )
    c1 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), f2_add(t0, t1)),
        f2_mul_xi(t2),
    )
    c2 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), f2_add(t0, t2)), t1
    )
    return _f6(c0, c1, c2)


def f6_sqr(a):
    return f6_mul(a, a)


def f6_mul_by_v(a):
    a0, a1, a2 = f6_slots(a)
    return _f6(f2_mul_xi(a2), a0, a1)


def f6_inv(a):
    a0, a1, a2 = f6_slots(a)
    c0 = f2_sub(f2_sqr(a0), f2_mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(f2_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    denom = f2_add(
        f2_mul(a0, c0), f2_mul_xi(f2_add(f2_mul(a2, c1), f2_mul(a1, c2)))
    )
    t = f2_inv(denom)
    return _f6(f2_mul(c0, t), f2_mul(c1, t), f2_mul(c2, t))


def f6_frob(a):
    a0, a1, a2 = f6_slots(a)
    return _f6(
        f2_conj(a0),
        f2_mul(f2_conj(a1), jnp.asarray(_G6_1_DEV)),
        f2_mul(f2_conj(a2), jnp.asarray(_G6_2_DEV)),
    )


# ---------------------------------------------------------------------------
# Fq12 ops ([..., 2, 3, 2, 48])
# ---------------------------------------------------------------------------


def _f12(a, b):
    return jnp.stack([a, b], axis=-4)


def f12_slots(a):
    return a[..., 0, :, :, :], a[..., 1, :, :, :]


def f12_ones(batch_shape) -> jnp.ndarray:
    one = np.zeros((2, 3, 2, NLIMB), dtype=np.int32)
    one[0, 0] = F2_ONE_DEV
    return jnp.broadcast_to(jnp.asarray(one), (*batch_shape, 2, 3, 2, NLIMB))


def f12_add(a, b):
    a0, a1 = f12_slots(a)
    b0, b1 = f12_slots(b)
    return _f12(f6_add(a0, b0), f6_add(a1, b1))


def f12_mul(a, b):
    a0, a1 = f12_slots(a)
    b0, b1 = f12_slots(b)
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    c0 = f6_add(t0, f6_mul_by_v(t1))
    c1 = f6_sub(f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), t0), t1)
    return _f12(c0, c1)


def f12_sqr(a):
    a0, a1 = f12_slots(a)
    t = f6_mul(a0, a1)
    c0 = f6_sub(
        f6_sub(f6_mul(f6_add(a0, a1), f6_add(a0, f6_mul_by_v(a1))), t),
        f6_mul_by_v(t),
    )
    c1 = f6_add(t, t)
    return _f12(c0, c1)


def f12_conj(a):
    a0, a1 = f12_slots(a)
    return _f12(a0, f6_neg(a1))


def f12_inv(a):
    a0, a1 = f12_slots(a)
    t = f6_inv(f6_sub(f6_sqr(a0), f6_mul_by_v(f6_sqr(a1))))
    return _f12(f6_mul(a0, t), f6_neg(f6_mul(a1, t)))


def f12_frob(a):
    a0, a1 = f12_slots(a)
    b0 = f6_frob(a0)
    b1 = f6_frob(a1)
    g = jnp.asarray(_G12_DEV)
    b1 = _f6(*[f2_mul(c, g) for c in f6_slots(b1)])
    return _f12(b0, b1)


def f12_frob2(a):
    return f12_frob(f12_frob(a))


def f12_select(c, a, b):
    """c: [...] bool, broadcast over the 4 trailing axes."""
    return jnp.where(c[..., None, None, None, None], a, b)


def f12_is_one(a):
    """Per-lane check a == 1 (Montgomery one in slot [0,0,0])."""
    return jnp.all(a == f12_ones(a.shape[:-4]), axis=(-1, -2, -3, -4))


def f12_pow_bits(a, bits: np.ndarray):
    """a^e for a FIXED exponent given as LSB-first bit array (host numpy).
    Square-and-multiply scan: branchless per-iteration select keeps the
    graph small (vs static unrolling) while the trip count stays static."""
    bits_d = jnp.asarray(bits.astype(np.int32))
    one = f12_ones(a.shape[:-4])

    def body(carry, bit):
        acc, base = carry
        acc = jnp.where(bit > 0, f12_mul(acc, base), acc)
        return (acc, f12_sqr(base)), None

    (acc, _), _ = lax.scan(body, (one, a), bits_d)
    return acc


# ---------------------------------------------------------------------------
# Host <-> device conversion for tower elements
# ---------------------------------------------------------------------------


def f2_to_device(vals: list) -> np.ndarray:
    """List of host Fq2 tuples → [n, 2, 48]."""
    return np.stack([fq2_const(v) for v in vals]).astype(np.int32)


def f12_to_device(vals: list) -> np.ndarray:
    """List of host Fq12 tuples → [n, 2, 3, 2, 48]."""
    out = np.zeros((len(vals), 2, 3, 2, NLIMB), dtype=np.int32)
    for i, (lo, hi) in enumerate(vals):
        for w, part in enumerate((lo, hi)):
            for v, c in enumerate(part):
                out[i, w, v] = fq2_const(c)
    return out


def f12_from_device(arr) -> list:
    from .bls381 import limbs_to_int

    host = np.asarray(arr).reshape(-1, 2, 3, 2, NLIMB)
    rinv = pow(R_MONT, -1, P)
    out = []
    for row in host:
        parts = []
        for w in range(2):
            parts.append(tuple(
                (limbs_to_int(row[w, v, 0]) * rinv % P,
                 limbs_to_int(row[w, v, 1]) * rinv % P)
                for v in range(3)
            ))
        out.append((parts[0], parts[1]))
    return out
