"""Device BLS12-381 arithmetic: batched field/point ops in JAX.

TPU offload point 1 (SURVEY.md §3.2): the per-set scalar multiplications of
batch signature verification — pubkey scaling by 64-bit random-linear-
combination scalars, signature scaling, and subgroup checks — vectorized
over the batch dimension.

Representation: Fq element = 48 limbs of 8 bits (base 2^8), little-endian,
held in int32. Products of 8-bit limbs are < 2^16 and a 48-term convolution
stays < 2^22 — comfortably inside int32, the widest integer multiply the
TPU VPU has (no u64). Montgomery form with R = 2^384:

    mont_mul(a, b) = a·b·R⁻¹ mod p
      t = conv(a, b)                      (96 limbs, coeffs < 2^22)
      m = low384(t) · N' mod R            (N' = -p⁻¹ mod R, one low-half conv)
      u = (t + m·p) / R                   (one conv + shift)
      conditional subtract p

Fq2 is a pair of Fq lanes; the Jacobian point layer is generic over a field-
ops record, exactly mirroring the host implementation in crypto/bls12_381/
curve.py (which doubles as the correctness oracle in tests).

Everything is shaped [batch, ...limbs] and jit/vmap/shard-friendly: scalar
bits drive a lax.fori_loop of fixed 64/256 trips with branchless selects.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..crypto.bls12_381.fields import P

NLIMB = 48  # 48 × 8-bit limbs = 384 bits
BASE = 8
MASK = (1 << BASE) - 1
R_MONT = 1 << 384
R2 = (R_MONT * R_MONT) % P
# N' = -p^{-1} mod R (full-width Montgomery constant)
NPRIME = (-pow(P, -1, R_MONT)) % R_MONT

AVAILABLE = True


def int_to_limbs(x: int, n: int = NLIMB) -> np.ndarray:
    return np.array([(x >> (BASE * i)) & MASK for i in range(n)], dtype=np.int32)


def limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(v) << (BASE * i) for i, v in enumerate(arr))


_P_LIMBS = int_to_limbs(P)
_NPRIME_LIMBS = int_to_limbs(NPRIME)
_R2_LIMBS = int_to_limbs(R2)
_ONE_MONT = int_to_limbs(R_MONT % P)  # 1 in Montgomery form
# 2^384 - p (for branchless compare/subtract via complement addition)
_PBAR_LIMBS = int_to_limbs(R_MONT - P)
_ZERO = np.zeros(NLIMB, dtype=np.int32)


# ---------------------------------------------------------------------------
# Limb-vector primitives (shapes [..., NLIMB], int32)
# ---------------------------------------------------------------------------


def _conv_full(a, b):
    """Full product convolution: [..., N] × [..., N] → [..., 2N-1].
    Outer product + anti-diagonal sums keeps everything MXU/VPU friendly."""
    n = a.shape[-1]
    outer = a[..., :, None] * b[..., None, :]  # [..., N, N] int32 (fits: 2^16)
    return _antidiagonal_sums(outer, 2 * n - 1)


def _conv_low(a, b):
    """Low-half convolution: product mod 2^(8N) — diagonals 0..N-1 only
    (carries go strictly upward, so truncating before normalize is exact)."""
    n = a.shape[-1]
    outer = a[..., :, None] * b[..., None, :]
    return _antidiagonal_sums(outer, n)


@functools.cache
def _adiag_matrix(n: int, out_cols: int) -> np.ndarray:
    """[N*N, out_cols] 0/1 matrix mapping outer-product entries to
    diagonals (out_cols < 2N-1 truncates to the low diagonals — a mod-2^(8c)
    product). Cached as numpy — a jnp constant cached across traces would
    leak tracers."""
    m = np.zeros((n * n, out_cols), dtype=np.int32)
    for i in range(n):
        for j in range(n):
            if i + j < out_cols:
                m[i * n + j, i + j] = 1
    return m


def _antidiagonal_sums(outer, out_cols: int):
    n = outer.shape[-1]
    flat = outer.reshape(*outer.shape[:-2], n * n)
    return flat @ jnp.asarray(_adiag_matrix(n, out_cols))  # int32 matmul


def _shift_carries(v):
    """One vectorized carry pass: keep low 8 bits, push carries one limb up
    (carry out of the last limb must be provably zero at every call site)."""
    hi = v >> BASE
    return (v & MASK) + jnp.concatenate(
        [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1
    )


def _resolve_carries(v):
    """Exact normalization for limbs in [0, 256]: carry-lookahead via an
    associative (generate, propagate) scan over the limb axis — a chain of
    255s before a 256 resolves in one log-depth pass instead of O(n)
    ripple passes."""
    g = v >= 256  # generates a carry
    p = v == 255  # propagates an incoming carry

    def combine(a, b):
        # a is closer to the LSB; carry out of the pair = b.g | (b.p & a.g)
        return (b[0] | (b[1] & a[0]), a[1] & b[1])

    G, _ = lax.associative_scan(combine, (g, p), axis=-1)
    carry_in = jnp.concatenate(
        [jnp.zeros_like(G[..., :1]), G[..., :-1]], axis=-1
    ).astype(v.dtype)
    return (v + carry_in) & MASK


def _carry_normalize(x, out_len: int, shrink_passes: int = 3):
    """Canonical 8-bit limbs from bounded coefficients (< 2^22): a few
    ripple passes shrink limbs into [0, 256], then one exact lookahead
    resolve."""
    n = x.shape[-1]
    if n < out_len:
        pad = [(0, 0)] * (x.ndim - 1) + [(0, out_len - n)]
        x = jnp.pad(x, pad)
    elif n > out_len:
        raise ValueError("carry overflow: input longer than output")
    v = x
    for _ in range(shrink_passes):
        v = _shift_carries(v)
    return _resolve_carries(v)


def _cond_sub_p(x):
    """x normalized, value in [0, 2p) → x mod p, branchless: s = x + (2^384
    - p); bit 384 of s is set iff x ≥ p, and then s's low 384 bits are x-p."""
    s = x + jnp.asarray(_PBAR_LIMBS)  # limbs ≤ 510
    s = _carry_normalize(s, NLIMB + 1, shrink_passes=2)
    ge = s[..., NLIMB] > 0
    return jnp.where(ge[..., None], s[..., :NLIMB], x)


def mont_mul(a, b):
    """Montgomery product a·b·R⁻¹ mod p. Inputs/outputs: [..., 48] int32,
    limbs < 2^8, value < p."""
    t = _conv_full(a, b)  # [..., 95], coeffs < 48·2^16 < 2^22
    t = _carry_normalize(t, 2 * NLIMB)  # 96 normalized limbs
    t_lo = t[..., :NLIMB]
    m = _conv_low(t_lo, jnp.asarray(_NPRIME_LIMBS))  # mod R: low half only
    m = _carry_normalize(m, NLIMB)
    mp = _carry_normalize(
        _conv_full(m, jnp.asarray(_P_LIMBS)), 2 * NLIMB
    )
    # t + m·p < 2Rp < 2^767: fits 96 limbs; low 48 limbs are zero by
    # construction of m, so /R is a limb shift.
    s = _carry_normalize(t + mp, 2 * NLIMB, shrink_passes=2)
    u = s[..., NLIMB:]
    return _cond_sub_p(u)


def to_mont(x_limbs):
    return mont_mul(x_limbs, jnp.asarray(_R2_LIMBS))


def from_mont(x_limbs):
    one = jnp.zeros_like(x_limbs).at[..., 0].set(1)
    return mont_mul(x_limbs, one)


def mod_add(a, b):
    v = _carry_normalize(a + b, NLIMB, shrink_passes=2)  # < 2p < 2^384
    return _cond_sub_p(v)


def mod_sub(a, b):
    """a - b mod p via complement: a + (2^384 - b) + p - 2^384; the 2^384
    bit of the normalized sum is always set (a-b+p ≥ 0), drop it."""
    comp_b = MASK - b  # 2^384 - b = ~b + 1 (limbwise complement, +1 below)
    v = a + comp_b + jnp.asarray(_P_LIMBS)
    v = v.at[..., 0].add(1)
    v = _carry_normalize(v, NLIMB + 1, shrink_passes=2)
    # v = (a - b + p) + 2^384, and a-b+p < 2p < 2^384 ⇒ limb 48 == 1
    return _cond_sub_p(v[..., :NLIMB])


# ---------------------------------------------------------------------------
# Field-ops records (device analog of crypto/bls12_381/curve.py FieldOps)
# ---------------------------------------------------------------------------


class DevFq:
    """Fq ops over [..., 48] limb arrays (values in Montgomery form)."""

    @staticmethod
    def add(a, b):
        return mod_add(a, b)

    @staticmethod
    def sub(a, b):
        return mod_sub(a, b)

    @staticmethod
    def mul(a, b):
        return mont_mul(a, b)

    @staticmethod
    def sqr(a):
        return mont_mul(a, a)

    @staticmethod
    def neg(a):
        zero = jnp.zeros_like(a)
        return mod_sub(zero, a)

    @staticmethod
    def zeros(shape):
        return jnp.zeros((*shape, NLIMB), dtype=jnp.int32)

    @staticmethod
    def is_zero(a):
        return jnp.all(a == 0, axis=-1)

    @staticmethod
    def select(c, a, b):
        """c: [...] bool — where(c, a, b) broadcast over limbs."""
        return jnp.where(c[..., None], a, b)


class DevFq2:
    """Fq2 ops over [..., 2, 48] limb arrays (c0 + c1·u, u² = -1)."""

    @staticmethod
    def add(a, b):
        return jnp.stack(
            [mod_add(a[..., 0, :], b[..., 0, :]), mod_add(a[..., 1, :], b[..., 1, :])],
            axis=-2,
        )

    @staticmethod
    def sub(a, b):
        return jnp.stack(
            [mod_sub(a[..., 0, :], b[..., 0, :]), mod_sub(a[..., 1, :], b[..., 1, :])],
            axis=-2,
        )

    @staticmethod
    def mul(a, b):
        a0, a1 = a[..., 0, :], a[..., 1, :]
        b0, b1 = b[..., 0, :], b[..., 1, :]
        t0 = mont_mul(a0, b0)
        t1 = mont_mul(a1, b1)
        c0 = mod_sub(t0, t1)
        cross = mont_mul(mod_add(a0, a1), mod_add(b0, b1))
        c1 = mod_sub(mod_sub(cross, t0), t1)
        return jnp.stack([c0, c1], axis=-2)

    @staticmethod
    def sqr(a):
        return DevFq2.mul(a, a)

    @staticmethod
    def neg(a):
        zero = jnp.zeros_like(a)
        return DevFq2.sub(zero, a)

    @staticmethod
    def zeros(shape):
        return jnp.zeros((*shape, 2, NLIMB), dtype=jnp.int32)

    @staticmethod
    def is_zero(a):
        return jnp.all(a == 0, axis=(-1, -2))

    @staticmethod
    def select(c, a, b):
        return jnp.where(c[..., None, None], a, b)


# ---------------------------------------------------------------------------
# Generic Jacobian point ops (branchless; infinity encoded as Z == 0)
# ---------------------------------------------------------------------------


def pt_double(F, pt):
    x, y, z = pt
    a = F.sqr(x)
    b = F.sqr(y)
    c = F.sqr(b)
    d = F.sub(F.sub(F.sqr(F.add(x, b)), a), c)
    d = F.add(d, d)
    e = F.add(F.add(a, a), a)
    f = F.sqr(e)
    x3 = F.sub(f, F.add(d, d))
    c8 = F.add(F.add(c, c), F.add(c, c))
    c8 = F.add(c8, c8)
    y3 = F.sub(F.mul(e, F.sub(d, x3)), c8)
    z3 = F.mul(F.add(y, y), z)
    return (x3, y3, z3)


def pt_add(F, p1, p2):
    """Branchless Jacobian add handling infinity and doubling cases via
    selects (device code cannot branch per lane)."""
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    inf1 = F.is_zero(z1)
    inf2 = F.is_zero(z2)
    z1z1 = F.sqr(z1)
    z2z2 = F.sqr(z2)
    u1 = F.mul(x1, z2z2)
    u2 = F.mul(x2, z1z1)
    s1 = F.mul(y1, F.mul(z2z2, z2))
    s2 = F.mul(y2, F.mul(z1z1, z1))
    h = F.sub(u2, u1)
    r = F.sub(s2, s1)
    same_x = F.is_zero(h)
    same_y = F.is_zero(r)
    is_double = same_x & same_y & ~inf1 & ~inf2
    is_inf_result = same_x & ~same_y & ~inf1 & ~inf2

    i = F.sqr(F.add(h, h))
    j = F.mul(h, i)
    r2 = F.add(r, r)
    v = F.mul(u1, i)
    x3 = F.sub(F.sub(F.sqr(r2), j), F.add(v, v))
    s1j = F.mul(s1, j)
    y3 = F.sub(F.mul(r2, F.sub(v, x3)), F.add(s1j, s1j))
    z3 = F.mul(F.mul(z1, z2), h)
    z3 = F.add(z3, z3)

    dx, dy, dz = pt_double(F, p1)

    x3 = F.select(is_double, dx, x3)
    y3 = F.select(is_double, dy, y3)
    z3 = F.select(is_double, dz, z3)

    zero = F.zeros(z3.shape[: z3.ndim - (1 if F is DevFq else 2)])
    z3 = F.select(is_inf_result, zero, z3)

    # infinity inputs pass the other operand through
    x3 = F.select(inf1, x2, x3)
    y3 = F.select(inf1, y2, y3)
    z3 = F.select(inf1, z2, z3)
    x3 = F.select(inf2 & ~inf1, x1, x3)
    y3 = F.select(inf2 & ~inf1, y1, y3)
    z3 = F.select(inf2 & ~inf1, z1, z3)
    return (x3, y3, z3)


def pt_scalar_mul(F, pt, scalar_bits):
    """Batch double-and-add: scalar_bits [batch, nbits] int32 (LSB first),
    pt = tuple of [batch, ...] coords. Fixed trip count, branchless."""
    nbits = scalar_bits.shape[-1]

    def body(i, carry):
        acc, addend = carry
        bit = scalar_bits[..., i]
        added = pt_add(F, acc, addend)
        acc = tuple(
            F.select(bit.astype(bool), a_new, a_old)
            for a_new, a_old in zip(added, acc)
        )
        addend = pt_double(F, addend)
        return (acc, addend)

    batch_shape = scalar_bits.shape[:-1]
    zero = F.zeros(batch_shape)
    one_mont = jnp.broadcast_to(
        jnp.asarray(_ONE_MONT), (*batch_shape, NLIMB)
    ).astype(jnp.int32)
    if F is DevFq2:
        one = jnp.stack([one_mont, jnp.zeros_like(one_mont)], axis=-2)
    else:
        one = one_mont
    inf = (one, one, zero)
    acc, _ = lax.fori_loop(0, nbits, body, (inf, pt))
    return acc


# ---------------------------------------------------------------------------
# Host <-> device conversion
# ---------------------------------------------------------------------------


def fq_to_device(values: list[int]) -> np.ndarray:
    """List of field ints → [batch, 48] Montgomery limb array."""
    return np.stack(
        [int_to_limbs(v * R_MONT % P) for v in values]
    ).astype(np.int32)


def fq_from_device(arr) -> list[int]:
    out = []
    host = np.asarray(arr)
    for row in host.reshape(-1, NLIMB):
        out.append(limbs_to_int(row) * pow(R_MONT, -1, P) % P)
    return out


def g1_points_to_device(points) -> tuple:
    """Host Jacobian G1 points (int tuples) → device limb arrays [n,48]×3."""
    xs, ys, zs = [], [], []
    for (x, y, z) in points:
        xs.append(x)
        ys.append(y)
        zs.append(z)
    return (
        jnp.asarray(fq_to_device(xs)),
        jnp.asarray(fq_to_device(ys)),
        jnp.asarray(fq_to_device(zs)),
    )


def g1_points_from_device(pt) -> list:
    xs = fq_from_device(pt[0])
    ys = fq_from_device(pt[1])
    zs = fq_from_device(pt[2])
    return list(zip(xs, ys, zs))


def g2_points_to_device(points) -> tuple:
    coords = [[], [], []]
    for p in points:
        for k in range(3):
            coords[k].append(p[k])
    out = []
    for lane in coords:
        c0 = fq_to_device([c[0] for c in lane])
        c1 = fq_to_device([c[1] for c in lane])
        out.append(jnp.asarray(np.stack([c0, c1], axis=1)))
    return tuple(out)


def g2_points_from_device(pt) -> list:
    out = []
    host = [np.asarray(c) for c in pt]
    n = host[0].shape[0]
    rinv = pow(R_MONT, -1, P)
    for i in range(n):
        coords = []
        for k in range(3):
            c0 = limbs_to_int(host[k][i, 0]) * rinv % P
            c1 = limbs_to_int(host[k][i, 1]) * rinv % P
            coords.append((c0, c1))
        out.append(tuple(coords))
    return out


def scalars_to_bits(scalars: list[int], nbits: int) -> np.ndarray:
    out = np.zeros((len(scalars), nbits), dtype=np.int32)
    for i, s in enumerate(scalars):
        for b in range(nbits):
            out[i, b] = (s >> b) & 1
    return out


# ---------------------------------------------------------------------------
# Jitted batch kernels
# ---------------------------------------------------------------------------


@jax.jit
def batch_g1_scalar_mul(xs, ys, zs, bits):
    """[n] G1 points × [n, nbits] scalars → [n] G1 points (Jacobian)."""
    return pt_scalar_mul(DevFq, (xs, ys, zs), bits)


@jax.jit
def batch_g2_scalar_mul(xs, ys, zs, bits):
    return pt_scalar_mul(DevFq2, (xs, ys, zs), bits)


@jax.jit
def g1_sum_reduce(xs, ys, zs):
    """Tree-reduce a batch of G1 points to a single sum (log2 n adds)."""
    pt = (xs, ys, zs)
    n = xs.shape[0]
    while n > 1:
        half = n // 2
        lo = tuple(c[:half] for c in pt)
        hi = tuple(c[half : half * 2] for c in pt)
        merged = pt_add(DevFq, lo, hi)
        if n % 2:
            pt = tuple(
                jnp.concatenate([m, c[-1:]], axis=0)
                for m, c in zip(merged, pt)
            )
            n = half + 1
        else:
            pt = merged
            n = half
    return pt


# ---------------------------------------------------------------------------
# Device-backed verify_signature_sets (the `tpu` backend's batch path)
# ---------------------------------------------------------------------------


def verify_signature_sets_device(sets, rng=None) -> bool:
    """RLC batch verification with the G1/G2 scalar multiplications on
    device; subgroup checks and the final multi-pairing remain host-side
    until the pairing kernel lands. Falls back to plain host verification
    for tiny batches (dispatch overhead dominates)."""
    import secrets as _secrets

    from ..crypto import bls
    from ..crypto.bls12_381 import (
        FQ,
        FQ2,
        G1_GEN,
        g2_in_subgroup,
        hash_to_g2,
        inf,
        is_inf,
        pairing_check,
        pt_add as host_pt_add,
        pt_neg,
    )
    from ..crypto.bls12_381.fields import R as CURVE_R

    sets = list(sets)
    if len(sets) < 8:
        return bls._BACKENDS["host"].verify_signature_sets(sets, rng)

    rand = rng if rng is not None else _secrets.SystemRandom()
    sig_points = []
    agg_pks = []
    scalars = []
    messages = []
    for s in sets:
        try:
            if s.signature.is_infinity():
                return False
            sig_pt = s.signature.point()
            if not g2_in_subgroup(sig_pt):
                return False
            pk_pts = [pk.point() for pk in s.pubkeys]
        except (bls.BlsError, ValueError):
            return False
        if not pk_pts:
            return False
        agg = inf(FQ)
        for p in pk_pts:
            agg = host_pt_add(FQ, agg, p)
        r = 0
        while r == 0:
            r = rand.getrandbits(bls.RAND_BITS)
        sig_points.append(sig_pt)
        agg_pks.append(agg)
        scalars.append(r)
        messages.append(s.message)

    n = len(sets)
    # Pad to a power-of-two bucket so jit caches few shapes (the reference
    # batches gossip work in fixed chunks of 64 for the same reason,
    # beacon_processor/src/lib.rs:200). Padding scalar 0 → infinity result,
    # sliced off below.
    bucket = 8
    while bucket < n:
        bucket *= 2
    pad = bucket - n
    scalars_p = scalars + [0] * pad
    pts_pad_g1 = agg_pks + [agg_pks[0]] * pad
    pts_pad_g2 = sig_points + [sig_points[0]] * pad

    bits = jnp.asarray(scalars_to_bits(scalars_p, bls.RAND_BITS))
    # G1: scale each aggregated pubkey by its scalar on device
    g1x, g1y, g1z = g1_points_to_device(pts_pad_g1)
    scaled_g1 = batch_g1_scalar_mul(g1x, g1y, g1z, bits)
    scaled_pks = g1_points_from_device(scaled_g1)[:n]
    # G2: scale each signature, reduce to the aggregate on device
    g2x, g2y, g2z = g2_points_to_device(pts_pad_g2)
    scaled_g2 = batch_g2_scalar_mul(g2x, g2y, g2z, bits)
    scaled_sigs = g2_points_from_device(scaled_g2)[:n]

    agg_sig = inf(FQ2)
    for sp in scaled_sigs:
        agg_sig = host_pt_add(FQ2, agg_sig, sp)

    by_message: dict[bytes, object] = {}
    for msg, spk in zip(messages, scaled_pks):
        prev = by_message.get(msg)
        by_message[msg] = spk if prev is None else host_pt_add(FQ, prev, spk)

    pairs = [(pt_neg(FQ, G1_GEN), agg_sig)]
    for msg, pk_pt in by_message.items():
        pairs.append((pk_pt, hash_to_g2(msg)))
    return pairing_check(pairs)
