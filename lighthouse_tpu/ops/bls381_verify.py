"""Fully-device BLS batch signature verification.

TPU analog of blst's `verify_multiple_aggregate_signatures`
(crypto/bls/src/impls/blst.rs:35-117) — the random-linear-combination batch
check

    e(-G1, Σ rᵢ·sigᵢ) · Π_m e(Σ_{i: msgᵢ=m} rᵢ·aggpkᵢ, H(m)) == 1

with EVERY group/field operation on device:

  1. per-set pubkey aggregation   — padded tree-reduction over the
                                     committee axis (G1, Fq lanes)
  2. G2 subgroup checks on sigs   — ψ-endomorphism ladder (bls381_pairing)
  3. rᵢ scalar multiplications    — batched double-and-add ladders (bls381)
  4. signature sum Σ rᵢ·sigᵢ     — G2 tree-reduction
  5. H(m) hash-to-curve           — device SSWU (bls381_htc; host does only
                                     the SHA-256 expand_message_xmd)
  6. Jacobian→affine              — batched Fermat inversions
  7. Miller loops + final exp     — one multi-pairing (bls381_pairing)

The host's remaining jobs: point decompression (bytes → ints, cached on the
PublicKey/Signature wrappers), RLC scalar sampling, and batch-shape
bucketing (powers of two, so jit caches a handful of shapes — the reference
batches gossip work in fixed chunks of 64 for the same reason,
beacon_processor/src/lib.rs:200).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..crypto.bls12_381.fields import P
from .bls381 import (
    NLIMB,
    DevFq,
    DevFq2,
    fq_to_device,
    g1_points_to_device,
    batch_g1_scalar_mul,
    batch_g2_scalar_mul,
    mont_mul,
    pt_add,
    scalars_to_bits,
)
from .bls381_htc import (
    f2_inv_staged,
    fq_inv_staged,
    hash_to_g2_device,
    messages_to_field_device,
)
from .bls381_pairing import (
    g1_affine_to_device,
    g2_affine_to_device,
    g2_subgroup_check_device,
    multi_pairing_check_device,
)

# ---------------------------------------------------------------------------
# Generic reductions / conversions
# ---------------------------------------------------------------------------


def _tree_reduce_scan(F, pt):
    """Tree-sum points along axis 1 (coords [n, k, ...], k a power of two,
    infinity pads) → [n, ...].

    Shape-stable formulation: one lax.scan whose body does a single
    [n, k/2]-lane batched pt_add of even/odd columns and re-pads with
    infinity — the buffer shape never changes, so the whole reduction is
    ONE compiled scan (unrolling the tree into straight-line adds, or one
    jit per level, both made XLA-CPU compiles explode)."""
    k = pt[0].shape[1]
    assert k & (k - 1) == 0, "tree reduce needs a power-of-two lane count"
    if k == 1:
        return tuple(c[:, 0] for c in pt)
    depth = (k - 1).bit_length()

    def body(buf, _):
        lo = tuple(c[:, 0::2] for c in buf)
        hi = tuple(c[:, 1::2] for c in buf)
        merged = pt_add(F, lo, hi)  # [n, k/2, ...]
        # re-pad to [n, k]: infinity (z=0) lanes are absorbed by pt_add
        buf = tuple(
            jnp.concatenate([m, jnp.zeros_like(m)], axis=1) for m in merged
        )
        return buf, None

    buf, _ = lax.scan(body, pt, None, length=depth)
    return tuple(c[:, 0] for c in buf)


_jit_tree_reduce_g1 = jax.jit(
    lambda xs, ys, zs: _tree_reduce_scan(DevFq, (xs, ys, zs))
)
_jit_tree_reduce_g2 = jax.jit(
    lambda xs, ys, zs: _tree_reduce_scan(DevFq2, (xs, ys, zs))
)


def g1_segment_sum(xs, ys, zs):
    """[n, k] padded G1 points (infinity pads) → [n] sums."""
    return _jit_tree_reduce_g1(xs, ys, zs)


def g2_sum_reduce(xs, ys, zs):
    """Tree-reduce a batch of G2 points to a single sum ([n] → [1])."""
    return _jit_tree_reduce_g2(xs[None], ys[None], zs[None])


@jax.jit
def _jit_g1_affine_from_inv(x, y, z, zinv):
    zinv2 = mont_mul(zinv, zinv)
    ax = mont_mul(x, zinv2)
    ay = mont_mul(y, mont_mul(zinv2, zinv))
    inf = jnp.all(z == 0, axis=-1)
    return ax, ay, inf


def g1_jac_to_affine(x, y, z):
    """Batched Jacobian→affine over Fq: returns (ax, ay, inf_mask)."""
    return _jit_g1_affine_from_inv(x, y, z, fq_inv_staged(z))


@jax.jit
def _jit_g2_affine_from_inv(x, y, z, zinv):
    from .bls381_tower import f2_mul, f2_sqr

    zinv2 = f2_sqr(zinv)
    ax = f2_mul(x, zinv2)
    ay = f2_mul(y, f2_mul(zinv2, zinv))
    inf = jnp.all(z == 0, axis=(-1, -2))
    return ax, ay, inf


def g2_jac_to_affine(x, y, z):
    """Batched Jacobian→affine over Fq2 (coords [..., 2, 48])."""
    return _jit_g2_affine_from_inv(x, y, z, f2_inv_staged(z))


# ---------------------------------------------------------------------------
# Host-side staging
# ---------------------------------------------------------------------------


def _bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def _affine_int(pt):
    """Host Jacobian int point → affine (x, y) or None; z==1 fast path (all
    decompressed points arrive affine)."""
    if pt is None:
        return None
    x, y, z = pt
    if isinstance(z, tuple):  # Fq2
        if z == (0, 0):
            return None
        if z == (1, 0):
            return (x, y)
        from ..crypto.bls12_381 import FQ2, to_affine

        return to_affine(FQ2, (x, y, z))
    if z == 0:
        return None
    if z == 1:
        return (x, y)
    from ..crypto.bls12_381 import FQ, to_affine

    return to_affine(FQ, (x, y, z))


_G1_INF_LIMBS = np.zeros(NLIMB, dtype=np.int32)


def _g1_affine_grid_to_device(grids):
    """[n][k] host affine-or-None G1 → Jacobian device arrays [n, k, 48]×3
    (infinity encoded z=0)."""
    from .bls381 import R_MONT, int_to_limbs

    n = len(grids)
    k = len(grids[0])
    xs = np.zeros((n, k, NLIMB), dtype=np.int32)
    ys = np.zeros((n, k, NLIMB), dtype=np.int32)
    zs = np.zeros((n, k, NLIMB), dtype=np.int32)
    one = int_to_limbs(R_MONT % P)
    cache: dict = {}
    for i, row in enumerate(grids):
        for j, aff in enumerate(row):
            if aff is None:
                continue
            key = aff[0]
            ent = cache.get(key)
            if ent is None:
                ent = (
                    int_to_limbs(aff[0] * R_MONT % P),
                    int_to_limbs(aff[1] * R_MONT % P),
                )
                cache[key] = ent
            xs[i, j] = ent[0]
            ys[i, j] = ent[1]
            zs[i, j] = one
    return jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs)


# ---------------------------------------------------------------------------
# The batch verifier
# ---------------------------------------------------------------------------


def verify_signature_sets_device_full(sets, rng=None) -> bool:
    """Full-device RLC batch verification. Each set: (signature, pubkeys[],
    message). Returns True iff every signature is valid (w.h.p.)."""
    import secrets as _secrets

    from ..crypto import bls
    from ..metrics import inc_counter

    sets = list(sets)
    if not sets:
        return False
    inc_counter("bls_device_batches_total")
    inc_counter("bls_device_sets_total", len(sets))
    rand = rng if rng is not None else _secrets.SystemRandom()

    sig_affs = []
    pk_rows = []
    scalars = []
    messages = []
    for s in sets:
        try:
            if s.signature.is_infinity():
                return False
            sig_aff = _affine_int(s.signature.point())
            pk_affs = [_affine_int(pk.point()) for pk in s.pubkeys]
        except (bls.BlsError, ValueError):
            return False
        if sig_aff is None or not pk_affs:
            return False
        r = 0
        while r == 0:
            r = rand.getrandbits(bls.RAND_BITS)
        sig_affs.append(sig_aff)
        pk_rows.append(pk_affs)
        scalars.append(r)
        messages.append(s.message)

    n = len(sets)
    nb = _bucket(n)

    # --- G2 subgroup checks on all signatures (device) ---
    sig_pad = sig_affs + [None] * (nb - n)
    qx, qy, q_inf = g2_affine_to_device(sig_pad)
    in_sub = np.asarray(g2_subgroup_check_device(qx, qy, q_inf))
    if not bool(in_sub.all()):
        return False

    # --- per-set pubkey aggregation (device, padded committee axis) ---
    kmax = _bucket(max(len(r) for r in pk_rows), floor=1)
    grid = [row + [None] * (kmax - len(row)) for row in pk_rows]
    grid += [[None] * kmax] * (nb - n)
    gx, gy, gz = _g1_affine_grid_to_device(grid)
    agg_x, agg_y, agg_z = g1_segment_sum(gx, gy, gz)

    # --- RLC scalar multiplications (device ladders) ---
    bits = jnp.asarray(scalars_to_bits(scalars + [0] * (nb - n), bls.RAND_BITS))
    s_pk = batch_g1_scalar_mul(agg_x, agg_y, agg_z, bits)
    one2 = jnp.broadcast_to(
        jnp.stack(
            [jnp.asarray(fq_to_device([1])[0]), jnp.zeros(NLIMB, jnp.int32)]
        ),
        (nb, 2, NLIMB),
    ).astype(jnp.int32)
    z_pad = jnp.where(q_inf[:, None, None], jnp.zeros_like(one2), one2)
    s_sig = batch_g2_scalar_mul(qx, qy, z_pad, bits)

    # --- signature aggregate Σ rᵢ·sigᵢ (device tree-reduce) ---
    agg_sig = g2_sum_reduce(*s_sig)

    # --- per-message aggregation of scaled pubkeys (device gather+reduce) ---
    groups: dict[bytes, list[int]] = {}
    for i, m in enumerate(messages):
        groups.setdefault(m, []).append(i)
    msgs = list(groups)
    m_count = len(msgs)
    mb = _bucket(m_count, floor=1)
    gmax = _bucket(max(len(v) for v in groups.values()), floor=1)
    if nb > n:
        # lane nb-1 is a padded set (scalar 0 ladder → infinity): reuse it
        # as the gather pad slot.
        pad_slot = nb - 1
    else:
        # exact-power batch: append an explicit infinity lane.
        s_pk = tuple(
            jnp.concatenate([c, jnp.zeros_like(c[-1:])], axis=0) for c in s_pk
        )
        pad_slot = nb
    idx = np.full((mb, gmax), pad_slot, dtype=np.int32)
    for gi, m in enumerate(msgs):
        for jj, si in enumerate(groups[m]):
            idx[gi, jj] = si
    gx2 = tuple(jnp.take(c, jnp.asarray(idx), axis=0) for c in s_pk)
    msg_pk = g1_segment_sum(*gx2)

    # --- H(m): device SSWU hash-to-curve ---
    u = messages_to_field_device(msgs + [b"\x00" * 32] * (mb - m_count))
    hm = hash_to_g2_device(jnp.asarray(u))

    # --- assemble the multi-pairing: (-G1, agg_sig) + (msg_pk_i, H(m_i)) ---
    from ..crypto.bls12_381 import FQ, G1_GEN
    from ..crypto.bls12_381.curve import pt_neg, to_affine

    neg_g1 = to_affine(FQ, pt_neg(FQ, G1_GEN))
    ngx, ngy, ng_inf = g1_affine_to_device([neg_g1])

    pk_ax, pk_ay, pk_inf = g1_jac_to_affine(*msg_pk)
    # mask out padded message lanes
    lane_pad = np.arange(mb) >= m_count
    pk_inf = pk_inf | jnp.asarray(lane_pad)
    hm_ax, hm_ay, hm_inf = g2_jac_to_affine(*hm)
    sig_ax, sig_ay, sig_inf = g2_jac_to_affine(*agg_sig)

    xp = jnp.concatenate([ngx, pk_ax], axis=0)
    yp = jnp.concatenate([ngy, pk_ay], axis=0)
    p_inf = jnp.concatenate([ng_inf, pk_inf], axis=0)
    qx2 = jnp.concatenate([sig_ax, hm_ax], axis=0)
    qy2 = jnp.concatenate([sig_ay, hm_ay], axis=0)
    q_inf2 = jnp.concatenate([sig_inf, hm_inf], axis=0)
    return bool(multi_pairing_check_device(xp, yp, p_inf, qx2, qy2, q_inf2))
