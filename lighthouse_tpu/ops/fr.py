"""Device arithmetic over the BLS12-381 scalar field Fr (r ≈ 2^255).

The KZG evaluation-form hot loops are Fr-heavy: the barycentric formula
evaluates p(z) as a 4096-term Σ pᵢ·wᵢ/(z−wᵢ) (crypto/kzg/src/lib.rs wraps
c-kzg, which does this in C; polynomial-commitments.md `evaluate_polynomial_
in_evaluation_form`). Per-term modular inversions make this the dominant
cost of blob verification on the host (4096 Fermat pows per blob), and it
is embarrassingly parallel — exactly the shape the TPU VPU wants.

Representation mirrors ops/bls381.py: 32 little-endian 8-bit limbs in
int32 (256 bits ≥ 255-bit r), Montgomery form with R = 2^256. The generic
convolution/carry helpers are shared with the Fq implementation; only the
modulus constants differ.

Kernels:
  * fr_mul / fr_add / fr_sub           — [..., 32] lanewise field ops
  * fr_inv                             — Fermat a^(r−2), vectorized fori
  * barycentric_eval_batch             — y_j = p_j(z_j) for a batch of
    blobs over the shared bit-reversed domain: one fused kernel
  * quotient_batch                     — qᵢ = (pᵢ−y)·(wᵢ−z)⁻¹ for device
    proof computation (compute_kzg_proof pointwise quotient)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..crypto.bls12_381.fields import R as FR_MOD
from .bls381 import (
    BASE,
    MASK,
    _carry_normalize,
    _conv_full,
    _conv_low,
)

NLIMB_FR = 32  # 32 × 8-bit limbs = 256 bits
R_MONT_FR = 1 << 256
R2_FR = (R_MONT_FR * R_MONT_FR) % FR_MOD
NPRIME_FR = (-pow(FR_MOD, -1, R_MONT_FR)) % R_MONT_FR


def _int_to_limbs(x: int, n: int = NLIMB_FR) -> np.ndarray:
    return np.array([(x >> (BASE * i)) & MASK for i in range(n)], dtype=np.int32)


def _limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(v) << (BASE * i) for i, v in enumerate(arr))


_R_LIMBS = _int_to_limbs(FR_MOD)
_NPRIME_LIMBS = _int_to_limbs(NPRIME_FR)
_R2_LIMBS = _int_to_limbs(R2_FR)
_ONE_MONT = _int_to_limbs(R_MONT_FR % FR_MOD)
# 2^256 - r, for the branchless conditional subtract
_RBAR_LIMBS = _int_to_limbs(R_MONT_FR - FR_MOD)
# Fermat exponent r-2, LSB-first bits (static constant; 255 bits)
_INV_EXP_BITS = np.array(
    [((FR_MOD - 2) >> i) & 1 for i in range((FR_MOD - 2).bit_length())],
    dtype=np.int32,
)


def _cond_sub_r(x):
    """x normalized in [0, 2r) → x mod r (same trick as bls381._cond_sub_p)."""
    s = x + jnp.asarray(_RBAR_LIMBS)
    s = _carry_normalize(s, NLIMB_FR + 1, shrink_passes=2)
    ge = s[..., NLIMB_FR] > 0
    return jnp.where(ge[..., None], s[..., :NLIMB_FR], x)


def fr_mul(a, b):
    """Montgomery product a·b·R⁻¹ mod r over [..., 32] int32 limbs."""
    t = _conv_full(a, b)
    t = _carry_normalize(t, 2 * NLIMB_FR)
    m = _conv_low(t[..., :NLIMB_FR], jnp.asarray(_NPRIME_LIMBS))
    m = _carry_normalize(m, NLIMB_FR)
    mp = _carry_normalize(_conv_full(m, jnp.asarray(_R_LIMBS)), 2 * NLIMB_FR)
    s = _carry_normalize(t + mp, 2 * NLIMB_FR, shrink_passes=2)
    return _cond_sub_r(s[..., NLIMB_FR:])


def fr_add(a, b):
    v = _carry_normalize(a + b, NLIMB_FR, shrink_passes=2)
    return _cond_sub_r(v)


def fr_sub(a, b):
    comp_b = MASK - b
    v = a + comp_b + jnp.asarray(_R_LIMBS)
    v = v.at[..., 0].add(1)
    v = _carry_normalize(v, NLIMB_FR + 1, shrink_passes=2)
    return _cond_sub_r(v[..., :NLIMB_FR])


def fr_inv(a):
    """Fermat inverse a^(r−2), vectorized over leading axes. a must be
    nonzero mod r (inverse of 0 returns 0 — harmless: callers mask)."""
    bits = jnp.asarray(_INV_EXP_BITS)
    one = jnp.broadcast_to(jnp.asarray(_ONE_MONT), a.shape).astype(jnp.int32)

    def body(i, acc):
        # LSB-first square-and-multiply: acc *= base when bit set
        base, out = acc
        out = jnp.where((bits[i] > 0)[..., None], fr_mul(out, base), out)
        base = fr_mul(base, base)
        return (base, out)

    _, out = lax.fori_loop(0, _INV_EXP_BITS.shape[0], body, (a, one))
    return out


def _tree_sum(v):
    """Log-depth Σ over axis -2 of [..., n, 32] (n a power of two)."""
    n = v.shape[-2]
    while n > 1:
        half = n // 2
        v = fr_add(v[..., :half, :], v[..., half : 2 * half, :])
        n = half
    return v[..., 0, :]


# ---------------------------------------------------------------------------
# Host <-> device
# ---------------------------------------------------------------------------


def fr_to_device(values) -> np.ndarray:
    """Iterable of ints mod r → [n, 32] Montgomery limb array."""
    return np.stack(
        [_int_to_limbs(v % FR_MOD * R_MONT_FR % FR_MOD) for v in values]
    ).astype(np.int32)


def fr_from_device(arr) -> list[int]:
    rinv = pow(R_MONT_FR, -1, FR_MOD)
    host = np.asarray(arr)
    return [
        _limbs_to_int(row) * rinv % FR_MOD
        for row in host.reshape(-1, NLIMB_FR)
    ]


# ---------------------------------------------------------------------------
# KZG kernels
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("log_n",))
def barycentric_eval_batch(evals, roots, zs, log_n: int):
    """p_j(z_j) for a batch of evaluation-form polynomials.

    evals: [m, n, 32]  blob field elements (Montgomery)
    roots: [n, 32]     bit-reversed domain (shared across the batch)
    zs:    [m, 32]     evaluation points (must not hit a domain point —
                       the host pre-checks and short-circuits those)
    Returns ys: [m, 32] in Montgomery form.

    y = (z^n − 1)·n⁻¹ · Σᵢ pᵢ·wᵢ·(z − wᵢ)⁻¹
    """
    n = 1 << log_n
    m = evals.shape[0]
    z_b = jnp.broadcast_to(zs[:, None, :], (m, n, NLIMB_FR))
    roots_b = jnp.broadcast_to(roots[None, :, :], (m, n, NLIMB_FR))
    d = fr_sub(z_b, roots_b)
    dinv = fr_inv(d)
    terms = fr_mul(fr_mul(evals, roots_b), dinv)
    s = _tree_sum(terms)  # [m, 32]
    # z^n by log_n squarings
    zn = zs
    for _ in range(log_n):
        zn = fr_mul(zn, zn)
    one = jnp.broadcast_to(jnp.asarray(_ONE_MONT), zn.shape).astype(jnp.int32)
    num = fr_sub(zn, one)
    n_inv = jnp.asarray(
        fr_to_device([pow(n, FR_MOD - 2, FR_MOD)])[0]
    )
    n_inv = jnp.broadcast_to(n_inv, zn.shape)
    return fr_mul(fr_mul(s, num), n_inv)


@jax.jit
def quotient_batch(evals, roots, z, y):
    """Pointwise opening quotient qᵢ = (pᵢ − y)·(wᵢ − z)⁻¹ over the domain.

    evals/roots: [n, 32]; z/y: [32]. Lanes where wᵢ == z produce 0 (the
    host fills the special-case lane). Returns [n, 32] Montgomery.
    """
    n = evals.shape[0]
    z_b = jnp.broadcast_to(z[None, :], (n, NLIMB_FR))
    y_b = jnp.broadcast_to(y[None, :], (n, NLIMB_FR))
    d = fr_sub(roots, z_b)
    return fr_mul(fr_sub(evals, y_b), fr_inv(d))
