"""Batched SHA-256 on device (JAX), specialised for SSZ Merkleization.

Merkleization is two-to-one hashing of 32-byte nodes: each parent =
SHA-256(left || right) over exactly 64 bytes of input. A 64-byte message is two
compression-function applications (the second block is the constant padding
block), so one tree level over N nodes = 2N batched compressions with zero
data-dependent control flow — ideal for the TPU VPU.

The compression rounds run in a `lax.fori_loop` (compact HLO; the batch
dimension provides all the parallelism), with a 16-word circular message
schedule held in registers. Big tree levels hash on device; the small top of
the tree finishes on host where dispatch overhead would dominate.

Reference equivalents: `ethereum_hashing` (SHA-256 w/ CPU SIMD dispatch) and
the level-by-level re-hash loop of consensus/cached_tree_hash/src/cache.rs:98-147.

All arrays are uint32 big-endian words: a 32-byte node is a row of 8 words.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# fmt: off
_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208, 0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

_IV = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)
# fmt: on

# Padding block for a message of exactly 64 bytes: 0x80, zeros, bit-length 512.
_PAD64 = np.zeros(16, dtype=np.uint32)
_PAD64[0] = 0x80000000
_PAD64[15] = 512

# Tree levels with at most this many parent nodes finish on host.
_HOST_TOP = 1 << 8


def _rotr(x, n):
    return (x >> n) | (x << (32 - n))


def _compress(state, block):
    """One SHA-256 compression. state: [N, 8] u32, block: [N, 16] u32.

    Message schedule kept as a [N, 16] circular buffer indexed mod 16; both the
    schedule recurrence and the round update run inside one fori_loop so the
    compiled program stays small (XLA vectorizes over N).
    """
    k = jnp.asarray(_K)

    def round_fn(t, carry):
        a, b, c, d, e, f, g, h, w = carry
        i = t & 15
        wt = lax.cond(
            t < 16,
            lambda: lax.dynamic_index_in_dim(w, i, axis=1, keepdims=False),
            lambda: (
                lax.dynamic_index_in_dim(w, i, axis=1, keepdims=False)
                + _ssig0(lax.dynamic_index_in_dim(w, (t + 1) & 15, axis=1, keepdims=False))
                + lax.dynamic_index_in_dim(w, (t + 9) & 15, axis=1, keepdims=False)
                + _ssig1(lax.dynamic_index_in_dim(w, (t + 14) & 15, axis=1, keepdims=False))
            ),
        )
        w = lax.cond(
            t < 16,
            lambda: w,
            lambda: lax.dynamic_update_index_in_dim(w, wt, i, axis=1),
        )
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + k[t] + wt
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = S0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g, w)

    init = tuple(state[:, i] for i in range(8)) + (block,)
    a, b, c, d, e, f, g, h, _ = lax.fori_loop(0, 64, round_fn, init)
    return jnp.stack([a, b, c, d, e, f, g, h], axis=-1) + state


def _ssig0(x):
    return _rotr(x, 7) ^ _rotr(x, 18) ^ (x >> 3)


def _ssig1(x):
    return _rotr(x, 17) ^ _rotr(x, 19) ^ (x >> 10)


@jax.jit
def sha256_pairs(blocks):
    """Hash N 64-byte messages: blocks [N, 16] u32 -> digests [N, 8] u32."""
    n = blocks.shape[0]
    iv = jnp.broadcast_to(jnp.asarray(_IV), (n, 8))
    st = _compress(iv, blocks)
    pad = jnp.broadcast_to(jnp.asarray(_PAD64), (n, 16))
    return _compress(st, pad)


def merkle_tree_levels(leaves):
    """All levels of the Merkle tree over a power-of-two number of leaf nodes.

    leaves: [N, 8] u32 (device or numpy), N a power of two. Returns list of
    arrays, index 0 = root level [1, 8], last = leaves. Big levels hash on
    device (one batched kernel call each, arrays stay on device); the small
    top of the tree finishes on host.
    """
    levels = [jnp.asarray(leaves)]
    nodes = levels[0]
    while nodes.shape[0] > max(_HOST_TOP, 1):
        nodes = sha256_pairs(nodes.reshape(-1, 16))
        levels.append(nodes)
    # Finish on host (batched host hasher; ≤ _HOST_TOP rows per level).
    from ..utils.sha256_batch import hash_rows

    host = np.asarray(nodes)
    while host.shape[0] > 1:
        rows = host.astype(">u4").view(np.uint8).reshape(-1, 64)
        host = (
            np.ascontiguousarray(hash_rows(rows))
            .view(">u4")
            .astype(np.uint32)
            .reshape(-1, 8)
        )
        levels.append(host)
    return levels[::-1]


def merkleize_device(leaves):
    """Merkle root of a power-of-two number of leaves. Returns [8] u32."""
    n = leaves.shape[0]
    assert n & (n - 1) == 0, f"leaf count {n} not a power of two"
    return np.asarray(merkle_tree_levels(leaves)[0][0])


def device_hash_rows(pairs: np.ndarray) -> np.ndarray:
    """[n, 64] uint8 → [n, 32] uint8 two-to-one hashing on device.

    Pads the row count to a power of two so each size class compiles once
    (one fused kernel call for the whole batch). This is the `device`
    mode of utils.sha256_batch.hash_rows — opt-in: on hosts without a
    real accelerator the per-shape XLA compile dwarfs the hashing.
    """
    m = pairs.shape[0]
    if m == 0:
        return np.zeros((0, 32), dtype=np.uint8)
    mp = 1 << (m - 1).bit_length()
    words = np.zeros((mp, 16), dtype=np.uint32)
    words[:m] = (
        np.ascontiguousarray(pairs).view(">u4").astype(np.uint32).reshape(m, 16)
    )
    dig = np.asarray(sha256_pairs(words))[:m]
    return dig.astype(">u4").view(np.uint8).reshape(m, 32)


def bytes_to_words(data: bytes) -> np.ndarray:
    """32-byte-node buffer -> [N, 8] u32 big-endian words."""
    assert len(data) % 32 == 0
    return np.frombuffer(data, dtype=">u4").astype(np.uint32).reshape(-1, 8)


def words_to_bytes(words) -> bytes:
    return np.asarray(words, dtype=np.uint32).astype(">u4").tobytes()
