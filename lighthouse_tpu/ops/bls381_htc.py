"""Device (batched, branchless) SSWU hash-to-G2 for BLS12-381.

The TPU analog of the hash-to-curve inside blst's signature verification
(reference: crypto/bls/src/impls/blst.rs:13 fixes the RFC 9380
BLS12381G2_XMD:SHA-256_SSWU_RO_ ciphersuite; every per-message H(m) in
batch verification runs it). Host keeps only expand_message_xmd — a few
SHA-256 calls over <200-byte inputs per message — and the wide-integer
mod-p reduction; the expensive field work (two SSWU maps with Fq2 square
roots, the 3-isogeny, cofactor clearing) runs on device, vmapped over the
message batch.

Design notes:
* Square roots use the complex method (p ≡ 3 mod 4), mirrored branchlessly
  from the host oracle `crypto/bls12_381/fields.py:f2_sqrt`: all four
  Fq-sqrt candidate exponentiations are STACKED into one fixed 379-bit
  square-and-multiply scan (lax.scan over static exponent bits), then
  per-lane selects pick the valid candidate. Non-square inputs yield
  garbage lanes that the SSWU select masks out — exactly one of
  gx1/gx2 is square, so the chosen lane is always exact.
* sgn0 needs canonical (non-Montgomery) parity: one extra mont_mul per
  coordinate converts out of Montgomery form.
* The 3-isogeny constants are taken from the host module (derived there
  via Vélu's formulas, pinned to RFC 9380 §E.3 by tests) and pushed as
  Montgomery limb constants.
* Cofactor clearing reuses `bls381_pairing.g2_clear_cofactor_device`
  (Budroni–Pintore x-ladders + ψ).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..crypto.bls12_381 import hash_to_curve as HH
from ..crypto.bls12_381.fields import P
from .bls381 import NLIMB, DevFq2, int_to_limbs, mont_mul, mod_add, mod_sub, pt_add
from .bls381_pairing import _one_fq2, g2_clear_cofactor_device
from .bls381_tower import (
    f2_add,
    f2_inv,
    f2_is_zero,
    f2_mul,
    f2_neg,
    f2_select,
    f2_sqr,
    f2_sub,
    fq2_const,
    fq_const,
)

# --- constants (Montgomery limb form) --------------------------------------

_A_DEV = fq2_const(HH._A)
_B_DEV = fq2_const(HH._B)
_Z_DEV = fq2_const(HH._Z)
_MBA_DEV = fq2_const(HH._MINUS_B_OVER_A)  # -B/A
_BZA_DEV = fq2_const(HH._B_OVER_ZA)  # B/(Z·A)
_X0_DEV = fq2_const(HH._X0)
_T_DEV = fq2_const(HH._T)
_U_DEV = fq2_const(HH._U)
_INV9_DEV = fq2_const(HH._INV9)
_INV27_DEV = fq2_const(HH._INV27)
_INV2_DEV = fq_const((P + 1) // 2)  # 1/2 mod p
_ONE_F2_DEV = fq2_const((1, 0))

_POW_BITS_WIDTH = 384  # all Fq exponents padded to one width → ONE compiled scan


def _bits_of(e: int, width: int = _POW_BITS_WIDTH) -> np.ndarray:
    return np.array([(e >> i) & 1 for i in range(width)], dtype=np.int32)


_SQRT_BITS = _bits_of((P + 1) // 4)
_PM2_BITS_PAD = _bits_of(P - 2)


def fq_pow_fixed(a, bits_np: np.ndarray):
    """a^e over [..., 48] Montgomery limbs, exponent as an LSB-first bit
    array. The bits ride as a RUNTIME argument into one jitted scan whose
    tiny body (2 mont_muls) compiles in seconds and is shared by every
    exponent of the same width — sqrt chains, Fermat inversions, the lot.
    (Baking each exponent into its own scan made XLA-CPU compile a fresh
    while loop per exponent; the mega-graphs took hours on slow hosts.)"""
    return _fq_pow_var(a, jnp.asarray(bits_np))


@jax.jit
def _fq_pow_var(a, bits):
    from .bls381 import _ONE_MONT

    one = jnp.broadcast_to(jnp.asarray(_ONE_MONT), a.shape).astype(jnp.int32)

    def body(carry, bit):
        acc, base = carry
        acc = jnp.where(bit > 0, mont_mul(acc, base), acc)
        return (acc, mont_mul(base, base)), None

    (acc, _), _ = lax.scan(body, (one, a), bits)
    return acc


def _fq_is_zero(a):
    return jnp.all(a == 0, axis=-1)


@jax.jit
def _jit_sqrt_norm(a):
    x, y = a[..., 0, :], a[..., 1, :]
    return mod_add(mont_mul(x, x), mont_mul(y, y))


@jax.jit
def _jit_sqrt_candidates(a, n):
    """Stack the four Fq-sqrt candidate bases for one shared pow scan."""
    x = a[..., 0, :]
    inv2 = jnp.asarray(_INV2_DEV)
    half_a = mont_mul(mod_add(x, n), inv2)
    half_b = mont_mul(mod_sub(x, n), inv2)
    neg_x = mod_sub(jnp.zeros_like(x), x)
    return jnp.stack([half_a, half_b, x, neg_x], axis=0)


@jax.jit
def _jit_sqrt_pick_t(a, n, roots):
    """Select the valid complex-method candidate; returns (t, 2t)."""
    x = a[..., 0, :]
    inv2 = jnp.asarray(_INV2_DEV)
    half_a = mont_mul(mod_add(x, n), inv2)
    t_a, t_b = roots[0], roots[1]
    ok_a = jnp.all(mont_mul(t_a, t_a) == half_a, axis=-1) & ~_fq_is_zero(t_a)
    t = jnp.where(ok_a[..., None], t_a, t_b)
    return t, mod_add(t, t)


@jax.jit
def _jit_sqrt_finish(a, roots, t, inv_2t):
    """Assemble (root, is_square) from the candidates + 1/(2t)."""
    x, y = a[..., 0, :], a[..., 1, :]
    zero = jnp.zeros_like(x)
    y_is_zero = _fq_is_zero(y)
    s_x, s_nx = roots[2], roots[3]
    neg_x = mod_sub(zero, x)

    y_over = mont_mul(y, inv_2t)
    root_cplx = jnp.stack([t, y_over], axis=-2)
    sq = f2_sqr(root_cplx)
    cplx_ok = jnp.all(sq == a, axis=(-1, -2))

    ok_sx = jnp.all(mont_mul(s_x, s_x) == x, axis=-1)
    root_y0 = jnp.where(
        ok_sx[..., None, None],
        jnp.stack([s_x, zero], axis=-2),
        jnp.stack([zero, s_nx], axis=-2),
    )
    y0_ok = ok_sx | jnp.all(mont_mul(s_nx, s_nx) == neg_x, axis=-1)

    root = jnp.where(y_is_zero[..., None, None], root_y0, root_cplx)
    is_sq = jnp.where(y_is_zero, y0_ok, cplx_ok)
    a_zero = f2_is_zero(a)
    root = jnp.where(a_zero[..., None, None], jnp.zeros_like(root), root)
    is_sq = is_sq | a_zero
    return root, is_sq


def f2_sqrt_device(a):
    """Batched Fq2 square root (complex method, p ≡ 3 mod 4).

    Returns (root, is_square); non-square lanes yield garbage roots with
    is_square False. Mirrors crypto/bls12_381/fields.py:f2_sqrt. Staged as
    small jits around the shared pow scan — one mega-jit here made XLA-CPU
    compile for hours."""
    sqrt_bits = jnp.asarray(_SQRT_BITS)
    norm = _jit_sqrt_norm(a)
    n = _fq_pow_var(norm, sqrt_bits)
    roots = _fq_pow_var(_jit_sqrt_candidates(a, n), sqrt_bits)
    t, two_t = _jit_sqrt_pick_t(a, n, roots)
    inv_2t = _fq_pow_var(two_t, jnp.asarray(_PM2_BITS_PAD))
    return _jit_sqrt_finish(a, roots, t, inv_2t)


def fq_inv_staged(a):
    """1/a over Fq limbs via the shared pow scan."""
    return _fq_pow_var(a, jnp.asarray(_PM2_BITS_PAD))


@jax.jit
def _jit_f2_norm(a):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return mod_add(mont_mul(a0, a0), mont_mul(a1, a1))


@jax.jit
def _jit_f2_scale_inv(a, ninv):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack(
        [mont_mul(a0, ninv), mod_sub(jnp.zeros_like(a0), mont_mul(a1, ninv))],
        axis=-2,
    )


def f2_inv_staged(a):
    """Fq2 inversion with the Fq pow hoisted to the shared scan."""
    return _jit_f2_scale_inv(a, fq_inv_staged(_jit_f2_norm(a)))


def _from_mont_fq(a):
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return mont_mul(a, one)


def f2_sgn0_device(a):
    """RFC 9380 sgn0 (m=2) over Montgomery limb Fq2: [...,] int32 in {0,1}."""
    c0 = _from_mont_fq(a[..., 0, :])
    c1 = _from_mont_fq(a[..., 1, :])
    s0 = c0[..., 0] & 1
    z0 = jnp.all(c0 == 0, axis=-1).astype(jnp.int32)
    s1 = c1[..., 0] & 1
    return s0 | (z0 & s1)


def _gx(x):
    """g(x) = x³ + A·x + B on E'."""
    a = jnp.asarray(_A_DEV)
    b = jnp.asarray(_B_DEV)
    return f2_add(f2_add(f2_mul(f2_sqr(x), x), f2_mul(a, x)), b)


@jax.jit
def _jit_sswu_tv(u):
    z = jnp.asarray(_Z_DEV)
    z_u2 = f2_mul(z, f2_sqr(u))
    tv = f2_add(f2_sqr(z_u2), z_u2)
    return z_u2, tv


@jax.jit
def _jit_sswu_gx(u, z_u2, tv, tv_inv):
    tv_zero = f2_is_zero(tv)
    one = jnp.broadcast_to(jnp.asarray(_ONE_F2_DEV), u.shape).astype(jnp.int32)
    x1_main = f2_mul(jnp.asarray(_MBA_DEV), f2_add(one, tv_inv))
    x1 = f2_select(
        tv_zero,
        jnp.broadcast_to(jnp.asarray(_BZA_DEV), u.shape).astype(jnp.int32),
        x1_main,
    )
    gx1 = _gx(x1)
    x2 = f2_mul(z_u2, x1)
    gx2 = _gx(x2)
    return x1, gx1, x2, gx2


@jax.jit
def _jit_sswu_select(u, x1, x2, roots, is_sq):
    y1, y2 = roots[0], roots[1]
    sq1 = is_sq[0]
    x = f2_select(sq1, x1, x2)
    y = f2_select(sq1, y1, y2)
    flip = f2_sgn0_device(u) != f2_sgn0_device(y)
    y = f2_select(flip, f2_neg(y), y)
    return x, y


def map_to_curve_sswu_device(u):
    """Batched simplified SWU onto E' ([..., 2, 48] → affine (x, y)).
    Staged orchestrator: tv → shared-scan inversion → gx candidates →
    staged sqrt → selects."""
    z_u2, tv = _jit_sswu_tv(u)
    tv_inv = f2_inv_staged(tv)
    x1, gx1, x2, gx2 = _jit_sswu_gx(u, z_u2, tv, tv_inv)
    roots, is_sq = f2_sqrt_device(jnp.stack([gx1, gx2], axis=0))
    return _jit_sswu_select(u, x1, x2, roots, is_sq)


@jax.jit
def _jit_iso(x, y, d_inv):
    """3-isogeny E' → E2 with 1/(x - x0) precomputed (Vélu-derived, RFC
    9380 §E.3-pinned — mirrors the host `_isogeny_to_e2`)."""
    d_inv2 = f2_sqr(d_inv)
    d_inv3 = f2_mul(d_inv2, d_inv)
    t = jnp.asarray(_T_DEV)
    u_c = jnp.asarray(_U_DEV)
    phi_x = f2_add(f2_add(x, f2_mul(t, d_inv)), f2_mul(u_c, d_inv2))
    phi_x = f2_mul(phi_x, jnp.asarray(_INV9_DEV))
    one = jnp.broadcast_to(jnp.asarray(_ONE_F2_DEV), x.shape).astype(jnp.int32)
    two_u = f2_add(u_c, u_c)
    deriv = f2_sub(f2_sub(one, f2_mul(t, d_inv2)), f2_mul(two_u, d_inv3))
    phi_y = f2_neg(f2_mul(f2_mul(y, deriv), jnp.asarray(_INV27_DEV)))
    return phi_x, phi_y


def isogeny_to_e2_device(x, y):
    d = f2_sub(x, jnp.asarray(_X0_DEV))
    return _jit_iso(x, y, f2_inv_staged(d))


@functools.partial(jax.jit, static_argnums=(2,))
def _jit_pair_add(px, py, n: int):
    one2 = _one_fq2((n,))
    q0 = (px[:n], py[:n], one2)
    q1 = (px[n:], py[n:], one2)
    return pt_add(DevFq2, q0, q1)


# g2_clear_cofactor_device orchestrates its own staged jits


def hash_to_g2_device(u):
    """Batched hash_to_curve field→group stage.

    u: [n, 2, 2, 48] — per message the two hash_to_field outputs u0, u1
    (Montgomery limbs). Returns Jacobian twisted G2 points ([n, 2, 48]×3)
    in the r-torsion subgroup. Python-level orchestration over staged jits
    (see fq_pow_fixed docstring for why)."""
    u = jnp.asarray(u)
    n = u.shape[0]
    # stack all u0 then all u1 (NOT a raw reshape, which would interleave
    # messages): lanes [0:n] are u0 maps, [n:2n] are u1 maps.
    flat = jnp.concatenate([u[:, 0], u[:, 1]], axis=0)
    x, y = map_to_curve_sswu_device(flat)
    px, py = isogeny_to_e2_device(x, y)
    s = _jit_pair_add(px, py, n)
    return g2_clear_cofactor_device(s)


def messages_to_field_device(messages, dst: bytes = HH.DST_G2_POP) -> np.ndarray:
    """Host stage: expand_message_xmd + mod-p reduction for a message list →
    [n, 2, 2, 48] Montgomery limb array feeding hash_to_g2_device."""
    from .bls381 import R_MONT

    out = np.zeros((len(messages), 2, 2, NLIMB), dtype=np.int32)
    for i, msg in enumerate(messages):
        u0, u1 = HH.hash_to_field_fq2(msg, 2, dst)
        for j, uval in enumerate((u0, u1)):
            out[i, j, 0] = int_to_limbs(uval[0] * R_MONT % P)
            out[i, j, 1] = int_to_limbs(uval[1] * R_MONT % P)
    return out
