"""Device (batched, jittable) optimal-ate pairing for BLS12-381.

This is the TPU analog of blst's `verify_multiple_aggregate_signatures`
multi-pairing core (reference: crypto/bls/src/impls/blst.rs:112-117) — the
single most important kernel for north-star metric 1 (BASELINE.md): the
Miller loops of a signature batch run vmapped over the batch dimension, the
loop results are tree-multiplied in Fq12, and ONE final exponentiation
decides the whole batch.

Design notes (derived, not transliterated — the reference's backend is
vendored C/assembly):

* The Miller loop runs on the TWIST: Q stays in E'(Fq2) Jacobian
  coordinates; no per-element untwisting into Fq12 (the slow host oracle in
  crypto/bls12_381/pairing_reference.py untwists — correct but scalar; the
  optimized host path in crypto/bls12_381/pairing.py now also stays on the
  twist). Line
  functions are derived by clearing denominators of the affine tangent /
  chord slope against untwisted coordinates (x·w⁻², y·w⁻³, tower w²=v,
  w⁶=ξ):

      tangent at T=(X,Y,Z):  a0 = −2YZ³·ξ·yp   b1 = 2Y²−3X³   b2 = 3X²Z²·xp
      chord  T→(x2,y2):      a0 = −Zλ·ξ·yp     b1 = Zλy2−θx2  b2 = θ·xp
                             (θ = y2Z³−Y, λ = x2Z²−X)

  giving the sparse Fq12 element l = (a0,0,0) + (0,b1,b2)·w. Scaling lines
  by Fq2 factors (the cleared denominators and one ξ) is sound: subfield
  elements die in the final exponentiation's (p⁶−1) easy part.

* Final exponentiation uses the BLS12 hard-part factorization
      (x−1)²·(x+p)·(x²+p²−1) + 3 == 3·(p⁴−p²+1)/r      (verified in-repo)
  so the device computes f^(3·(p¹²−1)/r). For pairing CHECKS this is
  equivalent (gcd(3, r)=1 on μ_r); for GT VALUES everything this module
  returns is the cube of the host oracle's value — tests assert exactly
  that relation. After the easy part, inversion is conjugation and x<0
  exponents use conj(f^|x|).

* G2 subgroup membership uses the ψ-endomorphism criterion
  ψ(Q) == [x]Q (valid since p ≡ x (mod r), verified in-repo; ψ = twist ∘
  Frobenius ∘ untwist has twisted coordinates ψ(x,y) = (ξ^(−(p−1)/3)·x̄,
  ξ^(−(p−1)/2)·ȳ)). A 64-iteration batched ladder replaces the 255-bit
  order multiplication the host oracle uses.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..crypto.bls12_381 import fields as HF
from ..crypto.bls12_381.fields import P, R, X
from . import bls381_tower as TW
from .bls381 import (
    NLIMB,
    DevFq2,
    R_MONT,
    _ONE_MONT,
    fq_to_device,
    int_to_limbs,
    mont_mul,
    pt_add,
    pt_double,
)
from .bls381_tower import (
    f2_add,
    f2_conj,
    f2_double,
    f2_is_zero,
    f2_mul,
    f2_mul_fq,
    f2_mul_xi,
    f2_neg,
    f2_select,
    f2_sqr,
    f2_sub,
    f2_triple,
    f12_conj,
    f12_frob,
    f12_frob2,
    f12_inv,
    f12_is_one,
    f12_mul,
    f12_ones,
    f12_pow_bits,
    f12_select,
    f12_sqr,
    fq2_const,
)

# ---------------------------------------------------------------------------
# Constants
# ---------------------------------------------------------------------------

_ATE = abs(X)  # 0xd201000000010000
# MSB-first bits after the leading 1 (63 entries) — the Miller loop schedule.
_ATE_TAIL_BITS = np.array([int(b) for b in bin(_ATE)[3:]], dtype=np.int32)
# LSB-first bits of |x| for exponentiation scans.
_X_BITS_LSB = np.array([(_ATE >> i) & 1 for i in range(_ATE.bit_length())],
                       dtype=np.int32)

# ψ coefficients (host-derived): ξ^(−(p−1)/3), ξ^(−(p−1)/2)
_PSI_CX = fq2_const(HF.f2_pow(HF.f2_inv(HF.XI), (P - 1) // 3))
_PSI_CY = fq2_const(HF.f2_pow(HF.f2_inv(HF.XI), (P - 1) // 2))

assert (X - 1) ** 2 * (X + P) * (X**2 + P**2 - 1) + 3 == 3 * ((P**4 - P**2 + 1) // R)
assert P % R == X % R  # ψ acts as [x] on G2 — the subgroup criterion


# ---------------------------------------------------------------------------
# Point plumbing
# ---------------------------------------------------------------------------


def g1_affine_to_device(points):
    """Host G1 affine pairs (x, y) (None for infinity) → (xp, yp, inf_mask).
    xp/yp: [n, 48] Montgomery limbs; infinity lanes hold dummy (0,0)."""
    xs, ys, inf = [], [], []
    for aff in points:
        if aff is None:
            xs.append(0); ys.append(0); inf.append(True)
        else:
            xs.append(aff[0]); ys.append(aff[1]); inf.append(False)
    return (
        jnp.asarray(fq_to_device(xs)),
        jnp.asarray(fq_to_device(ys)),
        jnp.asarray(np.array(inf, dtype=bool)),
    )


def g2_affine_to_device(points):
    """Host G2 affine pairs ((x0,x1),(y0,y1)) or None → (x, y, inf_mask).
    x/y: [n, 2, 48]."""
    xs, ys, inf = [], [], []
    for aff in points:
        if aff is None:
            xs.append((0, 0)); ys.append((0, 0)); inf.append(True)
        else:
            xs.append(aff[0]); ys.append(aff[1]); inf.append(False)
    pack = lambda vals: jnp.asarray(np.stack([fq2_const(v) for v in vals]))
    return pack(xs), pack(ys), jnp.asarray(np.array(inf, dtype=bool))


def _one_fq(batch_shape):
    return jnp.broadcast_to(jnp.asarray(_ONE_MONT), (*batch_shape, NLIMB)).astype(jnp.int32)


def _one_fq2(batch_shape):
    one = _one_fq(batch_shape)
    return jnp.stack([one, jnp.zeros_like(one)], axis=-2)


# ---------------------------------------------------------------------------
# Miller loop steps
# ---------------------------------------------------------------------------


def _line_to_f12(a0, b1, b2):
    """Sparse line slots → dense Fq12 [..., 2, 3, 2, 48]."""
    z = jnp.zeros_like(a0)
    lo = jnp.stack([a0, z, z], axis=-3)
    hi = jnp.stack([z, b1, b2], axis=-3)
    return jnp.stack([lo, hi], axis=-4)


def _dbl_step(T, xp, yp):
    """Tangent line at T evaluated at P, then T ← 2T. Returns (line, T')."""
    Xc, Yc, Zc = T
    XX = f2_sqr(Xc)
    YY = f2_sqr(Yc)
    ZZ = f2_sqr(Zc)
    YZ3 = f2_mul(f2_mul(Yc, Zc), ZZ)             # Y·Z³
    a0 = f2_mul_xi(f2_neg(f2_mul_fq(f2_double(YZ3), yp)))
    b1 = f2_sub(f2_double(YY), f2_triple(f2_mul(Xc, XX)))
    b2 = f2_mul_fq(f2_triple(f2_mul(XX, ZZ)), xp)
    return _line_to_f12(a0, b1, b2), pt_double(DevFq2, T)


def _add_step(T, q_x, q_y, q_jac_one, xp, yp):
    """Chord line through T and the affine base Q evaluated at P, then
    T ← T + Q."""
    Xc, Yc, Zc = T
    ZZ = f2_sqr(Zc)
    Z3 = f2_mul(ZZ, Zc)
    theta = f2_sub(f2_mul(q_y, Z3), Yc)
    lam = f2_sub(f2_mul(q_x, ZZ), Xc)
    zlam = f2_mul(Zc, lam)
    a0 = f2_mul_xi(f2_neg(f2_mul_fq(zlam, yp)))
    b1 = f2_sub(f2_mul(zlam, q_y), f2_mul(theta, q_x))
    b2 = f2_mul_fq(theta, xp)
    T_new = pt_add(DevFq2, T, (q_x, q_y, q_jac_one))
    return _line_to_f12(a0, b1, b2), T_new


# double-and-add op schedule (host-precomputed): one scan step per group
# op instead of a fused dbl+add+select body. The schedule halves the scan
# body (each step is ONE of the two branches, compiled as separate HLO
# computations under lax.cond) and skips the wasted always-computed add of
# the branchless form — fewer flops AND tractable LLVM compiles.
_MILLER_OPS = []
for _b in _ATE_TAIL_BITS:
    _MILLER_OPS.append(0)  # double step
    if _b:
        _MILLER_OPS.append(1)  # add step
_MILLER_OPS = np.array(_MILLER_OPS, dtype=np.int32)


def miller_loop_batch(xp, yp, q_x, q_y):
    """Batched f_{|x|,Q}(P), conjugated for x<0. Inputs: G1 affine limbs
    [n, 48]×2, G2 (twisted) affine limbs [n, 2, 48]×2. Returns [n] Fq12.
    Infinity handling is the CALLER's job (mask lanes to one)."""
    batch = xp.shape[:-1]
    one2 = _one_fq2(batch)
    T0 = (q_x, q_y, one2)
    f0 = f12_ones(batch)
    ops = jnp.asarray(_MILLER_OPS)

    def dbl_branch(carry):
        T, f = carry
        line, T2 = _dbl_step(T, xp, yp)
        return (T2, f12_mul(f12_sqr(f), line))

    def add_branch(carry):
        T, f = carry
        line, T2 = _add_step(T, q_x, q_y, one2, xp, yp)
        return (T2, f12_mul(f, line))

    def body(carry, op):
        return lax.cond(op > 0, add_branch, dbl_branch, carry), None

    (_, f), _ = lax.scan(body, (T0, f0), ops)
    return f12_conj(f)  # x < 0


# --- staged jit pieces ------------------------------------------------------
# One mega-jit (miller + final exp + reductions) made XLA-CPU compile for
# hours on slow hosts: each baked-in pow chain became its own while loop
# with a huge body. Instead: the Miller scan is one jit; the final
# exponentiation is orchestrated in Python over a SINGLE runtime-bits
# f12-pow scan (compiled once, reused for all five x-powers) plus small
# straight-line jits.

_miller_jit = jax.jit(miller_loop_batch)

# |x|-power op schedule (LSB-first square-and-multiply, one op per scan
# step — same body-splitting rationale as the Miller schedule)
_POW_X_OPS = []
for _i in range(64):
    if (_ATE >> _i) & 1:
        _POW_X_OPS.append(1)  # acc ×= base
    _POW_X_OPS.append(0)  # base ²= (harmless past the top bit)
_POW_X_OPS = np.array(_POW_X_OPS, dtype=np.int32)


@jax.jit
def _jit_f12_pow_x(a):
    """a^|x| via the fixed schedule."""
    one = f12_ones(a.shape[:-4])

    def mul_branch(carry):
        acc, base = carry
        return (f12_mul(acc, base), base)

    def sqr_branch(carry):
        acc, base = carry
        return (acc, f12_sqr(base))

    def body(carry, op):
        return lax.cond(op > 0, mul_branch, sqr_branch, carry), None

    (acc, _), _ = lax.scan(body, (one, a), jnp.asarray(_POW_X_OPS))
    return acc


def _pow_x_conj(a):
    """a^x = conj(a^|x|) (x < 0)."""
    return _jit_f12_conj(_jit_f12_pow_x(a))


_jit_f12_mul = jax.jit(f12_mul)
_jit_f12_conj = jax.jit(f12_conj)
_jit_f12_frob = jax.jit(f12_frob)
_jit_f12_frob2 = jax.jit(f12_frob2)


@jax.jit
def _jit_f12_inv(a):
    return f12_inv(a)


@jax.jit
def _jit_easy_part(F, Finv):
    t = f12_mul(f12_conj(F), Finv)  # ^(p⁶−1)
    return f12_mul(f12_frob2(t), t)  # ^(p²+1): now cyclotomic


@jax.jit
def _jit_t_cubed_mul(y4, t):
    return f12_mul(y4, f12_mul(f12_sqr(t), t))


def final_exp_cubed(F):
    """F^(3·(p¹²−1)/r) — easy part then the (x−1)²(x+p)(x²+p²−1)+3 chain.
    Cube of the host oracle's final_exponentiation; identical for ==1
    checks. Python orchestration over staged jits."""
    t = _jit_easy_part(F, _jit_f12_inv(F))
    y1 = _jit_f12_conj(_jit_f12_mul(_jit_f12_pow_x(t), t))
    y2 = _jit_f12_conj(_jit_f12_mul(_jit_f12_pow_x(y1), y1))
    y3 = _jit_f12_mul(_pow_x_conj(y2), _jit_f12_frob(y2))  # ^(x+p)
    a = _pow_x_conj(y3)  # y3^x
    b = _pow_x_conj(a)  # y3^(x²)
    y4 = _jit_f12_mul(_jit_f12_mul(b, _jit_f12_frob2(y3)), _jit_f12_conj(y3))
    return _jit_t_cubed_mul(y4, t)


@jax.jit
def _jit_mask(f, p_inf, q_inf):
    skip = p_inf | q_inf
    return f12_select(skip, f12_ones(f.shape[:-4]), f)


def _mask_and_reduce(f, p_inf, q_inf):
    """Infinity lanes → identity, then tree-product to [1] Fq12 — one
    shared batch f12_mul jit per halving level (log n dispatches)."""
    f = _jit_mask(f, p_inf, q_inf)
    n = f.shape[0]
    while n > 1:
        half = n // 2
        merged = _jit_f12_mul(f[:half], f[half : 2 * half])
        if n % 2:
            merged = jnp.concatenate([merged, f[-1:]], axis=0)
        f = merged
        n = f.shape[0]
    return f


def multi_pairing_check_device(xp, yp, p_inf, q_x, q_y, q_inf):
    """∏ e(P_i, Q_i) == 1 over the batch, entirely on device. Infinity
    lanes contribute the identity (host oracle behavior)."""
    f = _miller_jit(xp, yp, q_x, q_y)
    F = _mask_and_reduce(f, p_inf, q_inf)
    return f12_is_one(final_exp_cubed(F))[0]


def pairing_cubed_device(xp, yp, q_x, q_y):
    """e(P, Q)³ per lane (full final exp per element — for tests; batch
    verification never needs per-element GT values)."""
    f = _miller_jit(xp, yp, q_x, q_y)
    return final_exp_cubed(f)


# ---------------------------------------------------------------------------
# ψ endomorphism + G2 subgroup check
# ---------------------------------------------------------------------------


def psi(q_x, q_y):
    """ψ on twisted affine coordinates: (cx·x̄, cy·ȳ)."""
    return (
        f2_mul(f2_conj(q_x), jnp.asarray(_PSI_CX)),
        f2_mul(f2_conj(q_y), jnp.asarray(_PSI_CY)),
    )


def _psi_jac(T):
    """ψ on Jacobian coords: (cx·X̄, cy·Ȳ, Z̄) — ψ is Fq2-conjugate-linear
    and the coordinate weights stay consistent since Z̄ carries through."""
    Xc, Yc, Zc = T
    return (
        f2_mul(f2_conj(Xc), jnp.asarray(_PSI_CX)),
        f2_mul(f2_conj(Yc), jnp.asarray(_PSI_CY)),
        f2_conj(Zc),
    )


def _ladder_mul_const(T, bits_msb_first: np.ndarray):
    """[k]T for a fixed scalar via left-to-right double-and-add (branchless
    scan; bits ride as a runtime argument so ONE compiled scan serves every
    fixed scalar of the same width — see `_jit_ladder`)."""
    return _jit_ladder(*T, jnp.asarray(bits_msb_first[1:]))


@jax.jit
def _jit_ladder(Tx, Ty, Tz, tail_bits):
    """Left-to-right ladder: acc starts at T (the leading 1 bit), then one
    double(+conditional add of T) per remaining bit."""
    T = (Tx, Ty, Tz)
    batch = Tx.shape[:-2]

    def body(acc, bit):
        acc = pt_double(DevFq2, acc)
        added = pt_add(DevFq2, acc, T)
        take = jnp.broadcast_to(bit > 0, batch)
        acc = tuple(f2_select(take, a, b) for a, b in zip(added, acc))
        return acc, None

    acc, _ = lax.scan(body, T, tail_bits)
    return acc


# small shared point jits (straight-line pieces stay out of mega-graphs —
# a single fused cofactor/hash graph made XLA-CPU's LLVM stage blow up
# superlinearly on slow hosts)

_jit_pt_add_g2 = jax.jit(lambda ax, ay, az, bx, by, bz: pt_add(
    DevFq2, (ax, ay, az), (bx, by, bz)
))
_jit_pt_double_g2 = jax.jit(lambda x, y, z: pt_double(DevFq2, (x, y, z)))


_ATE_BITS_MSB = np.array([int(b) for b in bin(_ATE)[2:]], dtype=np.int32)


@jax.jit
def g2_subgroup_check_device(q_x, q_y, q_inf):
    """Batched ψ(Q) == [x]Q membership test (64-iteration ladder instead of
    the host's 255-bit order multiplication). Infinity counts as member."""
    batch = q_x.shape[:-2]
    one2 = _one_fq2(batch)
    T = (q_x, q_y, one2)
    xq = _ladder_mul_const(T, _ATE_BITS_MSB)          # [|x|]Q
    px, py = psi(q_x, q_y)
    s = pt_add(DevFq2, (px, py, one2), xq)            # ψ(Q) + [|x|]Q (x<0)
    return f2_is_zero(s[2]) | q_inf


# ---------------------------------------------------------------------------
# Fast cofactor clearing (Budroni–Pintore form, identity verified in-repo):
#   [h_eff]Q = [x²−x−1]Q + [x−1]ψ(Q) + ψ²(2Q)
# ---------------------------------------------------------------------------


_jit_neg_y = jax.jit(lambda x, y, z: (x, f2_neg(y), z))
_jit_psi_jac = jax.jit(lambda x, y, z: _psi_jac((x, y, z)))


def g2_clear_cofactor_device(T):
    """Jacobian twisted point(s) → subgroup point(s); 2 x-ladders + 3 ψ
    instead of a 636-bit scalar multiplication. Python orchestration over
    the shared ladder/point jits."""
    a = _ladder_mul_const(T, _ATE_BITS_MSB)           # [|x|]Q
    a = _jit_neg_y(*a)                                # [x]Q
    negT = _jit_neg_y(*T)
    c1 = _jit_pt_add_g2(*a, *negT)                    # [x−1]Q
    c2 = _ladder_mul_const(c1, _ATE_BITS_MSB)
    c2 = _jit_neg_y(*c2)                              # [x²−x]Q
    c3 = _jit_pt_add_g2(*c2, *negT)                   # [x²−x−1]Q
    out = _jit_pt_add_g2(*c3, *_jit_psi_jac(*c1))
    two_q = _jit_pt_double_g2(*T)
    return _jit_pt_add_g2(*out, *_jit_psi_jac(*_jit_psi_jac(*two_q)))
