"""Device epoch sweep: the fused all-validator rewards/penalties pass.

The single_pass.rs:20 analog on device (SURVEY §7 step 3): Altair's
epoch-boundary flag deltas, inactivity penalties, and inactivity-score
updates as ONE jitted pass over flat uint64 arrays — integer-only
(consensus-grade, no floats), shape-stable per validator count, epoch
scalars traced (no per-epoch recompiles).

uint64 requires JAX x64 mode, which is process-global and changes trace
cache keys for unrelated kernels. Importing this module therefore enables
x64 for the WHOLE process — use it from a dedicated process (the
LIGHTHOUSE_TPU_DEVICE_EPOCH_SWEEP=1 node flag, the parity tests'
subprocess, or a bench fork), never from one sharing compiles with the
uint32 crypto kernels.

Parity contract: bit-exact equality with the numpy sweep in
state_processing/altair.py for every input where the u64 overflow guard
(effective_balance·score) does not trip; the host wrapper must pre-check
that guard and keep such states on the host bigint path.
"""

from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax import jit  # noqa: E402

# altair participation flag weights (TIMELY_SOURCE/TARGET/HEAD)
PARTICIPATION_FLAG_WEIGHTS = (14, 26, 14)
WEIGHT_DENOMINATOR = 64
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2


@jit
def epoch_sweep(
    effective_balance,  # [n] u64
    slashed,  # [n] bool
    activation_epoch,  # [n] u64
    exit_epoch,  # [n] u64
    withdrawable_epoch,  # [n] u64
    prev_flags,  # [n] u8 previous-epoch participation
    scores,  # [n] u64 inactivity scores
    balances,  # [n] u64
    scalars,  # [9] u64: prev_epoch, curr_epoch, base_reward_per_increment,
    #                total_active_increments, in_leak, score_bias,
    #                score_recovery, inactivity_denom,
    #                effective_balance_increment — see host wrapper
):
    prev_epoch = scalars[0]
    curr_epoch = scalars[1]
    base_reward_per_increment = scalars[2]
    total_active_increments = scalars[3]
    in_leak = scalars[4] != 0
    score_bias = scalars[5]
    score_recovery = scalars[6]
    inactivity_denom = scalars[7]
    eb_increment = scalars[8]

    u64 = jnp.uint64
    one = jnp.uint64(1)

    def active_at(epoch):
        return (activation_epoch <= epoch) & (epoch < exit_epoch)

    prev_active = active_at(prev_epoch)
    curr_active = active_at(curr_epoch)
    del curr_active  # totals are precomputed on host (traced scalars)
    eligible = prev_active | (slashed & (prev_epoch + one < withdrawable_epoch))

    eb_increments = effective_balance // eb_increment
    base_rewards = eb_increments * base_reward_per_increment

    rewards = jnp.zeros_like(balances)
    penalties = jnp.zeros_like(balances)

    def unslashed_participating(flag_index):
        has = (prev_flags >> flag_index) & 1
        return (has == 1) & (~slashed) & prev_active

    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        participating = unslashed_participating(flag_index)
        upb = jnp.maximum(
            jnp.sum(jnp.where(participating, effective_balance, u64(0))),
            eb_increment,
        )
        upb_increments = upb // eb_increment
        got_flag = eligible & participating
        numer = base_rewards * u64(weight) * upb_increments
        flag_reward = numer // (
            total_active_increments * u64(WEIGHT_DENOMINATOR)
        )
        rewards = rewards + jnp.where(
            got_flag & ~in_leak, flag_reward, u64(0)
        )
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            missed = eligible & ~participating
            penalties = penalties + jnp.where(
                missed,
                (base_rewards * u64(weight)) // u64(WEIGHT_DENOMINATOR),
                u64(0),
            )

    # inactivity-score updates (process_inactivity_updates) — computed on
    # the PRE-update scores ordering-wise BEFORE the inactivity penalty
    # uses... the spec runs process_inactivity_updates before
    # rewards_and_penalties, so penalties see the UPDATED scores
    participating_target = unslashed_participating(TIMELY_TARGET_FLAG_INDEX)
    dec = eligible & participating_target
    inc = eligible & ~participating_target
    new_scores = scores - jnp.where(dec, jnp.minimum(one, scores), u64(0))
    new_scores = new_scores + jnp.where(inc, score_bias, u64(0))
    new_scores = new_scores - jnp.where(
        eligible & ~in_leak, jnp.minimum(score_recovery, new_scores), u64(0)
    )

    # inactivity penalties (get_inactivity_penalty_deltas) on the updated
    # scores
    inactive = eligible & ~participating_target
    penalties = penalties + jnp.where(
        inactive,
        (effective_balance * new_scores) // inactivity_denom,
        u64(0),
    )

    new_balances = balances + rewards
    new_balances = jnp.maximum(new_balances, penalties) - penalties
    return new_balances, new_scores
