"""Device Pippenger multi-scalar multiplication over G1.

The commitment-scale MSM (Σ sᵢ·Pᵢ, 4096 Lagrange setup points per blob —
crypto/kzg/src/lib.rs:110 `blob_to_kzg_commitment`, SURVEY §2.7-2/§7
step 2) bucketized exactly like blst's Pippenger, laid out TPU-first:

  * scalar digit decomposition + per-window counting sort happen on the
    HOST (numpy argsort over [nwin, n] uint8 digits — microseconds, and
    the scalars live on the host anyway);
  * the device does what it is good at: one gather to put each window's
    points in bucket order, a log-depth SEGMENTED tree scan (the bucket
    sums of a counting-sorted array are segment sums — computed with
    `lax.associative_scan` over the standard segmented-add monoid,
    vectorized point adds all the way down), a reverse suffix scan for
    the Σ j·Bⱼ running-sum trick, and four doublings per window for the
    Horner combine.

Per 4096-point MSM with 4-bit windows: 64 windows × (~2·log n segmented
combines + ~8 small lane ops + 4 doublings) — ~500k lane point-adds of
work at log sequential depth, vs 2M for per-point ladders.

Points are Jacobian [n, 48] Montgomery limb arrays (ops/bls381 layout);
infinity is Z == 0, so masking is free.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .bls381 import (
    NLIMB,
    DevFq,
    _ONE_MONT,
    g1_points_from_device,
    pt_add,
    pt_double,
)

WINDOW = 4  # digit bits; 64 windows cover 255-bit Fr scalars
NBITS = 256  # scalars are reduced mod r < 2^255; one spare window bit


def _host_digit_prep(scalars, window: int):
    """digits → (order, seg_start, last_idx, present) numpy arrays."""
    n = len(scalars)
    nwin = (NBITS + window - 1) // window
    ndig = 1 << window
    digits = np.zeros((nwin, n), dtype=np.int32)
    for i, s in enumerate(scalars):
        for w in range(nwin):
            digits[w, i] = (s >> (w * window)) & (ndig - 1)
    order = np.argsort(digits, axis=1, kind="stable").astype(np.int32)
    sd = np.take_along_axis(digits, order, axis=1)
    seg_start = np.zeros((nwin, n), dtype=bool)
    seg_start[:, 0] = True
    seg_start[:, 1:] = sd[:, 1:] != sd[:, :-1]
    # last occurrence of each nonzero digit d in the sorted row
    last_idx = np.zeros((nwin, ndig - 1), dtype=np.int32)
    present = np.zeros((nwin, ndig - 1), dtype=bool)
    for w in range(nwin):
        row = sd[w]
        # searchsorted: row is ascending; last index of d = right_bound - 1
        rb = np.searchsorted(row, np.arange(1, ndig), side="right")
        lb = np.searchsorted(row, np.arange(1, ndig), side="left")
        present[w] = rb > lb
        last_idx[w] = np.maximum(rb - 1, 0)
    return order, seg_start, last_idx, present


def _seg_combine(a, b):
    """Segmented-sum monoid: (flag, point) pairs; b is closer to the end."""
    fa, xa, ya, za = a
    fb, xb, yb, zb = b
    added = pt_add(DevFq, (xa, ya, za), (xb, yb, zb))
    x = DevFq.select(fb, xb, added[0])
    y = DevFq.select(fb, yb, added[1])
    z = DevFq.select(fb, zb, added[2])
    return (fa | fb, x, y, z)


def _inf_like(shape):
    one = jnp.broadcast_to(jnp.asarray(_ONE_MONT), (*shape, NLIMB)).astype(
        jnp.int32
    )
    return (one, one, jnp.zeros((*shape, NLIMB), dtype=jnp.int32))


@functools.partial(jax.jit, static_argnames=("window",))
def msm_pippenger_device(xs, ys, zs, order, seg_start, last_idx, present,
                         window: int = WINDOW):
    """Σ sᵢ·Pᵢ. Point arrays [n, 48]; index arrays from _host_digit_prep.
    Returns a single Jacobian point ([48], [48], [48])."""
    nwin = order.shape[0]
    ndig_m1 = last_idx.shape[1]

    def body(i, acc):
        w = nwin - 1 - i  # MSB window first (Horner)
        for _ in range(window):
            acc = pt_double(DevFq, acc)
        # gather this window's points into bucket (counting-sorted) order
        idx = order[w]
        pw = (
            jnp.take(xs, idx, axis=0),
            jnp.take(ys, idx, axis=0),
            jnp.take(zs, idx, axis=0),
        )
        flags = seg_start[w]
        f, bx, by, bz = lax.associative_scan(
            _seg_combine, (flags, *pw), axis=0
        )
        # bucket sums = scan value at each segment's last element
        li = last_idx[w]
        bkt = (
            jnp.take(bx, li, axis=0),
            jnp.take(by, li, axis=0),
            jnp.take(bz, li, axis=0),
        )
        pres = present[w]
        bkt = (
            bkt[0],
            bkt[1],
            DevFq.select(pres, bkt[2], jnp.zeros_like(bkt[2])),
        )
        # Σ j·Bⱼ via the running-sum trick: reverse inclusive scan then sum
        def add_combine(a, b):
            return pt_add(DevFq, a, b)

        running = lax.associative_scan(add_combine, bkt, axis=0, reverse=True)
        # tree-sum the running sums (ndig-1 lanes, pad to power of two)
        pad = 1
        while pad < ndig_m1:
            pad *= 2
        if pad != ndig_m1:
            pinf = _inf_like((pad - ndig_m1,))
            running = tuple(
                jnp.concatenate([r, p], axis=0) for r, p in zip(running, pinf)
            )
        m = pad
        while m > 1:
            half = m // 2
            running = pt_add(
                DevFq,
                tuple(c[:half] for c in running),
                tuple(c[half : 2 * half] for c in running),
            )
            m = half
        wsum = tuple(c[0] for c in running)
        return pt_add(DevFq, acc, wsum)

    acc = tuple(c[0] for c in _inf_like((1,)))
    return lax.fori_loop(0, nwin, body, acc)


def g1_msm_pippenger(scalars, points_dev, window: int = WINDOW):
    order, seg_start, last_idx, present = _host_digit_prep(scalars, window)
    x, y, z = msm_pippenger_device(
        *points_dev,
        jnp.asarray(order),
        jnp.asarray(seg_start),
        jnp.asarray(last_idx),
        jnp.asarray(present),
        window=window,
    )
    return g1_points_from_device((x[None], y[None], z[None]))[0]


def g1_msm_ladder(scalars, points_dev):
    """Ladder MSM: per-point 256-bit double-and-add (ops/bls381
    batch_g1_scalar_mul) then one log-depth tree sum. ~4× the point-add
    work of Pippenger but a tiny, already-cached kernel graph — the
    robust default while Pippenger's larger graph compiles only where a
    real compile service exists (see LIGHTHOUSE_TPU_MSM)."""
    from .bls381 import batch_g1_scalar_mul, g1_sum_reduce, scalars_to_bits

    bits = jnp.asarray(scalars_to_bits(scalars, NBITS))
    scaled = batch_g1_scalar_mul(*points_dev, bits)
    x, y, z = g1_sum_reduce(*scaled)
    return g1_points_from_device((x, y, z))[0]


def g1_msm_device(scalars, points_dev, window: int = WINDOW):
    """Host entry: scalars (list[int] mod r) × device points → host
    Jacobian int tuple. `points_dev` = (xs, ys, zs) [n, 48] arrays (keep
    the setup resident on device across calls — see TrustedSetup).
    Implementation: LIGHTHOUSE_TPU_MSM = pippenger | ladder (default
    pippenger on a real accelerator, ladder on the CPU test platform
    where the bucketized kernel's compile takes tens of minutes)."""
    import os

    choice = os.environ.get("LIGHTHOUSE_TPU_MSM")
    if choice is None:
        import jax

        choice = (
            "ladder" if jax.default_backend() == "cpu" else "pippenger"
        )
    if choice == "pippenger":
        return g1_msm_pippenger(scalars, points_dev, window)
    return g1_msm_ladder(scalars, points_dev)
