"""Multi-chip sharded Merkleization: the distributed device step.

The validator-scale analog of the reference's batch parallelism (SURVEY.md
§2.9): the Merkle leaf array is sharded across the `batch` mesh axis, each
device hashes its subtree locally (pure VPU work over its HBM shard), the
per-device subtree roots ride ICI via `all_gather`, and the small top of the
tree is folded on every device redundantly (replicated compute beats a
round-trip). Scales to any power-of-two device count with zero host
involvement.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sha256 import _compress, _IV, _PAD64


def _sha256_pairs_inline(nodes):
    """nodes [M, 8] u32 → parents [M//2, 8] u32 (M even, static)."""
    blocks = nodes.reshape(-1, 16)
    n = blocks.shape[0]
    iv = jnp.broadcast_to(jnp.asarray(_IV), (n, 8))
    st = _compress(iv, blocks)
    pad = jnp.broadcast_to(jnp.asarray(_PAD64), (n, 16))
    return _compress(st, pad)


def _reduce_to_root(nodes, depth: int):
    """Hash [2^depth, 8] down to [1, 8] with a static loop (depth is a
    compile-time constant — XLA unrolls into `depth` batched compressions)."""
    for _ in range(depth):
        nodes = _sha256_pairs_inline(nodes)
    return nodes


def sharded_merkle_root_fn(mesh: Mesh, per_device_leaves: int, n_devices: int):
    """Build a jitted fn: [N, 8] u32 leaves (N = n_devices * per_device_leaves,
    both powers of two) → [8] u32 Merkle root, sharded over `mesh`."""
    assert per_device_leaves & (per_device_leaves - 1) == 0
    assert n_devices & (n_devices - 1) == 0
    local_depth = (per_device_leaves - 1).bit_length()
    top_depth = (n_devices - 1).bit_length()

    def per_device(leaves_shard):
        # leaves_shard: [per_device_leaves, 8] local block
        subtree_root = _reduce_to_root(leaves_shard, local_depth)  # [1, 8]
        # ICI: gather every device's subtree root, fold the top replicated
        roots = lax.all_gather(
            subtree_root[0], "batch", tiled=False
        )  # [n_devices, 8]
        return _reduce_to_root(roots, top_depth)  # [1, 8]

    from jax.experimental.shard_map import shard_map

    sharded = shard_map(
        per_device,
        mesh=mesh,
        in_specs=P("batch", None),
        out_specs=P("batch", None),  # each device emits the (identical) root
        check_rep=False,
    )

    @jax.jit
    def merkle_root(leaves):
        out = sharded(leaves)  # [n_devices, 8] — identical rows
        return out[0]

    return merkle_root


@functools.cache
def build_sharded_merkle(n_devices: int, per_device_leaves: int):
    """Convenience: mesh over the first n_devices + the jitted root fn."""
    import numpy as np

    devices = np.array(jax.devices()[:n_devices])
    mesh = Mesh(devices, ("batch",))
    fn = sharded_merkle_root_fn(mesh, per_device_leaves, n_devices)
    sharding = NamedSharding(mesh, P("batch", None))
    return mesh, fn, sharding
