"""Multi-chip sharded BLS batch step: RLC scalar-muls + ICI point-sum.

The distributed half of batch signature verification (SURVEY.md §2.9): the
signature-set batch is sharded over the `batch` mesh axis — each device
runs the 64-bit RLC scalar-multiplication ladders for its shard of G1
points (the per-set aggregated pubkeys) and tree-reduces its shard to one
partial sum; the per-device partial sums ride ICI via `all_gather`, and the
tiny [n_devices] tail is folded replicated on every device. This mirrors
the reference's rayon chunk map-reduce over signature sets
(consensus/state_processing/src/per_block_processing/
block_signature_verifier.rs:396-404) with the chunk axis mapped onto the
device mesh instead of CPU threads.

Point addition is not an arithmetic `psum`, so the reduction is an
`all_gather` + replicated Jacobian fold (n_devices-1 adds) — negligible
next to the 64-iteration ladders and bandwidth-wise just 3·48 int32 limbs
per device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .bls381 import DevFq, pt_add, pt_scalar_mul


def _tree_reduce_points(F, pt):
    """Coords [k, ...] → [1, ...] Jacobian sum (static shapes)."""
    k = pt[0].shape[0]
    while k > 1:
        half = k // 2
        lo = tuple(c[:half] for c in pt)
        hi = tuple(c[half : 2 * half] for c in pt)
        merged = pt_add(F, lo, hi)
        if k % 2:
            pt = tuple(
                jnp.concatenate([m, c[-1:]], axis=0) for m, c in zip(merged, pt)
            )
            k = half + 1
        else:
            pt = merged
            k = half
    return pt


def sharded_rlc_g1_fn(mesh: Mesh):
    """Build the jitted sharded step: ([n,48]×3 G1 Jacobian, [n,64] scalar
    bits) sharded over `batch` → replicated [1,48]×3 Σ rᵢ·Pᵢ."""

    def per_device(xs, ys, zs, bits):
        scaled = pt_scalar_mul(DevFq, (xs, ys, zs), bits)
        part = _tree_reduce_points(DevFq, scaled)  # [1, 48] each coord
        gathered = tuple(
            lax.all_gather(c[0], "batch", tiled=False) for c in part
        )  # [n_devices, 48]
        return _tree_reduce_points(DevFq, gathered)

    from jax.experimental.shard_map import shard_map

    sharded = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P("batch"), P("batch"), P("batch"), P("batch")),
        out_specs=(P("batch"), P("batch"), P("batch")),  # identical rows
        check_rep=False,
    )

    @jax.jit
    def rlc_sum(xs, ys, zs, bits):
        out = sharded(xs, ys, zs, bits)
        return tuple(c[:1] for c in out)

    return rlc_sum


@functools.cache
def build_sharded_bls(n_devices: int):
    devices = np.array(jax.devices()[:n_devices])
    mesh = Mesh(devices, ("batch",))
    fn = sharded_rlc_g1_fn(mesh)
    sharding = NamedSharding(mesh, P("batch"))
    return mesh, fn, sharding


def dryrun_sharded_bls(mesh: Mesh) -> None:
    """One tiny sharded RLC step on `mesh`, cross-checked against the host
    bigint oracle. Raises on mismatch."""
    import random

    from ..crypto.bls12_381 import FQ, G1_GEN, pt_eq, pt_mul
    from ..crypto.bls12_381.curve import inf, pt_add as host_pt_add
    from .bls381 import g1_points_from_device, g1_points_to_device, scalars_to_bits

    n_devices = mesh.devices.size
    n = n_devices  # one point per device: the smallest real shard
    rng = random.Random(1234)
    pts = [pt_mul(FQ, G1_GEN, rng.randrange(1, 1 << 30)) for _ in range(n)]
    scalars = [rng.getrandbits(64) for _ in range(n)]

    fn = sharded_rlc_g1_fn(mesh)
    sharding = NamedSharding(mesh, P("batch"))
    xs, ys, zs = g1_points_to_device(pts)
    xs, ys, zs = (jax.device_put(c, sharding) for c in (xs, ys, zs))
    bits = jax.device_put(
        jnp.asarray(scalars_to_bits(scalars, 64)), sharding
    )
    got = g1_points_from_device(fn(xs, ys, zs, bits))[0]

    want = inf(FQ)
    for p, s in zip(pts, scalars):
        want = host_pt_add(FQ, want, pt_mul(FQ, p, s))
    assert pt_eq(FQ, got, want), "sharded RLC G1 sum mismatch vs host"
