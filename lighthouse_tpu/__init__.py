"""lighthouse_tpu: a TPU-native Ethereum consensus (beacon chain) framework.

A from-scratch re-design of the capabilities of the Lighthouse consensus client
(reference: jimmygchen/lighthouse) for TPU hardware: the batch-heavy work —
BLS12-381 batch signature verification, SSZ Merkleization, KZG blob proofs —
runs on device via JAX/XLA (Pallas where it pays), while spec logic, fork
choice, storage and networking live on the host.

Layering (mirrors reference layer map, SURVEY.md §1):
  utils/ ops/ parallel/   – hashing, device kernels, mesh/sharding helpers
  ssz/                    – SSZ serialization + Merkleization (ethereum_ssz, tree_hash)
  crypto/                 – BLS12-381 + KZG (crypto/bls, crypto/kzg)
  types/                  – consensus containers, EthSpec/ChainSpec (consensus/types)
  state_processing/       – state transition (consensus/state_processing)
  fork_choice/            – proto-array fork choice (consensus/{fork_choice,proto_array})
  store/                  – hot/cold storage (beacon_node/store)
  beacon_chain/           – chain orchestration (beacon_node/beacon_chain)
"""

__version__ = "0.1.0"
