"""Validator + account management (validator_manager / account_manager
analogs): create validators from an EIP-2333 seed (EIP-2334 paths), write
EIP-2335 keystores + deposit data, import/list keystores in a validator
directory. Driven by the `vm` CLI subcommands."""

from __future__ import annotations

import json
import os
import pathlib

import contextlib

from .crypto import bls
from .crypto.key_derivation import derive_sk_from_path, validator_keypair_path
from .crypto.keystore import Keystore


@contextlib.contextmanager
def _host_backend():
    """Key management needs real curve ops; restore the caller's backend
    after (mutating the process-global backend out from under a running
    chain breaks its verification)."""
    prev = bls.backend_name()
    bls.set_backend("host")
    try:
        yield
    finally:
        bls.set_backend(prev)


def create_validators(
    seed: bytes,
    count: int,
    out_dir: str | os.PathLike,
    password: str,
    first_index: int = 0,
    amount_gwei: int = 32_000_000_000,
    spec=None,
    E=None,
    fast_kdf: bool = False,
) -> list[dict]:
    """Derive `count` validators, write keystore-<pubkey>.json files and a
    deposit_data.json; returns the deposit-data records."""
    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    records = []
    with _host_backend():
        _create_all(seed, count, out, password, first_index, amount_gwei,
                    spec, E, fast_kdf, records)
    with open(out / "deposit_data.json", "w") as f:
        json.dump(records, f, indent=2)
    return records


def _create_all(seed, count, out, password, first_index, amount_gwei, spec, E,
                fast_kdf, records):
    for i in range(first_index, first_index + count):
        path = validator_keypair_path(i, "signing")
        sk_int = derive_sk_from_path(seed, path)
        sk = bls.SecretKey(sk_int)
        pk = sk.public_key()
        ks = Keystore.encrypt(
            sk.to_bytes(), password, path=path, _fast_kdf=fast_kdf
        )
        ks.save(out / f"keystore-{pk.to_bytes().hex()[:16]}.json")
        record = {
            "pubkey": pk.to_bytes().hex(),
            "withdrawal_credentials": None,
            "amount": amount_gwei,
            "path": path,
        }
        if spec is not None and E is not None:
            from .state_processing.genesis import build_deposit_data

            class _KP:  # build_deposit_data takes a keypair-shaped object
                pass

            kp = _KP()
            kp.sk, kp.pk = sk, pk
            data = build_deposit_data(kp, amount_gwei, spec, E)
            record["withdrawal_credentials"] = bytes(
                data.withdrawal_credentials
            ).hex()
            record["signature"] = bytes(data.signature).hex()
            record["deposit_data_root"] = data.hash_tree_root().hex()
        records.append(record)


def list_validators(dir_path: str | os.PathLike) -> list[dict]:
    out = []
    for p in sorted(pathlib.Path(dir_path).glob("keystore-*.json")):
        ks = Keystore.load(p)
        out.append({"pubkey": ks.pubkey.hex(), "path": ks.path, "file": p.name})
    return out


def import_keystore(
    keystore_path: str | os.PathLike,
    password: str,
    validators_dir: str | os.PathLike,
) -> bytes:
    """Validate the password and copy the keystore into the validator dir
    (returns the pubkey)."""
    ks = Keystore.load(keystore_path)
    ks.decrypt(password)  # raises on wrong password
    dest = pathlib.Path(validators_dir)
    dest.mkdir(parents=True, exist_ok=True)
    ks.save(dest / f"keystore-{ks.pubkey.hex()[:16]}.json")
    return ks.pubkey


def load_signers(dir_path: str | os.PathLike, password: str):
    """Decrypt every keystore in a directory into (pubkey, SecretKey)
    pairs — what a VC start-up does."""
    out = []
    with _host_backend():
        for p in sorted(pathlib.Path(dir_path).glob("keystore-*.json")):
            ks = Keystore.load(p)
            secret = ks.decrypt(password)
            out.append((ks.pubkey, bls.SecretKey.from_bytes(secret)))
    return out
