"""Off-node analytics service (the `watch` crate analog).

An updater polls a beacon node over the HTTP API and records canonical
slots, proposers, and finality progress into sqlite (the reference uses
postgres/diesel); query helpers compute the per-proposer block counts,
missed-slot lists, and participation the reference's REST server exposes
(watch/src/{updater,database,server})."""

from __future__ import annotations

import sqlite3
import threading

from ..eth2 import BeaconNodeHttpClient
from ..utils.http_server import JsonHttpServer, JsonRequestHandler
from .blockprint import classify_block


class WatchDB:
    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS canonical_slots ("
            "slot INTEGER PRIMARY KEY, root BLOB, proposer INTEGER, "
            "skipped INTEGER NOT NULL DEFAULT 0)"
        )
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS finality ("
            "checked_at_slot INTEGER PRIMARY KEY, "
            "justified_epoch INTEGER, finalized_epoch INTEGER)"
        )
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS gaps ("
            "lo INTEGER, hi INTEGER)"
        )
        # block-packing + participation analytics (watch's block_packing /
        # suboptimal_attestations tables)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS block_packing ("
            "slot INTEGER PRIMARY KEY, attestation_count INTEGER, "
            "attester_votes INTEGER, sync_bits INTEGER, sync_size INTEGER)"
        )
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS suboptimal_attestations ("
            "att_slot INTEGER, included_at INTEGER, delay INTEGER, "
            "PRIMARY KEY (att_slot, included_at))"
        )
        # blockprint (client-fingerprint) per proposal (watch/src/blockprint)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS blockprint ("
            "slot INTEGER PRIMARY KEY, best_guess TEXT, el_guess TEXT, "
            "graffiti TEXT)"
        )
        # per-block proposer rewards (watch's block_rewards table)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS block_rewards ("
            "slot INTEGER PRIMARY KEY, proposer INTEGER, total INTEGER, "
            "attestations INTEGER, sync_aggregate INTEGER)"
        )
        self._conn.commit()

    def record_gap(self, lo: int, hi: int):
        """History the node could not serve — these slots stay unrecorded
        and queries over them are knowingly incomplete."""
        with self._lock:
            self._conn.execute("INSERT INTO gaps VALUES (?, ?)", (lo, hi))
            self._conn.commit()

    def gaps(self) -> list[tuple[int, int]]:
        with self._lock:
            return self._conn.execute(
                "SELECT lo, hi FROM gaps ORDER BY lo"
            ).fetchall()

    def record_slot(self, slot: int, root: bytes | None, proposer: int | None):
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO canonical_slots VALUES (?, ?, ?, ?)",
                (slot, root, proposer, 1 if root is None else 0),
            )
            self._conn.commit()

    def record_finality(self, at_slot: int, justified: int, finalized: int):
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO finality VALUES (?, ?, ?)",
                (at_slot, justified, finalized),
            )
            self._conn.commit()

    # -- queries (server.rs routes) -------------------------------------------

    def proposer_counts(self) -> dict[int, int]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT proposer, COUNT(*) FROM canonical_slots "
                "WHERE skipped = 0 GROUP BY proposer"
            ).fetchall()
        return {p: c for p, c in rows}

    def missed_slots(self) -> list[int]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT slot FROM canonical_slots WHERE skipped = 1 "
                "ORDER BY slot"
            ).fetchall()
        return [r[0] for r in rows]

    def latest_finality(self) -> tuple[int, int] | None:
        with self._lock:
            return self._conn.execute(
                "SELECT justified_epoch, finalized_epoch FROM finality "
                "ORDER BY checked_at_slot DESC LIMIT 1"
            ).fetchone()

    def highest_slot(self) -> int:
        with self._lock:
            row = self._conn.execute(
                "SELECT MAX(slot) FROM canonical_slots"
            ).fetchone()
        return row[0] if row[0] is not None else -1

    def record_packing(
        self, slot: int, att_count: int, attester_votes: int,
        sync_bits: int, sync_size: int, suboptimal_rows=(),
    ):
        """One transaction per block: the packing row plus its suboptimal
        attestations (idempotent — re-walked boundary blocks replace
        rather than duplicate)."""
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO block_packing VALUES (?, ?, ?, ?, ?)",
                (slot, att_count, attester_votes, sync_bits, sync_size),
            )
            self._conn.executemany(
                "INSERT OR REPLACE INTO suboptimal_attestations "
                "VALUES (?, ?, ?)",
                list(suboptimal_rows),
            )
            self._conn.commit()

    def packing_stats(self) -> dict:
        """Aggregate block-packing view (server.rs block_packing route).
        Returns the suboptimal count from the SAME locked snapshot so the
        REST response is internally consistent vs a concurrent updater."""
        with self._lock:
            sub = self._conn.execute(
                "SELECT COUNT(*) FROM suboptimal_attestations"
            ).fetchone()[0]
            row = self._conn.execute(
            "SELECT COUNT(*), AVG(attestation_count), AVG(attester_votes), "
            "AVG(CAST(sync_bits AS REAL) / NULLIF(sync_size, 0)) "
                "FROM block_packing"
            ).fetchone()
        return {
            "blocks": row[0],
            "avg_attestations": row[1] or 0.0,
            "avg_attester_votes": row[2] or 0.0,
            "avg_sync_participation": row[3] or 0.0,
            "suboptimal_attestations": sub,
        }

    def has_block_between(self, lo: int, hi: int) -> bool:
        """Any recorded canonical block at a slot in (lo, hi) exclusive —
        re-walks consult this for history outside the fresh walk."""
        with self._lock:
            row = self._conn.execute(
                "SELECT 1 FROM canonical_slots WHERE skipped = 0 "
                "AND slot > ? AND slot < ? LIMIT 1",
                (lo, hi),
            ).fetchone()
        return row is not None

    def record_blockprint(self, slot: int, print_: dict):
        with self._lock:
            self._conn.execute(
                "INSERT OR IGNORE INTO blockprint VALUES (?, ?, ?, ?)",
                (
                    slot,
                    print_["best_guess"],
                    print_.get("el_guess"),
                    print_.get("graffiti", ""),
                ),
            )
            self._conn.commit()

    def blockprint_for_slot(self, slot: int) -> dict | None:
        with self._lock:
            row = self._conn.execute(
                "SELECT best_guess, el_guess, graffiti FROM blockprint "
                "WHERE slot = ?",
                (slot,),
            ).fetchone()
        if row is None:
            return None
        return {"best_guess": row[0], "el_guess": row[1], "graffiti": row[2]}

    def blockprint_shares(self) -> dict[str, int]:
        """Proposal counts per guessed client (the blockprint aggregate)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT best_guess, COUNT(*) FROM blockprint GROUP BY best_guess"
            ).fetchall()
        return {guess: count for guess, count in rows}

    def record_block_rewards(self, slot: int, rewards: dict):
        with self._lock:
            self._conn.execute(
                "INSERT OR IGNORE INTO block_rewards VALUES (?, ?, ?, ?, ?)",
                (
                    slot,
                    int(rewards["proposer_index"]),
                    int(rewards["total"]),
                    int(rewards["attestations"]),
                    int(rewards["sync_aggregate"]),
                ),
            )
            self._conn.commit()

    def has_block_rewards(self, slot: int) -> bool:
        with self._lock:
            return (
                self._conn.execute(
                    "SELECT 1 FROM block_rewards WHERE slot = ?", (slot,)
                ).fetchone()
                is not None
            )

    def rewards_stats(self) -> dict:
        """Aggregate proposer-reward analytics (watch's rewards queries)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT COUNT(*), COALESCE(SUM(total), 0), "
                "COALESCE(AVG(total), 0) FROM block_rewards"
            ).fetchone()
            per_proposer = self._conn.execute(
                "SELECT proposer, SUM(total) FROM block_rewards "
                "GROUP BY proposer"
            ).fetchall()
        return {
            "blocks": row[0],
            "total_gwei": int(row[1]),
            "mean_gwei": round(row[2], 1),
            "per_proposer": {str(p): int(t) for p, t in per_proposer},
        }

    def suboptimal_attestation_count(self) -> int:
        with self._lock:
            return self._conn.execute(
                "SELECT COUNT(*) FROM suboptimal_attestations"
            ).fetchone()[0]


class WatchUpdater:
    """Polls the node and fills the DB (updater.rs)."""

    def __init__(self, client: BeaconNodeHttpClient, db: WatchDB, types):
        self.client = client
        self.db = db
        self.types = types
        # rewards fetches that failed transiently: slot -> block root,
        # retried on every update (a permanent 4xx drops the entry)
        self._rewards_retry: dict[int, bytes] = {}

    def update(self) -> int:
        """Walk new canonical slots up to the node's head; returns how many
        slots were recorded."""
        syncing = self.client.get_syncing()
        head_slot = int(syncing["head_slot"])
        # slot 0 is genesis, not a proposal
        start = max(self.db.highest_slot() + 1, 1)
        if start > head_slot:
            return 0
        # walk the canonical chain backward from head to `start`
        blocks_by_slot: dict[int, tuple] = {}
        packing_jobs: list = []
        data = self.client.get_block_ssz("head")
        signed = self.types.decode_by_fork("SignedBeaconBlock", data)
        walk_complete = False
        while True:
            slot = int(signed.message.slot)
            blocks_by_slot[slot] = (
                signed.message.hash_tree_root(),
                int(signed.message.proposer_index),
            )
            packing_jobs.append(signed)
            parent = bytes(signed.message.parent_root)
            if slot <= max(start, 1) or parent == b"\x00" * 32:
                walk_complete = True
                break
            try:
                data = self.client.get_block_ssz("0x" + parent.hex())
            except Exception:  # noqa: BLE001 — history beyond the hot cache
                break
            signed = self.types.decode_by_fork("SignedBeaconBlock", data)

        # A slot with no block is only PROVABLY skipped when the walk
        # reached below it — an incomplete walk must leave a hole, never
        # record real proposals as missed (rows are write-once).
        certainty_floor = start if walk_complete else min(blocks_by_slot)
        if certainty_floor > start:
            # the hole is permanent (rows advance past it); record it so
            # queries are explicitly known-incomplete instead of silently so
            self.db.record_gap(start, certainty_floor - 1)
        recorded = 0
        for slot in range(start, head_slot + 1):
            ent = blocks_by_slot.get(slot)
            if ent is not None:
                self.db.record_slot(slot, ent[0], ent[1])
            elif slot >= certainty_floor:
                self.db.record_slot(slot, None, None)  # skipped slot
            else:
                continue  # hole: history unavailable, leave unrecorded
            recorded += 1
        for signed in packing_jobs:
            self._record_packing(signed, blocks_by_slot)
            slot = int(signed.message.slot)
            if self.db.blockprint_for_slot(slot) is None:
                self.db.record_blockprint(slot, classify_block(signed))
            if not self.db.has_block_rewards(slot):
                # root already computed during the walk — no re-merkleize
                self._fetch_rewards(slot, blocks_by_slot[slot][0])
        for slot, root in list(self._rewards_retry.items()):
            if self.db.has_block_rewards(slot):
                self._rewards_retry.pop(slot, None)
            else:
                self._fetch_rewards(slot, root)
        fin = self.client.get_finality_checkpoints("head")
        self.db.record_finality(
            head_slot,
            int(fin["current_justified"]["epoch"]),
            int(fin["finalized"]["epoch"]),
        )
        return recorded

    def _fetch_rewards(self, slot: int, root: bytes):
        """Pull per-block rewards from the node. Permanent refusals (4xx:
        pre-Altair block or parent state beyond the node's window) are
        dropped; transient failures are queued for retry on the next
        update so no silent permanent hole forms."""
        import urllib.error

        try:
            self.db.record_block_rewards(
                slot, self.client.get_block_rewards("0x" + root.hex())
            )
            self._rewards_retry.pop(slot, None)
        except urllib.error.HTTPError as e:
            if 400 <= e.code < 500:
                self._rewards_retry.pop(slot, None)  # permanent: give up
            else:
                self._rewards_retry[slot] = root
        except Exception:  # noqa: BLE001 — analytics must not wedge updates
            self._rewards_retry[slot] = root

    def _record_packing(self, signed, blocks_by_slot):
        """Per-block packing + suboptimal-attestation analytics
        (updater's block_packing / attestation passes). An attestation is
        suboptimal only when an EARLIER canonical block could have carried
        it — skipped slots between its slot and its inclusion don't count
        against it."""
        m = signed.message
        body = m.body
        att_count = len(body.attestations)
        votes = sum(sum(a.aggregation_bits) for a in body.attestations)
        agg = getattr(body, "sync_aggregate", None)
        sync_bits = sum(agg.sync_committee_bits) if agg is not None else 0
        sync_size = len(agg.sync_committee_bits) if agg is not None else 0
        suboptimal = [
            (int(a.data.slot), int(m.slot), int(m.slot) - int(a.data.slot))
            for a in body.attestations
            if int(m.slot) - int(a.data.slot) > 1
            and (
                any(
                    s in blocks_by_slot
                    for s in range(int(a.data.slot) + 1, int(m.slot))
                )
                # slots below the fresh walk live in the DB from earlier
                # runs — without this, re-walked boundary blocks would
                # REPLACE correct rows with false "optimal"
                or self.db.has_block_between(int(a.data.slot), int(m.slot))
            )
        ]
        self.db.record_packing(
            int(m.slot), att_count, votes, sync_bits, sync_size,
            suboptimal_rows=suboptimal,
        )


class WatchServer(JsonHttpServer):
    """REST surface over the DB (watch/src/server): /v1/slots/missed,
    /v1/proposers, /v1/finality, /v1/packing, /v1/gaps."""

    def __init__(self, db: WatchDB, port: int = 0):
        watch_db = db

        class _Handler(JsonRequestHandler):
            def do_GET(self):
                routes = {
                    "/v1/slots/missed": lambda: watch_db.missed_slots(),
                    "/v1/proposers": lambda: {
                        str(k): v for k, v in watch_db.proposer_counts().items()
                    },
                    "/v1/finality": lambda: watch_db.latest_finality(),
                    "/v1/packing": lambda: watch_db.packing_stats(),
                    "/v1/gaps": lambda: watch_db.gaps(),
                    "/v1/blockprint": lambda: watch_db.blockprint_shares(),
                    "/v1/rewards": lambda: watch_db.rewards_stats(),
                }
                fn = routes.get(self.route)
                if fn is None:
                    return self.send_json({"error": "not found"}, 404)
                try:
                    return self.send_json(fn())
                except Exception as e:  # noqa: BLE001 — 500, not a reset
                    return self.send_json({"error": str(e)}, 500)

        super().__init__(_Handler, port=port, name="watch-server")
