"""Blockprint: client-fingerprint classification of proposals.

The reference's watch integrates with the external `blockprint` ML
service (watch/src/blockprint/) that guesses which consensus client
built each block and aggregates per-client proposal shares. With zero
egress, this module ships the in-process analog: a transparent
heuristic classifier over the block's observable fingerprints (graffiti
conventions and, post-merge, the execution payload's extra_data, which
builders/ELs stamp) feeding the same per-slot table + aggregate the
reference's `/v1/blocks/{slot}/blockprint` style queries expose.

The labels use the public client names; classification confidence is
honest — anything unrecognized is "Unknown" rather than a forced guess.
"""

from __future__ import annotations

# (label, lowercase graffiti/extra-data markers) — the well-known public
# self-identification conventions each client ships by default
_MARKERS = [
    # most-specific first: "lighthouse-tpu" must win over its substring
    ("LighthouseTPU", (b"lighthouse-tpu", b"lighthouse_tpu")),
    ("Lighthouse", (b"lighthouse",)),
    ("Prysm", (b"prysm",)),
    ("Teku", (b"teku",)),
    ("Nimbus", (b"nimbus",)),
    ("Lodestar", (b"lodestar",)),
    ("Grandine", (b"grandine",)),
]

# execution-layer extra_data stamps (geth/nethermind/besu/erigon/reth) —
# identify the EL, which watch records alongside the CL guess
_EL_MARKERS = [
    ("Geth", (b"geth",)),
    ("Nethermind", (b"nethermind",)),
    ("Besu", (b"besu",)),
    ("Erigon", (b"erigon",)),
    ("Reth", (b"reth",)),
]


def _scan(data: bytes, markers) -> str | None:
    low = bytes(data).lower()
    for label, needles in markers:
        if any(n in low for n in needles):
            return label
    return None


def classify_block(signed_block) -> dict:
    """Best-guess fingerprint for one signed beacon block.

    Returns {"best_guess": str, "el_guess": str | None, "graffiti": str}.
    """
    body = signed_block.message.body
    graffiti = bytes(signed_block.message.body.graffiti)
    guess = _scan(graffiti, _MARKERS)
    el_guess = None
    payload = getattr(body, "execution_payload", None)
    if payload is not None:
        el_guess = _scan(bytes(payload.extra_data), _EL_MARKERS)
        if guess is None:
            # some setups stamp the CL name into extra_data instead
            guess = _scan(bytes(payload.extra_data), _MARKERS)
    return {
        "best_guess": guess or "Unknown",
        "el_guess": el_guess,
        "graffiti": graffiti.rstrip(b"\x00").decode("utf-8", "replace"),
    }
