"""Beacon API HTTP client (common/eth2 analog).

The client side of http_api, used by the HTTP-backed validator client,
checkpoint sync, and tooling. JSON for queries, SSZ for states/blocks
(Accept/Content-Type: application/octet-stream), matching the reference's
`BeaconNodeHttpClient` surface (common/eth2/src/lib.rs)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request


class ApiClientError(RuntimeError):
    def __init__(self, code: int, message: str):
        super().__init__(f"HTTP {code}: {message}")
        self.code = code


class BeaconNodeHttpClient:
    def __init__(self, base_url: str, timeout: float = 30.0):
        self.base = base_url.rstrip("/")
        self.timeout = timeout

    # -- plumbing -------------------------------------------------------------

    def _get(self, path: str, ssz: bool = False):
        req = urllib.request.Request(self.base + path)
        if ssz:
            req.add_header("Accept", "application/octet-stream")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                data = resp.read()
                if ssz or "json" not in resp.headers.get("Content-Type", ""):
                    return data
                return json.loads(data)
        except urllib.error.HTTPError as e:
            raise ApiClientError(e.code, e.read().decode(errors="replace")) from e

    def _post(self, path: str, body: bytes, content_type: str):
        req = urllib.request.Request(
            self.base + path,
            data=body,
            headers={"Content-Type": content_type},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status
        except urllib.error.HTTPError as e:
            raise ApiClientError(e.code, e.read().decode(errors="replace")) from e

    # -- node -----------------------------------------------------------------

    def get_health(self) -> bool:
        try:
            self._get("/eth/v1/node/health")
            return True
        except (ApiClientError, OSError):
            return False

    def get_version(self) -> str:
        return self._get("/eth/v1/node/version")["data"]["version"]

    def get_syncing(self) -> dict:
        return self._get("/eth/v1/node/syncing")["data"]

    # -- beacon ----------------------------------------------------------------

    def get_genesis(self) -> dict:
        return self._get("/eth/v1/beacon/genesis")["data"]

    def get_state_root(self, state_id: str = "head") -> bytes:
        data = self._get(f"/eth/v1/beacon/states/{state_id}/root")["data"]
        return bytes.fromhex(data["root"].removeprefix("0x"))

    def get_finality_checkpoints(self, state_id: str = "head") -> dict:
        return self._get(
            f"/eth/v1/beacon/states/{state_id}/finality_checkpoints"
        )["data"]

    def get_state_ssz(self, state_id: str = "head") -> bytes:
        return self._get(f"/eth/v2/debug/beacon/states/{state_id}", ssz=True)

    def get_block_ssz(self, block_id: str = "head") -> bytes:
        return self._get(f"/eth/v2/beacon/blocks/{block_id}", ssz=True)

    def get_proposer_duties(self, epoch: int) -> list[dict]:
        return self._get(f"/eth/v1/validator/duties/proposer/{epoch}")["data"]

    def get_block_rewards(self, block_id: str) -> dict:
        return self._get(f"/eth/v1/beacon/rewards/blocks/{block_id}")["data"]

    # -- validator -------------------------------------------------------------

    def produce_block_ssz(self, slot: int, randao_reveal: bytes) -> bytes:
        return self._get(
            f"/eth/v3/validator/blocks/{slot}?randao_reveal=0x{randao_reveal.hex()}",
            ssz=True,
        )

    def publish_block_ssz(self, data: bytes) -> int:
        return self._post(
            "/eth/v1/beacon/blocks", data, "application/octet-stream"
        )

    def publish_attestations_ssz(self, data: bytes) -> int:
        return self._post(
            "/eth/v1/beacon/pool/attestations", data, "application/octet-stream"
        )

    def publish_sync_committee_messages_ssz(self, data: bytes) -> int:
        return self._post(
            "/eth/v1/beacon/pool/sync_committees",
            data,
            "application/octet-stream",
        )

    def get_aggregate_attestation_ssz(self, slot: int, data_root: bytes) -> bytes:
        return self._get(
            "/eth/v1/validator/aggregate_attestation"
            f"?slot={int(slot)}&attestation_data_root=0x{bytes(data_root).hex()}",
            ssz=True,
        )

    def publish_aggregate_and_proofs_ssz(self, data: bytes) -> int:
        return self._post(
            "/eth/v1/validator/aggregate_and_proofs",
            data,
            "application/octet-stream",
        )

    def prepare_beacon_proposer(self, preparations: list[dict]) -> int:
        import json as _json

        return self._post(
            "/eth/v1/validator/prepare_beacon_proposer",
            _json.dumps(preparations).encode(),
            "application/json",
        )


class HttpBeaconNode:
    """validator_client BeaconNodeInterface over HTTP — the VC's real
    transport (the LocalBeaconNode stand-in talks to the chain object
    directly)."""

    def __init__(self, client: BeaconNodeHttpClient, types):
        self.client = client
        self.types = types

    def head_state(self):
        data = self.client.get_state_ssz("head")
        return self.types.decode_by_fork("BeaconState", data)

    def head_root(self):
        data = self._header_root()
        return data

    def _header_root(self):
        blk = self.client._get("/eth/v1/beacon/headers/head")
        return bytes.fromhex(blk["data"]["root"].removeprefix("0x"))

    def publish_block(self, signed_block):
        self.client.publish_block_ssz(signed_block.serialize())
        return signed_block.message.hash_tree_root()

    def publish_attestations(self, attestations):
        from ..ssz.core import List as SszList

        t = self.types
        data = SszList[t.Attestation, 1024].serialize_value(list(attestations))
        return self.client.publish_attestations_ssz(data)

    def produce_block(self, slot: int, randao_reveal: bytes):
        data = self.client.produce_block_ssz(slot, randao_reveal)
        return self.types.decode_by_fork("BeaconBlock", data)

    def publish_sync_committee_messages(self, messages):
        from ..ssz.core import List as SszList

        t = self.types
        data = SszList[t.SyncCommitteeMessage, 1024].serialize_value(
            list(messages)
        )
        return self.client.publish_sync_committee_messages_ssz(data)

    def get_aggregate(self, data):
        try:
            raw = self.client.get_aggregate_attestation_ssz(
                int(data.slot), data.hash_tree_root()
            )
        except ApiClientError as e:
            if e.code == 404:
                return None
            raise
        return self.types.Attestation.deserialize(raw)

    def publish_aggregates(self, signed_aggregates):
        """Returns a per-item result list like LocalBeaconNode (HTTP gives
        one batch status; a 2xx means the batch was accepted)."""
        from ..ssz.core import List as SszList

        t = self.types
        aggs = list(signed_aggregates)
        data = SszList[t.SignedAggregateAndProof, 1024].serialize_value(aggs)
        self.client.publish_aggregate_and_proofs_ssz(data)
        return [None] * len(aggs)

    def prepare_proposers(self, preparations: dict[int, bytes]):
        return self.client.prepare_beacon_proposer(
            [
                {
                    "validator_index": str(vi),
                    "fee_recipient": "0x" + bytes(fr).hex(),
                }
                for vi, fr in preparations.items()
            ]
        )
