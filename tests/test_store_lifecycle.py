"""Storage lifecycle subsystem (store/migrator.py + from_store restart).

Mirrors beacon_node/store migration tests: finality advances the
hot/cold split and prunes hot states, canonical restore-point states
land in the COLD db and pre-split states reconstruct bit-identically by
replay, the anchor watermark lets a node restart from its KV store, and
the range-sync/backfill watermarks mean a restarted node re-downloads
ZERO already-stored batches."""

import threading
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.chain import BeaconChain
from lighthouse_tpu.beacon_chain.checkpoint_sync import (
    CheckpointSyncError,
    checkpoint_boot,
    fetch_finalized_checkpoint,
)
from lighthouse_tpu.beacon_chain.harness import (
    HARNESS_GENESIS_TIME,
    BeaconChainHarness,
)
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.http_api import HttpApiServer
from lighthouse_tpu.http_api.block_index import BlockHeaderIndex
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.network import NetworkService
from lighthouse_tpu.network.sync.backfill import WATERMARK_KEY
from lighthouse_tpu.state_processing.accessors import (
    compute_start_slot_at_epoch,
)
from lighthouse_tpu.store import HotColdDB, MemoryStore, open_hot_cold
from lighthouse_tpu.store.kv import DBColumn
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
from lighthouse_tpu.utils.slot_clock import ManualSlotClock

S = E.SLOTS_PER_EPOCH


def _spec():
    return replace(minimal_spec(), altair_fork_epoch=0)


def _harness(store=None, migrate=True, epochs=5):
    bls.set_backend("fake_crypto")
    h = BeaconChainHarness(_spec(), E, validator_count=16, store=store)
    h.chain.migrator.enabled = migrate
    h.extend_chain(epochs * S)
    return h


def _canonical_roots(chain):
    """Canonical (root, block) pairs walked by parent links from head."""
    out = []
    r = chain.head_root
    while True:
        blk = chain._blocks_by_root.get(r) or chain.store.get_block(r)
        if blk is None or blk.message.slot == 0:
            break
        out.append((r, blk))
        r = bytes(blk.message.parent_root)
    return out


@pytest.fixture()
def migrated():
    h = _harness()
    assert h.finalized_epoch >= 2
    return h


# -- migration cycle ----------------------------------------------------------


def test_finality_advances_split_and_prunes_hot_states(migrated):
    chain = migrated.chain
    store = chain.store
    split = compute_start_slot_at_epoch(
        chain.finalized_checkpoint.epoch, E
    )
    assert store.split_slot == split
    # every hot-cached state is at/after the split — pre-split states
    # were pruned (restore points went cold first)
    assert all(int(st.slot) >= split for st in chain._states.values())
    # migrated canonical blocks are served from the store
    for root, blk in _canonical_roots(chain):
        if blk.message.slot < split:
            assert store.get_block(root) is not None
    assert REGISTRY.counter("store_migrations_total").value() >= 1
    assert store.generation >= 1


def test_restore_points_written_to_cold(migrated):
    chain = migrated.chain
    store = chain.store
    spacing = chain.migrator.slots_per_restore_point
    split = store.split_slot
    cold_states, _ = store.cold.stats(DBColumn.BEACON_STATE)
    assert cold_states >= 1
    # each pruned canonical restore-point slot has its state in COLD,
    # retrievable by the block's advertised state root
    for root, blk in _canonical_roots(chain):
        slot = int(blk.message.slot)
        if slot < split and slot % spacing == 0:
            raw = store.cold.get(
                DBColumn.BEACON_STATE, bytes(blk.message.state_root)
            )
            assert raw is not None, f"restore point missing at slot {slot}"


def test_pre_split_state_reconstructs_bit_identically(migrated):
    chain = migrated.chain
    split = chain.store.split_slot
    # a pre-split block OFF the restore-point grid forces actual replay
    spacing = chain.migrator.slots_per_restore_point
    victims = [
        (r, b)
        for r, b in _canonical_roots(chain)
        if b.message.slot < split and int(b.message.slot) % spacing != 0
    ]
    assert victims
    root, blk = victims[0]
    before = REGISTRY.counter("store_states_reconstructed_total").value()
    state = chain.state_for_block_root(root)
    assert state is not None
    # replay re-anchors on the block's own state-root commitment
    assert state.hash_tree_root() == bytes(blk.message.state_root)
    after = REGISTRY.counter("store_states_reconstructed_total").value()
    assert after == before + 1
    # second read is an LRU hit: no new reconstruction
    assert chain.state_for_block_root(root) is state
    assert (
        REGISTRY.counter("store_states_reconstructed_total").value() == after
    )


def test_reconstruction_differential_vs_never_pruned_store():
    """The acceptance differential: the same pre-split states read off a
    migrated store and off a never-pruned one (migrator disabled — the
    A/B seam) hash identically."""
    ha = _harness(migrate=True)
    hb = _harness(migrate=False)
    assert ha.chain.head_root == hb.chain.head_root
    assert hb.chain.store.split_slot == 0  # B never migrated
    split = ha.chain.store.split_slot
    assert split > 0
    checked = 0
    for root, blk in _canonical_roots(ha.chain):
        if not 0 < blk.message.slot < split:
            continue
        # every pre-split slot, including those BELOW the first restore
        # point — that span replays from the pinned genesis state whose
        # block is synthetic (the root→state mapping has no stored block)
        sa = ha.chain.state_for_block_root(root)
        sb = hb.chain.state_for_block_root(root)
        assert sa is not None, f"no reconstruction at slot {blk.message.slot}"
        assert sa.hash_tree_root() == sb.hash_tree_root()
        checked += 1
    assert checked >= split - 2


def test_anchor_watermark_and_fork_choice_snapshot_persisted(migrated):
    import json

    chain = migrated.chain
    fin = chain.finalized_checkpoint
    slot, block_root, state_root = chain.store.get_anchor_info()
    assert block_root == bytes(fin.root)
    fin_blk = chain._blocks_by_root[fin.root]
    assert slot == int(fin_blk.message.slot)
    assert state_root == bytes(fin_blk.message.state_root)
    # the anchor state is pinned COLD (survives all future pruning)
    assert chain.store.cold.get(DBColumn.BEACON_STATE, state_root) is not None
    snap = json.loads(chain.store.get_fork_choice_snapshot())
    assert snap["head_root"] == chain.head_root.hex()
    assert snap["finalized_epoch"] == int(fin.epoch)


def test_store_health_block_reports_split_and_columns(migrated):
    from lighthouse_tpu.metrics.system_health import process_health

    d = process_health(migrated.chain)
    st = d["store"]
    assert st["split_slot"] == migrated.chain.store.split_slot
    assert st["anchor_slot"] >= 1
    for side in ("hot", "cold"):
        assert st[side]["total_keys"] >= 1
        assert st[side]["total_bytes"] > 0
    assert st["cold"]["columns"]["beacon_block"]["keys"] >= 1


def test_migration_epoch_claim_is_atomic(migrated):
    m = migrated.chain.migrator
    top = m._last_migrated_epoch
    assert top == int(migrated.chain.finalized_checkpoint.epoch)
    assert not m._claim_epoch(top)  # re-claim refused
    assert m._claim_epoch(top + 1)
    m._unclaim_epoch(top + 1)  # refused submit path
    assert m._claim_epoch(top + 1)


# -- prune-while-serving (store-generation guards) ----------------------------


def test_block_index_retries_lookup_torn_by_migration(migrated, monkeypatch):
    """hot-map miss → store miss can tear across the hot-delete/cold-put
    handoff; the generation bump makes the index re-read the settled
    view instead of reporting the block gone."""
    chain = migrated.chain
    store = chain.store
    split = store.split_slot
    root, blk = next(
        (r, b) for r, b in _canonical_roots(chain) if b.message.slot < split
    )
    # a restarted node serves migrated history purely from the store
    chain._blocks_by_root.pop(root, None)
    index = BlockHeaderIndex(chain)
    real_get = store.get_block
    torn = {"n": 0}

    def get_block(r):
        if bytes(r) == root and torn["n"] == 0:
            torn["n"] += 1
            store.bump_generation()  # a migration batch ran underneath
            return None
        return real_get(r)

    monkeypatch.setattr(store, "get_block", get_block)
    got = index.block(root)
    assert got is not None
    assert got.message.hash_tree_root() == root
    assert torn["n"] == 1  # the torn read happened and was retried


def test_block_index_serves_through_concurrent_migration():
    """Directed concurrency: migration cycles run in a thread while the
    index syncs and serves every canonical header — no lookup may come
    back empty mid-batch."""
    h = _harness(migrate=False)  # build history, hold all prunes
    chain = h.chain
    canonical = _canonical_roots(chain)
    index = BlockHeaderIndex(chain)
    index.sync()
    chain.migrator.enabled = True
    failures = []

    def churn():
        try:
            chain.migrator.on_finality()  # runs the full cycle inline
        except Exception as e:  # noqa: BLE001 — surfaced below
            failures.append(e)

    t = threading.Thread(target=churn)
    t.start()
    try:
        for _ in range(20):
            index.sync()
            for root, _blk in canonical:
                assert index.header_entry(root) is not None
    finally:
        t.join()
    assert not failures
    assert chain.store.split_slot > 0  # the cycle really ran


# -- restart from the KV store ------------------------------------------------


def test_from_store_restart_resumes_chain(tmp_path):
    path = str(tmp_path / "db")
    h = _harness(store=open_hot_cold(path, "sqlite"))
    chain = h.chain
    assert h.finalized_epoch >= 2

    clock = ManualSlotClock(
        genesis_time=HARNESS_GENESIS_TIME,
        seconds_per_slot=h.spec.seconds_per_slot,
    )
    clock.set_slot(int(chain.head_state.slot))
    chain2 = BeaconChain.from_store(
        open_hot_cold(path, "sqlite"), h.spec, E, clock
    )
    assert chain2.head_root == chain.head_root
    assert int(chain2.finalized_checkpoint.epoch) == h.finalized_epoch
    anchor_slot, anchor_root, _sr = chain2.store.get_anchor_info()
    assert chain2.anchor_slot == anchor_slot
    assert chain2.genesis_block_root == anchor_root
    # pre-anchor history still serves (store + restore-point replay)
    pre = [
        (r, b)
        for r, b in _canonical_roots(chain)
        if b.message.slot < anchor_slot
    ]
    assert pre
    root, blk = pre[0]
    st = chain2.state_for_block_root(root)
    assert st is not None and st.hash_tree_root() == bytes(
        blk.message.state_root
    )


def test_from_store_refuses_anchorless_store():
    from lighthouse_tpu.beacon_chain.chain import BeaconChainError

    clock = ManualSlotClock(genesis_time=0, seconds_per_slot=12)
    with pytest.raises(BeaconChainError, match="anchor watermark"):
        BeaconChain.from_store(HotColdDB(MemoryStore()), _spec(), E, clock)


def test_restart_resumes_range_sync_without_redownload(tmp_path):
    """Kill a synced node mid-chain-growth; the restarted node's head
    resumes from the store and a fresh sync imports ONLY the new span."""
    a = _harness(epochs=3)
    path = str(tmp_path / "b")
    bls.set_backend("fake_crypto")
    hb = BeaconChainHarness(_spec(), E, validator_count=16,
                            store=open_hot_cold(path, "sqlite"))
    na = NetworkService(a.chain).start()
    nb = NetworkService(hb.chain).start()
    try:
        hb.slot_clock.set_slot(int(a.chain.head_state.slot))
        peer = nb.connect("127.0.0.1", na.port)
        assert nb.sync.sync_with(peer) > 0
        head_before = hb.chain.head_root
        assert head_before == a.chain.head_root
    finally:
        nb.stop()

    a.extend_chain(S)  # the chain grows while B is down
    clock = ManualSlotClock(
        genesis_time=HARNESS_GENESIS_TIME,
        seconds_per_slot=a.spec.seconds_per_slot,
    )
    clock.set_slot(int(a.chain.head_state.slot))
    chain_b2 = BeaconChain.from_store(
        open_hot_cold(path, "sqlite"), _spec(), E, clock
    )
    # restart resumed the pre-kill head — nothing to re-sync below it
    assert chain_b2.head_root == head_before
    nb2 = NetworkService(chain_b2).start()
    try:
        peer = nb2.connect("127.0.0.1", na.port)
        imported = nb2.sync.sync_with(peer)
        # only the new epoch's blocks, never the already-held span
        assert 0 < imported <= S + 1
        assert chain_b2.head_root == a.chain.head_root
    finally:
        nb2.stop()
        na.stop()


def test_restart_resumes_backfill_from_watermark(tmp_path):
    """Checkpoint-booted node backfills ONE batch, dies, restarts, and
    finishes — the persisted watermark means the two runs partition the
    span exactly (zero re-downloaded blocks)."""
    a = _harness(epochs=5)
    fin = a.chain.finalized_checkpoint
    anchor_block = a.chain._blocks_by_root[fin.root]
    anchor_state = a.chain._justified_state_provider(fin.root).copy()
    anchor_slot = int(anchor_block.message.slot)
    assert anchor_slot > 2 * S  # enough history for two backfill windows

    path = str(tmp_path / "b")
    clock = ManualSlotClock(
        genesis_time=HARNESS_GENESIS_TIME,
        seconds_per_slot=a.spec.seconds_per_slot,
    )
    clock.set_slot(int(a.chain.head_state.slot))
    chain_b = BeaconChain.from_checkpoint(
        open_hot_cold(path, "sqlite"), anchor_state, anchor_block,
        a.spec, E, clock,
    )
    na = NetworkService(a.chain).start()
    nb = NetworkService(chain_b).start()
    try:
        peer = nb.connect("127.0.0.1", na.port)
        stored1 = nb.sync.backfill(peer, max_batches=1)
        assert 0 < stored1 < anchor_slot - 1
        wm = chain_b.store.get_meta(WATERMARK_KEY)
        assert wm is not None  # the resume point is on disk
    finally:
        nb.stop()

    chain_b2 = BeaconChain.from_store(
        open_hot_cold(path, "sqlite"), a.spec, E, clock
    )
    nb2 = NetworkService(chain_b2).start()
    try:
        peer = nb2.connect("127.0.0.1", na.port)
        stored2 = nb2.sync.backfill(peer)
        # the two runs tile history exactly: slots 1..anchor-1, no overlap
        assert stored1 + stored2 == anchor_slot - 1
        # complete hash-linked history now served from B's store
        r = bytes(anchor_block.message.parent_root)
        walked = 0
        while r != b"\x00" * 32:
            blk = chain_b2.store.get_block(r)
            if blk is None:
                break
            walked += 1
            r = bytes(blk.message.parent_root)
        assert walked == anchor_slot - 1
    finally:
        nb2.stop()
        na.stop()


# -- peer checkpoint sync over the Beacon API ---------------------------------


def test_fetch_finalized_checkpoint_over_http(migrated):
    srv = HttpApiServer(migrated.chain).start()
    try:
        data = fetch_finalized_checkpoint(
            f"http://127.0.0.1:{srv.port}", E
        )
        fin = migrated.chain.finalized_checkpoint
        assert data.block_root == bytes(fin.root)
        assert data.finalized_epoch == int(fin.epoch)
        assert data.state.hash_tree_root() == bytes(
            data.block.message.state_root
        )
    finally:
        srv.stop()


def test_checkpoint_boot_anchors_on_peer_finality(migrated):
    srv = HttpApiServer(migrated.chain).start()
    try:
        chain = checkpoint_boot(
            f"http://127.0.0.1:{srv.port}",
            HotColdDB(MemoryStore()),
            migrated.spec,
            E,
        )
        fin = migrated.chain.finalized_checkpoint
        assert chain.head_root == bytes(fin.root)
        assert chain.anchor_slot == int(
            migrated.chain._blocks_by_root[fin.root].message.slot
        )
        # the boot stamped a restartable anchor watermark
        assert chain.store.get_anchor_info()[1] == bytes(fin.root)
    finally:
        srv.stop()


def test_checkpoint_sync_refuses_unfinalized_peer():
    bls.set_backend("fake_crypto")
    h = BeaconChainHarness(_spec(), E, validator_count=16)
    h.extend_chain(2)  # no finality yet
    srv = HttpApiServer(h.chain).start()
    try:
        with pytest.raises(CheckpointSyncError, match="finalized"):
            fetch_finalized_checkpoint(f"http://127.0.0.1:{srv.port}", E)
    finally:
        srv.stop()
