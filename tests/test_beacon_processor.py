"""BeaconProcessor work queues, batching, reprocessing, timer, executor."""

import threading
import time

from lighthouse_tpu.beacon_processor import (
    MAX_GOSSIP_ATTESTATION_BATCH_SIZE,
    BeaconProcessor,
    ReprocessQueue,
    WorkEvent,
    WorkType,
)
from lighthouse_tpu.utils.task_executor import ShutdownSignal, TaskExecutor


def test_priority_and_batching():
    proc = BeaconProcessor(num_workers=1)
    seen = []
    lock = threading.Lock()

    def single(item):
        with lock:
            seen.append(("single", item))

    def batch(items):
        with lock:
            seen.append(("batch", list(items)))

    # 100 attestations coalesce into batches of <= 64
    for i in range(100):
        assert proc.submit(WorkType.GOSSIP_ATTESTATION, i, batch)
    proc.submit(WorkType.GOSSIP_BLOCK, "blk", single)
    assert proc.drain()
    proc.shutdown()

    batches = [x for kind, x in seen if kind == "batch"]
    assert sum(len(b) for b in batches) == 100
    assert all(len(b) <= MAX_GOSSIP_ATTESTATION_BATCH_SIZE for b in batches)
    assert sorted(i for b in batches for i in b) == list(range(100))
    assert ("single", "blk") in seen


def test_queue_bound_backpressure():
    proc = BeaconProcessor(num_workers=1)
    blocker = threading.Event()

    def handler(items):
        blocker.wait(timeout=5)

    # fill the chain-segment queue (bound 64) while the worker is busy
    def slow(item):
        blocker.wait(timeout=5)

    accepted = sum(
        proc.submit(WorkType.CHAIN_SEGMENT, i, slow) for i in range(200)
    )
    assert accepted <= 66  # bound + in-flight slop
    blocker.set()
    proc.drain()
    proc.shutdown()


def test_reprocess_queue_block_and_slot():
    proc = BeaconProcessor(num_workers=1)
    rq = ReprocessQueue()
    seen = []

    def h(item):
        seen.append(item)

    ev = WorkEvent(WorkType.UNKNOWN_BLOCK_ATTESTATION, "att1", h)
    rq.hold_for_block(b"\x01" * 32, ev)
    rq.hold_for_slot(10, WorkEvent(WorkType.API_REQUEST, "early", h))

    assert rq.block_imported(b"\x01" * 32, proc) == 1
    assert rq.slot_started(9, proc) == 0
    assert rq.slot_started(10, proc) == 1
    proc.drain()
    proc.shutdown()
    assert sorted(seen) == ["att1", "early"]


def test_slot_timer_manual_tick():
    from lighthouse_tpu.beacon_chain.timer import SlotTimer
    from lighthouse_tpu.utils.slot_clock import ManualSlotClock

    clock = ManualSlotClock(genesis_time=0, seconds_per_slot=12)
    fired = []
    t = SlotTimer(clock, fired.append)
    clock.set_slot(3)
    assert t.tick()
    assert not t.tick()  # same slot: no double fire
    clock.set_slot(4)
    assert t.tick()
    assert fired == [3, 4]


def test_task_executor_critical_failure_triggers_shutdown():
    sig = ShutdownSignal()
    ex = TaskExecutor(sig)

    def boom():
        raise RuntimeError("died")

    ex.spawn(boom, "critical_service", critical=True)
    assert sig.wait(timeout=5)
    assert "critical_service" in sig.reason

    # non-critical failure does not shut down
    sig2 = ShutdownSignal()
    ex2 = TaskExecutor(sig2)
    ex2.spawn(boom, "optional_service", critical=False)
    ex2.join_all()
    assert not sig2.is_triggered()
