"""BeaconProcessor work queues, batching, reprocessing, timer, executor."""

import threading
import time

from lighthouse_tpu.beacon_processor import (
    MAX_GOSSIP_ATTESTATION_BATCH_SIZE,
    BeaconProcessor,
    ReprocessQueue,
    WorkEvent,
    WorkType,
)
from lighthouse_tpu.utils.task_executor import ShutdownSignal, TaskExecutor


def test_priority_and_batching():
    proc = BeaconProcessor(num_workers=1)
    seen = []
    lock = threading.Lock()

    def single(item):
        with lock:
            seen.append(("single", item))

    def batch(items):
        with lock:
            seen.append(("batch", list(items)))

    # 100 attestations coalesce into batches of <= 64
    for i in range(100):
        assert proc.submit(WorkType.GOSSIP_ATTESTATION, i, batch)
    proc.submit(WorkType.GOSSIP_BLOCK, "blk", single)
    assert proc.drain()
    proc.shutdown()

    batches = [x for kind, x in seen if kind == "batch"]
    assert sum(len(b) for b in batches) == 100
    assert all(len(b) <= MAX_GOSSIP_ATTESTATION_BATCH_SIZE for b in batches)
    assert sorted(i for b in batches for i in b) == list(range(100))
    assert ("single", "blk") in seen


def test_queue_bound_backpressure():
    proc = BeaconProcessor(num_workers=1)
    blocker = threading.Event()

    def handler(items):
        blocker.wait(timeout=5)

    # fill the chain-segment queue (bound 64) while the worker is busy
    def slow(item):
        blocker.wait(timeout=5)

    accepted = sum(
        proc.submit(WorkType.CHAIN_SEGMENT, i, slow) for i in range(200)
    )
    assert accepted <= 66  # bound + in-flight slop
    blocker.set()
    proc.drain()
    proc.shutdown()


def test_reprocess_queue_block_and_slot():
    proc = BeaconProcessor(num_workers=1)
    rq = ReprocessQueue()
    seen = []

    def h(item):
        seen.append(item)

    ev = WorkEvent(WorkType.UNKNOWN_BLOCK_ATTESTATION, "att1", h)
    rq.hold_for_block(b"\x01" * 32, ev)
    rq.hold_for_slot(10, WorkEvent(WorkType.API_REQUEST, "early", h))

    assert rq.block_imported(b"\x01" * 32, proc) == 1
    assert rq.slot_started(9, proc) == 0
    assert rq.slot_started(10, proc) == 1
    proc.drain()
    proc.shutdown()
    assert sorted(seen) == ["att1", "early"]


def test_reprocess_queue_per_root_and_total_caps():
    from lighthouse_tpu.metrics import REGISTRY

    rq = ReprocessQueue(per_root_cap=2, total_cap=5)

    def h(item):
        pass

    def ev(i):
        return WorkEvent(WorkType.UNKNOWN_BLOCK_ATTESTATION, i, h)

    root_cap_before = REGISTRY.counter("reprocess_expired_total").value(
        reason="root_cap"
    )
    total_cap_before = REGISTRY.counter("reprocess_expired_total").value(
        reason="total_cap"
    )
    root = b"\x01" * 32
    assert rq.hold_for_block(root, ev(1), slot=10)
    assert rq.hold_for_block(root, ev(2), slot=10)
    # one hostile root cannot monopolize the queue
    assert not rq.hold_for_block(root, ev(3), slot=10)
    assert REGISTRY.counter("reprocess_expired_total").value(
        reason="root_cap"
    ) == root_cap_before + 1
    # distinct roots fill to the total cap, then refuse
    for j in range(3):
        assert rq.hold_for_block(bytes([j + 2]) * 32, ev(j), slot=10)
    assert len(rq) == 5
    assert not rq.hold_for_block(b"\x09" * 32, ev(9), slot=10)
    assert not rq.hold_for_slot(11, ev(10))
    assert REGISTRY.counter("reprocess_expired_total").value(
        reason="total_cap"
    ) == total_cap_before + 2
    assert len(rq) == 5


def test_reprocess_queue_slot_expiry():
    from lighthouse_tpu.metrics import REGISTRY

    rq = ReprocessQueue(expiry_slots=2)

    def h(item):
        pass

    rq.hold_for_block(
        b"\x01" * 32, WorkEvent(WorkType.UNKNOWN_BLOCK_ATTESTATION, "a", h), slot=10
    )
    rq.hold_for_block(
        b"\x02" * 32, WorkEvent(WorkType.UNKNOWN_BLOCK_AGGREGATE, "b", h), slot=12
    )
    # unstamped entries never slot-expire (caps still bound them)
    rq.hold_for_block(
        b"\x03" * 32, WorkEvent(WorkType.UNKNOWN_BLOCK_ATTESTATION, "c", h)
    )
    before = REGISTRY.counter("reprocess_expired_total").value(reason="slot")
    assert rq.expire(12) == 0  # slot 10 + 2 not yet past
    assert rq.expire(13) == 1  # slot-10 entry expires; slot-12 survives
    assert rq.expire(15) == 1  # slot-12 entry expires; unstamped survives
    assert REGISTRY.counter("reprocess_expired_total").value(
        reason="slot"
    ) == before + 2
    assert len(rq) == 1
    # expired work never re-fires
    proc = BeaconProcessor(num_workers=1)
    assert rq.block_imported(b"\x01" * 32, proc) == 0
    assert rq.block_imported(b"\x03" * 32, proc) == 1
    proc.drain()
    proc.shutdown()


def test_shutdown_abandons_queued_work_with_counter():
    """Graceful-shutdown audit: work still queued when the processor stops
    is explicitly abandoned and counted, never silently dropped (and
    shutdown never blocks behind the backlog)."""
    from lighthouse_tpu.metrics import REGISTRY

    proc = BeaconProcessor(num_workers=1)

    def h(item):
        pass

    abandoned = REGISTRY.counter("beacon_processor_abandoned_total")
    before = abandoned.value(kind="api_request")
    # push while HOLDING the cv so the manager cannot drain between the
    # pushes and the shutdown flag — deterministic abandonment
    with proc._cv:
        for i in range(5):
            assert proc._queues.push(
                WorkEvent(WorkType.API_REQUEST, i, h)
            )
        proc._shutdown = True
        proc._cv.notify_all()
    proc._manager.join(timeout=2)
    assert not proc._manager.is_alive()
    assert abandoned.value(kind="api_request") == before + 5
    proc.shutdown()  # idempotent full cleanup (workers join on sentinels)


def test_slot_timer_manual_tick():
    from lighthouse_tpu.beacon_chain.timer import SlotTimer
    from lighthouse_tpu.utils.slot_clock import ManualSlotClock

    clock = ManualSlotClock(genesis_time=0, seconds_per_slot=12)
    fired = []
    t = SlotTimer(clock, fired.append)
    clock.set_slot(3)
    assert t.tick()
    assert not t.tick()  # same slot: no double fire
    clock.set_slot(4)
    assert t.tick()
    assert fired == [3, 4]


def test_task_executor_critical_failure_triggers_shutdown():
    sig = ShutdownSignal()
    ex = TaskExecutor(sig)

    def boom():
        raise RuntimeError("died")

    ex.spawn(boom, "critical_service", critical=True)
    assert sig.wait(timeout=5)
    assert "critical_service" in sig.reason

    # non-critical failure does not shut down
    sig2 = ShutdownSignal()
    ex2 = TaskExecutor(sig2)
    ex2.spawn(boom, "optional_service", critical=False)
    ex2.join_all()
    assert not sig2.is_triggered()
