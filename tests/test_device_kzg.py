"""Device KZG kernels vs the host bigint oracle.

VERDICT r4 #3: ops/fr.py + ops/msm.py + the _DeviceKzg path were untested.
These tests pin every kernel against the host implementation at small
shapes (the math is size-generic; the 4096-element mainnet domain rides
the same code), both accepting and rejecting, on the CPU test platform.
Reference behavior being mirrored: crypto/kzg/src/lib.rs:81-117 (c-kzg
wrapper), polynomial-commitments.md evaluate_polynomial_in_evaluation_form.
"""

import random

import numpy as np
import pytest

from lighthouse_tpu.crypto.bls12_381 import FQ, pt_add, pt_eq, pt_mul, to_affine
from lighthouse_tpu.crypto.bls12_381.curve import G1_GEN
from lighthouse_tpu.crypto.kzg import (
    FR_MODULUS,
    Kzg,
    TrustedSetup,
)

# every test in this file is tier-2: device kernels — XLA-CPU compiles
# take minutes cold. tests/conftest.py enforces this marker at collection.
pytestmark = pytest.mark.slow

N = 16  # dev domain size: big enough to exercise folds, small compiles
rng = random.Random(1234)


@pytest.fixture(scope="module")
def setup():
    return TrustedSetup.insecure_dev(N)


@pytest.fixture(scope="module")
def dev_kzg(setup, monkeypatch_module):
    monkeypatch_module.setenv("LIGHTHOUSE_TPU_MSM", "ladder")
    k = Kzg(setup, device=True)
    assert k._dev is not None
    return k


@pytest.fixture(scope="module")
def monkeypatch_module():
    from _pytest.monkeypatch import MonkeyPatch

    m = MonkeyPatch()
    yield m
    m.undo()


# ---------------------------------------------------------------------------
# Fr limb arithmetic
# ---------------------------------------------------------------------------


def test_fr_roundtrip_and_mul_add_sub_inv():
    from lighthouse_tpu.ops.fr import (
        fr_add,
        fr_from_device,
        fr_inv,
        fr_mul,
        fr_sub,
        fr_to_device,
    )

    xs = [rng.randrange(1, FR_MODULUS) for _ in range(8)]
    ys = [rng.randrange(1, FR_MODULUS) for _ in range(8)]
    # edge lanes: 0, 1, r-1
    xs[0], ys[0] = 0, 1
    xs[1], ys[1] = FR_MODULUS - 1, FR_MODULUS - 1
    a = fr_to_device(xs)
    b = fr_to_device(ys)
    assert fr_from_device(a) == xs  # encode/decode inverse

    got = fr_from_device(fr_mul(a, b))
    assert got == [x * y % FR_MODULUS for x, y in zip(xs, ys)]

    got = fr_from_device(fr_add(a, b))
    assert got == [(x + y) % FR_MODULUS for x, y in zip(xs, ys)]

    got = fr_from_device(fr_sub(a, b))
    assert got == [(x - y) % FR_MODULUS for x, y in zip(xs, ys)]

    nz = [v if v else 5 for v in xs]
    got = fr_from_device(fr_inv(fr_to_device(nz)))
    assert got == [pow(v, FR_MODULUS - 2, FR_MODULUS) for v in nz]


def test_barycentric_eval_matches_host(setup):
    import jax.numpy as jnp

    from lighthouse_tpu.ops.fr import (
        barycentric_eval_batch,
        fr_from_device,
        fr_to_device,
    )

    k = Kzg(setup)  # host oracle
    log_n = (N - 1).bit_length()
    evals_lists = [
        [rng.randrange(FR_MODULUS) for _ in range(N)] for _ in range(3)
    ]
    zs = [rng.randrange(FR_MODULUS) for _ in range(3)]
    ev = jnp.asarray(np.stack([fr_to_device(e) for e in evals_lists]))
    roots = jnp.asarray(fr_to_device(setup.roots_brp))
    z_dev = jnp.asarray(fr_to_device(zs))
    ys = fr_from_device(barycentric_eval_batch(ev, roots, z_dev, log_n))
    for got, evs, z in zip(ys, evals_lists, zs):
        assert got == k._evaluate_host(evs, z)


def test_quotient_batch_matches_host(setup):
    import jax.numpy as jnp

    from lighthouse_tpu.ops.fr import fr_from_device, fr_to_device, quotient_batch

    evals = [rng.randrange(FR_MODULUS) for _ in range(N)]
    z = rng.randrange(FR_MODULUS)
    k = Kzg(setup)
    y = k._evaluate_host(evals, z)
    got = fr_from_device(
        quotient_batch(
            jnp.asarray(fr_to_device(evals)),
            jnp.asarray(fr_to_device(setup.roots_brp)),
            jnp.asarray(fr_to_device([z]))[0],
            jnp.asarray(fr_to_device([y]))[0],
        )
    )
    want = [
        (e - y) * pow((w - z) % FR_MODULUS, FR_MODULUS - 2, FR_MODULUS)
        % FR_MODULUS
        for e, w in zip(evals, setup.roots_brp)
    ]
    assert got == want


# ---------------------------------------------------------------------------
# MSM
# ---------------------------------------------------------------------------


def _host_msm(scalars, points):
    acc = None
    for s, p in zip(scalars, points):
        term = pt_mul(FQ, p, s)
        acc = term if acc is None else pt_add(FQ, acc, term)
    return acc


def test_msm_ladder_matches_host(setup, monkeypatch):
    from lighthouse_tpu.ops.bls381 import g1_points_to_device
    from lighthouse_tpu.ops.msm import g1_msm_device

    monkeypatch.setenv("LIGHTHOUSE_TPU_MSM", "ladder")
    pts = setup.g1_lagrange[:8]
    dev = g1_points_to_device(pts)
    scalars = [rng.randrange(FR_MODULUS) for _ in range(8)]
    scalars[3] = 0  # zero lane must not poison the sum
    got = g1_msm_device(scalars, dev)
    assert pt_eq(FQ, got, _host_msm(scalars, pts))


@pytest.mark.slow
def test_msm_pippenger_matches_host(setup):
    """The bucketized kernel (big graph — slow XLA-CPU compile, hence
    slow-marked; the TPU bench path exercises it warm)."""
    from lighthouse_tpu.ops.bls381 import g1_points_to_device
    from lighthouse_tpu.ops.msm import g1_msm_pippenger

    pts = setup.g1_lagrange
    dev = g1_points_to_device(pts)
    scalars = [rng.randrange(FR_MODULUS) for _ in range(N)]
    scalars[0] = 0
    got = g1_msm_pippenger(scalars, dev)
    assert pt_eq(FQ, got, _host_msm(scalars, pts))


# ---------------------------------------------------------------------------
# End-to-end device engine vs host engine
# ---------------------------------------------------------------------------


def _blob(seed: int) -> bytes:
    r = random.Random(seed)
    return b"".join(
        r.randrange(FR_MODULUS).to_bytes(32, "big") for _ in range(N)
    )


def test_device_commitment_matches_host(setup, dev_kzg):
    host = Kzg(setup)
    blob = _blob(7)
    assert dev_kzg.blob_to_kzg_commitment(blob) == host.blob_to_kzg_commitment(
        blob
    )
    assert dev_kzg._dev is not None  # device path survived (no fallback)


def test_device_proof_roundtrip_and_reject(setup, dev_kzg):
    host = Kzg(setup)
    blob = _blob(8)
    c = dev_kzg.blob_to_kzg_commitment(blob)
    z = (99991).to_bytes(32, "big")
    proof, y = dev_kzg.compute_kzg_proof(blob, z)
    h_proof, h_y = host.compute_kzg_proof(blob, z)
    assert (proof, y) == (h_proof, h_y)
    assert dev_kzg.verify_kzg_proof(c, z, y, proof)
    bad_y = ((int.from_bytes(y, "big") + 1) % FR_MODULUS).to_bytes(32, "big")
    assert not dev_kzg.verify_kzg_proof(c, z, bad_y, proof)
    assert dev_kzg._dev is not None


def test_device_blob_batch_verify_accept_and_reject(setup, dev_kzg):
    blobs = [_blob(i) for i in range(20, 23)]
    cs = [dev_kzg.blob_to_kzg_commitment(b) for b in blobs]
    proofs = [dev_kzg.compute_blob_kzg_proof(b, c) for b, c in zip(blobs, cs)]
    assert dev_kzg.verify_blob_kzg_proof_batch(blobs, cs, proofs)
    assert not dev_kzg.verify_blob_kzg_proof_batch(blobs, cs, proofs[::-1])
    assert dev_kzg._dev is not None
