"""Event-driven node under storm: queue-routed gossip + autonomous sync.

The acceptance sims for the event-driven refactor, over real TCP sockets:

* a sustained attestation flood from faulty peers runs CONCURRENTLY with
  a range-sync catch-up driven by the autonomous SyncService — the sync
  completes, the flood's excess is shed through counted drops (reprocess
  caps, processor backpressure), and chain state transitions NEVER run on
  a socket reader thread (asserted two ways: direct thread-name
  instrumentation, and the stack profiler's thread-kind folding);
* the Accept/Ignore/Reject split: internal handler faults cost the
  forwarding peer nothing (`gossip_internal_error_total`), while genuine
  validation rejects still downscore;
* unknown-root aggregates park in the (bounded) reprocess queue like
  attestations have since PR 5, and slot-tick expiry reclaims work whose
  block never arrives;
* graceful shutdown leaks no threads.
"""

import threading
import time
from dataclasses import replace

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.metrics.profiler import StackProfiler
from lighthouse_tpu.network import NetworkService, SyncConfig
from lighthouse_tpu.network.sync import SyncService
from lighthouse_tpu.testing.sync_faults import FaultPlan, FaultyNetworkService
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


def _harness(slots=0):
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    if slots:
        h.extend_chain(slots, attest=False)
    return h


def _fast_cfg(**overrides) -> SyncConfig:
    kw = dict(backoff_base_s=0.01, backoff_max_s=0.05, chain_timeout_s=30.0)
    kw.update(overrides)
    return SyncConfig(**kw)


def _counter(name, **labels):
    return REGISTRY.counter(name).value(**labels)


def _wait(predicate, timeout=30.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _stop_all(*services):
    for s in services:
        s.stop()


# -- THE storm sim -------------------------------------------------------------


def test_gossip_storm_sync_completes_and_load_is_shed():
    """Two faulty peers flood unknown-root attestations at node B while
    the autonomous sync service catches B up 4 epochs from the honest
    peer. Asserts the tentpole contract end to end."""
    a = _harness(slots=4 * E.SLOTS_PER_EPOCH)
    b = _harness()
    f1, f2 = _harness(), _harness()
    na = NetworkService(a.chain).start()
    nb = NetworkService(
        b.chain,
        sync_config=_fast_cfg(max_parallel_downloads=2),
        sync_service_interval=0.1,
        heartbeat_interval=0.05,
    ).start()
    nf1 = NetworkService(f1.chain, heartbeat_interval=None).start()
    nf2 = NetworkService(f2.chain, heartbeat_interval=None).start()

    # direct instrumentation: record the THREAD each state transition on
    # B runs on — the tentpole claim is "never a gossip reader thread"
    seen_threads: set[str] = set()
    real_batch = b.chain.process_attestation_batch
    real_block = b.chain.process_block

    def rec_batch(atts):
        seen_threads.add(threading.current_thread().name)
        return real_batch(atts)

    def rec_block(*args, **kw):
        seen_threads.add(threading.current_thread().name)
        return real_block(*args, **kw)

    b.chain.process_attestation_batch = rec_batch
    b.chain.process_block = rec_block

    tip = a.chain.head_state.slot
    # flood payload: decodable attestations for a bounded set of unknown
    # roots — each parks (Ignore, no peer penalty) until the per-root cap
    # bites, then the refusals ARE the counted load shedding
    template = a.make_unaggregated_attestations(tip, a.chain.head_root)[0]
    garbage_roots = [bytes([0x70 + j]) * 32 for j in range(4)]
    t = a.chain.types

    stop_flood = threading.Event()
    published = [0, 0]

    def flood(nf, lane):
        i = 0
        while not stop_flood.is_set():
            att = template.copy()
            att.data.beacon_block_root = garbage_roots[i % len(garbage_roots)]
            # unique signature bytes → unique message-id (the flooder's
            # own publish dedup must not collapse the flood)
            att.signature = (lane * (1 << 32) + i).to_bytes(8, "little") + bytes(88)
            nf.gossip.publish(nf.topic_att, t.Attestation.serialize_value(att))
            published[lane] += 1
            i += 1
            time.sleep(0.002)  # sustained, not GIL-starving

    prof = StackProfiler(hz=200)
    prof.start()
    floods = []
    try:
        # no gossip blocks flow in this sim, so the service must close the
        # FULL lag itself — zero tolerance (see the re-entry test)
        nb.sync_service.head_lag_slots = 0
        b.slot_clock.set_slot(tip)
        nb.connect("127.0.0.1", na.port)
        nf1.connect("127.0.0.1", nb.port)
        nf2.connect("127.0.0.1", nb.port)

        held_before = _counter("reprocess_held_total")
        shed_before = _counter("reprocess_expired_total", reason="root_cap")
        floods = [
            threading.Thread(target=flood, args=(nf, lane), daemon=True)
            for lane, nf in enumerate((nf1, nf2))
        ]
        for th in floods:
            th.start()

        # NO sync_to_head call anywhere: the autonomous service sees the
        # 4-epoch lag through na's Status and catches up under the flood
        _wait(
            lambda: b.chain.head_root == a.chain.head_root,
            timeout=60,
            what="autonomous catch-up under flood",
        )
        # keep the flood going a moment past catch-up so the caps bite
        _wait(
            lambda: _counter("reprocess_expired_total", reason="root_cap")
            > shed_before,
            timeout=30,
            what="per-root cap shedding",
        )
    finally:
        stop_flood.set()
        for th in floods:
            th.join(timeout=5)
        prof.stop()
    try:
        assert nb.processor.drain(timeout=15)
        assert sum(published) > 0
        assert nb.sync_service.runs >= 1

        # load shed, counted: attestations parked up to the caps, excess
        # refused — never a hung socket
        assert _counter("reprocess_held_total") > held_before
        assert (
            _counter("reprocess_expired_total", reason="root_cap") > shed_before
        )
        assert len(nb.reprocess) <= nb.reprocess.total_cap

        # the flood was IGNORED work (unknown root): the honest peer and
        # even the flooders keep their standing — nobody was downscored
        # for our missing blocks
        assert nb.peers.get(f"127.0.0.1:{na.port}") is not None

        # tentpole: every state transition ran on a worker (or a sync
        # thread) — never on a `gossip-<peer>` socket reader
        assert seen_threads
        readers = [n for n in seen_threads if n.startswith("gossip-")]
        assert not readers, f"state transitions on reader threads: {readers}"

        # the profiler's thread-kind folding agrees: no sampled chain
        # frame sits under a gossip-reader thread kind
        for line in prof.collapsed().splitlines():
            if (
                "process_attestation_batch (" in line
                or "process_block (" in line
                or "per_block_processing (" in line
            ):
                kind = next(
                    (p for p in line.split(";") if p.startswith("thread:")), ""
                )
                assert not kind.startswith("thread:gossip-"), line

        # queue observability saw the storm: the attestation lane both
        # processed work and recorded queue waits
        assert (
            _counter("beacon_processor_processed_total", kind="gossip_attestation")
            > 0
        )

        # slot-tick expiry reclaims what the flood left parked: advance
        # the clock past the expiry window and tick
        expired_before = _counter("reprocess_expired_total", reason="slot")
        b.slot_clock.set_slot(tip + nb.reprocess.expiry_slots + 2)
        nb.slot_tick()
        assert _counter("reprocess_expired_total", reason="slot") > expired_before
        assert len(nb.reprocess) == 0
    finally:
        _stop_all(na, nb, nf1, nf2)


# -- Accept/Ignore/Reject split ------------------------------------------------


def test_internal_error_is_counted_not_downscored_but_reject_is():
    a = _harness(slots=2)
    b = _harness()
    na = NetworkService(a.chain).start()
    nb = NetworkService(b.chain).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        nb.connect("127.0.0.1", na.port)
        peer = nb.peers.get(f"127.0.0.1:{na.port}")
        assert peer is not None
        t = b.chain.types
        exit_ = t.SignedVoluntaryExit(
            message=t.VoluntaryExit(epoch=0, validator_index=3),
            signature=b"\x0b" * 96,
        )
        data = exit_.serialize()

        # internal fault (store error, bug): counted + logged, the
        # forwarding peer keeps its score
        def boom(_exit):
            raise RuntimeError("store exploded")

        b.chain.process_voluntary_exit = boom
        before_internal = _counter("gossip_internal_error_total")
        score_before = peer.score
        nb.gossip._deliver(nb.topic_exit, data, peer.peer_id)
        assert nb.processor.drain()
        assert _counter("gossip_internal_error_total") == before_internal + 1
        assert peer.score == score_before

        # genuine validation reject (ValueError family): downscored
        def reject(_exit):
            raise ValueError("spec-invalid exit")

        b.chain.process_voluntary_exit = reject
        before_invalid = _counter("gossip_invalid_total")
        nb.gossip._deliver(nb.topic_exit, data, peer.peer_id)
        assert nb.processor.drain()
        assert _counter("gossip_invalid_total") == before_invalid + 1
        assert peer.score < score_before
    finally:
        _stop_all(na, nb)


def test_unknown_root_aggregate_parks_and_expires():
    """An aggregate for a root we don't have parks in the reprocess queue
    (UNKNOWN_BLOCK_AGGREGATE lane) instead of erroring — and the slot
    tick expires it when the block never arrives."""
    a = _harness(slots=E.SLOTS_PER_EPOCH)
    b = _harness()
    na = NetworkService(a.chain).start()
    nb = NetworkService(b.chain, heartbeat_interval=None).start()
    try:
        tip = a.chain.head_state.slot
        b.slot_clock.set_slot(tip)
        nb.connect("127.0.0.1", na.port)
        t = a.chain.types
        att = a.make_attestations(tip, a.chain.head_root)[0]
        att = att.copy()
        garbage = b"\x55" * 32
        att.data.beacon_block_root = garbage
        agg = t.SignedAggregateAndProof(
            message=t.AggregateAndProof(
                aggregator_index=0,
                aggregate=att,
                selection_proof=b"\x01" * 96,
            ),
            signature=b"\x02" * 96,
        )
        held_before = _counter("reprocess_held_total")
        nb.gossip._deliver(nb.topic_aggregate, agg.serialize(), "test-origin")
        assert nb.processor.drain()
        assert _counter("reprocess_held_total") == held_before + 1
        assert garbage in nb.reprocess._by_block_root

        expired_before = _counter("reprocess_expired_total", reason="slot")
        b.slot_clock.set_slot(tip + nb.reprocess.expiry_slots + 2)
        nb.slot_tick()
        assert (
            _counter("reprocess_expired_total", reason="slot")
            == expired_before + 1
        )
        assert not nb.reprocess._by_block_root
    finally:
        _stop_all(na, nb)


def test_accepted_gossip_relays_through_the_relay_thread():
    """Validate-then-forward survives queueing: A publishes a block to B
    only; B's queued handler accepts it and the deferred relay (the
    gossip-relay thread, NOT a worker or reader) forwards it to C."""
    a = _harness(slots=2)
    b = _harness()
    c = _harness()
    # heartbeats off on ALL nodes: no meshes ever form, so B's eager
    # forward exercises the pre-mesh subscribed-peers fallback — with
    # A's heartbeat on, A GRAFTs into B's mesh and B's mesh-only forward
    # (minus the origin) correctly has nobody, which tests nothing
    na = NetworkService(a.chain, heartbeat_interval=None).start()
    nb = NetworkService(b.chain, heartbeat_interval=None).start()
    nc = NetworkService(c.chain, heartbeat_interval=None).start()
    try:
        for h in (b, c):
            h.slot_clock.set_slot(a.chain.head_state.slot)
        peer_ab = nb.connect("127.0.0.1", na.port)
        nb.sync.sync_with(peer_ab)
        blocks = nb.blocks_by_range(1, b.chain.head_state.slot)
        assert c.chain.process_chain_segment(blocks).error is None
        nc.connect("127.0.0.1", nb.port)  # C talks ONLY to B
        time.sleep(0.3)  # inbound registration + subscriptions settle

        slot = a.chain.head_state.slot + 1
        for h in (a, b, c):
            h.slot_clock.set_slot(slot)
        root, signed = a.add_block_at_slot(slot)
        # A's service knows only B: the flood publish reaches B alone;
        # C can only get the block if B's deferred Accept relays it
        na.publish_block(signed)
        _wait(lambda: b.chain.head_root == root, what="B imports via queue")
        _wait(lambda: c.chain.head_root == root, what="C gets B's relay")
    finally:
        _stop_all(na, nb, nc)


def test_early_attestation_parks_until_its_slot():
    """A near-future attestation (peer clock slightly ahead) parks via
    hold_for_slot instead of downscoring the forwarder; the slot tick
    re-fires it when its slot starts and it lands in the op pool."""
    a = _harness(slots=E.SLOTS_PER_EPOCH)
    b = _harness()
    na = NetworkService(a.chain).start()
    nb = NetworkService(b.chain, heartbeat_interval=None).start()
    try:
        tip = a.chain.head_state.slot
        b.slot_clock.set_slot(tip)
        peer = nb.connect("127.0.0.1", na.port)
        nb.sync.sync_with(peer)
        assert b.chain.head_root == a.chain.head_root
        t = b.chain.types
        att = a.make_unaggregated_attestations(tip + 1, a.chain.head_root)[0]
        before_pool = b.chain.op_pool.num_attestations()
        score_before = peer.score
        nb.gossip._deliver(
            nb.topic_att, t.Attestation.serialize_value(att), peer.peer_id
        )
        assert nb.processor.drain()
        assert b.chain.op_pool.num_attestations() == before_pool  # held
        assert peer.score == score_before  # honestly-early: no penalty
        assert len(nb.reprocess) == 1

        b.slot_clock.set_slot(tip + 1)
        nb.slot_tick()  # re-fires the held attestation on its slot
        assert nb.processor.drain()
        assert b.chain.op_pool.num_attestations() > before_pool
        assert len(nb.reprocess) == 0

        # a FAR-future slot (past the tolerance, clock now at tip+1) is
        # IGNORED without parking: window violations are never rejects
        # (spec semantics — lateness/clock skew is congestion, not
        # malice), but a hostile timestamp must not occupy the queue
        far = a.make_unaggregated_attestations(tip + 4, a.chain.head_root)[0]
        ignored_before = _counter("gossip_ignored_total")
        nb.gossip._deliver(
            nb.topic_att, t.Attestation.serialize_value(far), peer.peer_id
        )
        assert nb.processor.drain()
        assert _counter("gossip_ignored_total") == ignored_before + 1
        assert peer.score == score_before  # no penalty for clock skew
        assert len(nb.reprocess) == 0  # and nothing parked
    finally:
        _stop_all(na, nb)


# -- autonomous sync service ---------------------------------------------------


def test_sync_service_catches_up_and_reenters():
    """No caller ever invokes sync_to_head: the service notices the lag,
    catches up, goes idle, and re-enters when the node falls behind."""
    a = _harness(slots=2 * E.SLOTS_PER_EPOCH)
    b = _harness()
    na = NetworkService(a.chain).start()
    nb = NetworkService(
        b.chain, sync_config=_fast_cfg(), sync_service_interval=0.05
    ).start()
    try:
        # zero lag tolerance for the test: in production a ≤2-slot lag is
        # left to gossip delivery, but this sim HAS no gossip — the
        # service can race a concurrent extend_chain, catch up to a
        # mid-extension target, and the residual lag would sit inside the
        # default tolerance forever
        nb.sync_service.head_lag_slots = 0
        b.slot_clock.set_slot(a.chain.head_state.slot)
        nb.connect("127.0.0.1", na.port)
        _wait(
            lambda: b.chain.head_root == a.chain.head_root,
            what="first autonomous catch-up",
        )
        runs_first = nb.sync_service.runs
        assert runs_first >= 1

        # A advances another epoch that B never hears about via gossip;
        # the service re-enters on the new lag
        a.extend_chain(E.SLOTS_PER_EPOCH, attest=False)
        b.slot_clock.set_slot(a.chain.head_state.slot)
        _wait(
            lambda: b.chain.head_root == a.chain.head_root,
            what="re-entry after falling behind",
        )
        assert nb.sync_service.runs > runs_first
    finally:
        _stop_all(na, nb)


def test_sync_service_backs_off_after_failed_runs():
    """A peer that advertises a head it cannot serve: the first run makes
    real progress, subsequent runs import nothing — consecutive failures
    grow a capped exponential backoff instead of hammering the peer."""
    a = _harness(slots=E.SLOTS_PER_EPOCH)
    b = _harness()
    liar = FaultyNetworkService(
        a.chain, FaultPlan(stale_status_extra=E.SLOTS_PER_EPOCH)
    ).start()
    nb = NetworkService(b.chain, sync_config=_fast_cfg()).start()
    svc = SyncService(
        nb.sync, interval=0.05, backoff_base_s=0.05, backoff_max_s=0.2
    )
    try:
        b.slot_clock.set_slot(2 * E.SLOTS_PER_EPOCH)
        nb.connect("127.0.0.1", liar.port)
        failed_before = _counter("sync_service_runs_total", result="failed")
        svc.start()
        _wait(
            lambda: b.chain.head_root == a.chain.head_root,
            what="real blocks imported",
        )
        _wait(
            lambda: _counter("sync_service_runs_total", result="failed")
            >= failed_before + 2,
            what="repeated failed runs",
        )
        assert svc.backoff_s() > 0
        assert svc.backoff_s() <= svc.backoff_max_s
    finally:
        svc.stop()
        assert not svc.running
        _stop_all(liar, nb)


# -- graceful shutdown ---------------------------------------------------------


def test_stop_leaks_no_threads():
    """NetworkService with every loop armed (heartbeat/slot tick, sync
    service, processor workers, RPC server) stops without leaking a
    single live thread."""
    a = _harness(slots=2)
    before = set(threading.enumerate())
    n = NetworkService(
        a.chain, sync_service_interval=0.05, heartbeat_interval=0.02
    ).start()
    time.sleep(0.3)  # let every loop run at least once
    n.stop()
    _wait(
        lambda: not [
            th
            for th in threading.enumerate()
            if th not in before and th.is_alive()
        ],
        timeout=10,
        what="all service threads to exit",
    )


def test_stop_abandons_queued_work_with_counter():
    """NetworkService.stop on a node with parked + queued work: the
    processor abandons its backlog and the reprocess queue clears, both
    through counters — nothing silent, nothing hung."""
    a = _harness(slots=2)
    n = NetworkService(a.chain, heartbeat_interval=None).start()
    from lighthouse_tpu.beacon_processor import WorkEvent, WorkType

    n.reprocess.hold_for_block(
        b"\x99" * 32,
        WorkEvent(WorkType.UNKNOWN_BLOCK_ATTESTATION, "att", lambda _: None),
        slot=1,
    )
    shutdown_before = _counter("reprocess_expired_total", reason="shutdown")
    n.stop()
    assert _counter("reprocess_expired_total", reason="shutdown") == (
        shutdown_before + 1
    )
    assert len(n.reprocess) == 0
