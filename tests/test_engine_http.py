"""Engine API over JSON-RPC/HTTP with JWT auth + the engine watchdog.

HttpEngineClient ↔ MockEngineServer (the reference's MockServer analog)
end-to-end: JWT validation, payload JSON codec roundtrips byte-exactly
through SSZ, a full merge-era chain runs with its EL behind HTTP, and
the watchdog takes the engine offline/online (lib.rs:599-618,1389)."""

import time
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.execution_layer import (
    ExecutionLayerError,
    ForkchoiceState,
    MockExecutionLayer,
    PayloadAttributes,
    PayloadStatusV1,
)
from lighthouse_tpu.execution_layer.auth import (
    JwtError,
    generate_jwt,
    load_jwt_secret,
    validate_jwt,
)
from lighthouse_tpu.execution_layer.http import (
    HttpEngineClient,
    MockEngineServer,
    payload_from_json,
    payload_to_json,
)
from lighthouse_tpu.execution_layer.watchdog import EngineState, EngineWatchdog
from lighthouse_tpu.types.chain_spec import ForkName, minimal_spec
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

T = build_types(E)
SECRET = bytes(range(32))


def test_jwt_roundtrip_and_rejections(tmp_path):
    token = generate_jwt(SECRET)
    claims = validate_jwt(token, SECRET)
    assert "iat" in claims
    with pytest.raises(JwtError, match="bad signature"):
        validate_jwt(token, b"\x01" * 32)
    with pytest.raises(JwtError, match="drift"):
        validate_jwt(generate_jwt(SECRET, iat=int(time.time()) - 3600), SECRET)
    # jwtsecret file format (0x-hex)
    p = tmp_path / "jwtsecret"
    p.write_text("0x" + SECRET.hex() + "\n")
    assert load_jwt_secret(str(p)) == SECRET
    assert load_jwt_secret(SECRET.hex()) == SECRET


def test_payload_json_codec_roundtrip():
    mock = MockExecutionLayer(T, E)
    attrs = PayloadAttributes(
        timestamp=12, prev_randao=b"\x05" * 32,
        suggested_fee_recipient=b"\xaa" * 20,
        withdrawals=[T.Withdrawal(index=1, validator_index=2,
                                  address=b"\xbb" * 20, amount=99)],
    )
    payload = mock.get_payload(None, attrs, ForkName.CAPELLA)
    doc = payload_to_json(payload)
    back = payload_from_json(doc, T, ForkName.CAPELLA)
    assert back.serialize() == payload.serialize()  # byte-exact through JSON


def test_payload_json_codec_electra_fields():
    """Electra's deposit receipts / withdrawal requests survive the wire
    byte-exactly (regression: they were silently dropped)."""
    payload = T.ExecutionPayloadElectra(
        block_number=9,
        transactions=[b"\x01\x02"],
        deposit_receipts=[
            T.DepositReceipt(
                pubkey=b"\x0a" * 48,
                withdrawal_credentials=b"\x0b" * 32,
                amount=32_000_000_000,
                signature=b"\x0c" * 96,
                index=4,
            )
        ],
        withdrawal_requests=[
            T.ExecutionLayerWithdrawalRequest(
                source_address=b"\x0d" * 20,
                validator_pubkey=b"\x0e" * 48,
                amount=7,
            )
        ],
    )
    back = payload_from_json(payload_to_json(payload), T, ForkName.ELECTRA)
    assert back.serialize() == payload.serialize()


def _served_engine():
    mock = MockExecutionLayer(T, E)
    srv = MockEngineServer(mock, SECRET, T, E).start()
    client = HttpEngineClient(srv.url, SECRET, T)
    return mock, srv, client


def test_engine_rpc_roundtrip_and_auth():
    mock, srv, client = _served_engine()
    try:
        attrs = PayloadAttributes(timestamp=6, prev_randao=b"\x07" * 32)
        payload = client.get_payload(None, attrs, ForkName.BELLATRIX)
        assert payload.timestamp == 6
        # the served payload exists in the mock's chain
        assert bytes(payload.block_hash) in mock.generator.blocks
        # new payload notification over the wire
        from types import SimpleNamespace

        status = client.notify_new_payload(
            SimpleNamespace(execution_payload=payload)
        )
        assert status is PayloadStatusV1.VALID
        # wrong JWT secret → transport error
        bad = HttpEngineClient(srv.url, b"\x02" * 32, T)
        with pytest.raises(ExecutionLayerError):
            bad.notify_forkchoice_updated(
                ForkchoiceState(b"\x00" * 32, b"\x00" * 32, b"\x00" * 32), None
            )
    finally:
        srv.stop()


def test_chain_merges_with_el_behind_http():
    """The full merge path with the EL reached over authenticated
    JSON-RPC: a capella-at-genesis chain produces and imports blocks
    whose payloads come from HTTP get_payload."""
    bls.set_backend("fake_crypto")
    mock, srv, client = _served_engine()
    try:
        spec = replace(
            minimal_spec(),
            altair_fork_epoch=0, bellatrix_fork_epoch=0, capella_fork_epoch=0,
        )
        h = BeaconChainHarness(
            spec, E, validator_count=16, execution_layer=client
        )
        h.extend_chain(E.SLOTS_PER_EPOCH + 2)
        head = h.chain.head_state
        assert head.slot == E.SLOTS_PER_EPOCH + 2
        assert int(head.latest_execution_payload_header.block_number) > 0
    finally:
        srv.stop()


def test_watchdog_offline_online_cycle():
    mock, srv, client = _served_engine()
    wd = EngineWatchdog(client, upcheck_interval=0.05)
    try:
        attrs = PayloadAttributes(timestamp=6, prev_randao=b"\x07" * 32)
        wd.get_payload(None, attrs, ForkName.BELLATRIX)
        assert wd.state is EngineState.ONLINE
        # kill the server: next call marks offline, then fails fast
        srv.stop()
        with pytest.raises(ExecutionLayerError):
            wd.get_payload(None, attrs, ForkName.BELLATRIX)
        assert wd.state is EngineState.OFFLINE
        with pytest.raises(ExecutionLayerError, match="offline"):
            wd.notify_forkchoice_updated(
                ForkchoiceState(b"\x00" * 32, b"\x00" * 32, b"\x00" * 32), None
            )
        # bring a server back on the SAME engine; upcheck restores ONLINE
        srv2 = MockEngineServer(mock, SECRET, T, E).start()
        client.url = srv2.url
        time.sleep(0.06)
        assert wd.upcheck()
        assert wd.state is EngineState.ONLINE
        srv2.stop()
    finally:
        pass
