"""Columnar proto-array fork choice vs the retained scalar oracle.

Differential fuzz over randomized block trees (forks, slot skips), vote
churn with equivocations, proposer-boost application/removal, justified-
checkpoint flips, and prune-mid-sequence; plus the prune-under-votes
regression (votes referencing pruned roots must resolve to the -1
sentinel, never a stale index), batch-vs-single ingestion equivalence
through the ForkChoice wrapper, and a perf_smoke guard that the batch
path engages (counter check — no scalar fallback exists to fall into,
so the guard pins the ingestion path label instead)."""

import random

import numpy as np
import pytest

from lighthouse_tpu.fork_choice import (
    ExecutionStatus,
    ProtoArrayForkChoice,
    ProtoArrayForkChoiceReference,
)
from lighthouse_tpu.metrics import REGISTRY

# NOTE the 0xAA prefix: an all-zero anchor root would collide with the
# "no vote yet" sentinel, making every first vote move look like a move
# AWAY from the anchor (both implementations mirror each other on that
# pathological input — they subtract never-added balances and raise
# "negative node weight" identically — but real anchor roots are hashes)
R = lambda i: b"\xaa" + i.to_bytes(4, "big") + b"\x00" * 27  # noqa: E731

ZERO = b"\x00" * 32


def _pair(prune_threshold=4):
    col = ProtoArrayForkChoice(R(0), 0, R(0), 0, 0)
    ref = ProtoArrayForkChoiceReference(R(0), 0, R(0), 0, 0)
    col.proto_array.prune_threshold = prune_threshold
    ref.proto_array.prune_threshold = prune_threshold
    return col, ref


def _assert_state_equal(col, ref, ctx=""):
    pa = col.proto_array
    n = pa._n
    assert n == len(ref.proto_array.nodes), ctx
    assert pa.indices == ref.proto_array.indices, ctx
    assert pa._weights[:n].tolist() == [
        node.weight for node in ref.proto_array.nodes
    ], ctx
    assert [int(x) for x in pa._best_child[:n]] == [
        -1 if node.best_child is None else node.best_child
        for node in ref.proto_array.nodes
    ], ctx
    assert [int(x) for x in pa._best_desc[:n]] == [
        -1 if node.best_descendant is None else node.best_descendant
        for node in ref.proto_array.nodes
    ], ctx


class _Fuzzer:
    """One randomized columnar/oracle pair driven through the same op
    sequence. Balances never increase between score passes (the valid-
    sequence regime: the scalar oracle raises 'negative node weight' and
    corrupts itself mid-walk otherwise — both implementations raise the
    SAME error there, covered by a directed test below)."""

    def __init__(self, seed: int, n_val: int = 48):
        self.rng = random.Random(seed)
        self.col, self.ref = _pair()
        self.roots = [R(0)]
        self.slots = {R(0): 0}
        self.n_val = n_val
        self.balances = [100 + self.rng.randint(0, 50) for _ in range(n_val)]
        self.je = self.fe = 0
        self.eq: set[int] = set()
        self.justified_root = R(0)
        self.next_root = 1
        self.heads = 0

    def add_block(self):
        rng = self.rng
        parent = rng.choice(self.roots[-8:])
        root = R(self.next_root)
        self.next_root += 1
        slot = self.slots[parent] + rng.randint(1, 3)
        self.slots[root] = slot
        uje = rng.choice([None, self.je, self.je + 1])
        kw = dict(
            slot=slot,
            root=root,
            parent_root=parent,
            state_root=root,
            justified_epoch=self.je,
            finalized_epoch=self.fe,
            unrealized_justified_epoch=uje,
        )
        self.col.on_block(**kw)
        self.ref.on_block(**kw)
        self.roots.append(root)

    def churn_votes(self):
        rng = self.rng
        epoch = rng.randint(0, 6)
        target = rng.choice(self.roots)
        vs = rng.sample(range(self.n_val), rng.randint(1, 12))
        if rng.random() < 0.5:
            self.col.process_attestation_batch(
                np.asarray(vs, dtype=np.int64), target, epoch
            )
        else:
            for v in vs:
                self.col.process_attestation(v, target, epoch)
        for v in vs:
            self.ref.process_attestation(v, target, epoch)

    def head_round(self):
        rng = self.rng
        if rng.random() < 0.3:
            for _ in range(4):
                i = rng.randrange(self.n_val)
                self.balances[i] = max(0, self.balances[i] - rng.randint(1, 20))
        boost_root = rng.choice(self.roots) if rng.random() < 0.4 else ZERO
        boost = rng.randint(1, 50) if boost_root != ZERO else 0
        if rng.random() < 0.15:
            self.je = min(self.je + 1, 3)
        kw = dict(
            justified_checkpoint_root=self.justified_root,
            justified_epoch=self.je,
            finalized_epoch=self.fe,
            proposer_boost_root=boost_root,
            proposer_boost_amount=boost,
            equivocating_indices=set(self.eq),
        )
        try:
            h1 = self.col.get_head(
                justified_state_balances=np.asarray(
                    self.balances, dtype=np.uint64
                ),
                **kw,
            )
            e1 = None
        except Exception as ex:  # noqa: BLE001 — compared against oracle
            h1, e1 = None, str(ex)
        try:
            h2 = self.ref.get_head(
                justified_state_balances=list(self.balances), **kw
            )
            e2 = None
        except Exception as ex:  # noqa: BLE001
            h2, e2 = None, str(ex)
        assert (h1, e1) == (h2, e2)
        # 'best node is not viable for head' is a legitimate matching
        # outcome (a justified flip can orphan the whole best chain) and
        # leaves both sides fully applied; 'negative node weight' must not
        # occur under the non-increasing balance regime (it corrupts the
        # scalar oracle mid-walk — directed test below)
        assert e1 in (None, "best node is not viable for head")
        self.heads += 1
        _assert_state_equal(self.col, self.ref)

    def prune(self):
        fin = self.rng.choice(self.roots)
        self.col.proto_array.maybe_prune(fin)
        self.ref.proto_array.maybe_prune(fin)
        assert self.col.proto_array.indices == self.ref.proto_array.indices
        self._check_rid_invariants()
        if self.justified_root not in self.ref.proto_array.indices:
            self.justified_root = fin
        self.roots = [
            r for r in self.roots if r in self.ref.proto_array.indices
        ]

    def _check_rid_invariants(self):
        """After a prune (which may compact the intern table): every
        interned root maps to exactly its live node index (or -1), and
        every vote-column rid stays in range."""
        pa = self.col.proto_array
        for root, rid in pa._root_ids.items():
            assert 0 <= rid < pa._n_rids
            expect = pa.indices.get(root, -1) if root != ZERO else -1
            assert int(pa._rid_to_node[rid]) == expect, root.hex()
        assert int(self.col._cur_rid.max(initial=0)) < pa._n_rids
        assert int(self.col._next_rid.max(initial=0)) < pa._n_rids

    def step(self):
        op = self.rng.random()
        if op < 0.35:
            self.add_block()
        elif op < 0.72:
            self.churn_votes()
        elif op < 0.78 and len(self.roots) > 3:
            self.eq.add(self.rng.randrange(self.n_val))
        elif op < 0.9:
            self.head_round()
        else:
            self.prune()


@pytest.mark.parametrize("seed", range(6))
def test_differential_fuzz_columnar_vs_scalar_oracle(seed):
    f = _Fuzzer(seed)
    for _ in range(300):
        f.step()
    assert f.heads >= 10  # the sequence actually exercised head selection


def test_negative_weight_raises_identically():
    """Balance INCREASE while a vote is parked makes the move subtract
    more than it added — the scalar oracle raises 'negative node weight'
    mid-walk; the columnar pass must detect the same condition (checked
    u64 underflow, surfaced BEFORE any weight write)."""
    col, ref = _pair()
    for fc in (col, ref):
        fc.on_block(
            slot=1, root=R(1), parent_root=R(0), state_root=R(1),
            justified_epoch=0, finalized_epoch=0,
        )
        fc.process_attestation(0, R(1), 1)
        fc.get_head(
            justified_checkpoint_root=R(0), justified_epoch=0,
            finalized_epoch=0, justified_state_balances=[10],
        )
        # balance jumps 10 -> 50 while the vote stays: the pass skips the
        # unchanged vote but records 50 as the old balance...
        fc.process_attestation(0, R(1), 1)  # no-op (same target)
        fc.get_head(
            justified_checkpoint_root=R(0), justified_epoch=0,
            finalized_epoch=0, justified_state_balances=[50],
        )
        # ...so moving the vote now subtracts 50 from a 10-weight node
        fc.process_attestation(0, R(0), 2)
        with pytest.raises(ValueError, match="negative node weight"):
            fc.get_head(
                justified_checkpoint_root=R(0), justified_epoch=0,
                finalized_epoch=0, justified_state_balances=[50],
            )


def test_prune_under_votes_resolves_to_sentinel():
    """Votes referencing pruned roots must resolve to the -1 sentinel,
    not a stale (remapped) node index: after the prune drops a voted-for
    fork, the next delta round must neither crash nor credit a surviving
    node that inherited the pruned node's old index."""
    col, ref = _pair(prune_threshold=0)
    # trunk 1..5 plus a side fork F at slot 2 that prune will drop
    fork_root = R(99)
    for fc in (col, ref):
        for i in range(1, 6):
            fc.on_block(
                slot=i, root=R(i), parent_root=R(i - 1), state_root=R(i),
                justified_epoch=0, finalized_epoch=0,
            )
        fc.on_block(
            slot=2, root=fork_root, parent_root=R(1), state_root=fork_root,
            justified_epoch=0, finalized_epoch=0,
        )
        # validator 0 votes the doomed fork; validator 1 (heavier) the
        # trunk tip, so the trunk wins and the fork gets pruned away
        fc.process_attestation(0, fork_root, 1)
        fc.process_attestation(1, R(5), 1)
        assert fc.get_head(
            justified_checkpoint_root=R(0), justified_epoch=0,
            finalized_epoch=0, justified_state_balances=[10, 20],
        ) == R(5)
        fc.proto_array.maybe_prune(R(3))
        assert not fc.contains_block(fork_root)
    # the interned fork root now maps to the sentinel, NOT a live index
    pa = col.proto_array
    rid = pa._root_ids[fork_root]
    assert int(pa._rid_to_node[rid]) == -1
    # a later round (vote 0 moves off the pruned root) stays bit-identical
    for fc in (col, ref):
        fc.process_attestation(0, R(5), 2)
        assert fc.get_head(
            justified_checkpoint_root=R(3), justified_epoch=0,
            finalized_epoch=0, justified_state_balances=[10, 20],
        ) == R(5)
    _assert_state_equal(col, ref)
    # once no vote column references the pruned root anymore, the next
    # prune compacts its intern entry away entirely (no unbounded growth
    # of the rid table on a long-lived node)
    assert fork_root in pa._root_ids  # still interned: was referenced
    col.proto_array.maybe_prune(R(5))
    ref.proto_array.maybe_prune(R(5))
    assert fork_root not in pa._root_ids
    assert R(5) in pa._root_ids  # live vote target survives, remapped
    rid5 = pa._root_ids[R(5)]
    assert int(pa._rid_to_node[rid5]) == pa.indices[R(5)]
    for fc in (col, ref):
        assert fc.get_head(
            justified_checkpoint_root=R(5), justified_epoch=0,
            finalized_epoch=0, justified_state_balances=[10, 20],
        ) == R(5)
    _assert_state_equal(col, ref)


def test_pruned_root_readded_resolves_to_new_index():
    """A root voted for before its block is known (direct proto API) must
    resolve once the block arrives — the rid map is refreshed on insert."""
    col, _ = _pair()
    col.process_attestation(0, R(7), 1)  # unknown root: parked at sentinel
    pa = col.proto_array
    assert int(pa._rid_to_node[pa._root_ids[R(7)]]) == -1
    col.on_block(
        slot=1, root=R(7), parent_root=R(0), state_root=R(7),
        justified_epoch=0, finalized_epoch=0,
    )
    assert int(pa._rid_to_node[pa._root_ids[R(7)]]) == pa.indices[R(7)]
    assert col.get_head(
        justified_checkpoint_root=R(0), justified_epoch=0,
        finalized_epoch=0, justified_state_balances=[10],
    ) == R(7)


def test_execution_invalidation_matches_oracle():
    col, ref = _pair()
    for fc in (col, ref):
        for i in range(1, 5):
            fc.on_block(
                slot=i, root=R(i), parent_root=R(i - 1), state_root=R(i),
                justified_epoch=0, finalized_epoch=0,
                execution_status=ExecutionStatus.OPTIMISTIC,
            )
        fc.process_attestation(0, R(4), 1)
        assert fc.get_head(
            justified_checkpoint_root=R(0), justified_epoch=0,
            finalized_epoch=0, justified_state_balances=[10],
        ) == R(4)
        fc.proto_array.invalidate_block(R(3))
        assert fc.get_head(
            justified_checkpoint_root=R(0), justified_epoch=0,
            finalized_epoch=0, justified_state_balances=[10],
        ) == R(2)
    _assert_state_equal(col, ref)
    col.proto_array.propagate_execution_payload_validity(R(2))
    assert (
        col.proto_array.execution_status_of(R(2)) == ExecutionStatus.VALID
    )
    assert (
        col.proto_array.execution_status_of(R(3)) == ExecutionStatus.INVALID
    )


def test_batch_ingestion_equals_single():
    """process_attestation_batch must leave the vote columns exactly as
    the equivalent sequence of single-vote calls (including the strictly-
    newer-epoch accept rule and the first-vote default case)."""
    batch, single = (
        ProtoArrayForkChoice(R(0), 0, R(0), 0, 0),
        ProtoArrayForkChoice(R(0), 0, R(0), 0, 0),
    )
    for fc in (batch, single):
        fc.on_block(
            slot=1, root=R(1), parent_root=R(0), state_root=R(1),
            justified_epoch=0, finalized_epoch=0,
        )
        fc.on_block(
            slot=1, root=R(2), parent_root=R(0), state_root=R(2),
            justified_epoch=0, finalized_epoch=0,
        )
    rng = random.Random(5)
    for round_ in range(20):
        epoch = rng.randint(0, 5)
        target = rng.choice([R(1), R(2)])
        vs = rng.sample(range(64), rng.randint(1, 16))
        batch.process_attestation_batch(
            np.asarray(vs, dtype=np.int64), target, epoch
        )
        for v in vs:
            single.process_attestation(v, target, epoch)
        m = len(single._cur_rid)
        assert batch._next_rid[:m].tolist() == single._next_rid[:m].tolist()
        assert (
            batch._next_epoch[:m].tolist() == single._next_epoch[:m].tolist()
        )
    balances = np.full(64, 7, dtype=np.uint64)
    assert batch.get_head(
        justified_checkpoint_root=R(0), justified_epoch=0, finalized_epoch=0,
        justified_state_balances=balances,
    ) == single.get_head(
        justified_checkpoint_root=R(0), justified_epoch=0, finalized_epoch=0,
        justified_state_balances=balances,
    )


# ---------------------------------------------------------------------------
# ForkChoice wrapper batch entry
# ---------------------------------------------------------------------------

from lighthouse_tpu.fork_choice.fork_choice import (  # noqa: E402
    Checkpoint as FcCheckpoint,
    ForkChoice,
    ForkChoiceStore,
    InvalidAttestation,
)
from lighthouse_tpu.types.chain_spec import minimal_spec  # noqa: E402
from lighthouse_tpu.types.containers import build_types  # noqa: E402
from lighthouse_tpu.types.eth_spec import MinimalEthSpec  # noqa: E402


def _wrapper(current_slot=0):
    cp = FcCheckpoint(epoch=0, root=R(0))
    store = ForkChoiceStore(
        current_slot=current_slot,
        justified_checkpoint=cp,
        finalized_checkpoint=cp,
        unrealized_justified_checkpoint=cp,
        unrealized_finalized_checkpoint=cp,
    )
    proto = ProtoArrayForkChoice(R(0), 0, R(0), 0, 0)
    return ForkChoice(store, proto, minimal_spec(), MinimalEthSpec)


def _indexed(T, slot, head_root, target_epoch, target_root, indices):
    return T.IndexedAttestation(
        attesting_indices=list(indices),
        data=T.AttestationData(
            slot=slot,
            index=0,
            beacon_block_root=head_root,
            source=T.Checkpoint(epoch=0, root=R(0)),
            target=T.Checkpoint(epoch=target_epoch, root=target_root),
        ),
        signature=b"\x00" * 96,
    )


def test_on_attestation_batch_validates_groups_and_filters_equivocators():
    T = build_types(MinimalEthSpec)
    E = MinimalEthSpec
    fc = _wrapper(current_slot=E.SLOTS_PER_EPOCH + 2)
    fc.proto.on_block(
        slot=1, root=R(1), parent_root=R(0), state_root=R(1),
        justified_epoch=0, finalized_epoch=0,
    )
    e1 = E.SLOTS_PER_EPOCH
    fc.proto.on_block(
        slot=e1, root=R(2), parent_root=R(1), state_root=R(2),
        justified_epoch=0, finalized_epoch=0,
    )
    fc.store.equivocating_indices.add(3)
    slot = e1 + 1
    batch = [
        _indexed(T, slot, R(2), 1, R(2), (0, 1, 3)),   # valid; 3 equivocates
        _indexed(T, slot, R(2), 1, R(1), (4,)),        # FFG-inconsistent
        _indexed(T, slot, R(2), 1, R(2), (5, 6)),      # valid, same group
    ]
    counter = REGISTRY.counter("fork_choice_votes_applied_total")
    before = counter.value(path="batch")
    results = fc.on_attestation_batch(batch)
    assert results[0] is None and results[2] is None
    assert isinstance(results[1], InvalidAttestation)
    # 4 accepted votes (0, 1, 5, 6) in ONE grouped vectorized write; the
    # equivocating validator's vote never lands
    assert counter.value(path="batch") - before == 4
    proto = fc.proto
    rid = proto.proto_array._root_ids[R(2)]
    assert proto._next_rid[0] == rid and proto._next_rid[5] == rid
    assert int(proto._next_rid[3]) == 0
    assert int(proto._next_rid[4]) == 0


def test_on_attestation_batch_matches_sequential_on_attestation():
    T = build_types(MinimalEthSpec)
    E = MinimalEthSpec
    a, b = (
        _wrapper(current_slot=E.SLOTS_PER_EPOCH + 2),
        _wrapper(current_slot=E.SLOTS_PER_EPOCH + 2),
    )
    for fc in (a, b):
        fc.proto.on_block(
            slot=1, root=R(1), parent_root=R(0), state_root=R(1),
            justified_epoch=0, finalized_epoch=0,
        )
        fc.proto.on_block(
            slot=E.SLOTS_PER_EPOCH, root=R(2), parent_root=R(1),
            state_root=R(2), justified_epoch=0, finalized_epoch=0,
        )
    slot = E.SLOTS_PER_EPOCH + 1
    batch = [
        _indexed(T, slot, R(2), 1, R(2), (0, 1, 2)),
        _indexed(T, slot, R(2), 1, R(2), (2, 5)),
    ]
    a.on_attestation_batch(batch)
    for ia in batch:
        b.on_attestation(ia)
    m = len(b.proto._next_rid)
    assert a.proto._next_rid[:m].tolist() == b.proto._next_rid[:m].tolist()
    assert (
        a.proto._next_epoch[:m].tolist() == b.proto._next_epoch[:m].tolist()
    )


# ---------------------------------------------------------------------------
# perf_smoke: the columnar path engages
# ---------------------------------------------------------------------------


@pytest.mark.perf_smoke
def test_perf_smoke_batch_path_engages_and_stays_flat():
    """100k votes ingested through the batch entry + one get_head: the
    batch counter must account for every vote (no per-validator single
    fallback), the get_head stage spans must fire, and the wall clock
    stays array-program flat."""
    import time

    n_val = 100_000
    fc = ProtoArrayForkChoice(R(0), 0, R(0), 0, 0)
    for i in range(1, 17):
        fc.on_block(
            slot=i, root=R(i), parent_root=R(i - 1), state_root=R(i),
            justified_epoch=0, finalized_epoch=0,
        )
    counter = REGISTRY.counter("fork_choice_votes_applied_total")
    b_batch = counter.value(path="batch")
    b_single = counter.value(path="single")
    span_count = REGISTRY.histogram("trace_span_seconds_delta_compute").count
    balances = np.full(n_val, 32_000_000_000, dtype=np.uint64)
    idx = np.arange(n_val, dtype=np.int64)
    t0 = time.perf_counter()
    for start in range(0, n_val, 16384):
        fc.process_attestation_batch(
            idx[start : start + 16384], R(16), 1
        )
    head = fc.get_head(
        justified_checkpoint_root=R(0), justified_epoch=0,
        finalized_epoch=0, justified_state_balances=balances,
    )
    elapsed = time.perf_counter() - t0
    assert head == R(16)
    assert counter.value(path="batch") - b_batch == n_val
    assert counter.value(path="single") - b_single == 0
    assert (
        REGISTRY.histogram("trace_span_seconds_delta_compute").count
        > span_count
    )
    # generous bound: the scalar oracle needs seconds for the same work
    assert elapsed < 1.5, f"batch ingest + get_head took {elapsed:.2f}s"


def test_balances_held_without_copy():
    """The proto-array must hold the caller's uint64 balance array by
    reference (the scalar oracle copied a full Python list per get_head);
    the wrapper replaces the array wholesale on justified changes, so no
    copy is needed on the steady path."""
    fc = ProtoArrayForkChoice(R(0), 0, R(0), 0, 0)
    fc.on_block(
        slot=1, root=R(1), parent_root=R(0), state_root=R(1),
        justified_epoch=0, finalized_epoch=0,
    )
    balances = np.full(8, 10, dtype=np.uint64)
    fc.get_head(
        justified_checkpoint_root=R(0), justified_epoch=0,
        finalized_epoch=0, justified_state_balances=balances,
    )
    assert fc.balances is balances
