"""KZG commitments + blob proofs + DA checker.

Math validated on a small (n=64) insecure dev setup — the scheme is
size-generic; full 4096-element blobs ride the same code (ef-test style
coverage for commit/prove/verify, domain-point openings, batch RLC)."""

import hashlib
import random
from types import SimpleNamespace

import pytest

from lighthouse_tpu.crypto.kzg import (
    FR_MODULUS,
    Kzg,
    KzgError,
    TrustedSetup,
    fft_fr,
)

N = 64


@pytest.fixture(scope="module")
def kzg():
    return Kzg(TrustedSetup.insecure_dev(N))


def _blob(seed: int, n: int = N) -> bytes:
    rng = random.Random(seed)
    return b"".join(
        rng.randrange(FR_MODULUS).to_bytes(32, "big") for _ in range(n)
    )


def test_default_setup_resolves_mainnet_ceremony():
    """default() prefers the real ceremony output when one is reachable
    (env var, packaged file, or the known public locations) and only then
    falls back to the insecure dev setup. On this image the reference's
    embedded ceremony JSON is present, so default() must be mainnet-sized
    and commit the zero blob to the identity point."""
    import os

    ts = TrustedSetup.default()
    if not os.environ.get("LIGHTHOUSE_TPU_TRUSTED_SETUP") and not any(
        os.path.exists(p) for p in TrustedSetup.CEREMONY_SEARCH_PATHS
    ):
        pytest.skip("no ceremony file reachable; dev fallback expected")
    assert ts.n == 4096
    c = Kzg(ts).blob_to_kzg_commitment(bytes(4096 * 32))
    assert c[0] == 0xC0 and set(c[1:]) == {0}  # point at infinity


def test_fft_roundtrip():
    rng = random.Random(1)
    coeffs = [rng.randrange(FR_MODULUS) for _ in range(16)]
    evals = fft_fr(coeffs)
    back = fft_fr(evals, inverse=True)
    assert back == coeffs


def test_fft_evaluates_polynomial():
    # p(x) = 3 + 5x + 7x² on the order-4 domain
    coeffs = [3, 5, 7, 0]
    evals = fft_fr(coeffs)
    from lighthouse_tpu.crypto.kzg import _root_of_unity

    w = _root_of_unity(4)
    for i, e in enumerate(evals):
        x = pow(w, i, FR_MODULUS)
        assert e == (3 + 5 * x + 7 * x * x) % FR_MODULUS


def test_commit_prove_verify(kzg):
    blob = _blob(2)
    c = kzg.blob_to_kzg_commitment(blob)
    z = (12345).to_bytes(32, "big")
    proof, y = kzg.compute_kzg_proof(blob, z)
    assert kzg.verify_kzg_proof(c, z, y, proof)
    # wrong y rejected
    bad_y = ((int.from_bytes(y, "big") + 1) % FR_MODULUS).to_bytes(32, "big")
    assert not kzg.verify_kzg_proof(c, z, bad_y, proof)


def test_proof_at_domain_point(kzg):
    blob = _blob(3)
    c = kzg.blob_to_kzg_commitment(blob)
    z = kzg.setup.roots_brp[5].to_bytes(32, "big")
    proof, y = kzg.compute_kzg_proof(blob, z)
    # y must equal the raw evaluation stored in the blob at brp index 5
    assert int.from_bytes(y, "big") == int.from_bytes(blob[5 * 32 : 6 * 32], "big")
    assert kzg.verify_kzg_proof(c, z, y, proof)


def test_blob_proof_roundtrip(kzg):
    blob = _blob(4)
    c = kzg.blob_to_kzg_commitment(blob)
    proof = kzg.compute_blob_kzg_proof(blob, c)
    assert kzg.verify_blob_kzg_proof(blob, c, proof)
    tampered = bytearray(blob)
    tampered[33] ^= 1
    assert not kzg.verify_blob_kzg_proof(bytes(tampered), c, proof)


def test_blob_batch_verify(kzg):
    blobs = [_blob(i) for i in range(5, 8)]
    cs = [kzg.blob_to_kzg_commitment(b) for b in blobs]
    proofs = [kzg.compute_blob_kzg_proof(b, c) for b, c in zip(blobs, cs)]
    assert kzg.verify_blob_kzg_proof_batch(blobs, cs, proofs)
    # a swapped proof breaks the batch
    assert not kzg.verify_blob_kzg_proof_batch(blobs, cs, proofs[::-1])
    assert kzg.verify_blob_kzg_proof_batch([], [], [])


def test_field_element_range(kzg):
    blob = bytearray(_blob(9))
    blob[0:32] = (FR_MODULUS + 1).to_bytes(32, "big")
    with pytest.raises(KzgError):
        kzg.blob_to_kzg_commitment(bytes(blob))


def test_da_checker_flow(kzg):
    from lighthouse_tpu.beacon_chain.data_availability import (
        AvailabilityCheckError,
        DataAvailabilityChecker,
    )

    E = SimpleNamespace(MAX_BLOBS_PER_BLOCK=6)
    checker = DataAvailabilityChecker(kzg, E)

    blobs = [_blob(20), _blob(21)]
    cs = [kzg.blob_to_kzg_commitment(b) for b in blobs]
    proofs = [kzg.compute_blob_kzg_proof(b, c) for b, c in zip(blobs, cs)]
    sidecars = [
        SimpleNamespace(index=i, blob=b, kzg_commitment=c, kzg_proof=p)
        for i, (b, c, p) in enumerate(zip(blobs, cs, proofs))
    ]
    block = SimpleNamespace(
        message=SimpleNamespace(body=SimpleNamespace(blob_kzg_commitments=cs))
    )
    root = hashlib.sha256(b"block").digest()

    # block first: pending
    avail = checker.put_block(root, block)
    assert not avail.available
    # one blob: still pending
    avail = checker.put_blobs(root, sidecars[:1])
    assert not avail.available
    # second blob: complete — and non-destructive until the import pops it
    avail = checker.put_blobs(root, sidecars[1:])
    assert avail.available
    assert len(avail.blobs) == 2
    assert checker.has_pending(root)
    assert checker.check_availability(root).available  # re-checkable
    checker.pop(root)
    assert not checker.has_pending(root)

    # tampered proof rejected outright
    bad = SimpleNamespace(
        index=0, blob=blobs[0], kzg_commitment=cs[0], kzg_proof=proofs[1]
    )
    with pytest.raises(AvailabilityCheckError):
        checker.put_blobs(hashlib.sha256(b"other").digest(), [bad])

    # commitment mismatch vs block detected at completion; the poisoned
    # index is dropped so an honest re-send still completes the set
    root2 = hashlib.sha256(b"block2").digest()
    checker.put_block(root2, block)
    wrong_c = kzg.blob_to_kzg_commitment(_blob(99))
    proof_w = kzg.compute_blob_kzg_proof(_blob(99), wrong_c)
    mism = [
        SimpleNamespace(index=0, blob=_blob(99), kzg_commitment=wrong_c, kzg_proof=proof_w),
        sidecars[1],
    ]
    with pytest.raises(AvailabilityCheckError):
        checker.put_blobs(root2, mism)
    avail = checker.put_blobs(root2, sidecars[:1])  # honest recovery
    assert avail.available

    # finalization prune drops stale pending entries
    root3 = hashlib.sha256(b"stale").digest()
    checker.put_block(root3, block, slot=3)
    checker.prune_before(10)
    assert not checker.has_pending(root3)


# ---------------------------------------------------------------------------
# device fallback observability + strict mode (LIGHTHOUSE_TPU_STRICT_DEVICE)
# ---------------------------------------------------------------------------


class _ExplodingDev:
    def boom(self):
        raise RuntimeError("simulated remote-compile failure")


def test_device_fallback_is_counted_and_disables_device(kzg, monkeypatch):
    from lighthouse_tpu.metrics import REGISTRY

    monkeypatch.delenv("LIGHTHOUSE_TPU_STRICT_DEVICE", raising=False)
    counter = REGISTRY.counter("kzg_device_fallback_total")
    before = counter.value(stage="call")
    kzg._dev = _ExplodingDev()
    kzg._dev_warned = False
    assert kzg._device_call(lambda d: d.boom()) is None  # host fallback
    assert counter.value(stage="call") == before + 1
    assert kzg._dev is None  # device path disabled after the failure
    assert kzg.verify_blob_kzg_proof_device_stats() == {"device": False}


def test_device_fallback_strict_mode_raises(kzg, monkeypatch):
    from lighthouse_tpu.metrics import REGISTRY

    monkeypatch.setenv("LIGHTHOUSE_TPU_STRICT_DEVICE", "1")
    counter = REGISTRY.counter("kzg_device_fallback_total")
    before = counter.value(stage="call")
    kzg._dev = _ExplodingDev()
    with pytest.raises(KzgError, match="STRICT_DEVICE"):
        kzg._device_call(lambda d: d.boom())
    assert counter.value(stage="call") == before + 1  # still observable
    assert kzg._dev is None


def test_device_call_noop_when_no_device(kzg, monkeypatch):
    monkeypatch.setenv("LIGHTHOUSE_TPU_STRICT_DEVICE", "1")
    kzg._dev = None
    # no device configured at all is NOT a fallback event: strict mode
    # only guards a device path that was supposed to be live
    assert kzg._device_call(lambda d: d.boom()) is None
