"""Checkpoint (weak-subjectivity) sync + backfill.

Node A runs 3 epochs; node B boots from A's finalized checkpoint state,
follows the head forward, and backfills history to genesis over the RPC
(ClientGenesis::WeakSubjSszBytes + BackFillSync analog)."""

import time
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.chain import BeaconChain, BeaconChainError
from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.network import NetworkService
from lighthouse_tpu.store import HotColdDB, MemoryStore
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
from lighthouse_tpu.utils.slot_clock import ManualSlotClock


@pytest.fixture()
def source_chain():
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(4 * E.SLOTS_PER_EPOCH)
    assert h.finalized_epoch >= 1
    return h


def _checkpoint_of(h):
    """(state, block) at the source chain's finalized checkpoint."""
    fin = h.chain.finalized_checkpoint
    block = h.chain._blocks_by_root[fin.root]
    state = h.chain._justified_state_provider(fin.root)
    return state.copy(), block


def test_from_checkpoint_boots_and_follows(source_chain):
    h = source_chain
    state, block = _checkpoint_of(h)
    clock = ManualSlotClock(
        genesis_time=state.genesis_time,
        seconds_per_slot=h.spec.seconds_per_slot,
    )
    chain_b = BeaconChain.from_checkpoint(
        HotColdDB(MemoryStore()), state, block, h.spec, E, clock,
        wss_checkpoint=block.message.hash_tree_root(),
    )
    assert chain_b.anchor_slot == block.message.slot
    assert chain_b.head_root == block.message.hash_tree_root()

    na = NetworkService(h.chain).start()
    nb = NetworkService(chain_b).start()
    try:
        clock.set_slot(h.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", na.port)
        imported = nb.sync.sync_with(peer)
        assert imported > 0
        assert chain_b.head_root == h.chain.head_root

        # backfill reconstructs the COMPLETE pre-anchor history (blocks at
        # slots 1..anchor-1; every slot has a block in this chain)
        stored = nb.sync.backfill(peer)
        assert stored == block.message.slot - 1
        # the full chain back to slot 1 is now served from B's store
        r = block.message.parent_root
        walked = 0
        while r != b"\x00" * 32:
            blk = chain_b.store.get_block(r)
            if blk is None:
                break
            walked += 1
            r = blk.message.parent_root
        assert walked == stored
        assert walked >= block.message.slot - 1
    finally:
        na.stop()
        nb.stop()


def test_wss_checkpoint_mismatch_refused(source_chain):
    h = source_chain
    state, block = _checkpoint_of(h)
    clock = ManualSlotClock(genesis_time=state.genesis_time, seconds_per_slot=12)
    with pytest.raises(BeaconChainError):
        BeaconChain.from_checkpoint(
            HotColdDB(MemoryStore()), state, block, h.spec, E, clock,
            wss_checkpoint=b"\x13" * 32,
        )


def test_backfill_rejects_broken_hash_chain(source_chain):
    h = source_chain
    state, block = _checkpoint_of(h)
    clock = ManualSlotClock(genesis_time=state.genesis_time, seconds_per_slot=12)
    chain_b = BeaconChain.from_checkpoint(
        HotColdDB(MemoryStore()), state, block, h.spec, E, clock
    )
    # corrupt one historic block on the serving side
    victim_slot = max(1, block.message.slot - 2)
    victim_root = None
    for root, blk in h.chain._blocks_by_root.items():
        if blk.message.slot == victim_slot:
            victim_root = root
            break
    tampered = h.chain._blocks_by_root[victim_root].copy()
    tampered.message.state_root = b"\x66" * 32
    h.chain._blocks_by_root[victim_root] = tampered

    na = NetworkService(h.chain).start()
    nb = NetworkService(chain_b).start()
    try:
        clock.set_slot(h.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", na.port)
        stored = nb.sync.backfill(peer)
        # linkage breaks at the tampered block: nothing below it stored
        assert chain_b.store.get_block(victim_root) is None
        assert stored <= block.message.slot - victim_slot
    finally:
        na.stop()
        nb.stop()
