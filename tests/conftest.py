"""Test configuration: force an 8-device virtual CPU mesh.

The image's sitecustomize eagerly registers the axon TPU backend and pins
JAX_PLATFORMS=axon, so we must override via jax.config after import. Multi-chip
TPU hardware isn't available in CI; sharding correctness is validated on a
virtual host-platform mesh exactly as the driver's dryrun_multichip does (see
__graft_entry__.py).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compile cache: this XLA CPU build compiles slowly; cache across runs.
from lighthouse_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache()


def pytest_sessionstart(session):
    """Tier-1 guard: the BLS verification caches must export hit/miss
    counters through the metrics registry (the bench JSON and /metrics
    consumers rely on the series existing even at zero)."""
    from lighthouse_tpu.analysis import sanitizer  # noqa: F401 — registers
    from lighthouse_tpu.beacon_chain import (  # noqa: F401 — registers
        attestation_verification,  # gossip observation-delay histograms
        block_times_cache,  # slot-anchored block-delay histograms
    )
    from lighthouse_tpu.beacon_processor import (  # noqa: F401 — registers
        WorkType,  # queue-wait/work histograms + depth/busy gauges
    )
    from lighthouse_tpu.crypto import bls  # noqa: F401 — registers counters
    from lighthouse_tpu.fork_choice import (  # noqa: F401 — registers
        fork_choice,  # deferred-attestation outcome counters
        proto_array,  # vote-path counter + get_head stage span histograms
    )
    from lighthouse_tpu.beacon_chain import (  # noqa: F401 — registers
        state_advance,  # snapshot cache counters + production stage spans
    )
    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.metrics import profiler  # noqa: F401 — registers
    from lighthouse_tpu.metrics import trace_collector  # noqa: F401 — registers
    from lighthouse_tpu.network import rpc  # noqa: F401 — registers rpc series
    from lighthouse_tpu.network import sync  # noqa: F401 — registers sync series
    from lighthouse_tpu.network.gossipsub import (  # noqa: F401 — registers
        behaviour,  # mesh gauges + peer-score distribution histogram
    )
    from lighthouse_tpu.utils import compile_cache  # noqa: F401 — registers
    from lighthouse_tpu.state_processing import (  # noqa: F401 — registers
        attestation_batch,  # the batch path counter + attestation_apply span
        registry_columns,  # the columns counters + epoch_stage spans
    )
    import lighthouse_tpu.slasher  # noqa: F401 — registers slasher_* series
    from lighthouse_tpu.http_api import (  # noqa: F401 — registers api series
        columnar,  # assembly counter + cache_lookup/assemble/serialize spans
    )
    from lighthouse_tpu.testing import (  # noqa: F401 — registers testnet_*
        testnet,  # fault-injection/drop/delay counters + oracle outcomes
    )
    import lighthouse_tpu.das  # noqa: F401 — registers das_* series + spans
    from lighthouse_tpu.beacon_chain import (  # noqa: F401 — registers
        events,  # sse_* fan-out tier series
    )
    from lighthouse_tpu.http_api import (  # noqa: F401 — registers
        workers,  # api_worker_* serving-replica series
    )
    import lighthouse_tpu.validator_client  # noqa: F401 — registers vc_*
    # counters + vc_duty_cycle stage spans (bls_sign_batch_total comes
    # with the crypto.bls import above)
    from lighthouse_tpu.store import (  # noqa: F401 — registers store_*
        migrator,  # migration/reconstruction counters + prune spans
    )
    from lighthouse_tpu.beacon_chain import (  # noqa: F401 — registers
        checkpoint_sync,  # boot counter + anchor-slot gauge
    )

    text = REGISTRY.expose()
    for needle in (
        "bls_cache_hits_total",
        "bls_cache_misses_total",
        # PR 4: the host fork-pool task counter and the batch-verify path
        # counter must exist at zero (bench/asserts read them eagerly)
        'bls_pool_tasks_total{mode="inline"}',
        'bls_pool_tasks_total{mode="fork"}',
        'bls_batch_verify_total{path="msm"}',
        'bls_batch_verify_total{path="serial"}',
        # PR 5: the sync engine's gauge/counter series must exist at zero
        # — the sync_catchup bench and dashboards read them eagerly
        "sync_state",
        'sync_batch_downloads_total{chain="range"}',
        'sync_batch_downloads_total{chain="backfill"}',
        'sync_batch_retries_total{chain="range"}',
        'sync_batch_retries_total{chain="backfill"}',
        'sync_batch_failures_total{chain="range"}',
        'sync_batch_failures_total{chain="backfill"}',
        'sync_lookups_started_total{kind="single"}',
        'sync_lookups_started_total{kind="parent"}',
        "sync_lookups_completed_total",
        "sync_lookups_failed_total",
        "sync_lookup_reprocess_drained_total",
        # PR 6: the resident-columns counters and the per-stage epoch
        # spans must exist at zero — the epoch_transition benches and
        # the perf_smoke zero-rebuild guard read them eagerly
        'registry_columns_rebuilds_total{field="validators"}',
        'registry_columns_rebuilds_total{field="balances"}',
        'registry_columns_rebuilds_total{field="inactivity_scores"}',
        'registry_columns_row_writebacks_total{field="validators"}',
        'registry_columns_row_writebacks_total{field="balances"}',
        'registry_columns_row_writebacks_total{field="inactivity_scores"}',
        "trace_span_seconds_epoch_stage_columns_refresh",
        "trace_span_seconds_epoch_stage_justification",
        "trace_span_seconds_epoch_stage_inactivity",
        "trace_span_seconds_epoch_stage_rewards",
        "trace_span_seconds_epoch_stage_registry_updates",
        "trace_span_seconds_epoch_stage_slashings",
        "trace_span_seconds_epoch_stage_effective_balances",
        "trace_span_seconds_epoch_stage_final_updates",
        # PR 7: the columnar attestation pipeline's path counter, the
        # participation-column counters, and the apply span must exist at
        # zero — the attestation_batch bench and the perf_smoke
        # no-scalar-fallback guard read them eagerly
        'attestation_batch_total{path="columnar"}',
        'attestation_batch_total{path="scalar"}',
        'attestation_batch_total{path="scalar_small"}',
        'registry_columns_rebuilds_total{field="previous_epoch_participation"}',
        'registry_columns_rebuilds_total{field="current_epoch_participation"}',
        'registry_columns_row_writebacks_total{field="previous_epoch_participation"}',
        'registry_columns_row_writebacks_total{field="current_epoch_participation"}',
        "trace_span_seconds_attestation_apply",
        # PR 8: the beacon-san runtime sanitizer's violation counters must
        # exist at zero for every rule (dashboards and the sanitize soak
        # read them eagerly)
        'sanitizer_violations_total{rule="cow-write"}',
        'sanitizer_violations_total{rule="u64-wrap"}',
        'sanitizer_violations_total{rule="stale-read"}',
        # PR 9: observability pipeline series — trace collector, queue
        # observability, slot-anchored block/attestation delays — must
        # exist at zero (the traces endpoints, sync_catchup queue-wait
        # breakdown and dashboards read them eagerly)
        'trace_collector_traces_total{root="block_import"}',
        'trace_collector_traces_total{root="epoch_transition"}',
        'trace_collector_traces_total{root="attestation_batch"}',
        'trace_collector_traces_total{root="sync_range_batch"}',
        'trace_collector_traces_total{root="api_request"}',
        'trace_collector_traces_total{root="other"}',
        "trace_collector_ring_size",
        *(
            f"beacon_processor_queue_wait_seconds_{t.name.lower()}"
            for t in WorkType
        ),
        *(
            f"beacon_processor_work_seconds_{t.name.lower()}"
            for t in WorkType
        ),
        'beacon_processor_queue_depth_by_kind{kind="chain_segment"}',
        'beacon_processor_queue_depth_by_kind{kind="gossip_attestation"}',
        "beacon_processor_queue_depth",
        "beacon_processor_workers_busy",
        "beacon_processor_workers_total",
        "beacon_processor_busy_seconds_total",
        "beacon_block_observed_slot_start_delay_seconds",
        "beacon_block_gossip_verified_slot_start_delay_seconds",
        "beacon_block_signature_verified_slot_start_delay_seconds",
        "beacon_block_payload_verified_slot_start_delay_seconds",
        "beacon_block_imported_slot_start_delay_seconds",
        "beacon_block_head_slot_start_delay_seconds",
        "beacon_attestation_gossip_slot_start_delay_seconds",
        "beacon_aggregate_gossip_slot_start_delay_seconds",
        # PR 10: the profiler's sample/overrun counters, the compile-cache
        # counters, the gossip mesh/peer-score series, and the per-method
        # RPC latency histograms must exist at zero — /lighthouse/profile,
        # bench --profile, and dashboards read them eagerly
        'profiler_samples_total{root="block_import"}',
        'profiler_samples_total{root="sync_range_batch"}',
        'profiler_samples_total{root="other"}',
        'profiler_samples_total{root="unattributed"}',
        "profiler_overrun_total",
        "compile_cache_hits_total",
        "compile_cache_misses_total",
        "compile_cache_compile_seconds_total",
        'gossipsub_mesh_peers{topic="beacon_block"}',
        'gossipsub_mesh_peers{topic="beacon_aggregate_and_proof"}',
        "gossipsub_peer_score_distribution",
        "rpc_server_request_seconds_status",
        "rpc_server_request_seconds_beacon_blocks_by_range",
        "rpc_server_request_seconds_blob_sidecars_by_root",
        "rpc_client_request_seconds_status",
        "rpc_client_request_seconds_beacon_blocks_by_range",
        "rpc_client_request_seconds_metadata",
        # PR 11: event-driven node — gossip outcome counters, processor
        # abandonment, the bounded reprocess queue, and the autonomous
        # sync service must exist at zero (the gossip_soak bench and the
        # storm sim read them eagerly)
        "gossip_internal_error_total",
        "gossip_ignored_total",
        'beacon_processor_abandoned_total{kind="gossip_block"}',
        'beacon_processor_abandoned_total{kind="gossip_attestation"}',
        "reprocess_held_total",
        "reprocess_drained_total",
        'reprocess_expired_total{reason="slot"}',
        'reprocess_expired_total{reason="root_cap"}',
        'reprocess_expired_total{reason="total_cap"}',
        'reprocess_expired_total{reason="shutdown"}',
        "reprocess_queue_depth",
        'sync_service_runs_total{result="caught_up"}',
        'sync_service_runs_total{result="progress"}',
        'sync_service_runs_total{result="failed"}',
        "sync_service_backoff_seconds",
        'beacon_processor_queue_depth_by_kind{kind="gossip_sync_committee"}',
        # PR 12: array-program fork choice — the vote-ingestion path
        # counter, the get_head trace root, and its stage spans must
        # exist at zero (the fork_choice bench stage breakdown and the
        # perf_smoke no-scalar-fallback guard read them eagerly)
        'fork_choice_votes_applied_total{path="batch"}',
        'fork_choice_votes_applied_total{path="single"}',
        'trace_collector_traces_total{root="fork_choice_get_head"}',
        "trace_span_seconds_fork_choice_get_head",
        "trace_span_seconds_delta_compute",
        "trace_span_seconds_weight_roll",
        "trace_span_seconds_best_child",
        # PR 13: columnar slasher — engine/scan/tile counters, the
        # slasher_process trace root and its stage spans, and the
        # SLASHER_PROCESS processor lane series must exist at zero (the
        # slasher_ingest bench reads counter deltas + stage spans eagerly)
        "slasher_attester_slashings_found",
        "slasher_proposer_slashings_found",
        'slasher_slashings_found_total{kind="attester"}',
        'slasher_slashings_found_total{kind="proposer"}',
        'slasher_process_cycles_total{engine="columnar"}',
        'slasher_process_cycles_total{engine="reference"}',
        "slasher_attestations_processed_total",
        "slasher_exact_scans_total",
        "slasher_span_tiles_flushed_total",
        "slasher_span_rebuilds_total",
        'trace_collector_traces_total{root="slasher_process"}',
        'profiler_samples_total{root="slasher_process"}',
        "trace_span_seconds_slasher_process",
        "trace_span_seconds_span_gather",
        "trace_span_seconds_span_compare",
        "trace_span_seconds_span_update",
        "trace_span_seconds_persist",
        "beacon_processor_queue_wait_seconds_slasher_process",
        "beacon_processor_work_seconds_slasher_process",
        'beacon_processor_abandoned_total{kind="slasher_process"}',
        # PR 14: the API serving tier — the zero-copy assembly counter,
        # the per-route response-cache counters, and the api_request
        # cache_lookup/assemble/serialize stage spans must exist at zero
        # (the api_throughput bench reads counter deltas + stage spans
        # eagerly)
        'api_columnar_assembly_total{route="validators"}',
        'api_columnar_assembly_total{route="validator_balances"}',
        'api_columnar_assembly_total{route="committees"}',
        'api_columnar_assembly_total{route="headers"}',
        'api_cache_hits_total{route="validators"}',
        'api_cache_misses_total{route="validators"}',
        'api_cache_evictions_total{route="validators"}',
        'api_cache_hits_total{route="headers"}',
        'api_cache_misses_total{route="committees"}',
        'api_cache_evictions_total{route="validator_balances"}',
        "trace_span_seconds_cache_lookup",
        "trace_span_seconds_assemble",
        "trace_span_seconds_serialize",
        # PR 15: the testnet scenario harness — fault-plane verbs, frame
        # drop/delay accounting, oracle outcomes, and the peer-lifecycle
        # recovery counters the partition/heal scenarios assert — must
        # exist at zero (the testnet_soak bench and scenario_smoke read
        # them eagerly)
        'testnet_fault_injections_total{kind="partition"}',
        'testnet_fault_injections_total{kind="heal"}',
        'testnet_fault_injections_total{kind="eclipse"}',
        'testnet_fault_injections_total{kind="delay"}',
        'testnet_fault_injections_total{kind="flood"}',
        'testnet_fault_injections_total{kind="equivocation"}',
        'testnet_fault_injections_total{kind="withhold"}',
        "testnet_gossip_frames_dropped_total",
        "testnet_gossip_frames_delayed_total",
        'scenario_invariant_checks_total{result="pass"}',
        'scenario_invariant_checks_total{result="fail"}',
        'sync_service_backoff_resets_total{reason="new_serving_peer"}',
        'sync_service_backoff_resets_total{reason="peer_connected"}',
        "sync_fork_backtracks_total",
        # PR 16: the PeerDAS series — batched-vs-oracle cell verification,
        # sampling verdicts, reconstruction promotions — must exist at
        # zero (the da_verify bench and the withholding scenario read
        # them eagerly), plus the da_verify stage spans
        'das_cells_verified_total{path="batched"}',
        'das_cells_verified_total{path="oracle"}',
        'das_sampling_results_total{verdict="success"}',
        'das_sampling_results_total{verdict="failure"}',
        "das_reconstructions_total",
        "trace_span_seconds_da_verify",
        "trace_span_seconds_da_derive",
        "trace_span_seconds_da_msm",
        "trace_span_seconds_da_pairing",
        # PR 17: the proposer-pipeline series — snapshot-cache accounting,
        # the block_production trace root's stage spans, and the
        # fork-choice deferral queue outcomes — must exist at zero (the
        # block_production bench reads the stage breakdown eagerly and
        # the fleet scenarios difference the deferral counters)
        "state_advance_hits_total",
        "state_advance_misses_total",
        "state_advance_wasted_total",
        "trace_span_seconds_block_production",
        "trace_span_seconds_advance",
        "trace_span_seconds_pack",
        "trace_span_seconds_sign",
        'fork_choice_deferred_attestations_total{outcome="deferred"}',
        'fork_choice_deferred_attestations_total{outcome="applied"}',
        'fork_choice_deferred_attestations_total{outcome="dropped"}',
        # PR 18: the SSE fan-out tier + serving-worker pool series must
        # exist at zero — the sse_fanout bench differences the delivery/
        # drop counters eagerly, and the worker supervisor's respawn and
        # forwarding accounting is asserted by the lifecycle tests before
        # any worker has ever forked
        "sse_subscribers",
        "sse_events_delivered_total",
        "sse_events_serialized_total",
        'sse_dropped_total{reason="slow_consumer"}',
        'sse_dropped_total{reason="evicted"}',
        'sse_dropped_total{reason="publish_overflow"}',
        "api_worker_processes",
        'api_worker_respawns_total{reason="death"}',
        'api_worker_respawns_total{reason="head_refresh"}',
        'api_worker_events_fanned_total{topic="head"}',
        'api_worker_events_fanned_total{topic="block"}',
        'api_worker_events_fanned_total{topic="finalized_checkpoint"}',
        "api_worker_fan_drops_total",
        'api_worker_requests_forwarded_total{why="stale"}',
        'api_worker_requests_forwarded_total{why="proxy_route"}',
        # PR 19: the batched VC duty pipeline — the vc_epoch_100k bench
        # differences the publish/refusal counters and the sign-strategy
        # split eagerly, and the vc_duty_cycle trace root + stage spans
        # must exist at zero before any duty runs
        "vc_attestations_published_total",
        "vc_blocks_published_total",
        "vc_aggregates_published_total",
        "vc_sync_committee_messages_published_total",
        "vc_slashing_protection_refusals_total",
        'bls_sign_batch_total{path="fixed_base"}',
        'bls_sign_batch_total{path="per_key"}',
        'trace_collector_traces_total{root="vc_duty_cycle"}',
        "trace_span_seconds_vc_duty_cycle",
        "trace_span_seconds_vc_fetch",
        "trace_span_seconds_vc_assemble",
        "trace_span_seconds_vc_protect",
        "trace_span_seconds_vc_sign_batch",
        "trace_span_seconds_vc_publish",
        # PR 20: the storage lifecycle subsystem — the store_soak bench
        # differences the migration counters ON-vs-OFF, the health block
        # mirrors store_split_slot, and the checkpoint_boot_s bench reads
        # the boot counter eagerly (the MIGRATE_STORE queue-wait series
        # is covered by the WorkType loop above)
        "store_migrations_total",
        "store_blocks_migrated_total",
        "store_cold_snapshots_total",
        "store_states_reconstructed_total",
        "store_da_entries_pruned_total",
        "store_split_slot",
        "checkpoint_sync_boots_total",
        "checkpoint_sync_anchor_slot",
        "trace_span_seconds_store_prune",
        "trace_span_seconds_store_reconstruct",
    ):
        assert needle in text, (
            f"metric series {needle} missing from metrics exposition"
        )
    stats = bls.cache_stats()
    for cache in ("pubkey", "signature", "hash_to_g2"):
        assert cache in stats, f"cache_stats() missing the {cache!r} cache"


def pytest_collection_modifyitems(config, items):
    """Tier-1 guard: every test in the device/multichip files MUST carry
    the `slow` marker. Their kernels take minutes of XLA-CPU compile
    cold, and an unmarked test silently drags tier-1 past its window
    (round-5 verdict weak #2). Failing collection keeps the invariant
    enforced rather than documented."""
    import pytest as _pytest

    offenders = []
    for item in items:
        fname = item.path.name if hasattr(item, "path") else ""
        if (
            fname.startswith("test_device_") or fname == "test_multichip.py"
        ) and item.get_closest_marker("slow") is None:
            offenders.append(item.nodeid)
    if offenders:
        raise _pytest.UsageError(
            "device/multichip tests must be marked @pytest.mark.slow "
            "(tier-1 stays fast); unmarked: " + ", ".join(offenders)
        )
