"""Test configuration: force an 8-device virtual CPU mesh.

The image's sitecustomize eagerly registers the axon TPU backend and pins
JAX_PLATFORMS=axon, so we must override via jax.config after import. Multi-chip
TPU hardware isn't available in CI; sharding correctness is validated on a
virtual host-platform mesh exactly as the driver's dryrun_multichip does (see
__graft_entry__.py).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compile cache: this XLA CPU build compiles slowly; cache across runs.
from lighthouse_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache()
