"""The Beacon API serving tier (PR 14): zero-copy columnar response
assembly pinned byte-identical against the retained per-object oracles,
spec validator statuses, id/status filters + pagination boundaries,
head-keyed response caches invalidated through a real block import, the
/headers list route, and the pubkey→index map."""

import json
import urllib.request
from dataclasses import replace

import numpy as np
import pytest

from lighthouse_tpu.beacon_chain.chain import _make_persistent
from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.beacon_chain.events import ServerSentEventHandler
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.http_api import ApiError, BeaconApi, HttpApiServer
from lighthouse_tpu.http_api import columnar
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.state_processing import interop_genesis_state
from lighthouse_tpu.state_processing.registry_columns import (
    registry_columns_for,
)
from lighthouse_tpu.types.chain_spec import FAR_FUTURE_EPOCH, minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

_COMPACT = {"separators": (",", ":")}


def _dump(obj) -> bytes:
    return json.dumps(obj, **_COMPACT).encode()


class _StubChain:
    """The minimum BeaconApi needs to serve state routes (the bench's
    api_throughput fixture uses the same shape)."""

    def __init__(self, state, spec):
        self.head_state = state
        self.head_root = b"\xab" * 32
        self._states = {self.head_root: state}
        self._blocks_by_root = {}
        self.genesis_block_root = self.head_root
        self.genesis_validators_root = bytes(state.genesis_validators_root)
        self.event_handler = ServerSentEventHandler()
        self.spec = spec
        self.E = E
        self.store = None


def _build_state(altair: bool, n: int = 16):
    bls.set_backend("fake_crypto")
    spec = minimal_spec()
    if altair:
        spec = replace(spec, altair_fork_epoch=0)
    state = interop_genesis_state(
        bls.interop_keypairs(n), 1_600_000_000, b"\x42" * 32, spec, E
    )
    _make_persistent(state)
    return state, spec


def _diversify(state):
    """Cover every spec status family (current epoch is 0)."""
    far = FAR_FUTURE_EPOCH

    def mut(i, **kw):
        v = state.validators.mutate(i)
        for k, val in kw.items():
            setattr(v, k, val)

    mut(1, exit_epoch=3, withdrawable_epoch=9)  # active_exiting
    mut(2, slashed=True, exit_epoch=3, withdrawable_epoch=9)  # active_slashed
    mut(3, activation_epoch=far, activation_eligibility_epoch=far)  # pending_initialized
    mut(4, activation_epoch=99, activation_eligibility_epoch=0)  # pending_queued
    mut(5, exit_epoch=0, withdrawable_epoch=0)  # withdrawal_possible
    mut(6, exit_epoch=0, withdrawable_epoch=0)  # withdrawal_done (bal 0)
    state.balances[6] = 0
    mut(7, exit_epoch=0, withdrawable_epoch=9)  # exited_unslashed
    mut(8, slashed=True, exit_epoch=0, withdrawable_epoch=9)  # exited_slashed


@pytest.fixture(params=["altair", "phase0"])
def stub_api(request):
    state, spec = _build_state(altair=request.param == "altair")
    _diversify(state)
    return BeaconApi(_StubChain(state, spec))


# ---------------------------------------------------------------------------
# Vectorized statuses
# ---------------------------------------------------------------------------


def test_status_codes_match_scalar_fuzz():
    rng = np.random.default_rng(5)
    m = 512
    far = np.uint64(FAR_FUTURE_EPOCH)
    picks = np.array([0, 1, 2, 5, 50, FAR_FUTURE_EPOCH], dtype=np.uint64)
    aee = picks[rng.integers(0, picks.size, m)]
    ae = picks[rng.integers(0, picks.size, m)]
    ee = picks[rng.integers(0, picks.size, m)]
    we = picks[rng.integers(0, picks.size, m)]
    slashed = rng.random(m) < 0.3
    bal = np.where(rng.random(m) < 0.2, 0, 32_000_000_000).astype(np.uint64)
    for cur in (0, 1, 3, 49, 51):
        codes = columnar.status_codes(aee, ae, ee, we, slashed, bal, cur)
        for i in range(m):
            want = columnar.validator_status(
                int(aee[i]), int(ae[i]), int(ee[i]), int(we[i]),
                bool(slashed[i]), int(bal[i]), cur,
            )
            assert columnar.STATUSES[codes[i]] == want, (i, cur)
    assert far == np.uint64(FAR_FUTURE_EPOCH)


# ---------------------------------------------------------------------------
# Byte-identical differential: columnar vs per-object oracle
# ---------------------------------------------------------------------------


def test_validators_full_table_byte_identical(stub_api):
    api = stub_api
    body, ctype = api.serve_state_validators("head")
    assert ctype == "application/json"
    ref = _dump(api.state_validators_reference(api.chain.head_state))
    assert body == ref
    # every status family is exercised by the diversified registry
    statuses = {e["status"] for e in json.loads(body)["data"]}
    assert statuses == set(columnar.STATUSES)


def test_validators_filters_byte_identical(stub_api):
    api = stub_api
    st = api.chain.head_state
    full = json.loads(_dump(api.state_validators_reference(st)))
    pk9 = "0x" + bytes(st.validators[9].pubkey).hex()
    cases = [
        {"id": ["0", "9", "3"]},
        {"id": [pk9, "2"]},
        {"status": ["active"]},
        {"status": ["exited_slashed", "pending"]},
        {"limit": "5"},
        {"limit": "4", "offset": "7"},
        {"status": ["active"], "limit": "2", "offset": "1"},
    ]
    for query in cases:
        body, _ = api.serve_state_validators("head", query)
        doc = json.loads(body)
        # expected: filter the oracle's full table the spec way
        rows = full["data"]
        if "id" in query:
            wanted = set()
            for v in query["id"]:
                if v.isdigit():
                    wanted.add(int(v))
                else:
                    wanted.add(9)  # pk9 is the only pubkey used
            rows = [r for r in rows if int(r["index"]) in wanted]
        if "status" in query:
            keep = set()
            for s in query["status"]:
                if s in columnar.STATUS_FAMILIES:
                    keep.update(
                        columnar.STATUSES[c]
                        for c in columnar.STATUS_FAMILIES[s]
                    )
                else:
                    keep.add(s)
            rows = [r for r in rows if r["status"] in keep]
        off = int(query.get("offset", 0))
        lim = query.get("limit")
        rows = rows[off : off + int(lim)] if lim is not None else rows[off:]
        expected = dict(full, data=rows)
        assert doc == expected, query
        # byte-identity against the oracle rendering of the same rows
        assert body == _dump(expected), query


def test_balances_json_and_ssz(stub_api):
    api = stub_api
    st = api.chain.head_state
    body, _ = api.serve_state_validator_balances("head")
    assert body == _dump(api.state_validator_balances_reference(st))
    ssz, ctype = api.serve_state_validator_balances("head", ssz=True)
    assert ctype == "application/octet-stream"
    n = len(st.balances)
    expected = b"".join(
        i.to_bytes(8, "little") + int(st.balances[i]).to_bytes(8, "little")
        for i in range(n)
    )
    assert ssz == expected
    # paginated SSZ slice
    ssz_page, _ = api.serve_state_validator_balances(
        "head", {"limit": "3", "offset": "2"}, ssz=True
    )
    assert ssz_page == expected[2 * 16 : 5 * 16]


def test_committees_byte_identical(stub_api):
    api = stub_api
    body, _ = api.serve_state_committees("head")
    assert body == _dump(api.state_committees("head"))


def test_pagination_boundaries(stub_api):
    api = stub_api
    n = len(api.chain.head_state.balances)
    for query, want in (
        ({"limit": "0"}, 0),
        ({"offset": str(n)}, 0),
        ({"offset": str(n + 50)}, 0),
        ({"limit": str(n * 2)}, n),
        ({"limit": "5", "offset": str(n - 2)}, 2),
    ):
        body, _ = api.serve_state_validators("head", query)
        assert len(json.loads(body)["data"]) == want, query
    for bad in (
        {"limit": "-1"},
        {"limit": "nope"},
        {"offset": "-3"},
        {"status": ["bogus_status"]},
        {"id": ["0xzz"]},
    ):
        with pytest.raises(ApiError) as ei:
            api.serve_state_validators("head", bad)
        assert ei.value.code == 400, bad


def test_id_filter_string_ids_regression(stub_api):
    """The seed compared int indices against the request's STRING ids
    (`i not in indices` — never matched). Mixed string/pubkey ids must
    resolve, out-of-range and unknown ones drop silently."""
    api = stub_api
    st = api.chain.head_state
    pk = "0x" + bytes(st.validators[4].pubkey).hex()
    unknown_pk = "0x" + "77" * 48
    body, _ = api.serve_state_validators(
        "head", {"id": ["3", pk, "999999", unknown_pk]}
    )
    got = [e["index"] for e in json.loads(body)["data"]]
    assert got == ["3", "4"]
    # the oracle entry normalizes the same way
    doc = api.state_validators("head", ["3", pk, "999999", unknown_pk])
    assert [e["index"] for e in doc["data"]] == ["3", "4"]


def test_status_filter_on_oracle_path(monkeypatch):
    """A status= filter must work (not 500) when the state has no
    resident columns — the per-object fallback computes codes too."""
    monkeypatch.setenv("LIGHTHOUSE_TPU_RESIDENT_COLUMNS", "0")
    state, spec = _build_state(altair=True)
    _diversify(state)
    api = BeaconApi(_StubChain(state, spec))
    body, _ = api.serve_state_validators("head", {"status": ["exited_slashed"]})
    assert [e["index"] for e in json.loads(body)["data"]] == ["8"]
    # and the oracle fallback body is the same bytes the columnar path
    # produces for the same filter
    monkeypatch.delenv("LIGHTHOUSE_TPU_RESIDENT_COLUMNS")
    api2 = BeaconApi(_StubChain(state, spec))
    body2, _ = api2.serve_state_validators(
        "head", {"status": ["exited_slashed"]}
    )
    assert body2 == body


def test_block_index_survives_balanced_prune_and_import(stub_api):
    """A prune balanced by an equal number of imports (hot-map length
    unchanged) must still drop the pruned root and index the new one."""
    from lighthouse_tpu.http_api.block_index import BlockHeaderIndex

    class _Blk:
        def __init__(self, slot, parent):
            import types as _t

            body = _t.SimpleNamespace(hash_tree_root=lambda: b"\x0b" * 32)
            self.message = _t.SimpleNamespace(
                slot=slot, proposer_index=0, parent_root=parent,
                state_root=b"\x05" * 32, body=body,
            )
            self.signature = b"\x0c" * 96

    chain = stub_api.chain
    chain._blocks_by_root = {
        b"\x01" * 32: _Blk(7, b"\x00" * 32),
        b"\x02" * 32: _Blk(8, b"\x01" * 32),
    }
    index = BlockHeaderIndex(chain)
    assert index.roots_at_slot(7) == [b"\x01" * 32]
    # prune one, import one: same dict length
    del chain._blocks_by_root[b"\x01" * 32]
    chain._blocks_by_root[b"\x03" * 32] = _Blk(9, b"\x02" * 32)
    assert index.roots_at_slot(7) == []  # pruned root gone
    assert index.roots_at_slot(9) == [b"\x03" * 32]  # new root indexed
    assert index.roots_by_parent(b"\x02" * 32) == [b"\x03" * 32]


def test_server_stop_detaches_listeners():
    state, spec = _build_state(altair=True)
    chain = _StubChain(state, spec)
    api = BeaconApi(chain)
    assert len(chain.event_handler._listeners) == 2
    api.close()
    assert chain.event_handler._listeners == []


def test_columnar_assembly_counted_oracle_not(stub_api):
    api = stub_api
    c = REGISTRY.counter("api_columnar_assembly_total")
    before = c.value(route="validators")
    api.response_cache.clear()
    api.serve_state_validators("head")
    assert c.value(route="validators") == before + 1
    api.state_validators_reference(api.chain.head_state)
    assert c.value(route="validators") == before + 1  # oracle never counts


# ---------------------------------------------------------------------------
# Single validator + pubkey→index map
# ---------------------------------------------------------------------------


def test_single_validator_real_status_and_map(stub_api):
    api = stub_api
    doc = api.state_validator("head", "8")
    assert doc["data"]["status"] == "exited_slashed"
    pk = doc["data"]["validator"]["pubkey"]
    by_pk = api.state_validator("head", pk)
    assert by_pk["data"]["index"] == "8"
    assert by_pk == doc
    with pytest.raises(ApiError) as ei:
        api.state_validator("head", "0x" + "99" * 48)
    assert ei.value.code == 404
    with pytest.raises(ApiError) as ei:
        api.state_validator("head", "0x1234")
    assert ei.value.code == 400


def test_pubkey_index_first_occurrence_and_growth():
    state, spec = _build_state(altair=True, n=8)
    cols = registry_columns_for(state)
    cols.refresh(state)
    # duplicate pubkey: index must resolve to the FIRST occurrence
    v = state.validators.mutate(5)
    v.pubkey = bytes(state.validators[2].pubkey)
    cols.refresh(state)
    assert cols.pubkey_index()[bytes(state.validators[2].pubkey)] == 2
    # growth invalidates: an appended validator becomes findable
    new = state.validators[0].copy()
    new.pubkey = b"\x31" * 48
    state.validators.append(new)
    cols.refresh(state)
    assert cols.pubkey_index()[b"\x31" * 48] == len(state.validators) - 1


# ---------------------------------------------------------------------------
# Response cache: hit/miss, head-change invalidation via real import
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def rig():
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(E.SLOTS_PER_EPOCH + 2)
    server = HttpApiServer(h.chain).start()
    yield h, server
    server.stop()


def _get(server, path, accept=None):
    req = urllib.request.Request(f"http://127.0.0.1:{server.port}{path}")
    if accept:
        req.add_header("Accept", accept)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            data = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            return resp.status, (json.loads(data) if "json" in ctype else data)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_cache_hit_and_head_change_invalidation(rig):
    h, server = rig
    api = server.api
    hits = REGISTRY.counter("api_cache_hits_total")
    misses = REGISTRY.counter("api_cache_misses_total")
    evictions = REGISTRY.counter("api_cache_evictions_total")
    api.response_cache.clear()
    h0, m0, e0 = (
        hits.value(route="validators"),
        misses.value(route="validators"),
        evictions.value(route="validators"),
    )
    _, first = _get(server, "/eth/v1/beacon/states/head/validators")
    assert misses.value(route="validators") == m0 + 1
    _, second = _get(server, "/eth/v1/beacon/states/head/validators")
    assert hits.value(route="validators") == h0 + 1
    assert first == second
    assert len(api.response_cache) >= 1
    # a REAL block import moves the head; the head event (the same one
    # the SSE stream consumes) evicts entries keyed to the old head
    h.extend_chain(1)
    assert evictions.value(route="validators") >= e0 + 1
    _, third = _get(server, "/eth/v1/beacon/states/head/validators")
    assert misses.value(route="validators") == m0 + 2
    # and the fresh body is byte-identical to the oracle on the NEW head
    body, _ = api.serve_state_validators("head")
    assert body == _dump(api.state_validators_reference(h.chain.head_state))


def test_cache_byte_budget_lru():
    from lighthouse_tpu.http_api.response_cache import ResponseCache

    cache = ResponseCache(max_bytes=100)
    cache.put("validators", b"\x01" * 32, "a", b"x" * 40, "application/json")
    cache.put("validators", b"\x01" * 32, "b", b"y" * 40, "application/json")
    assert len(cache) == 2
    cache.put("validators", b"\x01" * 32, "c", b"z" * 40, "application/json")
    assert len(cache) == 2  # oldest evicted
    assert cache.get("validators", b"\x01" * 32, "a") is None
    assert cache.get("validators", b"\x01" * 32, "c") is not None
    # an over-budget body is served uncached, not stored
    cache.put("validators", b"\x01" * 32, "big", b"w" * 200, "application/json")
    assert cache.get("validators", b"\x01" * 32, "big") is None


def test_cache_generation_guard():
    """A body built before a concurrent invalidation must not be
    re-cached as fresh (the /headers block-event race)."""
    from lighthouse_tpu.http_api.response_cache import ResponseCache

    cache = ResponseCache(max_bytes=1000)
    gen = cache.generation
    cache.evict_route("headers")  # the race: invalidation mid-build
    cache.put("headers", b"\x01" * 32, "q", b"stale", "application/json",
              if_generation=gen)
    assert cache.get("headers", b"\x01" * 32, "q") is None
    cache.put("headers", b"\x01" * 32, "q", b"fresh", "application/json",
              if_generation=cache.generation)
    assert cache.get("headers", b"\x01" * 32, "q")[0] == b"fresh"


def test_trace_stages_recorded(rig):
    _h, server = rig
    server.api.response_cache.clear()
    deltas = {}
    for name in ("cache_lookup", "assemble", "serialize"):
        deltas[name] = REGISTRY.histogram(f"trace_span_seconds_{name}").count
    _get(server, "/eth/v1/beacon/states/head/validators")
    for name in ("cache_lookup", "assemble", "serialize"):
        assert (
            REGISTRY.histogram(f"trace_span_seconds_{name}").count
            > deltas[name]
        ), name


def test_balances_ssz_over_http(rig):
    h, server = rig
    status, raw = _get(
        server,
        "/eth/v1/beacon/states/head/validator_balances",
        accept="application/octet-stream",
    )
    assert status == 200
    st = h.chain.head_state
    assert len(raw) == len(st.balances) * 16
    assert int.from_bytes(raw[8:16], "little") == int(st.balances[0])


# ---------------------------------------------------------------------------
# /headers list + block-root-indexed lookups
# ---------------------------------------------------------------------------


def test_headers_list_route(rig):
    h, server = rig
    head = h.chain.head_block()
    head_slot = int(head.message.slot)
    _, doc = _get(server, "/eth/v1/beacon/headers")
    assert [e["root"] for e in doc["data"]] == [
        "0x" + h.chain.head_root.hex()
    ]
    assert doc["data"][0]["canonical"] is True
    # the list entry equals the single-header route's data
    _, single = _get(server, f"/eth/v1/beacon/headers/{head_slot}")
    assert doc["data"][0]["header"] == single["data"]["header"]
    # slot filter
    _, by_slot = _get(server, f"/eth/v1/beacon/headers?slot={head_slot - 1}")
    assert len(by_slot["data"]) == 1
    assert by_slot["data"][0]["header"]["message"]["slot"] == str(head_slot - 1)
    # parent_root filter finds the head by its parent
    parent = single["data"]["header"]["message"]["parent_root"]
    _, by_parent = _get(
        server, f"/eth/v1/beacon/headers?parent_root={parent}"
    )
    assert [e["root"] for e in by_parent["data"]] == [
        "0x" + h.chain.head_root.hex()
    ]
    _, bad = _get(server, "/eth/v1/beacon/headers?slot=notanum")
    assert bad["code"] == 400


def test_headers_cache_evicted_on_block_event(rig):
    h, server = rig
    evictions = REGISTRY.counter("api_cache_evictions_total")
    server.api.response_cache.clear()
    _get(server, "/eth/v1/beacon/headers")
    e0 = evictions.value(route="headers")
    h.extend_chain(1)
    assert evictions.value(route="headers") >= e0 + 1
    # the fresh listing shows the new head
    _, doc = _get(server, "/eth/v1/beacon/headers")
    assert doc["data"][0]["root"] == "0x" + h.chain.head_root.hex()


def test_block_by_root_served_from_store_after_hot_eviction(rig):
    """Pruned-from-hot blocks serve through the index's store LRU (one
    deserialization per residency, not per request)."""
    h, server = rig
    root = h.chain.head_root
    block = h.chain._blocks_by_root.pop(root)
    try:
        _, doc = _get(server, f"/eth/v1/beacon/headers/0x{root.hex()}")
        assert doc["data"]["root"] == "0x" + root.hex()
        status, ssz = _get(
            server,
            f"/eth/v2/beacon/blocks/0x{root.hex()}",
            accept="application/octet-stream",
        )
        assert status == 200 and ssz == block.serialize()
    finally:
        h.chain._blocks_by_root[root] = block
