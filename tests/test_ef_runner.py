"""ef-test conformance runner over locally generated goldens.

The runner walks the official consensus-spec-tests layout
(testing/ef_tests/src/handler.rs:10-50 analog); goldens come from
lighthouse_tpu.testing.golden_gen since vectors can't be downloaded in
this image. Also covers the bundled snappy decoder (official vectors are
.ssz_snappy) and the all-files-accessed check (Makefile:152 analog)."""

import pathlib

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.testing.ef_tests import (
    check_all_files_accessed,
    run_all,
)
from lighthouse_tpu.testing.golden_gen import generate_goldens
from lighthouse_tpu.testing.snappy import SnappyError, decompress, decompress_raw


@pytest.fixture(scope="module")
def vectors(tmp_path_factory):
    root = tmp_path_factory.mktemp("efvectors")
    n = generate_goldens(root)
    assert n >= 20
    return root


def test_runner_executes_all_families(vectors):
    bls.set_backend("fake_crypto")
    report = run_all(vectors, config="minimal")
    assert report.failed == 0, report.failures[:5]
    # ≥5 case families: operations, sanity, epoch_processing, shuffling,
    # ssz_static, fork
    assert report.passed >= 18
    assert report.skipped == 0


def test_runner_bls_family_real_crypto(vectors):
    bls.set_backend("host")
    try:
        report = run_all(vectors, config="general")
        assert report.failed == 0, report.failures[:5]
        assert report.passed >= 6
    finally:
        bls.set_backend("fake_crypto")


def test_all_files_accessed(vectors):
    bls.set_backend("fake_crypto")
    r1 = run_all(vectors, config="minimal")
    bls.set_backend("host")
    try:
        r2 = run_all(vectors, config="general")
    finally:
        bls.set_backend("fake_crypto")
    accessed = r1.accessed | r2.accessed
    missed = check_all_files_accessed(vectors, accessed)
    assert missed == [], missed


def test_runner_detects_regressions(vectors, tmp_path):
    """Tamper with a golden post-state: the runner must fail the case."""
    import shutil

    bls.set_backend("fake_crypto")
    broken = tmp_path / "broken"
    shutil.copytree(vectors, broken)
    posts = sorted(broken.rglob("epoch_processing/*/pyspec_tests/*/post.ssz"))
    assert posts
    data = bytearray(posts[0].read_bytes())
    data[100] ^= 0xFF
    posts[0].write_bytes(bytes(data))
    report = run_all(broken, config="minimal")
    assert report.failed >= 1


def test_snappy_roundtrip_against_reference_frames():
    # hand-built framed stream: identifier + one uncompressed chunk
    payload = b"hello ef tests" * 10
    frame = (
        b"\xff\x06\x00\x00sNaPpY"
        + b"\x01"
        + (len(payload) + 4).to_bytes(3, "little")
        + b"\x00\x00\x00\x00"
        + payload
    )
    assert decompress(frame) == payload

    # raw block with literals + a copy (compressing a repeat)
    # "abcdabcdabcd": literal "abcd" + copy(offset=4, len=8)
    raw = bytes([12]) + bytes([(4 - 1) << 2]) + b"abcd" + bytes(
        [(1 << 0) | ((8 - 4) << 2) | (0 << 5), 4]
    )
    assert decompress_raw(raw) == b"abcdabcdabcd"

    with pytest.raises(SnappyError):
        decompress_raw(b"\x20\x00")  # truncated
