"""Device epoch sweep parity (single_pass.rs:20 on device, SURVEY §7.3).

The fused jitted rewards/inactivity pass must be BIT-EXACT against the
numpy reference sweep. x64 mode is process-global, so the device run
happens in an isolated subprocess (same pattern as the multichip
dryrun); the oracle runs here."""

import json
import os
import subprocess
import sys
from dataclasses import replace

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every test in this file is tier-2: device sweep — slow XLA-CPU compile.
# tests/conftest.py enforces this marker at collection time.
pytestmark = pytest.mark.slow

_SUBPROC = r"""
import json, os, sys
sys.path.insert(0, sys.argv[1])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["LIGHTHOUSE_TPU_DEVICE_EPOCH_SWEEP"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")
from dataclasses import replace
from lighthouse_tpu.crypto import bls
bls.set_backend("fake_crypto")
from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
from lighthouse_tpu.state_processing import per_slot_processing
from lighthouse_tpu.state_processing.altair import (
    EpochArrays,
    _device_sweep_applicable,
    _device_sweep_enabled,
)

assert _device_sweep_enabled()
spec = replace(minimal_spec(), altair_fork_epoch=0)
h = BeaconChainHarness(spec, E, validator_count=16)
h.extend_chain(3 * E.SLOTS_PER_EPOCH)  # real participation + an epoch miss mix
st = h.chain.head_state.copy()
# the device path must ACTUALLY run — a vacuous numpy-vs-numpy pass
# would hide real divergence
assert _device_sweep_applicable(st, EpochArrays(st, E), spec, E)
# cross the next epoch boundary: epoch processing runs the DEVICE sweep
target = (st.slot // E.SLOTS_PER_EPOCH + 1) * E.SLOTS_PER_EPOCH
while st.slot < target:
    per_slot_processing(st, spec, E)
print(json.dumps({
    "root": st.hash_tree_root().hex(),
    "balances": [int(b) for b in st.balances][:4],
    "scores": [int(s) for s in st.inactivity_scores][:4],
}))
"""


@pytest.mark.slow
def test_device_sweep_bit_exact_vs_numpy():
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(3 * E.SLOTS_PER_EPOCH)
    st = h.chain.head_state.copy()
    from lighthouse_tpu.state_processing import per_slot_processing

    target = (st.slot // E.SLOTS_PER_EPOCH + 1) * E.SLOTS_PER_EPOCH
    while st.slot < target:
        per_slot_processing(st, spec, E)  # numpy sweep (flag unset here)
    oracle_root = st.hash_tree_root().hex()

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    res = subprocess.run(
        [sys.executable, "-c", _SUBPROC, REPO],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=REPO,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    doc = json.loads(res.stdout.strip().splitlines()[-1])
    assert doc["root"] == oracle_root, (
        f"device sweep diverged: {doc} vs numpy root {oracle_root}"
    )
    assert doc["balances"] == [int(b) for b in st.balances][:4]
    assert doc["scores"] == [int(s) for s in st.inactivity_scores][:4]
