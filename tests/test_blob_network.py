"""Blob sidecar persistence + p2p serving (Deneb DA networking).

Store roundtrip (BLOB_SIDECARS column), BlobSidecarsByRange/Root RPC
between two nodes, gossip sidecar staging into the DA checker
(deneb/p2p-interface.md; reference sync/block_sidecar_coupling.rs)."""

import random
import time
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto.kzg import FR_MODULUS, Kzg, TrustedSetup
from lighthouse_tpu.network import NetworkService
from lighthouse_tpu.network import messages as M
from lighthouse_tpu.ssz.merkle_proof import build_blob_sidecars
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

T = build_types(E)


@pytest.fixture(scope="module")
def kzg():
    return Kzg(TrustedSetup.insecure_dev(E.FIELD_ELEMENTS_PER_BLOB))


def _blob(seed, n=E.FIELD_ELEMENTS_PER_BLOB):
    rng = random.Random(seed)
    return b"".join(
        rng.randrange(FR_MODULUS).to_bytes(32, "big") for _ in range(n)
    )


def _sidecars(kzg, seed=1, n_blobs=2, slot=5):
    blobs = [_blob(seed + i) for i in range(n_blobs)]
    commitments = [kzg.blob_to_kzg_commitment(b) for b in blobs]
    body = T.BeaconBlockBodyDeneb(blob_kzg_commitments=commitments)
    block = T.BeaconBlockDeneb(slot=slot, proposer_index=0, body=body)
    signed = T.SignedBeaconBlockDeneb(message=block, signature=b"\x00" * 96)
    return signed, build_blob_sidecars(signed, blobs, kzg, E)


def _harness():
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    return BeaconChainHarness(spec, E, validator_count=16)


def test_store_blob_sidecar_roundtrip(kzg):
    h = _harness()
    signed, sidecars = _sidecars(kzg)
    root = signed.message.hash_tree_root()
    h.chain.store.put_blob_sidecars(root, sidecars)
    got = h.chain.store.get_blob_sidecars(root)
    assert len(got) == 2
    assert [bytes(s.kzg_commitment) for s in got] == [
        bytes(s.kzg_commitment) for s in sidecars
    ]
    assert got[0].serialize() == sidecars[0].serialize()
    assert h.chain.store.get_blob_sidecars(b"\x77" * 32) == []


def test_blob_rpc_by_root_and_range(kzg):
    a = _harness()
    a.extend_chain(2)
    b = _harness()
    na = NetworkService(a.chain).start()
    nb = NetworkService(b.chain).start()
    try:
        # stash sidecars under A's head block root (the canonical chain
        # walk serves them for its slot range)
        head_root = a.chain.head_root
        _signed, sidecars = _sidecars(kzg, slot=a.chain.head_state.slot)
        a.chain.store.put_blob_sidecars(head_root, sidecars)

        peer = nb.connect("127.0.0.1", na.port)
        ids = [
            M.BlobIdentifier(block_root=head_root, index=i) for i in range(2)
        ]
        got = peer.client.blob_sidecars_by_root(ids, T.BlobSidecar.deserialize)
        assert len(got) == 2
        assert [int(s.index) for s in got] == [0, 1]

        got = peer.client.blob_sidecars_by_range(
            1, a.chain.head_state.slot, T.BlobSidecar.deserialize
        )
        assert len(got) == 2  # only the head block has sidecars
    finally:
        na.stop()
        nb.stop()


def test_sidecar_completion_triggers_block_import(kzg):
    """A block that failed its DA gate (arrived before its last sidecar)
    must be imported the moment the completing sidecar lands — gossip
    dedup means nobody will re-send the block."""
    from lighthouse_tpu.beacon_chain.data_availability import Availability

    h = _harness()
    na = NetworkService(h.chain).start()
    try:
        signed, sidecars = _sidecars(kzg, seed=4)
        imported = []
        h.chain.process_blob_sidecars = lambda root, scs: Availability(
            available=True, block=signed, blobs=scs
        )
        h.chain.process_block = lambda blk: imported.append(blk)
        # the queue-routed path: deliver → GOSSIP_BLOB_SIDECAR lane
        na.gossip._deliver(
            na.topic_blob_sidecar, sidecars[0].serialize(), "test-origin"
        )
        assert na.processor.drain()
        assert imported == [signed]
        # already-known blocks are not re-imported
        imported.clear()
        h.chain.fork_choice.contains_block = lambda root: True
        na.gossip._deliver(
            na.topic_blob_sidecar, sidecars[0].serialize(), "test-origin"
        )
        assert na.processor.drain()
        assert imported == []
    finally:
        na.stop()


def test_blob_pruning_at_finality(kzg):
    """Sidecars of pruned forks and DA-window-expired blocks are deleted
    when finality advances."""
    h = _harness()
    signed, sidecars = _sidecars(kzg, seed=6)
    fork_root = signed.message.hash_tree_root()
    h.chain.store.put_blob_sidecars(fork_root, sidecars)
    assert h.chain.store.get_blob_sidecars(fork_root)
    # drive to finality: the orphan root (no block known) gets pruned
    h.extend_chain(4 * E.SLOTS_PER_EPOCH)
    assert h.chain.finalized_checkpoint.epoch >= 1
    assert h.chain.store.get_blob_sidecars(fork_root) == []


def test_gossip_blob_sidecar_stages_da(kzg):
    a = _harness()
    a.chain.data_availability_checker.kzg = kzg
    b = _harness()
    b.chain.data_availability_checker.kzg = kzg
    na = NetworkService(a.chain).start()
    nb = NetworkService(b.chain).start()
    try:
        nb.connect("127.0.0.1", na.port)
        time.sleep(0.2)
        signed, sidecars = _sidecars(kzg, seed=9)
        nb.publish_blob_sidecar(sidecars[0])
        root = signed.message.hash_tree_root()
        deadline = time.time() + 5
        while time.time() < deadline:
            if a.chain.data_availability_checker.has_pending(root):
                break
            time.sleep(0.05)
        assert a.chain.data_availability_checker.has_pending(root)
    finally:
        na.stop()
        nb.stop()
