"""RPC rate limiting (network/rate_limiter.py + RpcServer wiring).

Token-bucket semantics under a fake clock, cost-priced bulk protocols,
and the server answering RESP_RATE_LIMITED over a live socket
(rpc/rate_limiter.rs behavior)."""

from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.network import NetworkService
from lighthouse_tpu.network import messages as M
from lighthouse_tpu.network.rate_limiter import Quota, RateLimiter
from lighthouse_tpu.network.rpc import RpcClient, RpcError
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_bucket_deducts_and_replenishes():
    clock = FakeClock()
    rl = RateLimiter({"p": Quota(10, 10.0)}, clock=clock)  # 1 token/s
    assert rl.allow("peer", "p", 10)  # drain fully
    assert not rl.allow("peer", "p", 1)  # empty
    clock.t += 3.0
    assert rl.allow("peer", "p", 3)  # 3 tokens refilled
    assert not rl.allow("peer", "p", 1)
    clock.t += 100.0
    assert rl.allow("peer", "p", 10)  # capped at max_tokens
    assert not rl.allow("peer", "p", 1)


def test_oversized_cost_always_refused_but_bucket_unharmed():
    clock = FakeClock()
    rl = RateLimiter({"p": Quota(5, 5.0)}, clock=clock)
    assert not rl.allow("peer", "p", 6)  # can never be served
    assert rl.allow("peer", "p", 5)  # the refusal spent nothing


def test_buckets_are_per_peer_and_per_protocol():
    clock = FakeClock()
    rl = RateLimiter({"a": Quota(1, 10.0), "b": Quota(1, 10.0)}, clock=clock)
    assert rl.allow("x", "a")
    assert not rl.allow("x", "a")
    assert rl.allow("x", "b")  # different protocol
    assert rl.allow("y", "a")  # different peer
    assert rl.allow("x", "unknown-protocol", cost=1e9)  # no quota = no limit


def test_idle_buckets_pruned():
    clock = FakeClock()
    rl = RateLimiter({"p": Quota(4, 1.0)}, clock=clock)
    for i in range(600):
        rl.allow(f"peer{i}", "p")
    clock.t += 60.0  # all idle far past 2× replenish
    for i in range(600):  # trigger the amortized prune threshold
        rl.allow(f"late{i}", "p")
    assert len(rl._buckets) <= 700  # stale peers evicted, not accumulated


def test_server_sends_rate_limited_response():
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(4)
    na = NetworkService(h.chain).start()
    try:
        # throttle hard: 2 status requests per minute
        na.server.rate_limiter = RateLimiter(
            {M.PROTO_STATUS: Quota(2, 60.0)}
        )
        client = RpcClient("127.0.0.1", na.port)
        local = M.StatusMessage(
            fork_digest=na.fork_digest(),
            finalized_root=b"\x00" * 32,
            finalized_epoch=0,
            head_root=h.chain.head_root,
            head_slot=h.chain.head_state.slot,
        )
        client.status(local)
        client.status(local)
        with pytest.raises(RpcError, match="error response 3"):
            client.status(local)
        # bulk pricing: a by-range request for more blocks than the quota
        # allows is refused even on first contact
        na.server.rate_limiter = RateLimiter(
            {M.PROTO_BLOCKS_BY_RANGE: Quota(4, 60.0)}
        )
        with pytest.raises(RpcError, match="chunk error 3"):
            client.blocks_by_range(0, 8, na.decode_block)
        # within quota works
        blocks = client.blocks_by_range(1, 3, na.decode_block)
        assert blocks
    finally:
        na.stop()
