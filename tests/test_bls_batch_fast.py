"""Differential + adversarial tests for the MSM fast batch-verify path.

The fast path (`_HostBackend.verify_signature_sets`: Pippenger MSMs,
bilinearity regrouping, fork-pool Miller loops) is pinned against the
retained serial per-set loop (`verify_signature_sets_serial`) — the same
oracle discipline as test_pairing_fast.py and test_msm.py. The adversarial
case the RLC argument must hold for: ONE tampered signature or pubkey in a
1024-set batch flips the whole batch to invalid, at every pool size.
"""

import random
import time

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto.bls12_381 import FQ2, hash_to_g2, pt_mul
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.parallel import host_pool

rng = random.Random(0x5E7)


@pytest.fixture(autouse=True)
def host_backend():
    bls.set_backend("host")
    host_pool.reset_pool()
    yield
    host_pool.reset_pool()
    bls.set_backend("host")


HOST = bls._BACKENDS["host"]


def _random_batch(n_sets, n_keys=5, n_msgs=3, committee_max=3):
    """Batches with repeated messages AND repeated committees, so both
    regrouping factorizations (by-message / by-pubkeys) get exercised."""
    kps = bls.interop_keypairs(n_keys)
    sets = []
    for _ in range(n_sets):
        m = bytes([rng.randrange(n_msgs)]) * 32
        members = rng.sample(kps, rng.randrange(1, committee_max + 1))
        agg = bls.AggregateSignature.from_signatures(
            [kp.sk.sign(m) for kp in members]
        ).to_signature()
        sets.append(bls.SignatureSet(agg, [kp.pk for kp in members], m))
    return sets


def test_fast_agrees_with_serial_on_random_batches():
    for trial in range(4):
        sets = _random_batch(rng.randrange(1, 9))
        seed = 100 + trial
        serial = HOST.verify_signature_sets_serial(sets, random.Random(seed))
        fast = HOST.verify_signature_sets(sets, random.Random(seed))
        assert serial is True and fast is True


def test_fast_agrees_with_serial_on_tampered_batches():
    sets = _random_batch(6)
    variants = []
    # wrong signature: the same committee's valid signature over a DIFFERENT
    # message (a valid subgroup point, so only the pairing product catches it)
    kps = bls.interop_keypairs(5)
    by_bytes = {kp.pk.to_bytes(): kp for kp in kps}
    members = [by_bytes[pk.to_bytes()] for pk in sets[2].pubkeys]
    wrong_sig = bls.AggregateSignature.from_signatures(
        [kp.sk.sign(b"\xEE" * 32) for kp in members]
    ).to_signature()
    assert wrong_sig != sets[2].signature
    v = list(sets)
    v[2] = bls.SignatureSet(wrong_sig, v[2].pubkeys, v[2].message)
    variants.append(v)
    # wrong pubkey
    other = bls.interop_keypairs(9)[-1].pk
    v = list(sets)
    v[4] = bls.SignatureSet(v[4].signature, [other], v[4].message)
    variants.append(v)
    # wrong message
    v = list(sets)
    v[1] = bls.SignatureSet(v[1].signature, v[1].pubkeys, b"\xEE" * 32)
    variants.append(v)
    for i, v in enumerate(variants):
        assert HOST.verify_signature_sets_serial(v, random.Random(i)) is False
        assert HOST.verify_signature_sets(v, random.Random(i)) is False


def test_fast_rejects_structurally_invalid_sets():
    kp = bls.interop_keypairs(1)[0]
    m = b"\x01" * 32
    good = bls.SignatureSet(kp.sk.sign(m), [kp.pk], m)
    # infinity signature
    assert (
        HOST.verify_signature_sets(
            [good, bls.SignatureSet(bls.Signature.empty(), [kp.pk], m)], None
        )
        is False
    )
    # empty pubkey list
    assert (
        HOST.verify_signature_sets(
            [good, bls.SignatureSet(kp.sk.sign(m), [], m)], None
        )
        is False
    )
    # infinity pubkey encoding
    inf_pk = bls.PublicKey(bls.INFINITY_PUBLIC_KEY)
    assert (
        HOST.verify_signature_sets(
            [good, bls.SignatureSet(kp.sk.sign(m), [inf_pk], m)], None
        )
        is False
    )
    # malformed signature bytes (not on curve)
    bad_sig = bls.Signature(bytes([0x80]) + bytes(95))
    assert (
        HOST.verify_signature_sets(
            [good, bls.SignatureSet(bad_sig, [kp.pk], m)], None
        )
        is False
    )
    # non-subgroup signature is caught by the worker's subgroup check
    assert good.signature.subgroup_check()
    # empty batch
    assert HOST.verify_signature_sets([], None) is False


def _thousand_sets():
    """1024 single-key sets over one shared message: small secret keys make
    generation ~1k cheap ladders, and the shared message keeps hash_to_g2
    out of the runtime (this shape drives the G1-side MSM; the bench's
    gossip shape drives the G2 side)."""
    m = b"\xA7" * 32
    h = hash_to_g2(m)
    sets = []
    for i in range(1024):
        sk = bls.SecretKey(2 + i)
        pk = sk.public_key()
        sig = bls.Signature.from_point(pt_mul(FQ2, h, sk.scalar))
        sets.append(bls.SignatureSet(sig, [pk], m))
    return sets


def test_tampered_item_in_1k_batch_fails_across_pool_sizes(monkeypatch):
    sets = _thousand_sets()
    sig_tamper = list(sets)
    # swap two honest signatures: each is a valid G2 subgroup point, so only
    # the RLC pairing product can catch it
    sig_tamper[517] = bls.SignatureSet(
        sets[518].signature, sets[517].pubkeys, sets[517].message
    )
    pk_tamper = list(sets)
    pk_tamper[901] = bls.SignatureSet(
        sets[901].signature, [sets[902].pubkeys[0]], sets[901].message
    )
    for size in ("0", "4"):
        monkeypatch.setenv(host_pool.ENV_VAR, size)
        host_pool.reset_pool()
        assert bls.verify_signature_sets(sets, random.Random(7)) is True, size
        assert (
            bls.verify_signature_sets(sig_tamper, random.Random(7)) is False
        ), size
        assert (
            bls.verify_signature_sets(pk_tamper, random.Random(7)) is False
        ), size


@pytest.mark.perf_smoke
def test_64_set_batch_verify_engages_msm_within_budget():
    """64-set host batch verify under a generous wall-clock budget, with
    the MSM path provably engaged: the bls_msm_g2 span fires and the
    per-set serial loop (path="serial") is never taken."""
    sets = _random_batch(64, n_keys=8, n_msgs=6)
    msm_hist = REGISTRY.histogram("trace_span_seconds_bls_msm_g2")
    pair_hist = REGISTRY.histogram("trace_span_seconds_bls_parallel_pairing")
    path_counter = REGISTRY.counter("bls_batch_verify_total")
    msm_count0 = msm_hist.count
    pair_count0 = pair_hist.count
    serial0 = path_counter.value(path="serial")
    msm0 = path_counter.value(path="msm")

    bls.verify_signature_sets(sets, random.Random(11))  # warm caches/tables
    t0 = time.perf_counter()
    assert bls.verify_signature_sets(sets, random.Random(12)) is True
    elapsed = time.perf_counter() - t0

    assert msm_hist.count >= msm_count0 + 2  # MSM stage ran both times
    assert pair_hist.count >= pair_count0 + 2
    assert path_counter.value(path="msm") == msm0 + 2
    assert path_counter.value(path="serial") == serial0  # no fallback
    # generous bound: warm-path cost is ~6 Miller loops + 3 small MSMs
    # (~0.2 s measured on the 1-core CI image); 20× headroom for load
    assert elapsed < 4.0, f"64-set batch verify took {elapsed:.2f}s"
