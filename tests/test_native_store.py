"""Native C++ LSM store (store/_native/lsm_store.cc via store/native.py).

Covers the properties the reference gets from LevelDB
(beacon_node/store/src/leveldb_store.rs): durable point reads/writes,
atomic multi-op batches (crash-atomicity simulated by truncating the WAL
mid-record), ordered per-column iteration, compaction correctness, and a
randomized model check against a plain dict.
"""

import os
import random
import struct

import pytest

from lighthouse_tpu.store import HotColdDB, open_item_store
from lighthouse_tpu.store.kv import DBColumn, MemoryStore
from lighthouse_tpu.store.native import NativeStore, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


@pytest.fixture
def db_path(tmp_path):
    return str(tmp_path / "db")


def test_round_trip_and_reopen(db_path):
    s = NativeStore(db_path)
    s.put(DBColumn.BEACON_BLOCK, b"a" * 32, b"block-bytes")
    s.put(DBColumn.BEACON_STATE, b"a" * 32, b"state-bytes" * 1000)
    assert s.get(DBColumn.BEACON_BLOCK, b"a" * 32) == b"block-bytes"
    assert s.get(DBColumn.BEACON_BLOCK, b"b" * 32) is None
    # column isolation: same key, different columns
    assert s.get(DBColumn.BEACON_STATE, b"a" * 32) == b"state-bytes" * 1000
    s.close()

    s2 = NativeStore(db_path)  # WAL replay
    assert s2.get(DBColumn.BEACON_BLOCK, b"a" * 32) == b"block-bytes"
    s2.close()


def test_get_prefix_partial_read(db_path):
    s = NativeStore(db_path)
    val = bytes(range(256)) * 10
    s.put(DBColumn.BLOB_SIDECARS, b"r" * 32, val)
    assert s.get_prefix(DBColumn.BLOB_SIDECARS, b"r" * 32, 8) == val[:8]
    s.flush()  # now served from an SSTable pread
    assert s.get_prefix(DBColumn.BLOB_SIDECARS, b"r" * 32, 8) == val[:8]
    assert s.get_prefix(DBColumn.BLOB_SIDECARS, b"x" * 32, 8) is None
    s.close()


def test_delete_and_tombstone_shadowing(db_path):
    s = NativeStore(db_path)
    s.put(DBColumn.BEACON_BLOCK, b"k1", b"v1")
    s.flush()  # v1 lives in an SSTable
    s.delete(DBColumn.BEACON_BLOCK, b"k1")  # tombstone in memtable
    assert s.get(DBColumn.BEACON_BLOCK, b"k1") is None
    s.flush()  # tombstone now in a newer SSTable
    assert s.get(DBColumn.BEACON_BLOCK, b"k1") is None
    assert s.keys(DBColumn.BEACON_BLOCK) == []
    s.compact()  # full merge drops the pair entirely
    assert s.get(DBColumn.BEACON_BLOCK, b"k1") is None
    assert s.stats()["sstables"] <= 1
    s.close()


def test_atomic_batch_and_keys(db_path):
    s = NativeStore(db_path)
    s.put(DBColumn.BEACON_BLOCK, b"gone", b"x")
    s.do_atomically(
        [
            ("put", DBColumn.BEACON_BLOCK, b"k1", b"v1"),
            ("put", DBColumn.BEACON_BLOCK, b"k2", b"v2"),
            ("delete", DBColumn.BEACON_BLOCK, b"gone"),
            ("put", DBColumn.BEACON_STATE, b"k1", b"sv"),
        ]
    )
    assert sorted(s.keys(DBColumn.BEACON_BLOCK)) == [b"k1", b"k2"]
    assert s.keys(DBColumn.BEACON_STATE) == [b"k1"]
    s.close()


def test_torn_wal_tail_drops_only_last_batch(db_path):
    s = NativeStore(db_path)
    s.put(DBColumn.BEACON_BLOCK, b"first", b"committed")
    s.do_atomically(
        [
            ("put", DBColumn.BEACON_BLOCK, b"second", b"also-committed"),
        ]
    )
    s.put(DBColumn.BEACON_BLOCK, b"third", b"torn")
    # Simulate a crash that tore the last batch record: chop bytes off the
    # WAL tail without closing cleanly (close() would flush to an SSTable).
    wal = os.path.join(db_path, "wal.log")
    size = os.path.getsize(wal)
    with open(wal, "r+b") as f:
        f.truncate(size - 3)
    s.abandon()  # crash: no close-time flush

    s2 = NativeStore(db_path)
    assert s2.get(DBColumn.BEACON_BLOCK, b"first") == b"committed"
    assert s2.get(DBColumn.BEACON_BLOCK, b"second") == b"also-committed"
    assert s2.get(DBColumn.BEACON_BLOCK, b"third") is None  # torn → dropped
    # The truncated tail must not poison subsequent appends.
    s2.put(DBColumn.BEACON_BLOCK, b"fourth", b"post-crash")
    s2.close()
    s3 = NativeStore(db_path)
    assert s3.get(DBColumn.BEACON_BLOCK, b"fourth") == b"post-crash"
    s3.close()


def test_corrupt_wal_crc_detected(db_path):
    s = NativeStore(db_path)
    s.put(DBColumn.BEACON_BLOCK, b"ok", b"v")
    s.put(DBColumn.BEACON_BLOCK, b"bad", b"w")
    wal = os.path.join(db_path, "wal.log")
    data = open(wal, "rb").read()
    # Flip a bit inside the SECOND record's payload (first record intact).
    first_len = struct.unpack_from("<I", data, 4)[0]
    off = 8 + first_len + 8 + 2  # into the second payload
    data = data[:off] + bytes([data[off] ^ 0xFF]) + data[off + 1:]
    with open(wal, "wb") as f:
        f.write(data)
    s.abandon()  # crash: no close-time flush

    s2 = NativeStore(db_path)
    assert s2.get(DBColumn.BEACON_BLOCK, b"ok") == b"v"
    assert s2.get(DBColumn.BEACON_BLOCK, b"bad") is None
    s2.close()


def test_flush_compact_reopen_cycle(db_path):
    s = NativeStore(db_path, mem_limit_bytes=1 << 14)  # tiny: force flushes
    expect = {}
    rng = random.Random(1234)
    for i in range(400):
        k = rng.randrange(64).to_bytes(8, "little")
        v = rng.randbytes(rng.randrange(1, 2048))
        expect[k] = v
        s.put(DBColumn.BEACON_STATE, k, v)
        if rng.random() < 0.1:
            dk = rng.randrange(64).to_bytes(8, "little")
            expect.pop(dk, None)
            s.delete(DBColumn.BEACON_STATE, dk)
    assert s.stats()["sstables"] >= 1  # the small limit really flushed
    for k, v in expect.items():
        assert s.get(DBColumn.BEACON_STATE, k) == v
    assert sorted(s.keys(DBColumn.BEACON_STATE)) == sorted(expect)
    s.compact()
    assert sorted(s.keys(DBColumn.BEACON_STATE)) == sorted(expect)
    s.close()

    s2 = NativeStore(db_path)
    for k, v in expect.items():
        assert s2.get(DBColumn.BEACON_STATE, k) == v
    s2.close()


def test_model_check_vs_memory_store(db_path):
    """Randomized ops applied to both engines must agree at every step."""
    s = NativeStore(db_path, mem_limit_bytes=1 << 15)
    model = MemoryStore()
    rng = random.Random(99)
    cols = [DBColumn.BEACON_BLOCK, DBColumn.BEACON_STATE, DBColumn.OP_POOL]
    for step in range(300):
        col = rng.choice(cols)
        k = rng.randrange(48).to_bytes(4, "big")
        roll = rng.random()
        if roll < 0.55:
            v = rng.randbytes(rng.randrange(0, 512))
            s.put(col, k, v)
            model.put(col, k, v)
        elif roll < 0.75:
            s.delete(col, k)
            model.delete(col, k)
        elif roll < 0.9:
            ops = []
            for _ in range(rng.randrange(1, 6)):
                kk = rng.randrange(48).to_bytes(4, "big")
                if rng.random() < 0.7:
                    ops.append(("put", col, kk, rng.randbytes(32)))
                else:
                    ops.append(("delete", col, kk))
            s.do_atomically(ops)
            model.do_atomically(ops)
        else:
            s.flush() if rng.random() < 0.5 else s.compact()
        probe = rng.randrange(48).to_bytes(4, "big")
        assert s.get(col, probe) == model.get(col, probe), f"step {step}"
    for col in cols:
        assert sorted(s.keys(col)) == sorted(model.keys(col))
    s.close()


def test_hot_cold_db_on_native_store(tmp_path):
    """HotColdDB round-trips a real BeaconState through the native engine."""
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.state_processing.genesis import interop_genesis_state
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec

    old = bls.backend_name()
    bls.set_backend("fake_crypto")
    try:
        spec = minimal_spec()
        kps = bls.interop_keypairs(8)
        state = interop_genesis_state(
            kps, 1_600_000_000, b"\x42" * 32, spec, MinimalEthSpec
        )
        root = state.hash_tree_root()

        from lighthouse_tpu.types.containers import build_types

        store = HotColdDB(
            open_item_store(str(tmp_path / "hot"), "native"),
            open_item_store(str(tmp_path / "cold"), "native"),
            types=build_types(MinimalEthSpec),
        )
        store.put_state(root, state)
        got = store.get_state(root)
        assert got is not None
        assert got.hash_tree_root() == root
    finally:
        bls.set_backend(old)


def test_open_item_store_auto_prefers_native(tmp_path):
    s = open_item_store(str(tmp_path / "auto-db"))
    assert isinstance(s, NativeStore)
    s.close()


def test_second_opener_refused_by_lock(db_path):
    """LevelDB-style LOCK file: a second opener (e.g. the db CLI against a
    running node) fails loudly instead of corrupting the live store."""
    from lighthouse_tpu.store.native import NativeStoreError

    s = NativeStore(db_path)
    s.put(DBColumn.BEACON_BLOCK, b"k", b"v")
    with pytest.raises(NativeStoreError, match="locked by another process"):
        NativeStore(db_path)
    s.close()
    # released on close: reopen succeeds
    s2 = NativeStore(db_path)
    assert s2.get(DBColumn.BEACON_BLOCK, b"k") == b"v"
    s2.close()
