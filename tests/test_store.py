"""HotColdDB: fork-tagged SSZ persistence + schema versioning.

Mirrors beacon_node/store tests: states/blocks round-trip as SSZ bytes
across forks, schema mismatches are detected at open
(hot_cold_store.rs:50-55, lib.rs CURRENT_SCHEMA_VERSION)."""

from dataclasses import replace

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.state_processing import interop_genesis_state, per_slot_processing
from lighthouse_tpu.store import HotColdDB, MemoryStore
from lighthouse_tpu.store.hot_cold import (
    CURRENT_SCHEMA_VERSION,
    SCHEMA_VERSION_KEY,
    SchemaVersionError,
)
from lighthouse_tpu.store.kv import DBColumn, SqliteStore
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


def _genesis(spec):
    bls.set_backend("fake_crypto")
    kps = bls.interop_keypairs(8)
    return interop_genesis_state(kps, 1_600_000_000, b"\x42" * 32, spec, E)


def test_state_roundtrips_as_ssz_across_forks():
    types = build_types(E)
    store = HotColdDB(MemoryStore(), types=types)

    # phase0
    spec = minimal_spec()
    st0 = _genesis(spec)
    root0 = st0.hash_tree_root()
    store.put_state(root0, st0)
    raw = store.hot.get(DBColumn.BEACON_STATE, root0)
    assert raw[0] == 0  # phase0 tag
    assert raw[1:] == st0.serialize()  # SSZ bytes, not pickle
    got = store.get_state(root0)
    assert type(got).__name__ == "BeaconState"
    assert got.hash_tree_root() == root0

    # altair state decodes back to the altair variant
    spec_a = replace(minimal_spec(), altair_fork_epoch=0)
    st_a = _genesis(spec_a)
    root_a = st_a.hash_tree_root()
    store.put_state(root_a, st_a)
    got_a = store.get_state(root_a)
    assert type(got_a).__name__ == "BeaconStateAltair"
    assert got_a.hash_tree_root() == root_a
    assert got_a.inactivity_scores == st_a.inactivity_scores


def test_block_roundtrips_fork_tagged():
    types = build_types(E)
    store = HotColdDB(MemoryStore(), types=types)
    tf = types.types_for_fork(types.fork_of_state(_genesis(minimal_spec())))
    block = tf.BeaconBlock(slot=5, proposer_index=3)
    signed = tf.SignedBeaconBlock(message=block, signature=b"\x00" * 96)
    root = block.hash_tree_root()
    store.put_block(root, signed)
    got = store.get_block(root)
    assert got.message.slot == 5
    assert got.message.hash_tree_root() == root


def test_schema_version_mismatch_detected():
    mem = MemoryStore()
    HotColdDB(mem, types=build_types(E))  # stamps v CURRENT
    assert (
        int.from_bytes(mem.get(DBColumn.BEACON_META, SCHEMA_VERSION_KEY), "little")
        == CURRENT_SCHEMA_VERSION
    )
    mem.put(DBColumn.BEACON_META, SCHEMA_VERSION_KEY, (99).to_bytes(8, "little"))
    with pytest.raises(SchemaVersionError):
        HotColdDB(mem, types=build_types(E))


def test_sqlite_store_persists(tmp_path):
    path = str(tmp_path / "db.sqlite")
    types = build_types(E)
    store = HotColdDB(SqliteStore(path), types=types)
    st = _genesis(minimal_spec())
    per_slot_processing(st, minimal_spec(), E)
    root = st.hash_tree_root()
    store.put_state(root, st)
    store.hot.close()

    store2 = HotColdDB(SqliteStore(path), types=types)
    got = store2.get_state(root)
    assert got is not None and got.slot == st.slot
    assert got.hash_tree_root() == root
    store2.hot.close()
