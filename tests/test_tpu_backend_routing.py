"""_TpuBackend batch-verification routing: the FULL device verifier is
the primary path, batches are chunked to bounded shapes, a failing chunk
fails the batch, and kernel failures fall back (loudly, once) to the
partial device path. Kernel correctness itself is covered by the device
suites; this pins the wiring."""

import pytest

import lighthouse_tpu.ops.bls381 as ops_device
import lighthouse_tpu.ops.bls381_verify as ops_full
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto.bls import _TpuBackend


@pytest.fixture
def tpu_available(monkeypatch):
    monkeypatch.setattr(ops_device, "AVAILABLE", True, raising=False)
    monkeypatch.setattr(_TpuBackend, "_warned", False)
    return _TpuBackend()


def _sets(n):
    kps = bls.interop_keypairs(2)
    msg = b"\x11" * 32
    sig = kps[0].sk.sign(msg)
    return [bls.SignatureSet(sig, [kps[0].pk], msg) for _ in range(n)]


def test_full_path_chunks_batches(tpu_available, monkeypatch):
    backend = tpu_available
    calls = []

    def fake_full(sets, rng=None):
        calls.append(len(sets))
        return True

    monkeypatch.setattr(
        ops_full, "verify_signature_sets_device_full", fake_full
    )
    monkeypatch.setenv("LIGHTHOUSE_TPU_BLS_CHUNK", "4")
    assert backend.verify_signature_sets(_sets(10)) is True
    assert calls == [4, 4, 2]  # bounded shapes, full coverage


def test_failing_chunk_fails_the_batch(tpu_available, monkeypatch):
    backend = tpu_available
    calls = []

    def fake_full(sets, rng=None):
        calls.append(len(sets))
        return len(calls) != 2  # second chunk reports an invalid set

    monkeypatch.setattr(
        ops_full, "verify_signature_sets_device_full", fake_full
    )
    monkeypatch.setenv("LIGHTHOUSE_TPU_BLS_CHUNK", "3")
    assert backend.verify_signature_sets(_sets(9)) is False
    assert len(calls) == 2  # short-circuits after the failing chunk


def test_kernel_failure_falls_back_to_partial_path(tpu_available, monkeypatch):
    backend = tpu_available

    def exploding_full(sets, rng=None):
        raise RuntimeError("remote_compile: response body closed")

    partial = []
    monkeypatch.setattr(
        ops_full, "verify_signature_sets_device_full", exploding_full
    )
    monkeypatch.setattr(
        ops_device,
        "verify_signature_sets_device",
        lambda sets, rng=None: partial.append(len(sets)) or True,
    )
    assert backend.verify_signature_sets(_sets(5)) is True
    assert partial == [5]  # the partial device path served the batch
    assert _TpuBackend._warned  # and the failure was logged loudly


def test_empty_batch_uses_host_semantics(tpu_available):
    backend = tpu_available
    host = bls._BACKENDS["host"]
    assert backend.verify_signature_sets([]) == host.verify_signature_sets([])
