"""Runtime sanitizer (LIGHTHOUSE_TPU_SANITIZE=1): write-guarded views,
wide-dtype overflow checks, stale-read audits, and the slow-marked
block-import + epoch-transition soak (differential vs. the oracles).

The headline regression test: an escaped writeable `load_array` view —
the exact bug class that silently corrupts state roots — must raise a
counted `SanitizerError` at the write site under sanitize mode. The
all-modes freezes (committee slices, EpochArrays / RegistryColumns
column views) are asserted without the env flag: those invariants hold
unconditionally."""

import random

import numpy as np
import pytest

from lighthouse_tpu.analysis import sanitizer
from lighthouse_tpu.analysis.sanitizer import SanitizerError
from lighthouse_tpu.beacon_chain.chain import _make_persistent
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.ssz.persistent import PersistentByteList, PersistentList
from lighthouse_tpu.state_processing.per_epoch import process_epoch
from lighthouse_tpu.state_processing.registry_columns import (
    registry_columns_for,
)
from lighthouse_tpu.types.chain_spec import ForkName
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
from lighthouse_tpu.utils import safe_arith
from lighthouse_tpu.utils.safe_arith import ArithError

import test_registry_columns as trc


def _viol(rule: str) -> float:
    return REGISTRY.counter("sanitizer_violations_total").value(rule=rule)


@pytest.fixture
def sanitize(monkeypatch):
    monkeypatch.setenv(sanitizer.ENV_VAR, "1")


# ---------------------------------------------------------------------------
# cow-write: guarded load_array views
# ---------------------------------------------------------------------------


def test_escaped_load_array_view_is_caught(sanitize):
    """THE regression test: a consumer that keeps a load_array view and
    writes it (instead of committing via store_array) is caught at the
    write site with a counted violation."""
    lst = PersistentList(range(100))
    arr = lst.load_array()
    assert not arr.flags.writeable
    before = _viol("cow-write")
    with pytest.raises(SanitizerError, match="cow-write"):
        arr[3] = 42
    assert _viol("cow-write") == before + 1
    # the escape hatch is also guarded
    with pytest.raises(SanitizerError, match="cow-write"):
        arr.setflags(write=True)
    assert _viol("cow-write") == before + 2
    # the list itself never saw the write
    assert lst[3] == 3
    # byte lists share the contract
    bl = PersistentByteList(bytes(64))
    barr = bl.load_array()
    with pytest.raises(SanitizerError, match="cow-write"):
        barr[0] = 1


def test_sanctioned_store_array_still_works(sanitize):
    lst = PersistentList(range(100))
    staged = lst.load_array().copy()  # copies of guarded views are writable
    staged[7] = 1234
    assert lst.store_array(staged) == 1
    assert lst[7] == 1234
    _, dirty = lst.drain_dirty()
    assert dirty == {7}


def test_load_array_stays_writable_off_mode(monkeypatch):
    """No behavior change with the sanitizer off (bench mode)."""
    monkeypatch.delenv(sanitizer.ENV_VAR, raising=False)
    lst = PersistentList(range(10))
    arr = lst.load_array()
    assert arr.flags.writeable


# ---------------------------------------------------------------------------
# all-modes freezes (no env flag)
# ---------------------------------------------------------------------------


def test_committee_slices_frozen_in_all_modes():
    from lighthouse_tpu.state_processing.accessors import committee_cache_at

    state, _spec = trc._base_state(ForkName.ALTAIR, 320, 7)
    cc = committee_cache_at(state, 3, E)
    committee = cc.committee_array(state.slot, 0)
    assert not committee.flags.writeable
    with pytest.raises(ValueError):
        committee[0] = 1
    # list materialization (the SSZ/dict-key surface) is unaffected
    assert committee.tolist() == list(committee)


def test_epoch_arrays_views_frozen_in_all_modes(monkeypatch):
    from lighthouse_tpu.state_processing.altair import EpochArrays

    monkeypatch.setenv("LIGHTHOUSE_TPU_RESIDENT_COLUMNS", "0")
    state, _spec = trc._base_state(ForkName.ALTAIR, 200, 9)
    _make_persistent(state)
    arrays = EpochArrays(state, E)
    assert arrays.columns is None
    for name in ("effective_balance", "exit_epoch", "slashed"):
        view = getattr(arrays, name)
        assert not view.flags.writeable, name
    with pytest.raises(ValueError):
        arrays.effective_balance[0] = 1
    # the sanctioned writer updates the base the views read
    arrays.write_snapshot_rows("effective_balance", [0], [123])
    assert int(arrays.effective_balance[0]) == 123


def test_writable_window_refreezes_even_on_exception():
    """The guarded re-enable: writes succeed inside the window, the
    buffer is frozen again on exit — including an exceptional one."""
    arr = np.arange(8, dtype=np.uint64)
    arr.setflags(write=False)
    with sanitizer.writable_window(arr) as buf:
        buf[0] = 99
    assert not arr.flags.writeable
    assert arr[0] == 99
    with pytest.raises(RuntimeError):
        with sanitizer.writable_window(arr):
            raise RuntimeError("mid-window failure")
    assert not arr.flags.writeable


def test_registry_column_views_frozen_in_all_modes():
    state, _spec = trc._base_state(ForkName.ALTAIR, 200, 13)
    _make_persistent(state)
    cols = registry_columns_for(state)
    cols.refresh(state)
    assert not cols.effective_balance.flags.writeable
    assert not cols.balances.flags.writeable
    # ValueError from a plain frozen view; SanitizerError (counted) when
    # the suite itself runs under LIGHTHOUSE_TPU_SANITIZE=1
    with pytest.raises((ValueError, SanitizerError)):
        cols.balances[0] = 1
    # the sanctioned writer path commits to the list AND the column
    new = cols.balances.copy()
    new[0] += 5
    assert cols.write_balances(state, new) == 1
    assert state.balances[0] == int(new[0])


# ---------------------------------------------------------------------------
# u64-wrap: wide-dtype checks on the vectorized helpers
# ---------------------------------------------------------------------------


def test_vectorized_wrap_checks_fire_under_sanitize(sanitize):
    big = np.array([2**63, 5], dtype=np.uint64)
    before = _viol("u64-wrap")
    with pytest.raises(SanitizerError, match="u64-wrap"):
        safe_arith.add_u64(big, big)
    with pytest.raises(SanitizerError, match="u64-wrap"):
        safe_arith.mul_u64(big, np.uint64(3))
    with pytest.raises(SanitizerError, match="u64-wrap"):
        safe_arith.sub_u64(np.array([1], dtype=np.uint64), np.uint64(2))
    with pytest.raises(SanitizerError, match="u64-wrap"):
        safe_arith.div_u64(big, np.array([1, 0], dtype=np.uint64))
    assert _viol("u64-wrap") == before + 4
    # exact lanes pass
    assert safe_arith.add_u64(big, np.uint64(1))[1] == 6
    assert (
        safe_arith.sub_u64_saturating(
            np.array([1], dtype=np.uint64), np.uint64(2)
        )[0]
        == 0
    )


def test_vectorized_helpers_are_plain_ops_off_mode(monkeypatch):
    monkeypatch.delenv(sanitizer.ENV_VAR, raising=False)
    big = np.array([2**63], dtype=np.uint64)
    assert safe_arith.add_u64(big, big)[0] == 0  # wraps silently, as numpy


def test_scalar_checked_helpers_always_raise():
    assert safe_arith.safe_add(1, 2) == 3
    assert safe_arith.saturating_sub(3, 5) == 0
    assert safe_arith.saturating_add(2**64 - 1, 9) == 2**64 - 1
    with pytest.raises(ArithError):
        safe_arith.safe_add(2**64 - 1, 1)
    with pytest.raises(ArithError):
        safe_arith.safe_sub(3, 5)
    with pytest.raises(ArithError):
        safe_arith.safe_mul(2**33, 2**33)
    with pytest.raises(ArithError):
        safe_arith.safe_div(1, 0)


# ---------------------------------------------------------------------------
# stale-read: columns consumed while their source holds undrained dirt
# ---------------------------------------------------------------------------


def test_stale_column_read_is_audited(sanitize):
    state, _spec = trc._base_state(ForkName.ALTAIR, 200, 17)
    _make_persistent(state)
    cols = registry_columns_for(state)
    cols.refresh(state)
    _ = cols.balances  # fresh: clean read
    state.balances[0] = state.balances[0] + 5  # object-path write
    before = _viol("stale-read")
    with pytest.raises(SanitizerError, match="stale-read"):
        _ = cols.balances
    assert _viol("stale-read") == before + 1
    cols.refresh(state)  # drain → reads are clean again
    assert int(cols.balances[0]) == state.balances[0]


# ---------------------------------------------------------------------------
# soak: block ops + epoch transitions under the sanitizer, vs oracles
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("fork", [ForkName.ALTAIR, ForkName.ELECTRA])
def test_sanitize_soak_block_import_epoch_roundtrip(fork, monkeypatch):
    """Drive the real pipelines — columnar attestation batches, then a
    full epoch transition — with every sanitizer guard armed, and prove
    the result bit-identical to the scalar oracle run WITHOUT the
    sanitizer: the guards must catch nothing (zero violations) and
    change nothing (fingerprint equality). This is how a CoW regression
    gets caught before it reaches a 1M-validator bench."""
    import test_attestation_batch as tab

    from lighthouse_tpu.state_processing import attestation_batch
    from lighthouse_tpu.state_processing.attestation_batch import (
        process_attestations,
        process_attestations_reference,
    )
    from lighthouse_tpu.state_processing.per_block import ConsensusContext

    bls.set_backend("fake_crypto")
    monkeypatch.setattr(attestation_batch, "_SMALL_BATCH_ROWS", 0)
    counters_before = {r: _viol(r) for r in sanitizer.RULES}

    rng = random.Random(41)
    subject, spec = tab._att_state(fork, 520, 41)
    oracle, _ = tab._att_state(fork, 520, 41)
    atts = tab._make_attestations(subject, fork, rng, 24)

    # subject: persistent representation + resident columns + sanitizer
    monkeypatch.setenv(sanitizer.ENV_VAR, "1")
    _make_persistent(subject)
    registry_columns_for(subject).refresh(subject)
    process_attestations(
        subject, atts, spec, E, False, ConsensusContext(subject.slot), fork
    )
    subject.slot = (
        subject.slot // E.SLOTS_PER_EPOCH + 1
    ) * E.SLOTS_PER_EPOCH - 1
    process_epoch(subject, spec, E)
    # a CoW branch taken mid-soak must keep its own root
    branch = subject.copy()
    branch_root = branch.hash_tree_root()

    # oracle: plain lists, scalar loops, sanitizer OFF
    monkeypatch.delenv(sanitizer.ENV_VAR)
    process_attestations_reference(
        oracle, atts, spec, E, False, ConsensusContext(oracle.slot), fork
    )
    oracle.slot = subject.slot
    monkeypatch.setenv("LIGHTHOUSE_TPU_RESIDENT_COLUMNS", "0")
    process_epoch(oracle, spec, E)
    monkeypatch.delenv("LIGHTHOUSE_TPU_RESIDENT_COLUMNS")

    got = trc._state_fingerprint(subject)
    want = trc._state_fingerprint(oracle)
    for key in want:
        assert got[key] == want[key], f"{fork}: '{key}' diverged under sanitize"
    assert branch.hash_tree_root() == branch_root
    for rule, before in counters_before.items():
        assert _viol(rule) == before, f"sanitizer flagged {rule} on clean flows"
