"""Sync-committee light client: bootstrap, updates, store advancement.

A harness chain produces real states; the light client bootstraps from a
trusted root and follows finality using only headers + branches + sync
aggregates (consensus/types light_client_* + altair light-client spec)."""

from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.light_client import (
    LightClientError,
    create_bootstrap,
    create_update,
    initialize_light_client_store,
    process_light_client_update,
)
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


@pytest.fixture(scope="module")
def chain():
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(5 * E.SLOTS_PER_EPOCH)
    assert h.finalized_epoch >= 2
    return h


def test_bootstrap_roundtrip(chain):
    h = chain
    state = h.chain.head_state.copy()
    boot = create_bootstrap(state, E)
    trusted = boot.header.beacon.hash_tree_root()
    store = initialize_light_client_store(trusted, boot, E)
    assert store.finalized_header.beacon.slot == state.slot

    with pytest.raises(LightClientError):
        initialize_light_client_store(b"\x00" * 32, boot, E)

    # tampered branch refused
    bad = create_bootstrap(state, E)
    branch = list(bad.current_sync_committee_branch)
    branch[2] = b"\x13" * 32
    bad.current_sync_committee_branch = branch
    with pytest.raises(LightClientError):
        initialize_light_client_store(trusted, bad, E)


def test_update_advances_finality(chain):
    h = chain
    # bootstrap from an early state, then catch up via one update
    fin_cp = h.chain.finalized_checkpoint
    fin_state = h.chain._justified_state_provider(fin_cp.root)
    boot_state = fin_state.copy()
    boot = create_bootstrap(boot_state, E)
    store = initialize_light_client_store(
        boot.header.beacon.hash_tree_root(), boot, E
    )
    start_slot = store.finalized_header.beacon.slot
    # advance the chain so its finality moves past the bootstrap point
    h.extend_chain(2 * E.SLOTS_PER_EPOCH)

    attested = h.chain.head_state.copy()
    att_fin_root = attested.finalized_checkpoint.root
    att_fin_state = h.chain._justified_state_provider(att_fin_root)
    sync_agg = h.make_sync_aggregate(
        h.chain.head_state.copy(),
        h.chain.head_state.slot + 1,
        h.chain.head_root,
    )
    update = create_update(
        attested,
        att_fin_state,
        sync_agg,
        signature_slot=h.chain.head_state.slot + 1,
        E=E,
    )
    process_light_client_update(
        store,
        update,
        current_slot=h.chain.head_state.slot + 1,
        spec=h.spec,
        E=E,
        genesis_validators_root=h.chain.genesis_validators_root,
    )
    assert store.finalized_header.beacon.slot > start_slot
    assert store.next_sync_committee is not None

    # slot-order violation refused
    with pytest.raises(LightClientError):
        process_light_client_update(
            store, update, current_slot=0, spec=h.spec, E=E,
            genesis_validators_root=h.chain.genesis_validators_root,
        )

    # tampered finality branch refused
    bad = create_update(
        attested, att_fin_state, sync_agg,
        signature_slot=h.chain.head_state.slot + 1, E=E,
    )
    fb = list(bad.finality_branch)
    fb[3] = b"\x14" * 32
    bad.finality_branch = fb
    with pytest.raises(LightClientError):
        process_light_client_update(
            store, bad, current_slot=h.chain.head_state.slot + 1,
            spec=h.spec, E=E,
            genesis_validators_root=h.chain.genesis_validators_root,
        )


@pytest.mark.slow
def test_update_signature_checked_real_crypto(chain):
    """Under the host backend the sync-aggregate signature must actually
    verify; a bit-flipped signature is rejected."""
    h = chain
    bls.set_backend("host")
    try:
        spec = replace(minimal_spec(), altair_fork_epoch=0)
        hr = BeaconChainHarness(spec, E, validator_count=8)
        hr.extend_chain(2 * E.SLOTS_PER_EPOCH + 1)
        boot_state = hr.chain.head_state.copy()
        boot = create_bootstrap(boot_state, E)
        store = initialize_light_client_store(
            boot.header.beacon.hash_tree_root(), boot, E
        )
        # produce a real signed sync aggregate over the attested header:
        # extend one slot so the head block carries a sync aggregate
        hr.extend_chain(1)
        head_block = hr.chain.head_block()
        agg = head_block.message.body.sync_aggregate
        attested_root = head_block.message.parent_root
        attested_state = hr.chain._justified_state_provider(attested_root)
        fin_root = attested_state.finalized_checkpoint.root
        fin_state = (
            hr.chain._justified_state_provider(fin_root)
            if fin_root != b"\x00" * 32
            else hr.chain._states[hr.chain.genesis_block_root]
        )
        update = create_update(
            attested_state,
            fin_state,
            agg,
            signature_slot=head_block.message.slot,
            E=E,
        )
        process_light_client_update(
            store.__class__(
                finalized_header=store.finalized_header,
                current_sync_committee=attested_state.current_sync_committee,
            ),
            update,
            current_slot=head_block.message.slot,
            spec=spec,
            E=E,
            genesis_validators_root=hr.chain.genesis_validators_root,
        )
        # flip a signature bit → rejected
        bad_sig = bytearray(bytes(agg.sync_committee_signature))
        bad_sig[10] ^= 1
        bad_agg = type(agg)(
            sync_committee_bits=list(agg.sync_committee_bits),
            sync_committee_signature=bytes(bad_sig),
        )
        bad_update = create_update(
            attested_state, fin_state, bad_agg,
            signature_slot=head_block.message.slot, E=E,
        )
        with pytest.raises(LightClientError):
            process_light_client_update(
                store.__class__(
                    finalized_header=store.finalized_header,
                    current_sync_committee=attested_state.current_sync_committee,
                ),
                bad_update,
                current_slot=head_block.message.slot,
                spec=spec,
                E=E,
                genesis_validators_root=hr.chain.genesis_validators_root,
            )
    finally:
        bls.set_backend("fake_crypto")


def test_sync_committee_period_rollover(chain):
    """Crossing a sync-committee period boundary rotates next→current."""
    from lighthouse_tpu.light_client import LightClientStore, _period

    h = chain
    E_ = E
    # synthetic store just below a period boundary
    boot_state = h.chain.head_state.copy()
    boot = create_bootstrap(boot_state, E_)
    store = initialize_light_client_store(
        boot.header.beacon.hash_tree_root(), boot, E_
    )
    period_len = E_.SLOTS_PER_EPOCH * E_.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    old_next = boot_state.next_sync_committee
    store.next_sync_committee = old_next
    store.finalized_header.beacon.slot = period_len - 1

    # craft a consistent update finalizing INTO the next period: mutate the
    # header slots, then point the attested state's finalized checkpoint at
    # the crafted finalized header so the produced branch proves it
    from lighthouse_tpu.light_client import _block_header_of, build_light_client_types

    lt = build_light_client_types(E_)
    fin_state = h.chain.head_state.copy()
    fin_state.latest_block_header.slot = period_len + 1
    fin_header = _block_header_of(fin_state, lt)
    attested = h.chain.head_state.copy()
    attested.latest_block_header.slot = period_len + 5
    t = lt.base
    attested.finalized_checkpoint = t.Checkpoint(
        epoch=(period_len + 1) // E_.SLOTS_PER_EPOCH,
        root=fin_header.beacon.hash_tree_root(),
    )
    sync_agg = h.make_sync_aggregate(
        h.chain.head_state.copy(), h.chain.head_state.slot + 1, h.chain.head_root
    )
    update = create_update(
        attested, fin_state, sync_agg,
        signature_slot=period_len + 6, E=E_,
    )
    process_light_client_update(
        store, update, current_slot=period_len + 7, spec=h.spec, E=E_,
        genesis_validators_root=h.chain.genesis_validators_root,
    )
    assert _period(store.finalized_header.beacon.slot, E_) >= 1
    # rotation happened: current is the previously stored next
    assert store.current_sync_committee == old_next


def test_light_client_http_routes():
    """Served over the Beacon API: a light client bootstraps from the
    /light_client/bootstrap route and advances its store with the
    /light_client/update route — full server+client loop over HTTP."""
    import urllib.request

    from lighthouse_tpu.http_api import HttpApiServer
    from lighthouse_tpu.light_client import build_light_client_types

    bls.set_backend("host")
    try:
        spec = replace(minimal_spec(), altair_fork_epoch=0)
        h = BeaconChainHarness(spec, E, validator_count=8)
        h.extend_chain(3 * E.SLOTS_PER_EPOCH)
        srv = HttpApiServer(h.chain).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            fin_root = bytes(h.chain.finalized_checkpoint.root)
            assert fin_root != b"\x00" * 32
            resp = urllib.request.urlopen(
                f"{base}/eth/v1/beacon/light_client/bootstrap/0x{fin_root.hex()}",
                timeout=10,
            )
            raw = resp.read()
            # the consensus-version header selects the container family
            # (Electra's branches are deeper)
            version = resp.headers.get("Eth-Consensus-Version")
            assert version == "altair"
            lt = build_light_client_types(E, electra=version == "electra")
            boot = lt.LightClientBootstrap.deserialize(raw)
            store = initialize_light_client_store(fin_root, boot, E)
            resp = urllib.request.urlopen(
                f"{base}/eth/v1/beacon/light_client/update", timeout=10
            )
            raw = resp.read()
            assert resp.headers.get("Eth-Consensus-Version") == "altair"
            update = lt.LightClientUpdate.deserialize(raw)
            process_light_client_update(
                store,
                update,
                current_slot=int(h.chain.head_state.slot) + 1,
                genesis_validators_root=bytes(h.chain.genesis_validators_root),
                spec=spec,
                E=E,
            )
            assert store.optimistic_header.beacon.slot >= boot.header.beacon.slot
        finally:
            srv.stop()
    finally:
        bls.set_backend("fake_crypto")


def test_electra_deep_branches_round_trip():
    """Electra's 37-field state gets depth-6 sync-committee branches and a
    depth-7 finality branch (the spec's *_GINDEX_ELECTRA revision); the
    client verifies them against the attested state root."""
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.light_client import (
        LightClientStore,
        build_light_client_types,
    )

    spec = replace(
        minimal_spec(),
        altair_fork_epoch=0,
        bellatrix_fork_epoch=0,
        capella_fork_epoch=0,
        deneb_fork_epoch=0,
        electra_fork_epoch=0,
    )
    h = BeaconChainHarness(spec, E, validator_count=8, mock_execution_layer=True)
    h.extend_chain(3 * E.SLOTS_PER_EPOCH)
    assert type(h.chain.head_state).__name__ == "BeaconStateElectra"

    fin_root = bytes(h.chain.finalized_checkpoint.root)
    assert fin_root != b"\x00" * 32
    boot_state = h.chain.state_for_block_root(fin_root)
    boot = create_bootstrap(boot_state, E)
    assert len(boot.current_sync_committee_branch) == 6
    store = initialize_light_client_store(
        boot.header.beacon.hash_tree_root(), boot, E
    )

    head_block = h.chain.head_block()
    agg = head_block.message.body.sync_aggregate
    attested_root = bytes(head_block.message.parent_root)
    attested_state = h.chain.state_for_block_root(attested_root)
    cp_root = bytes(attested_state.finalized_checkpoint.root)
    fin_state = h.chain.state_for_block_root(cp_root)
    update = create_update(
        attested_state, fin_state, agg,
        signature_slot=int(head_block.message.slot), E=E,
    )
    assert len(update.next_sync_committee_branch) == 6
    assert len(update.finality_branch) == 7

    # SSZ round-trip through the Electra container family (what the HTTP
    # route ships with Eth-Consensus-Version: electra)
    lt = build_light_client_types(E, electra=True)
    update = lt.LightClientUpdate.deserialize(update.serialize())

    process_light_client_update(
        store, update,
        current_slot=int(h.chain.head_state.slot) + 1,
        spec=spec, E=E,
        genesis_validators_root=bytes(h.chain.genesis_validators_root),
    )
    assert store.finalized_header.beacon.slot >= boot.header.beacon.slot

    # a tampered deep branch must NOT verify (the extra level is part of
    # the proof, not padding)
    bad_branch = list(update.next_sync_committee_branch)
    bad_branch[5] = b"\x66" * 32  # the Electra-only level
    bad = lt.LightClientUpdate(
        attested_header=update.attested_header,
        next_sync_committee=update.next_sync_committee,
        next_sync_committee_branch=bad_branch,
        finalized_header=update.finalized_header,
        finality_branch=list(update.finality_branch),
        sync_aggregate=update.sync_aggregate,
        signature_slot=update.signature_slot,
    )
    with pytest.raises(LightClientError):
        process_light_client_update(
            LightClientStore(
                finalized_header=boot.header,
                current_sync_committee=boot.current_sync_committee,
            ),
            bad,
            current_slot=int(h.chain.head_state.slot) + 1,
            spec=spec, E=E,
            genesis_validators_root=bytes(h.chain.genesis_validators_root),
        )
