"""Execution block-hash verification: keccak-256, RLP, the ordered
Merkle-Patricia trie root, and header reconstruction — validated against
public mainnet/testnet block hashes (the same public vectors the
reference checks in execution_layer/src/block_hash.rs tests)."""

from types import SimpleNamespace

from lighthouse_tpu.execution_layer.block_hash import (
    EMPTY_OMMERS_HASH,
    calculate_execution_block_hash,
    rlp_encode_header_fields,
    rlp_encode_withdrawal,
    verify_payload_block_hash,
)
from lighthouse_tpu.utils.keccak import keccak256
from lighthouse_tpu.utils.rlp import (
    decode,
    encode,
    ordered_trie_root,
    trie_root,
)

EMPTY_TRIE_ROOT = bytes.fromhex(
    "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
)


def test_keccak_public_anchors():
    assert keccak256(b"").hex() == (
        "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
    )
    assert keccak256(b"abc").hex() == (
        "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
    )
    assert keccak256(bytes([0xC0])) == EMPTY_OMMERS_HASH
    # rate-boundary inputs exercise the multi-block sponge
    for n in (135, 136, 137, 272, 1000):
        assert len(keccak256(b"q" * n)) == 32


def test_rlp_encode_decode_round_trip():
    cases = [
        b"",
        b"\x00",
        b"\x7f",
        b"\x80",
        b"dog",
        b"x" * 55,
        b"y" * 56,
        b"z" * 1024,
        [],
        [b"cat", b"dog"],
        [[], [[]], [b"a", [b"b"]]],
    ]
    for case in cases:
        assert decode(encode(case)) == case
    # canonical single-byte rule
    assert encode(b"\x05") == b"\x05"
    assert encode(0) == b"\x80"
    assert encode(15) == b"\x0f"
    assert encode(1024) == b"\x82\x04\x00"


def test_trie_roots_match_public_values():
    assert ordered_trie_root([]) == EMPTY_TRIE_ROOT
    # the canonical single-entry trie from the yellow-paper test suite:
    # {0x80 -> 'dog'} style checks are covered by the block vectors below;
    # here, structural invariants:
    a = ordered_trie_root([b"dog", b"cat", b"bird"])
    b = ordered_trie_root([b"dog", b"cat"])
    assert a != b != EMPTY_TRIE_ROOT
    # order matters (it is an INDEX-keyed trie, not a set)
    assert ordered_trie_root([b"x", b"y"]) != ordered_trie_root([b"y", b"x"])
    # deep branch + extension shapes: 64 keys sharing prefixes
    many = trie_root({i.to_bytes(4, "big"): b"v%d" % i for i in range(64)})
    assert len(many) == 32


def _payload(**kw):
    base = dict(
        parent_hash=b"\x00" * 32,
        fee_recipient=b"\x00" * 20,
        state_root=b"\x00" * 32,
        receipts_root=EMPTY_TRIE_ROOT,
        logs_bloom=b"\x00" * 256,
        prev_randao=b"\x00" * 32,
        block_number=1,
        gas_limit=0x016345785D8A0000,
        gas_used=0x015534,
        timestamp=0x079E,
        extra_data=b"\x42",
        base_fee_per_gas=0x036B,
        block_hash=b"\x00" * 32,
        transactions=[],
    )
    base.update(kw)
    return SimpleNamespace(**base)


def test_bellatrix_block_vector():
    """Public bellatrix-era test block (difficulty 0, mix_hash set)."""
    p = _payload(
        parent_hash=bytes.fromhex(
            "927ca537f06c783a3a2635b8805eef1c8c2124f7444ad4a3389898dd832f2dbe"
        ),
        fee_recipient=bytes.fromhex("ba5e000000000000000000000000000000000000"),
        state_root=bytes.fromhex(
            "e97859b065bd8dbbb4519c7cb935024de2484c2b7f881181b4360492f0b06b82"
        ),
        receipts_root=bytes.fromhex(
            "29b0562f7140574dd0d50dee8a271b22e1a0a7b78fca58f7c60370d8317ba2a9"
        ),
        prev_randao=bytes.fromhex(
            "0000000000000000000000000000000000000000000000000000000000020000"
        ),
    )
    tx_root = bytes.fromhex(
        "50f738580ed699f0469702c7ccc63ed2e51bc034be9479b7bff4e68dee84accf"
    )
    rlp = rlp_encode_header_fields(p, tx_root, None, None)
    assert keccak256(rlp).hex() == (
        "5b1f0f2efdaa19e996b4aea59eeb67620259f09732732a339a10dac311333684"
    )


def test_mainnet_block_16182891_vector():
    """Real mainnet block 16182891 (public chain data)."""
    p = _payload(
        parent_hash=bytes.fromhex(
            "3e9c7b3f403947f110f68c4564a004b73dd8ebf73b143e46cc637926eec01a6d"
        ),
        fee_recipient=bytes.fromhex("dafea492d9c6733ae3d56b7ed1adb60692c98bc5"),
        state_root=bytes.fromhex(
            "5a8183d230818a167477420ce3a393ca3ef8706a7d596694ab6059894ed6fda9"
        ),
        receipts_root=bytes.fromhex(
            "371c76821b1cc21232574604eac5349d51647eb530e2a45d4f6fe2c501351aa5"
        ),
        logs_bloom=bytes.fromhex(
            "1a2c559955848d2662a0634cb40c7a6192a1524f11061203689bcbcdec901b05"
            "4084d4f4d688009d24c10918e0089b48e72fe2d7abafb903889d10c3827c6901"
            "096612d259801b1b7ba1663a4201f5f88f416a9997c55bcc2c54785280143b05"
            "7a008764c606182e324216822a2d5913e797a05c16cc1468d001acf3783b18e0"
            "0e0203033e43106178db554029e83ca46402dc49d929d7882a04a0e7215041bd"
            "abf7430bd10ef4bb658a40f064c63c4816660241c2480862f26742fdf9ca4163"
            "7731350301c344e439428182a03e384484e6d65d0c8a10117c6739ca201b6097"
            "4519a1ae6b0c3966c0f650b449d10eae065dab2c83ab4edbab5efdea50bbc801"
        ),
        block_number=16182891,
        gas_limit=0x1C9C380,
        gas_used=0xE9B752,
        timestamp=0x6399BF63,
        extra_data=bytes.fromhex(
            "496c6c756d696e61746520446d6f63726174697a6520447374726962757465"
        ),
        prev_randao=bytes.fromhex(
            "bf5289894b2ceab3549f92f063febbac896b280ddb18129a57cff13113c11b13"
        ),
        base_fee_per_gas=0x34187B238,
    )
    tx_root = bytes.fromhex(
        "0223f0cb35f184d2ac409e89dc0768ad738f777bd1c85d3302ca50f307180c94"
    )
    rlp = rlp_encode_header_fields(p, tx_root, None, None)
    assert keccak256(rlp).hex() == (
        "6da69709cd5a34079b6604d29cd78fc01dacd7c6268980057ad92a2bede87351"
    )


def test_deneb_block_vector_through_full_payload_path():
    """Public deneb devnet block — driven through the FULL payload path:
    empty transactions/withdrawals lists must produce the empty trie
    roots the vector's header carries."""
    p = _payload(
        parent_hash=bytes.fromhex(
            "172864416698b842f4c92f7b476be294b4ef720202779df194cd225f531053ab"
        ),
        fee_recipient=bytes.fromhex("878705ba3f8bc32fcf7f4caa1a35e72af65cf766"),
        state_root=bytes.fromhex(
            "c6457d0df85c84c62d1c68f68138b6e796e8a44fb44de221386fb2d5611c41e0"
        ),
        receipts_root=EMPTY_TRIE_ROOT,
        block_number=97,
        gas_limit=27482534,
        gas_used=0,
        timestamp=1692132829,
        extra_data=bytes.fromhex("d883010d00846765746888676f312e32302e37856c696e7578"),
        prev_randao=bytes.fromhex(
            "0b493c22d2ad4ca76c77ae6ad916af429b42b1dc98fdcb8e5ddbd049bbc5d623"
        ),
        base_fee_per_gas=2374,
        transactions=[],
        withdrawals=[],
        blob_gas_used=0,
        excess_blob_gas=0,
    )
    parent_beacon_root = bytes.fromhex(
        "f7d327d2c04e4f12e9cdd492e53d39a1d390f8b1571e3b2a22ac6e1e170e5b1a"
    )
    expected = bytes.fromhex(
        "a7448e600ead0a23d16f96aa46e8dea9eef8a7c5669a5f0a5ff32709afe9c408"
    )
    computed, tx_root = calculate_execution_block_hash(p, parent_beacon_root)
    assert tx_root == EMPTY_TRIE_ROOT
    assert computed == expected
    p.block_hash = expected
    assert verify_payload_block_hash(p, parent_beacon_root)
    # any field perturbation breaks the hash
    p.gas_used = 1
    assert not verify_payload_block_hash(p, parent_beacon_root)


def test_withdrawal_rlp_and_nonempty_roots():
    w = SimpleNamespace(index=7, validator_index=1234, address=b"\xaa" * 20, amount=5_000_000)
    enc = rlp_encode_withdrawal(w)
    assert decode(enc) == [b"\x07", b"\x04\xd2", b"\xaa" * 20, b"\x4c\x4b\x40"]
    root_one = ordered_trie_root([enc])
    root_two = ordered_trie_root([enc, enc])
    assert root_one != root_two != EMPTY_TRIE_ROOT
