"""Slasher detection: double votes, surround votes, double proposals,
pruning — and the produced slashings actually apply in the state
transition (slasher/src/slasher.rs test surface).

Every engine-generic test runs against BOTH engines: the columnar
min/max-span subsystem (default) and the retained scalar reference
(`slasher/reference.py`) — same detections, same emission order."""

from dataclasses import replace

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.slasher import Slasher, SlasherConfig
from lighthouse_tpu.slasher.columnar import ColumnarSlasher
from lighthouse_tpu.slasher.reference import ReferenceSlasher
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

T = build_types(E)

ENGINES = {"columnar": ColumnarSlasher, "reference": ReferenceSlasher}


@pytest.fixture(params=sorted(ENGINES))
def engine(request):
    return ENGINES[request.param]


def _att(indices, source, target, root=b"\x01" * 32, head=b"\x02" * 32):
    return T.IndexedAttestation(
        attesting_indices=indices,
        data=T.AttestationData(
            slot=target * E.SLOTS_PER_EPOCH,
            index=0,
            beacon_block_root=head,
            source=T.Checkpoint(epoch=source, root=root),
            target=T.Checkpoint(epoch=target, root=root),
        ),
        signature=b"\x00" * 96,
    )


def _header(proposer, slot, state_root=b"\x00" * 32):
    return T.SignedBeaconBlockHeader(
        message=T.BeaconBlockHeader(
            slot=slot,
            proposer_index=proposer,
            parent_root=b"\x11" * 32,
            state_root=state_root,
            body_root=b"\x22" * 32,
        ),
        signature=b"\x00" * 96,
    )


def test_double_vote_detected(engine):
    s = engine(E)
    s.accept_attestation(_att([1, 2], 0, 5, head=b"\x02" * 32))
    s.accept_attestation(_att([2, 3], 0, 5, head=b"\x03" * 32))  # same target, diff data
    out = s.process_queued(current_epoch=6)
    assert out["attester_slashings"] >= 1
    atts, _ = s.drain_slashings()
    assert atts
    sl = atts[0]
    assert sl.attestation_1.data.target.epoch == 5
    assert sl.attestation_1.data.hash_tree_root() != sl.attestation_2.data.hash_tree_root()


def test_duplicate_attestation_not_slashable(engine):
    s = engine(E)
    a = _att([1], 0, 5)
    s.accept_attestation(a)
    s.accept_attestation(_att([1], 0, 5))  # identical data
    out = s.process_queued(6)
    assert out["attester_slashings"] == 0


def test_surround_both_directions(engine):
    from lighthouse_tpu.state_processing.accessors import (
        is_slashable_attestation_data,
    )

    s = engine(E)
    s.accept_attestation(_att([7], 2, 3))
    s.process_queued(4)
    # new surrounds old: (1, 5) ⊃ (2, 3)
    s.accept_attestation(_att([7], 1, 5))
    assert s.process_queued(6)["attester_slashings"] == 1
    sl, _ = s.drain_slashings()
    # emitted order must satisfy the spec predicate (data_1 surrounds data_2)
    assert is_slashable_attestation_data(sl[0].attestation_1.data, sl[0].attestation_2.data)

    s2 = engine(E)
    s2.accept_attestation(_att([9], 1, 6))
    s2.process_queued(7)
    # old surrounds new: (2, 4) ⊂ (1, 6)
    s2.accept_attestation(_att([9], 2, 4))
    assert s2.process_queued(7)["attester_slashings"] == 1
    sl2, _ = s2.drain_slashings()
    assert is_slashable_attestation_data(
        sl2[0].attestation_1.data, sl2[0].attestation_2.data
    )


def test_double_proposal_detected(engine):
    s = engine(E)
    s.accept_block_header(_header(4, 32, state_root=b"\xaa" * 32))
    s.accept_block_header(_header(4, 32, state_root=b"\xbb" * 32))
    s.accept_block_header(_header(4, 33, state_root=b"\xcc" * 32))  # different slot ok
    out = s.process_queued(5)
    assert out["proposer_slashings"] == 1
    _, props = s.drain_slashings()
    assert props[0].signed_header_1.message.slot == 32


def test_double_proposal_not_reemitted_on_relay(engine):
    """Regression: the same equivocating header pair is re-gossiped by
    every peer; a re-seen pair must not re-emit another ProposerSlashing
    (one emission per equivocation, dedup keyed (proposer, slot, roots))."""
    s = engine(E)
    h1 = _header(4, 32, state_root=b"\xaa" * 32)
    h2 = _header(4, 32, state_root=b"\xbb" * 32)
    s.accept_block_header(h1)
    s.accept_block_header(h2)
    assert s.process_queued(5)["proposer_slashings"] == 1
    # the pair re-arrives (relay storm), same cycle AND a later cycle
    s.accept_block_header(h1)
    s.accept_block_header(h2)
    s.accept_block_header(h2)
    assert s.process_queued(5)["proposer_slashings"] == 0
    s.accept_block_header(h2)
    assert s.process_queued(6)["proposer_slashings"] == 0
    _, props = s.drain_slashings()
    assert len(props) == 1
    # a THIRD conflicting header is a new pair: emitted once
    s.accept_block_header(_header(4, 32, state_root=b"\xcc" * 32))
    assert s.process_queued(6)["proposer_slashings"] == 1


def test_pruning_bounds_history(engine):
    s = engine(E, SlasherConfig(history_length=4))
    s.accept_attestation(_att([1], 0, 1))
    s.process_queued(1)
    assert s.has_attestation_record(1, 1)
    s.process_queued(100)  # far future: epoch-1 record pruned
    assert not s.has_attestation_record(1, 1)
    assert s.attestation_record_count() == 0


def test_detected_slashing_applies_in_state_transition(engine):
    """End-to-end: the slasher's output feeds process_attester_slashing and
    the offender gets slashed (the slasher/service → op-pool → block path)."""
    from lighthouse_tpu.state_processing import interop_genesis_state
    from lighthouse_tpu.state_processing.per_block import process_attester_slashing
    from lighthouse_tpu.types.chain_spec import minimal_spec

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    kps = bls.interop_keypairs(8)
    state = interop_genesis_state(kps, 1_600_000_000, b"\x42" * 32, spec, E)
    state.slot = 6 * E.SLOTS_PER_EPOCH

    s = engine(E)
    s.accept_attestation(_att([3], 0, 5, head=b"\x02" * 32))
    s.accept_attestation(_att([3], 0, 5, head=b"\x03" * 32))
    s.process_queued(6)
    slashings, _ = s.drain_slashings()
    assert slashings
    process_attester_slashing(state, slashings[0], spec, E, verify_signatures=False)
    assert state.validators[3].slashed


def test_slasher_service_end_to_end():
    """SlasherService (slasher/service analog): a double vote observed on
    the live chain is detected at the epoch tick and the slashing lands
    in the op pool — then in a produced block."""
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.slasher.service import SlasherService
    from lighthouse_tpu.types.chain_spec import minimal_spec

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    svc = SlasherService(h.chain)
    assert h.chain.slasher_service is svc
    h.extend_chain(2 * E.SLOTS_PER_EPOCH)  # normal life: nothing slashable

    # equivocation: validator 3 votes twice for the same target epoch
    epoch = 1
    a1 = _att([3], 0, epoch, root=b"\x0a" * 32)
    a2 = _att([3], 0, epoch, root=b"\x0b" * 32)
    svc.observe_indexed_attestation(a1)
    svc.observe_indexed_attestation(a2)
    stats = svc.on_slot(h.chain.head_state.slot + E.SLOTS_PER_EPOCH)
    assert stats is not None
    assert h.chain.op_pool._attester_slashings, "slashing not pooled"
    # the produced block carries it
    slot = h.chain.head_state.slot + 1
    h.slot_clock.set_slot(slot)
    block, _ = h.chain.produce_block_on_state(slot, h.randao_reveal(0, slot))
    assert len(block.body.attester_slashings) == 1
    slashed = set(
        block.body.attester_slashings[0].attestation_1.attesting_indices
    ) & set(block.body.attester_slashings[0].attestation_2.attesting_indices)
    assert slashed == {3}


def test_columnar_kill_switch(monkeypatch):
    """LIGHTHOUSE_TPU_COLUMNAR_SLASHER=0 routes the factory to the
    retained scalar engine; default is the columnar subsystem."""
    assert isinstance(Slasher(E), ColumnarSlasher)
    monkeypatch.setenv("LIGHTHOUSE_TPU_COLUMNAR_SLASHER", "0")
    assert isinstance(Slasher(E), ReferenceSlasher)
    monkeypatch.setenv("LIGHTHOUSE_TPU_COLUMNAR_SLASHER", "1")
    assert isinstance(Slasher(E), ColumnarSlasher)


def test_persistence_restart_detects_double_vote(engine, tmp_path):
    """Detection history written through the KV store survives a restart:
    the first vote lands before the 'crash', the conflicting one after."""
    from lighthouse_tpu.store import open_item_store

    from lighthouse_tpu.store.kv import DBColumn

    store = open_item_store(str(tmp_path / "slasher-db"))
    s1 = engine(E, store=store)
    s1.accept_attestation(_att([7, 8], 0, 5, head=b"\x02" * 32))
    s1.accept_block_header(_header(3, 41))
    assert s1.process_queued(current_epoch=6) == {
        "attester_slashings": 0,
        "proposer_slashings": 0,
    }
    # the body is stored ONCE for the 2-index aggregate; records are small
    assert len(store.keys(DBColumn.SLASHER_INDEXED)) == 1
    assert len(store.keys(DBColumn.SLASHER_ATTESTATION)) == 2
    del s1  # no clean shutdown needed — process_queued already flushed

    s2 = engine(E, store=store)
    # records reloaded
    assert s2.has_attestation_record(7, 5) and s2.has_attestation_record(8, 5)
    assert 3 in s2._blocks and 41 in s2._blocks[3]
    # conflicting vote and proposal arriving after restart still slash
    s2.accept_attestation(_att([8], 0, 5, head=b"\x03" * 32))
    s2.accept_block_header(_header(3, 41, state_root=b"\x99" * 32))
    out = s2.process_queued(current_epoch=6)
    assert out["attester_slashings"] == 1
    assert out["proposer_slashings"] == 1
    store.close()


def test_persistence_prunes_on_disk(engine, tmp_path):
    from lighthouse_tpu.store import open_item_store
    from lighthouse_tpu.store.kv import DBColumn

    store = open_item_store(str(tmp_path / "slasher-db"))
    s = engine(E, SlasherConfig(history_length=4), store=store)
    s.accept_attestation(_att([1], 0, 2))
    s.process_queued(current_epoch=3)
    assert store.keys(DBColumn.SLASHER_ATTESTATION)
    s.process_queued(current_epoch=10)  # floor=6 > target 2 → pruned
    assert store.keys(DBColumn.SLASHER_ATTESTATION) == []
    assert store.keys(DBColumn.SLASHER_INDEXED) == []
    # a fresh instance sees the pruned view
    s2 = engine(E, SlasherConfig(history_length=4), store=store)
    assert s2.attestation_record_count() == 0
    store.close()
