"""Observability pipeline: trace trees, slot-anchored delays, queue waits.

PR 9's acceptance suite: span trees assemble with correct parentage
(including across `copy_context` thread hops and the beacon_processor
worker hop), completed traces land in the bounded collector and export as
Chrome trace-event JSON over HTTP, the BlockTimesCache carries the full
slot-anchored milestone set and shouts (once, with a per-stage breakdown)
about late head blocks, queue observability fills per-WorkType
time-in-queue histograms from the real sync path, and the whole layer
switches OFF (`LIGHTHOUSE_TPU_TRACE_COLLECT=0`) back to the flat
per-name histogram behavior."""

import contextvars
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.beacon_processor import BeaconProcessor, WorkType
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.metrics.trace_collector import (
    COLLECTOR,
    TraceCollector,
    span_count,
    stage_rollup,
    to_chrome_trace,
    trace_summary,
)
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
from lighthouse_tpu.utils.slot_clock import ManualSlotClock
from lighthouse_tpu.utils.tracing import Span, current_span, span


def _harness(slots=0, attest=False, validator_count=16):
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=validator_count)
    if slots:
        h.extend_chain(slots, attest=attest)
    return h


def _fake_trace(name: str, duration_s: float, trace_id: str) -> Span:
    """A hand-built closed root span (collector unit tests)."""
    s = Span(name)
    s.trace_id = trace_id
    s.duration_s = duration_s
    s.t0 = 0.0
    return s


# -- tree assembly -----------------------------------------------------------


def test_trace_tree_assembly_nested():
    with span("obs_test_root") as root:
        with span("stage_a"):
            with span("stage_a_inner"):
                pass
        with span("stage_b"):
            pass
    assert root.trace_id is not None
    assert [c.name for c in root.children] == ["stage_a", "stage_b"]
    assert [c.name for c in root.children[0].children] == ["stage_a_inner"]
    # every span carries the ROOT's trace id
    assert root.children[0].children[0].trace_id == root.trace_id
    assert span_count(root) == 4
    assert COLLECTOR.get(root.trace_id) is root
    # self-time: stages overlap when nested, so self-time (not duration)
    # is what sums back to the root's duration
    rollup = stage_rollup(root)
    assert set(rollup) == {"obs_test_root", "stage_a", "stage_a_inner", "stage_b"}
    total_self = sum(e["self_ms"] for e in rollup.values())
    assert total_self == pytest.approx(root.duration_s * 1000, rel=0.05, abs=0.5)


def test_trace_parentage_across_copy_context_thread():
    """The beacon_processor worker-hop contract, isolated: a thread run
    inside the submitter's copied Context attaches its spans under the
    submitting span."""

    def worker():
        assert current_span() is not None  # inherited via the Context
        with span("cross_thread_stage"):
            time.sleep(0.002)

    with span("obs_test_ctx_root") as root:
        ctx = contextvars.copy_context()
        t = threading.Thread(target=ctx.run, args=(worker,))
        t.start()
        t.join()
    assert [c.name for c in root.children] == ["cross_thread_stage"]
    child = root.children[0]
    assert child.trace_id == root.trace_id
    assert COLLECTOR.get(root.trace_id) is root


def test_trace_parentage_across_beacon_processor_hop():
    """End-to-end across the real scheduler: submit() copies the
    submitter's context, the worker runs the handler inside it, and the
    handler's spans land under the submitting span."""
    bp = BeaconProcessor(num_workers=2, name="obs-test")
    try:

        def handler(item):
            with span("worker_stage", item=item):
                pass

        with span("obs_test_submit_root") as root:
            assert bp.submit(WorkType.API_REQUEST, "x", handler)
            assert bp.drain(timeout=5.0)
        # the worker-side span attached under the submitting root
        assert "worker_stage" in [c.name for c in root.children]
        assert root.children[0].trace_id == root.trace_id
    finally:
        bp.shutdown()


# -- Chrome export golden shape ----------------------------------------------


def test_chrome_export_golden_shape():
    with span("obs_test_chrome", block="0xab") as root:
        with span("inner_stage"):
            pass
    doc = to_chrome_trace(root)
    # golden shape: the exact keys chrome://tracing / Perfetto load
    assert set(doc) == {"displayTimeUnit", "otherData", "traceEvents"}
    assert doc["displayTimeUnit"] == "ms"
    assert doc["otherData"] == {
        "trace_id": root.trace_id,
        "root": "obs_test_chrome",
    }
    assert len(doc["traceEvents"]) == 2
    for ev in doc["traceEvents"]:
        assert set(ev) == {"name", "cat", "ph", "ts", "dur", "pid", "tid", "args"}
        assert ev["ph"] == "X" and ev["cat"] == "span" and ev["pid"] == 0
        assert "self_time_ms" in ev["args"]
    # events sorted by ts, root first at ts=0
    ts = [ev["ts"] for ev in doc["traceEvents"]]
    assert ts == sorted(ts) and ts[0] == 0
    assert doc["traceEvents"][0]["args"]["block"] == "0xab"
    json.dumps(doc)  # must be JSON-serializable as-is


# -- collector bounds --------------------------------------------------------


def test_collector_ring_eviction():
    c = TraceCollector(ring_size=4, slowest_k=2)
    for i in range(10):
        c.record(_fake_trace("ring_root", 0.001 * (i + 1), f"ring-{i}"))
    recent = c.recent()
    assert len(recent) == 4  # ring bound holds
    assert [r.trace_id for r in recent] == ["ring-9", "ring-8", "ring-7", "ring-6"]
    # evicted-and-unreferenced ids are forgotten…
    assert c.get("ring-0") is None
    # …but reservoir-retained ones survive ring churn: the slowest two
    # are the last two recorded (durations increase monotonically)
    slowest = c.slowest("ring_root")
    assert [r.trace_id for r in slowest] == ["ring-9", "ring-8"]
    assert c.get("ring-8") is not None


def test_collector_slowest_reservoir_keeps_tail():
    c = TraceCollector(ring_size=2, slowest_k=2)
    c.record(_fake_trace("tail_root", 9.0, "slow-a"))  # slowest overall
    for i in range(6):
        c.record(_fake_trace("tail_root", 0.001, f"fast-{i}"))
    c.record(_fake_trace("tail_root", 5.0, "slow-b"))
    # the ring only remembers the last two, but the tail survives
    assert [r.trace_id for r in c.recent()] == ["slow-b", "fast-5"]
    assert [r.trace_id for r in c.slowest("tail_root")] == ["slow-a", "slow-b"]
    # the 9 s trace is long gone from the ring yet still fetchable by id
    assert c.get("slow-a") is not None
    assert trace_summary(c.get("slow-a"))["duration_ms"] == 9000.0


def test_collector_index_json_shape():
    c = TraceCollector(ring_size=8, slowest_k=2)
    c.record(_fake_trace("idx_root", 0.5, "idx-0"))
    doc = c.index_json()
    assert set(doc) == {"data"}
    assert set(doc["data"]) == {"recent", "slowest"}
    entry = doc["data"]["recent"][0]
    assert set(entry) == {"trace_id", "root", "duration_ms", "spans", "stages"}
    json.dumps(doc)


# -- off switch --------------------------------------------------------------


def test_off_switch_restores_flat_behavior(monkeypatch):
    monkeypatch.setenv("LIGHTHOUSE_TPU_TRACE_COLLECT", "0")
    hist = REGISTRY.histogram("trace_span_seconds_obs_test_flat")
    count_before = hist.count
    ring_before = [r.trace_id for r in COLLECTOR.recent(5)]
    with span("obs_test_flat") as root:
        with span("obs_test_flat_child") as child:
            pass
    # no tree assembly: no trace ids, no child attachment, no delivery
    assert root.trace_id is None and child.trace_id is None
    assert root.children == []
    assert [r.trace_id for r in COLLECTOR.recent(5)] == ring_before
    # the flat per-name histogram still observes every span (today's
    # behavior, exactly)
    assert hist.count == count_before + 1
    # children INHERIT the off decision; a fresh root re-reads the env
    monkeypatch.setenv("LIGHTHOUSE_TPU_TRACE_COLLECT", "1")
    with span("obs_test_flat") as root2:
        pass
    assert root2.trace_id is not None


# -- block import acceptance: trace + milestones over HTTP -------------------


def test_block_import_yields_trace_tree_and_full_milestones():
    """THE acceptance path: a block imported in the harness yields a
    retrievable ≥5-span trace tree with correct parentage at
    /lighthouse/traces/<id> (Chrome trace-event JSON), and its BlockTimes
    entry carries the full slot-anchored milestone set."""
    from lighthouse_tpu.http_api import HttpApiServer
    from lighthouse_tpu.metrics.server import MetricsServer
    from lighthouse_tpu.state_processing import per_slot_processing
    from lighthouse_tpu.state_processing.accessors import (
        get_beacon_proposer_index,
    )

    h = _harness()
    # drive the gossip pipeline explicitly so EVERY milestone lands
    # (extend_chain's direct process_block skips the gossip stage)
    slot = h.chain.head_state.slot + 1
    h.slot_clock.set_slot(slot)
    h.slot_clock.set_seconds_into_slot(1.0)
    state = h.chain.head_state.copy()
    while state.slot < slot:
        per_slot_processing(state, h.spec, E)
    proposer = get_beacon_proposer_index(state, E)
    parent_root = h.chain.head_root
    block, _ = h.chain.produce_block_on_state(
        slot,
        h.randao_reveal(proposer, slot, state),
        sync_aggregate_fn=lambda st: h.make_sync_aggregate(
            st, slot, parent_root
        ),
    )
    signed = h.sign_block(block, state)
    gossip_verified = h.chain.verify_block_for_gossip(signed)
    root_hash = h.chain.process_block(gossip_verified)

    # -- the trace tree
    tree = next(t for t in COLLECTOR.recent(50) if t.name == "block_import")
    assert span_count(tree) >= 5
    child_names = {c.name for c in tree.children}
    assert {"state_transition", "fork_choice_on_block"} <= child_names
    st = next(c for c in tree.children if c.name == "state_transition")
    assert {c.name for c in st.children} >= {
        "signature_set_assembly",
        "signature_batch_verify",
    }
    for c in tree.children:
        assert c.trace_id == tree.trace_id and c.parent is tree

    # -- retrievable over HTTP as Chrome trace-event JSON, both servers
    msrv = MetricsServer().start()
    asrv = HttpApiServer(h.chain).start()
    try:
        for port in (msrv.port, asrv.port):
            doc = json.load(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/lighthouse/traces/{tree.trace_id}"
                )
            )
            assert doc["otherData"]["trace_id"] == tree.trace_id
            assert len(doc["traceEvents"]) >= 5
            idx = json.load(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/lighthouse/traces"
                )
            )
            held = [e["trace_id"] for e in idx["data"]["recent"]] + [
                e["trace_id"]
                for roots in idx["data"]["slowest"].values()
                for e in roots
            ]
            assert tree.trace_id in held
    finally:
        msrv.stop()
        asrv.stop()

    # -- the full slot-anchored milestone set
    bt = h.chain.block_times_cache.get(root_hash)
    assert bt is not None
    assert set(bt.stamps) == {
        "observed",
        "gossip_verified",
        "signature_verified",
        "payload_verified",
        "imported",
        "became_head",
    }
    assert set(bt.slot_offsets) == set(bt.stamps)
    # milestones are ordered along the pipeline
    stamps = [bt.stamps[m] for m in (
        "observed", "gossip_verified", "signature_verified",
        "payload_verified", "imported", "became_head",
    )]
    assert stamps == sorted(stamps)
    # the manual clock sat at 1.0 s into the slot for the whole import
    assert bt.slot_offsets["observed"] == pytest.approx(1.0)
    assert bt.all_delays["imported_slot_start"] == pytest.approx(1.0)
    assert "observed_to_imported" in bt.all_delays
    assert "imported_to_head" in bt.all_delays


def test_api_requests_are_traced():
    h = _harness(slots=1)
    from lighthouse_tpu.http_api import HttpApiServer

    before = REGISTRY.counter("trace_collector_traces_total").value(
        root="api_request"
    )
    srv = HttpApiServer(h.chain).start()
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/eth/v1/beacon/genesis"
        ).read()
        # the trace endpoints themselves must NOT mint api_request traces
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/lighthouse/traces"
        ).read()
    finally:
        srv.stop()
    after = REGISTRY.counter("trace_collector_traces_total").value(
        root="api_request"
    )
    assert after == before + 1


def test_trace_404_for_unknown_id():
    from lighthouse_tpu.metrics.server import MetricsServer

    srv = MetricsServer().start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/lighthouse/traces/ffffffffffff"
            )
        assert exc_info.value.code == 404
    finally:
        srv.stop()


# -- slot-anchored delays ----------------------------------------------------


def test_block_times_cache_slot_anchoring_and_breakdown():
    from lighthouse_tpu.beacon_chain.block_times_cache import BlockTimesCache

    clock = ManualSlotClock(seconds_per_slot=12)
    cache = BlockTimesCache(slot_clock=clock, seconds_per_slot=12)
    clock.set_slot(7)
    root = b"\x01" * 32

    clock.set_seconds_into_slot(2.0)
    cache.set_observed(root, 7, 100.0)
    clock.set_seconds_into_slot(3.0)
    cache.set_gossip_verified(root, 7, 100.8)
    cache.set_signature_verified(root, 7, 101.0)
    clock.set_seconds_into_slot(4.5)
    cache.set_payload_verified(root, 7, 101.5)
    cache.set_imported(root, 7, 102.0)
    cache.set_became_head(root, 7, 102.5)

    e = cache.get(root)
    assert e.slot_offsets["observed"] == pytest.approx(2.0)
    assert e.slot_offsets["gossip_verified"] == pytest.approx(3.0)
    assert e.slot_offsets["payload_verified"] == pytest.approx(4.5)
    assert e.all_delays["observed_to_imported"] == pytest.approx(2.0)
    assert e.all_delays["imported_to_head"] == pytest.approx(0.5)
    bd = e.stage_breakdown_ms()
    assert bd["gossip_verified"] == pytest.approx(800.0)
    assert bd["imported"] == pytest.approx(500.0)
    # first write wins: a replayed observation can't rewrite history
    clock.set_seconds_into_slot(9.0)
    cache.set_observed(root, 7, 999.0)
    assert e.stamps["observed"] == 100.0
    # legacy accessors still resolve (pre-milestone-chain API surface)
    assert e.observed_at == 100.0 and e.imported_at == 102.0


def test_late_head_block_warning_carries_breakdown(caplog):
    from lighthouse_tpu.beacon_chain.block_times_cache import BlockTimesCache

    clock = ManualSlotClock(seconds_per_slot=12)
    cache = BlockTimesCache(slot_clock=clock, seconds_per_slot=12)
    clock.set_slot(3)
    root = b"\x02" * 32
    cache.set_observed(root, 3, 50.0)
    cache.set_imported(root, 3, 53.4)
    clock.set_seconds_into_slot(6.0)  # way past the 4 s deadline
    with caplog.at_level(logging.WARNING, logger="lighthouse_tpu"):
        cache.set_became_head(root, 3, 53.9)
    late = [r for r in caplog.records if "late head block" in r.getMessage()]
    assert len(late) == 1
    msg = late[0].getMessage()
    assert "head_slot_offset_s=6.0" in msg
    assert "deadline_s=4.0" in msg
    assert "stage_imported_ms=3400.0" in msg  # the per-stage breakdown
    assert "stage_became_head_ms=500.0" in msg


def test_timely_head_and_syncing_head_stay_quiet(caplog):
    from lighthouse_tpu.beacon_chain.block_times_cache import BlockTimesCache

    clock = ManualSlotClock(seconds_per_slot=12)
    cache = BlockTimesCache(slot_clock=clock, seconds_per_slot=12)
    with caplog.at_level(logging.WARNING, logger="lighthouse_tpu"):
        # timely: within the deadline
        clock.set_slot(1)
        clock.set_seconds_into_slot(2.0)
        cache.set_became_head(b"\x03" * 32, 1, 10.0)
        # catch-up: hours late relative to its own slot, but the clock is
        # far ahead — range sync must not flood the log
        clock.set_slot(500)
        clock.set_seconds_into_slot(2.0)
        cache.set_became_head(b"\x04" * 32, 3, 20.0)
    assert not [
        r for r in caplog.records if "late head block" in r.getMessage()
    ]


def test_attestation_observation_delay_histograms():
    h = _harness(slots=2)
    hist = REGISTRY.histogram(
        "beacon_attestation_gossip_slot_start_delay_seconds"
    )
    before = hist.count
    slot = h.chain.head_state.slot
    h.slot_clock.set_seconds_into_slot(3.5)
    atts = h.make_unaggregated_attestations(slot, h.chain.head_root)
    h.chain.process_attestation_batch(atts)
    assert hist.count >= before + len(atts)


# -- queue observability -----------------------------------------------------


def test_queue_wait_and_work_histograms_populated():
    bp = BeaconProcessor(num_workers=1, name="obs-queue-test")
    try:
        wait = REGISTRY.histogram("beacon_processor_queue_wait_seconds_api_request")
        run = REGISTRY.histogram("beacon_processor_work_seconds_api_request")
        busy = REGISTRY.counter("beacon_processor_busy_seconds_total")
        w0, r0, b0 = wait.count, run.count, busy.value()
        for i in range(5):
            bp.submit(WorkType.API_REQUEST, i, lambda item: time.sleep(0.001))
        assert bp.drain(timeout=5.0)
        assert wait.count == w0 + 5  # one wait sample per event
        assert run.count == r0 + 5  # singletons: one run sample per event
        assert busy.value() > b0  # busy-seconds accumulated
        assert REGISTRY.gauge("beacon_processor_workers_total").value() == 1.0
    finally:
        bp.shutdown()


def test_sync_sim_populates_chain_segment_queue_waits():
    """The acceptance sim: a real two-node catch-up through the range-sync
    state machine rides the CHAIN_SEGMENT queue and must leave
    time-in-queue samples behind."""
    from lighthouse_tpu.network import NetworkService

    a = _harness(slots=E.SLOTS_PER_EPOCH)
    b = _harness()
    wait = REGISTRY.histogram("beacon_processor_queue_wait_seconds_chain_segment")
    run = REGISTRY.histogram("beacon_processor_work_seconds_chain_segment")
    w0, r0 = wait.count, run.count
    na = NetworkService(a.chain, heartbeat_interval=None).start()
    nb = NetworkService(b.chain, heartbeat_interval=None).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", na.port)
        imported = nb.sync.sync_with(peer)
        assert imported == E.SLOTS_PER_EPOCH
    finally:
        na.stop()
        nb.stop()
    assert wait.count > w0, "range-sync batches must record time-in-queue"
    assert run.count > r0, "range-sync batches must record handler run time"


def test_bench_histogram_percentiles_helper():
    import bench

    # 10 samples in the ≤0.25 bucket of a (0.1, 0.25, 0.5) histogram
    buckets = (0.1, 0.25, 0.5)
    counts = [0, 10, 0, 0]
    p = bench._hist_percentiles(buckets, counts)
    assert p["count"] == 10
    assert 100.0 < p["p50_ms"] <= 250.0
    assert p["p50_ms"] < p["p99_ms"] <= 250.0
    assert bench._hist_percentiles(buckets, [0, 0, 0, 0]) is None


# -- validator monitor satellite ---------------------------------------------


def test_validator_monitor_columnar_and_bounded():
    from lighthouse_tpu.beacon_chain.validator_monitor import (
        MAX_INCLUSION_DELAY_SLOTS,
        MonitoredValidator,
    )

    h = _harness()
    mon = h.chain.validator_monitor
    for i in range(16):
        mon.add_validator(i)
    h.extend_chain(2 * E.SLOTS_PER_EPOCH)
    v0 = mon.summary(0)
    # the columnar path still credits inclusions with sane delays
    assert v0.attestations_included >= 1
    assert all(d >= 1 for d in v0.inclusion_delays.values())

    # the bound: a long soak can't grow the per-validator dict forever
    mv = MonitoredValidator(index=0, pubkey=b"")
    for slot in range(MAX_INCLUSION_DELAY_SLOTS * 3):
        assert mv.record_inclusion(slot, 1)
    assert len(mv.inclusion_delays) == MAX_INCLUSION_DELAY_SLOTS
    # oldest evicted, newest retained
    assert (MAX_INCLUSION_DELAY_SLOTS * 3 - 1) in mv.inclusion_delays
    assert 0 not in mv.inclusion_delays
    # dedup still works within the retained window
    assert not mv.record_inclusion(MAX_INCLUSION_DELAY_SLOTS * 3 - 1, 2)


# -- overhead guard ----------------------------------------------------------


@pytest.mark.perf_smoke
def test_trace_collection_overhead_bounded(monkeypatch):
    """Collection-on vs collection-off block import: the tree assembly +
    collector delivery must stay within a calibrated bound. Median-of-N
    per mode; the bound is loose (2× + 50 ms absolute floor) because
    minimal-preset imports are single-digit ms and CI boxes are noisy —
    what it catches is an accidental O(spans²) walk or a lock on the
    import path, not a 5% regression."""
    import statistics

    def run_mode(collect: str) -> float:
        monkeypatch.setenv("LIGHTHOUSE_TPU_TRACE_COLLECT", collect)
        h = _harness()
        times = []
        for _ in range(8):
            slot = h.chain.head_state.slot + 1
            t0 = time.perf_counter()
            h.add_block_at_slot(slot)
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    off = run_mode("0")
    on = run_mode("1")
    assert on <= off * 2.0 + 0.05, (
        f"trace collection overhead out of bounds: on={on * 1000:.2f}ms "
        f"off={off * 1000:.2f}ms"
    )
