"""Gossipsub mesh behaviour + peer scoring, unit level.

Deterministic throughout: the behaviour's only clock is heartbeat ticks,
the RNG is seeded, and the transport is a recording fake — no sockets,
no sleeps.
"""

import hashlib

import pytest

from lighthouse_tpu.network.gossipsub import (
    GossipsubBehaviour,
    GossipsubConfig,
    GraftFrame,
    IHaveFrame,
    IWantFrame,
    MessageCache,
    PeerScore,
    PeerScoreParams,
    PeerScoreThresholds,
    PruneFrame,
    PublishFrame,
    SubscriptionFrame,
    TopicScoreParams,
    decode_frame,
    encode_frame,
)

TOPIC = "/eth2/00000000/beacon_block/ssz_snappy"


def mid_of(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()[:20]


# ---------------------------------------------------------------------------
# mcache
# ---------------------------------------------------------------------------


def test_mcache_gossip_window_and_expiry():
    mc = MessageCache(history_length=3, gossip_window=2)
    mc.put(b"a" * 20, "t", b"da")
    mc.shift()
    mc.put(b"b" * 20, "t", b"db")
    mc.shift()
    mc.put(b"c" * 20, "t", b"dc")
    # gossip window (2 newest windows): c and b, not a
    assert set(mc.gossip_ids("t")) == {b"b" * 20, b"c" * 20}
    # but a is still answerable from full history
    assert mc.get(b"a" * 20) == ("t", b"da")
    mc.shift()  # a's window falls off history (3 windows kept)
    assert mc.get(b"a" * 20) is None
    assert mc.get(b"b" * 20) == ("t", b"db")


def test_mcache_topics_are_separate():
    mc = MessageCache()
    mc.put(b"a" * 20, "t1", b"x")
    mc.put(b"b" * 20, "t2", b"y")
    assert mc.gossip_ids("t1") == [b"a" * 20]
    assert mc.gossip_ids("t2") == [b"b" * 20]
    assert mc.topics_in_gossip_window() == {"t1", "t2"}


def test_mcache_retransmission_cap_is_per_requester():
    mc = MessageCache()
    mc.put(b"a" * 20, "t", b"x")
    for _ in range(3):
        assert mc.get_for_iwant(b"a" * 20, "p1", limit=3) == ("t", b"x")
    # anti-spam: after `limit` serves THIS requester is refused...
    assert mc.get_for_iwant(b"a" * 20, "p1", limit=3) is None
    # ...but an honest different requester still gets the message
    # (a global count would break its promise and penalize US)
    assert mc.get_for_iwant(b"a" * 20, "p2", limit=3) == ("t", b"x")
    assert mc.get(b"a" * 20) is not None  # plain get unaffected


# ---------------------------------------------------------------------------
# score engine
# ---------------------------------------------------------------------------


def _params(**topic_kw) -> PeerScoreParams:
    return PeerScoreParams(topics={"t": TopicScoreParams(**topic_kw)})


def test_score_p1_time_in_mesh_accrues_only_in_mesh():
    ps = PeerScore(_params(time_in_mesh_weight=0.5, time_in_mesh_cap=4))
    ps.add_peer("p")
    ps.graft("p", "t")
    for _ in range(3):
        ps.refresh()
    assert ps.score("p") == pytest.approx(0.5 * 3)
    for _ in range(10):
        ps.refresh()
    assert ps.score("p") == pytest.approx(0.5 * 4)  # capped
    ps.prune("p", "t")
    assert ps.score("p") == 0.0  # P1 stops counting outside the mesh


def test_score_p2_first_deliveries_accumulate_cap_and_decay():
    ps = PeerScore(
        _params(
            first_message_deliveries_weight=2.0,
            first_message_deliveries_cap=5.0,
            first_message_deliveries_decay=0.5,
        )
    )
    ps.add_peer("p")
    for _ in range(8):
        ps.first_delivery("p", "t")
    assert ps.score("p") == pytest.approx(2.0 * 5.0)  # capped at 5
    ps.refresh()
    assert ps.score("p") == pytest.approx(2.0 * 2.5)  # decayed
    for _ in range(12):
        ps.refresh()
    assert ps.score("p") == 0.0  # decay_to_zero snaps


def test_score_p3_mesh_delivery_deficit_squared_after_activation():
    ps = PeerScore(
        _params(
            time_in_mesh_weight=0.0,
            first_message_deliveries_weight=0.0,
            mesh_message_deliveries_weight=-1.0,
            mesh_message_deliveries_threshold=4.0,
            mesh_message_deliveries_activation=2,
            mesh_message_deliveries_decay=1.0,
        )
    )
    ps.add_peer("p")
    ps.graft("p", "t")
    assert ps.score("p") == 0.0  # not yet active
    ps.refresh()
    ps.refresh()  # mesh_time = 2 = activation
    assert ps.score("p") == pytest.approx(-16.0)  # (4-0)^2
    ps.first_delivery("p", "t")
    ps.first_delivery("p", "t")
    assert ps.score("p") == pytest.approx(-4.0)  # (4-2)^2
    ps.duplicate_delivery("p", "t")
    ps.duplicate_delivery("p", "t")
    assert ps.score("p") == 0.0  # quota met (duplicates count in-mesh)


def test_score_p4_invalid_messages_squared():
    ps = PeerScore(_params(invalid_message_deliveries_weight=-2.0))
    ps.add_peer("p")
    for i, expected in [(1, -2.0), (2, -8.0), (3, -18.0)]:
        ps.invalid_message("p", "t")
        assert ps.score("p") == pytest.approx(expected), i


def test_score_p7_behaviour_penalty_and_decay():
    ps = PeerScore(
        PeerScoreParams(behaviour_penalty_weight=-5.0, behaviour_penalty_decay=0.5)
    )
    ps.add_peer("p")
    ps.behaviour_penalty("p")
    ps.behaviour_penalty("p")
    assert ps.score("p") == pytest.approx(-20.0)  # -5 * 2^2
    ps.refresh()
    assert ps.score("p") == pytest.approx(-5.0)  # -5 * 1^2


def test_score_positive_topics_capped_negatives_not():
    params = PeerScoreParams(
        topics={
            "a": TopicScoreParams(
                topic_weight=1.0, first_message_deliveries_weight=10.0
            ),
            "b": TopicScoreParams(
                topic_weight=1.0, invalid_message_deliveries_weight=-10.0
            ),
        },
        topic_score_cap=25.0,
    )
    ps = PeerScore(params)
    ps.add_peer("p")
    for _ in range(10):
        ps.first_delivery("p", "a")  # +100 uncapped, 25 capped
    assert ps.score("p") == pytest.approx(25.0)
    ps.invalid_message("p", "b")  # -10, applied beyond the cap
    assert ps.score("p") == pytest.approx(15.0)


# ---------------------------------------------------------------------------
# behaviour harness
# ---------------------------------------------------------------------------


class Net:
    """Recording transport + always-valid (configurable) delivery."""

    def __init__(self, **cfg_kw):
        self.sent: list[tuple[str, object]] = []
        self.delivered: list[tuple[str, bytes, str]] = []
        self.valid = True
        cfg = GossipsubConfig(**cfg_kw)
        self.b = GossipsubBehaviour(
            send=lambda pid, raw: self.sent.append((pid, decode_frame(raw))),
            deliver=self._deliver,
            mid_fn=mid_of,
            px_provider=lambda topic, exclude: [
                (p, "10.0.0.1", 4000)
                for p in self.b.mesh.get(topic, ())
                if p != exclude
            ],
            thresholds=PeerScoreThresholds(
                gossip_threshold=-40,
                publish_threshold=-60,
                graylist_threshold=-80,
                accept_px_threshold=10,
            ),
            config=cfg,
            seed=1234,
        )

    def _deliver(self, topic, data, origin):
        self.delivered.append((topic, data, origin))
        return self.valid

    def add_subscribed_peer(self, pid, topic=TOPIC):
        self.b.add_peer(pid)
        self.b.handle_frame(
            pid, SubscriptionFrame(subscribe=True, topic=topic.encode())
        )

    def frames_to(self, pid, cls):
        return [f for p, f in self.sent if p == pid and isinstance(f, cls)]

    def clear(self):
        self.sent.clear()


def test_add_peer_announces_subscriptions():
    net = Net()
    net.b.subscribe(TOPIC)
    net.b.add_peer("p1")
    subs = net.frames_to("p1", SubscriptionFrame)
    assert [bytes(s.topic).decode() for s in subs] == [TOPIC]
    assert all(bool(s.subscribe) for s in subs)


def test_heartbeat_grafts_up_to_d():
    net = Net(d=3, d_lo=2, d_hi=6)
    net.b.subscribe(TOPIC)
    for i in range(8):
        net.add_subscribed_peer(f"p{i}")
    net.clear()
    net.b.heartbeat()
    grafted = {p for p, f in net.sent if isinstance(f, GraftFrame)}
    assert len(grafted) == 3
    assert net.b.mesh_peers(TOPIC) == grafted


def test_graft_refused_when_mesh_full():
    net = Net(d=2, d_lo=1, d_hi=3)
    net.b.subscribe(TOPIC)
    for i in range(3):
        net.add_subscribed_peer(f"p{i}")
        net.b.handle_frame(f"p{i}", GraftFrame(topic=TOPIC.encode()))
    assert len(net.b.mesh_peers(TOPIC)) == 3
    net.clear()
    net.add_subscribed_peer("p3")
    net.b.handle_frame("p3", GraftFrame(topic=TOPIC.encode()))
    assert "p3" not in net.b.mesh_peers(TOPIC)
    assert net.frames_to("p3", PruneFrame)  # refused: mesh at d_hi


def test_heartbeat_prunes_oversized_mesh_keeping_best_scores():
    net = Net(d=3, d_lo=2, d_hi=4, d_score=2)
    net.b.subscribe(TOPIC)
    for i in range(6):
        pid = f"p{i}"
        net.add_subscribed_peer(pid)
        # force everyone into the mesh directly (inbound GRAFTs would be
        # refused past d_hi — that refusal has its own test above)
        net.b.mesh[TOPIC].add(pid)
        net.b.score.graft(pid, TOPIC)
    assert len(net.b.mesh_peers(TOPIC)) == 6  # > d_hi
    # give p0/p1 the best scores: deliveries
    for _ in range(5):
        net.b.score.first_delivery("p0", TOPIC)
        net.b.score.first_delivery("p1", TOPIC)
    net.clear()
    net.b.heartbeat()
    mesh = net.b.mesh_peers(TOPIC)
    assert len(mesh) == 3  # back to D
    assert {"p0", "p1"} <= mesh  # d_score best retained deterministically
    pruned = {p for p, f in net.sent if isinstance(f, PruneFrame)}
    assert pruned == {f"p{i}" for i in range(6)} - mesh
    # pruned peers are under backoff: the next heartbeat must not re-graft
    net.clear()
    net.b.heartbeat()
    assert not any(isinstance(f, GraftFrame) for _, f in net.sent)


def test_prune_carries_backoff_and_px():
    net = Net(d=2, d_lo=1, d_hi=3, d_score=1, prune_backoff=7)
    net.b.subscribe(TOPIC)
    for i in range(5):
        pid = f"p{i}"
        net.add_subscribed_peer(pid)
        net.b.mesh[TOPIC].add(pid)
        net.b.score.graft(pid, TOPIC)
    net.clear()
    net.b.heartbeat()
    prunes = [f for _, f in net.sent if isinstance(f, PruneFrame)]
    assert prunes
    for pf in prunes:
        assert int(pf.backoff) == 7
        assert len(pf.px) >= 1  # peer exchange carried on mesh prunes


def test_graft_rejected_during_backoff_with_penalty():
    net = Net(prune_backoff=10)
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("p0")
    net.b.backoff[(TOPIC, "p0")] = net.b.ticks + 10
    net.clear()
    net.b.handle_frame("p0", GraftFrame(topic=TOPIC.encode()))
    assert net.frames_to("p0", PruneFrame)  # refused
    assert "p0" not in net.b.mesh_peers(TOPIC)
    assert net.b.peer_score("p0") < 0  # P7 backoff-violation penalty


def test_graft_from_negative_score_peer_refused():
    net = Net()
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("bad")
    net.b.score.invalid_message("bad", TOPIC)  # score < 0
    net.clear()
    net.b.handle_frame("bad", GraftFrame(topic=TOPIC.encode()))
    assert net.frames_to("bad", PruneFrame)
    assert "bad" not in net.b.mesh_peers(TOPIC)


def test_negative_score_mesh_member_pruned_on_heartbeat():
    net = Net(d=3, d_lo=2)
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("p0")
    net.b.handle_frame("p0", GraftFrame(topic=TOPIC.encode()))
    assert "p0" in net.b.mesh_peers(TOPIC)
    net.b.score.invalid_message("p0", TOPIC)
    net.clear()
    net.b.heartbeat()
    assert "p0" not in net.b.mesh_peers(TOPIC)
    assert net.frames_to("p0", PruneFrame)


def test_publish_floods_to_subscribed_above_publish_threshold():
    net = Net()
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("good")
    net.add_subscribed_peer("awful")
    net.add_subscribed_peer("other-topic")
    net.b.handle_frame(
        "other-topic", SubscriptionFrame(subscribe=False, topic=TOPIC.encode())
    )
    # push "awful" below the publish threshold (-60): 6 invalids at -2·n²
    for _ in range(6):
        net.b.score.invalid_message("awful", TOPIC)
    net.clear()
    net.b.publish(TOPIC, b"block-bytes")
    targets = {p for p, f in net.sent if isinstance(f, PublishFrame)}
    assert targets == {"good"}
    assert net.b.mcache.get(mid_of(b"block-bytes")) == (TOPIC, b"block-bytes")


def test_remote_publish_validates_forwards_and_scores():
    net = Net(d=2, d_lo=1)
    net.b.subscribe(TOPIC)
    for pid in ("origin", "m1", "m2"):
        net.add_subscribed_peer(pid)
        net.b.handle_frame(pid, GraftFrame(topic=TOPIC.encode()))
    net.clear()
    net.b.handle_frame(
        "origin", PublishFrame(topic=TOPIC.encode(), data=b"payload")
    )
    assert net.delivered == [(TOPIC, b"payload", "origin")]
    fwd = {p for p, f in net.sent if isinstance(f, PublishFrame)}
    assert fwd == {"m1", "m2"}  # mesh minus origin
    assert net.b.peer_score("origin") > 0  # P2 first delivery
    # duplicate: not re-delivered, not re-forwarded
    net.clear()
    net.b.handle_frame(
        "m1", PublishFrame(topic=TOPIC.encode(), data=b"payload")
    )
    assert len(net.delivered) == 1
    assert not net.sent


def test_invalid_remote_publish_not_forwarded_and_penalized():
    net = Net(d=2, d_lo=1)
    net.b.subscribe(TOPIC)
    for pid in ("origin", "m1"):
        net.add_subscribed_peer(pid)
        net.b.handle_frame(pid, GraftFrame(topic=TOPIC.encode()))
    net.valid = False
    net.clear()
    net.b.handle_frame(
        "origin", PublishFrame(topic=TOPIC.encode(), data=b"garbage")
    )
    assert not any(isinstance(f, PublishFrame) for _, f in net.sent)
    assert net.b.peer_score("origin") < 0


def test_graylisted_peer_is_ignored_entirely():
    net = Net()
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("evil")
    # drive below graylist (-80): 7 invalids → -2·49 = -98
    for _ in range(7):
        net.b.score.invalid_message("evil", TOPIC)
    assert net.b.peer_score("evil") < -80
    net.clear()
    before = len(net.delivered)
    net.b.handle_frame(
        "evil", PublishFrame(topic=TOPIC.encode(), data=b"whatever")
    )
    net.b.handle_frame("evil", GraftFrame(topic=TOPIC.encode()))
    assert len(net.delivered) == before  # never validated
    assert not net.sent  # not even a PRUNE back
    assert "evil" not in net.b.mesh_peers(TOPIC)


def test_heartbeat_emits_ihave_to_nonmesh_peers_only():
    net = Net(d=2, d_lo=1, d_lazy=5, gossip_window=3)
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("meshed")
    net.b.handle_frame("meshed", GraftFrame(topic=TOPIC.encode()))
    net.add_subscribed_peer("lazy1")
    net.add_subscribed_peer("lazy2")
    net.b.publish(TOPIC, b"m1")
    net.b.publish(TOPIC, b"m2")
    net.clear()
    # keep lazy peers out of the mesh for this heartbeat so gossip
    # targeting is observable
    net.b.mesh[TOPIC] = {"meshed"}
    net.b.config.d_lo = 0  # no grafting this round
    net.b.heartbeat()
    ihave_targets = {p for p, f in net.sent if isinstance(f, IHaveFrame)}
    assert ihave_targets == {"lazy1", "lazy2"}
    for _, f in net.sent:
        if isinstance(f, IHaveFrame):
            assert {bytes(m) for m in f.message_ids} == {
                mid_of(b"m1"),
                mid_of(b"m2"),
            }


def test_ihave_triggers_iwant_and_tracks_promise():
    net = Net()
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("p0")
    missing = mid_of(b"unseen")
    known = mid_of(b"seen")
    net.b.publish(TOPIC, b"seen")
    net.clear()
    net.b.handle_frame(
        "p0", IHaveFrame(topic=TOPIC.encode(), message_ids=[missing, known])
    )
    [iw] = net.frames_to("p0", IWantFrame)
    assert [bytes(m) for m in iw.message_ids] == [missing]  # only the unseen
    assert missing in net.b._promises
    # repeated IHAVE for an already-promised mid sends nothing new
    net.clear()
    net.b.handle_frame(
        "p0", IHaveFrame(topic=TOPIC.encode(), message_ids=[missing])
    )
    assert not net.frames_to("p0", IWantFrame)


def test_broken_iwant_promise_costs_behaviour_penalty():
    net = Net(iwant_promise_ticks=2)
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("flaky")
    net.b.handle_frame(
        "flaky",
        IHaveFrame(topic=TOPIC.encode(), message_ids=[mid_of(b"ghost")]),
    )
    assert net.b.peer_score("flaky") == 0.0
    net.b.heartbeat()
    net.b.heartbeat()  # promise deadline passes, message never arrived
    assert net.b.peer_score("flaky") < 0
    assert not net.b._promises


def test_kept_promise_is_not_penalized():
    net = Net(iwant_promise_ticks=2)
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("honest")
    net.b.handle_frame(
        "honest",
        IHaveFrame(topic=TOPIC.encode(), message_ids=[mid_of(b"late-msg")]),
    )
    net.b.handle_frame(
        "honest", PublishFrame(topic=TOPIC.encode(), data=b"late-msg")
    )
    net.b.heartbeat()
    net.b.heartbeat()
    assert net.b.peer_score("honest") > 0  # first delivery, no penalty


def test_iwant_served_from_mcache_with_retransmission_cap():
    net = Net(gossip_retransmission=2)
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("asker")
    net.b.publish(TOPIC, b"stored")
    mid = mid_of(b"stored")
    for i in range(2):
        net.clear()
        net.b.handle_frame("asker", IWantFrame(message_ids=[mid]))
        [pub] = net.frames_to("asker", PublishFrame)
        assert bytes(pub.data) == b"stored", i
    net.clear()
    net.b.handle_frame("asker", IWantFrame(message_ids=[mid]))
    assert not net.frames_to("asker", PublishFrame)  # cap reached


def test_prune_with_px_records_candidates_only_above_threshold():
    from lighthouse_tpu.network.gossipsub import PeerRecord

    net = Net()
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("pruner")
    frame = PruneFrame(
        topic=TOPIC.encode(),
        backoff=5,
        px=[PeerRecord(peer_id=b"cand", host=b"10.0.0.9", port=4000)],
    )
    # zero-score pruner is below accept_px_threshold (10): PX refused
    net.b.handle_frame("pruner", frame)
    assert net.b.take_px_candidates() == []
    # raise pruner above the threshold: 11 first-deliveries
    for _ in range(11):
        net.b.score.first_delivery("pruner", TOPIC)
    net.b.handle_frame("pruner", frame)
    assert net.b.take_px_candidates() == [("cand", "10.0.0.9", 4000)]
    # backoff recorded against the pruner
    assert net.b.backoff[(TOPIC, "pruner")] > net.b.ticks


def test_unsubscribe_prunes_mesh_and_announces():
    net = Net()
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("p0")
    net.b.handle_frame("p0", GraftFrame(topic=TOPIC.encode()))
    net.clear()
    net.b.unsubscribe(TOPIC)
    assert net.frames_to("p0", PruneFrame)
    subs = net.frames_to("p0", SubscriptionFrame)
    assert subs and not bool(subs[-1].subscribe)
    assert TOPIC not in net.b.subscriptions


def test_opportunistic_graft_when_mesh_median_sags():
    net = Net(d=3, d_lo=2, d_hi=6, opportunistic_graft_ticks=1)
    net.b.subscribe(TOPIC)
    for pid in ("sad1", "sad2"):
        net.add_subscribed_peer(pid)
        net.b.handle_frame(pid, GraftFrame(topic=TOPIC.encode()))
        # slightly negative-adjacent: low but valid (0 score would block
        # nothing; use delivered-then-decayed peers instead)
    # two fresh peers with strong scores, outside the mesh
    for pid in ("star1", "star2"):
        net.add_subscribed_peer(pid)
        for _ in range(5):
            net.b.score.first_delivery(pid, TOPIC)
    net.clear()
    net.b.heartbeat()
    mesh = net.b.mesh_peers(TOPIC)
    # mesh median (0.x from the sad pair) < opportunistic threshold (1.0)
    # → at least one star grafted on top of normal fill
    assert mesh & {"star1", "star2"}


def test_graft_now_fills_mesh_immediately():
    net = Net(d=2)
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("p0")
    net.add_subscribed_peer("p1")
    net.clear()
    net.b.graft_now(TOPIC)
    assert len(net.b.mesh_peers(TOPIC)) == 2
    assert len([f for _, f in net.sent if isinstance(f, GraftFrame)]) == 2


def test_frame_encode_decode_symmetry_through_wire():
    # behaviour output is decodable by a second behaviour (wire sanity)
    net_a, net_b = Net(), Net()
    net_a.b.subscribe(TOPIC)
    net_b.b.subscribe(TOPIC)
    raw_frames: list[bytes] = []
    net_a.b._send = lambda pid, raw: raw_frames.append(raw)
    net_a.b.add_peer("b")
    net_a.b.handle_frame(
        "b", SubscriptionFrame(subscribe=True, topic=TOPIC.encode())
    )
    net_a.b.publish(TOPIC, b"cross")
    net_b.b.add_peer("a")
    for raw in raw_frames:
        net_b.b.handle_frame("a", decode_frame(raw))
    assert net_b.b.peer_topics["a"] == {TOPIC}
    assert net_b.b.seen(mid_of(b"cross"))


def test_publish_on_unsubscribed_topic_dropped_without_credit():
    net = Net()
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("spammer")
    net.clear()
    net.b.handle_frame(
        "spammer", PublishFrame(topic=b"/junk/topic", data=b"x" * 1000)
    )
    assert net.delivered == []  # never validated
    assert not net.sent  # never forwarded
    assert net.b.peer_score("spammer") == 0.0  # no P2 farming
    assert net.b.mcache.get(mid_of(b"x" * 1000)) is None  # never cached


def test_remote_prune_backoff_clamped_and_cleared_on_disconnect():
    net = Net(prune_backoff=10)  # clamp = 10 * max_backoff_factor(4) = 40
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("p0")
    net.b.handle_frame(
        "p0", PruneFrame(topic=TOPIC.encode(), backoff=2**60, px=[])
    )
    assert net.b.backoff[(TOPIC, "p0")] <= net.b.ticks + 40  # not permanent
    net.b.remove_peer("p0")
    assert (TOPIC, "p0") not in net.b.backoff  # no leak for cheap peer ids


def test_duplicate_graft_does_not_reset_mesh_time():
    net = Net()
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("p0")
    net.b.handle_frame("p0", GraftFrame(topic=TOPIC.encode()))
    for _ in range(3):
        net.b.score.refresh()  # mesh_time = 3
    before = net.b.peer_score("p0")
    assert before > 0  # P1 accrued
    net.b.handle_frame("p0", GraftFrame(topic=TOPIC.encode()))  # duplicate
    assert net.b.peer_score("p0") == before  # clock NOT reset
    assert "p0" in net.b.mesh_peers(TOPIC)


def test_ihave_budget_per_peer_per_heartbeat():
    net = Net(max_ihave_messages=2)
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("p0")
    for i in range(4):
        net.b.handle_frame(
            "p0",
            IHaveFrame(
                topic=TOPIC.encode(), message_ids=[mid_of(b"m%d" % i)]
            ),
        )
    # only the first 2 frames in this heartbeat elicited IWANTs
    assert len(net.frames_to("p0", IWantFrame)) == 2
    assert len(net.b._promises) == 2
    net.clear()
    net.b.heartbeat()  # budget resets
    net.b.handle_frame(
        "p0", IHaveFrame(topic=TOPIC.encode(), message_ids=[mid_of(b"m9")])
    )
    assert len(net.frames_to("p0", IWantFrame)) == 1


def test_junk_topic_frames_create_no_per_peer_state():
    net = Net()
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("spammer")
    topics_before = set(net.b.peer_topics["spammer"])
    net.clear()
    net.b.handle_frame("spammer", GraftFrame(topic=b"/junk/t1"))
    net.b.handle_frame(
        "spammer", PruneFrame(topic=b"/junk/t2", backoff=5, px=[])
    )
    # GRAFT on an unknown topic is refused with a PRUNE, and neither
    # frame grew peer_topics / backoff / score stats
    assert net.frames_to("spammer", PruneFrame)
    assert net.b.peer_topics["spammer"] == topics_before
    assert not any(k[0].startswith("/junk/") for k in net.b.backoff)
    assert net.b.peer_score("spammer") == 0.0


def test_peer_topics_capped_against_subscription_floods():
    net = Net()
    net.b.add_peer("spammer")
    for i in range(net.b.MAX_PEER_TOPICS + 100):
        net.b.handle_frame(
            "spammer",
            SubscriptionFrame(subscribe=True, topic=b"/junk/%d" % i),
        )
    assert len(net.b.peer_topics["spammer"]) == net.b.MAX_PEER_TOPICS


def test_frames_racing_disconnect_leave_no_ghost_state():
    net = Net()
    net.b.subscribe(TOPIC)
    net.add_subscribed_peer("gone")
    net.b.remove_peer("gone")
    net.b.handle_frame(
        "gone", SubscriptionFrame(subscribe=True, topic=TOPIC.encode())
    )
    net.b.handle_frame("gone", GraftFrame(topic=TOPIC.encode()))
    net.b.handle_frame(
        "gone", PruneFrame(topic=TOPIC.encode(), backoff=5, px=[])
    )
    assert "gone" not in net.b.peer_topics
    assert "gone" not in net.b.mesh_peers(TOPIC)
    assert not net.b.score.known("gone")
    assert not any(k[1] == "gone" for k in net.b.backoff)
