"""beacon-san linter: per-rule firing/non-firing fixtures, suppression
syntax, and the tier-1 whole-package cleanliness gate.

Every rule family ships one known-bad snippet that MUST fire and one
known-good snippet that MUST NOT (the linter's own regression suite),
and `test_package_is_lint_clean` runs the linter over the entire
`lighthouse_tpu/` package — a new unsuppressed violation anywhere in the
tree fails tier-1, which is what makes the invariants enforced rather
than documented."""

from pathlib import Path

import pytest

from lighthouse_tpu.analysis import lint_paths, lint_source, main
from lighthouse_tpu.analysis.lint import RULES

PKG = Path(__file__).resolve().parent.parent / "lighthouse_tpu"

# a synthetic path inside state_processing/ (the safe-arith rule's scope)
SP = "lighthouse_tpu/state_processing/_fixture.py"
# a synthetic path outside it
OUT = "lighthouse_tpu/network/_fixture.py"


def _rules(violations):
    return [v.rule for v in violations]


# ---------------------------------------------------------------------------
# safe-arith
# ---------------------------------------------------------------------------


def test_safe_arith_fires_on_raw_state_arithmetic():
    bad = (
        "def f(state, index, E):\n"
        "    return state.validators[index].effective_balance // E.INC\n"
    )
    assert _rules(lint_source(bad, SP)) == ["safe-arith"]


def test_safe_arith_fires_through_name_taint():
    bad = (
        "def f(state, i, fee):\n"
        "    balance = state.balances[i]\n"
        "    return balance - fee\n"
    )
    assert _rules(lint_source(bad, SP)) == ["safe-arith"]


def test_safe_arith_fires_on_augassign_and_load_products():
    bad = (
        "def f(state, arrays, rewards):\n"
        "    balances = arrays.load_balances(state)\n"
        "    balances += rewards\n"
        "    return balances\n"
    )
    assert _rules(lint_source(bad, SP)) == ["safe-arith"]


def test_safe_arith_clean_when_routed_through_helpers():
    good = (
        "from lighthouse_tpu.utils.safe_arith import safe_div, safe_sub\n"
        "def f(state, index, E, fee):\n"
        "    inc = safe_div(state.validators[index].effective_balance, E.INC)\n"
        "    balance = state.balances[index]\n"
        "    return safe_sub(balance, fee) + inc\n"
    )
    assert lint_source(good, SP) == []


def test_safe_arith_scoped_to_state_processing():
    outside = (
        "def f(state, index, E):\n"
        "    return state.validators[index].effective_balance // E.INC\n"
    )
    assert lint_source(outside, OUT) == []


# a synthetic path inside fork_choice/ — in the safe-arith scope since the
# columnar proto-array (PR 12: weight/balance columns are u64 quantities)
FC = "lighthouse_tpu/fork_choice/_fixture.py"


def test_safe_arith_fires_on_fork_choice_weight_columns():
    bad = (
        "def f(self, i, delta):\n"
        "    self._weights[i] = self._weights[i] + delta\n"
    )
    assert _rules(lint_source(bad, FC)) == ["safe-arith"]


def test_safe_arith_fires_on_fork_choice_balance_taint():
    bad = (
        "def f(self, vi, boost):\n"
        "    old = self._balances[vi]\n"
        "    return old * boost\n"
    )
    assert _rules(lint_source(bad, FC)) == ["safe-arith"]


def test_safe_arith_clean_fork_choice_routed_through_vector_helpers():
    good = (
        "from lighthouse_tpu.utils.safe_arith import add_u64, sub_u64\n"
        "def f(self, n, pos, neg):\n"
        "    total = add_u64(self._weights[:n], pos)\n"
        "    self._weights[:n] = sub_u64(total, neg)\n"
    )
    assert lint_source(good, FC) == []


# a synthetic path inside slasher/ — in the safe-arith scope since the
# columnar span subsystem (PR 13: span distances are clamped uint16
# lanes, epoch windows are uint arithmetic)
SL = "lighthouse_tpu/slasher/_fixture.py"


def test_safe_arith_fires_on_slasher_span_gathers():
    bad = (
        "def f(self, spans, idx, epoch):\n"
        "    mins = spans.gather_min(idx, epoch)\n"
        "    return mins - 1\n"
    )
    assert _rules(lint_source(bad, SL)) == ["safe-arith"]


def test_safe_arith_slasher_clean_when_comparing_only():
    good = (
        "def f(self, spans, idx, epoch, d):\n"
        "    mins = spans.gather_min(idx, epoch)\n"
        "    maxs = spans.gather_max(idx, epoch)\n"
        "    return (mins < d) | (maxs > d)\n"
    )
    assert lint_source(good, SL) == []


def test_safe_arith_span_gathers_scoped_to_slasher():
    outside = (
        "def f(self, spans, idx, epoch):\n"
        "    return spans.gather_min(idx, epoch) - 1\n"
    )
    assert lint_source(outside, OUT) == []


# a synthetic path inside das/ — in the safe-arith scope since the
# PeerDAS subsystem (PR 16: sidecar indices and column/point derivations
# are uint64 lanes; the bigint-mod-p FR math stays out of the vocab)
DAS = "lighthouse_tpu/das/_fixture.py"


def test_safe_arith_fires_on_das_sidecar_index_arithmetic():
    bad = (
        "def f(sidecar, fe):\n"
        "    return sidecar.index * fe\n"
    )
    assert _rules(lint_source(bad, DAS)) == ["safe-arith"]


def test_safe_arith_fires_on_das_point_index_taint():
    bad = (
        "def f(commitment, j, cell):\n"
        "    k = cell_point_index(commitment, j, cell)\n"
        "    return k * 32\n"
    )
    assert _rules(lint_source(bad, DAS)) == ["safe-arith"]


def test_safe_arith_das_clean_when_routed_through_helpers():
    good = (
        "from lighthouse_tpu.utils.safe_arith import safe_add, safe_mul\n"
        "def f(sidecar, fe, k):\n"
        "    return safe_add(safe_mul(int(sidecar.index), fe), k)\n"
    )
    assert lint_source(good, DAS) == []


def test_safe_arith_das_index_vocab_scoped_to_das():
    # `.index` is far too generic to taint globally (list.index results,
    # registry positions, ...) — the vocab binds to das/ paths only
    outside = (
        "def f(sidecar, fe):\n"
        "    return sidecar.index * fe\n"
    )
    assert lint_source(outside, OUT) == []
    assert lint_source(outside, SP) == []


# a synthetic path matching beacon_chain/state_advance.py — in the
# safe-arith scope since the proposer pipeline (PR 17: the pre-advance
# drives per_slot_processing over the same uint64 state quantities the
# epoch sweeps mutate). The scope binds to the FILE, not beacon_chain/.
SA = "lighthouse_tpu/beacon_chain/state_advance_fixture.py"
BC = "lighthouse_tpu/beacon_chain/_fixture.py"


def test_safe_arith_fires_in_state_advance():
    bad = (
        "def f(state, index, fee):\n"
        "    balance = state.balances[index]\n"
        "    return balance - fee\n"
    )
    assert _rules(lint_source(bad, SA)) == ["safe-arith"]


def test_safe_arith_state_advance_clean_through_helpers():
    good = (
        "from lighthouse_tpu.utils.safe_arith import safe_sub\n"
        "def f(state, index, fee):\n"
        "    balance = state.balances[index]\n"
        "    return safe_sub(balance, fee)\n"
    )
    assert lint_source(good, SA) == []


def test_metric_hygiene_fires_in_state_advance():
    # metric-hygiene is package-wide, so the new module is covered like
    # any other — the real file's loop-registered span names carry an
    # allow at the registration site; a dynamic name here must fire
    bad = (
        "from lighthouse_tpu.metrics import inc_counter\n"
        "def f(stage):\n"
        "    inc_counter(f'state_advance_{stage}_total')\n"
    )
    assert _rules(lint_source(bad, SA)) == ["metric-hygiene"]


def test_safe_arith_scope_is_state_advance_not_beacon_chain():
    # chain.py and friends stay out of scope — only the advance module
    # (which runs the slot/epoch transitions) carries the rule
    outside = (
        "def f(state, index, fee):\n"
        "    balance = state.balances[index]\n"
        "    return balance - fee\n"
    )
    assert lint_source(outside, BC) == []
    assert lint_source(outside, OUT) == []


# a synthetic path inside validator_client/ — in the safe-arith scope
# since the batched duty pipeline (PR 19: duty slots, checkpoint epochs,
# and slashing-protection watermark epochs are uint64 wire quantities,
# with an epoch/slot vocabulary scoped to the VC)
VC = "lighthouse_tpu/validator_client/_fixture.py"


def test_safe_arith_fires_on_vc_duty_slot_arithmetic():
    bad = (
        "def f(duty, lookahead):\n"
        "    return duty.slot + lookahead\n"
    )
    assert _rules(lint_source(bad, VC)) == ["safe-arith"]


def test_safe_arith_fires_on_vc_epoch_producer_taint():
    bad = (
        "def f(slot, E):\n"
        "    start = compute_start_slot_at_epoch(slot, E)\n"
        "    return start + E.SLOTS_PER_EPOCH\n"
    )
    assert _rules(lint_source(bad, VC)) == ["safe-arith"]


def test_safe_arith_fires_on_vc_watermark_epochs():
    bad = (
        "def f(entry, prev):\n"
        "    return entry.target_epoch - prev.source_epoch\n"
    )
    assert _rules(lint_source(bad, VC)) == ["safe-arith"]


def test_safe_arith_vc_clean_when_routed_through_helpers():
    good = (
        "from lighthouse_tpu.utils.safe_arith import safe_add\n"
        "def f(slot, E):\n"
        "    start = compute_start_slot_at_epoch(slot, E)\n"
        "    return safe_add(start, E.SLOTS_PER_EPOCH)\n"
    )
    assert lint_source(good, VC) == []


def test_safe_arith_vc_slot_vocab_scoped_to_validator_client():
    # `.slot` / `.epoch` are far too generic to taint globally (every
    # SSZ container carries a slot) — the vocab binds to the VC only
    outside = (
        "def f(duty, lookahead):\n"
        "    return duty.slot + lookahead\n"
    )
    assert lint_source(outside, OUT) == []
    assert lint_source(outside, BC) == []


# a synthetic path inside store/ — in the safe-arith scope since the
# lifecycle subsystem (PR 20: the migration cycle's finalized-boundary
# and DA-cutoff slot math is uint64 arithmetic; the reference uses
# saturating subtraction exactly where a raw `-` would underflow)
ST = "lighthouse_tpu/store/_fixture.py"


def test_safe_arith_fires_on_store_da_cutoff_arithmetic():
    bad = (
        "def f(chain, epoch, E):\n"
        "    finalized_slot = compute_start_slot_at_epoch(epoch, E)\n"
        "    return finalized_slot - chain.da_window_slots()\n"
    )
    assert _rules(lint_source(bad, ST)) == ["safe-arith"]


def test_safe_arith_fires_on_store_window_producer_taint():
    bad = (
        "def f(chain, finalized_slot):\n"
        "    window = chain.da_window_slots()\n"
        "    return finalized_slot + window\n"
    )
    assert _rules(lint_source(bad, ST)) == ["safe-arith"]


def test_safe_arith_store_clean_when_routed_through_helpers():
    # the migrator's actual shape: the cutoff rides saturating_sub and
    # restore-point spacing is a modulo (never flagged)
    good = (
        "from lighthouse_tpu.utils.safe_arith import saturating_sub\n"
        "def f(chain, epoch, E, spacing):\n"
        "    finalized_slot = compute_start_slot_at_epoch(epoch, E)\n"
        "    cutoff = saturating_sub(finalized_slot, chain.da_window_slots())\n"
        "    return cutoff % spacing\n"
    )
    assert lint_source(good, ST) == []


def test_safe_arith_store_vocab_scoped_to_store():
    # `da_window_slots` taints inside store/ only; the same snippet is
    # clean at an out-of-scope path (compute_start_slot_at_epoch stays
    # VC/store-scoped too — http_api callers do presentation math on it)
    outside = (
        "def f(chain, finalized_slot):\n"
        "    return finalized_slot - chain.da_window_slots()\n"
    )
    assert lint_source(outside, OUT) == []


def test_safe_arith_store_epoch_claim_bookkeeping_stays_clean():
    # the migrator's atomic epoch claim decrements a plain Python int on
    # unclaim — deliberately OUT of the vocab (`.epoch` attrs untainted
    # in store/), so the claim/unclaim pattern lints clean
    good = (
        "def unclaim(self, epoch):\n"
        "    if self._last_migrated_epoch == epoch:\n"
        "        self._last_migrated_epoch = epoch - 1\n"
    )
    assert lint_source(good, ST) == []


def test_fork_safety_fires_on_das_shaped_worker():
    # das/proofs.py keeps its pool workers (_msm_shard/_prove_shard)
    # metrics-free for exactly this rule: counters are parent-side only
    bad = (
        "from lighthouse_tpu.metrics import inc_counter\n"
        "def _msm_shard(task):\n"
        "    inc_counter('das_cells_verified_total', 1.0)\n"
        "    return task\n"
        "def run(pool, tasks):\n"
        "    return pool.map(_msm_shard, tasks)\n"
    )
    assert "fork-safety" in _rules(lint_source(bad, DAS))


def test_queue_discipline_fires_on_column_sidecar_processing():
    # process_data_column_sidecars joined the state-transition vocabulary:
    # column gossip must ride a beacon_processor lane, not the reader
    bad = (
        "class Svc:\n"
        "    def __init__(self):\n"
        "        self.gossip.subscribe(self.topic_col, self._on_column)\n"
        "    def _on_column(self, data):\n"
        "        sc = self.decode_column(data)\n"
        "        self.chain.process_data_column_sidecars(self.root, [sc])\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["queue-discipline"]


def test_metric_hygiene_fires_on_dynamic_das_series():
    bad = (
        "from lighthouse_tpu.metrics import inc_counter\n"
        "def f(subnet):\n"
        "    inc_counter(f'das_column_subnet_{subnet}_total', 1.0)\n"
    )
    assert _rules(lint_source(bad, DAS)) == ["metric-hygiene"]


def test_cow_aliasing_fires_on_attesting_index_view_write_in_fork_choice():
    # the batch entry reads attesting_indices.load_array() — a frozen
    # CoW view; writing it must fire regardless of the module's path
    bad = (
        "def f(indexed):\n"
        "    v = indexed.attesting_indices.load_array()\n"
        "    v[0] = 7\n"
    )
    assert _rules(lint_source(bad, FC)) == ["cow-aliasing"]


# ---------------------------------------------------------------------------
# cow-aliasing
# ---------------------------------------------------------------------------


def test_cow_aliasing_fires_on_load_array_write():
    bad = (
        "def f(lst, idx):\n"
        "    arr = lst.load_array()\n"
        "    arr[idx] = 0\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["cow-aliasing"]


def test_cow_aliasing_fires_on_committee_slice_and_column_views():
    bad = (
        "def f(cc, cols, slot, index):\n"
        "    committee = cc.committee_array(slot, index)\n"
        "    committee[0] = 7\n"
        "    cols.effective_balance[3] = 1\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["cow-aliasing", "cow-aliasing"]


def test_cow_aliasing_fires_on_setflags_reenable():
    bad = (
        "def f(lst):\n"
        "    arr = lst.load_array()\n"
        "    arr.setflags(write=True)\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["cow-aliasing"]


def test_cow_aliasing_fires_on_self_attr_views_across_methods():
    bad = (
        "class T:\n"
        "    def __init__(self, lst):\n"
        "        self.read = lst.load_array()\n"
        "    def commit(self, i, v):\n"
        "        self.read[i] = v\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["cow-aliasing"]


def test_cow_aliasing_clean_when_copied_first():
    good = (
        "def f(lst, idx):\n"
        "    arr = lst.load_array().copy()\n"
        "    arr[idx] = 0\n"
        "    lst.store_array(arr)\n"
    )
    assert lint_source(good, OUT) == []


# ---------------------------------------------------------------------------
# fork-safety
# ---------------------------------------------------------------------------


def test_fork_safety_fires_on_metrics_in_worker():
    bad = (
        "from ..metrics import inc_counter\n"
        "def _worker(task):\n"
        "    inc_counter('tasks')\n"
        "    return task * 2\n"
        "def run(pool, tasks):\n"
        "    return pool.map(_worker, tasks)\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["fork-safety"]


def test_fork_safety_fires_through_same_module_callees():
    bad = (
        "import logging\n"
        "def _inner(x):\n"
        "    logging.info(x)\n"
        "    return x\n"
        "def _worker(task):\n"
        "    return _inner(task)\n"
        "def run(pool, tasks):\n"
        "    return pool.map(_worker, tasks)\n"
    )
    assert "fork-safety" in _rules(lint_source(bad, OUT))


def test_fork_safety_fires_on_lambda():
    bad = (
        "def run(pool, tasks):\n"
        "    return pool.map(lambda t: t, tasks)\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["fork-safety"]


def test_fork_safety_fires_on_serving_worker_entry():
    """PR 18: `spawn_serving_worker(entry, ctx)` forks exactly like a
    pool submit — the entry function is held to the same lock-free bar."""
    bad = (
        "from ..metrics import inc_counter\n"
        "from .workers import spawn_serving_worker\n"
        "def _entry(ctx):\n"
        "    inc_counter('serves')\n"
        "    return 0\n"
        "def boot(ctx):\n"
        "    return spawn_serving_worker(_entry, ctx)\n"
    )
    v = lint_source(bad, OUT)
    assert _rules(v) == ["fork-safety"]
    assert "inc_counter" in v[0].message


def test_fork_safety_fires_on_serving_worker_entry_via_callee():
    bad = (
        "import logging\n"
        "def _inner(ctx):\n"
        "    logging.info('serving %s', ctx)\n"
        "def _entry(ctx):\n"
        "    return _inner(ctx)\n"
        "def boot(workers, ctx):\n"
        "    return workers.spawn_serving_worker(_entry, ctx)\n"
    )
    assert "fork-safety" in _rules(lint_source(bad, OUT))


def test_fork_safety_fires_on_serving_worker_lambda_entry():
    bad = (
        "def boot(ctx):\n"
        "    return spawn_serving_worker(lambda c: c.run(), ctx)\n"
    )
    v = lint_source(bad, OUT)
    assert _rules(v) == ["fork-safety"]
    assert "serving-worker fork entry" in v[0].message


def test_fork_safety_clean_serving_worker_delegate_entry():
    """The sanctioned shape (workers._serving_worker_main): the entry
    re-initializes then delegates into a runtime object — nothing the
    scanner flags runs before the child has replaced inherited state."""
    good = (
        "from .workers import spawn_serving_worker\n"
        "class _Runtime:\n"
        "    def __init__(self, ctx):\n"
        "        self.ctx = ctx\n"
        "    def run(self):\n"
        "        return 0\n"
        "def _entry(ctx):\n"
        "    return _Runtime(ctx).run()\n"
        "def boot(ctx):\n"
        "    return spawn_serving_worker(_entry, ctx)\n"
    )
    assert lint_source(good, OUT) == []


def test_fork_safety_resolves_workers_across_one_import_hop(tmp_path):
    """`pool.map(worker, ...)` where `worker` is imported from a sibling
    module: the linter must follow the ImportFrom and scan the worker in
    its home module (how crypto/bls submits pairing.miller_product)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "workers.py").write_text(
        "from .metrics import inc_counter\n"
        "def tally_chunk(chunk):\n"
        "    inc_counter('chunks')\n"
        "    return chunk\n"
    )
    (pkg / "metrics.py").write_text("def inc_counter(name): pass\n")
    caller = pkg / "caller.py"
    caller.write_text(
        "from .workers import tally_chunk\n"
        "def run(pool, tasks):\n"
        "    return pool.map(tally_chunk, tasks)\n"
    )
    violations = lint_paths([caller])
    assert _rules(violations) == ["fork-safety"]
    assert "inc_counter" in violations[0].message


def test_fork_safety_resolves_absolute_imports(tmp_path):
    """`from pkg.workers import f` (level=0): the resolver must ascend
    to the directory holding the top-level package instead of joining
    the full dotted path onto the caller's own directory (which yields
    a nonexistent pkg/sub/pkg/... path and silently skips the scan)."""
    pkg = tmp_path / "pkg"
    sub = pkg / "sub"
    sub.mkdir(parents=True)
    for d in (pkg, sub):
        (d / "__init__.py").write_text("")
    (pkg / "workers.py").write_text(
        "import logging\n"
        "def tally(chunk):\n"
        "    logging.info(chunk)\n"
        "    return chunk\n"
    )
    caller = sub / "caller.py"
    caller.write_text(
        "from pkg.workers import tally\n"
        "def run(pool, tasks):\n"
        "    return pool.map(tally, tasks)\n"
    )
    violations = lint_paths([caller])
    assert _rules(violations) == ["fork-safety"]
    assert "logging" in violations[0].message


def test_fork_safety_clean_for_pure_worker():
    good = (
        "def _worker(task):\n"
        "    return sum(task) * 2\n"
        "def run(pool, tasks):\n"
        "    return pool.map(_worker, tasks)\n"
    )
    assert lint_source(good, OUT) == []


def test_fork_safety_audit_of_real_pool_workers():
    """The PR 8 satellite audit, pinned as a test: every callable the BLS
    batch verifier submits to the fork pool (_prep_chunk, _hash_g2_chunk,
    _msm_chunk, pairing.miller_product via the import hop) must stay
    lock-free — no metrics, logging, spans, jax, or locks anywhere in
    their same-module call graphs."""
    targets = [
        PKG / "crypto" / "bls" / "__init__.py",
        PKG / "parallel" / "host_pool.py",
    ]
    violations = [v for v in lint_paths(targets) if v.rule == "fork-safety"]
    assert violations == [], "\n".join(v.render() for v in violations)


# ---------------------------------------------------------------------------
# dirty-channel
# ---------------------------------------------------------------------------


def test_dirty_channel_fires_on_inline_literal():
    bad = "def f(lst):\n    return lst.drain_dirty('columns')\n"
    assert _rules(lint_source(bad, OUT)) == ["dirty-channel"]


def test_dirty_channel_fires_on_unregistered_constant():
    bad = (
        "CH = 'columns'\n"
        "def f(lst):\n"
        "    return lst.drain_dirty(CH)\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["dirty-channel"]


def test_dirty_channel_clean_when_registered():
    good = (
        "CH = 'columns'\n"
        "def f(lst):\n"
        "    base, dirty = lst.drain_dirty(CH)\n"
        "    token = lst.dirt_token_for(CH)\n"
        "    return base, dirty, token\n"
    )
    assert lint_source(good, OUT) == []


def test_dirty_channel_fires_on_handle_write_after_drain():
    bad = (
        "def f(state, lst, i, E):\n"
        "    v = lst.mutate(i)\n"
        "    total = get_total_active_balance(state, E)\n"
        "    v.exit_epoch = total\n"
    )
    assert _rules(lint_source(bad, SP)) == ["dirty-channel"]


def test_dirty_channel_clean_when_reads_precede_handle():
    good = (
        "def f(state, lst, i, E):\n"
        "    total = get_total_active_balance(state, E)\n"
        "    v = lst.mutate(i)\n"
        "    v.exit_epoch = total\n"
    )
    assert lint_source(good, SP) == []


# ---------------------------------------------------------------------------
# metric-hygiene
# ---------------------------------------------------------------------------


def test_metric_hygiene_fires_on_fstring_name():
    bad = (
        "from lighthouse_tpu.metrics import inc_counter\n"
        "def f(kind):\n"
        "    inc_counter(f'work_done_{kind}_total')\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["metric-hygiene"]


def test_metric_hygiene_fires_on_dynamic_registry_and_span_names():
    bad = (
        "from lighthouse_tpu.metrics import REGISTRY\n"
        "from lighthouse_tpu.utils.tracing import span\n"
        "def f(name, peer):\n"
        "    REGISTRY.histogram(name).observe(1.0)\n"
        "    with span('rpc_' + peer):\n"
        "        pass\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["metric-hygiene"] * 2


def test_metric_hygiene_clean_for_literals_and_module_constants():
    good = (
        "from lighthouse_tpu.metrics import REGISTRY, inc_counter, observe\n"
        "from lighthouse_tpu.utils.tracing import span\n"
        "IMPORT_SPAN = 'block_import'\n"
        "def f(hist, cache, epoch, index):\n"
        "    inc_counter('beacon_blocks_imported_total')\n"
        "    observe('beacon_block_observed_to_imported_seconds', 0.1)\n"
        "    REGISTRY.histogram('trace_span_seconds_block_import')\n"
        "    with span(IMPORT_SPAN):\n"
        "        pass\n"
        "    hist.observe(1.0)\n"  # method named observe: not a metric call
        "    cache.observe(epoch, index)\n"  # ObservedCache.observe likewise
    )
    assert lint_source(good, OUT) == []


def test_metric_hygiene_suppressible_like_any_rule():
    src = (
        "from lighthouse_tpu.metrics import REGISTRY\n"
        "KINDS = ('a', 'b')\n"
        "for k in KINDS:\n"
        "    REGISTRY.counter(\n"
        "        # lint: allow(metric-hygiene) -- bounded by KINDS\n"
        "        f'work_{k}_total',\n"
        "    )\n"
    )
    assert lint_source(src, OUT) == []


# ---------------------------------------------------------------------------
# queue-discipline
# ---------------------------------------------------------------------------


def test_queue_discipline_fires_on_inline_chain_processing():
    bad = (
        "class Svc:\n"
        "    def __init__(self):\n"
        "        self.gossip.subscribe(self.topic_block, self._on_block)\n"
        "    def _on_block(self, data):\n"
        "        signed = self.decode_block(data)\n"
        "        self.chain.process_block(signed)\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["queue-discipline"]


def test_queue_discipline_fires_through_one_callee_hop():
    bad = (
        "class Svc:\n"
        "    def __init__(self):\n"
        "        self.gossip.subscribe(self.topic_att, self._on_att)\n"
        "    def _on_att(self, data):\n"
        "        self._apply(data)\n"
        "    def _apply(self, data):\n"
        "        self.chain.process_attestation_batch([data])\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["queue-discipline"]


def test_queue_discipline_fires_on_chain_touching_decode_step():
    bad = (
        "class Svc:\n"
        "    def __init__(self, wt):\n"
        "        self.gossip.subscribe_queued(\n"
        "            self.topic_block, wt, self._decode, self._process\n"
        "        )\n"
        "    def _decode(self, data):\n"
        "        return self.chain.process_block(data)\n"
        "    def _process(self, item):\n"
        "        pass\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["queue-discipline"]


def test_queue_discipline_follows_local_aliases():
    """Registering through a local alias (`decode = self._decode`) must
    not dodge the scan — review found the package's own attestation
    decode briefly registered exactly this way."""
    bad = (
        "class Svc:\n"
        "    def __init__(self, wt):\n"
        "        decode = self._decode\n"
        "        for topic in self.topics:\n"
        "            self.gossip.subscribe_queued(topic, wt, decode)\n"
        "    def _decode(self, data):\n"
        "        return self.chain.process_attestation_batch([data])\n"
    )
    assert _rules(lint_source(bad, OUT)) == ["queue-discipline"]


def test_queue_discipline_clean_when_routed_through_submit():
    good = (
        "class Svc:\n"
        "    def __init__(self, wt):\n"
        "        self.gossip.subscribe_queued(\n"
        "            self.topic_block, wt, self._decode, self._process\n"
        "        )\n"
        "    def _decode(self, data):\n"
        "        return self.chain.types.decode_by_fork('SignedBeaconBlock', data)\n"
        "    def _process(self, signed):\n"
        "        # the queued process step MAY touch the chain: it runs on\n"
        "        # a beacon_processor worker, not the reader thread\n"
        "        self.chain.process_block(signed)\n"
    )
    assert lint_source(good, OUT) == []


def test_queue_discipline_ignores_non_gossip_subscribe():
    good = (
        "class Bus:\n"
        "    def __init__(self):\n"
        "        self.events.subscribe('head', self._on_head)\n"
        "    def _on_head(self, ev):\n"
        "        self.chain.process_block(ev)\n"
    )
    assert lint_source(good, OUT) == []


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def test_line_suppression_with_reason_is_honored():
    src = (
        "def f(lst, idx):\n"
        "    arr = lst.load_array()\n"
        "    # lint: allow(cow-aliasing) -- test fixture stages in place\n"
        "    arr[idx] = 0\n"
    )
    assert lint_source(src, OUT) == []


def test_suppression_without_reason_is_a_violation():
    src = (
        "def f(lst, idx):\n"
        "    arr = lst.load_array()\n"
        "    arr[idx] = 0  # lint: allow(cow-aliasing)\n"
    )
    rules = _rules(lint_source(src, OUT))
    assert "suppression" in rules and "cow-aliasing" in rules


def test_file_level_suppression():
    src = (
        "# lint: allow-file(cow-aliasing) -- fixture module\n"
        "def f(lst, idx):\n"
        "    arr = lst.load_array()\n"
        "    arr[idx] = 0\n"
    )
    assert lint_source(src, OUT) == []


def test_unknown_rule_in_suppression_is_flagged():
    src = "x = 1  # lint: allow(made-up-rule) -- whatever\n"
    assert _rules(lint_source(src, OUT)) == ["suppression"]


def test_allow_syntax_in_strings_does_not_count():
    src = (
        'DOC = "# lint: allow(cow-aliasing) -- not a comment"\n'
        "def f(lst, idx):\n"
        "    arr = lst.load_array()\n"
        "    arr[idx] = 0\n"
    )
    assert _rules(lint_source(src, OUT)) == ["cow-aliasing"]


# ---------------------------------------------------------------------------
# Whole-package gate + CLI
# ---------------------------------------------------------------------------


def test_package_is_lint_clean():
    """Tier-1 gate: `python -m lighthouse_tpu.analysis lighthouse_tpu/`
    must exit 0 — any new unsuppressed violation fails the suite."""
    violations = lint_paths([PKG])
    assert violations == [], "\n".join(v.render() for v in violations)


def test_cli_entrypoint(capsys):
    assert main(["--list-rules", str(PKG)]) == 0
    assert set(capsys.readouterr().out.split()) == set(RULES)
    assert main([str(PKG)]) == 0


def test_cli_reports_violations(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(lst, i):\n    a = lst.load_array()\n    a[i] = 1\n")
    assert main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "cow-aliasing" in out and "bad.py:3" in out
