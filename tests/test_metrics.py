"""Metrics registry + structured logging (lighthouse_metrics / logging
analogs) and their wiring into the import/epoch paths."""

import logging

from lighthouse_tpu.metrics import (
    REGISTRY,
    Registry,
    inc_counter,
    observe,
    set_gauge,
    start_timer,
)


def test_counter_gauge_histogram_roundtrip():
    r = Registry()
    c = r.counter("requests_total")
    c.inc()
    c.inc(2, route="blocks")
    assert c.value() == 1
    assert c.value(route="blocks") == 2

    g = r.gauge("head_slot")
    g.set(42)
    assert g.value() == 42

    h = r.histogram("import_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    assert h.count == 3
    assert abs(h.sum - 5.55) < 1e-9

    text = r.expose()
    assert "# TYPE requests_total counter" in text
    assert 'requests_total{route="blocks"} 2' in text
    assert "head_slot 42" in text
    assert 'import_seconds_bucket{le="+Inf"} 3' in text
    assert "import_seconds_count 3" in text


def test_timer_records_duration():
    r = Registry()
    h = r.histogram("op_seconds")
    with h.start_timer():
        pass
    assert h.count == 1
    assert h.sum >= 0


def test_global_helpers():
    inc_counter("test_global_counter", 3)
    set_gauge("test_global_gauge", 7)
    observe("test_global_hist", 0.2)
    t = start_timer("test_global_hist")
    t.stop()
    assert REGISTRY.counter("test_global_counter").value() == 3
    assert REGISTRY.histogram("test_global_hist").count == 2


def test_structured_logging_counts_into_metrics():
    from lighthouse_tpu.utils.logging import get_logger

    log = get_logger("lighthouse_tpu.test")
    before = REGISTRY.counter("log_records_total").value(level="info")
    log.info("imported block", slot=5, root="0xabcd")
    after = REGISTRY.counter("log_records_total").value(level="info")
    assert after == before + 1


def test_block_import_records_metrics():
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    before_blocks = REGISTRY.counter("beacon_blocks_imported_total").value()
    before_epochs = REGISTRY.histogram("epoch_transition_seconds").count
    h = BeaconChainHarness(minimal_spec(), E, validator_count=8)
    h.extend_chain(E.SLOTS_PER_EPOCH + 1)
    assert (
        REGISTRY.counter("beacon_blocks_imported_total").value()
        == before_blocks + E.SLOTS_PER_EPOCH + 1
    )
    assert REGISTRY.histogram("epoch_transition_seconds").count > before_epochs
    assert REGISTRY.histogram("beacon_block_import_seconds").count > 0
