"""Execution layer seam + MockEL + merge e2e.

Mirrors the reference's execution-layer test surface: MockExecutionLayer
(execution_layer/src/test_utils/mock_execution_layer.rs:12) payload
production/validation, and beacon-chain e2e runs that actually cross the
merge so process_execution_payload / process_withdrawals fire in the real
import pipeline (beacon_chain payload tests)."""

from dataclasses import replace

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.execution_layer import (
    ForkchoiceState,
    MockExecutionLayer,
    PayloadAttributes,
    PayloadStatusV1,
)
from lighthouse_tpu.state_processing.bellatrix import (
    NewPayloadRequest,
    is_merge_transition_complete,
)
from lighthouse_tpu.types.chain_spec import ForkName, minimal_spec
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


def _mock_el():
    return MockExecutionLayer(build_types(E), E)


def test_mock_el_payload_roundtrip():
    el = _mock_el()
    attrs = PayloadAttributes(timestamp=12, prev_randao=b"\x01" * 32)
    payload = el.get_payload(None, attrs, ForkName.BELLATRIX)  # terminal parent
    assert payload != type(payload)()  # non-default
    assert payload.block_number == el.generator.blocks[bytes(payload.parent_hash)].block_number + 1
    assert len(payload.transactions) == 1
    assert el.notify_new_payload(NewPayloadRequest(payload)) is PayloadStatusV1.VALID

    # chained payload
    p2 = el.get_payload(bytes(payload.block_hash), PayloadAttributes(13, b"\x02" * 32), ForkName.BELLATRIX)
    assert p2.parent_hash == payload.block_hash
    assert p2.block_number == payload.block_number + 1

    # forkchoice updated on a known head
    st = ForkchoiceState(bytes(p2.block_hash), bytes(payload.block_hash), b"\x00" * 32)
    assert el.notify_forkchoice_updated(st, None) is PayloadStatusV1.VALID
    assert el.generator.head_hash == bytes(p2.block_hash)

    # a payload whose claimed hash does not match its RLP header → INVALID
    # (real keccak verification, block_hash.rs behavior)
    fake = type(p2)(parent_hash=b"\x77" * 32, block_hash=b"\x88" * 32)
    assert el.notify_new_payload(NewPayloadRequest(fake)) is PayloadStatusV1.INVALID

    # correctly-hashed payload on an UNKNOWN parent → SYNCING (not VALID)
    from lighthouse_tpu.execution_layer.block_hash import (
        calculate_execution_block_hash,
    )

    orphan = type(p2)(parent_hash=b"\x77" * 32)
    orphan.block_hash, _ = calculate_execution_block_hash(orphan)
    assert el.notify_new_payload(NewPayloadRequest(orphan)) is PayloadStatusV1.SYNCING


def test_mock_el_pow_block_lookup():
    el = _mock_el()
    terminal = el.generator.terminal_block_hash
    pow_block = el.get_pow_block(terminal)
    assert pow_block is not None
    assert pow_block.total_difficulty >= el.generator.terminal_total_difficulty
    assert el.get_pow_block(b"\x99" * 32) is None


def test_chain_crosses_merge_with_real_payloads():
    """Bellatrix chain with a MockEL: the first produced block is the merge
    transition block; every subsequent import runs process_execution_payload
    on a non-default, hash-linked payload."""
    spec = replace(
        minimal_spec(), altair_fork_epoch=0, bellatrix_fork_epoch=0
    )
    h = BeaconChainHarness(spec, E, validator_count=16, mock_execution_layer=True)
    assert not is_merge_transition_complete(h.chain.head_state)
    h.extend_chain(E.SLOTS_PER_EPOCH + 2)
    st = h.chain.head_state
    assert is_merge_transition_complete(st)
    header = st.latest_execution_payload_header
    assert header.block_number >= E.SLOTS_PER_EPOCH
    assert header.block_hash != b"\x00" * 32
    # the EL knows the head payload (hash-linked chain intact)
    assert bytes(header.block_hash) in h.chain.execution_layer.generator.blocks


def test_merged_chain_processes_withdrawals_in_pipeline():
    """Capella-at-genesis + MockEL + one validator with 0x01 credentials and
    an excess balance: the partial-withdrawal sweep reaches the payload AND
    debits the balance through the real import pipeline."""
    spec = replace(
        minimal_spec(),
        altair_fork_epoch=0,
        bellatrix_fork_epoch=0,
        capella_fork_epoch=0,
    )
    excess = 1_000_000_000  # 1 ETH over max effective

    def modifier(state):
        v = state.validators[0]
        v.withdrawal_credentials = b"\x01" + b"\x00" * 11 + b"\xaa" * 20
        state.balances[0] = E.MAX_EFFECTIVE_BALANCE + excess

    h = BeaconChainHarness(
        spec,
        E,
        validator_count=16,
        mock_execution_layer=True,
        genesis_modifier=modifier,
    )
    h.extend_chain(4)
    st = h.chain.head_state
    assert st.next_withdrawal_index >= 1  # sweep advanced
    # excess debited (small attestation rewards may accrue after the sweep)
    assert st.balances[0] < E.MAX_EFFECTIVE_BALANCE + excess // 100
    # the withdrawal rode an actual payload
    head_block = h.chain.head_block()
    found = False
    r = h.chain.head_root
    for _ in range(4):
        blk = h.chain._blocks_by_root.get(r)
        if blk is None:
            break
        w = getattr(blk.message.body.execution_payload, "withdrawals", [])
        if any(int(x.amount) == excess for x in w):
            found = True
            break
        r = blk.message.parent_root
    assert found, "withdrawal never appeared in a payload"
