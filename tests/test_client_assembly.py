"""Node assembly + API client + network config + validator manager.

ClientBuilder wires store→chain→network→http→VC→timer (builder.rs:109-787
analog); the eth2 HTTP client drives a VC over the wire; config.yaml
round-trips into ChainSpec; validator-manager creates/imports keystores."""

from dataclasses import replace

import pytest

from lighthouse_tpu.client import ClientBuilder, ClientConfig
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


def _cfg(**kw):
    bls.set_backend("fake_crypto")
    base = dict(
        spec=replace(minimal_spec(), altair_fork_epoch=0),
        E=E,
        validator_count=16,
        validate=True,
        manual_slot_clock=True,
    )
    base.update(kw)
    return ClientConfig(**base)


def test_client_builder_full_node_reaches_finality():
    client = ClientBuilder(_cfg()).build().start()
    try:
        for slot in range(1, 4 * E.SLOTS_PER_EPOCH + 1):
            client.on_slot(slot)
        assert client.chain.head_state.slot == 4 * E.SLOTS_PER_EPOCH
        assert client.chain.finalized_checkpoint.epoch >= 2
        assert client.http_server is not None and client.network is not None
    finally:
        client.stop()


def test_two_clients_sync_via_network():
    a = ClientBuilder(_cfg()).build().start()
    b = ClientBuilder(_cfg(validate=False)).build().start()
    try:
        for slot in range(1, 9):
            a.on_slot(slot)
        b.slot_clock.set_slot(8)
        peer = b.network.connect("127.0.0.1", a.network.port)
        imported = b.network.sync.sync_with(peer)
        assert imported == 8
        assert b.chain.head_root == a.chain.head_root
    finally:
        a.stop()
        b.stop()


def test_vc_over_http_client():
    """A validator client running over the REAL HTTP transport proposes a
    block on the node (eth2 client + HttpBeaconNode path)."""
    from lighthouse_tpu.eth2 import BeaconNodeHttpClient, HttpBeaconNode
    from lighthouse_tpu.types.containers import build_types
    from lighthouse_tpu.validator_client import ValidatorClient

    node_client = ClientBuilder(_cfg(validate=False)).build().start()
    try:
        api = BeaconNodeHttpClient(
            f"http://127.0.0.1:{node_client.http_server.port}"
        )
        assert api.get_health()
        genesis = api.get_genesis()
        assert genesis["genesis_validators_root"].startswith("0x")

        remote = HttpBeaconNode(api, build_types(E))
        vc = ValidatorClient(
            None, node_client.keypairs, node_client.chain.spec, E, node=remote
        )
        node_client.slot_clock.set_slot(1)
        root = vc.on_slot(1)
        assert root is not None
        assert node_client.chain.head_state.slot == 1
        assert node_client.chain.head_root == root
    finally:
        node_client.stop()


@pytest.mark.slow
def test_bn_imports_blocks_through_device_bls(monkeypatch):
    """--bls-backend tpu end-to-end: a ClientBuilder-assembled node (VC,
    network, state advance) imports VC-produced blocks through the FULL
    device verifier (ops/bls381_verify), at small shapes on the test mesh.
    Pins VERDICT r3 weak #2: the tpu backend must be reachable from the
    node, not just bench/tests."""
    from lighthouse_tpu.metrics import REGISTRY

    # keep the x64 epoch sweep out of the shared test process (it flips
    # jax x64 process-wide on import); the node path is exercised by the
    # isolated test_device_epoch_sweep suite
    monkeypatch.setenv("LIGHTHOUSE_TPU_DEVICE_EPOCH_SWEEP", "0")
    monkeypatch.setenv("LIGHTHOUSE_TPU_BLS_CHUNK", "16")
    counter = REGISTRY.counter("bls_device_batches_total")
    before = counter.value()
    client = ClientBuilder(
        _cfg(bls_backend="tpu", validator_count=8)
    ).build().start()
    try:
        assert bls.backend_name() == "tpu"
        for slot in range(1, 5):
            client.on_slot(slot)
        assert client.chain.head_state.slot == 4
        assert counter.value() > before, (
            "no batch rode the device verifier"
        )
    finally:
        client.stop()
        bls.set_backend("fake_crypto")


def test_network_config_yaml_roundtrip():
    from lighthouse_tpu.types.network_config import (
        Eth2NetworkConfig,
        built_in_network,
    )

    net = built_in_network("minimal-dev")
    text = net.to_config_yaml()
    assert "PRESET_BASE" in text and "ALTAIR_FORK_EPOCH" in text
    back = Eth2NetworkConfig.from_config_yaml(text, name="roundtrip")
    assert back.spec.altair_fork_epoch == 0
    assert back.spec.seconds_per_slot == net.spec.seconds_per_slot
    assert back.E is net.E

    main = built_in_network("mainnet")
    assert main.spec.altair_fork_epoch == 74240
    # disabled fork serializes as FAR_FUTURE and loads back as None
    assert "18446744073709551615" in main.to_config_yaml()
    back_main = Eth2NetworkConfig.from_config_yaml(main.to_config_yaml())
    assert back_main.spec.electra_fork_epoch is None


def test_validator_manager_create_list_import(tmp_path):
    from lighthouse_tpu import validator_manager as VM

    seed = b"\x07" * 32
    records = VM.create_validators(
        seed,
        2,
        tmp_path / "v1",
        "pw",
        spec=minimal_spec(),
        E=E,
        fast_kdf=True,
    )
    assert len(records) == 2
    assert records[0]["deposit_data_root"]
    listed = VM.list_validators(tmp_path / "v1")
    assert len(listed) == 2
    assert listed[0]["path"].startswith("m/12381/3600/")

    ks_file = next((tmp_path / "v1").glob("keystore-*.json"))
    pk = VM.import_keystore(ks_file, "pw", tmp_path / "v2")
    assert VM.list_validators(tmp_path / "v2")[0]["pubkey"] == pk.hex()
    with pytest.raises(Exception):
        VM.import_keystore(ks_file, "wrong", tmp_path / "v3")

    signers = VM.load_signers(tmp_path / "v1", "pw")
    assert len(signers) == 2
    # pubkey rendering is backend-dependent; compare under real crypto
    bls.set_backend("host")
    try:
        assert signers[0][1].public_key().to_bytes() == signers[0][0]
    finally:
        bls.set_backend("fake_crypto")
