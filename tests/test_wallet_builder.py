"""EIP-2386 wallets + the builder (MEV relay) client seam."""

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto.key_derivation import derive_sk_from_path
from lighthouse_tpu.crypto.wallet import Wallet, WalletError
from lighthouse_tpu.execution_layer import MockExecutionLayer
from lighthouse_tpu.execution_layer.builder_client import (
    MockBuilder,
    ValidatorRegistration,
)
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


def test_wallet_roundtrip_and_account_derivation():
    bls.set_backend("host")
    seed = b"\x21" * 32
    w = Wallet.create("w1", "wallet-pw", seed=seed, _fast_kdf=True)
    assert w.nextaccount == 0

    ks0 = w.next_validator("wallet-pw", "ks-pw", _fast_kdf=True)
    ks1 = w.next_validator("wallet-pw", "ks-pw", _fast_kdf=True)
    assert w.nextaccount == 2
    assert ks0.path == "m/12381/3600/0/0/0"
    assert ks1.path == "m/12381/3600/1/0/0"
    # keystore secrets match direct EIP-2334 derivation
    assert int.from_bytes(ks0.decrypt("ks-pw"), "big") == derive_sk_from_path(
        seed, "m/12381/3600/0/0/0"
    )

    back = Wallet.from_json(w.to_json())
    assert back.decrypt_seed("wallet-pw") == seed
    with pytest.raises(Exception):
        back.decrypt_seed("wrong")
    with pytest.raises(WalletError):
        Wallet({"type": "nd"})


def test_mock_builder_bid_and_unblind():
    t = build_types(E)
    el = MockExecutionLayer(t, E)
    builder = MockBuilder(el, t, E)
    pubkey = b"\xaa" * 48

    # unregistered validators get no bid
    assert builder.get_header(1, None, pubkey) is None
    builder.register_validators([ValidatorRegistration(pubkey=pubkey)])
    bid = builder.get_header(1, None, pubkey)
    assert bid is not None and bid.value_wei > 0
    assert bid.header.block_hash != b"\x00" * 32

    # a blinded block round-trips to the full payload
    class _Blinded:
        pass

    blinded = _Blinded()
    blinded.message = _Blinded()
    blinded.message.body = _Blinded()
    blinded.message.body.execution_payload_header = bid.header
    payload = builder.submit_blinded_block(blinded)
    assert bytes(payload.block_hash) == bytes(bid.header.block_hash)
    assert payload.hash_tree_root() is not None
    with pytest.raises(RuntimeError):
        bad = _Blinded()
        bad.message = _Blinded()
        bad.message.body = _Blinded()
        bad.message.body.execution_payload_header = t.ExecutionPayloadHeaderCapella()
        builder.submit_blinded_block(bad)


def test_wallet_accepts_long_seeds():
    # 64-byte BIP39-style seeds are the normal EIP-2386 input
    seed64 = b"\x05" * 64
    w = Wallet.create("w64", "pw", seed=seed64, _fast_kdf=True)
    assert w.decrypt_seed("pw") == seed64
    ks = w.next_validator("pw", "kpw", _fast_kdf=True)
    assert ks.path == "m/12381/3600/0/0/0"
