"""Differential tests: optimized pairing path vs the retained reference.

The fast path (sparse-line twisted Miller loop, cyclotomic final
exponentiation, wNAF/fixed-base scalar mult, ψ-based subgroup/cofactor ops)
is pinned against `pairing_reference` and the plain binary/order-check
implementations on random inputs. Oracles are the ORIGINAL algorithms, kept
importable precisely for this purpose — a transcription slip in any
addition chain or line formula fails here, not in production.
"""

import random
import time

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto.bls import cache_stats, hash_to_g2_cached
from lighthouse_tpu.crypto.bls12_381 import (
    FQ,
    FQ2,
    G1_GEN,
    G2_GEN,
    P,
    R,
    g1_gen_mul,
    g2_in_subgroup,
    hash_to_g2,
    inf,
    is_inf,
    pairing,
    pairing_check,
    pt_eq,
    pt_mul,
    pt_mul_binary,
    pt_neg,
)
from lighthouse_tpu.crypto.bls12_381 import fields as F
from lighthouse_tpu.crypto.bls12_381 import pairing_reference as ref

# the package re-exports the `pairing` FUNCTION under the submodule's name,
# so fetch the module object itself for the internal fast-path entry points
import importlib

fast = importlib.import_module("lighthouse_tpu.crypto.bls12_381.pairing")
from lighthouse_tpu.crypto.bls12_381.curve import (
    H2_EFF,
    g2_clear_cofactor,
    to_affine,
)

rng = random.Random(1337)


@pytest.fixture(autouse=True)
def host_backend():
    bls.set_backend("host")
    yield
    bls.set_backend("host")


def _rand_f2():
    return (rng.randrange(P), rng.randrange(P))


def _rand_f12():
    return (
        (_rand_f2(), _rand_f2(), _rand_f2()),
        (_rand_f2(), _rand_f2(), _rand_f2()),
    )


def _rand_g1():
    return pt_mul(FQ, G1_GEN, rng.randrange(1, R))


def _rand_g2():
    return pt_mul(FQ2, G2_GEN, rng.randrange(1, R))


def _non_subgroup_g2():
    x = F.f2(3, 1)
    while True:
        rhs = F.f2_add(F.f2_mul(F.f2_mul(x, x), x), (4, 4))
        y = F.f2_sqrt(rhs)
        if y is not None:
            pt = (x, y, F.f2(1))
            if not is_inf(FQ2, pt_mul_binary(FQ2, pt, R)):
                return pt
        x = F.f2_add(x, F.f2(1))


# ---------------------------------------------------------------------------
# Field-level differentials
# ---------------------------------------------------------------------------


def test_sparse_vs_dense_f12_mul():
    for _ in range(8):
        f = _rand_f12()
        c0, c4, c5 = _rand_f2(), _rand_f2(), _rand_f2()
        dense = ((c0, F.F2_ZERO, F.F2_ZERO), (F.F2_ZERO, c4, c5))
        assert F.f12_mul_by_045(f, c0, c4, c5) == F.f12_mul(f, dense)
    # degenerate coefficients (vertical-line shapes)
    f = _rand_f12()
    for c in [(F.F2_ZERO, _rand_f2(), F.F2_ZERO),
              (_rand_f2(), F.F2_ZERO, F.F2_ZERO)]:
        dense = ((c[0], F.F2_ZERO, F.F2_ZERO), (F.F2_ZERO, c[1], c[2]))
        assert F.f12_mul_by_045(f, *c) == F.f12_mul(f, dense)


def _easy_part(f):
    t = F.f12_mul(F.f12_conj(f), F.f12_inv(f))
    return F.f12_mul(F.f12_frob_n(t, 2), t)


def test_cyclotomic_sqr_vs_generic():
    # cyclotomic squaring is only valid inside the cyclotomic subgroup —
    # enter it via the easy part of random Fq12 elements
    for _ in range(4):
        t = _easy_part(_rand_f12())
        assert F.f12_cyclotomic_sqr(t) == F.f12_sqr(t)


def test_cyclotomic_pow_vs_generic():
    t = _easy_part(_rand_f12())
    for e in (1, 2, 3, abs(fast.X), rng.getrandbits(64) | 1):
        assert F.f12_cyclotomic_pow(t, e) == F.f12_pow(t, e)
    assert F.f12_cyclotomic_pow(t, 0) == F.F12_ONE
    # negative exponent = conjugate in the subgroup
    assert F.f12_cyclotomic_pow(t, -5) == F.f12_inv(F.f12_pow(t, 5))


def test_final_exponentiation_vs_generic():
    # the x-power addition chain must reproduce the EXACT generic hard part
    # (not the cubed variant) on arbitrary Miller-loop outputs
    m = fast.miller_loop(
        to_affine(FQ2, _rand_g2()), to_affine(FQ, _rand_g1())
    )
    assert fast.final_exponentiation(m) == ref.final_exponentiation(m)


# ---------------------------------------------------------------------------
# Pairing differentials
# ---------------------------------------------------------------------------


def test_pairing_matches_reference_on_random_points():
    for _ in range(2):
        p, q = _rand_g1(), _rand_g2()
        assert fast.pairing(p, q) == ref.pairing(p, q)


def test_pairing_infinity_handling_matches_reference():
    assert fast.pairing(inf(FQ), G2_GEN) == ref.pairing(inf(FQ), G2_GEN)
    assert fast.pairing(G1_GEN, inf(FQ2)) == ref.pairing(G1_GEN, inf(FQ2))
    assert fast.pairing(inf(FQ), G2_GEN) == F.F12_ONE


def test_multi_pairing_matches_reference():
    pairs = [(_rand_g1(), _rand_g2()), (G1_GEN, G2_GEN)]
    assert fast.multi_pairing(pairs) == ref.multi_pairing(pairs)
    # a productive check both agree on
    a = rng.randrange(2, 2**32)
    good = [
        (pt_mul(FQ, G1_GEN, a), G2_GEN),
        (pt_neg(FQ, G1_GEN), pt_mul(FQ2, G2_GEN, a)),
    ]
    assert fast.pairing_check(good) and ref.pairing_check(good)
    bad = [(pt_mul(FQ, G1_GEN, a + 1), G2_GEN), good[1]]
    assert not fast.pairing_check(bad)


# ---------------------------------------------------------------------------
# Scalar-multiplication differentials
# ---------------------------------------------------------------------------


def test_wnaf_vs_binary_pt_mul():
    pts = [(FQ, G1_GEN), (FQ2, G2_GEN)]
    scalars = [0, 1, 2, 3, 15, 16, R - 1, R, R + 1, -7,
               rng.getrandbits(64), rng.randrange(R), -rng.randrange(R)]
    for k, g in pts:
        base = pt_mul(k, g, rng.randrange(2, 100))
        for n in scalars:
            assert pt_eq(k, pt_mul(k, base, n), pt_mul_binary(k, base, n))
    # infinity base
    assert is_inf(FQ, pt_mul(FQ, inf(FQ), 12345))


def test_g1_gen_mul_vs_binary():
    for n in (1, 2, 16, R - 1, rng.randrange(R), rng.randrange(R)):
        assert pt_eq(FQ, g1_gen_mul(n), pt_mul_binary(FQ, G1_GEN, n))
    assert is_inf(FQ, g1_gen_mul(0))
    assert pt_eq(FQ, g1_gen_mul(R + 5), pt_mul_binary(FQ, G1_GEN, 5))


# ---------------------------------------------------------------------------
# ψ-endomorphism subgroup/cofactor differentials
# ---------------------------------------------------------------------------


def test_g2_subgroup_psi_vs_order_ladder():
    for _ in range(3):
        q = _rand_g2()
        assert g2_in_subgroup(q)
        assert is_inf(FQ2, pt_mul_binary(FQ2, q, R))
    bad = _non_subgroup_g2()
    assert not g2_in_subgroup(bad)
    assert g2_in_subgroup(inf(FQ2))


def test_g2_clear_cofactor_bp_vs_heff_ladder():
    for _ in range(2):
        pt = _non_subgroup_g2()
        want = pt_mul_binary(FQ2, pt, H2_EFF)
        got = g2_clear_cofactor(pt)
        assert pt_eq(FQ2, got, want)
        assert g2_in_subgroup(got)


# ---------------------------------------------------------------------------
# Verification caches
# ---------------------------------------------------------------------------


def test_hash_to_g2_cache_hits_and_counters():
    msg = bytes([rng.randrange(256) for _ in range(32)])
    before = cache_stats()["hash_to_g2"]
    h1 = hash_to_g2_cached(msg)
    h2 = hash_to_g2_cached(msg)
    after = cache_stats()["hash_to_g2"]
    assert h1 is h2
    assert after["hits"] >= before["hits"] + 1
    assert after["misses"] >= before["misses"] + 1
    assert pt_eq(FQ2, h1, hash_to_g2(msg))


def test_pubkey_validate_dedupes_subgroup_check():
    kp = bls.interop_keypairs(1)[0]
    pk = bls.PublicKey(kp.pk.to_bytes())  # fresh object, same encoding
    before = cache_stats()["pubkey_validated"]
    assert pk.validate()
    assert bls.PublicKey(kp.pk.to_bytes()).validate()
    after = cache_stats()["pubkey_validated"]
    assert after["hits"] >= before["hits"] + 1  # second check was deduped


def test_verify_uses_caches_and_still_rejects_bad():
    sk = bls.interop_secret_key(5)
    pk = sk.public_key()
    msg = b"\x37" * 32
    sig = sk.sign(msg)
    assert sig.verify(pk, msg)
    assert sig.verify(pk, msg)  # cached path must stay correct
    assert not sig.verify(pk, b"\x38" * 32)
    other = bls.interop_secret_key(6).public_key()
    assert not sig.verify(other, msg)
    # non-subgroup signature rejected despite caches
    bad_pt = _non_subgroup_g2()
    from lighthouse_tpu.crypto.bls12_381 import g2_to_bytes

    bad_sig = bls.Signature(g2_to_bytes(bad_pt))
    assert not bad_sig.verify(pk, msg)
    assert not bad_sig.verify(pk, msg)  # and stays rejected on the rerun


# ---------------------------------------------------------------------------
# Perf smoke (loose wall-clock bound; catches O(bits) regressions on CI
# without a device — the optimized path runs this in well under 200 ms)
# ---------------------------------------------------------------------------


@pytest.mark.perf_smoke
def test_pairing_check_perf_smoke():
    sk = bls.interop_secret_key(0)
    msg = b"\x11" * 32
    h = hash_to_g2_cached(msg)
    sig = sk.sign(msg)
    pairs = [(sk.public_key().point(), h), (pt_neg(FQ, G1_GEN), sig.point())]
    pairing_check(pairs)  # warm any lazy tables
    t0 = time.perf_counter()
    assert pairing_check(pairs)
    elapsed = time.perf_counter() - t0
    # loose absolute ceiling: catches O(bits) blowups even on a slow box
    assert elapsed < 2.0, (
        f"pairing_check(2 pairs) took {elapsed:.2f}s — the host pairing "
        "hot path has catastrophically regressed"
    )
    # relative bound: the optimized path must actually beat the retained
    # reference path on the same machine (real margin is ~7×; requiring 2×
    # keeps the assertion robust to scheduler noise while still failing if
    # the fast path silently falls back to reference-class cost)
    t0 = time.perf_counter()
    assert ref.pairing_check(pairs)
    ref_elapsed = time.perf_counter() - t0
    assert elapsed * 2 < ref_elapsed, (
        f"optimized pairing_check ({elapsed*1000:.0f}ms) is not meaningfully "
        f"faster than pairing_reference ({ref_elapsed*1000:.0f}ms)"
    )
