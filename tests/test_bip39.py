"""BIP-39 mnemonic encode/decode/seed vs the spec's published vectors.

Vectors are from the BIP-39 reference test set (trezor/python-mnemonic
vectors.json — passphrase "TREZOR"), the same set the reference's bip39
crate pins (account_manager/src/wallet/create.rs consumer)."""

import pytest

from lighthouse_tpu.crypto.bip39 import (
    Bip39Error,
    entropy_to_mnemonic,
    generate_mnemonic,
    mnemonic_to_entropy,
    mnemonic_to_seed,
    validate_mnemonic,
)

# (entropy_hex, mnemonic, seed_hex_with_TREZOR_passphrase)
SPEC_VECTORS = [
    (
        "00000000000000000000000000000000",
        "abandon abandon abandon abandon abandon abandon abandon abandon "
        "abandon abandon abandon about",
        "c55257c360c07c72029aebc1b53c05ed0362ada38ead3e3e9efa3708e5349553"
        "1f09a6987599d18264c1e1c92f2cf141630c7a3c4ab7c81b2f001698e7463b04",
    ),
    (
        "80808080808080808080808080808080",
        "letter advice cage absurd amount doctor acoustic avoid letter "
        "advice cage above",
        "d71de856f81a8acc65e6fc851a38d4d7ec216fd0796d0a6827a3ad6ed5511a30"
        "fa280f12eb2e47ed2ac03b5c462a0358d18d69fe4f985ec81778c1b370b652a8",
    ),
    (
        "ffffffffffffffffffffffffffffffff",
        "zoo zoo zoo zoo zoo zoo zoo zoo zoo zoo zoo wrong",
        "ac27495480225222079d7be181583751e86f571027b0497b5b5d11218e0a8a13"
        "332572917f0f8e5a589620c6f15b11c61dee327651a14c34e18231052e48c069",
    ),
]


@pytest.mark.parametrize("ent_hex,mnemonic,seed_hex", SPEC_VECTORS)
def test_spec_vectors(ent_hex, mnemonic, seed_hex):
    entropy = bytes.fromhex(ent_hex)
    assert entropy_to_mnemonic(entropy) == mnemonic
    assert mnemonic_to_entropy(mnemonic) == entropy
    assert mnemonic_to_seed(mnemonic, "TREZOR").hex() == seed_hex


@pytest.mark.parametrize("strength", [128, 160, 192, 224, 256])
def test_roundtrip_all_strengths(strength):
    import hashlib

    entropy = hashlib.sha256(f"e{strength}".encode()).digest()[: strength // 8]
    m = entropy_to_mnemonic(entropy)
    assert len(m.split()) == (strength + strength // 32) // 11
    assert mnemonic_to_entropy(m) == entropy
    assert validate_mnemonic(m)


def test_generate_is_valid_and_random():
    a = generate_mnemonic(256)
    b = generate_mnemonic(256)
    assert a != b
    assert len(a.split()) == 24
    assert validate_mnemonic(a)


def test_rejections():
    good = SPEC_VECTORS[0][1]
    # swapped word order breaks the checksum
    words = good.split()
    words[0], words[-1] = words[-1], words[0]
    assert not validate_mnemonic(" ".join(words))
    with pytest.raises(Bip39Error, match="checksum"):
        mnemonic_to_entropy(" ".join(words))
    with pytest.raises(Bip39Error, match="unknown"):
        mnemonic_to_entropy(good.replace("about", "zzzz"))
    with pytest.raises(Bip39Error, match="words"):
        mnemonic_to_entropy("abandon abandon")
    with pytest.raises(Bip39Error):
        entropy_to_mnemonic(b"\x00" * 13)


def test_wallet_mnemonic_recovery_roundtrip():
    """create_with_mnemonic → recover yields the same seed, hence the
    same first validator keystore (create.rs/recover.rs behavior)."""
    from lighthouse_tpu.crypto.wallet import Wallet

    w, mnemonic = Wallet.create_with_mnemonic("w1", "pw", _fast_kdf=True)
    assert validate_mnemonic(mnemonic)
    w2 = Wallet.recover("w1-again", "pw2", mnemonic, _fast_kdf=True)
    assert w.decrypt_seed("pw") == w2.decrypt_seed("pw2")
    ks1 = w.next_validator("pw", "kpw", _fast_kdf=True)
    ks2 = w2.next_validator("pw2", "kpw", _fast_kdf=True)
    assert ks1.decrypt("kpw") == ks2.decrypt("kpw")
