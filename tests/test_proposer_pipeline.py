"""Proposer-boost late-block re-orgs and the proposer pipeline.

`get_proposer_head` at three layers: directed condition tests on the
columnar proto-array (each re-org precondition flipped in isolation),
differential fuzz against the retained scalar oracle, and chain-level
end-to-end — a weak late head makes `produce_block_on_state` build on
its parent, with the observation-time gates (lateness, re-org cutoff,
finalization distance) exercised on the real chain. Plus the HTTP
surface dedup: the SSZ and object renderings of block production are
byte-identical through the one pipeline."""

import random

import numpy as np
import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.fork_choice import (
    ProtoArrayForkChoice,
    ProtoArrayForkChoiceReference,
)
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec

E = MinimalEthSpec

R = lambda i: b"\xaa" + i.to_bytes(4, "big") + b"\x00" * 27  # noqa: E731
ZERO = b"\x00" * 32


@pytest.fixture(autouse=True)
def _fake_crypto():
    prev = bls.backend_name()
    bls.set_backend("fake_crypto")
    yield
    bls.set_backend(prev)


def _harness(n=16):
    return BeaconChainHarness(minimal_spec(), E, validator_count=n)


# ---------------------------------------------------------------------------
# proto-array directed conditions
# ---------------------------------------------------------------------------

#: committee_weight=125 with the spec thresholds: head weak < 25,
#: parent strong > 200
CW, HEAD_PCT, PARENT_PCT, SPE = 125, 20, 160, 8


def _chain_pair(head_uje=None, parent_uje=None, head_slot=2, parent_slot=1):
    """anchor(R0)@0 <- parent(R1) <- head(R2); 10 validators of 100."""
    col = ProtoArrayForkChoice(R(0), 0, R(0), 0, 0)
    ref = ProtoArrayForkChoiceReference(R(0), 0, R(0), 0, 0)
    for fc in (col, ref):
        fc.on_block(
            slot=parent_slot,
            root=R(1),
            parent_root=R(0),
            state_root=R(1),
            justified_epoch=0,
            finalized_epoch=0,
            unrealized_justified_epoch=parent_uje,
        )
        fc.on_block(
            slot=head_slot,
            root=R(2),
            parent_root=R(1),
            state_root=R(2),
            justified_epoch=0,
            finalized_epoch=0,
            unrealized_justified_epoch=head_uje,
        )
    return col, ref


def _run_head(col, ref, parent_votes=8, head_votes=0, boost=(ZERO, 0)):
    balances = [100] * 10
    for v in range(parent_votes):
        col.process_attestation(v, R(1), 0)
        ref.process_attestation(v, R(1), 0)
    for v in range(parent_votes, parent_votes + head_votes):
        col.process_attestation(v, R(2), 0)
        ref.process_attestation(v, R(2), 0)
    kw = dict(
        justified_checkpoint_root=R(0),
        justified_epoch=0,
        finalized_epoch=0,
        proposer_boost_root=boost[0],
        proposer_boost_amount=boost[1],
        equivocating_indices=set(),
    )
    col.get_head(
        justified_state_balances=np.asarray(balances, dtype=np.uint64), **kw
    )
    ref.get_head(justified_state_balances=balances, **kw)


def _both(col, ref, slot, head_root=R(2), cw=CW, spe=SPE):
    a = col.proto_array.get_proposer_head(
        slot, head_root, cw, HEAD_PCT, PARENT_PCT, spe
    )
    b = ref.proto_array.get_proposer_head(
        slot, head_root, cw, HEAD_PCT, PARENT_PCT, spe
    )
    assert a == b
    return a


def test_reorg_fires_on_weak_late_single_slot_head():
    col, ref = _chain_pair()
    _run_head(col, ref)  # parent weight 800, head weight 0
    assert _both(col, ref, 3) == R(1)


def test_no_reorg_when_head_not_weak():
    col, ref = _chain_pair()
    _run_head(col, ref, parent_votes=7, head_votes=1)  # head weight 100 >= 25
    assert _both(col, ref, 3) is None


def test_no_reorg_when_parent_not_strong():
    col, ref = _chain_pair()
    _run_head(col, ref, parent_votes=2)  # parent weight 200, not > 200
    assert _both(col, ref, 3) is None


def test_no_reorg_across_epoch_boundary():
    col, ref = _chain_pair()
    _run_head(col, ref)
    assert _both(col, ref, 3, spe=3) is None  # 3 % 3 == 0: shuffling flips


def test_no_reorg_unless_proposing_next_slot():
    col, ref = _chain_pair()
    _run_head(col, ref)
    assert _both(col, ref, 4) is None  # skipped slot after the head


def test_no_reorg_on_multi_slot_head():
    col, ref = _chain_pair(head_slot=3)  # parent@1 <- head@3: gap
    _run_head(col, ref)
    assert _both(col, ref, 4) is None


def test_no_reorg_when_ffg_not_competitive():
    col, ref = _chain_pair(head_uje=1, parent_uje=0)
    _run_head(col, ref)
    assert _both(col, ref, 3) is None


def test_reorg_judges_head_without_its_boost():
    # the last get_head pass boosted the (otherwise voteless) head; the
    # re-org decision backs the boost out and still sees a weak head
    col, ref = _chain_pair()
    _run_head(col, ref, boost=(R(2), 500))
    pa = col.proto_array
    assert int(pa._weights[pa.indices[R(2)]]) == 500  # boost in the column
    assert _both(col, ref, 3) == R(1)


def test_no_reorg_for_unknown_or_anchor_head():
    col, ref = _chain_pair()
    _run_head(col, ref)
    assert _both(col, ref, 3, head_root=R(9)) is None  # unknown
    assert _both(col, ref, 1, head_root=R(0)) is None  # anchor: no parent


# ---------------------------------------------------------------------------
# differential fuzz vs the scalar oracle
# ---------------------------------------------------------------------------


def test_proposer_head_differential_fuzz():
    for seed in range(12):
        rng = random.Random(seed)
        col = ProtoArrayForkChoice(R(0), 0, R(0), 0, 0)
        ref = ProtoArrayForkChoiceReference(R(0), 0, R(0), 0, 0)
        roots, slots = [R(0)], {R(0): 0}
        n_val = 32
        balances = [100 + rng.randint(0, 50) for _ in range(n_val)]
        next_root = 1
        for _ in range(40):
            op = rng.random()
            if op < 0.45:
                parent = rng.choice(roots[-6:])
                root = R(next_root)
                next_root += 1
                slot = slots[parent] + rng.randint(1, 2)
                slots[root] = slot
                kw = dict(
                    slot=slot,
                    root=root,
                    parent_root=parent,
                    state_root=root,
                    justified_epoch=0,
                    finalized_epoch=0,
                    unrealized_justified_epoch=rng.choice([None, 0, 1]),
                )
                col.on_block(**kw)
                ref.on_block(**kw)
                roots.append(root)
            elif op < 0.85:
                target = rng.choice(roots)
                for v in rng.sample(range(n_val), rng.randint(1, 8)):
                    col.process_attestation(v, target, 0)
                    ref.process_attestation(v, target, 0)
            else:
                boost_root = (
                    rng.choice(roots) if rng.random() < 0.5 else ZERO
                )
                kw = dict(
                    justified_checkpoint_root=R(0),
                    justified_epoch=0,
                    finalized_epoch=0,
                    proposer_boost_root=boost_root,
                    proposer_boost_amount=(
                        rng.randint(1, 400) if boost_root != ZERO else 0
                    ),
                    equivocating_indices=set(),
                )
                col.get_head(
                    justified_state_balances=np.asarray(
                        balances, dtype=np.uint64
                    ),
                    **kw,
                )
                ref.get_head(justified_state_balances=list(balances), **kw)
            # every node is a proposer-head candidate every step — the
            # decision must be differential-equal across the whole array
            cw = rng.randint(0, 600)
            spe = rng.choice([4, 8])
            for root in rng.sample(roots, min(len(roots), 5)):
                slot = slots[root] + rng.choice([1, 2])
                a = col.proto_array.get_proposer_head(
                    slot, root, cw, HEAD_PCT, PARENT_PCT, spe
                )
                b = ref.proto_array.get_proposer_head(
                    slot, root, cw, HEAD_PCT, PARENT_PCT, spe
                )
                assert a == b, (seed, root.hex()[:10], slot, cw, spe)


# ---------------------------------------------------------------------------
# chain-level end-to-end
# ---------------------------------------------------------------------------


def _rig_late_weak_head(h, late_seconds=5.0, chain_slots=None):
    """Build silently into epoch 1, cast the fleet's first (and therefore
    registering — VoteTracker is epoch-monotonic) votes on the intended
    parent, then land one unattested block observed `late_seconds` into
    its slot. Returns (parent_root, late_root, slot)."""
    h.extend_chain(
        E.SLOTS_PER_EPOCH + 1 if chain_slots is None else chain_slots,
        attest=False,
    )
    parent = h.chain.head_root
    slot = int(h.chain.head_state.slot) + 1
    h.slot_clock.set_slot(slot)
    h.slot_clock.set_seconds_into_slot(late_seconds)
    h.add_block_at_slot(slot)
    h.slot_clock.set_seconds_into_slot(0.0)
    late = h.chain.head_root
    assert late != parent
    # Votes only count from the slot after the attestation's (the store
    # rejects same-slot votes as "from the future"), so ingest the parent
    # votes once the proposal slot begins: the parent's own committee,
    # plus slot `slot`'s committee — which missed the late block by the
    # attestation deadline and attested the parent it could see. Two
    # committees put the parent at ~200% of one committee's weight,
    # clearing the 160% strong-parent bar.
    h.slot_clock.set_slot(slot + 1)
    h.chain.fork_choice.on_tick(slot + 1)
    atts = h.make_unaggregated_attestations(
        slot - 1, parent
    ) + h.make_unaggregated_attestations(slot, parent)
    h.chain.process_attestation_batch(atts)
    h.chain.recompute_head()  # apply pending votes -> fresh weight columns
    assert h.chain.head_root == late  # still head: the parent's only child
    return parent, late, slot


def test_chain_reorgs_out_late_weak_head():
    h = _harness()
    parent, late, slot = _rig_late_weak_head(h)  # observed past the 2 s deadline
    h.slot_clock.set_slot(slot + 1)
    assert h.chain.get_proposer_head(slot + 1) == parent
    block, _post = h.chain.produce_block_on_state(
        slot + 1, h.randao_reveal(0, slot + 1)
    )
    assert block.parent_root == parent  # built around the weak head


def test_chain_keeps_timely_head():
    h = _harness()
    # same weak-head rig, but the head was observed ON time: no re-org
    parent, head, slot = _rig_late_weak_head(h, late_seconds=0.0)
    h.slot_clock.set_slot(slot + 1)
    assert h.chain.get_proposer_head(slot + 1) == head


def test_chain_keeps_late_head_past_reorg_cutoff():
    h = _harness()
    _parent, late, slot = _rig_late_weak_head(h)
    h.slot_clock.set_slot(slot + 1)
    # proposing too deep into the slot to win our own boost: keep head
    h.slot_clock.set_seconds_into_slot(1.5)  # cutoff is deadline/2 = 1.0 s
    assert h.chain.get_proposer_head(slot + 1) == late
    h.slot_clock.set_seconds_into_slot(0.0)


def test_chain_keeps_late_head_when_finality_lags():
    h = _harness()
    _parent, late, slot = _rig_late_weak_head(h)
    # pretend finality stalled relative to the spec knob: any re-org is
    # too risky when the chain is not finalizing
    h.chain.spec.reorg_max_epochs_since_finalization = 0
    h.slot_clock.set_slot(slot + 1)
    epoch = (slot + 1) // E.SLOTS_PER_EPOCH
    assert epoch > h.chain.fork_choice.store.finalized_checkpoint.epoch
    assert h.chain.get_proposer_head(slot + 1) == late


def test_chain_keeps_head_across_epoch_boundary():
    h = _harness()
    # land the late weak head on the last slot of an epoch: proposing the
    # first slot of the next epoch must never re-org (shuffling stability)
    _parent, late, slot = _rig_late_weak_head(
        h, chain_slots=2 * E.SLOTS_PER_EPOCH - 2
    )
    assert (slot + 1) % E.SLOTS_PER_EPOCH == 0
    h.slot_clock.set_slot(slot + 1)
    assert h.chain.get_proposer_head(slot + 1) == late


# ---------------------------------------------------------------------------
# production pipeline plumbing
# ---------------------------------------------------------------------------


def test_production_consumes_preadvanced_snapshot():
    from lighthouse_tpu.beacon_chain.state_advance import StateAdvanceTimer
    from lighthouse_tpu.metrics import REGISTRY

    h = _harness()
    h.extend_chain(3)
    timer = StateAdvanceTimer(h.chain)
    cur = int(h.chain.head_state.slot)
    timer.on_slot_tick(cur)
    hits = REGISTRY.counter("state_advance_hits_total")
    before = hits.value()
    h.slot_clock.set_slot(cur + 1)
    block, post = h.chain.produce_block_on_state(
        cur + 1, h.randao_reveal(0, cur + 1)
    )
    assert hits.value() == before + 1
    assert int(block.slot) == cur + 1
    assert int(post.slot) == cur + 1


def test_block_production_trace_root_with_stage_spans():
    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.metrics.trace_collector import COLLECTOR

    h = _harness()
    h.extend_chain(2)
    counter = REGISTRY.counter("trace_collector_traces_total")
    before = counter.value(root="block_production")
    slot = int(h.chain.head_state.slot) + 1
    h.slot_clock.set_slot(slot)
    h.chain.produce_block_on_state(slot, h.randao_reveal(0, slot))
    assert counter.value(root="block_production") == before + 1
    trace = next(
        t for t in COLLECTOR.recent() if t.name == "block_production"
    )
    stages = {c.name for c in trace.children}
    assert {"advance", "pack", "assemble"} <= stages


def test_vc_proposal_is_one_block_production_trace():
    """The VC wraps randao+produce+sign in ONE root; the chain must not
    mint a second one underneath it."""
    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.metrics.trace_collector import COLLECTOR
    from lighthouse_tpu.validator_client import LocalBeaconNode, ValidatorClient

    h = _harness()
    h.extend_chain(2)
    vc = ValidatorClient(
        h.chain, h.keypairs, h.spec, E, node=LocalBeaconNode(h.chain)
    )
    counter = REGISTRY.counter("trace_collector_traces_total")
    before = counter.value(root="block_production")
    slot = int(h.chain.head_state.slot) + 1
    h.slot_clock.set_slot(slot)
    root = vc.on_slot(slot)
    assert root is not None
    assert counter.value(root="block_production") == before + 1
    trace = next(
        t for t in COLLECTOR.recent() if t.name == "block_production"
    )
    stages = {c.name for c in trace.children}
    assert "sign" in stages


# ---------------------------------------------------------------------------
# HTTP surface dedup
# ---------------------------------------------------------------------------


def test_produce_block_renderings_byte_identical():
    from lighthouse_tpu.http_api import BeaconApi

    h = _harness()
    h.extend_chain(3)
    api = BeaconApi(h.chain)
    slot = int(h.chain.head_state.slot) + 1
    h.slot_clock.set_slot(slot)
    randao = h.randao_reveal(0, slot)
    ssz = api.produce_block_ssz(slot, randao)
    obj = api.produce_block(slot, randao)
    assert ssz == obj.serialize()
