"""Batched VC duty pipeline (PR 19): differential oracles.

Every batch program keeps its per-key predecessor as the oracle:
fixed-base scalar mul vs the generic ladder (same group elements, same
compressed bytes), `bls.sign_batch` vs per-key `sk.sign`, the epoch duty
table vs the committee walk, the batch slashing-protection transaction
vs sequential per-key checks (including hostile surround / lowball /
double-vote mixes and crash-point atomicity), and the whole VC pipeline
batch-vs-per-key under LIGHTHOUSE_TPU_VC_BATCH — identical chain roots,
identical slashing-DB end state. Keymanager keystore routes are covered
at scale (satellite: 1k in tier-1, 10k behind the slow mark)."""

import random
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.crypto.bls12_381 import (
    FQ2,
    R,
    FixedBaseTable,
    fixed_base_window,
    fixed_base_worthwhile,
    g2_to_bytes,
    hash_to_g2,
    pt_mul,
)
from lighthouse_tpu.crypto.keystore import Keystore
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
from lighthouse_tpu.validator_client import ValidatorClient, _columns
from lighthouse_tpu.validator_client.http_api import KeymanagerApi
from lighthouse_tpu.validator_client.slashing_protection import (
    NotSafe,
    SlashingDatabase,
)


def _vc_setup(validator_count=16):
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=validator_count)
    vc = ValidatorClient(h.chain, h.keypairs, spec, E)
    return h, vc


# --- fixed-base windowed scalar multiplication ------------------------------


def test_fixed_base_table_matches_pt_mul():
    """Differential fuzz: table lookups + adds yield the exact same
    group elements (hence identical compressed bytes) as the generic
    wNAF ladder — at edge scalars and across window widths."""
    rng = random.Random(0xF1EB)
    h = hash_to_g2(b"vc-batch-fixture")
    scalars = [0, 1, 2, 3, R - 1, (1 << 255) - 1] + [
        rng.randrange(R) for _ in range(5)
    ]
    for w in (2, 5, 10):
        tbl = FixedBaseTable(FQ2, h, w)
        for s in scalars:
            assert g2_to_bytes(tbl.mul(s)) == g2_to_bytes(pt_mul(FQ2, h, s))


def test_fixed_base_window_and_worthwhile():
    # wider windows only pay off at larger batch sizes
    assert fixed_base_window(1) <= fixed_base_window(100)
    assert fixed_base_window(100) <= fixed_base_window(100_000)
    # one signature never amortizes a table; a committee does
    assert not fixed_base_worthwhile(1)
    assert fixed_base_worthwhile(3000)


def test_fixed_base_rejects_bad_inputs():
    h = hash_to_g2(b"vc-batch-bad-inputs")
    with pytest.raises(ValueError):
        FixedBaseTable(FQ2, h, 1)
    tbl = FixedBaseTable(FQ2, h, 3)
    with pytest.raises(ValueError):
        tbl.mul(-1)


# --- batch signing ----------------------------------------------------------


def test_sign_batch_bit_identical_host(monkeypatch):
    """Host backend: sign_batch output is BIT-identical to per-key
    signing, on both scalar-mul strategies (generic ladder for small
    groups, fixed-base window table when forced worthwhile)."""
    bls.set_backend("host")
    try:
        kps = bls.interop_keypairs(6)
        sks = [kp.sk for kp in kps]
        msgs = [b"\x01" * 32] * 3 + [b"\x02" * 32] * 2 + [b"\x03" * 32]
        per_key = [sk.sign(m).to_bytes() for sk, m in zip(sks, msgs)]
        for force_fixed_base in (False, True):
            if force_fixed_base:
                monkeypatch.setattr(
                    bls, "fixed_base_worthwhile", lambda m: True
                )
            batch = bls.sign_batch(sks, msgs)
            assert [s.to_bytes() for s in batch] == per_key
    finally:
        bls.set_backend("fake_crypto")


def test_sign_batch_fake_backend_and_length_mismatch():
    bls.set_backend("fake_crypto")
    kps = bls.interop_keypairs(4)
    msgs = [b"\x05" * 32] * 4
    batch = bls.sign_batch([k.sk for k in kps], msgs)
    assert [s.to_bytes() for s in batch] == [
        k.sk.sign(m).to_bytes() for k, m in zip(kps, msgs)
    ]
    with pytest.raises(bls.BlsError):
        bls.sign_batch([kps[0].sk], [])


# --- epoch duty table -------------------------------------------------------


def test_epoch_duty_table_matches_committee_walk():
    from lighthouse_tpu.state_processing.accessors import (
        committee_cache_at,
        compute_start_slot_at_epoch,
        epoch_duty_table,
    )

    h, _vc = _vc_setup(validator_count=24)
    st = h.chain.head_state
    table = epoch_duty_table(st, 0, E)
    cc = committee_cache_at(st, 0, E)
    start = compute_start_slot_at_epoch(0, E)
    expected = {}
    for slot in range(start, start + E.SLOTS_PER_EPOCH):
        for ci in range(cc.committees_per_slot):
            committee = cc.committee(slot, ci)
            for pos, vi in enumerate(committee):
                expected[int(vi)] = (slot, ci, pos, len(committee))
    idx = list(range(-2, len(st.validators) + 2))
    found, slots, cidx, pos, size = table.lookup(idx)
    hits = [i for i, f in zip(idx, found) if f]
    got = {
        vi: (int(s), int(c), int(p), int(n))
        for vi, s, c, p, n in zip(hits, slots, cidx, pos, size)
    }
    assert got == expected
    # negative and beyond-registry indices report not-found
    assert not found[0] and not found[1] and not found[-1]


# --- duties service ---------------------------------------------------------


def test_our_indices_pubkey_index_matches_scan(monkeypatch):
    """Satellite: `_our_indices` resolves through the resident columns'
    pubkey_index(); column-less states keep the O(n) scan."""
    h, vc = _vc_setup()
    st = h.chain.head_state
    ds = vc.duties_service
    assert _columns(st) is not None  # the fast path is actually live
    via_columns = ds._our_indices(st)
    assert via_columns == ds._our_indices_scan(st)
    assert sorted(via_columns) == list(range(16))
    # column-less fallback: disabling residency must not change results
    monkeypatch.setenv("LIGHTHOUSE_TPU_RESIDENT_COLUMNS", "0")
    assert _columns(st) is None
    assert ds._our_indices(st) == via_columns


def test_duties_bulk_fetch_matches_scan(monkeypatch):
    h, vc = _vc_setup()
    ds = vc.duties_service
    bulk = ds.attester_duties(0)
    monkeypatch.setenv("LIGHTHOUSE_TPU_VC_BATCH", "0")
    ds._duty_cache.clear()
    scan = ds.attester_duties(0)
    assert bulk == scan
    # pagination must not change the result set or its order
    monkeypatch.delenv("LIGHTHOUSE_TPU_VC_BATCH", raising=False)
    monkeypatch.setenv("LIGHTHOUSE_TPU_VC_DUTIES_PAGE", "3")
    ds._duty_cache.clear()
    assert ds.attester_duties(0) == scan


def test_http_duties_route_matches_vc_bulk_fetch():
    """The Beacon API duties route and the in-process bulk surface
    resolve through the same epoch duty table — identical assignments."""
    from lighthouse_tpu.http_api import BeaconApi

    h, vc = _vc_setup()
    api = BeaconApi(h.chain)
    rows = api.attester_duties(0, list(range(16)))["data"]
    local = vc.node.attester_duties(0, list(range(16)))
    local.sort(
        key=lambda d: (d.slot, d.committee_index, d.committee_position)
    )
    assert [
        (
            int(r["validator_index"]),
            int(r["slot"]),
            int(r["committee_index"]),
            int(r["validator_committee_index"]),
            int(r["committee_length"]),
        )
        for r in rows
    ] == [
        (
            d.validator_index,
            d.slot,
            d.committee_index,
            d.committee_position,
            d.committee_size,
        )
        for d in local
    ]


# --- whole-pipeline differential -------------------------------------------


def test_vc_batch_pipeline_matches_per_key_oracle(monkeypatch):
    """Tentpole oracle: drive two identical chains for 2 epochs, one VC
    on the batch pipeline and one forced per-key via the kill switch.
    Chain head roots (covering every published block / attestation /
    sync message bit-for-bit), finality, and the slashing-DB end state
    must be identical."""
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    results = {}
    for mode in ("1", "0"):
        monkeypatch.setenv("LIGHTHOUSE_TPU_VC_BATCH", mode)
        bls.set_backend("fake_crypto")
        h = BeaconChainHarness(spec, E, validator_count=16)
        vc = ValidatorClient(h.chain, h.keypairs, spec, E)
        roots = []
        for slot in range(1, 2 * E.SLOTS_PER_EPOCH + 1):
            h.slot_clock.set_slot(slot)
            vc.on_slot(slot)
            roots.append(bytes(h.chain.head_root))
        db = vc.store.slashing_db._conn
        dump = (
            db.execute(
                "SELECT validator_id, slot, signing_root FROM signed_blocks"
                " ORDER BY validator_id, slot"
            ).fetchall(),
            db.execute(
                "SELECT validator_id, source_epoch, target_epoch,"
                " signing_root FROM signed_attestations"
                " ORDER BY validator_id, target_epoch"
            ).fetchall(),
        )
        results[mode] = (roots, dump, h.finalized_epoch)
    assert results["1"] == results["0"]


def test_vc_duty_cycle_trace_root_recorded():
    from lighthouse_tpu.metrics import REGISTRY

    h, vc = _vc_setup(validator_count=8)

    def _traces():
        for line in REGISTRY.expose().splitlines():
            if line.startswith("trace_collector_traces_total") and (
                'root="vc_duty_cycle"' in line
            ):
                return float(line.rsplit(" ", 1)[1])
        return 0.0

    before = _traces()
    h.slot_clock.set_slot(1)
    vc.on_slot(1)
    assert _traces() > before


# --- batched slashing protection -------------------------------------------


def test_slashing_batch_matches_sequential_hostile_fuzz():
    """Hostile mix — lowball targets, surrounds, surrounded-by, double
    votes, idempotent re-signs, source>target, unregistered keys — the
    batch's per-entry refusals and the DB end state equal sequential
    per-key calls in entry order."""
    rng = random.Random(0x5EED)
    pks = [bytes([i + 1]) * 48 for i in range(8)]
    db_seq, db_batch = SlashingDatabase(), SlashingDatabase()
    for db in (db_seq, db_batch):
        for pk in pks[:7]:  # pks[7] stays unregistered
            db.register_validator(pk)
    for _round in range(6):
        entries = []
        for _ in range(25):
            entries.append(
                (
                    rng.choice(pks),
                    rng.randrange(0, 14),  # sometimes > target
                    rng.randrange(0, 12),
                    bytes([rng.randrange(4)]) * 32,  # forced collisions
                )
            )
        seq_statuses = []
        for pk, s, t, root in entries:
            try:
                db_seq.check_and_insert_attestation(pk, s, t, root)
                seq_statuses.append(None)
            except NotSafe as e:
                seq_statuses.append(str(e))
        batch_statuses = [
            None if st is None else str(st)
            for st in db_batch.check_and_insert_attestations_batch(entries)
        ]
        assert batch_statuses == seq_statuses
    q = (
        "SELECT validator_id, source_epoch, target_epoch, signing_root"
        " FROM signed_attestations ORDER BY validator_id, target_epoch"
    )
    assert (
        db_seq._conn.execute(q).fetchall()
        == db_batch._conn.execute(q).fetchall()
    )


def test_slashing_batch_atomic_on_crash(monkeypatch):
    """Satellite: an interrupted batch leaves the DB at the pre-batch
    watermark — even when the crash lands AFTER part of the batch was
    staged into sqlite."""
    db = SlashingDatabase()
    pk = b"\xaa" * 48
    db.register_validator(pk)
    db.check_and_insert_attestation(pk, 0, 1, b"\x01" * 32)
    q = "SELECT * FROM signed_attestations ORDER BY target_epoch"
    before = db._conn.execute(q).fetchall()

    real = SlashingDatabase._insert_attestation_rows

    def crash_after_partial_stage(rows):
        real(db, rows[:1])  # first row staged, then the process "dies"
        raise RuntimeError("crash mid-batch")

    monkeypatch.setattr(db, "_insert_attestation_rows", crash_after_partial_stage)
    with pytest.raises(RuntimeError, match="crash mid-batch"):
        db.check_and_insert_attestations_batch(
            [(pk, 1, 2, b"\x02" * 32), (pk, 2, 3, b"\x03" * 32)]
        )
    assert db._conn.execute(q).fetchall() == before
    # the rolled-back entries are still signable afterwards
    monkeypatch.setattr(db, "_insert_attestation_rows", lambda rows: real(db, rows))
    assert db.check_and_insert_attestations_batch(
        [(pk, 1, 2, b"\x02" * 32)]
    ) == [None]


def test_slashing_batch_refuses_only_slashable_entry():
    """Satellite: one slashable message in a batch refuses ONLY that
    message; the rest commit."""
    db = SlashingDatabase()
    pks = [bytes([i + 1]) * 48 for i in range(3)]
    for pk in pks:
        db.register_validator(pk)
        db.check_and_insert_attestation(pk, 2, 3, b"\x0a" * 32)
    statuses = db.check_and_insert_attestations_batch(
        [
            (pks[0], 3, 4, b"\x0b" * 32),  # fine
            (pks[1], 1, 5, b"\x0c" * 32),  # surrounds the (2, 3) vote
            (pks[2], 3, 4, b"\x0d" * 32),  # fine
        ]
    )
    assert statuses[0] is None and statuses[2] is None
    assert isinstance(statuses[1], NotSafe)
    assert "surrounds" in str(statuses[1])
    n = db._conn.execute(
        "SELECT COUNT(*) FROM signed_attestations"
    ).fetchone()[0]
    assert n == 5  # 3 seed rows + the 2 safe entries


# --- keymanager keystore routes at scale ------------------------------------


def _keystore_roundtrip(n: int):
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    vc = ValidatorClient(None, [], spec, E)
    api = KeymanagerApi(vc)
    keystores, passwords, pks = [], [], []
    for i in range(n):
        sk = bls.SecretKey(i + 1)
        pk = bytes(sk.public_key().to_bytes())
        ks = Keystore.encrypt(
            (i + 1).to_bytes(32, "big"), f"pw{i}", pubkey=pk, _fast_kdf=True
        )
        keystores.append(ks.to_json())
        passwords.append(f"pw{i}")
        pks.append(pk)
    out = api.import_keystores(keystores, passwords)
    assert [s["status"] for s in out["data"]] == ["imported"] * n
    assert len(api.list_keystores()["data"]) == n
    # duplicate-add idempotence: re-import reports duplicate, count holds
    again = api.import_keystores(keystores[: min(n, 16)], passwords[: min(n, 16)])
    assert [s["status"] for s in again["data"]] == ["duplicate"] * min(n, 16)
    assert len(api.list_keystores()["data"]) == n
    # full removal round-trip
    out = api.delete_keystores(["0x" + pk.hex() for pk in pks])
    assert [s["status"] for s in out["data"]] == ["deleted"] * n
    assert api.list_keystores()["data"] == []


def test_keymanager_keystore_roundtrip_1k():
    _keystore_roundtrip(1000)


@pytest.mark.slow
def test_keymanager_keystore_roundtrip_10k():
    _keystore_roundtrip(10_000)


def test_keymanager_sign_valid_after_remove_readd():
    """Host crypto: a key removed and re-imported signs the same bytes,
    and the signature still verifies."""
    bls.set_backend("host")
    try:
        kps = bls.interop_keypairs(2)
        spec = replace(minimal_spec(), altair_fork_epoch=0)
        vc = ValidatorClient(None, kps, spec, E)
        api = KeymanagerApi(vc)
        kp = kps[0]
        pk = bytes(kp.pk.to_bytes())
        root = b"\x11" * 32
        sig_before = vc.store.signer_for(pk).sign(root)
        ks = Keystore.encrypt(
            kp.sk.to_bytes(), "pw", pubkey=pk, _fast_kdf=True
        )
        out = api.delete_keystores(["0x" + pk.hex()])
        assert out["data"][0]["status"] == "deleted"
        assert vc.store.signer_for(pk) is None
        out = api.import_keystores([ks.to_json()], ["pw"])
        assert out["data"][0]["status"] == "imported"
        sig_after = vc.store.signer_for(pk).sign(root)
        assert sig_after == sig_before
        assert bls.Signature.from_bytes(sig_after).verify(kp.pk, root)
    finally:
        bls.set_backend("fake_crypto")
