"""Differential fuzz: Pippenger bucketed MSM vs the per-point wNAF oracle.

`crypto/bls12_381/msm.py` is what the RLC batch verifier's soundness rides
on, so it is pinned against `msm_naive` (n independent `pt_mul` ladders —
the pre-Pippenger production path) across the shapes the verifier feeds it:
RLC-sized 64-bit scalars, zero scalars, duplicate points, infinity inputs,
explicit window-size sweeps, and both groups.
"""

import random

import pytest

from lighthouse_tpu.crypto.bls12_381 import (
    FQ,
    FQ2,
    G1_GEN,
    G2_GEN,
    R,
    inf,
    is_inf,
    msm,
    msm_naive,
    pt_eq,
    pt_mul,
)
from lighthouse_tpu.crypto.bls12_381.msm import _signed_digits, window_size

rng = random.Random(0xB10C)


def _points(k, gen, n):
    """n pseudo-random small multiples of the generator (cheap ladders)."""
    return [pt_mul(k, gen, rng.randrange(1, 1 << 20)) for _ in range(n)]


@pytest.mark.parametrize("k,gen", [(FQ, G1_GEN), (FQ2, G2_GEN)], ids=["g1", "g2"])
def test_msm_matches_wnaf_random_sizes(k, gen):
    # random n ∈ {1..257}: below, at, and above the bucketing threshold
    sizes = [1, 2, 3, 7, 8, 9] + (
        [rng.randrange(1, 258) for _ in range(4)] + [257]
        if k is FQ
        else [rng.randrange(10, 65)]  # G2 adds are 3×; keep runtime sane
    )
    for n in sizes:
        pts = _points(k, gen, n)
        ss = [rng.getrandbits(64) for _ in range(n)]  # RLC-sized scalars
        assert pt_eq(k, msm(k, pts, ss), msm_naive(k, pts, ss)), n


@pytest.mark.parametrize("window", [1, 2, 3, 5, 8, 13])
def test_msm_window_sweep(window):
    pts = _points(FQ, G1_GEN, 33)
    ss = [rng.getrandbits(64) for _ in range(33)]
    expect = msm_naive(FQ, pts, ss)
    assert pt_eq(FQ, msm(FQ, pts, ss, window=window), expect)


@pytest.mark.parametrize("k,gen", [(FQ, G1_GEN), (FQ2, G2_GEN)], ids=["g1", "g2"])
def test_msm_zero_scalars_and_infinity_points(k, gen):
    pts = _points(k, gen, 12)
    pts[3] = inf(k)
    pts[7] = inf(k)
    ss = [rng.getrandbits(64) for _ in range(12)]
    ss[0] = 0
    ss[7] = 0  # zero scalar on an infinity point too
    ss[11] = 0
    # force the bucketed path even though only 8 contributors remain
    got = msm(k, pts, ss, window=4)
    assert pt_eq(k, got, msm_naive(k, pts, ss))
    # degenerate: everything vanishes
    assert is_inf(k, msm(k, pts, [0] * 12))
    assert is_inf(k, msm(k, [inf(k)] * 5, [1, 2, 3, 4, 5]))
    assert is_inf(k, msm(k, [], []))


def test_msm_duplicate_points_and_negative_scalars():
    base = _points(FQ, G1_GEN, 4)
    pts = base + base + [base[0]] * 8  # heavy duplication → bucket collisions
    ss = [rng.getrandbits(64) for _ in range(len(pts))]
    assert pt_eq(FQ, msm(FQ, pts, ss), msm_naive(FQ, pts, ss))
    ss_neg = [s if i % 3 else -s for i, s in enumerate(ss)]
    assert pt_eq(FQ, msm(FQ, pts, ss_neg), msm_naive(FQ, pts, ss_neg))


def test_msm_full_width_scalars():
    # order-sized scalars (the verifier only feeds 64-bit, but the seam the
    # Pallas backend slots behind must be width-generic)
    pts = _points(FQ, G1_GEN, 9)
    ss = [rng.randrange(R) for _ in range(9)]
    assert pt_eq(FQ, msm(FQ, pts, ss), msm_naive(FQ, pts, ss))


def test_msm_single_point_equals_pt_mul():
    p = _points(FQ2, G2_GEN, 1)[0]
    s = rng.getrandbits(64)
    assert pt_eq(FQ2, msm(FQ2, [p], [s], window=6), pt_mul(FQ2, p, s))


def test_msm_length_mismatch_raises():
    with pytest.raises(ValueError):
        msm(FQ, [G1_GEN], [1, 2])


def test_signed_digits_reconstruct():
    for _ in range(50):
        c = rng.randrange(1, 13)
        s = rng.getrandbits(rng.randrange(1, 130))
        digits = _signed_digits(s, c)
        half = 1 << (c - 1)
        assert all(-half <= d <= half for d in digits)
        assert sum(d << (c * i) for i, d in enumerate(digits)) == s


def test_window_size_monotone_sane():
    # the heuristic must stay in bounds and grow with n
    assert 1 <= window_size(1, 64) <= 16
    assert window_size(4096, 64) >= window_size(16, 64)
