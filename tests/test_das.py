"""PeerDAS DA subsystem: differential fuzz + directed unit coverage.

The differential spine (ISSUE 16 satellite):
  * erasure extend/recover round-trips bit-exactly from EVERY >=50%
    column-subset shape (contiguous, tail, interleaved, random);
  * the batched cell verifier agrees with the per-cell scalar oracle on
    clean batches AND pinpoints tampered cells/proofs/commitments inside
    a real batch;
  * the store's slot-keyed DA retention index stays equal to a
    brute-force rescan under a fuzzed put/delete workload;
  * the segment-wide blob-KZG bisection attributes the poisoned block
    exactly.

Scenario-sized spec (DasTestnetEthSpec: 64 field elements over 16
columns) so the whole file is host-Fr math in test time; the arithmetic
(50% threshold, custody/sampling disjointness) is size-independent.
"""

import random
from types import SimpleNamespace

import pytest

from lighthouse_tpu.beacon_chain.chain import BeaconChain
from lighthouse_tpu.beacon_chain.data_availability import (
    AvailabilityCheckError,
    DataAvailabilityChecker,
    InvalidComponentsError,
    MissingComponentsError,
)
from lighthouse_tpu.crypto.kzg import FR_MODULUS, Kzg, KzgError, TrustedSetup
from lighthouse_tpu.das import (
    ErasureError,
    SamplingEngine,
    blobs_from_matrix,
    build_data_column_sidecars,
    cell_point_index,
    cell_to_fr,
    cells_from_extended,
    column_subnet,
    compute_cells_and_proofs,
    custody_columns,
    extend_evals,
    fr_to_cell,
    recover_extended,
    recover_matrix,
    sidecar_cells,
    verify_cell_kzg_proof,
    verify_cell_kzg_proof_batch,
    verify_data_column_sidecar,
    verify_data_column_sidecars,
)
from lighthouse_tpu.das.erasure import column_natural_positions
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.store import DBColumn, HotColdDB, MemoryStore
from lighthouse_tpu.testing.testnet import DasTestnetEthSpec as E
from lighthouse_tpu.types.containers import build_types

T = build_types(E)
FE = E.FIELD_ELEMENTS_PER_BLOB
COLS = E.NUMBER_OF_COLUMNS
HALF = COLS // 2


def _counter(name, **labels):
    return REGISTRY.counter(name).value(**labels)


def _blob(seed: int) -> bytes:
    rng = random.Random(seed)
    return b"".join(
        rng.randrange(FR_MODULUS).to_bytes(32, "big") for _ in range(FE)
    )


@pytest.fixture(scope="module")
def kzg():
    return Kzg(TrustedSetup.insecure_dev(FE))


@pytest.fixture(scope="module")
def blobs():
    return [_blob(11), _blob(12)]


@pytest.fixture(scope="module")
def signed_block(kzg, blobs):
    body = T.BeaconBlockBodyDeneb(
        blob_kzg_commitments=[kzg.blob_to_kzg_commitment(b) for b in blobs]
    )
    block = T.BeaconBlockDeneb(slot=5, proposer_index=3, body=body)
    return T.SignedBeaconBlockDeneb(message=block, signature=b"\x00" * 96)


@pytest.fixture(scope="module")
def sidecars(signed_block, blobs, kzg):
    return build_data_column_sidecars(signed_block, blobs, kzg, E)


@pytest.fixture(scope="module")
def block_root(signed_block):
    return signed_block.message.hash_tree_root()


# -- erasure round trip --------------------------------------------------------


def test_extend_prefix_is_bit_exact():
    evals = [random.Random(1).randrange(FR_MODULUS) for _ in range(FE)]
    ext = extend_evals(evals)
    assert len(ext) == 2 * FE
    assert ext[:FE] == evals


def test_extend_rejects_non_power_of_two():
    with pytest.raises(ErasureError):
        extend_evals([1, 2, 3])


def test_column_positions_partition_the_domain():
    n2 = 2 * FE
    seen = sorted(
        p for c in range(COLS) for p in column_natural_positions(c, COLS, n2)
    )
    assert seen == list(range(n2))


@pytest.mark.parametrize(
    "pattern",
    ["contiguous", "tail", "interleaved", "random3", "random4", "random5"],
)
def test_recover_round_trip_from_any_half(pattern):
    """Any exactly-50% column subset recovers the extended vector
    bit-identically — the acceptance criterion's fuzz clause."""
    evals = [random.Random(2).randrange(FR_MODULUS) for _ in range(FE)]
    ext = extend_evals(evals)
    cells = cells_from_extended(ext, COLS)
    if pattern == "contiguous":
        keep = list(range(HALF))
    elif pattern == "tail":
        keep = list(range(HALF, COLS))
    elif pattern == "interleaved":
        keep = list(range(0, COLS, 2))
    else:
        keep = random.Random(int(pattern[-1])).sample(range(COLS), HALF)
    known = {c: cells[c] for c in keep}
    assert recover_extended(known, COLS) == ext


def test_recover_below_threshold_raises():
    evals = [random.Random(3).randrange(FR_MODULUS) for _ in range(FE)]
    cells = cells_from_extended(extend_evals(evals), COLS)
    known = {c: cells[c] for c in range(HALF - 1)}
    with pytest.raises(ErasureError, match="need >="):
        recover_extended(known, COLS)


def test_recover_rejects_inconsistent_columns():
    """With MORE than 50% supplied the data is over-determined: a single
    corrupted value violates the degree bound and must be detected (at
    exactly 50% any values interpolate — there is nothing to check)."""
    evals = [random.Random(4).randrange(FR_MODULUS) for _ in range(FE)]
    cells = cells_from_extended(extend_evals(evals), COLS)
    known = {c: list(cells[c]) for c in range(HALF + 1)}
    known[0][0] = (known[0][0] + 1) % FR_MODULUS
    with pytest.raises(ErasureError, match="blob degree"):
        recover_extended(known, COLS)


def test_recover_rejects_malformed_column():
    evals = [random.Random(5).randrange(FR_MODULUS) for _ in range(FE)]
    cells = cells_from_extended(extend_evals(evals), COLS)
    known = {c: cells[c] for c in range(HALF)}
    known[0] = known[0][:-1]  # truncated column
    with pytest.raises(ErasureError, match="malformed"):
        recover_extended(known, COLS)
    known = {c: cells[c] for c in range(HALF)}
    known[COLS] = known.pop(0)  # out-of-range column index
    with pytest.raises(ErasureError, match="malformed"):
        recover_extended(known, COLS)


# -- batched verifier vs scalar oracle ----------------------------------------


def _batch_items(blobs, kzg):
    items = []
    for blob in blobs:
        cells, proofs, commitment = compute_cells_and_proofs(blob, kzg, COLS)
        items.extend(
            (commitment, j, cells[j], proofs[j]) for j in range(COLS)
        )
    return items


def test_batched_matches_oracle_on_clean_batch(blobs, kzg):
    items = _batch_items(blobs, kzg)
    assert len(items) == 2 * COLS
    assert verify_cell_kzg_proof_batch(items, kzg) is True
    for c, j, cell, proof in items:
        assert verify_cell_kzg_proof(c, j, cell, proof, kzg) is True


def _tamper_cell(cell: bytes) -> bytes:
    vals = cell_to_fr(cell)
    vals[0] = (vals[0] + 1) % FR_MODULUS
    return fr_to_cell(vals)


@pytest.mark.parametrize("what", ["cell", "proof", "commitment"])
def test_tamper_rejected_inside_a_real_batch(blobs, kzg, what):
    """One tampered item fails the WHOLE batch; the scalar oracle then
    pinpoints exactly the tampered index — the attribution contract the
    network layer's bisection relies on."""
    items = _batch_items(blobs, kzg)
    k = len(items) // 2
    c, j, cell, proof = items[k]
    if what == "cell":
        items[k] = (c, j, _tamper_cell(cell), proof)
    elif what == "proof":
        items[k] = (c, j, cell, items[k + 1][3])
    else:
        items[k] = (items[0][0], j, cell, proof)
    assert verify_cell_kzg_proof_batch(items, kzg) is False
    verdicts = [
        verify_cell_kzg_proof(ci, ji, celli, proofi, kzg)
        for ci, ji, celli, proofi in items
    ]
    assert verdicts[k] is False
    assert all(v for i, v in enumerate(verdicts) if i != k)


def test_non_canonical_cell_raises_not_false(blobs, kzg):
    items = _batch_items(blobs, kzg)
    c, j, cell, proof = items[0]
    bad = b"\xff" * len(cell)
    with pytest.raises(KzgError):
        verify_cell_kzg_proof_batch([(c, j, bad, proof)], kzg)
    with pytest.raises(KzgError):
        verify_cell_kzg_proof(c, j, bad, proof, kzg)


def test_cell_point_index_deterministic_and_in_range(blobs, kzg):
    cells, _proofs, commitment = compute_cells_and_proofs(blobs[0], kzg, COLS)
    fe = len(cells[0]) // 32
    for j in (0, COLS - 1):
        k = cell_point_index(commitment, j, cells[j])
        assert 0 <= k < fe
        assert k == cell_point_index(commitment, j, cells[j])


# -- sidecar assembly / structural gate / matrix recovery ---------------------


def test_build_verify_and_ssz_round_trip(sidecars, kzg):
    assert len(sidecars) == COLS
    verify_data_column_sidecars(sidecars, kzg, E)
    for sc in sidecars:
        verify_data_column_sidecar(sc, E)
        rt = T.DataColumnSidecar.deserialize(sc.serialize())
        assert rt.hash_tree_root() == sc.hash_tree_root()


def test_blobless_block_has_no_columns(kzg):
    body = T.BeaconBlockBodyDeneb()
    blk = T.BeaconBlockDeneb(slot=1, body=body)
    signed = T.SignedBeaconBlockDeneb(message=blk, signature=b"\x00" * 96)
    assert build_data_column_sidecars(signed, [], kzg, E) == []


def test_sidecar_structural_rejects(sidecars):
    sc = sidecars[0]
    oob = T.DataColumnSidecar(
        index=COLS,
        column=list(sc.column),
        kzg_commitments=list(sc.kzg_commitments),
        kzg_proofs=list(sc.kzg_proofs),
        signed_block_header=sc.signed_block_header,
        kzg_commitments_inclusion_proof=list(
            sc.kzg_commitments_inclusion_proof
        ),
    )
    with pytest.raises(ValueError, match="out of range"):
        verify_data_column_sidecar(oob, E)
    short = T.DataColumnSidecar(
        index=0,
        column=list(sc.column),
        kzg_commitments=list(sc.kzg_commitments),
        kzg_proofs=list(sc.kzg_proofs)[:1],
        signed_block_header=sc.signed_block_header,
        kzg_commitments_inclusion_proof=list(
            sc.kzg_commitments_inclusion_proof
        ),
    )
    with pytest.raises(ValueError, match="mismatch"):
        verify_data_column_sidecar(short, E)
    branch = [bytes(h) for h in sc.kzg_commitments_inclusion_proof]
    branch[0] = bytes(32)
    broken = T.DataColumnSidecar(
        index=0,
        column=list(sc.column),
        kzg_commitments=list(sc.kzg_commitments),
        kzg_proofs=list(sc.kzg_proofs),
        signed_block_header=sc.signed_block_header,
        kzg_commitments_inclusion_proof=branch,
    )
    with pytest.raises(ValueError, match="inclusion proof"):
        verify_data_column_sidecar(broken, E)


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_recover_matrix_round_trip_bit_exact(sidecars, blobs, seed):
    """Any 50% sidecar subset rebuilds every cell of every column AND the
    original blobs, bit-identically."""
    keep = random.Random(seed).sample(range(COLS), HALF)
    before = _counter("das_reconstructions_total")
    matrix = recover_matrix([sidecars[c] for c in keep], E)
    assert _counter("das_reconstructions_total") == before + 1
    assert sorted(matrix) == list(range(COLS))
    for sc in sidecars:
        for row, cell in enumerate(sc.column):
            assert matrix[int(sc.index)][row] == bytes(cell)
    assert blobs_from_matrix(matrix, E) == blobs


def test_recover_matrix_below_threshold_raises(sidecars):
    with pytest.raises(ErasureError):
        recover_matrix(sidecars[: HALF - 1], E)
    with pytest.raises(ValueError, match="no column sidecars"):
        recover_matrix([], E)


def test_sidecar_cells_shape(sidecars):
    items = sidecar_cells(sidecars[3])
    assert len(items) == 2
    for commitment, j, cell, proof in items:
        assert j == 3
        assert len(cell) == 32 * E.field_elements_per_cell()
        assert len(commitment) == 48 and len(proof) == 48


# -- custody + sampling --------------------------------------------------------


def test_custody_deterministic_distinct_in_range():
    a = custody_columns(b"\x01" * 32, E.CUSTODY_REQUIREMENT, COLS)
    assert a == custody_columns(b"\x01" * 32, E.CUSTODY_REQUIREMENT, COLS)
    assert len(a) == E.CUSTODY_REQUIREMENT == len(set(a))
    assert all(0 <= c < COLS for c in a)
    assert list(a) == sorted(a)
    # saturating: asking for more than exists customies everything
    assert custody_columns(b"\x02" * 32, COLS + 5, COLS) == tuple(range(COLS))
    # different node ids diverge (sha256 walk, not a modular range)
    assert a != custody_columns(b"\x03" * 32, E.CUSTODY_REQUIREMENT, COLS)


def test_column_subnet_bounded():
    for j in range(COLS):
        assert 0 <= column_subnet(j, E) < E.DATA_COLUMN_SIDECAR_SUBNET_COUNT


def test_select_samples_deterministic_non_custody(block_root):
    eng = SamplingEngine(b"\x07" * 32, E)
    picks = eng.select_samples(block_root)
    assert picks == eng.select_samples(block_root)
    assert len(picks) == E.SAMPLES_PER_SLOT
    assert list(picks) == sorted(picks)
    assert not set(picks) & set(eng.custody)
    # a different root re-rolls the choice (deterministic per-root, not fixed)
    other = eng.select_samples(b"\xaa" * 32)
    assert other != picks or eng.select_samples(b"\xbb" * 32) != picks


def test_sampling_verdict_under_withholding(sidecars, block_root):
    eng = SamplingEngine(b"\x07" * 32, E)
    picks = eng.select_samples(block_root)
    withheld = {picks[0]}
    asked = []

    def fetch(col):
        asked.append(col)
        return None if col in withheld else sidecars[col]

    fail_before = _counter("das_sampling_results_total", verdict="failure")
    ok, fetched = eng.sample(block_root, have=set(), fetch=fetch)
    assert ok is False
    # every sample is still attempted after the miss (extras count toward
    # reconstruction) and the served ones come back
    assert asked == list(picks)
    assert [int(sc.index) for sc in fetched] == [c for c in picks if c not in withheld]
    assert _counter("das_sampling_results_total", verdict="failure") == fail_before + 1

    ok_before = _counter("das_sampling_results_total", verdict="success")
    ok2, fetched2 = eng.sample(block_root, have=set(picks), fetch=fetch)
    assert ok2 is True and fetched2 == []
    assert len(asked) == len(picks)  # pre-staged columns skip the network
    assert _counter("das_sampling_results_total", verdict="success") == ok_before + 1


# -- DA checker routes ---------------------------------------------------------


def _checker(kzg, custody=None):
    return DataAvailabilityChecker(kzg, E, custody=custody)


def test_full_column_route(kzg, signed_block, sidecars, block_root):
    chk = _checker(kzg)
    assert chk.put_block(block_root, signed_block, slot=5).available is False
    out = chk.put_columns(block_root, list(sidecars), slot=5)
    assert out.available is True
    assert [int(sc.index) for sc in out.columns] == list(range(COLS))
    chk.pop(block_root)
    assert not chk.has_pending(block_root)


def test_reconstruction_route_promotes_to_full(
    kzg, signed_block, sidecars, block_root
):
    chk = _checker(kzg)
    chk.put_block(block_root, signed_block, slot=5)
    keep = random.Random(31).sample(range(COLS), HALF)
    before = _counter("das_reconstructions_total")
    out = chk.put_columns(block_root, [sidecars[c] for c in keep], slot=5)
    assert out.available is True
    assert _counter("das_reconstructions_total") == before + 1
    assert len(out.columns) == COLS
    # the rebuilt sidecars carry the ORIGINAL cells, bit-exact
    by_index = {int(sc.index): sc for sc in out.columns}
    for sc in sidecars:
        rebuilt = by_index[int(sc.index)]
        assert [bytes(c) for c in rebuilt.column] == [
            bytes(c) for c in sc.column
        ]
    verify_data_column_sidecars(out.columns, kzg, E)


def test_custody_plus_sampling_route(kzg, signed_block, sidecars, block_root):
    custody = custody_columns(b"\x09" * 32, E.CUSTODY_REQUIREMENT, COLS)
    chk = _checker(kzg, custody=custody)
    chk.put_block(block_root, signed_block, slot=5)
    out = chk.put_columns(
        block_root, [sidecars[c] for c in custody], slot=5
    )
    assert out.available is False  # custody staged, no sampling verdict yet
    assert chk.sampling_pending(block_root)
    out = chk.set_sampling_result(block_root, True, slot=5)
    assert out.available is True
    assert sorted(int(sc.index) for sc in out.columns) == sorted(custody)
    assert not chk.sampling_pending(block_root)


def test_sub_threshold_without_custody_stays_pending(
    kzg, signed_block, sidecars, block_root
):
    chk = _checker(kzg)  # no custody configured -> needs >=50%
    chk.put_block(block_root, signed_block, slot=5)
    out = chk.put_columns(block_root, sidecars[: HALF - 1], slot=5)
    assert out.available is False
    # even a positive sampling verdict cannot substitute for custody
    assert chk.set_sampling_result(block_root, True, slot=5).available is False


def test_blob_route_and_taxonomy(kzg, blobs, signed_block, block_root):
    commitments = [bytes(c) for c in signed_block.message.body.blob_kzg_commitments]
    scs = [
        SimpleNamespace(
            index=i,
            blob=b,
            kzg_commitment=c,
            kzg_proof=kzg.compute_blob_kzg_proof(b, c),
        )
        for i, (b, c) in enumerate(zip(blobs, commitments))
    ]
    chk = _checker(kzg)
    chk.put_block(block_root, signed_block, slot=5)
    out = chk.put_blobs(block_root, scs, slot=5)
    assert out.available is True and len(out.blobs) == len(blobs)

    # MissingComponentsError: locally unverifiable, never a REJECT
    with pytest.raises(MissingComponentsError):
        _checker(None).put_blobs(block_root, scs, slot=5)
    assert issubclass(MissingComponentsError, AvailabilityCheckError)
    assert issubclass(InvalidComponentsError, AvailabilityCheckError)
    assert issubclass(AvailabilityCheckError, ValueError)


def test_wrong_root_header_is_invalid_components(kzg, sidecars):
    with pytest.raises(InvalidComponentsError, match="does not root"):
        _checker(kzg).put_columns(b"\x00" * 32, sidecars[:1], slot=5)


def test_finality_watermark_refuses_stale_components(
    kzg, signed_block, sidecars, block_root
):
    """prune_before sets a watermark; nothing behind it can be staged —
    an in-flight sampling fetch racing the finality prune must not
    resurrect the entry (block slot is 5 here)."""
    chk = _checker(kzg)
    chk.prune_before(100)
    assert chk.put_block(block_root, signed_block, slot=200).available is False
    assert not chk.has_pending(block_root)
    assert chk.put_columns(block_root, sidecars[:2], slot=200).available is False
    assert not chk.has_pending(block_root)
    # a verdict alone NEVER creates an entry
    assert chk.set_sampling_result(b"\x42" * 32, True, slot=200).available is False
    assert not chk.has_pending(b"\x42" * 32)


def test_prune_before_drops_by_block_slot_and_activity(
    kzg, signed_block, sidecars, block_root
):
    chk = _checker(kzg)
    chk.put_block(block_root, signed_block, slot=50)  # block slot is 5
    other = b"\x33" * 32
    chk._pending[other] = type(chk._pending[block_root])()  # blockless entry
    chk._pending[other].inserted_at_slot = 3
    chk.prune_before(4)
    assert chk.has_pending(block_root)  # block slot 5 >= 4
    assert not chk.has_pending(other)  # inserted at 3 < 4
    chk.prune_before(6)
    assert not chk.has_pending(block_root)  # block slot 5 < 6, despite slot=50


# -- segment-wide blob KZG coalescing -----------------------------------------


def _segment_groups(kzg, n_blocks=4):
    groups = []
    for b in range(n_blocks):
        blob = _blob(100 + b)
        commitment = kzg.blob_to_kzg_commitment(blob)
        sc = SimpleNamespace(
            index=0,
            blob=blob,
            kzg_commitment=commitment,
            kzg_proof=kzg.compute_blob_kzg_proof(blob, commitment),
        )
        groups.append((bytes([b]) * 32, [sc]))
    return groups


def _bisect(kzg, groups):
    chain_like = SimpleNamespace(
        data_availability_checker=SimpleNamespace(kzg=kzg)
    )
    return BeaconChain._bisect_segment_kzg(chain_like, groups)


def test_segment_bisect_clean_is_one_batch(kzg):
    assert _bisect(kzg, _segment_groups(kzg)) == set()
    assert _bisect(kzg, []) == set()


@pytest.mark.parametrize("bad_at", [0, 2, 3])
def test_segment_bisect_attributes_poisoned_block_exactly(kzg, bad_at):
    groups = _segment_groups(kzg)
    sc = groups[bad_at][1][0]
    sc.kzg_proof = groups[(bad_at + 1) % len(groups)][1][0].kzg_proof
    assert _bisect(kzg, groups) == {groups[bad_at][0]}


def test_segment_bisect_two_bad_blocks(kzg):
    groups = _segment_groups(kzg)
    for bad_at in (1, 3):
        groups[bad_at][1][0].kzg_proof = groups[0][1][0].kzg_proof
    assert _bisect(kzg, groups) == {groups[1][0], groups[3][0]}


# -- store: slot-keyed DA retention index -------------------------------------


def test_da_index_matches_rescan_under_fuzz():
    """The incrementally maintained slot index equals a brute-force scan
    of the stored slot prefixes after any interleaving of puts (including
    re-puts at a NEW slot) and deletes."""
    db = HotColdDB(MemoryStore(), types=T)
    rng = random.Random(77)
    mirror = {}  # root -> slot
    roots = [bytes([i]) * 32 for i in range(20)]
    for _step in range(300):
        root = rng.choice(roots)
        if rng.random() < 0.3 and root in mirror:
            db._da_delete(DBColumn.DATA_COLUMNS, root)
            del mirror[root]
        else:
            slot = rng.randrange(32)
            db._da_put(
                DBColumn.DATA_COLUMNS,
                root,
                slot,
                slot.to_bytes(8, "little") + b"payload",
            )
            mirror[root] = slot
        cutoff = rng.randrange(34)
        expect = sorted(
            (r, s) for r, s in mirror.items() if s < cutoff
        )
        got = sorted(db.data_column_entries_before(cutoff))
        assert got == expect
    assert sorted(db.data_column_entries()) == sorted(mirror.items())


def test_da_index_lazy_rebuild_from_prefixes():
    """A DB opened over a pre-existing store rebuilds the index from the
    8-byte prefixes alone — no sidecar decode."""
    hot = MemoryStore()
    db = HotColdDB(hot, types=T)
    for i, slot in enumerate([3, 9, 9, 17]):
        db.hot.put(  # bypass _da_put: simulate a pre-index database
            DBColumn.BLOB_SIDECARS,
            bytes([i]) * 32,
            slot.to_bytes(8, "little") + b"x",
        )
    assert sorted(db.blob_sidecar_entries_before(10)) == [
        (bytes([0]) * 32, 3),
        (bytes([1]) * 32, 9),
        (bytes([2]) * 32, 9),
    ]
    db._da_delete(DBColumn.BLOB_SIDECARS, bytes([1]) * 32)
    assert sorted(db.blob_sidecar_entries_before(10)) == [
        (bytes([0]) * 32, 3),
        (bytes([2]) * 32, 9),
    ]


def test_data_column_store_round_trip(sidecars, block_root):
    db = HotColdDB(MemoryStore(), types=T)
    db.put_data_column_sidecars(block_root, sidecars[:3])
    got = db.get_data_column_sidecars(block_root)
    assert [sc.hash_tree_root() for sc in got] == [
        sc.hash_tree_root() for sc in sidecars[:3]
    ]
    slot = int(sidecars[0].signed_block_header.message.slot)
    assert db.data_column_entries_before(slot + 1) == [(block_root, slot)]
    assert db.data_column_entries_before(slot) == []
    db.delete_data_column_sidecars(block_root)
    assert db.get_data_column_sidecars(block_root) == []
    assert db.data_column_entries() == []
