"""Standalone metrics server, system health, monitoring push, discovery.

Covers the http_metrics crate analog (/metrics text exposition on its own
port), common/system_health, common/monitoring_api (push payload shape +
failure isolation), and the discv5-analog discovery layer with a
standalone boot node (boot_node crate)."""

import json
import time
import urllib.request

from lighthouse_tpu.metrics import REGISTRY, inc_counter, set_gauge
from lighthouse_tpu.metrics.monitoring import MonitoringService
from lighthouse_tpu.metrics.server import MetricsServer
from lighthouse_tpu.metrics.system_health import observe_system_health, system_health
from lighthouse_tpu.network.discovery import BootNode, DiscoveryService, Enr


def _get(url: str):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode()


def test_metrics_server_exposition():
    inc_counter("test_obs_requests_total", amount=3)
    set_gauge("test_obs_queue_depth", 7, queue="gossip")
    srv = MetricsServer().start()
    try:
        code, body = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert code == 200
        assert "test_obs_requests_total 3" in body
        assert 'test_obs_queue_depth{queue="gossip"} 7' in body
        # scrape-time system gauges refreshed
        assert "system_cpu_cores" in body
        code, body = _get(f"http://127.0.0.1:{srv.port}/health")
        assert (code, body) == (200, "OK")
    finally:
        srv.stop()


def test_system_health_snapshot():
    h = system_health()
    assert h.total_memory_bytes > 0
    assert h.cpu_cores >= 1
    assert h.disk_bytes_total > 0
    observe_system_health()
    assert REGISTRY.gauge("system_cpu_cores").value() >= 1


def test_monitoring_push_payload_and_failure_isolation():
    sent = []

    class _Store:
        def pubkeys(self):
            return [b"\x01" * 48, b"\x02" * 48]

    svc = MonitoringService(
        "http://example.invalid/api",
        validator_store=_Store(),
        sender=lambda ep, payload: sent.append((ep, payload)),
    )
    svc.send()
    assert len(sent) == 1
    records = json.loads(sent[0][1])
    assert records[0]["process"] == "validator"
    assert records[0]["validator_total"] == 2
    assert records[0]["client_name"] == "lighthouse_tpu"

    # a raising sender must not propagate (monitoring never kills the node)
    def boom(ep, payload):
        raise ConnectionError("no egress")

    svc.sender = boom
    svc.send()  # no raise


def test_discovery_bootstrap_via_boot_node():
    boot = BootNode().start()
    a = DiscoveryService(tcp_port=9001, bootnodes=[boot.enr()]).start()
    b = DiscoveryService(tcp_port=9002, bootnodes=[boot.enr()]).start()
    try:
        # registering round: each node queries the bootnode (which learns it)
        a.discover()
        b.discover()
        # now A can find B through the bootnode's table
        found = a.discover()
        ports = {e.tcp_port for e in found}
        assert 9002 in ports
        assert b.ping(a.local_enr)
    finally:
        a.stop()
        b.stop()
        boot.stop()


def test_discovery_subnet_predicates_and_seq():
    boot = BootNode().start()
    a = DiscoveryService(tcp_port=9101, bootnodes=[boot.enr()]).start()
    b = DiscoveryService(tcp_port=9102, bootnodes=[boot.enr()]).start()
    try:
        b.update_subnets([3, 7])
        assert b.local_enr.seq == 2
        a.discover()
        b.discover()  # b registers its subnet-bearing record
        hits = a.discover(subnet=7)
        assert any(e.tcp_port == 9102 for e in hits)
        assert not any(e.tcp_port == 9102 for e in a.discover(subnet=5))
    finally:
        a.stop()
        b.stop()
        boot.stop()


def test_banned_peer_cannot_reregister():
    """peerdb semantics: a ban survives redial — add() refuses, so neither
    inbound registration nor discovery reconnects can mint a fresh
    unbanned identity for the same peer id."""
    from lighthouse_tpu.network import BAN_THRESHOLD, Peer, PeerManager

    pm = PeerManager()
    p = Peer(host="127.0.0.1", port=9300, client=None)
    assert pm.add(p)
    pm.report(p.peer_id, BAN_THRESHOLD)  # drive to ban
    assert pm.is_banned(p.peer_id)
    fresh = Peer(host="127.0.0.1", port=9300, client=None)
    assert not pm.add(fresh)
    assert pm.is_banned(p.peer_id)
    assert fresh not in pm.peers()


def test_enr_roundtrip_and_stale_eviction():
    e = Enr(node_id="ab", ip="127.0.0.1", udp_port=1, tcp_port=2,
            fork_digest="deadbeef", seq=3, subnets=[1])
    assert Enr.from_dict(e.to_dict()) == e

    d = DiscoveryService(tcp_port=1)
    d.add_record(e)
    assert d.records()
    d._last_seen["ab"] = time.monotonic() - DiscoveryService.RECORD_TTL - 1
    d.maintain()
    assert not d.records()
    d.stop()


def test_tracing_spans_record_metrics_and_parentage():
    """Spans time into the metrics registry, know their parents, and the
    import hot path produces a block_import > state_transition tree."""
    from lighthouse_tpu.metrics import REGISTRY
    from lighthouse_tpu.utils.tracing import current_span, span, traced

    with span("outer") as outer:
        assert current_span() is outer
        with span("inner") as inner:
            assert inner.parent is outer
        assert current_span() is outer
    assert current_span() is None
    assert outer.duration_s is not None
    assert REGISTRY.histogram("trace_span_seconds_outer").count >= 1

    @traced("decorated_work")
    def work():
        return current_span().name

    assert work() == "decorated_work"

    # hot path integration: one imported block records both spans
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    before = REGISTRY.histogram("trace_span_seconds_block_import").count
    h = BeaconChainHarness(
        replace(minimal_spec(), altair_fork_epoch=0), E, validator_count=8
    )
    h.extend_chain(2)
    assert REGISTRY.histogram("trace_span_seconds_block_import").count >= before + 2
    assert REGISTRY.histogram("trace_span_seconds_state_transition").count >= 2
    assert REGISTRY.histogram("trace_span_seconds_fork_choice_on_block").count >= 2
