"""Sync engine: multi-peer range sync, fault injection, backfill, lookups.

The sim-harness suite for network/sync/: real nodes over real TCP, with a
FaultyNetworkService injecting the adversary matrix (drops, truncation,
self-consistent forks, slow responses, stale Status, mid-sync
disconnect). Asserts the engine's contract: sync completes to the honest
head despite the faults, faulty peers are downscored and rotated out, and
recovery paths (retry/backoff, parent lookups, reprocess-queue drains)
leave their counters behind."""

import time
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.network import NetworkService, SyncConfig
from lighthouse_tpu.network.rpc import MAX_REQUEST_BLOCKS, RpcClient, RpcError
from lighthouse_tpu.network.sync import SYNC_STATE_STALLED
from lighthouse_tpu.network.sync.backfill import WATERMARK_KEY
from lighthouse_tpu.testing.sync_faults import FaultPlan, FaultyNetworkService
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


def _harness(slots=0, attest=False):
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    if slots:
        h.extend_chain(slots, attest=attest)  # attest=True where finality matters
    return h


def _fast_cfg(**overrides) -> SyncConfig:
    """Test-speed retry clocks; semantics unchanged."""
    kw = dict(
        backoff_base_s=0.01,
        backoff_max_s=0.05,
        chain_timeout_s=30.0,
        max_parallel_downloads=1,  # deterministic peer rotation in tests
    )
    kw.update(overrides)
    return SyncConfig(**kw)


def _counter(name, **labels):
    return REGISTRY.counter(name).value(**labels)


def _stop_all(*services):
    for s in services:
        s.stop()


# -- multi-peer range sync ----------------------------------------------------


def test_range_sync_multi_peer_completes():
    a = _harness(slots=3 * E.SLOTS_PER_EPOCH)
    b = _harness()
    na = NetworkService(a.chain).start()
    na2 = NetworkService(a.chain).start()  # second server on the same chain
    nb = NetworkService(b.chain, sync_config=_fast_cfg(max_parallel_downloads=4)).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        nb.connect("127.0.0.1", na.port)
        nb.connect("127.0.0.1", na2.port)
        before = _counter("sync_batch_downloads_total", chain="range")
        imported = nb.sync.sync_to_head()
        assert imported == 3 * E.SLOTS_PER_EPOCH
        assert b.chain.head_root == a.chain.head_root
        # 24 slots / 16-slot batches = 2 batches, each downloaded once
        assert _counter("sync_batch_downloads_total", chain="range") >= before + 2
    finally:
        _stop_all(na, na2, nb)


def test_mid_sync_disconnect_retries_on_second_peer():
    a = _harness(slots=6 * E.SLOTS_PER_EPOCH)
    b = _harness()
    faulty = FaultyNetworkService(
        a.chain, FaultPlan(disconnect_after=1)
    ).start()
    honest = NetworkService(a.chain).start()
    nb = NetworkService(b.chain, sync_config=_fast_cfg()).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        # faulty first: deterministic rotation tries it before the honest
        nb.connect("127.0.0.1", faulty.port)
        nb.connect("127.0.0.1", honest.port)
        before = _counter("sync_batch_retries_total", chain="range")
        imported = nb.sync.sync_to_head()
        assert imported == 6 * E.SLOTS_PER_EPOCH
        assert b.chain.head_root == a.chain.head_root
        # the dead peer's batches were retried on the second peer
        assert _counter("sync_batch_retries_total", chain="range") > before
    finally:
        _stop_all(faulty, honest, nb)


def test_flaky_peer_truncated_then_valid_batch_backoff():
    """A lone flaky peer truncates its first batch. The prefix imports
    cleanly, the NEXT batch hits an unknown parent, both roll back, and
    the backoff'd re-download (now honest) completes the sync — the old
    loop stalled forever here."""
    a = _harness(slots=4 * E.SLOTS_PER_EPOCH)
    b = _harness()
    flaky = FaultyNetworkService(a.chain, FaultPlan(truncate_first=1)).start()
    nb = NetworkService(b.chain, sync_config=_fast_cfg()).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", flaky.port)
        before = _counter("sync_batch_retries_total", chain="range")
        imported = nb.sync.sync_with(peer)
        assert imported == 4 * E.SLOTS_PER_EPOCH
        assert b.chain.head_root == a.chain.head_root
        assert _counter("sync_batch_retries_total", chain="range") > before
        # the flaky peer paid for the rollback
        assert nb.peers.get(peer.peer_id).score < 0
    finally:
        _stop_all(flaky, nb)


def test_forked_batches_downscore_and_rotate_peer():
    """One peer serves self-consistent forked batches (pass the download
    hash-chain check, fail import). Sync must still reach the honest head,
    with the forker downscored and its batches re-downloaded elsewhere."""
    a = _harness(slots=4 * E.SLOTS_PER_EPOCH)
    b = _harness()
    forker = FaultyNetworkService(a.chain, FaultPlan(fork_first=100)).start()
    honest = NetworkService(a.chain).start()
    nb = NetworkService(b.chain, sync_config=_fast_cfg()).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        forker_peer = nb.connect("127.0.0.1", forker.port)
        nb.connect("127.0.0.1", honest.port)
        imported = nb.sync.sync_to_head()
        assert imported == 4 * E.SLOTS_PER_EPOCH
        assert b.chain.head_root == a.chain.head_root
        assert nb.peers.get(forker_peer.peer_id).score < 0
    finally:
        _stop_all(forker, honest, nb)


def test_slow_peer_times_out_and_rotates():
    a = _harness(slots=2 * E.SLOTS_PER_EPOCH)
    b = _harness()
    slow = FaultyNetworkService(a.chain, FaultPlan(delay_s=0.6)).start()
    honest = NetworkService(a.chain).start()
    nb = NetworkService(
        b.chain, sync_config=_fast_cfg(batch_timeout_s=0.2)
    ).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        nb.connect("127.0.0.1", slow.port)
        nb.connect("127.0.0.1", honest.port)
        before = _counter("sync_batch_retries_total", chain="range")
        imported = nb.sync.sync_to_head()
        assert imported == 2 * E.SLOTS_PER_EPOCH
        assert b.chain.head_root == a.chain.head_root
        assert _counter("sync_batch_retries_total", chain="range") > before
    finally:
        _stop_all(slow, honest, nb)


def test_stale_status_degrades_gracefully():
    """A peer advertising a head 2 epochs past reality: the phantom
    batches come back empty (legal — slots can be skipped), the chain
    completes at the real head, and the node reports itself stalled
    rather than looping."""
    a = _harness(slots=E.SLOTS_PER_EPOCH)
    b = _harness()
    liar = FaultyNetworkService(
        a.chain, FaultPlan(stale_status_extra=2 * E.SLOTS_PER_EPOCH)
    ).start()
    nb = NetworkService(b.chain, sync_config=_fast_cfg()).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot + 2 * E.SLOTS_PER_EPOCH)
        peer = nb.connect("127.0.0.1", liar.port)
        imported = nb.sync.sync_with(peer)
        assert imported == E.SLOTS_PER_EPOCH
        assert b.chain.head_root == a.chain.head_root
        assert REGISTRY.gauge("sync_state").value() == SYNC_STATE_STALLED
    finally:
        _stop_all(liar, nb)


# -- block lookups -------------------------------------------------------------


def test_unknown_parent_block_recovered_via_parent_lookup():
    """A gossip block 3 deep past our head: attestations for it are held
    in the reprocess queue, the parent lookup walks the missing ancestry
    via blocks_by_root, imports the chain, and the held attestations
    drain into the op pool."""
    a = _harness(slots=E.SLOTS_PER_EPOCH)
    b = _harness()
    na = NetworkService(a.chain).start()
    nb = NetworkService(b.chain, sync_config=_fast_cfg()).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", na.port)
        nb.sync.sync_with(peer)
        assert b.chain.head_root == a.chain.head_root

        # A advances 3 blocks that B never hears about (no publish)
        signed3 = None
        for _ in range(3):
            slot = a.chain.head_state.slot + 1
            _, signed3 = a.add_block_at_slot(slot)
        head_root = a.chain.head_root
        tip_slot = a.chain.head_state.slot
        b.slot_clock.set_slot(tip_slot)

        # attestations for the unknown head arrive FIRST, while B has no
        # peers — they park in the reprocess queue (the lookup they spawn
        # fails harmlessly)
        nb._drop_peer(peer)
        t = b.chain.types
        atts = a.make_unaggregated_attestations(tip_slot, head_root)
        before_pool = b.chain.op_pool.num_attestations()
        for att in atts[:2]:
            # the queue-routed gossip path: deliver → GOSSIP_ATTESTATION
            # lane → batch handler parks the unknown-root attestation
            nb.gossip._deliver(
                nb.topic_att, t.Attestation.serialize_value(att), "test-origin"
            )
        assert nb.processor.drain()
        assert b.chain.op_pool.num_attestations() == before_pool  # held
        assert nb.reprocess._by_block_root  # parked under the unknown root

        # reconnect, then the tip block gossips in: parent unknown →
        # 3-deep ancestor walk → import → reprocess drain
        nb.connect("127.0.0.1", na.port)
        before_started = _counter("sync_lookups_started_total", kind="parent")
        before_drained = _counter("sync_lookup_reprocess_drained_total")
        nb.gossip._deliver(nb.topic_block, signed3.serialize(), "test-origin")

        deadline = time.time() + 10
        while time.time() < deadline:
            if (
                b.chain.fork_choice.contains_block(head_root)
                and b.chain.op_pool.num_attestations() > before_pool
            ):
                break
            time.sleep(0.05)
        assert b.chain.fork_choice.contains_block(head_root)
        assert nb.processor.drain()
        assert b.chain.op_pool.num_attestations() > before_pool
        assert _counter("sync_lookups_started_total", kind="parent") > before_started
        assert _counter("sync_lookup_reprocess_drained_total") >= before_drained + 2
        assert not nb.reprocess._by_block_root  # fully drained
    finally:
        _stop_all(na, nb)


def test_gossip_block_import_drains_held_attestations():
    """The common out-of-order gossip case: the attestation beats its
    block by one hop. The block then imports through the NORMAL gossip
    path (no lookup needed) — the held attestation must still drain."""
    a = _harness(slots=2)
    b = _harness()
    na = NetworkService(a.chain).start()
    nb = NetworkService(b.chain, sync_config=_fast_cfg()).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", na.port)
        nb.sync.sync_with(peer)
        slot = a.chain.head_state.slot + 1
        _, signed = a.add_block_at_slot(slot)
        b.slot_clock.set_slot(slot)
        t = b.chain.types
        att = a.make_unaggregated_attestations(slot, a.chain.head_root)[0]
        before_pool = b.chain.op_pool.num_attestations()
        nb.gossip._deliver(
            nb.topic_att, t.Attestation.serialize_value(att), "test-origin"
        )
        assert nb.processor.drain()
        assert b.chain.op_pool.num_attestations() == before_pool  # held
        # parent known: direct import through the GOSSIP_BLOCK lane
        nb.gossip._deliver(nb.topic_block, signed.serialize(), "test-origin")
        assert nb.processor.drain()
        assert b.chain.op_pool.num_attestations() > before_pool
        assert not nb.reprocess._by_block_root
    finally:
        _stop_all(na, nb)


def test_lookup_inflight_dedup():
    """The same unknown root flooded from many handlers spawns ONE lookup."""
    a = _harness(slots=2)
    b = _harness()
    na = NetworkService(a.chain).start()
    nb = NetworkService(b.chain, sync_config=_fast_cfg()).start()
    try:
        b.slot_clock.set_slot(a.chain.head_state.slot)
        nb.connect("127.0.0.1", na.port)
        before = _counter("sync_lookups_started_total", kind="single")
        root = a.chain.head_root
        started = [nb.sync.on_unknown_block_root(root) for _ in range(5)]
        assert sum(started) <= 1  # dedup'd (or already imported by a race)
        deadline = time.time() + 10
        while time.time() < deadline and not b.chain.fork_choice.contains_block(root):
            time.sleep(0.05)
        assert b.chain.fork_choice.contains_block(root)
        assert _counter("sync_lookups_started_total", kind="single") == before + 1
    finally:
        _stop_all(na, nb)


# -- backfill ------------------------------------------------------------------


def _checkpoint_pair(h):
    """Node B booted from A's finalized checkpoint (state, block)."""
    from lighthouse_tpu.beacon_chain.chain import BeaconChain
    from lighthouse_tpu.store import HotColdDB, MemoryStore
    from lighthouse_tpu.utils.slot_clock import ManualSlotClock

    fin = h.chain.finalized_checkpoint
    block = h.chain._blocks_by_root[fin.root]
    state = h.chain._justified_state_provider(fin.root).copy()
    clock = ManualSlotClock(
        genesis_time=state.genesis_time,
        seconds_per_slot=h.spec.seconds_per_slot,
    )
    chain_b = BeaconChain.from_checkpoint(
        HotColdDB(MemoryStore()), state, block, h.spec, E, clock
    )
    return chain_b, block, clock


def test_backfill_resumes_from_persisted_watermark():
    h = _harness(slots=4 * E.SLOTS_PER_EPOCH, attest=True)
    assert h.finalized_epoch >= 1
    chain_b, anchor_block, clock = _checkpoint_pair(h)
    anchor_slot = int(anchor_block.message.slot)
    na = NetworkService(h.chain).start()
    nb = NetworkService(
        chain_b, sync_config=_fast_cfg(epochs_per_batch=1)
    ).start()
    try:
        clock.set_slot(h.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", na.port)
        nb.sync.sync_with(peer)

        first = nb.sync.backfill(peer, max_batches=1)
        assert 0 < first < anchor_slot - 1  # partial: one 8-slot window
        assert chain_b.store.get_meta(WATERMARK_KEY) is not None

        # a later run resumes from the watermark instead of re-walking
        second = nb.sync.backfill(peer)
        assert first + second == anchor_slot - 1
        # full chain back to slot 1 served from B's store
        r = bytes(anchor_block.message.parent_root)
        walked = 0
        while r != b"\x00" * 32:
            blk = chain_b.store.get_block(r)
            if blk is None:
                break
            walked += 1
            r = bytes(blk.message.parent_root)
        assert walked == anchor_slot - 1
    finally:
        _stop_all(na, nb)


def test_backfill_walks_through_empty_gap_window():
    """A non-finality-style gap wider than one whole window (17 skipped
    slots > the 8-slot window here): the empty window is stepped past
    in memory instead of terminating the walk, and everything below the
    gap still backfills."""
    h = _harness(slots=4)
    # jump the chain across a >2-window gap, then build a short tip
    h.add_block_at_slot(h.chain.head_state.slot + 17)
    h.extend_chain(2)
    head_root = h.chain.head_root
    head_block = h.chain._blocks_by_root[head_root]
    state = h.chain.head_state.copy()
    from lighthouse_tpu.beacon_chain.chain import BeaconChain
    from lighthouse_tpu.store import HotColdDB, MemoryStore
    from lighthouse_tpu.utils.slot_clock import ManualSlotClock

    clock = ManualSlotClock(
        genesis_time=state.genesis_time,
        seconds_per_slot=h.spec.seconds_per_slot,
    )
    chain_b = BeaconChain.from_checkpoint(
        HotColdDB(MemoryStore()), state, head_block, h.spec, E, clock
    )
    na = NetworkService(h.chain).start()
    nb = NetworkService(
        chain_b, sync_config=_fast_cfg(epochs_per_batch=1)
    ).start()
    try:
        clock.set_slot(h.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", na.port)
        stored = nb.sync.backfill(peer)
        # every pre-anchor block (4 + gap block + 1 of the 2-tip) landed
        assert stored == 6
        r = bytes(head_block.message.parent_root)
        walked = 0
        while r != b"\x00" * 32:
            blk = chain_b.store.get_block(r)
            if blk is None:
                break
            walked += 1
            r = bytes(blk.message.parent_root)
        assert walked == 6
    finally:
        _stop_all(na, nb)


def test_backfill_unlinked_batch_downscores_peer():
    """Garbage/fork spam during backfill is no longer free: a non-empty
    window with zero chain-linked blocks costs the peer an
    invalid-message downscore before the engine gives up on it."""
    h = _harness(slots=4 * E.SLOTS_PER_EPOCH, attest=True)
    chain_b, anchor_block, clock = _checkpoint_pair(h)
    spammer = FaultyNetworkService(h.chain, FaultPlan(fork_first=100)).start()
    nb = NetworkService(chain_b, sync_config=_fast_cfg()).start()
    try:
        clock.set_slot(h.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", spammer.port)
        before = _counter("sync_batch_failures_total", chain="backfill")
        stored = nb.sync.backfill(peer)
        assert stored == 0
        assert nb.peers.get(peer.peer_id).score < 0
        assert _counter("sync_batch_failures_total", chain="backfill") > before
    finally:
        _stop_all(spammer, nb)


# -- RPC server caps (satellite) ----------------------------------------------


def test_rpc_server_clamps_hostile_range_count():
    """A hostile BlocksByRange count is clamped, not served: the response
    covers at most MAX_REQUEST_BLOCKS slots, and the rate-limiter prices
    the clamped work — an immediate repeat is over quota."""
    a = _harness(slots=6)
    na = NetworkService(a.chain).start()
    try:
        client = RpcClient("127.0.0.1", na.port)
        blocks = client.blocks_by_range(
            1, MAX_REQUEST_BLOCKS + 50_000, na.decode_block
        )
        assert [blk.message.slot for blk in blocks] == [1, 2, 3, 4, 5, 6]
        # the clamped request still cost a full bucket of tokens
        with pytest.raises(RpcError):
            client.blocks_by_range(1, MAX_REQUEST_BLOCKS, na.decode_block)
    finally:
        na.stop()
