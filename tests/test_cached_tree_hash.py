"""Incremental tree-hash cache vs full recompute.

Mirrors the reference's cached_tree_hash tests (cache.rs:203-237): dirty
single leaves, growth, shrink-triggered rebuild, and the state-level cache
staying consistent with the from-scratch SSZ root across realistic
mutations and copies."""

import random

import numpy as np
import pytest

from lighthouse_tpu.ssz.cached_tree_hash import (
    BeaconStateHashCache,
    TreeHashCache,
    cached_state_root,
)
from lighthouse_tpu.ssz.merkle import merkleize


def _rand_leaves(rng, n):
    return np.frombuffer(
        bytes(rng.randrange(256) for _ in range(n * 32)), dtype=np.uint8
    ).reshape(n, 32).copy()


def _full_root(leaves: np.ndarray, limit: int) -> bytes:
    return merkleize(leaves.tobytes(), limit=limit)


def test_tree_hash_cache_matches_merkleize():
    rng = random.Random(0)
    limit = 64
    cache = TreeHashCache(limit)
    leaves = _rand_leaves(rng, 10)
    assert cache.update(leaves) == _full_root(leaves, limit)

    # single dirty leaf
    leaves[3] = _rand_leaves(rng, 1)[0]
    assert cache.update(leaves) == _full_root(leaves, limit)

    # growth within the pow2 block
    leaves = np.vstack([leaves, _rand_leaves(rng, 5)])
    assert cache.update(leaves) == _full_root(leaves, limit)

    # growth crossing pow2 (rebuild path)
    leaves = np.vstack([leaves, _rand_leaves(rng, 8)])
    assert cache.update(leaves) == _full_root(leaves, limit)

    # shrink (rebuild path)
    leaves = leaves[:7]
    assert cache.update(leaves) == _full_root(leaves, limit)

    # no-op update
    assert cache.update(leaves) == _full_root(leaves, limit)


def test_tree_hash_cache_empty_and_full():
    cache = TreeHashCache(16)
    empty = np.zeros((0, 32), dtype=np.uint8)
    assert cache.update(empty) == _full_root(empty, 16)
    rng = random.Random(1)
    full = _rand_leaves(rng, 16)
    assert cache.update(full) == _full_root(full, 16)


def test_update_rows_sparse_matches_merkleize():
    """The dirty-index fast path (no diff, no scan) must agree with the
    full-diff path and with from-scratch merkleize across random sparse
    update sequences, including growth within the pow2 envelope."""
    rng = random.Random(7)
    limit = 256
    cache = TreeHashCache(limit)
    n = 21
    leaves = _rand_leaves(rng, n)
    assert cache.update(leaves) == _full_root(leaves, limit)
    for _ in range(30):
        # mutate a few random chunks
        k = rng.randrange(1, 5)
        idx = sorted(rng.sample(range(n), min(k, n)))
        rows = _rand_leaves(rng, len(idx))
        for r, i in enumerate(idx):
            leaves[i] = rows[r]
        # occasional growth within the same pow2 block
        if rng.random() < 0.3 and n < 32:
            grow = _rand_leaves(rng, 1)
            leaves = np.vstack([leaves, grow])
            idx.append(n)
            rows = np.vstack([rows, grow])
            n += 1
        assert cache.can_sparse(n)
        got = cache.update_rows(np.asarray(idx, dtype=np.int64), rows, n)
        assert got == _full_root(leaves, limit)


def test_update_rows_refuses_outside_envelope():
    rng = random.Random(8)
    cache = TreeHashCache(64)
    leaves = _rand_leaves(rng, 8)
    cache.update(leaves)
    # growth crossing the pow2 envelope is NOT sparse-updatable
    assert not cache.can_sparse(9)
    with pytest.raises(ValueError):
        cache.update_rows(np.array([8]), _rand_leaves(rng, 1), 9)
    # neither is shrink
    assert not cache.can_sparse(7)


def test_cache_copy_is_cow_shares_until_first_write():
    """`copy()` must not duplicate the layer arrays; the first dirty
    write un-shares, and both sides stay correct and independent."""
    rng = random.Random(9)
    cache = TreeHashCache(64)
    leaves = _rand_leaves(rng, 32)
    cache.update(leaves)
    dup = cache.copy()
    assert all(
        np.shares_memory(a, b) for a, b in zip(cache.layers, dup.layers)
    )
    mutated = leaves.copy()
    mutated[5] = _rand_leaves(rng, 1)[0]
    assert cache.update(mutated) == _full_root(mutated, 64)
    # the write un-shared: dup's layers are not the mutated arrays
    assert not np.shares_memory(cache.layers[0], dup.layers[0])
    assert dup.update(leaves) == _full_root(leaves, 64)  # unaffected
    # sparse writes un-share too
    dup2 = dup.copy()
    row = _rand_leaves(rng, 1)
    leaves[0] = row[0]
    assert dup.update_rows(np.array([0]), row, 32) == _full_root(leaves, 64)
    assert not np.shares_memory(dup.layers[0], dup2.layers[0])


def test_cache_copy_is_independent():
    rng = random.Random(2)
    cache = TreeHashCache(32)
    leaves = _rand_leaves(rng, 8)
    cache.update(leaves)
    dup = cache.copy()
    mutated = leaves.copy()
    mutated[0] = _rand_leaves(rng, 1)[0]
    assert cache.update(mutated) == _full_root(mutated, 32)
    assert dup.update(leaves) == _full_root(leaves, 32)  # unaffected


# --- state-level ------------------------------------------------------------


def _fresh_root(state) -> bytes:
    """From-scratch root bypassing the instance override."""
    return type(state).hash_tree_root_of(state)


def test_cached_state_root_matches_full():
    from dataclasses import replace

    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.state_processing import interop_genesis_state
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    kps = bls.interop_keypairs(8)
    state = interop_genesis_state(kps, 1_600_000_000, b"\x42" * 32, spec, E)

    assert state.hash_tree_root() == _fresh_root(state)

    # balance mutation
    state.balances[0] += 12345
    assert state.hash_tree_root() == _fresh_root(state)

    # validator mutation (object-level memo invalidation)
    state.validators[3].slashed = True
    state.validators[3].withdrawable_epoch = 99
    assert state.hash_tree_root() == _fresh_root(state)

    # participation + inactivity churn
    state.current_epoch_participation[2] = 7
    state.inactivity_scores[5] = 42
    assert state.hash_tree_root() == _fresh_root(state)

    # slot-vector rotation
    state.block_roots[1] = b"\x11" * 32
    state.randao_mixes[0] = b"\x22" * 32
    assert state.hash_tree_root() == _fresh_root(state)

    # registry growth
    v = state.validators[0].copy()
    v.pubkey = b"\x05" * 48
    state.validators.append(v)
    state.balances.append(31_000_000_000)
    state.previous_epoch_participation.append(0)
    state.current_epoch_participation.append(0)
    state.inactivity_scores.append(0)
    assert state.hash_tree_root() == _fresh_root(state)

    # copies stay consistent and independent
    dup = state.copy()
    dup.balances[1] += 1
    assert dup.hash_tree_root() == _fresh_root(dup)
    assert state.hash_tree_root() == _fresh_root(state)


def test_cached_root_through_state_transition():
    """The cache must survive per-slot/per-epoch processing (the paths that
    mutate every big field)."""
    from dataclasses import replace

    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=8)
    h.extend_chain(E.SLOTS_PER_EPOCH + 3)
    st = h.chain.head_state
    assert st.hash_tree_root() == _fresh_root(st)


def _persistent_state(n_validators: int, seed: int = 5):
    """An Altair state with a persistent (tree-states) registry of
    `n_validators` cloned-and-varied validators."""
    from dataclasses import replace

    from lighthouse_tpu.beacon_chain.chain import _make_persistent
    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.state_processing import interop_genesis_state
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    rng = random.Random(seed)
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    state = interop_genesis_state(
        bls.interop_keypairs(8), 1_600_000_000, b"\x42" * 32, spec, E
    )
    v0 = state.validators[0]
    vs, bal = [], []
    for i in range(n_validators):
        v = v0.copy()
        v.withdrawal_credentials = i.to_bytes(32, "little")
        v.effective_balance = 32_000_000_000 - (i % 7) * 1_000_000_000
        vs.append(v)
        bal.append(30_000_000_000 + rng.randrange(4_000_000_000))
    state.validators = vs
    state.balances = bal
    _make_persistent(state)
    return state


def test_columnar_registry_vs_per_object_roots():
    """Differential fuzz of the tentpole: the columnar batched registry
    path (dirty-index sparse updates + full columnar rebuilds) must be
    bit-identical to the plain per-object SSZ path across randomized
    mutation sequences — append, exit, slash, balance churn, and
    `state.copy()` aliasing."""
    from lighthouse_tpu.ssz.persistent import CONTAINER_BLOCK

    rng = random.Random(13)
    # enough validators for several container blocks (columnar bulk path)
    state = _persistent_state(2 * CONTAINER_BLOCK + 37)
    assert state.hash_tree_root() == _fresh_root(state)

    copies = []
    for step in range(12):
        n = len(state.validators)
        op = rng.randrange(5)
        if op == 0:  # registry append (deposit)
            v = state.validators[rng.randrange(n)].copy()
            v.withdrawal_credentials = rng.randbytes(32)
            state.validators.append(v)
            state.balances.append(32_000_000_000)
        elif op == 1:  # exit
            v = state.validators.mutate(rng.randrange(n))
            v.exit_epoch = rng.randrange(1, 2**32)
            v.withdrawable_epoch = v.exit_epoch + 256
        elif op == 2:  # slash
            v = state.validators.mutate(rng.randrange(n))
            v.slashed = True
            v.effective_balance = 0
        elif op == 3:  # balance churn
            for _ in range(rng.randrange(1, 40)):
                i = rng.randrange(n)
                state.balances[i] = rng.randrange(40_000_000_000)
        else:  # copy aliasing: keep the copy, mutate the original later
            cp = state.copy()
            copies.append((cp, cp.hash_tree_root()))
        root = state.hash_tree_root()
        assert root == _fresh_root(state), f"divergence at step {step} (op {op})"
    # every historical copy still roots to what it rooted before — the
    # CoW layers and structural sharing never leaked mutations backwards
    for c, r in copies:
        assert c.hash_tree_root() == r
        assert r == _fresh_root(c)


def test_mass_churn_takes_rebuild_path_and_matches():
    """Past the rebuild fraction (or a dirty-tracker overflow) the
    registry re-roots through the batched columnar rebuild — same bits."""
    state = _persistent_state(700)
    state.hash_tree_root()
    for i in range(0, 700, 2):  # dirty more than half the registry
        v = state.validators.mutate(i)
        v.effective_balance = 31_000_000_000
    assert state.hash_tree_root() == _fresh_root(state)


def test_registry_list_replacement_falls_back_safely():
    """Assigning a foreign persistent list (token lineage break) must
    full-diff, never trust stale dirty info."""
    from lighthouse_tpu.ssz.persistent import PersistentList

    state = _persistent_state(300)
    state.hash_tree_root()
    # replace balances wholesale with a list whose dirt baseline the
    # committed cache has never seen
    fresh = PersistentList([i * 3 for i in range(311)])
    state.balances = fresh
    assert state.hash_tree_root() == _fresh_root(state)
    # and mutations on the replacement keep working incrementally
    state.balances[7] = 123456
    assert state.hash_tree_root() == _fresh_root(state)


@pytest.mark.perf_smoke
def test_warm_noop_reroot_never_rescans_registry():
    """The dirty-index contract: a no-op warm re-root does ZERO hashing
    and ZERO full-list extractions; a one-balance churn hashes only one
    path (never the 'diff all leaves' scan the old cache paid)."""
    import time

    from lighthouse_tpu.ssz import cached_tree_hash as cth

    state = _persistent_state(3000)
    state.hash_tree_root()  # commit
    before = cth.stats()
    t0 = time.perf_counter()
    state.hash_tree_root()  # no-op re-root
    elapsed = time.perf_counter() - t0
    delta = {k: cth.stats()[k] - before[k] for k in before}
    assert delta["rows_hashed"] == 0, delta
    assert delta["full_extracts"] == 0, delta
    # loose wall bound: a no-op re-root is small-field recompute only
    assert elapsed < 0.25, elapsed

    # one balance write: a single path lift, not a registry scan
    before = cth.stats()
    state.balances[17] = int(state.balances[17]) + 1
    state.hash_tree_root()
    delta = {k: cth.stats()[k] - before[k] for k in before}
    assert delta["full_extracts"] == 0, delta
    assert 0 < delta["rows_hashed"] < 64, delta


def test_altair_and_electra_states_use_cache_and_match_plain_roots():
    """Altair+ states are not subclasses of the phase0 BeaconState, so
    they carry their own cached hash_tree_root hook — roots must equal
    the from-scratch classmethod computation through arbitrary churn."""
    import random
    from dataclasses import replace

    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.state_processing import interop_genesis_state
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    rng = random.Random(5)
    for forks in (
        dict(altair_fork_epoch=0),
        dict(
            altair_fork_epoch=0,
            bellatrix_fork_epoch=0,
            capella_fork_epoch=0,
            deneb_fork_epoch=0,
            electra_fork_epoch=0,
        ),
    ):
        spec = replace(minimal_spec(), **forks)
        state = interop_genesis_state(
            bls.interop_keypairs(8), 1_600_000_000, b"\x42" * 32, spec, E
        )
        plain = type(state).hash_tree_root_of(state)
        assert state.hash_tree_root() == plain
        assert "_thc_cache" in state.__dict__  # the cache really engaged
        # churn: balances, validator record, participation, randao
        for _ in range(5):
            i = rng.randrange(len(state.balances))
            state.balances[i] = int(state.balances[i]) + rng.randrange(100)
            v = state.validators[rng.randrange(len(state.validators))]
            v.effective_balance = 31_000_000_000
            state.current_epoch_participation[
                rng.randrange(len(state.current_epoch_participation))
            ] = rng.randrange(8)
            state.randao_mixes[rng.randrange(8)] = rng.randbytes(32)
            assert state.hash_tree_root() == type(state).hash_tree_root_of(state)
        # copies share nothing observable: mutate the copy, original stable
        snap = state.hash_tree_root()
        cp = state.copy()
        cp.balances[0] = 1
        assert cp.hash_tree_root() != snap
        assert state.hash_tree_root() == snap
