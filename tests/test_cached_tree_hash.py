"""Incremental tree-hash cache vs full recompute.

Mirrors the reference's cached_tree_hash tests (cache.rs:203-237): dirty
single leaves, growth, shrink-triggered rebuild, and the state-level cache
staying consistent with the from-scratch SSZ root across realistic
mutations and copies."""

import random

import numpy as np
import pytest

from lighthouse_tpu.ssz.cached_tree_hash import (
    BeaconStateHashCache,
    TreeHashCache,
    cached_state_root,
)
from lighthouse_tpu.ssz.merkle import merkleize


def _rand_leaves(rng, n):
    return np.frombuffer(
        bytes(rng.randrange(256) for _ in range(n * 32)), dtype=np.uint8
    ).reshape(n, 32).copy()


def _full_root(leaves: np.ndarray, limit: int) -> bytes:
    return merkleize(leaves.tobytes(), limit=limit)


def test_tree_hash_cache_matches_merkleize():
    rng = random.Random(0)
    limit = 64
    cache = TreeHashCache(limit)
    leaves = _rand_leaves(rng, 10)
    assert cache.update(leaves) == _full_root(leaves, limit)

    # single dirty leaf
    leaves[3] = _rand_leaves(rng, 1)[0]
    assert cache.update(leaves) == _full_root(leaves, limit)

    # growth within the pow2 block
    leaves = np.vstack([leaves, _rand_leaves(rng, 5)])
    assert cache.update(leaves) == _full_root(leaves, limit)

    # growth crossing pow2 (rebuild path)
    leaves = np.vstack([leaves, _rand_leaves(rng, 8)])
    assert cache.update(leaves) == _full_root(leaves, limit)

    # shrink (rebuild path)
    leaves = leaves[:7]
    assert cache.update(leaves) == _full_root(leaves, limit)

    # no-op update
    assert cache.update(leaves) == _full_root(leaves, limit)


def test_tree_hash_cache_empty_and_full():
    cache = TreeHashCache(16)
    empty = np.zeros((0, 32), dtype=np.uint8)
    assert cache.update(empty) == _full_root(empty, 16)
    rng = random.Random(1)
    full = _rand_leaves(rng, 16)
    assert cache.update(full) == _full_root(full, 16)


def test_cache_copy_is_independent():
    rng = random.Random(2)
    cache = TreeHashCache(32)
    leaves = _rand_leaves(rng, 8)
    cache.update(leaves)
    dup = cache.copy()
    mutated = leaves.copy()
    mutated[0] = _rand_leaves(rng, 1)[0]
    assert cache.update(mutated) == _full_root(mutated, 32)
    assert dup.update(leaves) == _full_root(leaves, 32)  # unaffected


# --- state-level ------------------------------------------------------------


def _fresh_root(state) -> bytes:
    """From-scratch root bypassing the instance override."""
    return type(state).hash_tree_root_of(state)


def test_cached_state_root_matches_full():
    from dataclasses import replace

    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.state_processing import interop_genesis_state
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    kps = bls.interop_keypairs(8)
    state = interop_genesis_state(kps, 1_600_000_000, b"\x42" * 32, spec, E)

    assert state.hash_tree_root() == _fresh_root(state)

    # balance mutation
    state.balances[0] += 12345
    assert state.hash_tree_root() == _fresh_root(state)

    # validator mutation (object-level memo invalidation)
    state.validators[3].slashed = True
    state.validators[3].withdrawable_epoch = 99
    assert state.hash_tree_root() == _fresh_root(state)

    # participation + inactivity churn
    state.current_epoch_participation[2] = 7
    state.inactivity_scores[5] = 42
    assert state.hash_tree_root() == _fresh_root(state)

    # slot-vector rotation
    state.block_roots[1] = b"\x11" * 32
    state.randao_mixes[0] = b"\x22" * 32
    assert state.hash_tree_root() == _fresh_root(state)

    # registry growth
    v = state.validators[0].copy()
    v.pubkey = b"\x05" * 48
    state.validators.append(v)
    state.balances.append(31_000_000_000)
    state.previous_epoch_participation.append(0)
    state.current_epoch_participation.append(0)
    state.inactivity_scores.append(0)
    assert state.hash_tree_root() == _fresh_root(state)

    # copies stay consistent and independent
    dup = state.copy()
    dup.balances[1] += 1
    assert dup.hash_tree_root() == _fresh_root(dup)
    assert state.hash_tree_root() == _fresh_root(state)


def test_cached_root_through_state_transition():
    """The cache must survive per-slot/per-epoch processing (the paths that
    mutate every big field)."""
    from dataclasses import replace

    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=8)
    h.extend_chain(E.SLOTS_PER_EPOCH + 3)
    st = h.chain.head_state
    assert st.hash_tree_root() == _fresh_root(st)


def test_altair_and_electra_states_use_cache_and_match_plain_roots():
    """Altair+ states are not subclasses of the phase0 BeaconState, so
    they carry their own cached hash_tree_root hook — roots must equal
    the from-scratch classmethod computation through arbitrary churn."""
    import random
    from dataclasses import replace

    from lighthouse_tpu.crypto import bls
    from lighthouse_tpu.state_processing import interop_genesis_state
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    bls.set_backend("fake_crypto")
    rng = random.Random(5)
    for forks in (
        dict(altair_fork_epoch=0),
        dict(
            altair_fork_epoch=0,
            bellatrix_fork_epoch=0,
            capella_fork_epoch=0,
            deneb_fork_epoch=0,
            electra_fork_epoch=0,
        ),
    ):
        spec = replace(minimal_spec(), **forks)
        state = interop_genesis_state(
            bls.interop_keypairs(8), 1_600_000_000, b"\x42" * 32, spec, E
        )
        plain = type(state).hash_tree_root_of(state)
        assert state.hash_tree_root() == plain
        assert "_thc_cache" in state.__dict__  # the cache really engaged
        # churn: balances, validator record, participation, randao
        for _ in range(5):
            i = rng.randrange(len(state.balances))
            state.balances[i] = int(state.balances[i]) + rng.randrange(100)
            v = state.validators[rng.randrange(len(state.validators))]
            v.effective_balance = 31_000_000_000
            state.current_epoch_participation[
                rng.randrange(len(state.current_epoch_participation))
            ] = rng.randrange(8)
            state.randao_mixes[rng.randrange(8)] = rng.randbytes(32)
            assert state.hash_tree_root() == type(state).hash_tree_root_of(state)
        # copies share nothing observable: mutate the copy, original stable
        snap = state.hash_tree_root()
        cp = state.copy()
        cp.balances[0] = 1
        assert cp.hash_tree_root() != snap
        assert state.hash_tree_root() == snap
