"""Proto-array fork choice tests: vote weighting, reorgs, viability,
proposer boost, pruning, execution invalidation."""

import pytest

from lighthouse_tpu.fork_choice import (
    ExecutionStatus,
    ProtoArrayForkChoice,
)

R = lambda i: bytes([i]) * 32  # noqa: E731


def make_fc(justified_epoch=0, finalized_epoch=0):
    return ProtoArrayForkChoice(
        finalized_root=R(0),
        finalized_slot=0,
        finalized_state_root=R(100),
        justified_epoch=justified_epoch,
        finalized_epoch=finalized_epoch,
    )


def add_block(fc, slot, root, parent, je=0, fe=0):
    fc.on_block(
        slot=slot,
        root=root,
        parent_root=parent,
        state_root=root,
        justified_epoch=je,
        finalized_epoch=fe,
    )


def head(fc, balances, boost_root=b"\x00" * 32, boost=0):
    return fc.get_head(
        justified_checkpoint_root=R(0),
        justified_epoch=0,
        finalized_epoch=0,
        justified_state_balances=balances,
        proposer_boost_root=boost_root,
        proposer_boost_amount=boost,
    )


def test_single_chain_head():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 2, R(2), R(1))
    assert head(fc, [1, 1]) == R(2)


def test_votes_decide_fork():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 1, R(2), R(0))  # competing fork
    # two validators vote for R(1), one for R(2)
    fc.process_attestation(0, R(1), 1)
    fc.process_attestation(1, R(1), 1)
    fc.process_attestation(2, R(2), 1)
    assert head(fc, [10, 10, 10]) == R(1)
    # votes move: all to R(2)
    fc.process_attestation(0, R(2), 2)
    fc.process_attestation(1, R(2), 2)
    assert head(fc, [10, 10, 10]) == R(2)


def test_balance_weighting():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 1, R(2), R(0))
    fc.process_attestation(0, R(1), 1)  # whale
    fc.process_attestation(1, R(2), 1)
    fc.process_attestation(2, R(2), 1)
    assert head(fc, [100, 10, 10]) == R(1)


def test_tie_break_deterministic():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 1, R(2), R(0))
    # no votes: higher root wins
    assert head(fc, []) == R(2)


def test_proposer_boost_flips_head():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 1, R(2), R(0))
    fc.process_attestation(0, R(1), 1)
    assert head(fc, [10]) == R(1)
    # boost on R(2) outweighs the 10-unit vote
    assert head(fc, [10], boost_root=R(2), boost=50) == R(2)
    # boost expires next call
    assert head(fc, [10]) == R(1)


def test_viability_filter_justification():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0), je=0)
    add_block(fc, 2, R(2), R(1), je=1)  # justified child
    add_block(fc, 2, R(3), R(1), je=0)  # stale-justification child
    fc.process_attestation(0, R(3), 1)
    # with store justified_epoch=1, R(3) is not viable despite the vote
    got = fc.get_head(
        justified_checkpoint_root=R(0),
        justified_epoch=1,
        finalized_epoch=0,
        justified_state_balances=[10],
    )
    assert got == R(2)


def test_equivocation_removes_weight():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 1, R(2), R(0))
    fc.process_attestation(0, R(1), 1)
    fc.process_attestation(1, R(2), 1)
    assert head(fc, [100, 10]) == R(1)
    got = fc.get_head(
        justified_checkpoint_root=R(0),
        justified_epoch=0,
        finalized_epoch=0,
        justified_state_balances=[100, 10],
        equivocating_indices={0},
    )
    assert got == R(2)
    # and the slashed weight never comes back
    assert head(fc, [100, 10]) == R(2)


def test_execution_invalidation():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 2, R(2), R(1))
    add_block(fc, 3, R(3), R(2))
    fc.process_attestation(0, R(3), 1)
    assert head(fc, [10]) == R(3)
    fc.proto_array.invalidate_block(R(2))  # invalidates R(2), R(3)
    assert head(fc, [10]) == R(1)


def test_is_descendant():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 2, R(2), R(1))
    add_block(fc, 1, R(3), R(0))
    pa = fc.proto_array
    assert pa.is_descendant(R(0), R(2))
    assert pa.is_descendant(R(1), R(2))
    assert not pa.is_descendant(R(3), R(2))
    assert not pa.is_descendant(R(2), R(1))


def test_prune():
    fc = make_fc()
    fc.proto_array.prune_threshold = 0  # prune aggressively
    for i in range(1, 6):
        add_block(fc, i, R(i), R(i - 1))
    fc.process_attestation(0, R(5), 1)
    assert head(fc, [10]) == R(5)
    fc.proto_array.maybe_prune(R(3))
    assert not fc.contains_block(R(1))
    assert not fc.contains_block(R(2))
    assert fc.contains_block(R(3))
    # head still works on the pruned array (deltas resize on next pass)
    got = fc.get_head(
        justified_checkpoint_root=R(3),
        justified_epoch=0,
        finalized_epoch=0,
        justified_state_balances=[10],
    )
    assert got == R(5)


# ---------------------------------------------------------------------------
# ForkChoice wrapper validation (spec validate_on_attestation)
# ---------------------------------------------------------------------------

from lighthouse_tpu.fork_choice.fork_choice import (  # noqa: E402
    Checkpoint as FcCheckpoint,
    ForkChoice,
    ForkChoiceStore,
    InvalidAttestation,
)
from lighthouse_tpu.types.chain_spec import minimal_spec  # noqa: E402
from lighthouse_tpu.types.containers import build_types  # noqa: E402
from lighthouse_tpu.types.eth_spec import MinimalEthSpec  # noqa: E402


def make_wrapper(current_slot=0):
    cp = FcCheckpoint(epoch=0, root=R(0))
    store = ForkChoiceStore(
        current_slot=current_slot,
        justified_checkpoint=cp,
        finalized_checkpoint=cp,
        unrealized_justified_checkpoint=cp,
        unrealized_finalized_checkpoint=cp,
    )
    return ForkChoice(store, make_fc(), minimal_spec(), MinimalEthSpec)


def _attestation(T, slot, head_root, target_epoch, target_root, indices=(0,)):
    return T.IndexedAttestation(
        attesting_indices=list(indices),
        data=T.AttestationData(
            slot=slot,
            index=0,
            beacon_block_root=head_root,
            source=T.Checkpoint(epoch=0, root=R(0)),
            target=T.Checkpoint(epoch=target_epoch, root=target_root),
        ),
        signature=b"\x00" * 96,
    )


def test_on_attestation_target_chain_consistency():
    """An attestation whose target root is not the checkpoint block of the
    head block's chain at target.epoch must be rejected (ADVICE r1)."""
    T = build_types(MinimalEthSpec)
    fc = make_wrapper(current_slot=MinimalEthSpec.SLOTS_PER_EPOCH + 2)
    # epoch-0 chain: R0 (genesis anchor) <- R1; epoch-1 blocks: R2 on R1,
    # and a fork F3 directly on R0 (its epoch-1 checkpoint block is R0).
    add_block(fc.proto, 1, R(1), R(0))
    e1 = MinimalEthSpec.SLOTS_PER_EPOCH
    add_block(fc.proto, e1, R(2), R(1))
    add_block(fc.proto, e1 + 1, R(3), R(0))
    slot = e1 + 1
    # Consistent: head R2, target (epoch 1, R2's chain checkpoint = R2)
    fc.on_attestation(_attestation(T, slot, R(2), 1, R(2)))
    # Inconsistent: head R3 (checkpoint at epoch 1 start is R0), target R2
    with pytest.raises(InvalidAttestation):
        fc.on_attestation(_attestation(T, slot, R(3), 1, R(2)))
    # Consistent fork vote: head R3, target R0
    fc.on_attestation(_attestation(T, slot, R(3), 1, R(0), indices=(1,)))


def test_on_tick_promotes_unrealized_checkpoints():
    """Crossing an epoch boundary must promote unrealized j/f checkpoints
    even without new block imports (spec on_tick_per_slot; ADVICE r1)."""
    fc = make_wrapper(current_slot=3)
    fc.store.unrealized_justified_checkpoint = FcCheckpoint(epoch=1, root=R(1))
    fc.store.unrealized_finalized_checkpoint = FcCheckpoint(epoch=0, root=R(0))
    add_block(fc.proto, 1, R(1), R(0))
    fc.on_tick(MinimalEthSpec.SLOTS_PER_EPOCH)  # cross into epoch 1
    assert fc.store.justified_checkpoint.epoch == 1
    assert fc.store.justified_checkpoint.root == R(1)


# ---------------------------------------------------------------------------
# Same-slot gossip deferral (fork_choice.rs queued_attestations)
# ---------------------------------------------------------------------------

from lighthouse_tpu.metrics import REGISTRY  # noqa: E402


def _deferred(outcome):
    return REGISTRY.counter("fork_choice_deferred_attestations_total").value(
        outcome=outcome
    )


def _deferral_wrapper():
    fc = make_wrapper(current_slot=2)
    add_block(fc.proto, 1, R(1), R(0))
    add_block(fc.proto, 2, R(2), R(1))
    return fc


def test_same_slot_gossip_attestation_defers_until_tick():
    """A gossip vote from the store's current slot queues (it would fail
    the "from the future" recency rule) and drains into the vote tracker
    on the tick that clears it — the weight the next slot's proposer-boost
    re-org decision reads."""
    T = build_types(MinimalEthSpec)
    fc = _deferral_wrapper()
    d0, a0 = _deferred("deferred"), _deferred("applied")
    fc.on_attestation(_attestation(T, 2, R(2), 0, R(0)))
    assert len(fc._deferred_attestations) == 1
    assert _deferred("deferred") == d0 + 1
    assert fc.proto._next_rid.size == 0  # vote NOT applied yet
    fc.on_tick(3)
    assert fc._deferred_attestations == []
    assert _deferred("applied") == a0 + 1
    assert int(fc.proto._next_rid[0]) == fc.proto.proto_array.vote_root_id(
        R(2)
    )


def test_store_lagging_gossip_attestation_defers_until_its_tick():
    """The store only advances on ticks: a wall-clock slot-3 vote arriving
    while the store still reads slot 2 must queue (not reject), and must
    stay queued through the slot-3 tick — it drains at slot 4."""
    T = build_types(MinimalEthSpec)
    fc = _deferral_wrapper()
    fc.on_attestation(_attestation(T, 3, R(2), 0, R(0)))
    assert len(fc._deferred_attestations) == 1
    fc.on_tick(3)
    assert len(fc._deferred_attestations) == 1  # slot-3 vote not yet clear
    fc.on_tick(4)
    assert fc._deferred_attestations == []
    assert int(fc.proto._next_rid[0]) == fc.proto.proto_array.vote_root_id(
        R(2)
    )


def test_deferred_attestation_structurally_validated_at_enqueue():
    """Structural validation runs at enqueue time (is_from_block=True
    skips only the two gossip recency rules), so garbage never occupies
    the queue waiting for a tick to bounce it."""
    T = build_types(MinimalEthSpec)
    fc = _deferral_wrapper()
    with pytest.raises(InvalidAttestation):
        fc.on_attestation(_attestation(T, 2, R(9), 0, R(0)))  # unknown head
    assert fc._deferred_attestations == []


def test_past_slot_gossip_attestation_applies_immediately():
    T = build_types(MinimalEthSpec)
    fc = _deferral_wrapper()
    fc.on_attestation(_attestation(T, 1, R(1), 0, R(0)))
    assert fc._deferred_attestations == []
    assert int(fc.proto._next_rid[0]) == fc.proto.proto_array.vote_root_id(
        R(1)
    )


def test_deferral_queue_cap_sheds(monkeypatch):
    import lighthouse_tpu.fork_choice.fork_choice as fc_mod

    monkeypatch.setattr(fc_mod, "_MAX_DEFERRED_ATTESTATIONS", 2)
    T = build_types(MinimalEthSpec)
    fc = _deferral_wrapper()
    x0 = _deferred("dropped")
    for vi in range(3):
        fc.on_attestation(_attestation(T, 2, R(2), 0, R(0), indices=(vi,)))
    assert len(fc._deferred_attestations) == 2
    assert _deferred("dropped") == x0 + 1


def test_batch_path_defers_same_slot_votes_too():
    """on_attestation_batch reports a deferred vote as accepted (None) —
    it is consumed, just later — and the drain applies it through the
    vectorized batch write."""
    T = build_types(MinimalEthSpec)
    fc = _deferral_wrapper()
    results = fc.on_attestation_batch(
        [
            _attestation(T, 2, R(2), 0, R(0), indices=(0, 1)),
            _attestation(T, 1, R(1), 0, R(0), indices=(2,)),
        ]
    )
    assert results == [None, None]
    assert len(fc._deferred_attestations) == 1
    rid1 = fc.proto.proto_array.vote_root_id(R(1))
    assert int(fc.proto._next_rid[2]) == rid1  # past-slot vote landed now
    fc.on_tick(3)
    rid2 = fc.proto.proto_array.vote_root_id(R(2))
    assert [int(fc.proto._next_rid[v]) for v in (0, 1)] == [rid2, rid2]
