"""Proto-array fork choice tests: vote weighting, reorgs, viability,
proposer boost, pruning, execution invalidation."""

import pytest

from lighthouse_tpu.fork_choice import (
    ExecutionStatus,
    ProtoArrayForkChoice,
)

R = lambda i: bytes([i]) * 32  # noqa: E731


def make_fc(justified_epoch=0, finalized_epoch=0):
    return ProtoArrayForkChoice(
        finalized_root=R(0),
        finalized_slot=0,
        finalized_state_root=R(100),
        justified_epoch=justified_epoch,
        finalized_epoch=finalized_epoch,
    )


def add_block(fc, slot, root, parent, je=0, fe=0):
    fc.on_block(
        slot=slot,
        root=root,
        parent_root=parent,
        state_root=root,
        justified_epoch=je,
        finalized_epoch=fe,
    )


def head(fc, balances, boost_root=b"\x00" * 32, boost=0):
    return fc.get_head(
        justified_checkpoint_root=R(0),
        justified_epoch=0,
        finalized_epoch=0,
        justified_state_balances=balances,
        proposer_boost_root=boost_root,
        proposer_boost_amount=boost,
    )


def test_single_chain_head():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 2, R(2), R(1))
    assert head(fc, [1, 1]) == R(2)


def test_votes_decide_fork():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 1, R(2), R(0))  # competing fork
    # two validators vote for R(1), one for R(2)
    fc.process_attestation(0, R(1), 1)
    fc.process_attestation(1, R(1), 1)
    fc.process_attestation(2, R(2), 1)
    assert head(fc, [10, 10, 10]) == R(1)
    # votes move: all to R(2)
    fc.process_attestation(0, R(2), 2)
    fc.process_attestation(1, R(2), 2)
    assert head(fc, [10, 10, 10]) == R(2)


def test_balance_weighting():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 1, R(2), R(0))
    fc.process_attestation(0, R(1), 1)  # whale
    fc.process_attestation(1, R(2), 1)
    fc.process_attestation(2, R(2), 1)
    assert head(fc, [100, 10, 10]) == R(1)


def test_tie_break_deterministic():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 1, R(2), R(0))
    # no votes: higher root wins
    assert head(fc, []) == R(2)


def test_proposer_boost_flips_head():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 1, R(2), R(0))
    fc.process_attestation(0, R(1), 1)
    assert head(fc, [10]) == R(1)
    # boost on R(2) outweighs the 10-unit vote
    assert head(fc, [10], boost_root=R(2), boost=50) == R(2)
    # boost expires next call
    assert head(fc, [10]) == R(1)


def test_viability_filter_justification():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0), je=0)
    add_block(fc, 2, R(2), R(1), je=1)  # justified child
    add_block(fc, 2, R(3), R(1), je=0)  # stale-justification child
    fc.process_attestation(0, R(3), 1)
    # with store justified_epoch=1, R(3) is not viable despite the vote
    got = fc.get_head(
        justified_checkpoint_root=R(0),
        justified_epoch=1,
        finalized_epoch=0,
        justified_state_balances=[10],
    )
    assert got == R(2)


def test_equivocation_removes_weight():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 1, R(2), R(0))
    fc.process_attestation(0, R(1), 1)
    fc.process_attestation(1, R(2), 1)
    assert head(fc, [100, 10]) == R(1)
    got = fc.get_head(
        justified_checkpoint_root=R(0),
        justified_epoch=0,
        finalized_epoch=0,
        justified_state_balances=[100, 10],
        equivocating_indices={0},
    )
    assert got == R(2)
    # and the slashed weight never comes back
    assert head(fc, [100, 10]) == R(2)


def test_execution_invalidation():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 2, R(2), R(1))
    add_block(fc, 3, R(3), R(2))
    fc.process_attestation(0, R(3), 1)
    assert head(fc, [10]) == R(3)
    fc.proto_array.invalidate_block(R(2))  # invalidates R(2), R(3)
    assert head(fc, [10]) == R(1)


def test_is_descendant():
    fc = make_fc()
    add_block(fc, 1, R(1), R(0))
    add_block(fc, 2, R(2), R(1))
    add_block(fc, 1, R(3), R(0))
    pa = fc.proto_array
    assert pa.is_descendant(R(0), R(2))
    assert pa.is_descendant(R(1), R(2))
    assert not pa.is_descendant(R(3), R(2))
    assert not pa.is_descendant(R(2), R(1))


def test_prune():
    fc = make_fc()
    fc.proto_array.prune_threshold = 0  # prune aggressively
    for i in range(1, 6):
        add_block(fc, i, R(i), R(i - 1))
    fc.process_attestation(0, R(5), 1)
    assert head(fc, [10]) == R(5)
    fc.proto_array.maybe_prune(R(3))
    assert not fc.contains_block(R(1))
    assert not fc.contains_block(R(2))
    assert fc.contains_block(R(3))
    # head still works on the pruned array (deltas resize on next pass)
    got = fc.get_head(
        justified_checkpoint_root=R(3),
        justified_epoch=0,
        finalized_epoch=0,
        justified_state_balances=[10],
    )
    assert got == R(5)
