"""Adversarial in-process testnet fleet (testing/testnet.py).

Tier-1 runs the 3-node `scenario_smoke` (partition → heal → converge),
the 4-node eclipse-and-recover and 3-node equivocating-proposer regimes,
the /lighthouse/health `chain` block, and directed regression tests for
the three peer-lifecycle bugs the partition/heal scenarios flushed out:

  * the SyncService Status-polled every peer every tick even when synced,
    draining the host-keyed RPC rate-limit buckets until post-heal dials
    were refused;
  * a range-sync batch failing on its FIRST block's unknown parent
    indicted (and eventually banned) peers honestly serving a competing
    fork — now it backtracks to the finalized boundary instead;
  * block lookups capped rotation at `lookup_max_attempts` even with more
    connected peers, so post-heal fork roots held only by the other half
    were never fetched.

Full-fleet scenarios (10 nodes) and the remaining fault regimes are
`slow`-marked.
"""

import time
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.network import NetworkService, SyncConfig
from lighthouse_tpu.network.sync import SyncService
from lighthouse_tpu.network.sync.block_lookups import BlockLookups
from lighthouse_tpu.testing.testnet import (
    ChainHealthOracle,
    DasTestnetEthSpec,
    FaultPlane,
    ScenarioFailure,
    Testnet,
    run_churn_soak_scenario,
    run_column_withholding_scenario,
    run_eclipse_scenario,
    run_equivocation_scenario,
    run_gossip_flood_scenario,
    run_late_delivery_scenario,
    run_late_proposer_scenario,
    run_partition_heal_scenario,
    run_production_under_flood_scenario,
    run_smoke_scenario,
    scenario_seed,
)
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


@pytest.fixture(autouse=True)
def _restore_bls_backend():
    """Scenario nodes boot through ClientBuilder, which sets the global
    BLS backend — restore whatever the surrounding suite had."""
    prev = bls.backend_name()
    yield
    bls.set_backend(prev)


def _spec():
    return replace(minimal_spec(), altair_fork_epoch=0)


def _counter(name, **labels):
    return REGISTRY.counter(name).value(**labels)


# -- fault plane unit surface --------------------------------------------------


def test_fault_plane_verbs_and_components():
    plane = FaultPlane()
    for i, name in enumerate(["a", "b", "c", "d"]):
        plane.register(name, "127.0.0.1", 9000 + i)
    assert plane.node_for("127.0.0.1", 9001) == "b"
    assert plane.edge("a", "b") == 0.0
    plane.partition(["a", "b"], ["c", "d"])
    assert plane.edge("a", "c") is None
    assert plane.edge("c", "a") is None
    assert plane.edge("a", "b") == 0.0
    assert not plane.dial_allowed("a", "d")
    assert plane.dial_allowed("a", "b")
    assert plane.components(["a", "b", "c", "d"]) in (
        [{"a", "b"}, {"c", "d"}],
        [{"c", "d"}, {"a", "b"}],
    )
    plane.delay("a", "b", 0.5)
    assert plane.edge("a", "b") == 0.5
    assert plane.edge("b", "a") == 0.5  # symmetric by default
    plane.mute("c", "d")
    assert plane.edge("c", "d") is None
    assert plane.dial_allowed("c", "d")  # muted, not blocked
    plane.lie_status("d", 64)
    assert plane.status_extra("d") == 64
    plane.heal()
    assert plane.edge("a", "c") == 0.0
    assert plane.status_extra("d") == 0
    assert plane.components(["a", "b", "c", "d"]) == [{"a", "b", "c", "d"}]


def test_scenario_seed_env_override(monkeypatch):
    assert scenario_seed(42) == 42
    monkeypatch.setenv("LIGHTHOUSE_TPU_SCENARIO_SEED", "777")
    assert scenario_seed(42) == 777


# -- /lighthouse/health chain block -------------------------------------------


def test_health_chain_block_served_per_node():
    """Every node's Beacon API serves its OWN chain vitals in one health
    GET — the oracle's single-endpoint contract."""
    net = Testnet.create(_spec(), E, node_count=2, validator_count=8, seed=9)
    try:
        oracle = ChainHealthOracle(net)
        net.run_until_slot(E.SLOTS_PER_EPOCH + 1, start_slot=1)
        for node in net.nodes:
            c = oracle.chain_block(node)
            assert c["head_slot"] == int(node.chain.head_state.slot)
            assert c["head_root"] == "0x" + node.chain.head_root.hex()
            assert c["clock_slot"] == E.SLOTS_PER_EPOCH + 1
            assert c["head_lag_slots"] in (0, 1)
            assert c["finalized_epoch"] == int(
                node.chain.finalized_checkpoint.epoch
            )
            assert c["finalized_distance_epochs"] >= 0
            assert c["reorgs_total"] == node.chain.reorgs_total
            assert c["max_reorg_depth"] == node.chain.max_reorg_depth
            # altair chain one epoch in: participation is a real rate
            assert 0.0 <= c["participation_prev_epoch"] <= 1.0
    finally:
        net.shutdown()


def test_health_without_chain_omits_chain_block():
    """The standalone MetricsServer path (no chain bound) keeps serving
    process health — just without the per-node block."""
    from lighthouse_tpu.metrics.server import serve_lighthouse_path
    import json

    code, _ctype, body = serve_lighthouse_path("/lighthouse/health")
    assert code == 200
    data = json.loads(body)["data"]
    assert "chain" not in data
    assert "uptime_seconds" in data


# -- tier-1 scenario smoke -----------------------------------------------------


def test_scenario_smoke_partition_heal_converges():
    """The tentpole contract at its smallest shape: 3 real nodes run
    healthy, fork under a partition, heal, and converge to one head with
    finality advancing — asserted through each node's health endpoint."""
    report = run_smoke_scenario(_spec(), E)
    assert report["recovery_slots"] <= 6 * E.SLOTS_PER_EPOCH
    assert report["recovery_to_finality_s"] > 0


def test_eclipse_victim_recovers_when_honest_peers_readmitted():
    report = run_eclipse_scenario(_spec(), E)
    # the victim was genuinely dark (behind AND on its own fork) ...
    assert report["victim_gap_slots"] > 0
    # ... and rejoined the fleet head once honest peers returned
    assert report["recovery_slots"] <= 6 * E.SLOTS_PER_EPOCH


def test_equivocating_proposer_slashed_exactly_once():
    """gossip → SLASHER_PROCESS lane → emission, end to end: the observer
    node (the only one running a slasher) must turn the double proposal
    into exactly ONE ProposerSlashing."""
    report = run_equivocation_scenario(_spec(), E)
    assert report["slashings_emitted"] == 1
    assert report["slasher_cycles"] >= 1


def _das_spec():
    """Deneb from genesis: blob commitments (and so the DAS column
    pipeline) are live from slot 0."""
    return replace(
        minimal_spec(),
        altair_fork_epoch=0,
        bellatrix_fork_epoch=0,
        capella_fork_epoch=0,
        deneb_fork_epoch=0,
    )


def test_fault_plane_withhold_verb():
    """The withhold verb's deterministic plumbing, no fleet needed."""
    plane = FaultPlane()
    withheld = plane.withhold_columns("n0", 0.75, 16)
    assert len(withheld) == 12
    assert plane.withheld_columns("n0") == frozenset(withheld)
    assert plane.withheld_columns("n1") == frozenset()
    # fraction 0 clears; heal clears everything
    plane.withhold_columns("n0", 0.0, 16)
    assert plane.withheld_columns("n0") == frozenset()
    plane.withhold_columns("n0", 0.5, 16)
    plane.heal()
    assert plane.withheld_columns("n0") == frozenset()


def test_column_withholding_refusal_then_recovery():
    """The PeerDAS availability contract end to end on 3 real nodes: an
    adversary withholding >50% of a blob block's columns sees every
    honest node's sampling fail and the fleet refuse (then finalize
    past) its head; withholding <50% leaves enough columns for honest
    nodes to cross the reconstruction threshold and import. The custody
    arithmetic of DasTestnetEthSpec makes both verdicts deterministic,
    not probabilistic."""
    report = run_column_withholding_scenario(_das_spec(), DasTestnetEthSpec)
    assert report["sampling_failures"] >= 1
    assert report["reconstructions"] >= 1
    assert len(report["withheld_refusal"]) == 12  # 0.75 * 16 columns
    assert report["recovery_slots"] <= 6 * DasTestnetEthSpec.SLOTS_PER_EPOCH
    # the fault fleet counted the injections
    assert _counter("testnet_fault_injections_total", kind="withhold") >= 2
    assert _counter("das_reconstructions_total") >= 1


def test_late_proposer_reorged_out_while_finality_advances():
    """The proposer-boost re-org regime on 4 real nodes: a block
    withheld past the attestation deadline loses its committee (they
    attest the parent — same-slot gossip votes carried by the fork
    choice deferral queue), and the next slot's proposer builds on the
    parent, orphaning it while the fleet single-heads and finalizes."""
    report = run_late_proposer_scenario(_spec(), E)
    assert report["deferred_applied"] > 0
    assert min(report["finalized"]) >= 1
    assert report["recovery_slots"] <= 6 * E.SLOTS_PER_EPOCH


# -- directed regressions: SyncService status-poll discipline ------------------


class _StubClock:
    def __init__(self, slot=0):
        self.slot = slot

    def now(self):
        return self.slot


class _StubHead:
    def __init__(self, slot=0):
        self.slot = slot


class _StubChain:
    def __init__(self):
        self.slot_clock = _StubClock()
        self.head_state = _StubHead()


class _StubService:
    def __init__(self):
        self.chain = _StubChain()
        self.port = 0


class _StubPeer:
    def __init__(self, pid):
        self.peer_id = pid


class _StubManager:
    def __init__(self):
        self.service = _StubService()
        self.polls = 0
        self.candidates = []

    def poll_sync_candidates(self):
        self.polls += 1
        return self.candidates, self.candidates, 0

    def _range_sync(self, serving, target):
        return 0


def test_sync_service_skips_status_polls_when_synced():
    """A node at its head must NOT Status-poll every tick: co-hosted
    nodes share host-keyed rate-limit buckets, and the per-tick storm
    starved post-heal handshakes fleet-wide."""
    mgr = _StubManager()
    svc = SyncService(mgr, interval=0.01, status_poll_interval=5.0)
    for _ in range(5):
        svc._tick()
    assert mgr.polls == 1  # the initial refresh only
    # falling behind the clock re-enables eager polling immediately
    mgr.service.chain.slot_clock.slot = 10
    for _ in range(3):
        svc._tick()
    assert mgr.polls == 4


def test_sync_service_backoff_resets_on_new_serving_peer():
    """Failures earned against one peer set must not throttle a NEW
    serving peer (partition heal, eclipse lifted)."""
    mgr = _StubManager()
    svc = SyncService(mgr, interval=0.01)
    svc._consecutive_failures = 5
    svc._last_serving_ids = {"old-peer"}
    mgr.candidates = [_StubPeer("new-peer")]
    before = _counter(
        "sync_service_backoff_resets_total", reason="new_serving_peer"
    )
    svc._tick()
    assert svc._consecutive_failures == 0
    assert (
        _counter("sync_service_backoff_resets_total", reason="new_serving_peer")
        == before + 1
    )


def test_sync_service_peer_connected_wakes_sleeping_loop():
    """A fresh connection cuts the backoff sleep short instead of serving
    out a sentence earned against dead peers."""
    mgr = _StubManager()
    svc = SyncService(mgr, interval=30.0)  # would sleep 30 s per cycle
    svc.start()
    try:
        assert mgr.polls == 0
        svc.on_peer_connected()
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and mgr.polls == 0:
            time.sleep(0.01)
        assert mgr.polls >= 1
    finally:
        svc.stop()


# -- directed regression: range sync backtracks on a competing fork ------------


def _harness(slots=0):
    bls.set_backend("fake_crypto")
    h = BeaconChainHarness(_spec(), E, validator_count=16)
    if slots:
        h.extend_chain(slots, attest=False)
    return h


def test_range_sync_backtracks_on_competing_fork():
    """A node whose head sits on a fork of the serving peer's chain must
    import the competing chain from the finalized boundary — NOT retry
    the impossible window and ban the honest peer."""
    a = _harness()
    a.extend_chain(4, attest=False)
    b = _harness()
    na = NetworkService(a.chain, heartbeat_interval=None).start()
    nb = NetworkService(
        b.chain,
        heartbeat_interval=None,
        sync_config=SyncConfig(backoff_base_s=0.01, backoff_max_s=0.05),
    ).start()
    try:
        # shared prefix: b imports a's first 4 blocks
        for blk in na.blocks_by_range(1, 4):
            b.slot_clock.set_slot(int(blk.message.slot))
            b.chain.process_block(blk)
        # diverge: a extends its canonical chain; b builds its own block
        # at a slot a skipped differently (distinct chains above slot 4)
        a.extend_chain(12, attest=False)  # a: slots 1..16
        b.add_block_at_slot(6)  # b: fork block at 6 on the shared prefix
        assert b.chain.head_root != a.chain.head_root
        b.slot_clock.set_slot(16)
        peer = nb.connect("127.0.0.1", na.port)
        backtracks = _counter("sync_fork_backtracks_total")
        nb.sync.sync_with(peer)
        assert _counter("sync_fork_backtracks_total") == backtracks + 1
        # the competing chain (a's head) landed in b's fork choice
        assert b.chain.fork_choice.contains_block(a.chain.head_root)
        # and the honest peer is still connected, not downscored to a ban
        alive = nb.peers.get(peer.peer_id)
        assert alive is not None and not alive.banned
        assert alive.score > -40
    finally:
        na.stop()
        nb.stop()


# -- directed regression: lookup rotation spans the whole pool -----------------


class _LookupCtx:
    """select_peer in list order; only the honest peer serves the root."""

    def __init__(self, honest_id, block):
        self.honest_id = honest_id
        self.block = block

    def select_peer(self, pool, exclude=(), strikes=None):
        for p in pool:
            if p.peer_id not in exclude:
                return p
        return None

    def blocks_by_root(self, peer, roots):
        return [self.block] if peer.peer_id == self.honest_id else []


class _LookupPeers:
    def __init__(self, peers):
        self._peers = peers

    def peers(self):
        return list(self._peers)

    def report(self, peer_id, delta):
        pass


class _LookupService:
    def __init__(self, peers):
        self.peers = _LookupPeers(peers)


def test_failed_lookup_root_negative_cached():
    """A root the whole pool just failed to serve must not re-trigger a
    full-pool sweep per spam message — the negative cache bounds the
    amplification the whole-pool rotation would otherwise hand an
    unknown-root flood."""
    import lighthouse_tpu.network.sync.block_lookups as bl

    a = _harness(slots=1)
    peers = [_StubPeer(f"p{i}") for i in range(4)]
    ctx = _LookupCtx("nobody", None)  # every peer answers empty
    lookups = BlockLookups(
        _LookupService(peers), ctx, SyncConfig(lookup_max_attempts=3)
    )
    lookups.service.chain = a.chain
    lookups.service.reprocess = None
    lookups.service.processor = None
    garbage = b"\x66" * 32
    assert lookups._spawn(garbage, None, kind="single") is True
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and lookups.inflight_count():
        time.sleep(0.01)
    assert garbage in lookups._recent_failures
    # within the TTL the same root refuses to spawn another sweep
    assert lookups._spawn(garbage, None, kind="single") is False
    # an expired entry retries (a heal may have brought serving peers)
    lookups._recent_failures[garbage] -= bl.LOOKUP_NEGATIVE_TTL_S + 1
    assert lookups._spawn(garbage, None, kind="single") is True
    while lookups.inflight_count():
        time.sleep(0.01)
    # ... and a FRESH PEER voids the verdict immediately: "nobody had
    # it" only binds the pool that said so
    assert lookups._spawn(garbage, None, kind="single") is False
    lookups.peer_connected()
    assert lookups._spawn(garbage, None, kind="single") is True


def test_lookup_rotation_spans_whole_pool_past_empty_answers():
    """Six connected peers, only the LAST holds the block: the fetch must
    rotate past every honest 'don't have it' instead of stopping at the
    3-attempt budget (post-heal fork roots live on the other half)."""
    a = _harness(slots=1)
    head_root = a.chain.head_root
    block = a.chain._blocks_by_root[head_root]
    peers = [_StubPeer(f"p{i}") for i in range(6)]
    ctx = _LookupCtx("p5", block)
    lookups = BlockLookups(
        _LookupService(peers), ctx, SyncConfig(lookup_max_attempts=3)
    )
    got = lookups._fetch_root(head_root)
    assert got is not None
    assert got.message.hash_tree_root() == head_root


# -- storage lifecycle verbs (kill / restart / join) ---------------------------


def _drive_to_finality(net, start: int, target: int) -> int:
    """Run slots until every LIVE node shares one head and finalizes
    >= target (finality needs ~4 epochs of runway from a standing
    start). Returns the last slot driven."""
    S = E.SLOTS_PER_EPOCH
    slot = start
    for slot in range(start, start + 6 * S):
        net.run_slot(slot)
        heads = {n.chain.head_root for n in net.live_nodes}
        fins = [
            int(n.chain.finalized_checkpoint.epoch) for n in net.live_nodes
        ]
        if len(heads) == 1 and min(fins) >= target:
            return slot
    raise AssertionError(
        f"no finality >= {target} within 6 epochs (got {fins})"
    )


def test_kill_restart_needs_disk_backed_fleet():
    net = Testnet.create(_spec(), E, node_count=2, validator_count=8, seed=3)
    try:
        with pytest.raises(ScenarioFailure, match="disk-backed"):
            net.kill("node0")
    finally:
        net.shutdown()


def test_kill_restart_node_resumes_from_store(tmp_path):
    """The kill→restart cycle at its smallest shape: a 3-node disk-backed
    fleet finalizes, one node dies (store kept), the fleet keeps going,
    and the restarted node rebuilds from its KV store and reconverges —
    while finality never stalls."""
    S = E.SLOTS_PER_EPOCH
    net = Testnet.create(
        _spec(), E, node_count=3, validator_count=12, seed=7,
        db_dir=str(tmp_path),
    )
    try:
        oracle = ChainHealthOracle(net)
        slot = _drive_to_finality(net, start=1, target=1)
        oracle.check(
            require_single_head=True, min_finalized_epoch=1,
            what="pre-kill baseline",
        )
        fin_before = min(
            int(n.chain.finalized_checkpoint.epoch) for n in net.nodes
        )
        victim = net.kill("node2")
        assert not victim.alive
        assert len(net.live_nodes) == 2
        # the fleet runs an epoch without the victim
        net.run_until_slot(slot + S, start_slot=slot + 1)
        slot += S
        net.restart("node2")
        assert victim.alive and victim.client is not None
        # the restarted chain resumed from the anchor watermark, not genesis
        assert victim.chain.anchor_slot >= S
        net.settle(timeout=10.0)
        _drive_to_finality(net, start=slot + 1, target=fin_before + 1)
        oracle.check(
            require_single_head=True, min_finalized_epoch=fin_before + 1,
            what="post-restart",
        )
    finally:
        net.shutdown()


def test_join_node_checkpoint_syncs_into_live_fleet(tmp_path):
    """A brand-new node joins a running fleet by checkpoint sync off a
    peer's Beacon API: it anchors on the peer's finalized state (NOT
    genesis), follows the head forward, and serves its own health."""
    S = E.SLOTS_PER_EPOCH
    net = Testnet.create(
        _spec(), E, node_count=3, validator_count=12, seed=13,
        db_dir=str(tmp_path),
    )
    try:
        slot = _drive_to_finality(net, start=1, target=1)
        joiner = net.join("node3", checkpoint_from="node0")
        assert joiner.alive
        assert len(net.live_nodes) == 4
        # anchored on finality, history absent below the anchor
        assert joiner.chain.anchor_slot >= S
        assert REGISTRY.counter("checkpoint_sync_boots_total").value() >= 1
        net.settle(timeout=10.0)
        net.run_until_slot(slot + S, start_slot=slot + 1)
        net.wait_for(
            lambda: joiner.chain.head_root
            == net.node("node0").chain.head_root,
            timeout=20.0, what="joiner follows the live head",
        )
        oracle = ChainHealthOracle(net)
        c = oracle.chain_block(joiner)
        assert c["head_slot"] >= slot
    finally:
        net.shutdown()


# -- full-fleet scenarios (slow) -----------------------------------------------


@pytest.mark.slow
def test_partition_heal_six_node_fleet():
    report = run_partition_heal_scenario(_spec(), E)
    assert report["max_reorg_depth"] >= 1  # competing forks really built
    assert report["recovery_slots"] <= 6 * E.SLOTS_PER_EPOCH


@pytest.mark.slow
def test_partition_heal_ten_node_fleet():
    """The full-fleet regime: 10 real nodes, uneven halves, competing
    forks, convergence + finality after heal."""
    report = run_partition_heal_scenario(
        _spec(), E, node_count=10, validator_count=50, seed=11
    )
    assert report["max_reorg_depth"] >= 1
    assert report["recovery_to_finality_s"] > 0


@pytest.mark.slow
def test_late_delivery_regime():
    report = run_late_delivery_scenario(_spec(), E)
    assert report["recovery_slots"] <= 6 * E.SLOTS_PER_EPOCH


@pytest.mark.slow
def test_gossip_flood_sheds_and_finalizes():
    report = run_gossip_flood_scenario(_spec(), E)
    assert report["flood_sent"] > 0
    assert any(v > 0 for v in report["shed"].values())
    assert min(report["finalized"]) >= 1


@pytest.mark.slow
def test_churn_soak_fleet_keeps_finalizing_with_bounded_stores():
    """The churn regime: every round ~20% of the fleet dies and restarts
    from disk while the oracle asserts finality never stalls, heads
    reconverge, and the migrator keeps the hot stores bounded."""
    report = run_churn_soak_scenario(_spec(), E, churn_rounds=2)
    assert report["churn_rounds"] == 2
    assert report["finalized_epoch_min"] >= 3
    assert report["finalized_slots_per_wall_s"] > 0
    # bounded hot store: growth over the whole churn stays under the
    # oracle's 4x budget (the per-round check already enforced it live)
    assert report["hot_store_growth"] <= 4.0


@pytest.mark.slow
def test_block_production_bounded_under_flood():
    """Proposals keep landing — and the block_production trace root
    keeps a bounded mean — while attacker nodes flood the gossip lanes
    the production pipeline shares workers with."""
    report = run_production_under_flood_scenario(_spec(), E)
    assert report["flood_sent"] > 0
    assert report["blocks_published"] > 0
    assert report["mean_production_ms"] <= 1000.0
    assert min(report["finalized"]) >= 1
