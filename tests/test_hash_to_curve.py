"""RFC 9380 conformance for hash-to-G2 (BLS12381G2_XMD:SHA-256_SSWU_RO_).

Pins (1) the expand_message_xmd SHA-256 expander against RFC 9380 §K.1
vectors, (2) the full hash_to_curve pipeline against the §J.10.1
known-answer vectors, and (3) the 3-isogeny rational map against the
Appendix E.3 coefficient table via exact polynomial expansion of the
Vélu-derived map (the two must agree coefficient-for-coefficient).

Reference parity: the reference hashes to G2 inside blst with the same
ciphersuite (crypto/bls/src/impls/blst.rs:13 DST); matching the RFC vectors
is what makes signatures wire-compatible with it.
"""

import lighthouse_tpu.crypto.bls12_381.fields as F
import lighthouse_tpu.crypto.bls12_381.hash_to_curve as H
from lighthouse_tpu.crypto.bls12_381.curve import (
    FQ2,
    H2_EFF,
    g2_in_subgroup,
    to_affine,
)
from lighthouse_tpu.crypto.bls12_381.fields import P
from lighthouse_tpu.crypto.bls12_381.hash_to_curve import (
    expand_message_xmd,
    hash_to_g2,
    map_to_curve_sswu,
)

# --- §K.1: expand_message_xmd with SHA-256 ---------------------------------

XMD_DST = b"QUUX-V01-CS02-with-expander-SHA256-128"
XMD_VECTORS = [
    (b"", 0x20, "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235"),
    (b"abc", 0x20, "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a0d605615"),
    (
        b"abcdef0123456789",
        0x20,
        "eff31487c770a893cfb36f912fbfcbff40d5661771ca4b2cb4eafe524333f5c1",
    ),
]


def test_expand_message_xmd_rfc_vectors():
    for msg, n, expect in XMD_VECTORS:
        assert expand_message_xmd(msg, XMD_DST, n).hex() == expect


# --- §J.10.1: BLS12381G2_XMD:SHA-256_SSWU_RO_ ------------------------------

G2_DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"
G2_VECTORS = [
    (
        b"",
        (
            0x0141EBFBDCA40EB85B87142E130AB689C673CF60F1A3E98D69335266F30D9B8D4AC44C1038E9DCDD5393FAF5C41FB78A,
            0x05CB8437535E20ECFFAEF7752BADDF98034139C38452458BAEEFAB379BA13DFF5BF5DD71B72418717047F5B0F37DA03D,
            0x0503921D7F6A12805E72940B963C0CF3471C7B2A524950CA195D11062EE75EC076DAF2D4BC358C4B190C0C98064FDD92,
            0x12424AC32561493F3FE3C260708A12B7C620E7BE00099A974E259DDC7D1F6395C3C811CDD19F1E8DBF3E9ECFDCBAB8D6,
        ),
    ),
    (
        b"abc",
        (
            0x02C2D18E033B960562AAE3CAB37A27CE00D80CCD5BA4B7FE0E7A210245129DBEC7780CCC7954725F4168AFF2787776E6,
            0x139CDDBCCDC5E91B9623EFD38C49F81A6F83F175E80B06FC374DE9EB4B41DFE4CA3A230ED250FBE3A2ACF73A41177FD8,
            0x1787327B68159716A37440985269CF584BCB1E621D3A7202BE6EA05C4CFE244AEB197642555A0645FB87BF7466B2BA48,
            0x00AA65DAE3C8D732D10ECD2C50F8A1BAF3001578F71C694E03866E9F3D49AC1E1CE70DD94A733534F106D4CEC0EDDD16,
        ),
    ),
]


def test_hash_to_g2_rfc_vectors():
    for msg, (xc0, xc1, yc0, yc1) in G2_VECTORS:
        pt = hash_to_g2(msg, G2_DST)
        (gx0, gx1), (gy0, gy1) = to_affine(FQ2, pt)
        assert (gx0, gx1, gy0, gy1) == (xc0, xc1, yc0, yc1), msg
        assert g2_in_subgroup(pt)


def test_h2_eff_matches_rfc_constant():
    # RFC 9380 §8.8.2 h_eff literal
    assert H2_EFF == int(
        "0xbc69f08f2ee75b3584c6a0ea91b352888e2a8e9145ad7689986ff03150"
        "8ffe1329c2f178731db956d82bf015d1212b02ec0ec69d7477c1ae954cbc"
        "06689f6a359894c0adebbf6b4e8020005aaa95551",
        16,
    )


# --- Appendix E.3 isogeny table vs the Vélu-derived map --------------------


def _pmul(a, b):
    out = [(0, 0)] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        for j, bj in enumerate(b):
            out[i + j] = F.f2_add(out[i + j], F.f2_mul(ai, bj))
    return out


def _padd(a, b):
    n = max(len(a), len(b))
    za, zb = a + [(0, 0)] * (n - len(a)), b + [(0, 0)] * (n - len(b))
    return [F.f2_add(x, y) for x, y in zip(za, zb)]


def _pscale(a, s):
    return [F.f2_mul(c, s) for c in a]


def test_isogeny_matches_rfc_e3_table():
    """Expand x_num=(x·d²+t·d+u)/9, y_num=-(d³-t·d-2u)/27 over d=x-x0 and
    compare against the RFC 9380 E.3 k_(i,j) coefficient table."""
    x0, t, u = H._X0, H._T, H._U
    inv9 = (pow(9, -1, P), 0)
    inv27 = (pow(27, -1, P), 0)
    d = [F.f2_neg(x0), F.F2_ONE]
    d2, d3 = _pmul(d, d), _pmul(_pmul(d, d), d)
    xp = [(0, 0), (1, 0)]
    x_num = _pscale(_padd(_padd(_pmul(xp, d2), _pscale(d, t)), [u]), inv9)
    y_num = _pscale(
        _padd(_padd(d3, _pscale(d, F.f2_neg(t))), [F.f2_mul_scalar(u, P - 2)]),
        F.f2_neg(inv27),
    )

    K1_01 = 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6
    k1 = [
        (K1_01, K1_01),
        (0, 0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A),
        (
            0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
            0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D,
        ),
        (0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1, 0),
    ]
    k2 = [(0, P - 72), (12, P - 12), (1, 0)]
    K3_00 = 0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706
    k3 = [
        (K3_00, K3_00),
        (0, 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE),
        (
            0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
            0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F,
        ),
        (0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10, 0),
    ]
    k4 = [(P - 432, P - 432), (0, P - 216), (18, P - 18), (1, 0)]

    for got, want in [(x_num, k1), (d2, k2), (y_num, k3), (d3, k4)]:
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert F.f2_sub(g, w) == (0, 0)


def test_sswu_isogeny_composition_lands_on_e2():
    """SSWU output sits on E'; the isogeny must land on E2: y² = x³ + 4(1+u)."""
    for i in range(4):
        fe = H.hash_to_field_fq2(bytes([i]), 1, G2_DST)[0]
        x, y = map_to_curve_sswu(fe)
        # on E'?
        lhs = F.f2_sqr(y)
        rhs = F.f2_add(
            F.f2_add(F.f2_mul(F.f2_sqr(x), x), F.f2_mul(H._A, x)), H._B
        )
        assert F.f2_sub(lhs, rhs) == (0, 0)
        # isogeny lands on E2?
        ix, iy = H._isogeny_to_e2(x, y)
        lhs = F.f2_sqr(iy)
        rhs = F.f2_add(F.f2_mul(F.f2_sqr(ix), ix), (4, 4))
        assert F.f2_sub(lhs, rhs) == (0, 0)
