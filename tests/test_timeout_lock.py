"""TimeoutRwLock: loud-failure readers-writer lock (timeout_rw_lock.rs
analog) + concurrent chain imports stay consistent under it."""

import threading
import time

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.utils.timeout_lock import LockTimeout, TimeoutRwLock


def test_readers_share_writers_exclude():
    lk = TimeoutRwLock("t", timeout=0.5)
    g1 = lk.acquire_read()
    g2 = lk.acquire_read()  # concurrent readers OK
    with pytest.raises(LockTimeout, match="write lock 't'"):
        lk.acquire_write(timeout=0.1)
    g1.release()
    g2.release()
    w = lk.acquire_write()
    with pytest.raises(LockTimeout):
        lk.acquire_read(timeout=0.1)
    w.release()
    lk.acquire_read().release()


def test_writer_preference_blocks_new_readers():
    lk = TimeoutRwLock("t", timeout=1.0)
    r = lk.acquire_read()
    got_write = threading.Event()

    def writer():
        with lk.acquire_write(timeout=2.0):
            got_write.set()

    t = threading.Thread(target=writer)
    t.start()
    time.sleep(0.1)  # writer is now waiting
    with pytest.raises(LockTimeout):
        lk.acquire_read(timeout=0.15)  # new readers queue behind the writer
    r.release()
    t.join(timeout=2)
    assert got_write.is_set()


def test_guard_context_manager_and_double_release():
    lk = TimeoutRwLock("t")
    with lk.acquire_write():
        pass
    g = lk.acquire_write()
    g.release()
    g.release()  # idempotent
    lk.acquire_write().release()


def test_concurrent_gossip_imports_consistent():
    """Two threads hammer the same chain with interleaved blocks and
    attestation batches (the gossip-reader / VC race the lock exists
    for); the chain must finish consistent, with every block imported."""
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.types.chain_spec import minimal_spec
    from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

    prev = bls.backend_name()
    bls.set_backend("fake_crypto")
    try:
        # one harness produces the canonical inputs...
        src = BeaconChainHarness(minimal_spec(), E, validator_count=16)
        blocks, atts = [], []
        for slot in range(1, 2 * E.SLOTS_PER_EPOCH + 1):
            src.slot_clock.set_slot(slot)
            src.add_block_at_slot(slot)
            blocks.append(src.chain._blocks_by_root[src.chain.head_root])
            atts.append(src.make_unaggregated_attestations(slot, src.chain.head_root))
        # ...a second chain imports them from two racing threads
        dst = BeaconChainHarness(minimal_spec(), E, validator_count=16)
        dst.slot_clock.set_slot(2 * E.SLOTS_PER_EPOCH)
        errs = []

        def feed_blocks():
            for b in blocks:
                try:
                    dst.chain.process_block(b)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

        def feed_atts():
            for batch in atts:
                try:
                    dst.chain.process_attestation_batch(batch)
                except Exception:  # noqa: BLE001 — unknown-head atts racing
                    pass

        t1 = threading.Thread(target=feed_blocks)
        t2 = threading.Thread(target=feed_atts)
        t1.start(); t2.start()
        t1.join(timeout=60); t2.join(timeout=60)
        assert not errs, errs
        assert dst.chain.head_root == src.chain.head_root
        assert dst.chain.head_state.slot == 2 * E.SLOTS_PER_EPOCH
    finally:
        bls.set_backend(prev)
