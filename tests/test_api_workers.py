"""Multi-process Beacon API serving workers (http_api/workers.py, PR 18).

The read-replica tier end to end over real forks: N workers accepting on
the ONE pre-fork-bound public socket, read-tier routes served from each
worker's CoW snapshot (byte-identical to the parent's answer), mutations
and operator routes forwarded to the parent, the head-event generation
guard (a stale worker must never serve a pre-head body), crash respawn,
merged cross-process /metrics, health RSS aggregation, and a leak-free
stop()."""

import json
import os
import signal
import threading
import time
import urllib.request

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.http_api import HttpApiServer
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec

E = MinimalEthSpec
FULL_TABLE = "/eth/v1/beacon/states/head/validators"


def _get(port, path, timeout=10):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), r.read()


@pytest.fixture(scope="module")
def rig():
    prev = bls.backend_name()
    bls.set_backend("fake_crypto")
    h = BeaconChainHarness(minimal_spec(), E, validator_count=16)
    h.extend_chain(4)
    srv = HttpApiServer(h.chain, workers=2)
    # tests trigger head changes back to back; don't make them wait out
    # the production rotation coalescing window
    srv._pool.respawn_min_interval = 0.05
    srv.start()
    yield h, srv
    srv.stop()
    bls.set_backend(prev)


def _served_by(port, path, want, attempts=400):
    """Issue GETs until every server id in `want` has answered at least
    once (kernel accept balancing is not deterministic); returns
    {server_id: body}."""
    seen = {}
    for _ in range(attempts):
        _, hdr, body = _get(port, path)
        seen[hdr["X-Api-Served-By"]] = body
        if set(want) <= set(seen):
            return seen
    raise AssertionError(f"server ids seen {set(seen)} never covered {want}")


def _wait(pred, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def test_both_workers_serve_read_tier_locally(rig):
    h, srv = rig
    bodies = _served_by(srv.port, FULL_TABLE, {"http_api-w0", "http_api-w1"})
    # every replica answered from its own process, byte-identical
    assert bodies["http_api-w0"] == bodies["http_api-w1"]


def test_worker_bodies_byte_identical_to_parent(rig):
    h, srv = rig
    _, _, parent_body = _get(srv.parent_port, FULL_TABLE)
    bodies = _served_by(srv.port, FULL_TABLE, {"http_api-w0", "http_api-w1"})
    for name, body in bodies.items():
        assert body == parent_body, f"{name} diverged from the parent body"


def test_operator_routes_forward_to_parent(rig):
    h, srv = rig
    status, hdr, _ = _get(srv.port, "/eth/v1/node/version")
    assert status == 200
    assert hdr["X-Api-Served-By"] == "parent"
    assert hdr["X-Api-Forwarded-By"].startswith("http_api-w")


def test_posts_always_forward(rig):
    h, srv = rig
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/eth/v1/beacon/pool/voluntary_exits",
        data=b"not json",
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            hdr, status = dict(r.headers), r.status
    except urllib.error.HTTPError as e:  # bad body → 4xx, still forwarded
        hdr, status = dict(e.headers), e.code
    assert status != 200
    assert hdr["X-Api-Served-By"] == "parent"


def test_head_change_generation_guard(rig):
    """After a head event no response may carry the pre-head body — stale
    workers forward to the parent until the rotation hands them a fresh
    CoW snapshot, after which they serve locally again."""
    h, srv = rig
    pool = srv._pool
    # the headers listing embeds the head root — any head move changes it
    route = "/eth/v1/beacon/headers"
    _, _, before_body = _get(srv.port, route)
    pids_before = {w["pid"] for w in pool.worker_info()}
    resp_before = REGISTRY.counter("api_worker_respawns_total").value(
        reason="head_refresh"
    )
    h.extend_chain(1)
    # bound on staleness detection: the generation heartbeat cadence
    time.sleep(2 * pool.heartbeat_interval + 0.1)
    _, _, parent_now = _get(srv.parent_port, route)
    assert parent_now != before_body  # the head did change
    for _ in range(30):
        _, hdr, body = _get(srv.port, route)
        assert body != before_body, (
            f"pre-head body served by {hdr['X-Api-Served-By']} after the "
            "head event — the generation guard leaked a stale read"
        )
        assert body == parent_now
    # the supervisor rotates stale workers off the old snapshot…
    assert _wait(
        lambda: REGISTRY.counter("api_worker_respawns_total").value(
            reason="head_refresh"
        )
        > resp_before
    )
    assert _wait(lambda: {w["pid"] for w in pool.worker_info()} != pids_before)
    # …and the refreshed replicas serve the new head locally, byte-exact
    names = {w["name"] for w in pool.worker_info()}
    bodies = _served_by(srv.port, route, names)
    for name, body in bodies.items():
        assert body == parent_now, f"{name} served a stale post-rotation body"


def test_merged_metrics_spans_processes(rig):
    h, srv = rig
    # the forwarded-request counters live in worker processes; their delta
    # snapshots flow to the parent on the snapshot cadence
    _get(srv.port, "/eth/v1/node/version")

    def merged_has_forwards():
        _, _, body = _get(srv.port, "/metrics")
        text = body.decode()
        assert "api_worker_processes 2" in text
        for line in text.splitlines():
            if line.startswith(
                'api_worker_requests_forwarded_total{why="proxy_route"}'
            ):
                return float(line.rsplit(" ", 1)[1]) > 0
        return False

    assert _wait(merged_has_forwards, timeout=5.0)


def test_health_aggregates_worker_rss(rig):
    h, srv = rig
    _, _, body = _get(srv.port, "/lighthouse/health")
    data = json.loads(body)["data"]
    aw = data["system"]["api_workers"]
    assert aw["count"] == 2
    assert aw["rss_total_bytes"] > 0
    pids = {w["pid"] for w in aw["workers"]}
    assert len(pids) == 2 and os.getpid() not in pids
    assert pids == {w["pid"] for w in srv._pool.worker_info()}
    assert all(w["rss_bytes"] > 0 for w in aw["workers"])


def test_worker_death_respawns_and_serving_continues(rig):
    h, srv = rig
    pool = srv._pool
    victim = pool.worker_info()[0]["pid"]
    deaths = REGISTRY.counter("api_worker_respawns_total").value(reason="death")
    os.kill(victim, signal.SIGKILL)
    assert _wait(
        lambda: REGISTRY.counter("api_worker_respawns_total").value(
            reason="death"
        )
        == deaths + 1
    )
    assert _wait(
        lambda: len(pool.worker_info()) == 2
        and victim not in {w["pid"] for w in pool.worker_info()}
    )
    status, _, _ = _get(srv.port, FULL_TABLE)
    assert status == 200
    assert REGISTRY.gauge("api_worker_processes").value() == 2


def test_sse_stream_relays_through_worker(rig):
    h, srv = rig
    url = f"http://127.0.0.1:{srv.port}/eth/v1/events?topics=head&max_seconds=3"
    holder = {}

    def read():
        with urllib.request.urlopen(url, timeout=15) as r:
            holder["hdr"] = dict(r.headers)
            holder["body"] = r.read().decode()

    t = threading.Thread(target=read, daemon=True)
    t.start()
    time.sleep(0.4)  # subscription established before the event fires
    h.extend_chain(1)
    assert h.chain.event_handler.flush(10.0)
    t.join(20.0)
    assert not t.is_alive()
    # the stream is stateful: workers relay it to the parent's fan-out tier
    assert holder["hdr"]["X-Api-Served-By"] == "parent"
    assert holder["hdr"]["Content-Type"] == "text/event-stream"
    assert "event: head" in holder["body"]


def test_stop_leaves_zero_children_and_threads():
    bls_prev = bls.backend_name()
    bls.set_backend("fake_crypto")
    try:
        h = BeaconChainHarness(minimal_spec(), E, validator_count=8)
        h.extend_chain(2)
        sup_before = sum(
            1
            for t in threading.enumerate()
            if t.name == "http_api-supervisor"
        )
        srv = HttpApiServer(h.chain, workers=2).start()
        pids = [w["pid"] for w in srv._pool.worker_info()]
        assert len(pids) == 2
        status, _, _ = _get(srv.port, FULL_TABLE)
        assert status == 200
        srv.stop()
        # every child reaped — a zombie or survivor would still have a pid
        for pid in pids:
            with pytest.raises(ProcessLookupError):
                os.kill(pid, 0)
        assert srv._pool is None
        assert (
            sum(
                1
                for t in threading.enumerate()
                if t.name == "http_api-supervisor"
            )
            == sup_before
        )
    finally:
        bls.set_backend(bls_prev)


def test_single_process_mode_unchanged():
    bls_prev = bls.backend_name()
    bls.set_backend("fake_crypto")
    try:
        h = BeaconChainHarness(minimal_spec(), E, validator_count=8)
        h.extend_chain(2)
        srv = HttpApiServer(h.chain, workers=0).start()
        try:
            status, hdr, _ = _get(srv.port, FULL_TABLE)
            assert status == 200
            assert "X-Api-Served-By" not in hdr
        finally:
            srv.stop()
    finally:
        bls.set_backend(bls_prev)
