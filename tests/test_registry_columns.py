"""Resident columnar registry: differential fuzz vs the per-validator
oracle, copy-aliasing isolation, and the zero-rebuild steady-state guard.

The tentpole contract (registry_columns.py): the resident columns are a
PROVEN mirror of the persistent lists — every epoch transition run over
them must leave the state bit-identical to the retained legacy
per-validator path, under randomized participation, slashings, ejections,
activation churn and `state.copy()` aliasing, across phase0/altair/electra.
"""

import os
import random
from dataclasses import replace

import numpy as np
import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.beacon_chain.chain import _make_persistent
from lighthouse_tpu.state_processing import interop_genesis_state
from lighthouse_tpu.state_processing.per_epoch import process_epoch
from lighthouse_tpu.state_processing.registry_columns import (
    RegistryColumns,
    registry_columns_for,
)
from lighthouse_tpu.types.chain_spec import ForkName, minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

FAR = (1 << 64) - 1

_FORK_OVERRIDES = {
    ForkName.PHASE0: {},
    ForkName.ALTAIR: dict(altair_fork_epoch=0),
    ForkName.ELECTRA: dict(
        altair_fork_epoch=0,
        bellatrix_fork_epoch=0,
        capella_fork_epoch=0,
        deneb_fork_epoch=0,
        electra_fork_epoch=0,
    ),
}


def _base_state(fork: ForkName, n: int, seed: int):
    """A boundary-ready state with randomized registry shape: mixed
    activation/exit/slashing status, participation, scores, balances."""
    bls.set_backend("fake_crypto")
    rng = random.Random(seed)
    spec = replace(minimal_spec(), **_FORK_OVERRIDES[fork])
    state = interop_genesis_state(
        bls.interop_keypairs(8), 1_600_000_000, b"\x42" * 32, spec, E
    )
    v0 = state.validators[0]
    vs, bal = [], []
    for i in range(n):
        v = v0.copy()
        v.withdrawal_credentials = bytes([rng.choice([0x00, 0x01, 0x02])]) + (
            i.to_bytes(31, "little")
        )
        v.effective_balance = rng.choice(
            [32_000_000_000, 31_000_000_000, 16_000_000_000]
        )
        if rng.random() < 0.1:  # pending activation (churn fodder)
            v.activation_epoch = FAR
            v.activation_eligibility_epoch = rng.choice([FAR, 0, 1])
        if rng.random() < 0.08:  # exited / exiting
            v.exit_epoch = rng.randrange(1, 12)
            v.withdrawable_epoch = v.exit_epoch + 256
        if rng.random() < 0.06:  # slashed, some at the correlated epoch
            v.slashed = True
            v.withdrawable_epoch = rng.choice(
                [3 + E.EPOCHS_PER_SLASHINGS_VECTOR // 2, 40, 300]
            )
        vs.append(v)
        bal.append(rng.randrange(0, 40_000_000_000))
    state.validators = vs
    state.balances = bal
    if fork >= ForkName.ALTAIR:
        state.previous_epoch_participation = bytearray(
            rng.randrange(8) for _ in range(n)
        )
        state.current_epoch_participation = bytearray(
            rng.randrange(8) for _ in range(n)
        )
        state.inactivity_scores = [rng.randrange(6) for _ in range(n)]
    for s in range(len(state.slashings)):
        state.slashings[s] = rng.randrange(0, 64_000_000_000)
    state.slot = 4 * E.SLOTS_PER_EPOCH - 1
    # a justified past so rewards/finality logic engages
    t = type(state)
    state.finalized_checkpoint = state.finalized_checkpoint.copy()
    state.finalized_checkpoint.epoch = 1
    return state, spec


def _phase0_attestations(state, spec, rng):
    """Seed pending attestations so the phase0 reward components engage."""
    from lighthouse_tpu.state_processing.accessors import (
        get_beacon_committee,
        get_block_root,
        get_previous_epoch,
    )
    from lighthouse_tpu.types.containers import build_types

    t = build_types(E)
    prev = get_previous_epoch(state, E)
    atts = []
    for slot in range(prev * E.SLOTS_PER_EPOCH, (prev + 1) * E.SLOTS_PER_EPOCH):
        committee = get_beacon_committee(state, slot, 0, E)
        bits = [rng.random() < 0.8 for _ in committee]
        data = t.AttestationData(
            slot=slot,
            index=0,
            beacon_block_root=state.block_roots[
                slot % E.SLOTS_PER_HISTORICAL_ROOT
            ],
            source=state.previous_justified_checkpoint,
            target=t.Checkpoint(
                epoch=prev, root=get_block_root(state, prev, E)
            ),
        )
        atts.append(
            t.PendingAttestation(
                aggregation_bits=bits,
                data=data,
                inclusion_delay=rng.randrange(1, E.SLOTS_PER_EPOCH),
                proposer_index=rng.randrange(len(state.validators)),
            )
        )
    state.previous_epoch_attestations = atts


def _state_fingerprint(state):
    """Everything the epoch transition mutates, field by field — compared
    against the oracle run (sharper diagnostics than root equality, and
    independent of the caching machinery under test)."""
    fp = {
        "balances": list(state.balances),
        "validators": [
            (
                v.effective_balance,
                bool(v.slashed),
                v.activation_eligibility_epoch,
                v.activation_epoch,
                v.exit_epoch,
                v.withdrawable_epoch,
            )
            for v in state.validators
        ],
        "checkpoints": (
            state.previous_justified_checkpoint.epoch,
            state.current_justified_checkpoint.epoch,
            state.finalized_checkpoint.epoch,
        ),
        "slashings": list(state.slashings),
    }
    if hasattr(state, "inactivity_scores"):
        fp["scores"] = list(state.inactivity_scores)
        fp["prev_part"] = bytes(state.previous_epoch_participation)
        fp["curr_part"] = bytes(state.current_epoch_participation)
    # the from-scratch SSZ root (bypassing every cache) seals the rest
    fp["root"] = type(state).hash_tree_root_of(state)
    return fp


@pytest.mark.parametrize("fork", [ForkName.PHASE0, ForkName.ALTAIR, ForkName.ELECTRA])
@pytest.mark.parametrize("seed", [11, 12])
def test_resident_epoch_matches_per_validator_oracle(fork, seed):
    """Cross-fork differential fuzz: the resident-columns transition must
    be bit-identical to the legacy per-validator path on an identical
    state, including registry churn (activations, ejections, slashings)
    and balance movement."""
    from lighthouse_tpu.state_processing.epoch_reference import (
        process_epoch_reference,
    )

    rng = random.Random(seed)
    subject, spec = _base_state(fork, 700, seed)
    if fork == ForkName.PHASE0:
        _phase0_attestations(subject, spec, rng)
    legacy = subject.copy()  # plain lists: copies stay plain
    scalar = subject.copy()

    _make_persistent(subject)
    cols = registry_columns_for(subject)
    assert cols is not None
    cols.refresh(subject)

    process_epoch(subject, spec, E)

    # comparator 1: the scalar per-validator spec loops (the bench's
    # vs_baseline oracle)
    process_epoch_reference(scalar, spec, E)
    # comparator 2: the legacy snapshot path (r05's shipped code)
    os.environ["LIGHTHOUSE_TPU_RESIDENT_COLUMNS"] = "0"
    try:
        process_epoch(legacy, spec, E)
    finally:
        del os.environ["LIGHTHOUSE_TPU_RESIDENT_COLUMNS"]

    got = _state_fingerprint(subject)
    for name, other in (("scalar-oracle", scalar), ("legacy-snapshot", legacy)):
        want = _state_fingerprint(other)
        for key in want:
            assert got[key] == want[key], f"{fork}: '{key}' vs {name} diverged"


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_resident_epochs_survive_copy_aliasing_and_churn(seed):
    """Divergent copies must never share dirty writes: interleave
    randomized mutations, epoch transitions and state copies; every
    branch's cached root must equal its own from-scratch root."""
    rng = random.Random(seed)
    state, spec = _base_state(ForkName.ALTAIR, 520, seed)
    _make_persistent(state)
    registry_columns_for(state).refresh(state)
    branches = []
    for step in range(6):
        n = len(state.validators)
        op = rng.randrange(5)
        if op == 0:  # deposit-ish: append a validator
            v = state.validators[rng.randrange(n)].copy()
            v.withdrawal_credentials = rng.randbytes(32)
            state.validators.append(v)
            state.balances.append(32_000_000_000)
            state.inactivity_scores.append(0)
            state.previous_epoch_participation.append(0)
            state.current_epoch_participation.append(0)
        elif op == 1:  # slashing-ish mutation through the CoW discipline
            v = state.validators.mutate(rng.randrange(n))
            v.slashed = True
            v.withdrawable_epoch = 4 + E.EPOCHS_PER_SLASHINGS_VECTOR // 2
        elif op == 2:  # balance churn through the object path
            for _ in range(rng.randrange(1, 50)):
                state.balances[rng.randrange(n)] = rng.randrange(
                    40_000_000_000
                )
        elif op == 3:  # a full epoch transition on the resident path
            state.slot = (
                (state.slot // E.SLOTS_PER_EPOCH) + 1
            ) * E.SLOTS_PER_EPOCH - 1
            process_epoch(state, spec, E)
        else:  # branch: keep a copy, later mutate the original
            cp = state.copy()
            branches.append((cp, cp.hash_tree_root()))
        assert state.hash_tree_root() == type(state).hash_tree_root_of(state), (
            f"step {step} (op {op})"
        )
    for cp, root in branches:
        assert cp.hash_tree_root() == root
        assert root == type(cp).hash_tree_root_of(cp)


def test_validator_root_rows_match_per_object_ssz():
    """The columns' leaf-matrix element roots are bit-identical to
    per-object SSZ Merkleization for every validator shape in the fuzz
    registry (slashed/exited/pending/compounding)."""
    state, _ = _base_state(ForkName.ALTAIR, 400, 31)
    _make_persistent(state)
    cols = registry_columns_for(state)
    cols.refresh(state)
    rows = cols.validator_root_rows(None)
    for i, v in enumerate(state.validators):
        assert rows[i].tobytes() == type(v).hash_tree_root_of(v), i
    # sparse gather agrees too
    idx = np.array([0, 7, 399], dtype=np.int64)
    sparse = cols.validator_root_rows(idx)
    for r, i in enumerate(idx):
        assert sparse[r].tobytes() == rows[int(i)].tobytes()


def test_phase0_vectorized_deltas_match_reference_oracle():
    """Satellite: the vectorized phase0 get_attestation_deltas /
    process_slashings must equal the retained loop oracles."""
    from lighthouse_tpu.state_processing.per_epoch import (
        get_attestation_deltas,
        get_attestation_deltas_reference,
        process_slashings,
        process_slashings_reference,
    )

    for seed in (41, 42):
        rng = random.Random(seed)
        state, spec = _base_state(ForkName.PHASE0, 360, seed)
        _phase0_attestations(state, spec, rng)
        rewards, penalties = get_attestation_deltas(state, E)
        ref_r, ref_p = get_attestation_deltas_reference(state, E)
        assert [int(x) for x in rewards] == ref_r
        assert [int(x) for x in penalties] == ref_p

        # slashings: vectorized bulk writeback vs per-index loop
        a = state.copy()
        b = state.copy()
        process_slashings(a, E)
        os.environ["LIGHTHOUSE_TPU_RESIDENT_COLUMNS"] = "0"
        try:
            process_slashings_reference(b, E)
        finally:
            del os.environ["LIGHTHOUSE_TPU_RESIDENT_COLUMNS"]
        assert list(a.balances) == list(b.balances)


def test_shuffle_list_matches_compute_shuffled_index_elementwise():
    """Satellite: the batched one-call-per-round shuffle must equal the
    scalar spec algorithm element-wise (shuffle_list semantics:
    out[i] == values[compute_shuffled_index(i)])."""
    from lighthouse_tpu.state_processing.shuffle import (
        _shuffled_positions,
        compute_shuffled_index,
        shuffle_list,
    )

    rng = random.Random(5)
    for n in (2, 7, 255, 256, 257, 800):
        seed = rng.randbytes(32)
        rounds = E.SHUFFLE_ROUND_COUNT
        perm = _shuffled_positions(n, seed, rounds)
        values = list(range(1000, 1000 + n))
        shuffled = shuffle_list(values, seed, rounds)
        for i in range(n):
            want = compute_shuffled_index(i, n, seed, rounds)
            assert int(perm[i]) == want, (n, i)
            assert shuffled[i] == values[want], (n, i)


def test_committee_cache_slices_match_shuffled_permutation():
    """Committee assignment is one shuffled-permutation slice: committees
    partition the active set exactly, with plain-int members."""
    from lighthouse_tpu.state_processing.accessors import (
        CommitteeCache,
        get_active_validator_indices,
        get_current_epoch,
    )

    state, _ = _base_state(ForkName.ALTAIR, 640, 51)
    _make_persistent(state)
    epoch = get_current_epoch(state, E)
    cc = CommitteeCache.build(state, epoch, E)
    active = set(get_active_validator_indices(state, epoch))
    seen = []
    for slot in range(
        epoch * E.SLOTS_PER_EPOCH, (epoch + 1) * E.SLOTS_PER_EPOCH
    ):
        for index in range(cc.committees_per_slot):
            members = cc.committee(slot, index)
            assert all(type(m) is int for m in members)
            seen.extend(members)
    assert len(seen) == len(active)
    assert set(seen) == active


@pytest.mark.perf_smoke
def test_steady_state_epoch_rebuilds_zero_columns():
    """The residency guarantee: after the one-time warm-up, epoch
    transitions must perform ZERO full column rebuilds (the counter
    stays flat) and the columns channel must stay on the sparse path."""
    from lighthouse_tpu.metrics import REGISTRY

    state, spec = _base_state(ForkName.ALTAIR, 3000, 61)
    _make_persistent(state)
    registry_columns_for(state).refresh(state)  # one-time warm-up

    counter = REGISTRY.counter("registry_columns_rebuilds_total")
    before = dict(counter.values())
    for _ in range(3):
        # a block's worth of inter-epoch churn, then the transition
        rng = random.Random(int(state.slot))
        for _ in range(64):
            i = rng.randrange(len(state.balances))
            state.balances[i] = int(state.balances[i]) + 1
        state.validators.mutate(rng.randrange(len(state.validators))).slashed = True
        state.slot = (
            (state.slot // E.SLOTS_PER_EPOCH) + 1
        ) * E.SLOTS_PER_EPOCH - 1
        process_epoch(state, spec, E)
        state.hash_tree_root()
    after = dict(counter.values())
    assert after == before, f"columns rebuilt in steady state: {before} -> {after}"


def test_appended_zero_pubkey_validator_roots_correctly():
    """Regression: a validator appended with an all-zero pubkey must get
    the true subtree root sha256(64 zero bytes) — the sparse refresh's
    pubkey diff runs against zero-extended columns, so appended rows
    must be hashed unconditionally."""
    state, _ = _base_state(ForkName.ALTAIR, 300, 81)
    _make_persistent(state)
    cols = registry_columns_for(state)
    cols.refresh(state)
    v = state.validators[0].copy()
    v.pubkey = b"\x00" * 48
    state.validators.append(v)
    state.balances.append(1)
    state.inactivity_scores.append(0)
    state.previous_epoch_participation.append(0)
    state.current_epoch_participation.append(0)
    cols.refresh(state)
    rows = cols.validator_root_rows(np.array([300], dtype=np.int64))
    assert rows[0].tobytes() == type(v).hash_tree_root_of(
        state.validators[300]
    )
    assert state.hash_tree_root() == type(state).hash_tree_root_of(state)


def test_columns_detach_on_plain_list_replacement():
    """Wholesale field replacement with a plain list breaks residency
    safely: the columns detach and the state keeps rooting correctly."""
    state, spec = _base_state(ForkName.ALTAIR, 300, 71)
    _make_persistent(state)
    registry_columns_for(state).refresh(state)
    state.hash_tree_root()
    state.balances = [1_000_000_000] * len(state.validators)  # plain again
    assert registry_columns_for(state) is None
    assert "_registry_columns" not in state.__dict__
    assert state.hash_tree_root() == type(state).hash_tree_root_of(state)
    state.slot = ((state.slot // E.SLOTS_PER_EPOCH) + 1) * E.SLOTS_PER_EPOCH - 1
    process_epoch(state, spec, E)  # legacy path, still correct
    assert state.hash_tree_root() == type(state).hash_tree_root_of(state)
