"""Eth1 deposit follower + eth1 genesis service (beacon_node/eth1)."""

from dataclasses import replace

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.eth1 import (
    DepositCacheError,
    DepositLog,
    Eth1GenesisService,
    Eth1Service,
    MockEth1Provider,
)
from lighthouse_tpu.state_processing.genesis import build_deposit_data
from lighthouse_tpu.state_processing.per_block import process_deposit
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


@pytest.fixture()
def rig():
    bls.set_backend("fake_crypto")
    spec = replace(
        minimal_spec(),
        min_genesis_active_validator_count=4,
        min_genesis_time=1_500_000_000,
        genesis_delay=60,
        eth1_follow_distance=2,
    )
    provider = MockEth1Provider(spec)
    service = Eth1Service(provider, spec, E)
    kps = bls.interop_keypairs(8)
    return spec, provider, service, kps


def test_deposit_cache_contiguity_and_proofs(rig):
    spec, provider, service, kps = rig
    datas = [build_deposit_data(kp, 32_000_000_000, spec, E) for kp in kps[:4]]
    for d in datas:
        provider.submit_deposit(d)
    provider.mine_block()
    service.update()
    assert len(service.deposit_cache.logs) == 4

    # non-contiguous insert refused
    with pytest.raises(DepositCacheError):
        service.deposit_cache.insert_log(
            DepositLog(index=9, deposit_data=datas[0], block_number=1)
        )

    # the proofs verify through real deposit processing
    from lighthouse_tpu.state_processing import interop_genesis_state

    state = interop_genesis_state(kps[4:8], 1_600_000_000, b"\x42" * 32, spec, E)
    deposits = service.deposit_cache.get_deposits(0, 2, 4)
    state.eth1_data.deposit_root = service.deposit_cache.deposit_root(4)
    state.eth1_data.deposit_count = 4
    state.eth1_deposit_index = 0
    n0 = len(state.validators)
    for dep in deposits:
        process_deposit(state, dep, spec, E)
    assert len(state.validators) == n0 + 2


def test_eth1_vote_follows_distance(rig):
    spec, provider, service, kps = rig
    for d in (build_deposit_data(kp, 32_000_000_000, spec, E) for kp in kps[:4]):
        provider.submit_deposit(d)
    for _ in range(10):
        provider.mine_block()
    service.update()

    from lighthouse_tpu.state_processing import interop_genesis_state

    state = interop_genesis_state(kps[:4], 2_000_000_000, b"\x42" * 32, spec, E)
    vote = service.eth1_data_for_voting(state)
    # candidate must be behind the follow distance and carry the cache root
    assert vote.deposit_count == 4
    assert vote.deposit_root == service.deposit_cache.deposit_root(4)

    # no eligible candidate → default vote (current eth1_data)
    empty = Eth1Service(MockEth1Provider(spec), spec, E)
    assert empty.eth1_data_for_voting(state) == state.eth1_data


def test_eth1_genesis_service_builds_valid_genesis(rig):
    spec, provider, service, kps = rig
    gs = Eth1GenesisService(service, spec, E)
    assert gs.try_genesis() is None  # no deposits yet
    for kp in kps[:4]:
        provider.submit_deposit(build_deposit_data(kp, 32_000_000_000, spec, E))
    provider.mine_block()
    state = gs.try_genesis()
    assert state is not None
    assert len(state.validators) == 4
    assert state.genesis_time == provider._blocks[-1].timestamp + spec.genesis_delay
    from lighthouse_tpu.state_processing.genesis import is_valid_genesis_state

    assert is_valid_genesis_state(state, spec, E)
