"""Noise transport security (network/noise.py).

Unit level: the Noise XX handshake itself (key agreement, mutual ed25519
identity authentication, AEAD framing, tamper detection, resumable frame
reads). Integration level: two beacon nodes running the full gossip/RPC
stack over NoiseTransport, plus a plaintext dialer being rejected.

Reference match: lighthouse_network's transport builder secures every
connection with libp2p-noise (Noise_XX_25519_ChaChaPoly_SHA256 with a
signed identity payload)."""

import socket
import struct
import threading
import time
from dataclasses import replace

import pytest

# noise needs the optional `cryptography` package; the module itself
# imports fine without it (lazy guard) but every test here exercises the
# real primitives
pytest.importorskip("cryptography")

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.network import NetworkService
from lighthouse_tpu.network.noise import (
    NoiseError,
    NoiseIdentity,
    NoiseTransport,
    peer_id_of_identity_pub,
    secure_inbound,
    secure_outbound,
)
from lighthouse_tpu.network.rpc import RpcClient, RpcError
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


def _pair(seed_a=b"a", seed_b=b"b"):
    sa, sb = socket.socketpair()
    ia = NoiseIdentity.from_seed(seed_a)
    ib = NoiseIdentity.from_seed(seed_b)
    out = {}

    def responder():
        try:
            out["srv"] = secure_inbound(sb, ib)
        except NoiseError as e:
            out["err"] = e

    t = threading.Thread(target=responder)
    t.start()
    client = secure_outbound(sa, ia)
    t.join()
    if "err" in out:
        raise out["err"]
    return client, out["srv"], ia, ib


def test_handshake_mutual_authentication():
    client, server, ia, ib = _pair()
    assert client.remote_identity == ib.identity_pub_bytes()
    assert server.remote_identity == ia.identity_pub_bytes()
    assert client.remote_peer_id == ib.peer_id()
    assert server.remote_peer_id == ia.peer_id()
    # peer ids are identity multihashes over the protobuf pubkey
    assert client.remote_peer_id.startswith("0024")


def test_transport_round_trip_multi_frame():
    client, server, _, _ = _pair()
    big = b"0123456789abcdef" * 20000  # 320 KB: spans many 64KB frames
    # send from a thread: the kernel socket buffer is smaller than the
    # payload, so a synchronous sendall would deadlock against our read
    sender = threading.Thread(target=client.sendall, args=(big,))
    sender.start()
    got = bytearray()
    while len(got) < len(big):
        chunk = server.recv(1 << 16)
        assert chunk
        got += chunk
    assert bytes(got) == big
    sender.join()
    server.sendall(b"reply")
    assert client.recv(1024) == b"reply"


def test_bidirectional_interleaved():
    client, server, _, _ = _pair()
    for i in range(20):
        msg = bytes([i]) * (i * 100 + 1)
        client.sendall(msg)
        assert server.recv(len(msg) + 10) == msg
        server.sendall(msg)
        assert client.recv(len(msg) + 10) == msg


def test_tampered_ciphertext_rejected():
    client, server, _, _ = _pair()
    raw_client_side = client._sock  # underlying socket
    # craft a frame with flipped ciphertext bits
    ct = bytearray(client._send.encrypt(b"", b"attack payload"))
    ct[0] ^= 0xFF
    raw_client_side.sendall(struct.pack(">H", len(ct)) + bytes(ct))
    with pytest.raises(NoiseError):
        server.recv(1024)


def test_wrong_identity_signature_rejected():
    """A responder whose payload signs the WRONG static key must fail
    the initiator's verification."""
    sa, sb = socket.socketpair()
    ia = NoiseIdentity.from_seed(b"good")
    ib = NoiseIdentity.from_seed(b"evil")
    # break ib's certification: swap its static key after the payload
    # would have been built — easiest is to monkeypatch handshake_payload
    # to sign a different static key
    other = NoiseIdentity.from_seed(b"other")
    ib.handshake_payload = other.handshake_payload  # type: ignore[method-assign]
    errs = {}

    def responder():
        try:
            secure_inbound(sb, ib)
        except (NoiseError, OSError) as e:
            errs["srv"] = e

    t = threading.Thread(target=responder)
    t.start()
    with pytest.raises(NoiseError, match="identity signature"):
        secure_outbound(sa, ia)
    sa.close()
    t.join(timeout=5)


def test_recv_resumes_after_timeout_mid_frame():
    """A read timeout mid-frame must not desynchronize the stream (the
    gossip reader probes with short timeouts and retries)."""
    sa, sb = socket.socketpair()
    ia, ib = NoiseIdentity.from_seed(b"x"), NoiseIdentity.from_seed(b"y")
    out = {}
    t = threading.Thread(target=lambda: out.update(s=secure_inbound(sb, ib)))
    t.start()
    client = secure_outbound(sa, ia)
    t.join()
    server = out["s"]

    payload = b"slow delivery test"
    frame = client._send.encrypt(b"", payload)
    wire = struct.pack(">H", len(frame)) + frame
    # dribble the first 7 bytes, let the server time out, then finish
    sa.sendall(wire[:7])
    server.settimeout(0.2)
    for _ in range(3):
        try:
            got = server.recv(1024)
            break
        except TimeoutError:
            sa.sendall(wire[7:])  # finish the frame, then retry
    assert got == payload


def _harness(slots=0):
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    if slots:
        h.extend_chain(slots)
    return h


def test_two_nodes_full_stack_over_noise():
    """Gossip + RPC + range sync between two nodes, every stream secured
    with Noise XX."""
    a = _harness(slots=E.SLOTS_PER_EPOCH)
    b = _harness()
    na = NetworkService(a.chain, transport=NoiseTransport()).start()
    nb = NetworkService(b.chain, transport=NoiseTransport()).start()
    try:
        # RPC over noise (client must use the node's transport)
        client = RpcClient("127.0.0.1", na.port, transport=nb.transport)
        status = client.status(nb.local_status())
        assert int(status.head_slot) == a.chain.head_state.slot

        # plaintext dialer is refused by a noise listener
        plain = RpcClient("127.0.0.1", na.port, timeout=2.0)
        with pytest.raises((RpcError, OSError)):
            plain.status(nb.local_status())

        # peering + range sync over noise
        b.slot_clock.set_slot(a.chain.head_state.slot)
        peer = nb.connect("127.0.0.1", na.port)
        nb.sync.sync_with(peer)
        assert b.chain.head_root == a.chain.head_root
        time.sleep(0.2)  # let A's inbound-peer registration settle

        # gossip over noise: a fresh block produced on A reaches B
        slot = a.chain.head_state.slot + 1
        a.slot_clock.set_slot(slot)
        b.slot_clock.set_slot(slot)
        root, signed = a.add_block_at_slot(slot)
        na.publish_block(signed)
        deadline = time.time() + 10
        while time.time() < deadline and b.chain.head_root != root:
            time.sleep(0.05)
        assert b.chain.head_root == root
    finally:
        na.stop()
        nb.stop()
