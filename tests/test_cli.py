"""Umbrella CLI (lighthouse binary / lcli / database_manager analogs)."""

import json
from dataclasses import replace

import pytest

from lighthouse_tpu.cli import main
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.state_processing import interop_genesis_state
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E


@pytest.fixture()
def state_file(tmp_path):
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    kps = bls.interop_keypairs(8)
    st = interop_genesis_state(kps, 1_600_000_000, b"\x42" * 32, spec, E)
    p = tmp_path / "state.ssz"
    p.write_bytes(st.serialize())
    return p, st


def test_state_root_cmd(state_file, capsys):
    p, st = state_file
    assert main(["--spec", "minimal", "state-root", str(p)]) == 0
    out = capsys.readouterr().out.strip()
    assert out == "0x" + st.hash_tree_root().hex()


def test_pretty_ssz_cmd(state_file, capsys):
    p, st = state_file
    assert main(["--spec", "minimal", "pretty-ssz", "state", str(p)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["slot"] == 0
    assert doc["fork"]["current_version"].startswith("0x")


def test_skip_slots_cmd(state_file, tmp_path, capsys):
    p, st = state_file
    out = tmp_path / "advanced.ssz"
    assert (
        main(
            ["--spec", "minimal", "skip-slots", str(p), "5", "--output", str(out)]
        )
        == 0
    )
    from lighthouse_tpu.types.containers import build_types

    advanced = build_types(E).types_for_fork(
        build_types(E).fork_of_state(st)
    ).BeaconState.deserialize(out.read_bytes())
    assert advanced.slot == 5


def test_db_cmds(tmp_path, capsys):
    from lighthouse_tpu.store import HotColdDB
    from lighthouse_tpu.store.kv import SqliteStore
    from lighthouse_tpu.types.containers import build_types

    path = str(tmp_path / "db.sqlite")
    HotColdDB(SqliteStore(path), types=build_types(E)).hot.close()
    assert main(["db", "version", path]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["compatible"] is True
    assert main(["db", "inspect", path]) == 0
    inspect = json.loads(capsys.readouterr().out)
    assert "beacon_block" in inspect
    assert main(["db", "migrate", path]) == 0


def test_interop_keys_cmd(capsys):
    assert main(["interop-keys", "2"]) == 0
    out = capsys.readouterr().out
    assert "a99a76ed7796f7be22d5b7e8" in out  # well-known interop pk 0


def test_boot_node_cmd_serves_discovery(capsys):
    """boot-node subcommand (boot_node crate analog): prints its record
    and answers discovery queries while running."""
    import threading
    import time

    from lighthouse_tpu.network.discovery import DiscoveryService, Enr

    t = threading.Thread(target=main, args=(["boot-node", "--run-for", "3"],))
    t.start()
    time.sleep(0.5)
    out = capsys.readouterr().out
    enr = Enr.from_dict(json.loads(out.strip().splitlines()[0]))
    d = DiscoveryService(tcp_port=9400, bootnodes=[enr]).start()
    try:
        assert d.ping(enr)
        d.discover()  # registers us at the bootnode
    finally:
        d.stop()
        t.join(timeout=5)


def test_db_migrate_v1_blob_prefix(tmp_path, capsys):
    """v1→v2 migration prepends the slot prefix to BLOB_SIDECARS values."""
    from lighthouse_tpu.store.hot_cold import SCHEMA_VERSION_KEY
    from lighthouse_tpu.store.kv import DBColumn, SqliteStore
    from lighthouse_tpu.types.containers import build_types

    t = build_types(E)
    sc = t.BlobSidecar()
    hdr = sc.signed_block_header.message.copy()
    hdr.slot = 77
    sc.signed_block_header = t.SignedBeaconBlockHeader(
        message=hdr, signature=b"\x00" * 96
    )
    data = sc.serialize()
    v1_value = len(data).to_bytes(4, "little") + data  # no slot prefix

    path = str(tmp_path / "v1.db")
    store = SqliteStore(path)
    store.put(DBColumn.BEACON_META, SCHEMA_VERSION_KEY, (1).to_bytes(8, "little"))
    store.put(DBColumn.BLOB_SIDECARS, b"\x0c" * 32, v1_value)
    store.close()

    assert main(["--spec", "minimal", "db", "migrate", path]) == 0
    assert "migrated v1 -> v2 (1 blob entries)" in capsys.readouterr().out

    store = SqliteStore(path)
    raw = store.get(DBColumn.BLOB_SIDECARS, b"\x0c" * 32)
    assert int.from_bytes(raw[:8], "little") == 77
    assert raw[8:] == v1_value
    assert (
        int.from_bytes(store.get(DBColumn.BEACON_META, SCHEMA_VERSION_KEY), "little")
        == 2
    )
    store.close()


def test_am_wallet_and_exit_flow(tmp_path, capsys):
    """account_manager analog: wallet create/list on disk; a voluntary
    exit signed from a keystore and submitted over the Beacon API lands
    in the pool and in the next produced block."""
    from dataclasses import replace as _replace

    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
    from lighthouse_tpu.crypto.keystore import Keystore
    from lighthouse_tpu.http_api import HttpApiServer

    # wallets
    wdir = tmp_path / "wallets"
    assert main([
        "am", "wallet-create", "--dir", str(wdir), "--name", "w1",
        "--password", "pw", "--seed", "11" * 32, "--fast-kdf",
    ]) == 0
    created = json.loads(capsys.readouterr().out)
    assert created["name"] == "w1"
    assert main(["am", "wallet-list", "--dir", str(wdir)]) == 0
    listed = json.loads(capsys.readouterr().out)
    assert [w["name"] for w in listed] == ["w1"]

    # exit: chain where exits are immediately eligible
    bls.set_backend("host")
    try:
        spec = _replace(
            minimal_spec(), altair_fork_epoch=0, shard_committee_period=0
        )
        h = BeaconChainHarness(spec, E, validator_count=8)
        h.extend_chain(2)
        srv = HttpApiServer(h.chain).start()
        try:
            kp = h.keypairs[3]
            ks = Keystore.encrypt(
                kp.sk.scalar.to_bytes(32, "big"), "pw",
                pubkey=kp.pk.to_bytes(), _fast_kdf=True,
            )
            ks_path = tmp_path / "v3.json"
            ks_path.write_text(ks.to_json())
            rc = main([
                "--spec", "minimal", "am", "exit",
                "--keystore", str(ks_path), "--password", "pw",
                "--validator-index", "3", "--epoch", "0",
                "--beacon-url", f"http://127.0.0.1:{srv.port}",
            ])
            assert rc == 0
            assert json.loads(capsys.readouterr().out)["code"] == 200
            assert 3 in h.chain.op_pool._voluntary_exits
            # packed into the next block
            slot = h.chain.head_state.slot + 1
            h.slot_clock.set_slot(slot)
            h.add_block_at_slot(slot)
            assert h.chain.head_state.validators[3].exit_epoch != (
                (1 << 64) - 1
            )
        finally:
            srv.stop()
    finally:
        bls.set_backend("fake_crypto")
