"""SSZ serialization + hash-tree-root conformance.

Vectors: hand-computed per the SSZ spec plus well-known roots (zero containers,
spec examples). Mirrors the role of ef-tests ssz_static/ssz_generic
(testing/ef_tests/src/cases/ssz_*.rs in the reference).
"""

import pytest

from lighthouse_tpu.ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    Bytes32,
    Container,
    List,
    Vector,
    boolean,
    uint8,
    uint16,
    uint64,
    uint256,
)
from lighthouse_tpu.ssz.core import DeserializationError
from lighthouse_tpu.ssz.merkle import merkleize, mix_in_length
from lighthouse_tpu.utils.hash import ZERO_HASHES, hash32_concat, sha256


class Checkpoint(Container):
    epoch: uint64
    root: Bytes32


class VarTest(Container):
    a: uint16
    b: List[uint16, 1024]
    c: uint8


def test_uint_roundtrip():
    assert uint64.serialize_value(0x0123456789ABCDEF) == bytes.fromhex(
        "efcdab8967452301"
    )
    assert uint64.deserialize(bytes.fromhex("efcdab8967452301")) == 0x0123456789ABCDEF
    assert uint16.serialize_value(0x0102) == b"\x02\x01"
    assert uint256.serialize_value(1) == b"\x01" + b"\x00" * 31


def test_container_fixed_serialize():
    cp = Checkpoint(epoch=5, root=b"\x11" * 32)
    enc = cp.serialize()
    assert enc == (5).to_bytes(8, "little") + b"\x11" * 32
    assert Checkpoint.deserialize(enc) == cp


def test_container_variable_serialize():
    # Spec example shape: fixed(a) | offset(b) | fixed(c) | payload(b)
    v = VarTest(a=0xAABB, b=[1, 2, 3], c=0xFF)
    enc = v.serialize()
    assert enc[:2] == bytes.fromhex("bbaa")
    assert int.from_bytes(enc[2:6], "little") == 7  # 2 + 4 + 1
    assert enc[6] == 0xFF
    assert enc[7:] == b"\x01\x00\x02\x00\x03\x00"
    assert VarTest.deserialize(enc) == v


def test_container_bad_offset_rejected():
    v = VarTest(a=1, b=[1], c=2)
    enc = bytearray(v.serialize())
    enc[2] = 99  # corrupt first offset
    with pytest.raises(DeserializationError):
        VarTest.deserialize(bytes(enc))


def test_hash_tree_root_uint():
    assert uint64.hash_tree_root_of(5) == (5).to_bytes(8, "little") + b"\x00" * 24


def test_hash_tree_root_container():
    cp = Checkpoint(epoch=5, root=b"\x22" * 32)
    expect = hash32_concat(uint64.hash_tree_root_of(5), b"\x22" * 32)
    assert cp.hash_tree_root() == expect


def test_list_root_mixes_length():
    t = List[uint64, 1024]
    # 1024 uint64 = 256 chunks
    root = t.hash_tree_root_of([])
    assert root == mix_in_length(ZERO_HASHES[8], 0)
    root1 = t.hash_tree_root_of([7])
    leaf = (7).to_bytes(8, "little").ljust(32, b"\x00")
    expect = leaf
    for d in range(8):
        expect = hash32_concat(expect, ZERO_HASHES[d])
    assert root1 == mix_in_length(expect, 1)


def test_bitlist_roundtrip_and_root():
    t = Bitlist[9]
    bits = [True, False, True, True, False, False, False, True, True]
    enc = t.serialize_value(bits)
    assert enc == bytes([0b10001101, 0b00000011])
    assert t.deserialize(enc) == bits
    packed = bytes([0b10001101, 0b00000001]).ljust(32, b"\x00")
    assert t.hash_tree_root_of(bits) == mix_in_length(packed, 9)
    with pytest.raises(DeserializationError):
        t.deserialize(b"")
    with pytest.raises(DeserializationError):
        t.deserialize(bytes([0b10001101, 0b00000000]))  # no delimiter


def test_bitvector():
    t = Bitvector[10]
    bits = [True] * 10
    enc = t.serialize_value(bits)
    assert enc == bytes([0xFF, 0x03])
    assert t.deserialize(enc) == bits
    with pytest.raises(DeserializationError):
        t.deserialize(bytes([0xFF, 0x07]))  # excess bit


def test_bytelist():
    t = ByteList[64]
    assert t.serialize_value(b"ab") == b"ab"
    root = t.hash_tree_root_of(b"ab")
    chunk = b"ab".ljust(32, b"\x00")
    assert root == mix_in_length(hash32_concat(chunk, ZERO_HASHES[0]), 2)


def test_vector_of_containers():
    t = Vector[Checkpoint, 2]
    cps = [Checkpoint(epoch=1), Checkpoint(epoch=2)]
    root = t.hash_tree_root_of(cps)
    assert root == hash32_concat(cps[0].hash_tree_root(), cps[1].hash_tree_root())
    enc = t.serialize_value(cps)
    assert t.deserialize(enc) == cps


def test_merkleize_device_path_consistency():
    # Force the device path (>= 2048 chunks) and compare with small-scale host.
    chunks = [i.to_bytes(32, "little") for i in range(3000)]
    root_big = merkleize(chunks, limit=4096)
    # host reference
    import lighthouse_tpu.ssz.merkle as m

    saved = m._DEVICE_THRESHOLD
    try:
        m._DEVICE_THRESHOLD = 1 << 60
        root_host = merkleize(chunks, limit=4096)
    finally:
        m._DEVICE_THRESHOLD = saved
    assert root_big == root_host


def test_default_values():
    v = VarTest()
    assert v.a == 0 and v.b == [] and v.c == 0
    cp = Checkpoint()
    assert cp.root == b"\x00" * 32


def test_copy_is_deep():
    v = VarTest(a=1, b=[1, 2], c=3)
    w = v.copy()
    w.b.append(9)
    assert v.b == [1, 2]
