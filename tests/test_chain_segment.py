"""Chain-segment import with one segment-wide signature batch
(signature_verify_chain_segment, block_verification.rs:568)."""

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec

E = MinimalEthSpec
N_BLOCKS = 5


def _build_segment(n_validators=8, n_blocks=N_BLOCKS):
    src = BeaconChainHarness(minimal_spec(), E, validator_count=n_validators)
    blocks = []
    for slot in range(1, n_blocks + 1):
        src.slot_clock.set_slot(slot)
        src.add_block_at_slot(slot)
        blocks.append(src.chain._blocks_by_root[src.chain.head_root])
        src.attest_to_head(slot)
    return src, blocks


def test_segment_imports_with_single_batch(monkeypatch):
    bls.set_backend("host")
    try:
        src, blocks = _build_segment()
        dst = BeaconChainHarness(minimal_spec(), E, validator_count=8)
        dst.slot_clock.set_slot(N_BLOCKS)
        calls = []
        real = bls.verify_signature_sets

        def counting(sets, rng=None):
            calls.append(len(sets))
            return real(sets, rng)

        monkeypatch.setattr(bls, "verify_signature_sets", counting)
        res = dst.chain.process_chain_segment(blocks)
        assert res.error is None and res.imported == N_BLOCKS
        assert dst.chain.head_root == src.chain.head_root
        # ONE batch covered the whole segment: a single call holding every
        # set (proposals + randao + attestations across all blocks); the
        # per-block imports then ran signature-free
        assert len(calls) == 1, calls
        assert calls[0] >= 2 * N_BLOCKS  # >= proposal+randao per block
    finally:
        bls.set_backend("host")


def test_segment_with_bad_signature_rejected_atomically():
    bls.set_backend("host")
    src, blocks = _build_segment()
    # corrupt the proposer signature of the middle block
    bad = blocks[2]
    tampered = type(bad)(
        message=bad.message,
        signature=b"\x01" + bytes(bad.signature)[1:],
    )
    blocks[2] = tampered
    dst = BeaconChainHarness(minimal_spec(), E, validator_count=8)
    dst.slot_clock.set_slot(N_BLOCKS)
    res = dst.chain.process_chain_segment(blocks)
    assert res.error is not None
    assert res.imported == 0  # batch failed before anything imported
    assert dst.chain.head_state.slot == 0


def test_segment_not_a_chain_rejected():
    bls.set_backend("fake_crypto")
    try:
        _src, blocks = _build_segment()
        shuffled = [blocks[0], blocks[3], blocks[1]]
        dst = BeaconChainHarness(minimal_spec(), E, validator_count=8)
        dst.slot_clock.set_slot(N_BLOCKS)
        res = dst.chain.process_chain_segment(shuffled)
        assert res.error is not None and "chain" in str(res.error)
    finally:
        bls.set_backend("host")
