"""SSE broadcast fan-out tier (beacon_chain/events.py, PR 18).

The events.rs broadcast-channel semantics under concurrency: the chain's
publishing thread never blocks on consumers, each event is serialized to
wire bytes exactly once and the frame buffer is SHARED across every
subscriber queue, slow consumers drop-oldest (counted) and are evicted
after persistent lag, and flush() is the happens-before edge between
publishing and draining."""

import threading
import time

import pytest

from lighthouse_tpu.beacon_chain import events as ev_mod
from lighthouse_tpu.beacon_chain.events import (
    _EVICT_AFTER,
    _QUEUE_CAP,
    TOPIC_BLOCK,
    TOPIC_HEAD,
    ServerSentEventHandler,
)
from lighthouse_tpu.metrics import REGISTRY

_DELIVERED = REGISTRY.counter("sse_events_delivered_total")
_SERIALIZED = REGISTRY.counter("sse_events_serialized_total")
_DROPPED = REGISTRY.counter("sse_dropped_total")
_SUBS = REGISTRY.gauge("sse_subscribers")


def _publish_blocks(h, n, start=0):
    for i in range(n):
        h.register_block(bytes([i % 256]) * 32, start + i)


def test_serialize_once_shared_frame_across_1k_subscribers():
    h = ServerSentEventHandler()
    subs = [h.subscribe([TOPIC_BLOCK]) for _ in range(1000)]
    try:
        before = _SERIALIZED.value()
        _publish_blocks(h, 5)
        assert h.flush(10.0)
        # one serialization per EVENT, not per (event, subscriber)
        assert _SERIALIZED.value() == before + 5
        for _ in range(5):
            recs = [s.poll_record() for s in subs]
            assert all(r is not None for r in recs)
            frame0 = recs[0][1]
            assert isinstance(frame0, bytes)
            # the SAME buffer object landed in all 1000 queues
            assert all(r[1] is frame0 for r in recs)
    finally:
        for s in subs:
            h.unsubscribe(s)
        h.close()


def test_slow_consumer_evicted_counted_never_blocking():
    h = ServerSentEventHandler()
    stuck = h.subscribe([TOPIC_BLOCK])  # never drains
    healthy = h.subscribe([TOPIC_BLOCK])
    got, stop = [], threading.Event()

    def drainer():
        while True:
            ev = healthy.poll(timeout=0.05)
            if ev is not None:
                got.append(ev)
            elif stop.is_set():
                return

    t = threading.Thread(target=drainer, daemon=True)
    t.start()
    n = _QUEUE_CAP + _EVICT_AFTER + 20
    before_slow = _DROPPED.value(reason="slow_consumer")
    before_evict = _DROPPED.value(reason="evicted")
    t0 = time.monotonic()
    # paced in small bursts: the stuck consumer overflows regardless, but
    # the healthy drainer (whose queue also has cap _QUEUE_CAP) gets
    # scheduler time to keep up — the test isolates SLOW-consumer
    # eviction, not raw publisher-vs-consumer throughput
    for base in range(0, n, 32):
        _publish_blocks(h, min(32, n - base), start=base)
        time.sleep(0.005)
    publish_wall = time.monotonic() - t0
    assert h.flush(30.0)
    stop.set()
    t.join(10.0)
    try:
        # the stuck consumer was evicted, flagged, and counted — the
        # publishing thread never blocked on it (n cheap enqueues)
        assert stuck.evicted and stuck.closed
        assert stuck not in h._subs
        assert _DROPPED.value(reason="slow_consumer") - before_slow >= _EVICT_AFTER
        assert _DROPPED.value(reason="evicted") - before_evict == 1
        assert publish_wall < 10.0
        # the healthy concurrent drainer saw every event, in order
        assert len(got) == n
        assert [e["data"]["slot"] for e in got] == [str(i) for i in range(n)]
    finally:
        h.unsubscribe(healthy)
        h.close()


def test_eviction_gauge_and_double_unsubscribe_accounting():
    h = ServerSentEventHandler()
    base = _SUBS.value()
    stuck = h.subscribe([TOPIC_BLOCK])
    keeper = h.subscribe([TOPIC_HEAD])  # blocks don't match: never lags
    try:
        assert _SUBS.value() == base + 2
        _publish_blocks(h, _QUEUE_CAP + _EVICT_AFTER)
        assert h.flush(30.0)
        assert stuck.evicted
        assert _SUBS.value() == base + 1  # eviction adjusted the gauge
        # unsubscribing an already-evicted sub must NOT double-decrement
        h.unsubscribe(stuck)
        assert _SUBS.value() == base + 1
        h.unsubscribe(keeper)
        assert _SUBS.value() == base
        h.unsubscribe(keeper)  # idempotent
        assert _SUBS.value() == base
    finally:
        h.close()


def test_listeners_race_publish_without_corruption():
    h = ServerSentEventHandler()
    calls = []
    errors = []

    def mk(tag):
        def fn(topic, data):
            calls.append(tag)

        return fn

    listeners = [mk(i) for i in range(8)]
    stop = threading.Event()

    def churn():
        # add/remove listeners continuously while the publisher runs
        try:
            while not stop.is_set():
                for fn in listeners:
                    h.add_listener([TOPIC_BLOCK, TOPIC_HEAD], fn)
                for fn in listeners:
                    h.remove_listener(fn)
        except Exception as e:  # noqa: BLE001 — the test asserts absence
            errors.append(e)

    threads = [threading.Thread(target=churn, daemon=True) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        _publish_blocks(h, 300)
    finally:
        stop.set()
        for t in threads:
            t.join(10.0)
    assert not errors
    # consistent final state: churn always removed what it added
    assert h._listeners == []
    # a listener registered now still fires synchronously on publish
    marker = []
    h.add_listener([TOPIC_BLOCK], lambda t, d: marker.append(d))
    h.register_block(b"\xaa" * 32, 7)
    assert marker and marker[0]["slot"] == "7"
    h.close()


def test_listener_fault_contained():
    h = ServerSentEventHandler()

    def bad(topic, data):
        raise RuntimeError("boom")

    seen = []
    h.add_listener([TOPIC_BLOCK], bad)
    h.add_listener([TOPIC_BLOCK], lambda t, d: seen.append(t))
    h.register_block(b"\x01" * 32, 1)  # must not raise
    assert seen == [TOPIC_BLOCK]


def test_publish_overflow_counted_and_flush_stays_sound():
    h = ServerSentEventHandler()
    sub = h.subscribe([TOPIC_BLOCK])
    h.close()  # stop the broadcast thread; staged events now pile up
    h._bq = __import__("queue").Queue(maxsize=1)
    before = _DROPPED.value(reason="publish_overflow")
    _publish_blocks(h, 3)  # 1 staged, 2 overflow
    assert _DROPPED.value(reason="publish_overflow") == before + 2
    # overflow closed the flush() accounting for the lost events; the
    # re-armed thread (any subscribe re-arms) drains the staged one
    extra = h.subscribe([TOPIC_BLOCK])
    assert h.flush(10.0)
    assert sub.poll_record(timeout=5.0) is not None
    h.unsubscribe(sub)
    h.unsubscribe(extra)
    h.close()


def test_close_and_rearm():
    h = ServerSentEventHandler()
    s1 = h.subscribe([TOPIC_BLOCK])
    assert h._thread is not None and h._thread.is_alive()
    old = h._thread
    h.close()
    assert not old.is_alive()
    assert h._thread is None
    # a later subscribe re-arms a fresh broadcast thread
    s2 = h.subscribe([TOPIC_BLOCK])
    assert h._thread is not None and h._thread.is_alive()
    h.register_block(b"\x02" * 32, 9)
    assert h.flush(10.0)
    assert s2.poll() is not None
    h.unsubscribe(s1)
    h.unsubscribe(s2)
    h.close()


def test_flush_without_events_returns_immediately():
    h = ServerSentEventHandler()
    t0 = time.monotonic()
    assert h.flush(5.0)
    assert time.monotonic() - t0 < 1.0


def test_reinit_after_fork_keeps_listeners_drops_subs():
    h = ServerSentEventHandler()
    h.add_listener([TOPIC_HEAD], lambda t, d: None)
    sub = h.subscribe([TOPIC_BLOCK])
    h.register_block(b"\x03" * 32, 1)
    assert h.flush(10.0)
    h.reinit_after_fork()
    # subscriber queues belong to the parent's consumers — gone; the
    # synchronous listeners (cache invalidation) survive the fork
    assert h._subs == []
    assert len(h._listeners) == 1
    assert h._thread is None
    assert h._published_seq == 0 and h._delivered_seq == 0
    # and the handler still works post-reinit
    seen = []
    h.add_listener([TOPIC_BLOCK], lambda t, d: seen.append(d))
    h.register_block(b"\x04" * 32, 2)
    assert seen
    h.unsubscribe(sub)  # parent-side bookkeeping still safe to call
    h.close()


def test_subscribe_rejects_unknown_topics():
    h = ServerSentEventHandler()
    with pytest.raises(ValueError):
        h.subscribe(["nope"])
    with pytest.raises(ValueError):
        h.add_listener(["nope"], lambda t, d: None)


def test_delivered_counter_counts_per_subscriber_enqueue():
    h = ServerSentEventHandler()
    a = h.subscribe([TOPIC_BLOCK])
    b = h.subscribe([TOPIC_BLOCK, TOPIC_HEAD])
    try:
        before = _DELIVERED.value()
        _publish_blocks(h, 4)  # matches both subs → 8 enqueues
        h.register_head(b"\x05" * 32, 4, b"\x06" * 32)  # matches only b
        assert h.flush(10.0)
        assert _DELIVERED.value() == before + 9
    finally:
        h.unsubscribe(a)
        h.unsubscribe(b)
        h.close()


def test_module_constants_are_sane():
    # the bench and the eviction test both reason from these
    assert ev_mod._BROADCAST_CAP >= 4 * _QUEUE_CAP
    assert 0 < _EVICT_AFTER < _QUEUE_CAP
