"""Columnar attestation pipeline: differential fuzz vs the scalar oracle,
reject parity, participation-column residency/aliasing, and the satellite
fast paths (eth1 vote tally, batched sync-committee sampling, bitmask
max-cover, phase0 validate-then-mutate).

Contract (attestation_batch.py): the batched path must leave the state
bit-identical to `process_attestations_reference` — participation bytes,
balances (proposer reward floors!), and the state root — across forks,
randomized committees, sparse/full/duplicate aggregation patterns and
already-set flags; and a rejected batch must leave NO partial writes.
"""

import random
from dataclasses import replace

import numpy as np
import pytest

from lighthouse_tpu.beacon_chain.chain import _make_persistent
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.ssz.persistent import PersistentByteList
from lighthouse_tpu.state_processing import interop_genesis_state
from lighthouse_tpu.state_processing.accessors import (
    committee_cache_at,
    get_attesting_indices,
    get_current_epoch,
    get_previous_epoch,
)
from lighthouse_tpu.state_processing import attestation_batch
from lighthouse_tpu.state_processing.attestation_batch import (
    process_attestations,
    process_attestations_reference,
)

# the real calibrated threshold, captured before the force-columnar
# fixture zeroes it for the differential tests
_REAL_SMALL_BATCH_ROWS = attestation_batch._SMALL_BATCH_ROWS
from lighthouse_tpu.state_processing.per_block import (
    BlockProcessingError,
    ConsensusContext,
)
from lighthouse_tpu.state_processing.registry_columns import (
    registry_columns_for,
)
from lighthouse_tpu.types.chain_spec import ForkName, minimal_spec
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E

T = build_types(E)

_FORK_OVERRIDES = {
    ForkName.ALTAIR: dict(altair_fork_epoch=0),
    ForkName.DENEB: dict(
        altair_fork_epoch=0,
        bellatrix_fork_epoch=0,
        capella_fork_epoch=0,
        deneb_fork_epoch=0,
    ),
    ForkName.ELECTRA: dict(
        altair_fork_epoch=0,
        bellatrix_fork_epoch=0,
        capella_fork_epoch=0,
        deneb_fork_epoch=0,
        electra_fork_epoch=0,
    ),
}


@pytest.fixture(autouse=True)
def fake_crypto():
    old = bls.backend_name()
    bls.set_backend("fake_crypto")
    yield
    bls.set_backend(old)


@pytest.fixture(autouse=True)
def force_columnar(monkeypatch):
    """Zero the small-batch dispatch threshold so the minimal-preset
    fixtures exercise the columnar fold (the dispatch itself is covered
    by test_small_batch_dispatch)."""
    monkeypatch.setattr(attestation_batch, "_SMALL_BATCH_ROWS", 0)


def _att_state(fork: ForkName, n: int, seed: int):
    """A mid-epoch state with randomized participation (some flags
    already set) and non-trivial block roots, positioned so both
    previous- and current-epoch attestations are includable."""
    rng = random.Random(seed)
    spec = replace(minimal_spec(), **_FORK_OVERRIDES[fork])
    state = interop_genesis_state(
        bls.interop_keypairs(8), 1_600_000_000, b"\x42" * 32, spec, E
    )
    v0 = state.validators[0]
    vs, bal = [], []
    for i in range(n):
        v = v0.copy()
        v.withdrawal_credentials = i.to_bytes(32, "little")
        v.effective_balance = rng.choice(
            [32_000_000_000, 31_000_000_000, 16_000_000_000]
        )
        vs.append(v)
        bal.append(32_000_000_000)
    state.validators = vs
    state.balances = bal
    state.previous_epoch_participation = bytearray(
        rng.randrange(8) for _ in range(n)
    )
    state.current_epoch_participation = bytearray(
        rng.randrange(8) for _ in range(n)
    )
    state.inactivity_scores = [0] * n
    for s in range(len(state.block_roots)):
        state.block_roots[s] = bytes([s % 251]) * 32
    state.slot = 3 * E.SLOTS_PER_EPOCH + E.SLOTS_PER_EPOCH // 2
    return state, spec


def _make_attestations(state, fork, rng, count):
    """`count` valid attestations over random includable (slot, committee)
    pairs: random sparse/full bits, deliberate duplicates, and a mix of
    matching/missing head roots."""
    from lighthouse_tpu.state_processing.accessors import (
        get_block_root,
        get_block_root_at_slot,
    )

    current = get_current_epoch(state, E)
    lo = (
        current * E.SLOTS_PER_EPOCH - E.SLOTS_PER_EPOCH
        if fork >= ForkName.DENEB
        else state.slot - E.SLOTS_PER_EPOCH
    )
    hi = state.slot - E.MIN_ATTESTATION_INCLUSION_DELAY
    atts = []
    while len(atts) < count:
        slot = rng.randrange(lo, hi + 1)
        epoch = slot // E.SLOTS_PER_EPOCH
        cc = committee_cache_at(state, epoch, E)
        index = rng.randrange(cc.committees_per_slot)
        committee = cc.committee_array(slot, index)
        density = rng.choice([0.05, 0.5, 1.0])
        bits = [rng.random() < density for _ in range(committee.size)]
        if not any(bits):
            bits[rng.randrange(len(bits))] = True
        source = (
            state.current_justified_checkpoint
            if epoch == current
            else state.previous_justified_checkpoint
        )
        head = (
            get_block_root_at_slot(state, slot, E)
            if rng.random() < 0.7
            else b"\x99" * 32
        )
        att = T.Attestation(
            aggregation_bits=bits,
            data=T.AttestationData(
                slot=slot,
                index=index,
                beacon_block_root=head,
                source=source,
                target=T.Checkpoint(
                    epoch=epoch, root=get_block_root(state, epoch, E)
                ),
            ),
            signature=b"\x00" * 96,
        )
        atts.append(att)
        if rng.random() < 0.4 and len(atts) < count:
            # deliberate duplicate committee, different pattern: the
            # first-occurrence reward attribution fold must handle it
            bits2 = [b or (rng.random() < 0.3) for b in bits]
            atts.append(
                T.Attestation(
                    aggregation_bits=bits2,
                    data=att.data,
                    signature=b"\x00" * 96,
                )
            )
    return atts


def _ctxt(state):
    c = ConsensusContext(state.slot)
    c.set_proposer_index(0)
    return c


def _run_both(state, spec, fork, atts):
    """(batched-resident, scalar-plain) end states for the same input."""
    batched = state.copy()
    _make_persistent(batched)
    registry_columns_for(batched).refresh(batched)
    process_attestations(batched, atts, spec, E, False, _ctxt(batched), fork)
    oracle = state.copy()
    process_attestations_reference(
        oracle, atts, spec, E, False, _ctxt(oracle), fork
    )
    return batched, oracle


@pytest.mark.parametrize(
    "fork", [ForkName.ALTAIR, ForkName.DENEB, ForkName.ELECTRA]
)
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_batch_vs_reference_differential(fork, seed):
    rng = random.Random(100 + seed)
    state, spec = _att_state(fork, 192, seed)
    atts = _make_attestations(state, fork, rng, 24)
    batched, oracle = _run_both(state, spec, fork, atts)
    assert bytes(batched.previous_epoch_participation) == bytes(
        oracle.previous_epoch_participation
    )
    assert bytes(batched.current_epoch_participation) == bytes(
        oracle.current_epoch_participation
    )
    assert list(batched.balances) == list(oracle.balances)
    # representation-independent: the state roots agree too
    assert batched.hash_tree_root() == oracle.hash_tree_root()


def test_batch_matches_reference_on_already_set_flags():
    """A second identical batch earns the proposer nothing on either path."""
    fork = ForkName.ALTAIR
    rng = random.Random(7)
    state, spec = _att_state(fork, 128, 7)
    atts = _make_attestations(state, fork, rng, 12)
    batched, oracle = _run_both(state, spec, fork, atts)
    b2, o2 = batched.copy(), oracle.copy()
    process_attestations(b2, atts, spec, E, False, _ctxt(b2), fork)
    process_attestations_reference(o2, atts, spec, E, False, _ctxt(o2), fork)
    assert list(b2.balances) == list(o2.balances)
    assert list(b2.balances) == list(batched.balances)  # no new rewards
    assert bytes(b2.current_epoch_participation) == bytes(
        batched.current_epoch_participation
    )


@pytest.mark.parametrize("message", ["source", "target"])
def test_reject_parity_and_no_partial_writes(message):
    """Both paths reject the same malformed attestation, and the batched
    path leaves NO partial writes even when a LATER attestation in the
    block is the bad one (the scalar loop would have already mutated)."""
    fork = ForkName.ALTAIR
    rng = random.Random(21)
    state, spec = _att_state(fork, 128, 21)
    atts = _make_attestations(state, fork, rng, 6)
    bad = atts[-1]
    if message == "source":
        wrong = T.Checkpoint(
            epoch=bad.data.source.epoch, root=b"\x55" * 32
        )
        bad_data = T.AttestationData(
            slot=bad.data.slot,
            index=bad.data.index,
            beacon_block_root=bad.data.beacon_block_root,
            source=wrong,
            target=bad.data.target,
        )
        atts[-1] = T.Attestation(
            aggregation_bits=bad.aggregation_bits,
            data=bad_data,
            signature=b"\x00" * 96,
        )
        expect = "source checkpoint mismatch"
    else:
        bad_data = T.AttestationData(
            slot=bad.data.slot,
            index=bad.data.index,
            beacon_block_root=bad.data.beacon_block_root,
            source=bad.data.source,
            target=T.Checkpoint(
                epoch=bad.data.target.epoch + 5, root=bad.data.target.root
            ),
        )
        atts[-1] = T.Attestation(
            aggregation_bits=bad.aggregation_bits,
            data=bad_data,
            signature=b"\x00" * 96,
        )
        expect = "target"

    batched = state.copy()
    _make_persistent(batched)
    before_prev = bytes(batched.previous_epoch_participation)
    before_cur = bytes(batched.current_epoch_participation)
    before_bal = list(batched.balances)
    with pytest.raises(BlockProcessingError, match=expect):
        process_attestations(
            batched, atts, spec, E, False, _ctxt(batched), fork
        )
    assert bytes(batched.previous_epoch_participation) == before_prev
    assert bytes(batched.current_epoch_participation) == before_cur
    assert list(batched.balances) == before_bal

    oracle = state.copy()
    with pytest.raises(BlockProcessingError):
        process_attestations_reference(
            oracle, atts, spec, E, False, _ctxt(oracle), fork
        )


def test_reject_empty_bits_and_bad_length():
    fork = ForkName.ALTAIR
    rng = random.Random(33)
    state, spec = _att_state(fork, 128, 33)
    good = _make_attestations(state, fork, rng, 1)[0]
    empty = T.Attestation(
        aggregation_bits=[False] * len(good.aggregation_bits),
        data=good.data,
        signature=b"\x00" * 96,
    )
    with pytest.raises(BlockProcessingError, match="invalid indexed"):
        process_attestations(
            state.copy(), [empty], spec, E, False, _ctxt(state), fork
        )
    short = T.Attestation(
        aggregation_bits=good.aggregation_bits[:-1],
        data=good.data,
        signature=b"\x00" * 96,
    )
    with pytest.raises(BlockProcessingError, match="bitfield length"):
        process_attestations(
            state.copy(), [short], spec, E, False, _ctxt(state), fork
        )


def test_kill_switch_runs_scalar_path(monkeypatch):
    fork = ForkName.ALTAIR
    rng = random.Random(5)
    state, spec = _att_state(fork, 96, 5)
    atts = _make_attestations(state, fork, rng, 4)
    monkeypatch.setenv("LIGHTHOUSE_TPU_BATCH_ATTESTATIONS", "0")
    c = REGISTRY.counter("attestation_batch_total")
    before = c.value(path="scalar")
    off = state.copy()
    process_attestations(off, atts, spec, E, False, _ctxt(off), fork)
    assert c.value(path="scalar") == before + 1
    monkeypatch.delenv("LIGHTHOUSE_TPU_BATCH_ATTESTATIONS")
    on = state.copy()
    process_attestations(on, atts, spec, E, False, _ctxt(on), fork)
    assert bytes(off.current_epoch_participation) == bytes(
        on.current_epoch_participation
    )
    assert list(off.balances) == list(on.balances)


def test_indexed_attestations_shared_with_context():
    """The batch pipeline's columnar assembly must be what fork choice /
    the slasher / signature sets see: memoized on the context, sorted,
    and SSZ-identical to a field-machinery construction."""
    fork = ForkName.ALTAIR
    rng = random.Random(9)
    state, spec = _att_state(fork, 96, 9)
    atts = _make_attestations(state, fork, rng, 3)
    st = state.copy()
    _make_persistent(st)
    ctxt = _ctxt(st)
    process_attestations(st, atts, spec, E, False, ctxt, fork)
    for att in atts:
        indexed = ctxt.peek_indexed_attestation(att)
        assert indexed is not None
        expect = get_attesting_indices(st, att.data, att.aggregation_bits, E)
        assert list(indexed.attesting_indices) == expect
        rebuilt = T.IndexedAttestation(
            attesting_indices=expect,
            data=att.data,
            signature=att.signature,
        )
        assert indexed.hash_tree_root() == rebuilt.hash_tree_root()
        assert indexed.serialize() == rebuilt.serialize()


# --- participation columns: residency, aliasing, rotation -------------------


def test_participation_copy_aliasing_isolation():
    fork = ForkName.ALTAIR
    rng = random.Random(13)
    state, spec = _att_state(fork, 128, 13)
    _make_persistent(state)
    registry_columns_for(state).refresh(state)
    frozen = state.copy()
    frozen_bytes = bytes(frozen.current_epoch_participation)
    frozen_root = frozen.hash_tree_root()
    atts = _make_attestations(state, fork, rng, 8)
    process_attestations(state, atts, spec, E, False, _ctxt(state), fork)
    assert bytes(state.current_epoch_participation) != frozen_bytes or bytes(
        state.previous_epoch_participation
    ) != bytes(frozen.previous_epoch_participation)
    # the copy saw none of it — list contents, resident columns, root
    assert bytes(frozen.current_epoch_participation) == frozen_bytes
    cols = registry_columns_for(frozen)
    cols.refresh(frozen)
    assert cols.current_epoch_participation.tobytes() == frozen_bytes
    assert frozen.hash_tree_root() == frozen_root


def test_participation_rotation_keeps_residency():
    """process_participation_flag_updates on the persistent representation
    must rotate the columns and hash caches along: zero column rebuilds
    and matching roots afterwards."""
    from lighthouse_tpu.state_processing.altair import (
        process_participation_flag_updates,
    )

    fork = ForkName.ALTAIR
    state, spec = _att_state(fork, 128, 17)
    _make_persistent(state)
    cols = registry_columns_for(state)
    cols.refresh(state)
    state.hash_tree_root()  # warm the per-field caches
    prev_cur = bytes(state.current_epoch_participation)
    c = REGISTRY.counter("registry_columns_rebuilds_total")
    before = {
        f: c.value(field=f)
        for f in (
            "previous_epoch_participation",
            "current_epoch_participation",
        )
    }
    process_participation_flag_updates(state, E)
    cols.refresh(state)
    assert isinstance(state.previous_epoch_participation, PersistentByteList)
    assert bytes(state.previous_epoch_participation) == prev_cur
    assert bytes(state.current_epoch_participation) == bytes(
        len(state.validators)
    )
    for f, v in before.items():
        assert c.value(field=f) == v, f"rotation rebuilt {f}"
    assert cols.previous_epoch_participation.tobytes() == prev_cur
    assert not cols.current_epoch_participation.any()
    # root parity with a plain recompute after rotation
    assert state.hash_tree_root() == type(state).hash_tree_root_of(state)


@pytest.mark.perf_smoke
def test_happy_path_zero_scalar_fallbacks(monkeypatch):
    """A healthy chain must never take the kill-switch/fallback scalar
    path, and any real-shaped batch must engage the columnar fold."""
    from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness

    monkeypatch.setattr(
        attestation_batch, "_SMALL_BATCH_ROWS", _REAL_SMALL_BATCH_ROWS
    )
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    c = REGISTRY.counter("attestation_batch_total")
    before_scalar = c.value(path="scalar")
    h = BeaconChainHarness(spec, E, validator_count=16)
    h.extend_chain(2 * E.SLOTS_PER_EPOCH, attest=True)
    assert c.value(path="scalar") == before_scalar

    # a block whose row count clears the dispatch threshold goes columnar
    fork = ForkName.ALTAIR
    state, aspec = _att_state(fork, 2048, 41)
    rng = random.Random(41)
    atts = _make_attestations(state, fork, rng, 12)
    while sum(len(a.aggregation_bits) for a in atts) < 2 * _REAL_SMALL_BATCH_ROWS:
        atts += _make_attestations(state, fork, rng, 4)
    before_columnar = c.value(path="columnar")
    st = state.copy()
    _make_persistent(st)
    process_attestations(st, atts, aspec, E, False, _ctxt(st), fork)
    assert c.value(path="columnar") == before_columnar + 1
    assert c.value(path="scalar") == before_scalar


def test_small_batch_dispatch(monkeypatch):
    """Blocks under the row threshold take the (cheaper) scalar loop,
    counted separately from the kill-switch path — and produce the same
    state as the forced columnar fold."""
    monkeypatch.setattr(
        attestation_batch, "_SMALL_BATCH_ROWS", _REAL_SMALL_BATCH_ROWS
    )
    fork = ForkName.ALTAIR
    state, spec = _att_state(fork, 96, 19)
    rng = random.Random(19)
    atts = _make_attestations(state, fork, rng, 3)
    assert sum(len(a.aggregation_bits) for a in atts) < _REAL_SMALL_BATCH_ROWS
    c = REGISTRY.counter("attestation_batch_total")
    before = {p: c.value(path=p) for p in ("columnar", "scalar", "scalar_small")}
    small = state.copy()
    process_attestations(small, atts, spec, E, False, _ctxt(small), fork)
    assert c.value(path="scalar_small") == before["scalar_small"] + 1
    assert c.value(path="columnar") == before["columnar"]
    monkeypatch.setattr(attestation_batch, "_SMALL_BATCH_ROWS", 0)
    forced = state.copy()
    process_attestations(forced, atts, spec, E, False, _ctxt(forced), fork)
    assert bytes(small.current_epoch_participation) == bytes(
        forced.current_epoch_participation
    )
    assert bytes(small.previous_epoch_participation) == bytes(
        forced.previous_epoch_participation
    )
    assert list(small.balances) == list(forced.balances)


# --- PersistentByteList -----------------------------------------------------


def test_persistent_byte_list_matches_bytearray_root():
    from lighthouse_tpu.ssz.core import ParticipationList

    rng = random.Random(3)
    data = bytes(rng.randrange(8) for _ in range(10_000))
    plist_t = ParticipationList[1 << 20]
    assert plist_t.hash_tree_root_of(
        PersistentByteList(data)
    ) == plist_t.hash_tree_root_of(bytearray(data))
    assert bytes(PersistentByteList(data)) == data


def test_persistent_byte_list_cow_and_dirty_channels():
    lst = PersistentByteList(bytes(9000))
    cp = lst.copy()
    assert lst.shared_block_count(cp) == 2
    lst[5] = 7
    lst[8500] = 3
    lst.append(9)
    assert cp[5] == 0 and len(cp) == 9000
    base, dirty = lst.drain_dirty()
    assert dirty == {5, 8500, 9000}
    # unchanged-value writes don't mark
    lst[5] = 7
    _, dirty2 = lst.drain_dirty()
    assert dirty2 == set()
    # store_array marks exactly the changed rows in the named channel
    # (stage into a copy: load_array views are read-only under beacon-san)
    arr = lst.load_array().copy()
    arr[100] = 42
    lst.channel("columns")
    lst.store_array(arr)
    _, hash_dirty = lst.drain_dirty()
    _, col_dirty = lst.drain_dirty("columns")
    assert hash_dirty == {100}
    # the columns channel was created after the earlier writes, so it
    # only ever saw the store_array mark
    assert col_dirty == {100}


def test_persistent_byte_list_sparse_reroot_exact():
    from lighthouse_tpu.ssz.cached_tree_hash import ByteListCache
    from lighthouse_tpu.ssz.core import ParticipationList

    rng = random.Random(4)
    plist_t = ParticipationList[1 << 16]
    lst = PersistentByteList(bytes(rng.randrange(8) for _ in range(20_000)))
    cache = ByteListCache(plist_t.chunk_count())
    cache.root(lst)  # commit the baseline (full extract)
    for _ in range(50):
        lst[rng.randrange(len(lst))] = rng.randrange(8)
    lst.append(5)
    root1 = cache.root(lst)
    fresh = ByteListCache(plist_t.chunk_count())
    assert root1 == fresh.root(lst)


# --- satellites -------------------------------------------------------------


def test_eth1_tally_matches_scan():
    from lighthouse_tpu.state_processing.per_block import (
        eth1_data_vote_count_scan,
        process_eth1_data,
    )

    rng = random.Random(6)
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    state = interop_genesis_state(
        bls.interop_keypairs(8), 1_600_000_000, b"\x42" * 32, spec, E
    )
    period = E.slots_per_eth1_voting_period()
    choices = [
        T.Eth1Data(
            deposit_root=bytes([i]) * 32, deposit_count=8, block_hash=b"\x01" * 32
        )
        for i in range(3)
    ]
    for step in range(3 * period):
        vote = rng.choice(choices)
        pre_scan = eth1_data_vote_count_scan(state, vote) + 1
        process_eth1_data(state, vote, E)
        assert eth1_data_vote_count_scan(state, vote) == pre_scan
        tally = state.__dict__["_lh_eth1_tally"]
        assert tally["counts"][vote.serialize()] == pre_scan
        if pre_scan * 2 > period:
            assert state.eth1_data == vote
        if (step + 1) % period == 0:
            # period boundary: the epoch reset replaces the list (the
            # tally keys on the list object's identity and rebuilds)
            state.eth1_data_votes = []
            assert eth1_data_vote_count_scan(state, vote) == 0
            process_eth1_data(state, vote, E)
            assert eth1_data_vote_count_scan(state, vote) == 1


def test_sync_committee_indices_batched_matches_reference():
    from lighthouse_tpu.state_processing.altair import (
        get_next_sync_committee_indices,
        get_next_sync_committee_indices_reference,
    )

    for seed in (1, 2):
        state, spec = _att_state(ForkName.ALTAIR, 100 + seed * 37, seed)
        ref = get_next_sync_committee_indices_reference(state, E)
        fast = get_next_sync_committee_indices(state, E)
        assert fast == ref
        # resident-columns path agrees too
        st = state.copy()
        _make_persistent(st)
        registry_columns_for(st).refresh(st)
        assert get_next_sync_committee_indices(st, E) == ref


def test_phase0_attestation_validates_before_mutating():
    spec = minimal_spec()
    state = interop_genesis_state(
        bls.interop_keypairs(16), 1_600_000_000, b"\x42" * 32, spec, E
    )
    state.slot = 2 * E.SLOTS_PER_EPOCH + 2
    for s in range(len(state.block_roots)):
        state.block_roots[s] = bytes([s % 251]) * 32
    from lighthouse_tpu.state_processing.accessors import (
        get_beacon_committee,
        get_block_root,
    )
    from lighthouse_tpu.state_processing.per_block import process_attestation

    current = get_current_epoch(state, E)
    slot = state.slot - 1
    committee = get_beacon_committee(state, slot, 0, E)
    att = T.Attestation(
        aggregation_bits=[False] * len(committee),  # empty => invalid indexed
        data=T.AttestationData(
            slot=slot,
            index=0,
            beacon_block_root=get_block_root(state, current, E),
            source=state.current_justified_checkpoint,
            target=T.Checkpoint(
                epoch=current, root=get_block_root(state, current, E)
            ),
        ),
        signature=b"\x00" * 96,
    )
    before = len(state.current_epoch_attestations)
    with pytest.raises(BlockProcessingError, match="invalid indexed"):
        process_attestation(state, att, spec, E, False, _ctxt(state))
    # the old order appended the PendingAttestation before validating
    assert len(state.current_epoch_attestations) == before
    assert len(state.previous_epoch_attestations) == 0


def test_op_pool_bitmask_max_cover():
    """The numpy coverage sets must reproduce greedy max-cover exactly:
    biggest uncovered gain first, ties to insertion order, zero-gain
    candidates dropped."""
    from lighthouse_tpu.beacon_chain.op_pool import OperationPool

    spec = replace(minimal_spec(), altair_fork_epoch=0)
    state = interop_genesis_state(
        bls.interop_keypairs(16), 1_600_000_000, b"\x42" * 32, spec, E
    )
    state.slot = E.SLOTS_PER_EPOCH + 2
    pool = OperationPool(spec, E)
    current = get_current_epoch(state, E)
    slot = state.slot - 1
    cc = committee_cache_at(state, current, E)
    committee = cc.committee_array(slot, 0)
    k = committee.size

    def att(bits):
        return T.Attestation(
            aggregation_bits=bits,
            data=T.AttestationData(
                slot=slot,
                index=0,
                beacon_block_root=b"\x00" * 32,
                source=state.current_justified_checkpoint,
                target=T.Checkpoint(epoch=current, root=b"\x00" * 32),
            ),
            signature=b"\x00" * 96,
        )

    full = [True] * k
    half = [i < k // 2 for i in range(k)]
    other = [i >= k // 2 for i in range(k)]
    for a in (att(half), att(other), att(full)):
        # bypass the insert-time disjoint merge: max-cover must see the
        # exact aggregation patterns, not their union
        pool._add_unmerged(a)
    chosen = pool.get_attestations_for_block(state)
    # full covers everything; half/other add nothing afterwards
    assert len(chosen) == 1
    assert list(chosen[0].aggregation_bits) == full
    # the retained rescan walk packs the identical set
    assert pool.get_attestations_for_block_reference(state) == chosen
