"""State-advance pre-computation: cache discipline (CoW hand-out,
hit/miss/wasted accounting, head-change invalidation), the slot-claimed
timer and its STATE_ADVANCE processor lane, and snapshot-aliasing fuzz —
mutating a pre-advanced snapshot must never leak into the head state's
resident columns or dirty channels, and vice versa."""

import random

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.beacon_chain.state_advance import (
    StateAdvanceCache,
    StateAdvanceTimer,
)
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.metrics import REGISTRY
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec


@pytest.fixture(autouse=True)
def _fake_crypto():
    prev = bls.backend_name()
    bls.set_backend("fake_crypto")
    yield
    bls.set_backend(prev)


def _harness(validators: int = 16) -> BeaconChainHarness:
    return BeaconChainHarness(
        minimal_spec(), MinimalEthSpec, validator_count=validators
    )


def _counts():
    return tuple(
        REGISTRY.counter(f"state_advance_{k}_total").value()
        for k in ("hits", "misses", "wasted")
    )


class _State:
    """Counterfeit state: enough surface for cache bookkeeping tests."""

    def __init__(self, slot=0):
        self.slot = slot
        self.copies = 0

    def copy(self):
        self.copies += 1
        c = _State(self.slot)
        return c


# ---------------------------------------------------------------------------
# cache semantics
# ---------------------------------------------------------------------------


def test_cache_get_returns_copy_and_retains_entry():
    c = StateAdvanceCache()
    st = _State(slot=9)
    c.put(b"\x01" * 32, 9, st)
    h0, m0, w0 = _counts()
    a = c.get(b"\x01" * 32, 9)
    b = c.get(b"\x01" * 32, 9)
    assert a is not None and b is not None
    assert a is not st and b is not st and a is not b  # CoW copies
    h1, m1, w1 = _counts()
    # first consume is THE hit; the second hand-out of the same entry is
    # not double-counted (one advance saved, however many readers)
    assert (h1 - h0, m1 - m0, w1 - w0) == (1, 0, 0)


def test_cache_miss_on_wrong_key():
    c = StateAdvanceCache()
    c.put(b"\x01" * 32, 9, _State(slot=9))
    h0, m0, _ = _counts()
    assert c.get(b"\x02" * 32, 9) is None
    assert c.get(b"\x01" * 32, 8) is None
    h1, m1, _ = _counts()
    assert (h1 - h0, m1 - m0) == (0, 2)


def test_cache_replacement_of_unconsumed_entry_is_wasted():
    c = StateAdvanceCache()
    c.put(b"\x01" * 32, 9, _State(slot=9))
    _, _, w0 = _counts()
    c.put(b"\x02" * 32, 10, _State(slot=10))  # first entry never consumed
    _, _, w1 = _counts()
    assert w1 - w0 == 1
    c.get(b"\x02" * 32, 10)  # consume
    c.put(b"\x03" * 32, 11, _State(slot=11))
    _, _, w2 = _counts()
    assert w2 - w1 == 0  # consumed entries are not wasted


def test_cache_invalidate_spares_entry_for_new_head():
    c = StateAdvanceCache()
    c.put(b"\x01" * 32, 9, _State(slot=9))
    _, _, w0 = _counts()
    c.invalidate(b"\x01" * 32)  # head "changed" TO the entry's key
    assert c.get(b"\x01" * 32, 9) is not None  # survived
    c.invalidate(b"\x02" * 32)  # head changed away — drop (consumed: no waste)
    assert c._state is None
    c.put(b"\x01" * 32, 9, _State(slot=9))
    c.invalidate(b"\x02" * 32)  # unconsumed drop
    _, _, w1 = _counts()
    assert w1 - w0 == 1
    assert c._state is None


def test_cache_clear_resets_without_wasted_accounting():
    c = StateAdvanceCache()
    c.put(b"\x01" * 32, 9, _State(slot=9))
    _, _, w0 = _counts()
    c.clear()
    _, _, w1 = _counts()
    assert w1 == w0
    assert c.get(b"\x01" * 32, 9) is None


# ---------------------------------------------------------------------------
# timer: slot claims + processor lane
# ---------------------------------------------------------------------------


class _Chain:
    """Counterfeit chain for timer-dispatch tests (no state transition)."""

    def __init__(self):
        self.head_root = b"\x07" * 32
        self.head_state = _State(slot=5)
        self.state_advance_cache = StateAdvanceCache()
        self.state_advance_timer = None


class _Processor:
    def __init__(self, accept=True):
        self.accept = accept
        self.submitted = []

    def submit(self, work_type, item, handler):
        self.submitted.append((work_type, item, handler))
        return self.accept


def test_timer_attaches_to_chain_and_claims_slots():
    ch = _Chain()
    timer = StateAdvanceTimer(ch)
    assert ch.state_advance_timer is timer
    runs = []
    timer._advance = runs.append
    timer.on_slot_tick(5)
    timer.on_slot_tick(5)  # competing driver, same slot: claimed already
    timer.on_slot_tick(4)  # stale tick never un-advances
    timer.on_slot_tick(6)
    assert runs == [5, 6]


def test_timer_submits_on_state_advance_lane():
    from lighthouse_tpu.beacon_processor import WorkType

    ch = _Chain()
    timer = StateAdvanceTimer(ch)
    proc = _Processor(accept=True)
    timer.on_slot_tick(5, processor=proc)
    assert len(proc.submitted) == 1
    work_type, item, handler = proc.submitted[0]
    assert work_type == WorkType.STATE_ADVANCE
    assert item == 5 and handler == timer._advance
    # the claim stands: the inline driver for the same slot is a no-op
    runs = []
    timer._advance = runs.append
    timer.on_slot_tick(5)
    assert runs == []


def test_timer_refused_submit_unclaims_slot():
    ch = _Chain()
    timer = StateAdvanceTimer(ch)
    proc = _Processor(accept=False)
    timer.on_slot_tick(5, processor=proc)  # refused -> unclaimed
    runs = []
    timer._advance = runs.append
    timer.on_slot_tick(5)  # retry wins the claim back
    assert runs == [5]


def test_state_advance_queue_bound_is_tiny():
    from lighthouse_tpu.beacon_processor import _QUEUE_BOUNDS, WorkType

    assert WorkType.STATE_ADVANCE < WorkType.SLASHER_PROCESS
    assert _QUEUE_BOUNDS[WorkType.STATE_ADVANCE] <= 4


# ---------------------------------------------------------------------------
# timer: real advances
# ---------------------------------------------------------------------------


def test_timer_head_change_mid_advance_discards_as_wasted(monkeypatch):
    from lighthouse_tpu.beacon_chain import state_advance as sa

    h = _harness()
    h.extend_chain(2)
    timer = StateAdvanceTimer(h.chain)
    cur = int(h.chain.head_state.slot)

    real = sa.per_slot_processing

    def flip_head_then_process(state, spec, E):
        # the import of a competing block lands while the worker is mid-
        # transition: the head root this advance is keyed off dies
        h.chain.head_root = b"\xee" * 32
        return real(state, spec, E)

    monkeypatch.setattr(sa, "per_slot_processing", flip_head_then_process)
    h0, _, w0 = _counts()
    timer.on_slot_tick(cur)
    h1, _, w1 = _counts()
    assert w1 - w0 == 1
    assert h1 == h0
    assert h.chain.state_advance_cache._state is None  # nothing cached


def test_timer_skips_stale_head():
    h = _harness()
    h.extend_chain(2)
    timer = StateAdvanceTimer(h.chain)
    cur = int(h.chain.head_state.slot)
    # clock two slots ahead of the head: this slot's block is still in
    # flight — a pre-advance off the old head could never be consumed
    timer.on_slot_tick(cur + 2)
    assert h.chain.state_advance_cache._state is None


# ---------------------------------------------------------------------------
# snapshot aliasing fuzz
# ---------------------------------------------------------------------------


def test_snapshot_mutation_never_leaks_into_head_state():
    h = _harness()
    h.extend_chain(3)
    timer = StateAdvanceTimer(h.chain)
    cur = int(h.chain.head_state.slot)
    timer.on_slot_tick(cur)

    head = h.chain.head_state
    head_root_hash = head.hash_tree_root()
    head_balances = [int(b) for b in head.balances]

    rng = random.Random(0xA11A5)
    for trial in range(4):
        snap = h.chain.state_advance_cache.get(h.chain.head_root, cur + 1)
        assert snap is not None and snap.slot == cur + 1
        n = len(snap.balances)
        # churn the snapshot through every mutation channel the CoW
        # discipline tracks: balance writes, registry mutations (dirty
        # channels), appends, and a re-hash that drains caches
        for _ in range(20):
            snap.balances[rng.randrange(n)] = rng.randrange(40_000_000_000)
        v = snap.validators.mutate(rng.randrange(n))
        v.slashed = True
        v.withdrawable_epoch = 7
        snap.balances.append(32_000_000_000)
        snap.hash_tree_root()
        # the head state saw none of it
        assert [int(b) for b in head.balances] == head_balances, trial
        assert not any(v.slashed for v in head.validators), trial
        assert head.hash_tree_root() == head_root_hash, trial


def test_head_mutation_never_leaks_into_snapshot():
    h = _harness()
    h.extend_chain(3)
    timer = StateAdvanceTimer(h.chain)
    cur = int(h.chain.head_state.slot)
    timer.on_slot_tick(cur)

    snap = h.chain.state_advance_cache.get(h.chain.head_root, cur + 1)
    snap_hash = snap.hash_tree_root()
    snap_balances = [int(b) for b in snap.balances]

    head = h.chain.head_state
    rng = random.Random(0x5EED)
    for _ in range(20):
        head.balances[rng.randrange(len(head.balances))] = rng.randrange(
            40_000_000_000
        )
    head.validators.mutate(0).slashed = True
    head.hash_tree_root()

    assert [int(b) for b in snap.balances] == snap_balances
    assert not snap.validators[0].slashed
    assert snap.hash_tree_root() == snap_hash
    # and a FRESH copy from the still-cached entry is equally unpolluted
    snap2 = h.chain.state_advance_cache.get(h.chain.head_root, cur + 1)
    assert snap2.hash_tree_root() == snap_hash
