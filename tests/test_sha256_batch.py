"""Differential fuzz: numpy multi-buffer SHA-256 vs hashlib.

The vectorized host hasher (utils/sha256_batch) backs the registry-scale
Merkleization caches, so it must be bit-identical to OpenSSL for every
batch size and message length — including the precomputed-pad-schedule
fast path (`hash_rows_numpy`) and every dispatcher mode."""

import hashlib
import random

import numpy as np
import pytest

from lighthouse_tpu.utils.sha256_batch import (
    _BATCH_MIN,
    _CHUNK,
    hash_rows,
    hash_rows_hashlib,
    hash_rows_numpy,
    sha256_batch,
)


def _expected(rows) -> bytes:
    return b"".join(hashlib.sha256(bytes(r)).digest() for r in rows)


def test_pair_hashing_matches_hashlib_across_batch_sizes():
    rng = np.random.default_rng(1)
    # straddle the chunking boundary and the empty/one-row edges
    for n in (0, 1, 2, 3, 63, 64, 300, _CHUNK - 1, _CHUNK, _CHUNK + 5):
        pairs = rng.integers(0, 256, (n, 64), dtype=np.uint8)
        exp = _expected(pairs)
        assert hash_rows_numpy(pairs).tobytes() == exp
        assert hash_rows_hashlib(pairs).tobytes() == exp
        assert hash_rows(pairs).tobytes() == exp


def test_pair_hashing_fuzz_random_batches():
    rng = np.random.default_rng(2)
    pyrng = random.Random(2)
    for _ in range(25):
        n = pyrng.randrange(1, 500)
        pairs = rng.integers(0, 256, (n, 64), dtype=np.uint8)
        assert hash_rows_numpy(pairs).tobytes() == _expected(pairs)


def test_general_length_fuzz():
    """sha256_batch pads + multi-blocks arbitrary same-length messages;
    sweep the padding boundaries (55/56/63/64...) and random lengths."""
    rng = np.random.default_rng(3)
    pyrng = random.Random(3)
    lengths = [0, 1, 31, 32, 55, 56, 57, 63, 64, 65, 119, 120, 128, 200]
    lengths += [pyrng.randrange(0, 400) for _ in range(10)]
    for length in lengths:
        n = pyrng.randrange(1, 40)
        msgs = rng.integers(0, 256, (n, length), dtype=np.uint8)
        assert sha256_batch(msgs).tobytes() == _expected(msgs), length


def test_dispatcher_modes_agree(monkeypatch):
    rng = np.random.default_rng(4)
    pairs = rng.integers(0, 256, (_BATCH_MIN + 7, 64), dtype=np.uint8)
    exp = _expected(pairs)
    for mode in ("auto", "hashlib", "numpy"):
        monkeypatch.setenv("LIGHTHOUSE_TPU_SHA256_MODE", mode)
        assert hash_rows(pairs).tobytes() == exp, mode


def test_device_mode_falls_back_to_host(monkeypatch):
    """`device` must never be a correctness hazard: with the kernel
    unusable (or on a cpu backend) the dispatcher still hashes right."""
    monkeypatch.setenv("LIGHTHOUSE_TPU_SHA256_MODE", "device")
    rng = np.random.default_rng(5)
    pairs = rng.integers(0, 256, (33, 64), dtype=np.uint8)
    assert hash_rows(pairs).tobytes() == _expected(pairs)


def test_hash_rows_output_is_writable():
    """Tree layers are mutated in place — a read-only result (frombuffer
    over bytes) would break every sparse path update."""
    rng = np.random.default_rng(6)
    for fn in (hash_rows_numpy, hash_rows_hashlib, hash_rows):
        out = fn(rng.integers(0, 256, (9, 64), dtype=np.uint8))
        assert out.flags.writeable
        out[0, 0] ^= 1  # must not raise


def test_zero_copy_rows_unaffected_by_source_mutation():
    """hash_rows_hashlib wraps its own bytearray; mutating the input
    after the call must not change the returned digests."""
    rng = np.random.default_rng(7)
    pairs = rng.integers(0, 256, (17, 64), dtype=np.uint8)
    out = hash_rows_hashlib(pairs)
    snapshot = out.tobytes()
    pairs[:] = 0
    assert out.tobytes() == snapshot
