"""Tests for the consensus types layer (presets, ChainSpec, containers)."""

from lighthouse_tpu.types import (
    ChainSpec,
    Domain,
    ForkName,
    MainnetEthSpec,
    MinimalEthSpec,
    build_types,
    compute_signing_root,
    mainnet_spec,
    minimal_spec,
    spec_with_forks_at_genesis,
)


def test_presets():
    assert MainnetEthSpec.SLOTS_PER_EPOCH == 32
    assert MinimalEthSpec.SLOTS_PER_EPOCH == 8
    assert MainnetEthSpec.slots_per_eth1_voting_period() == 2048
    assert MinimalEthSpec.slots_per_eth1_voting_period() == 32
    assert MainnetEthSpec.SYNC_COMMITTEE_SIZE == 512
    assert MinimalEthSpec.SYNC_COMMITTEE_SIZE == 32


def test_fork_schedule_mainnet():
    spec = mainnet_spec()
    assert spec.fork_name_at_epoch(0) == ForkName.PHASE0
    assert spec.fork_name_at_epoch(74240) == ForkName.ALTAIR
    assert spec.fork_name_at_epoch(144896) == ForkName.BELLATRIX
    assert spec.fork_name_at_epoch(194048) == ForkName.CAPELLA
    assert spec.fork_name_at_epoch(269568) == ForkName.DENEB
    assert ForkName.DENEB > ForkName.CAPELLA >= ForkName.CAPELLA


def test_fork_data_root_zero():
    # hash(bytes32(0) || bytes32(0)) — the canonical zero Merkle node.
    root = ChainSpec.compute_fork_data_root(b"\x00" * 4, b"\x00" * 32)
    assert root.hex() == (
        "f5a5fd42d16a20302798ef6ed309979b43003d2320d9f0e8ea9831a92759fb4b"
    )


def test_domain_layout():
    spec = mainnet_spec()
    t = build_types(MainnetEthSpec)
    fork = t.Fork(previous_version=b"\x00" * 4, current_version=b"\x01\x00\x00\x00", epoch=10)
    d_before = spec.get_domain(5, Domain.BEACON_ATTESTER, fork, b"\x11" * 32)
    d_after = spec.get_domain(10, Domain.BEACON_ATTESTER, fork, b"\x11" * 32)
    assert d_before[:4] == (1).to_bytes(4, "little")
    assert d_after[:4] == (1).to_bytes(4, "little")
    assert d_before[4:] != d_after[4:]  # different fork versions mix in


def test_signing_root():
    root = compute_signing_root(b"\xaa" * 32, b"\xbb" * 32)
    import hashlib

    assert root == hashlib.sha256(b"\xaa" * 32 + b"\xbb" * 32).digest()


def test_state_roundtrip_all_forks():
    t = build_types(MinimalEthSpec)
    for fork, ns in t.forks.items():
        state = ns.BeaconState()
        data = state.serialize()
        state2 = ns.BeaconState.deserialize(data)
        assert state2.hash_tree_root() == state.hash_tree_root(), fork
        assert t.fork_of_state(state) == fork

        block = ns.SignedBeaconBlock()
        data = block.serialize()
        block2 = ns.SignedBeaconBlock.deserialize(data)
        assert block2.hash_tree_root() == block.hash_tree_root(), fork


def test_state_field_mutation_and_copy():
    t = build_types(MinimalEthSpec)
    s = t.BeaconState()
    s.slot = 5
    s.validators = [t.Validator(effective_balance=32 * 10**9)]
    s.balances = [32 * 10**9]
    c = s.copy()
    c.slot = 6
    c.balances[0] = 1
    assert s.slot == 5 and s.balances[0] == 32 * 10**9
    assert c.validators[0].effective_balance == 32 * 10**9
    # copy must not share mutable validator objects
    c.validators[0].slashed = True
    assert not s.validators[0].slashed


def test_forks_at_genesis_helper():
    spec = spec_with_forks_at_genesis(minimal_spec(), ForkName.CAPELLA)
    assert spec.fork_name_at_epoch(0) == ForkName.CAPELLA
    assert spec.deneb_fork_epoch is None


def test_deneb_blob_sidecar_shape():
    t = build_types(MainnetEthSpec)
    sc = t.BlobSidecar()
    assert len(sc.blob) == 131072
    assert len(sc.kzg_commitment_inclusion_proof) == 17
