"""p2p stack: RPC methods, gossip propagation, peer scoring, range sync.

Two (or three) in-process nodes over real TCP sockets — the
testing/simulator LocalNetwork analog at unit scale."""

import time
from dataclasses import replace

import pytest

from lighthouse_tpu.beacon_chain.harness import BeaconChainHarness
from lighthouse_tpu.crypto import bls
from lighthouse_tpu.network import BAN_THRESHOLD, NetworkService
from lighthouse_tpu.network.rpc import RpcClient, RpcError
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec as E
from lighthouse_tpu.utils.snappy import compress, decompress


def _harness(slots=0):
    bls.set_backend("fake_crypto")
    spec = replace(minimal_spec(), altair_fork_epoch=0)
    h = BeaconChainHarness(spec, E, validator_count=16)
    if slots:
        h.extend_chain(slots)
    return h


@pytest.fixture()
def two_nodes():
    a = _harness(slots=E.SLOTS_PER_EPOCH)
    b = _harness()
    na = NetworkService(a.chain).start()
    nb = NetworkService(b.chain).start()
    yield a, na, b, nb
    na.stop()
    nb.stop()


def test_snappy_compress_roundtrip():
    for payload in (b"", b"x", b"hello world" * 1000):
        assert decompress(compress(payload)) == payload


def test_rpc_status_ping_metadata(two_nodes):
    a, na, b, nb = two_nodes
    client = RpcClient("127.0.0.1", na.port)
    status = client.status(nb.local_status())
    assert int(status.head_slot) == a.chain.head_state.slot
    assert bytes(status.head_root) == a.chain.head_root
    assert client.ping(1) >= 1
    md = client.metadata()
    assert int(md.seq_number) >= 1


def test_blocks_by_range_and_root(two_nodes):
    a, na, b, nb = two_nodes
    client = RpcClient("127.0.0.1", na.port)
    blocks = client.blocks_by_range(1, 4, na.decode_block)
    assert [blk.message.slot for blk in blocks] == [1, 2, 3, 4]
    root = blocks[0].message.hash_tree_root()
    got = client.blocks_by_root([root], na.decode_block)
    assert len(got) == 1 and got[0].message.hash_tree_root() == root


def test_range_sync_catches_up(two_nodes):
    a, na, b, nb = two_nodes
    assert b.chain.head_state.slot == 0
    b.slot_clock.set_slot(a.chain.head_state.slot)
    peer = nb.connect("127.0.0.1", na.port)
    imported = nb.sync.sync_with(peer)
    assert imported == E.SLOTS_PER_EPOCH
    assert b.chain.head_root == a.chain.head_root


def test_gossip_block_propagates(two_nodes):
    a, na, b, nb = two_nodes
    b.slot_clock.set_slot(a.chain.head_state.slot)
    peer = nb.connect("127.0.0.1", na.port)
    nb.sync.sync_with(peer)
    time.sleep(0.2)  # let A's inbound-peer registration settle

    # A produces a block and gossips it; B imports via the gossip path
    slot = a.chain.head_state.slot + 1
    a.slot_clock.set_slot(slot)
    b.slot_clock.set_slot(slot)
    root, signed = a.add_block_at_slot(slot)
    na.publish_block(signed)
    deadline = time.time() + 5
    while time.time() < deadline and b.chain.head_root != root:
        time.sleep(0.05)
    assert b.chain.head_root == root


def test_invalid_gossip_downscores_and_bans(two_nodes):
    a, na, b, nb = two_nodes
    b.slot_clock.set_slot(a.chain.head_state.slot)
    peer = nb.connect("127.0.0.1", na.port)
    nb.sync.sync_with(peer)
    time.sleep(0.2)
    # B floods A with undecodable blocks on the block topic
    [a_peer] = na.peers.peers()
    n_invalid = int(-BAN_THRESHOLD // 10) + 1
    for i in range(n_invalid):
        nb.gossip.publish(nb.topic_block, b"garbage" + bytes([i]))
    deadline = time.time() + 5
    target = None
    while time.time() < deadline:
        target = na.peers._peers.get(a_peer.peer_id)
        if target is not None and target.banned:
            break
        time.sleep(0.05)
    assert target is not None and target.banned
    # the ban severs the live connection (not just future redials): A
    # closes the gossip socket, so B's further floods never reach A's chain
    deadline = time.time() + 5
    while time.time() < deadline and target.gossip_sock is not None:
        time.sleep(0.05)
    assert target.gossip_sock is None
    # and A refuses to dial the banned peer again
    with pytest.raises(RpcError):
        na.connect("127.0.0.1", target.port)


def test_gossip_operation_topics_feed_pools(two_nodes):
    """Exits, slashings, and sync-committee messages gossip across nodes
    into the op/sync pools (gossip_methods.rs operation handlers)."""
    a, na, b, nb = two_nodes
    b.slot_clock.set_slot(a.chain.head_state.slot)
    nb.connect("127.0.0.1", na.port)
    time.sleep(0.2)
    t = b.chain.types

    # this exit is spec-invalid at epoch 1 (validator hasn't been active
    # for SHARD_COMMITTEE_PERIOD) — gossip verification must refuse to
    # pool it even though fake_crypto would accept the signature
    exit_ = t.SignedVoluntaryExit(
        message=t.VoluntaryExit(epoch=0, validator_index=3),
        signature=b"\x0b" * 96,
    )
    nb.publish_voluntary_exit(exit_)

    header = t.BeaconBlockHeader(
        slot=1, proposer_index=2, parent_root=b"\x01" * 32,
        state_root=b"\x02" * 32, body_root=b"\x03" * 32,
    )
    header2 = t.BeaconBlockHeader(
        slot=1, proposer_index=2, parent_root=b"\x04" * 32,
        state_root=b"\x02" * 32, body_root=b"\x03" * 32,
    )
    slashing = t.ProposerSlashing(
        signed_header_1=t.SignedBeaconBlockHeader(
            message=header, signature=b"\x0c" * 96
        ),
        signed_header_2=t.SignedBeaconBlockHeader(
            message=header2, signature=b"\x0d" * 96
        ),
    )
    nb.publish_proposer_slashing(slashing)

    state = a.chain.head_state
    member_pk = bytes(state.current_sync_committee.pubkeys[0])
    vi = next(
        i for i, v in enumerate(state.validators)
        if bytes(v.pubkey) == member_pk
    )
    msg = t.SyncCommitteeMessage(
        slot=int(state.slot),
        beacon_block_root=a.chain.head_root,
        validator_index=vi,
        signature=b"\x0e" * 96,  # fake_crypto accepts
    )
    nb.publish_sync_committee_message(msg)

    deadline = time.time() + 5
    while time.time() < deadline:
        if (
            a.chain.op_pool._proposer_slashings
            and a.chain.sync_message_pool._msgs
        ):
            break
        time.sleep(0.05)
    assert a.chain.op_pool._proposer_slashings
    assert a.chain.sync_message_pool._msgs
    # the invalid exit was verified at gossip time and never pooled
    assert not a.chain.op_pool._voluntary_exits


def test_attestation_subnet_routing(two_nodes):
    """Attestations ride their computed subnet topic (validator.md
    compute_subnet_for_attestation) and still reach peers — who subscribe
    to every subnet — and the subnet service advertises duty subnets in
    the discovery record."""
    from lighthouse_tpu.network import messages as M
    from lighthouse_tpu.network.discovery import DiscoveryService
    from lighthouse_tpu.network.subnet_service import AttestationSubnetService
    from lighthouse_tpu.validator_client import ValidatorClient

    a, na, b, nb = two_nodes
    b.slot_clock.set_slot(a.chain.head_state.slot)
    nb.connect("127.0.0.1", na.port)
    nb.sync.sync_with(nb.peers.peers()[0])
    time.sleep(0.2)
    slot = b.chain.head_state.slot + 1
    b.slot_clock.set_slot(slot)
    a.slot_clock.set_slot(slot)
    atts = b.make_unaggregated_attestations(slot, b.chain.head_root)
    before = a.chain.op_pool.num_attestations()
    for att in atts[:4]:
        nb.publish_attestation(att)
    deadline = time.time() + 5
    while time.time() < deadline:
        if a.chain.op_pool.num_attestations() > before:
            break
        time.sleep(0.05)
    assert a.chain.op_pool.num_attestations() > before

    # subnet computation is deterministic and in range
    subnet = M.compute_subnet_for_attestation(4, slot, 2, E)
    assert 0 <= subnet < M.ATTESTATION_SUBNET_COUNT

    # duty subnets advertised via discovery attnets
    na.discovery = DiscoveryService(tcp_port=na.port)
    svc = AttestationSubnetService(na, node_id_seed=7)
    vc = ValidatorClient(a.chain, a.keypairs, a.spec, E)
    epoch = a.chain.head_state.slot // E.SLOTS_PER_EPOCH
    duties = vc.duties_service.attester_duties(epoch)
    subnets = svc.register_duties(duties, epoch)
    assert subnets  # 16 validators → at least one duty subnet
    assert set(svc.persistent_subnets) <= set(svc.active_subnets())
    assert na.discovery.local_enr.subnets == svc.active_subnets()
    na.discovery.stop()


def test_fork_digest_mismatch_rejected():
    a = _harness()
    spec2 = replace(minimal_spec(), altair_fork_epoch=0, altair_fork_version=b"\x09\x00\x00\x09")
    bls.set_backend("fake_crypto")
    b = BeaconChainHarness(spec2, E, validator_count=16)
    na = NetworkService(a.chain).start()
    nb = NetworkService(b.chain).start()
    try:
        with pytest.raises(RpcError):
            nb.connect("127.0.0.1", na.port)
        assert not nb.peers.peers()
    finally:
        na.stop()
        nb.stop()
