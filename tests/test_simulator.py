"""Multi-node in-process simulator (testing/simulator analog).

basic-sim: N full nodes (chain + gossip network + VC) finalize together.
fallback-sim: kill one BN mid-run; its VC fails over via
BeaconNodeFallback and the chain keeps finalizing
(testing/simulator/src/fallback_sim.rs:129-212).
"""

import pytest

from lighthouse_tpu.crypto import bls
from lighthouse_tpu.testing.simulator import (
    LocalNetwork,
    run_basic_sim,
    run_fallback_sim,
)
from lighthouse_tpu.types.chain_spec import minimal_spec
from lighthouse_tpu.types.eth_spec import MinimalEthSpec
from lighthouse_tpu.validator_client.beacon_node_fallback import (
    AllNodesFailed,
    BeaconNodeFallback,
    CandidateHealth,
)

E = MinimalEthSpec


@pytest.fixture(autouse=True)
def _fake_crypto():
    """Sim asserts liveness/finality logic, not signatures — fake_crypto
    keeps 2-node × 4-epoch runs in test-suite time (the reference's sim
    runs minutes on real crypto in CI for the same reason it's a separate
    binary)."""
    prev = bls.backend_name()
    bls.set_backend("fake_crypto")
    yield
    bls.set_backend(prev)


def test_basic_sim_two_nodes_finalize():
    net = run_basic_sim(minimal_spec(), E, node_count=2, epochs=4)
    try:
        net.check_all_heads_equal()
        assert net.nodes[0].chain.finalized_checkpoint.epoch >= 1
        # both nodes imported blocks produced by the *other* node's VC
        assert net.nodes[0].chain.head_state.slot == 4 * E.SLOTS_PER_EPOCH
    finally:
        net.shutdown()


def test_fallback_sim_survives_bn_death():
    net = run_fallback_sim(minimal_spec(), E, epochs=5, kill_at_epoch=2)
    try:
        survivor = net.nodes[0].chain
        assert survivor.finalized_checkpoint.epoch >= 2
        assert survivor.head_state.slot == 5 * E.SLOTS_PER_EPOCH
        # the dead node's VC kept working through the survivor
        dead_vc = net.nodes[1].vc
        assert isinstance(dead_vc.node, BeaconNodeFallback)
        states = {c.name: c.health for c in dead_vc.node.candidates}
        assert CandidateHealth.ONLINE in states.values()
    finally:
        net.shutdown()


class _FlakyNode:
    """Scripted BeaconNodeInterface: fails until told to recover."""

    def __init__(self):
        self.up = True
        self.calls = 0

    def head_root(self):
        self.calls += 1
        if not self.up:
            raise ConnectionError("down")
        return b"\x11" * 32


def test_beacon_node_fallback_first_success_and_recovery():
    a, b = _FlakyNode(), _FlakyNode()
    fb = BeaconNodeFallback([a, b], recheck_interval=0.0)
    assert fb.head_root() == b"\x11" * 32
    assert (a.calls, b.calls) == (1, 0)  # preference order respected

    a.up = False
    assert fb.head_root() == b"\x11" * 32  # failed over to b
    assert fb.candidates[0].health is CandidateHealth.OFFLINE

    a.up = True
    fb.head_root()  # recheck_interval=0 → a is re-probed and recovers
    assert fb.candidates[0].health is CandidateHealth.ONLINE

    a.up = b.up = False
    with pytest.raises(AllNodesFailed):
        fb.head_root()
